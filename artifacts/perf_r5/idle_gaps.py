"""Locate the ~20% device idle inside the streamed training block.

PERF_NOTES_r4: the G=50 training block's device self-time is ~51.5 ms of
64 ms wall — ~20% of the compiled program is DMA stalls / serialization
that per-op self-time tables cannot attribute.  This captures a trace of
the same block and reconstructs the DEVICE TIMELINE: merge all op
intervals per device lane, then report the gaps (idle windows) with the
ops bracketing each gap — the thing a self-time table hides.

Run:  python artifacts/perf_r5/idle_gaps.py [variant] [outdir]
(on the TPU; also runs on CPU to validate the parsing pipeline).
"""

from __future__ import annotations

import glob
import json
import sys
from collections import defaultdict
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "perf_r4"))


def load_trace_events(logdir: str):
    """Trace-viewer JSON events out of the xplane proto."""
    from xprof.convert import raw_to_tool_data as rtd

    files = glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True)
    assert files, f"no xplane under {logdir}"
    data, _ = rtd.xspace_to_tool_data(files, "trace_viewer", {})
    if isinstance(data, bytes):
        import gzip

        try:
            data = gzip.decompress(data)
        except Exception:
            pass
        data = data.decode()
    return json.loads(data)


def device_gaps(trace: dict, min_gap_us: float = 20.0):
    """Merge per-lane op intervals on DEVICE planes; report idle gaps."""
    pids = {}
    names = {}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pids[ev["pid"]] = ev["args"]["name"]
    device_pids = {p for p, n in pids.items()
                   if "TPU" in n or "/device" in n.lower() or "Device" in n}
    lanes = defaultdict(list)
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") == "X" and ev.get("pid") in device_pids:
            lanes[(ev["pid"], ev.get("tid"))].append(
                (ev["ts"], ev["ts"] + ev.get("dur", 0), ev.get("name", "?")))
    report = {}
    for lane, ivs in lanes.items():
        ivs.sort()
        t0, t1 = ivs[0][0], max(e for _, e, _ in ivs)
        busy = 0.0
        gaps = []
        cur_end, cur_name = ivs[0][1], ivs[0][2]
        busy_end = ivs[0][1]
        for s, e, name in ivs[1:]:
            if s > busy_end:
                gaps.append((s - busy_end, cur_name, name, busy_end))
            if e > busy_end:
                busy += min(e - s, e - busy_end)
                busy_end = e
                cur_name = name
        span = t1 - t0
        gaps = [g for g in gaps if g[0] >= min_gap_us]
        report[f"{pids[lane[0]]}/t{lane[1]}"] = {
            "span_ms": round(span / 1e3, 3),
            "busy_ms": round((span - sum(g[0] for g in gaps)) / 1e3, 3),
            "idle_pct": round(100 * sum(g[0] for g in gaps) / span, 1),
            "top_gaps": [
                {"gap_us": round(g, 1), "after": a[:70], "before": b[:70]}
                for g, a, b, _ in sorted(gaps, reverse=True)[:15]
            ],
        }
    return report


def main():
    variant = sys.argv[1] if len(sys.argv) > 1 else "base"
    logdir = sys.argv[2] if len(sys.argv) > 2 else f"/tmp/idle_{variant}"
    import jax

    from profile_block import build_run  # perf_r4 methodology

    run = build_run(variant)
    print("# compiling...", flush=True)
    float(run())
    with jax.profiler.trace(logdir):
        float(run())
    rep = device_gaps(load_trace_events(logdir))
    print(json.dumps(rep, indent=1))
    (Path(__file__).parent / f"idle_gaps_{variant}.json").write_text(
        json.dumps(rep, indent=1))


if __name__ == "__main__":
    main()
