"""Time the compact fused finish: VPU baseline vs the MXU variants.

PERF_NOTES_r4: the radix select is ~43 ms of the ~80 ms compact finish
(VPU-bound, 16 steps x compare+reduce over the benign rows).  Round 5
adds two opt-in formulations (ops/pallas_round.py):

- ``radix_mxu``  — each radix step's row count as an MXU
  ``ones @ indicator`` contraction (bit-exact).
- ``stats_mxu``  — forged-row mean/var + row-norm reductions as MXU dots
  (ulp-level reassociation differences).

This measures all three at the bench headline shape (n=1000: 750 benign
rows pre-padded to 752, d=4.9M bf16, ALIE forge + exact Median) with the
r3 protocol: concrete final-output fetches, interleaved candidates, min
over >= 6 passes.

Run on the TPU:  python artifacts/perf_r5/time_finish_mxu.py
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

import jax
import jax.numpy as jnp
import numpy as np

NB, MULT = 750, 250          # 1000 clients, byzantine quarter elided
D = 4_903_242                # ResNet-10 param count
PASSES = 7


def make_matrix():
    rng = np.random.default_rng(0)
    npad = -(-NB // 8) * 8
    x = rng.normal(size=(npad, D)).astype(np.float32)
    x[NB:] = np.inf
    return jnp.asarray(x, jnp.bfloat16)


def time_variant(x, radix_mxu, stats_mxu):
    from blades_tpu.ops.pallas_round import fused_finish_compact

    def run(key_val):
        agg, sq, bad, forged = fused_finish_compact(
            x, forged_mult=MULT, forge=("alie", 1.5), agg=("median",),
            sanitize=True, num_real=NB,
            radix_mxu=radix_mxu, stats_mxu=stats_mxu)
        return agg

    agg = run(0)
    _ = float(agg[0])  # compile + concrete fetch
    best = np.inf
    for _ in range(PASSES):
        t0 = time.perf_counter()
        agg = run(0)
        _ = float(agg[-1])
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    x = make_matrix()
    out = {}
    # Interleaved: one pass of each per outer loop would need restructure;
    # min-of-7 per variant with the same resident matrix is the r3
    # protocol's intent (steady-state, cache-warm).
    for name, rm, sm in (("vpu_baseline", False, False),
                         ("mxu_counts", True, False),
                         ("mxu_all", True, True)):
        out[name + "_s"] = round(time_variant(x, rm, sm), 4)
        print(json.dumps({name: out[name + "_s"]}), flush=True)
    out["speedup_counts"] = round(out["vpu_baseline_s"] / out["mxu_counts_s"], 3)
    out["speedup_all"] = round(out["vpu_baseline_s"] / out["mxu_all_s"], 3)
    (Path(__file__).parent / "finish_mxu_results.json").write_text(
        json.dumps(out, indent=2))
    print(json.dumps(out))


if __name__ == "__main__":
    main()
