"""CCT (transformer backbone) TPU throughput evidence (VERDICT r4 #8).

The CCT/CVT zoo + pretrained import exist with unit tests, but through
round 4 no perf or curve artifact exercised the attention path on the
TPU.  This measures the same FL-round workload shape as bench.py —
FedAvg + ALIE + exact Median through the streamed single-chip round —
on the catalog CCT (cct_2_3x2_32: 2 encoder blocks, 2 heads, SeqPool;
``global_model: cct`` in tuned_examples/fedavg_cct_cifar10.yaml) at two
scales, and writes ``results.json`` next to this file.

Run on the TPU:  python artifacts/cct_bench/measure.py
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

import numpy as np

BATCH = 32
LOCAL_STEPS = 1


def bench_cct(num_clients: int, client_block: int, timed_rounds: int = 5,
              model: str = "cct") -> dict:
    import jax
    import jax.numpy as jnp

    from blades_tpu.adversaries import get_adversary, make_malicious_mask
    from blades_tpu.core import FedRound, Server, TaskSpec
    from blades_tpu.parallel.streamed import streamed_step

    f = num_clients // 4
    task = TaskSpec(model=model, input_shape=(32, 32, 3), num_classes=10,
                    lr=0.1, compute_dtype="bfloat16").build()
    server = Server.from_config(aggregator="Median", lr=0.5)
    adv = get_adversary("ALIE", num_clients=num_clients, num_byzantine=f)
    fr = FedRound(task=task, server=server, adversary=adv, batch_size=BATCH,
                  num_batches_per_round=LOCAL_STEPS)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(num_clients, BATCH, 32, 32, 3)),
                    jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=(num_clients, BATCH)), jnp.int32)
    ln = jnp.full((num_clients,), BATCH, jnp.int32)
    mal = make_malicious_mask(num_clients, f)

    state = fr.init(jax.random.PRNGKey(0), num_clients)
    d = sum(p.size for p in jax.tree.leaves(state.server.params))
    step = streamed_step(fr, client_block=client_block, d_chunk=1 << 17,
                         malicious_prefix=f)

    state, m = step(state, x, y, ln, mal, jax.random.PRNGKey(1))
    _ = float(m["train_loss"])  # concrete fetch (relay-safe timing)

    t0 = time.perf_counter()
    for r in range(timed_rounds):
        state, m = step(state, x, y, ln, mal,
                        jax.random.fold_in(jax.random.PRNGKey(2), r))
    final = float(m["train_loss"])
    assert final == final
    dt = time.perf_counter() - t0
    return {
        "model": model, "clients": num_clients, "byzantine": f,
        "params": d, "client_block": client_block,
        "rounds_per_sec": round(timed_rounds / dt, 3),
        "train_loss_final": round(final, 4),
    }


def main():
    out = []
    # The tuned-example scale (n=60) and a giant-federation scale.
    for n, cb in ((60, 30), (1000, 50)):
        out.append(bench_cct(n, cb))
        print(json.dumps(out[-1]), flush=True)
        (Path(__file__).parent / "results.json").write_text(
            json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
