"""Forged-row separability vs synthetic heterogeneity (VERDICT r4 #3).

Question: at what per-client feature-drift dial ``h``
(``datasets._heterogenize_partition``) do ALIE's forged rows stop being
separable by the filtering defenses' own statistics — the precondition
for reproducing the published CIFAR-10 collapse of SignGuard /
ClippedClustering / CenteredClipping / DnC at 25-30% malicious
(``/root/reference/doc/source/images/cifar10.png``, ALIE row)?

Instead of burning a 36-cell accuracy grid per candidate ``h``, this
measures the defenses' DECISIONS directly on the forged update matrix,
per round, at small scale:

- ``sg_forged_kept``: fraction of forged rows surviving SignGuard's
  norm band + sign-census majority (the defense fails when ~1).
- ``ccl_forged_kept``: fraction of forged rows inside ClippedClustering's
  majority cosine cluster.
- ``dnc_forged_kept``: fraction kept by DnC's spectral outlier score.
- ``benign_cos``: mean pairwise cosine among benign rows (the spread the
  forged cluster must hide in; ~1 = the homogeneity problem).
- ``forged_z``: ||forged - benign_mean|| / mean ||benign_i - benign_mean||
  (how far outside the benign cloud the forged row sits).

Run (CPU is fine at this scale):
    python artifacts/alie_separability/measure.py [--out results.json]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

import numpy as np

# Runnable from anywhere: the repo root is two levels up.
sys.path.insert(0, str(Path(__file__).resolve().parents[2]))


_FR_CACHE = {}


def _build_round(n, f, model, input_shape, num_classes):
    """fr + a jitted round compiled ONCE and reused for every h (the data
    is an argument, not a closure — a per-h closure would recompile the
    resnet10 round per grid point, ~25 min each on CPU)."""
    import jax

    from blades_tpu.adversaries import get_adversary
    from blades_tpu.core import FedRound, Server, TaskSpec
    from blades_tpu.data.sampler import sample_client_batches

    key = (n, f, model, input_shape, num_classes)
    if key in _FR_CACHE:
        return _FR_CACHE[key]
    task = TaskSpec(model=model, input_shape=input_shape,
                    num_classes=num_classes, lr=0.1).build()
    server = Server.from_config(aggregator="Mean", lr=1.0)
    adv = get_adversary("ALIE", num_clients=n, num_byzantine=f)
    fr = FedRound(task=task, server=server, adversary=adv, batch_size=32)

    @jax.jit
    def round_updates(state, x, y, ln, mal, key):
        """Mirror of FedRound.step up to the forged matrix (round.py:148-176),
        returning the matrix for measurement plus the advanced state."""
        k_sample, k_train, k_adv, k_agg, _ = jax.random.split(key, 5)
        bx, by = sample_client_batches(k_sample, x, y, ln, fr.batch_size,
                                       fr.num_batches_per_round)
        hooks = fr._hooks()
        updates, client_opt, _ = fr.task.local_round_batched(
            state.server.params, state.client_opt, bx, by,
            jax.random.split(k_train, n), mal, *hooks)
        forged = fr.adversary.on_updates_ready(
            updates, mal, k_adv, aggregator=fr.server.aggregator,
            global_params=state.server.params)
        server, _ = fr.server.step(state.server, forged, key=k_agg)
        return forged, type(state)(server=server, client_opt=client_opt)

    _FR_CACHE[key] = (fr, round_updates)
    return fr, round_updates


def measure_h(h: float, *, n=30, f=9, rounds=6, noise=3.0, alpha=0.1,
              model="resnet10", dataset="cifar10", seed=5):
    import jax
    import jax.numpy as jnp

    from blades_tpu.adversaries import make_malicious_mask
    from blades_tpu.data import DatasetCatalog
    from blades_tpu.ops import clustering

    ds = DatasetCatalog.get_dataset(
        {"type": dataset, "synthetic_noise": noise,
         "synthetic_heterogeneity": h},
        num_clients=n, iid=False, alpha=alpha, seed=seed)
    assert ds.synthetic
    x = jnp.array(ds.train.x)
    y = jnp.array(ds.train.y)
    ln = jnp.array(ds.train.lengths)
    mal = make_malicious_mask(n, f)
    mal_np = np.asarray(mal)

    fr, round_updates = _build_round(n, f, model, ds.input_shape,
                                     ds.num_classes)
    state = fr.init(jax.random.PRNGKey(0), n)

    rows = []
    for r in range(rounds):
        forged, state = round_updates(state, x, y, ln, mal,
                                      jax.random.PRNGKey(100 + r))
        U = np.asarray(forged, np.float64)
        ben = U[~mal_np]
        frg = U[mal_np]

        # Benign geometry.
        bn = ben / np.maximum(np.linalg.norm(ben, axis=1, keepdims=True),
                              1e-12)
        cos = bn @ bn.T
        iu = np.triu_indices(len(ben), 1)
        bmean = ben.mean(axis=0)
        bdev = np.linalg.norm(ben - bmean, axis=1).mean()
        forged_z = float(np.linalg.norm(frg[0] - bmean) / max(bdev, 1e-12))

        # SignGuard's decision (aggregators.py Signguard.aggregate).
        norms = np.linalg.norm(U, axis=1)
        M = np.median(norms)
        clipped = U * np.minimum(1.0, M / np.maximum(norms, 1e-12))[:, None]
        cn = np.minimum(norms, M)
        s1 = (cn >= 0.1 * M) & (cn <= 3.0 * M)
        s2 = np.asarray(clustering.kmeans_majority(
            clustering.sign_features(jnp.asarray(clipped, jnp.float32))))
        sg_mask = s1 & s2

        # ClippedClustering's majority cosine cluster (fresh threshold =
        # median norm, the steady-state value).
        cl = U * np.minimum(1.0, M / np.maximum(norms, 1e-12))[:, None]
        nn = cl / np.maximum(np.linalg.norm(cl, axis=1, keepdims=True), 1e-12)
        dist = 1.0 - np.clip(nn @ nn.T, -1.0, 1.0)
        ccl_mask = np.asarray(clustering.agglomerative_majority(
            jnp.asarray(dist, jnp.float32), linkage="average"))

        # DnC's decision, recomputed transparently with the SAME
        # coordinate subsample the aggregator would draw
        # (aggregators.py DnC: idx = permutation(k_iter, d)[:sub_dim]
        # for k_iter in split(key, num_iters); num_iters=1 here).
        k_iter = jax.random.split(jax.random.PRNGKey(r), 1)[0]
        idx = np.asarray(jax.random.permutation(k_iter, U.shape[1])[:10000])
        sub = U[:, idx]
        cen = sub - sub.mean(axis=0)
        v = np.linalg.svd(cen, full_matrices=False)[2][0]
        score = (cen @ v) ** 2
        keep = U.shape[0] - int(1.0 * f)
        dnc_mask = np.argsort(np.argsort(score)) < keep

        rows.append({
            "round": r,
            "benign_cos_mean": float(cos[iu].mean()),
            "benign_cos_std": float(cos[iu].std()),
            "forged_z": forged_z,
            "sg_forged_kept": float(sg_mask[mal_np].mean()),
            "sg_benign_kept": float(sg_mask[~mal_np].mean()),
            "ccl_forged_kept": float(ccl_mask[mal_np].mean()),
            "ccl_benign_kept": float(ccl_mask[~mal_np].mean()),
            "dnc_forged_kept": float(dnc_mask[mal_np].mean()),
            "dnc_benign_kept": float(dnc_mask[~mal_np].mean()),
        })
        print(json.dumps({"h": h, **rows[-1]}), flush=True)

    def avg(k):
        return round(float(np.mean([r[k] for r in rows[1:]])), 3)

    return {"h": h, "n": n, "f": f, "rounds": rounds, "noise": noise,
            "alpha": alpha, "model": model,
            **{k: avg(k) for k in rows[0] if k != "round"}}


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=str(Path(__file__).parent / "results.json"))
    p.add_argument("--h-grid", nargs="+", type=float,
                   default=[0.0, 0.5, 1.0, 2.0, 4.0])
    p.add_argument("--model", default="resnet10")
    p.add_argument("--rounds", type=int, default=6)
    args = p.parse_args(argv)

    results = []
    for h in args.h_grid:
        results.append(measure_h(h, model=args.model, rounds=args.rounds))
        Path(args.out).write_text(json.dumps(results, indent=2))
    print(json.dumps(results, indent=2))


if __name__ == "__main__":
    main()
