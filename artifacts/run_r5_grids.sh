#!/bin/bash
# Round-5 TPU grid queue (VERDICT r4 #3/#4): run on the TPU, in this
# order.  Each invocation is resumable (curves.json rewritten per cell).
set -x
cd "$(dirname "$0")/.."

# 1. Complete IPM-100 to the reference 9x4 matrix (missing: the 10%
#    column for the six existing aggregators + Trimmedmean/Multikrum/
#    Centeredclipping everywhere).  ~18 cells x ~100 s.
python -m blades_tpu.benchmarks.accuracy_curves \
  --dataset cifar10 --rounds 200 --num-clients 60 \
  --adversary '{"type": "IPM", "scale": 100.0}' \
  --aggregators Mean Median Trimmedmean GeoMed Multikrum Centeredclipping Signguard Clippedclustering DnC \
  --malicious 0 6 12 18 --noniid-alpha 0.1 --synthetic-noise 3.0 \
  --rounds-per-dispatch 10 \
  --resume-from artifacts/accuracy_curves/cifar10_ipm100/curves.json \
  --out artifacts/accuracy_curves/cifar10_ipm100_r5

# 2. Complete IPM-0.1 the same way (~19 cells).
python -m blades_tpu.benchmarks.accuracy_curves \
  --dataset cifar10 --rounds 200 --num-clients 60 \
  --adversary '{"type": "IPM", "scale": 0.1}' \
  --aggregators Mean Median Trimmedmean GeoMed Multikrum Centeredclipping Signguard Clippedclustering DnC \
  --malicious 0 6 12 18 --noniid-alpha 0.1 --synthetic-noise 3.0 \
  --rounds-per-dispatch 10 \
  --resume-from artifacts/accuracy_curves/cifar10_ipm01/curves.json \
  --out artifacts/accuracy_curves/cifar10_ipm01_r5

# 3. ALIE-hard rerun with benign heterogeneity (h chosen from
#    artifacts/alie_separability/results.json — fill in H below).
H=${ALIE_H:?set ALIE_H from the separability measurement}
python -m blades_tpu.benchmarks.accuracy_curves \
  --dataset cifar10 --rounds 200 --num-clients 60 \
  --adversary ALIE \
  --aggregators Mean Median Trimmedmean GeoMed Multikrum Centeredclipping Signguard Clippedclustering DnC \
  --malicious 0 6 12 15 18 --noniid-alpha 0.1 --synthetic-noise 3.0 \
  --synthetic-heterogeneity "$H" --rounds-per-dispatch 10 \
  --out artifacts/accuracy_curves/cifar10_alie_het
