#!/bin/bash
# Round-5 TPU grid queue (VERDICT r4 #3/#4): run on the TPU, in this
# order.  Each invocation is resumable (curves.json rewritten per cell).
set -x
cd "$(dirname "$0")/.."

# 1. Complete IPM-100 to the reference 9x4 matrix (missing: the 10%
#    column for the six existing aggregators + Trimmedmean/Multikrum/
#    Centeredclipping everywhere).  ~18 cells x ~100 s.
python -m blades_tpu.benchmarks.accuracy_curves \
  --dataset cifar10 --rounds 200 --num-clients 60 \
  --adversary '{"type": "IPM", "scale": 100.0}' \
  --aggregators Mean Median Trimmedmean GeoMed Multikrum Centeredclipping Signguard Clippedclustering DnC \
  --malicious 0 6 12 18 --noniid-alpha 0.1 --synthetic-noise 3.0 \
  --rounds-per-dispatch 10 \
  --resume-from artifacts/accuracy_curves/cifar10_ipm100/curves.json \
  --out artifacts/accuracy_curves/cifar10_ipm100_r5

# 2. Complete IPM-0.1 the same way (~19 cells).
python -m blades_tpu.benchmarks.accuracy_curves \
  --dataset cifar10 --rounds 200 --num-clients 60 \
  --adversary '{"type": "IPM", "scale": 0.1}' \
  --aggregators Mean Median Trimmedmean GeoMed Multikrum Centeredclipping Signguard Clippedclustering DnC \
  --malicious 0 6 12 18 --noniid-alpha 0.1 --synthetic-noise 3.0 \
  --rounds-per-dispatch 10 \
  --resume-from artifacts/accuracy_curves/cifar10_ipm01/curves.json \
  --out artifacts/accuracy_curves/cifar10_ipm01_r5

# 3. ALIE-hard rerun with benign heterogeneity.  h = 1.0 chosen by the
#    separability measurement (artifacts/alie_separability/README.md:
#    all three filtering defenses keep ALIE's forged rows at h in
#    [1, 2]; h = 4 re-separates them and degrades the data).
H=${ALIE_H:-1.0}

# 3a. Cheap benign-baseline check first: 9 cells at zero attackers —
#     the grid is only meaningful if the wider spread leaves the task
#     learnable (expect >= ~0.8; the r4 grid's benign row was
#     0.89-0.96 at h=0).
python -m blades_tpu.benchmarks.accuracy_curves \
  --dataset cifar10 --rounds 200 --num-clients 60 \
  --adversary ALIE \
  --aggregators Mean Median Trimmedmean GeoMed Multikrum Centeredclipping Signguard Clippedclustering DnC \
  --malicious 0 --noniid-alpha 0.1 --synthetic-noise 3.0 \
  --synthetic-heterogeneity "$H" --rounds-per-dispatch 10 \
  --out artifacts/accuracy_curves/cifar10_alie_het

# 3b. The full grid, resuming over the benign row.
python -m blades_tpu.benchmarks.accuracy_curves \
  --dataset cifar10 --rounds 200 --num-clients 60 \
  --adversary ALIE \
  --aggregators Mean Median Trimmedmean GeoMed Multikrum Centeredclipping Signguard Clippedclustering DnC \
  --malicious 0 6 12 15 18 --noniid-alpha 0.1 --synthetic-noise 3.0 \
  --synthetic-heterogeneity "$H" --rounds-per-dispatch 10 \
  --resume-from artifacts/accuracy_curves/cifar10_alie_het/curves.json \
  --out artifacts/accuracy_curves/cifar10_alie_het

# 4. Rerun the separability measurement with the faithful model (the
#    committed CPU run used a CNN proxy; resnet10 takes ~2 min here).
python artifacts/alie_separability/measure.py \
  --out artifacts/alie_separability/results.json

# 5. CCT transformer-backbone bench evidence.
python artifacts/cct_bench/measure.py
