"""Round-4 training-block timing: baseline vs remat variants.

Protocol (artifacts/PERF_NOTES_r3.md): in-jit lax.scan repetition whose
body input depends on the carry (else XLA hoists the loop-invariant
body), interleaved candidates in ONE process, min over >=6 passes.

Run: cd /root/repo && PYTHONPATH="$PYTHONPATH:." python artifacts/perf_r4/time_block.py
"""

from __future__ import annotations

import dataclasses
import functools
import json
import sys
import time

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from blades_tpu.core.task import Task, TaskSpec
from blades_tpu.models.resnet import BasicBlock, ResNet

G = 50          # clients per block (bench.py client_block)
BATCH = 32
LOCAL_STEPS = 1
REP = 8
PASSES = 6


class RematTask(Task):
    """Full remat: recompute the forward during backward (saves only
    inputs), so forward activations never round-trip HBM."""

    def loss_fn(self, params, x, y, dropout_key=None):
        f = functools.partial(Task.loss_fn, self)
        return jax.checkpoint(f)(params, x, y, dropout_key)


def make_task(variant: str) -> Task:
    spec = TaskSpec(model="resnet10", input_shape=(32, 32, 3),
                    num_classes=10, lr=0.1, compute_dtype="bfloat16")
    base = spec.build()
    if variant == "base":
        return base
    if variant == "remat_full":
        return RematTask(spec=base.spec, model=base.model)
    if variant == "remat_block":
        # Save only residual-block boundaries; recompute inside each block.
        model = ResNet(nn.remat(BasicBlock), (1, 1, 1, 1), 10)
        return Task(spec=spec, model=model)
    if variant == "remat_block_full":
        model = ResNet(nn.remat(BasicBlock), (1, 1, 1, 1), 10)
        return RematTask(spec=spec, model=model)
    raise ValueError(variant)


def make_timed(task: Task, params, opt, bx, by, keys, mal):
    """Jitted REP-iteration scan over the block; body input depends on
    the carry, carry depends on the full update tensor."""

    def body(c, _):
        bxp = bx + c * 1e-30
        upd, _opt2, loss = task.local_round_batched(
            params, opt, bxp, by, keys, mal
        )
        return loss.sum() + upd.sum() * 1e-30, None

    @jax.jit
    def run():
        out, _ = lax.scan(body, jnp.float32(0.0), None, length=REP)
        return out

    return run


def main():
    rng = np.random.default_rng(0)
    bx = jnp.asarray(rng.normal(size=(G, LOCAL_STEPS, BATCH, 32, 32, 3)),
                     jnp.float32)
    by = jnp.asarray(rng.integers(0, 10, size=(G, LOCAL_STEPS, BATCH)),
                     jnp.int32)
    keys = jax.random.split(jax.random.PRNGKey(0), G)
    mal = jnp.zeros((G,), bool)

    variants = sys.argv[1:] or ["base", "remat_full", "remat_block"]
    runs = {}
    for v in variants:
        task = make_task(v)
        params = task.init_params(jax.random.PRNGKey(0))
        opt = jax.vmap(lambda _: task.init_client_opt_state(params))(
            jnp.arange(G)
        )
        runs[v] = make_timed(task, params, opt, bx, by, keys, mal)

    # Warmup/compile all first.
    for v, run in runs.items():
        t0 = time.perf_counter()
        val = float(run())
        print(f"# compile+first {v}: {time.perf_counter() - t0:.1f}s "
              f"val={val:.4f}", flush=True)

    times = {v: [] for v in runs}
    for p in range(PASSES):
        for v, run in runs.items():
            t0 = time.perf_counter()
            _ = float(run())
            times[v].append((time.perf_counter() - t0) / REP)

    out = {v: {"ms_min": round(min(ts) * 1e3, 2),
               "ms_all": [round(t * 1e3, 2) for t in ts]}
           for v, ts in times.items()}
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
