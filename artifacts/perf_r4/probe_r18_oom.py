"""Compile-probe the ResNet-18@576 streamed train block to find what
pushed it over the HBM edge.

Usage: python artifacts/perf_r4/probe_r18_oom.py [bn_vjp(0|1)] [out_dtype(bf16|f32)]
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np

import blades_tpu.models.layers as layers_mod

bn_vjp = sys.argv[1] != "0" if len(sys.argv) > 1 else True
od = jnp.bfloat16 if (len(sys.argv) < 3 or sys.argv[2] == "bf16") else None

import os
if not bn_vjp:
    os.environ["BLADES_TPU_BN_VJP"] = "0"
if False:
    # Force the naive (pre-r4) BN formulation.
    orig = layers_mod.BatchStatsNorm.__call__

    import flax.linen as nn

    def naive(self, x):
        features = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (features,))
        bias = self.param("bias", nn.initializers.zeros, (features,))
        axes = tuple(range(x.ndim - 1))
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
        y = (x - mean) * jax.lax.rsqrt(var + self.epsilon)
        return y * scale + bias

    layers_mod.BatchStatsNorm.__call__ = nn.compact(naive)

from blades_tpu.adversaries import get_adversary, make_malicious_mask
from blades_tpu.core import FedRound, Server, TaskSpec
from blades_tpu.parallel.streamed import streamed_step

N, CB, BATCH = 576, 32, 32
task = TaskSpec(model="resnet18", input_shape=(32, 32, 3), num_classes=10,
                lr=0.1, compute_dtype="bfloat16").build()
server = Server.from_config(aggregator="Median", lr=0.5)
adv = get_adversary("ALIE", num_clients=N, num_byzantine=N // 4)
fr = FedRound(task=task, server=server, adversary=adv, batch_size=BATCH,
              num_batches_per_round=1)
state = fr.init(jax.random.PRNGKey(0), N)
step = streamed_step(fr, client_block=CB, d_chunk=1 << 17)
d = sum(p.size for p in jax.tree.leaves(state.server.params))
from blades_tpu.ops.pallas_select import _BLOCK_D

d_alloc = -(-d // _BLOCK_D) * _BLOCK_D
buf = jnp.zeros((N, d_alloc), jnp.bfloat16)
x = jnp.zeros((N, 32, 32, 32, 3), jnp.float32)
y = jnp.zeros((N, 32), jnp.int32)
lengths = jnp.full((N,), 32, jnp.int32)
mal = make_malicious_mask(N, N // 4)
keys = jax.random.split(jax.random.PRNGKey(0), N)
try:
    lowered = step.train_block.lower(
        buf, state.client_opt, state.server.params, x, y, lengths, mal,
        keys, keys, jnp.int32(0))
    c = lowered.compile()
    mem = c.memory_analysis()
    print("OK  bn_vjp=%s out=%s: %s" % (bn_vjp, od, mem))
except Exception as e:
    print("OOM bn_vjp=%s out=%s: %s" % (bn_vjp, od, str(e)[:300]))
