"""Round-4: fused finish kernel timing at bench scale (n=1000, d=4.9M).

Variants: median vs mean (radix cost), alie forge on/off, sanitize
on/off.  Protocol: in-jit scan with carry-dependent input (the carry
perturbs the malicious mask's float weights? no — perturb via updates),
interleaved, min over >=6 passes.

NOTE: the real matrix is bf16 and huge (9.8 GB); we can't scan-carry it
(double-buffer OOM).  Instead each timed call runs the kernel REP times
with the INPUT build outside: body depends on carry via a scalar added
to the forge_noise/updates? Adding to updates copies 9.8GB.  Trick: the
kernel's output feeds the carry, and the carry perturbs the *malicious
weights* wb through a (n,1)-sized input — but fused_finish takes a bool
mask.  So instead: time via host loop over independent dispatches of the
SAME compiled fn but fetch a value each iteration (forces completion;
relay pipelining makes per-dispatch overhead ~1ms at this granularity,
acceptable at 20-90ms kernels), min over many iters, interleaved.

Run: cd /root/repo && PYTHONPATH="$PYTHONPATH:." python artifacts/perf_r4/time_finish.py
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from blades_tpu.ops.pallas_round import fused_finish

N = 1000
D = 4_903_242
PASSES = 8


def main():
    from blades_tpu.ops.pallas_select import _BLOCK_D

    d_alloc = -(-D // _BLOCK_D) * _BLOCK_D
    # Zeros: a random matrix would need 2x HBM to draw (f32 intermediate)
    # and the kernel's cost is data-independent (fixed radix step count).
    updates = jnp.zeros((N, d_alloc), jnp.bfloat16)
    mal = jnp.arange(N) < N // 4

    cfgs = {
        "median_alie_san": dict(forge=("alie", 1.5), agg=("median",),
                                sanitize=True),
        "median_noforge_nosan": dict(forge=None, agg=("median",),
                                     sanitize=False),
        "mean_alie_san": dict(forge=("alie", 1.5), agg=("mean",),
                              sanitize=True),
        "mean_noforge_nosan": dict(forge=None, agg=("mean",),
                                   sanitize=False),
        "trimmed_alie_san": dict(forge=("alie", 1.5), agg=("trimmed", 250),
                                 sanitize=True),
    }
    names = sys.argv[1:] or list(cfgs)

    REP = 6
    fns = {}
    for name in names:
        kw = cfgs[name]

        def f(u, m, kw=kw):
            # In-jit repetition; the mask depends on the carry through
            # c != c (False, but XLA can't prove it for a float carry),
            # so the kernel re-runs every iteration while the giant
            # matrix stays a read-only loop invariant (no carry copy).
            def body(c, _):
                m2 = m ^ (c != c)
                a, sq, bad = fused_finish(u, m2, None, **kw)
                return a[0] + sq[0], None

            out, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=REP)
            return out

        jf = jax.jit(f)
        t0 = time.perf_counter()
        v = float(jf(updates, mal))
        print(f"# compile {name}: {time.perf_counter() - t0:.1f}s v={v:.4f}",
              flush=True)
        fns[name] = jf

    times = {v: [] for v in fns}
    for p in range(PASSES):
        for name, jf in fns.items():
            t0 = time.perf_counter()
            _ = float(jf(updates, mal))
            times[name].append((time.perf_counter() - t0) / REP)

    print(json.dumps({v: {"ms_min": round(min(ts) * 1e3, 1),
                          "ms_med": round(sorted(ts)[len(ts) // 2] * 1e3, 1)}
                      for v, ts in times.items()}, indent=2))


if __name__ == "__main__":
    main()
