"""Standalone: pallas per-client BN backward vs the jnp formulation,
stage-1 ResNet shape (G=50, B=32, 32x32, C=64), bf16.

Inputs: x, dy (G,B,H,W,C) bf16; mean, r, scale (G,C) f32 (saved by the
forward).  Outputs: dx (G,B,H,W,C) bf16; dscale, dbias (G,C) f32.

Run: cd /root/repo && PYTHONPATH="$PYTHONPATH:." python artifacts/perf_r4/time_bn_pallas.py
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

G, B, H, W, C = 50, 32, 32, 32, 64
N = B * H * W
REP = 8
PASSES = 6


def jnp_bwd(x, dy, mean, r, scale):
    """The hand-VJP formulas as XLA sees them (per client via vmap)."""

    def one(x, dy, mean, r, scale):
        xhat = (x - mean) * r
        dyf = dy.astype(jnp.float32)
        dbias = jnp.sum(dyf, axis=(0, 1))
        dscale = jnp.sum(dyf * xhat.astype(jnp.float32), axis=(0, 1))
        dxhat = dy * scale.astype(dy.dtype)
        mean_dxhat = (jnp.sum(dxhat.astype(jnp.float32), axis=(0, 1))
                      / N).astype(dy.dtype)
        m2 = (dscale * scale / N).astype(dy.dtype)
        dx = r.astype(dy.dtype) * (dxhat - mean_dxhat
                                   - xhat * m2.astype(dy.dtype))
        return dx, dscale, dbias

    mean = mean.astype(x.dtype)[:, None, None, :]
    r_ = r.astype(x.dtype)[:, None, None, :]
    # one() sees (B*H, W, C); mean/r broadcast as (1, 1, C)
    return jax.vmap(one)(
        x.reshape(G, B * H, W, C), dy.reshape(G, B * H, W, C),
        mean, r_, scale,
    )


NT = 4096  # N-tile: (4096, 64) bf16 + f32 temps fit scoped VMEM


def _bn_reduce_kernel(x_ref, dy_ref, mean_ref, r_ref, dscale_ref,
                      dbias_ref):
    g, t = pl.program_id(0), pl.program_id(1)
    x = x_ref[0]
    dy = dy_ref[0]
    mean = mean_ref[pl.ds(g, 1)]
    r = r_ref[pl.ds(g, 1)]
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    xhat = (xf - mean) * r

    @pl.when(t == 0)
    def _init():
        dscale_ref[pl.ds(g, 1)] = jnp.zeros((1, C), jnp.float32)
        dbias_ref[pl.ds(g, 1)] = jnp.zeros((1, C), jnp.float32)

    dbias_ref[pl.ds(g, 1)] += jnp.sum(dyf, axis=0, keepdims=True)
    dscale_ref[pl.ds(g, 1)] += jnp.sum(dyf * xhat, axis=0, keepdims=True)


def _bn_dx_kernel(x_ref, dy_ref, mean_ref, r_ref, scale_ref, dscale_ref,
                  dbias_ref, dx_ref):
    g = pl.program_id(0)
    x = x_ref[0]
    dy = dy_ref[0]
    mean = mean_ref[pl.ds(g, 1)]
    r = r_ref[pl.ds(g, 1)]
    scale = scale_ref[pl.ds(g, 1)]
    dscale = dscale_ref[pl.ds(g, 1)]
    dbias = dbias_ref[pl.ds(g, 1)]
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    xhat = (xf - mean) * r
    dxhat = dyf * scale
    mean_dxhat = dbias * scale / N
    m2 = dscale * scale / N
    dx = r * (dxhat - mean_dxhat - xhat * m2)
    dx_ref[0] = dx.astype(dx_ref.dtype)


def _gc_spec():
    return pl.BlockSpec((G, C), lambda *a: (0, 0), memory_space=pltpu.VMEM)


def _tile_spec():
    return pl.BlockSpec((1, NT, C), lambda g, t: (g, t, 0),
                        memory_space=pltpu.VMEM)


@jax.jit
def pallas_bwd(x, dy, mean, r, scale):
    x2 = x.reshape(G, N, C)
    dy2 = dy.reshape(G, N, C)
    dscale, dbias = pl.pallas_call(
        _bn_reduce_kernel,
        grid=(G, N // NT),
        in_specs=[_tile_spec(), _tile_spec(), _gc_spec(), _gc_spec()],
        out_specs=[_gc_spec(), _gc_spec()],
        out_shape=[jax.ShapeDtypeStruct((G, C), jnp.float32),
                   jax.ShapeDtypeStruct((G, C), jnp.float32)],
    )(x2, dy2, mean, r)
    dx = pl.pallas_call(
        _bn_dx_kernel,
        grid=(G, N // NT),
        in_specs=[_tile_spec(), _tile_spec(), _gc_spec(), _gc_spec(),
                  _gc_spec(), _gc_spec(), _gc_spec()],
        out_specs=_tile_spec(),
        out_shape=jax.ShapeDtypeStruct((G, N, C), x.dtype),
    )(x2, dy2, mean, r, scale, dscale, dbias)
    return dx.reshape(x.shape), dscale, dbias


def timed(fn, args):
    @jax.jit
    def run(*a):
        def body(c, _):
            out = fn(a[0] + c.astype(a[0].dtype) * 0, *a[1:])
            return out[1][0, 0] + out[2][0, 0], None

        out, _ = lax.scan(body, jnp.float32(0.0), None, length=REP)
        return out

    return lambda: run(*args)


def main():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(G, B, H, W, C)), jnp.bfloat16)
    dy = jnp.asarray(rng.normal(size=(G, B, H, W, C)) * 0.1, jnp.bfloat16)
    mean = jnp.asarray(rng.normal(size=(G, C)) * 0.1, jnp.float32)
    r = jnp.asarray(1.0 + rng.random((G, C)), jnp.float32)
    scale = jnp.asarray(1.0 + rng.random((G, C)) * 0.1, jnp.float32)

    # Correctness first.
    def jnp_flat(x, dy, mean, r, scale):
        dx, ds, db = jnp_bwd(x, dy, mean, r, scale)
        return dx.reshape(x.shape), ds, db

    a = jnp_flat(x, dy, mean, r, scale)
    b = pallas_bwd(x, dy, mean, r, scale)
    for u, v, name in zip(a, b, ("dx", "dscale", "dbias")):
        err = float(jnp.max(jnp.abs(u.astype(jnp.float32)
                                    - v.astype(jnp.float32))))
        print(f"# {name} maxdiff {err:.5f}")

    runs = {"jnp": timed(jnp_flat, (x, dy, mean, r, scale)),
            "pallas": timed(pallas_bwd, (x, dy, mean, r, scale))}
    for name, run in runs.items():
        t0 = time.perf_counter()
        float(run())
        print(f"# compile {name}: {time.perf_counter() - t0:.1f}s",
              flush=True)
    times = {k: [] for k in runs}
    for p in range(PASSES):
        for name, run in runs.items():
            t0 = time.perf_counter()
            float(run())
            times[name].append((time.perf_counter() - t0) / REP)
    for name, ts in times.items():
        print(f"{name}: {min(ts) * 1e3:.2f} ms")


if __name__ == "__main__":
    main()
