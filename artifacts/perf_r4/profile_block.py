"""Capture a device trace of the G=50 vmapped training block and dump
per-op self times grouped by category (the r3 methodology).

Run: cd /root/repo && PYTHONPATH="$PYTHONPATH:." python artifacts/perf_r4/profile_block.py [variant] [outdir]
"""

from __future__ import annotations

import glob
import sys
import time
from collections import defaultdict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

G = 50
BATCH = 32
LOCAL_STEPS = 1
REP = 8


def build_run(variant: str):
    import blades_tpu.models.layers as layers_mod
    import blades_tpu.models.resnet as resnet_mod
    from blades_tpu.core.task import TaskSpec

    if variant != "base":
        import importlib

        tb = importlib.import_module("time_bn")
        resnet_mod.BatchStatsNorm = tb.VARIANTS[variant]

    task = TaskSpec(model="resnet10", input_shape=(32, 32, 3), num_classes=10,
                    lr=0.1, compute_dtype="bfloat16").build()
    params = task.init_params(jax.random.PRNGKey(0))
    opt = jax.vmap(lambda _: task.init_client_opt_state(params))(
        jnp.arange(G))
    rng = np.random.default_rng(0)
    bx = jnp.asarray(rng.normal(size=(G, LOCAL_STEPS, BATCH, 32, 32, 3)),
                     jnp.float32)
    by = jnp.asarray(rng.integers(0, 10, size=(G, LOCAL_STEPS, BATCH)),
                     jnp.int32)
    keys = jax.random.split(jax.random.PRNGKey(0), G)
    mal = jnp.zeros((G,), bool)

    def body(c, _):
        bxp = bx + c * 1e-30
        upd, _o, loss = task.local_round_batched(params, opt, bxp, by, keys,
                                                 mal)
        return loss.sum() + upd.sum() * 1e-30, None

    @jax.jit
    def run():
        out, _ = lax.scan(body, jnp.float32(0.0), None, length=REP)
        return out

    return run


def dump_hlo_stats(logdir: str, top: int = 40):
    """Parse the xplane proto and print per-op self time."""
    from xprof.convert import raw_to_tool_data as rtd

    files = glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True)
    assert files, f"no xplane under {logdir}"
    data, _ = rtd.xspace_to_tool_data(files, "hlo_stats", {})
    import gzip
    import json as j

    if isinstance(data, bytes):
        try:
            data = gzip.decompress(data)
        except Exception:
            pass
        data = data.decode()
    rows = j.loads(data)
    return rows


def main():
    variant = sys.argv[1] if len(sys.argv) > 1 else "base"
    logdir = sys.argv[2] if len(sys.argv) > 2 else f"/tmp/prof_{variant}"
    run = build_run(variant)
    print(f"# compiling {variant}...", flush=True)
    float(run())
    with jax.profiler.trace(logdir):
        v = float(run())
    print(f"# traced val={v:.4f}", flush=True)
    time.sleep(1)
    rows = dump_hlo_stats(logdir)
    cols = [c["id"] for c in rows["cols"]]
    recs = []
    for r in rows["rows"]:
        rec = dict(zip(cols, [c.get("v") for c in r["c"]]))
        recs.append(rec)
    by_cat = defaultdict(float)
    for r in recs:
        by_cat[r["category"]] += r["total_self_time"] or 0.0
    print("== per-category self time (ms per block iter, REP=%d) ==" % REP)
    for cat, us in sorted(by_cat.items(), key=lambda kv: -kv[1]):
        print(f"  {cat:40s} {us / 1e3 / REP:8.2f} ms")
    print(f"  {'TOTAL':40s} {sum(by_cat.values()) / 1e3 / REP:8.2f} ms")
    print("== top 30 ops ==")
    for r in sorted(recs, key=lambda r: -(r["total_self_time"] or 0))[:30]:
        expr = (r["hlo_op_expression"] or "")[:110].replace("\n", " ")
        print(f"  {(r['total_self_time'] or 0) / 1e3 / REP:7.3f} ms "
              f"x{int(r['occurrences'] or 0):4d} [{r['category']}] "
              f"{r['bound_by']} dma%={r['dma_stall_percent']}: {expr}")


if __name__ == "__main__":
    main()
