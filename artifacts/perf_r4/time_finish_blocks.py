"""Round-4: fused finish vs _BLOCK_D (grid-step overhead hypothesis).

Run: cd /root/repo && PYTHONPATH="$PYTHONPATH:." python artifacts/perf_r4/time_finish_blocks.py
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

import blades_tpu.ops.pallas_round as pr

N = 1000
D = 4_903_242
PASSES = 6
REP = 6


def build(block_d: int, kw: dict, updates, mal):
    def f(u, m):
        # __wrapped__: bypass fused_finish's jit cache (1024 and 2048
        # pad to the SAME d_alloc, so the cached trace would collide).
        ff = pr.fused_finish.__wrapped__

        def body(c, _):
            m2 = m ^ (c != c)
            a, sq, bad = ff(u, m2, None, **kw)
            return a[0] + sq[0], None

        out, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=REP)
        return out

    return jax.jit(f)


def main():
    # ONE shared buffer (HBM fits only one): width divisible by every
    # tested block size, so no in-call padding for any variant.
    d_alloc = 4904960
    assert all(d_alloc % b == 0 for b in (512, 1024, 2048))
    updates = jnp.zeros((N, d_alloc), jnp.bfloat16)
    mal = jnp.arange(N) < N // 4

    cfgs = {
        "mean_nosan": dict(forge=None, agg=("mean",), sanitize=False),
        "median_alie_san": dict(forge=("alie", 1.5), agg=("median",),
                                sanitize=True),
    }
    runs = {}
    for block_d in (512, 1024, 2048):
        pr._BLOCK_D = block_d
        for cname, kw in cfgs.items():
            name = f"{cname}_b{block_d}"
            try:
                jf = build(block_d, kw, updates, mal)
                t0 = time.perf_counter()
                v = float(jf(updates, mal))
                print(f"# compile {name}: {time.perf_counter() - t0:.1f}s",
                      flush=True)
                runs[name] = jf
            except Exception as e:
                print(f"# {name} FAILED: {type(e).__name__}: {str(e)[:200]}",
                      flush=True)

    times = {v: [] for v in runs}
    for p in range(PASSES):
        for name, jf in runs.items():
            t0 = time.perf_counter()
            _ = float(jf(updates, mal))
            times[name].append((time.perf_counter() - t0) / REP)

    print(json.dumps({v: {"ms_min": round(min(ts) * 1e3, 1)}
                      for v, ts in times.items()}, indent=2))


if __name__ == "__main__":
    main()
