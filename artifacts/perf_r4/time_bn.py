"""Round-4: BN formulation variants inside the vmapped training block.

Each variant swaps BatchStatsNorm.__call__ (patched only during trace/
compile; compiled executables keep their traced program), then all
variants are timed interleaved in one process, min over >=6 passes.

Run: cd /root/repo && PYTHONPATH="$PYTHONPATH:." python artifacts/perf_r4/time_bn.py
"""

from __future__ import annotations

import json
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

import blades_tpu.models.layers as layers_mod
from blades_tpu.core.task import TaskSpec

G = 50
BATCH = 32
LOCAL_STEPS = 1
REP = 8
PASSES = 6

_ORIG_CALL = layers_mod.BatchStatsNorm.__call__


# ---------------------------------------------------------------------------
# Variant BN bodies: all per-lane (B, H, W, C); vmap adds the client axis.
# ---------------------------------------------------------------------------


def bn_onepass(self, x):
    """E[x^2] - E[x]^2 so both stats come from ONE pass over x."""
    features = x.shape[-1]
    scale = self.param("scale", jax.nn.initializers.ones, (features,))
    bias = self.param("bias", jax.nn.initializers.zeros, (features,))
    axes = tuple(range(x.ndim - 1))
    mean = jnp.mean(x, axis=axes)
    mean2 = jnp.mean(x * x, axis=axes)
    var = mean2 - mean * mean
    y = (x - mean) * lax.rsqrt(var + self.epsilon)
    return y * scale + bias


def bn_f32stats(self, x):
    """Stats accumulated in f32 (bf16 activations)."""
    features = x.shape[-1]
    scale = self.param("scale", jax.nn.initializers.ones, (features,))
    bias = self.param("bias", jax.nn.initializers.zeros, (features,))
    axes = tuple(range(x.ndim - 1))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes)
    var = jnp.mean(xf * xf, axis=axes) - mean * mean
    y = (xf - mean) * lax.rsqrt(var + self.epsilon)
    return (y * scale + bias).astype(x.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def _bn_cvjp(x, scale, bias, eps):
    y, _ = _bn_cvjp_fwd(x, scale, bias, eps)
    return y


def _bn_cvjp_fwd(x, scale, bias, eps):
    axes = tuple(range(x.ndim - 1))
    n = 1
    for a in axes:
        n *= x.shape[a]
    mean = jnp.mean(x, axis=axes)
    var = jnp.mean(x * x, axis=axes) - mean * mean
    r = lax.rsqrt(var + eps)
    xhat = (x - mean) * r
    y = xhat * scale + bias
    return y, (xhat, r, scale, n)


def _bn_cvjp_bwd(eps, res, dy):
    xhat, r, scale, n = res
    axes = tuple(range(dy.ndim - 1))
    dbias = jnp.sum(dy, axis=axes)
    dscale = jnp.sum(dy * xhat, axis=axes)
    dxhat = dy * scale
    mean_dxhat = jnp.sum(dxhat, axis=axes) / n
    mean_dxhat_xhat = dscale * scale / n
    dx = r * (dxhat - mean_dxhat - xhat * mean_dxhat_xhat)
    return dx, dscale, dbias


_bn_cvjp.defvjp(_bn_cvjp_fwd, _bn_cvjp_bwd)


def bn_customvjp(self, x):
    """Hand-written BN backward (saves xhat; standard 2-reduction bwd)."""
    features = x.shape[-1]
    scale = self.param("scale", jax.nn.initializers.ones, (features,))
    bias = self.param("bias", jax.nn.initializers.zeros, (features,))
    return _bn_cvjp(x, scale.astype(x.dtype), bias.astype(x.dtype),
                    self.epsilon)


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def _bn_cvjp2(x, scale, bias, eps):
    y, _ = _bn_cvjp2_fwd(x, scale, bias, eps)
    return y


def _bn_cvjp2_fwd(x, scale, bias, eps):
    axes = tuple(range(x.ndim - 1))
    n = 1
    for a in axes:
        n *= x.shape[a]
    mean = jnp.mean(x, axis=axes)
    var = jnp.mean(x * x, axis=axes) - mean * mean
    r = lax.rsqrt(var + eps)
    y = (x - mean) * r * scale + bias
    return y, (x, mean, r, scale, n)


def _bn_cvjp2_bwd(eps, res, dy):
    """Saves x (the conv output, which XLA materializes anyway) instead
    of xhat; recomputes xhat elementwise in the backward."""
    x, mean, r, scale, n = res
    axes = tuple(range(dy.ndim - 1))
    xhat = (x - mean) * r
    dbias = jnp.sum(dy, axis=axes)
    dscale = jnp.sum(dy * xhat, axis=axes)
    dxhat = dy * scale
    dx = r * (dxhat - jnp.sum(dxhat, axis=axes) / n
              - xhat * (dscale * scale / n))
    return dx, dscale, dbias


_bn_cvjp2.defvjp(_bn_cvjp2_fwd, _bn_cvjp2_bwd)


def bn_customvjp_savex(self, x):
    features = x.shape[-1]
    scale = self.param("scale", jax.nn.initializers.ones, (features,))
    bias = self.param("bias", jax.nn.initializers.zeros, (features,))
    return _bn_cvjp2(x, scale.astype(x.dtype), bias.astype(x.dtype),
                     self.epsilon)


import flax.linen as nn  # noqa: E402

import blades_tpu.models.resnet as resnet_mod  # noqa: E402


def bn_class(body):
    """A fresh flax Module class NAMED BatchStatsNorm (so param paths are
    unchanged) whose __call__ is the variant body."""
    ns = {
        "__annotations__": {"epsilon": float, "use_scale": bool,
                            "use_bias": bool},
        "epsilon": 1e-5,
        "use_scale": True,
        "use_bias": True,
        "__call__": nn.compact(body),
        "__module__": __name__,
    }
    return type("BatchStatsNorm", (nn.Module,), ns)


VARIANTS = {
    "base": layers_mod.BatchStatsNorm,
    "onepass": bn_class(bn_onepass),
    "f32stats": bn_class(bn_f32stats),
    "customvjp": bn_class(bn_customvjp),
    "customvjp_savex": bn_class(bn_customvjp_savex),
}


def make_timed(task, params, opt, bx, by, keys, mal):
    def body(c, _):
        bxp = bx + c * 1e-30
        upd, _o, loss = task.local_round_batched(params, opt, bxp, by, keys,
                                                 mal)
        return loss.sum() + upd.sum() * 1e-30, None

    @jax.jit
    def run():
        out, _ = lax.scan(body, jnp.float32(0.0), None, length=REP)
        return out

    return run


def main():
    rng = np.random.default_rng(0)
    bx = jnp.asarray(rng.normal(size=(G, LOCAL_STEPS, BATCH, 32, 32, 3)),
                     jnp.float32)
    by = jnp.asarray(rng.integers(0, 10, size=(G, LOCAL_STEPS, BATCH)),
                     jnp.int32)
    keys = jax.random.split(jax.random.PRNGKey(0), G)
    mal = jnp.zeros((G,), bool)

    task = TaskSpec(model="resnet10", input_shape=(32, 32, 3), num_classes=10,
                    lr=0.1, compute_dtype="bfloat16").build()
    params = task.init_params(jax.random.PRNGKey(0))
    opt = jax.vmap(lambda _: task.init_client_opt_state(params))(
        jnp.arange(G))

    names = sys.argv[1:] or list(VARIANTS)
    runs = {}
    for name in names:
        resnet_mod.BatchStatsNorm = VARIANTS[name]
        try:
            run = make_timed(task, params, opt, bx, by, keys, mal)
            t0 = time.perf_counter()
            val = float(run())  # traces+compiles under the patch
            print(f"# compile {name}: {time.perf_counter() - t0:.1f}s "
                  f"val={val:.4f}", flush=True)
            runs[name] = run
        finally:
            resnet_mod.BatchStatsNorm = layers_mod.BatchStatsNorm

    times = {v: [] for v in runs}
    for p in range(PASSES):
        for v, run in runs.items():
            t0 = time.perf_counter()
            _ = float(run())
            times[v].append((time.perf_counter() - t0) / REP)

    print(json.dumps({v: {"ms_min": round(min(ts) * 1e3, 2)}
                      for v, ts in times.items()}, indent=2))


if __name__ == "__main__":
    main()
