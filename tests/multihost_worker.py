"""Worker process for the 2-process jax.distributed smoke test.

Usage: python multihost_worker.py <coordinator_addr> <num_procs> <proc_id>

Each process brings 4 virtual CPU devices; the global mesh spans all 8
across both processes — the TPU-native analogue of the reference's NCCL
``init_process_group`` bring-up (ref: fllib/communication/
communicator.py:119-184), with the client->server gradient push riding
the same distributed runtime the collectives use.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Before ANY jax import/backend use: jax < 0.5 lacks jax_num_cpu_devices
# and its CPU client reads --xla_force_host_platform_device_count from
# XLA_FLAGS exactly once, at first backend creation.
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f]
os.environ["XLA_FLAGS"] = " ".join(
    _flags + ["--xla_force_host_platform_device_count=4"])

import jax  # noqa: E402

if hasattr(jax.config, "jax_num_cpu_devices"):
    jax.config.update("jax_num_cpu_devices", 4)
jax.config.update("jax_platforms", "cpu")

from blades_tpu.parallel import init_distributed  # noqa: E402


def main(coord: str, num_procs: int, proc_id: int) -> None:
    init_distributed(coordinator_address=coord, num_processes=num_procs,
                     process_id=proc_id)
    assert jax.process_count() == num_procs, jax.process_count()
    assert jax.device_count() == 4 * num_procs, jax.device_count()

    import jax.numpy as jnp
    import numpy as np

    from blades_tpu.adversaries import get_adversary, make_malicious_mask
    from blades_tpu.core import FedRound, Server, TaskSpec
    from blades_tpu.parallel import make_mesh, shard_map_step
    from blades_tpu.parallel.mesh import client_axis_sharding, replicated_sharding

    N = 16
    task = TaskSpec(model="mlp", lr=0.1, input_shape=(8, 8, 1)).build()
    server = Server.from_config(aggregator="Median", lr=1.0)
    adv = get_adversary("ALIE", num_clients=N, num_byzantine=4)
    fr = FedRound(task=task, server=server, adversary=adv, batch_size=4,
                  num_clients=N)
    mesh = make_mesh()  # all 8 GLOBAL devices, both processes

    rng = np.random.default_rng(0)  # same host data on every process
    x = rng.normal(size=(N, 8, 8, 8, 1)).astype(np.float32)
    y = rng.integers(0, 10, size=(N, 8)).astype(np.int32)
    ln = np.full((N,), 8, np.int32)
    mal = np.asarray(make_malicious_mask(N, 4))

    cs = client_axis_sharding(mesh)
    rep = replicated_sharding(mesh)
    put = lambda a, s: jax.make_array_from_callback(  # noqa: E731
        a.shape, s, lambda idx: a[idx]
    )
    from blades_tpu.core.round import RoundState

    state = fr.init(jax.random.PRNGKey(0), N)
    state = RoundState(
        server=jax.tree.map(lambda a: put(np.asarray(a), rep), state.server),
        client_opt=jax.tree.map(lambda a: put(np.asarray(a), cs),
                                state.client_opt),
    )
    xs, ys, lns, mals = (put(a, cs) for a in (x, y, ln, mal))

    step = shard_map_step(fr, mesh)
    losses = []
    for r in range(3):
        state, m = step(state, xs, ys, lns, mals,
                        jax.random.fold_in(jax.random.PRNGKey(1), r))
        losses.append(float(m["train_loss"]))
    assert all(np.isfinite(losses)), losses
    print(f"proc {proc_id}: multihost round OK losses={losses}", flush=True)


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
