"""Partitioner tests (model: fllib/datasets/tests/test_dataset.py)."""

import numpy as np
import pytest

from blades_tpu.data.partition import (
    dirichlet_partition,
    iid_partition,
    partition_dataset,
    partition_proportions,
)


def test_iid_partition_covers_all_indices():
    shards = iid_partition(103, 7, seed=0)
    allidx = np.sort(np.concatenate(shards))
    assert np.array_equal(allidx, np.arange(103))
    sizes = [len(s) for s in shards]
    assert max(sizes) - min(sizes) <= 1


def test_iid_partition_deterministic():
    a = iid_partition(100, 5, seed=42)
    b = iid_partition(100, 5, seed=42)
    for x, y in zip(a, b):
        assert np.array_equal(x, y)
    c = iid_partition(100, 5, seed=43)
    assert any(not np.array_equal(x, y) for x, y in zip(a, c))


def test_dirichlet_partition_covers_and_respects_min_size():
    labels = np.repeat(np.arange(10), 100)
    shards = dirichlet_partition(labels, 8, alpha=0.1, seed=0)
    allidx = np.sort(np.concatenate(shards))
    assert np.array_equal(allidx, np.arange(1000))
    assert min(len(s) for s in shards) >= 10


def test_dirichlet_skew_increases_as_alpha_drops():
    labels = np.repeat(np.arange(10), 200)

    def skew(alpha):
        shards = dirichlet_partition(labels, 10, alpha=alpha, seed=1)
        part = partition_dataset(
            np.zeros((2000, 1), np.float32), labels, 10, iid=False, alpha=alpha, seed=1
        )
        props = partition_proportions(part, 10).astype(float)
        props /= props.sum(axis=1, keepdims=True)
        # Mean per-client entropy: lower = more skew.
        with np.errstate(divide="ignore", invalid="ignore"):
            ent = -np.nansum(np.where(props > 0, props * np.log(props), 0.0), axis=1)
        return ent.mean()

    assert skew(0.1) < skew(10.0)


def test_partition_dataset_padding_is_cyclic_real_rows():
    x = np.arange(40, dtype=np.float32).reshape(20, 2)
    y = np.arange(20, dtype=np.int32)
    part = partition_dataset(x, y, 3, iid=True, seed=0)
    for i in range(3):
        n = part.lengths[i]
        real = set(map(tuple, part.x[i, :n]))
        padded = set(map(tuple, part.x[i, n:]))
        assert padded <= real  # padding rows are copies of the client's own rows


def test_partition_dataset_max_shard_cap():
    x = np.zeros((100, 2), np.float32)
    y = np.zeros(100, np.int32)
    part = partition_dataset(x, y, 4, iid=True, seed=0, max_shard=10)
    assert part.x.shape == (4, 10, 2)
    assert (part.lengths == 10).all()


def test_synthetic_dataset_seed_determinism():
    from blades_tpu.data import DatasetCatalog

    a = DatasetCatalog.get_dataset("mnist", num_clients=4, seed=0)
    b = DatasetCatalog.get_dataset("mnist", num_clients=4, seed=0)
    c = DatasetCatalog.get_dataset("mnist", num_clients=4, seed=1)
    assert np.array_equal(a.train.x, b.train.x)
    if a.synthetic:  # different seed must give different synthetic data
        assert not np.array_equal(a.train.x, c.train.x)


def test_random_crop_flip_augmentation():
    import jax
    import jax.numpy as jnp

    from blades_tpu.data.augment import get_augmentation, random_crop_flip

    x = jnp.arange(2 * 8 * 8 * 3, dtype=jnp.float32).reshape(2, 8, 8, 3)
    key = jax.random.PRNGKey(0)
    out = random_crop_flip(key, x, padding=2)
    assert out.shape == x.shape
    # Deterministic per key; different keys give different crops.
    assert jnp.array_equal(out, random_crop_flip(key, x, padding=2))
    assert not jnp.array_equal(out, random_crop_flip(jax.random.PRNGKey(1), x, padding=2))
    # Pixel multiset is preserved or zero-padded, never invented.
    assert out.max() <= x.max()
    assert get_augmentation("cifar") is random_crop_flip
    assert get_augmentation(None) is None


def test_dirichlet_partition_giant_federation_repair():
    """1000 clients x ~50 samples at alpha=0.1: rejection sampling cannot
    clear min_size, so the repair path must — every client >= 10 rows,
    full coverage, no duplicates, deterministic per seed."""
    rng = np.random.default_rng(0)
    y = rng.integers(0, 10, size=50_000)
    shards = dirichlet_partition(y, 1000, alpha=0.1, seed=3)
    sizes = [len(s) for s in shards]
    assert min(sizes) >= 10
    allidx = np.concatenate(shards)
    assert len(allidx) == 50_000 and len(np.unique(allidx)) == 50_000
    shards2 = dirichlet_partition(y, 1000, alpha=0.1, seed=3)
    for a, b in zip(shards, shards2):
        np.testing.assert_array_equal(np.sort(a), np.sort(b))


def test_dirichlet_partition_impossible_raises():
    y = np.zeros(50, dtype=int)
    with pytest.raises(ValueError, match="min_size"):
        dirichlet_partition(y, 10, alpha=0.1, seed=0)


def test_train_frac_subsamples_train_pool():
    """train_frac subsets the TRAIN pool before partitioning (the
    reference's dataset-subsetting dial); test data stays full."""
    from blades_tpu.data import DatasetCatalog

    full = DatasetCatalog.get_dataset("mnist", num_clients=4, seed=3)
    half = DatasetCatalog.get_dataset(
        {"type": "mnist", "train_frac": 0.5}, num_clients=4, seed=3)
    n_full = int(np.asarray(full.train.lengths).sum())
    n_half = int(np.asarray(half.train.lengths).sum())
    assert abs(n_half - n_full // 2) <= 4
    assert (np.asarray(half.test.lengths).sum()
            == np.asarray(full.test.lengths).sum())

    import pytest

    with pytest.raises(ValueError, match="train_frac"):
        DatasetCatalog.get_dataset({"type": "mnist", "train_frac": 0.0},
                                   num_clients=4, seed=3)
