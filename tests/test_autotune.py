"""Execution autotuner tests (blades_tpu/perf/autotune.py, ISSUE 10):

- plan-space enumeration: baseline-first ordering, tier partition (the
  reassociating tier absent without the opt-in), dedupe, truncation;
- selection: deterministic heuristic fallback off-TPU, measured winner
  under an injected fake clock, tie-break by heuristic rank;
- plan cache: atomic-write durability (orphaned ``.tmp`` cleanup),
  corrupt / stale-version / key-mismatch tolerance (miss => re-tune,
  never a crash), cross-process hits (the module is stdlib-only and
  loaded standalone in a subprocess), ``tools/show_plan.py``;
- driver integration: default-tier tuned runs are BIT-identical to the
  untuned path per aggregator (the acceptance criterion — pinned
  non-baseline default-tier plans, not just the trivial heuristic
  winner), provenance stamped schema-valid into round rows and sweep
  summaries, and kill-and-resume replays the checkpoint-recorded plan
  even when the on-disk cache has a different winner (no silent
  re-tune drift mid-trajectory).

Compile-heavy cases (per-aggregator zoo, streamed builds) are
slow-marked per the tier-1 budget convention (tools/check_tier1_budget).
"""

import json
import pickle
import subprocess
import sys
from pathlib import Path

import jax
import numpy as np
import pytest

from blades_tpu.algorithms import FedavgConfig
from blades_tpu.perf.autotune import (
    D_CHUNK_LADDER,
    PLAN_CACHE_VERSION,
    Plan,
    PlanCache,
    apply_plan,
    cache_key,
    enumerate_plans,
    select_plan,
    timed_measure_fn,
)

AUTOTUNE_PY = (Path(__file__).resolve().parents[1]
               / "blades_tpu" / "perf" / "autotune.py")


def tiny_config(**overrides):
    cfg = (
        FedavgConfig()
        .data(dataset="mnist", num_clients=6, seed=3)
        .training(global_model="mlp", server_lr=1.0, train_batch_size=8,
                  aggregator={"type": "Mean"})
        .client(lr=0.1)
        .evaluation(evaluation_interval=0)
    )
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


def _params(algo):
    return [np.asarray(p) for p in jax.tree.leaves(algo.state.server.params)]


def _run_rounds(cfg, rounds=3):
    algo = cfg.build()
    rows = [algo.train() for _ in range(rounds)]
    return algo, rows


# ---------------------------------------------------------------------------
# Plan / enumeration
# ---------------------------------------------------------------------------


def test_default_chunk_constants_agree():
    """autotune.py is stdlib-only by design (the cross-process cache
    test loads it standalone), so it repeats the canonical chunk
    literal instead of importing it — this pins the agreement."""
    from blades_tpu.parallel.streamed import DEFAULT_D_CHUNK

    assert Plan().d_chunk == DEFAULT_D_CHUNK
    assert FedavgConfig().d_chunk == DEFAULT_D_CHUNK
    assert DEFAULT_D_CHUNK in D_CHUNK_LADDER


def test_plan_validates_fields():
    with pytest.raises(ValueError, match="execution"):
        Plan(execution="warp")
    with pytest.raises(ValueError, match="mxu_finish"):
        Plan(mxu_finish="sometimes")
    with pytest.raises(ValueError, match="tier"):
        Plan(tier="experimental")
    with pytest.raises(ValueError, match="d_chunk"):
        Plan(d_chunk=512)


def test_plan_dict_roundtrip_and_unknown_fields():
    p = Plan(execution="streamed", d_chunk=1 << 16, mxu_finish="counts")
    assert Plan.from_dict(p.as_dict()) == p
    # A plan dict written by a FUTURE layout must read as stale, never be
    # half-applied.
    with pytest.raises(ValueError, match="unknown plan fields"):
        Plan.from_dict({**p.as_dict(), "warp_factor": 9})
    with pytest.raises(ValueError, match="dict"):
        Plan.from_dict("dense")


def test_enumerate_baseline_first_and_default_tier_only():
    space = enumerate_plans(
        executions=["dense"], d_chunks=[1 << 17],
        prefetch_options=[False, True],
    )
    assert space.baseline == Plan()  # today's heuristic resolution
    assert [p.prefetch for p in space.candidates] == [False, True]
    assert all(p.tier == "default" for p in space.candidates)
    assert space.truncated == 0


def test_enumerate_reassociating_tier_requires_opt_in():
    kw = dict(
        executions=["streamed", "dense"],  # baseline streamed
        d_chunks=[1 << 17, 1 << 16],
        mxu_modes=["", "counts", "all"],
        pack_factors=[1, 2],
    )
    default = enumerate_plans(**kw)
    # Without the opt-in: streamed-only (the dense switch reassociates),
    # no "all" finish (stats reassociate), no packing.
    assert all(p.execution == "streamed" for p in default.candidates)
    assert all(p.mxu_finish in ("", "counts") for p in default.candidates)
    assert default.baseline.d_chunk == 1 << 17
    both = enumerate_plans(allow_reassociating=True, **kw)
    tiers = {p.tier for p in both.candidates}
    assert tiers == {"default", "reassociating"}
    assert any(p.execution == "dense" for p in both.candidates)
    assert any(p.mxu_finish == "all" for p in both.candidates)
    # Every default-tier candidate survives the filter unchanged, in order.
    assert [p for p in both.candidates if p.tier == "default"] == \
        list(default.candidates)


def test_enumerate_dedupes_and_truncates():
    space = enumerate_plans(executions=["dense"], d_chunks=[1 << 17],
                            prefetch_options=[False, False, True])
    assert len(space.candidates) == 2  # duplicate collapsed
    tight = enumerate_plans(executions=["streamed"],
                            d_chunks=list(D_CHUNK_LADDER),
                            mxu_modes=["", "counts"],
                            max_candidates=4)
    assert len(tight.candidates) == 4
    assert tight.truncated == 2  # 3 chunks x 2 modes - 4, recorded loudly


# ---------------------------------------------------------------------------
# selection: heuristic fallback + injected-clock measured path
# ---------------------------------------------------------------------------


def test_select_heuristic_fallback_is_rank_zero():
    space = enumerate_plans(executions=["dense"], d_chunks=[1 << 17],
                            prefetch_options=[False, True])
    plan, prov = select_plan(space, measure_fn=None)
    assert plan == space.baseline
    assert prov["mode"] == "heuristic" and prov["timed"] is False
    assert [c["median_s"] for c in prov["candidates"]] == [None, None]
    assert prov["winner_id"] == plan.plan_id


def test_select_measured_picks_fastest_and_breaks_ties_by_rank():
    space = enumerate_plans(executions=["dense"], d_chunks=[1 << 17],
                            prefetch_options=[False, True])
    times = {False: 0.5, True: 0.2}
    plan, prov = select_plan(space,
                             measure_fn=lambda p: times[p.prefetch])
    assert plan.prefetch is True
    assert prov["mode"] == "measured" and prov["timed"] is True
    assert prov["candidates"][1]["median_s"] == 0.2
    # Exact tie: heuristic rank (enumeration order) wins => deterministic.
    plan, _ = select_plan(space, measure_fn=lambda p: 0.3)
    assert plan == space.baseline
    # Every measurement failing degrades to the heuristic, not a crash.
    plan, prov = select_plan(space, measure_fn=lambda p: None)
    assert plan == space.baseline and prov["mode"] == "heuristic"


def test_timed_measure_fn_injected_clock_deterministic():
    """The timed trial harness under a fake clock and a fake build:
    warmup dispatches are not timed, the median of reps is reported,
    and a candidate whose build raises is ranked out with a warning."""
    ticks = iter(range(1000))

    class FakeAlgo:
        trained = 0

        def train(self):
            FakeAlgo.trained += 1

    cfg = tiny_config()
    cfg.validate()
    measure = timed_measure_fn(
        cfg, warmup=1, reps=3,
        clock=lambda: float(next(ticks)),
        build=lambda cand: FakeAlgo(),
    )
    t = measure(Plan())
    # clock pairs (0,1), (2,3), (4,5): every timed dispatch spans one
    # tick under this clock -> median exactly 1.0, reproducibly.
    assert t == 1.0
    assert FakeAlgo.trained == 4  # 1 warmup + 3 reps
    # Per-ROUND normalization: one dispatch of a w=4 scan-window plan
    # advances 4 FL rounds, so the same dispatch median reports 4x
    # cheaper per round — without this a windowed candidate could never
    # beat w=1 on the measured path.
    assert measure(Plan(rounds_per_dispatch=4)) == 0.25

    def broken_build(cand):
        raise RuntimeError("no such kernel")

    bad = timed_measure_fn(cfg, clock=lambda: 0.0, build=broken_build)
    with pytest.warns(RuntimeWarning, match="no such kernel"):
        assert bad(Plan()) is None


def test_apply_plan_materialises_knobs():
    cfg = tiny_config()
    apply_plan(cfg, Plan(execution="streamed", d_chunk=1 << 16,
                         mxu_finish="counts"))
    assert cfg.execution == "streamed"
    assert cfg.d_chunk == 1 << 16
    assert cfg.mxu_finish == "counts"
    assert cfg.client_packing == "off"
    cfg2 = tiny_config()
    apply_plan(cfg2, Plan(rounds_per_dispatch=4, client_packing=2))
    assert cfg2.rounds_per_dispatch == 4
    assert cfg2.chained_dispatch is True
    assert cfg2.client_packing == 2
    # A USER-pinned window (the plan space never varies it, so
    # plan.rpd == config.rpd) keeps the user's own chained_dispatch
    # setting — the plain multi_step discipline is a legal explicit
    # choice the tuner must not silently rewrite.
    cfg3 = tiny_config(rounds_per_dispatch=4)
    apply_plan(cfg3, Plan(rounds_per_dispatch=4))
    assert cfg3.chained_dispatch is False


# ---------------------------------------------------------------------------
# plan cache: durability + corrupt tolerance
# ---------------------------------------------------------------------------


def _key(tmp_path, tier="default"):
    return cache_key("fp-abc", tier=tier, device_kind="cpu",
                     jaxlib_version="0.0-test")


def test_cache_roundtrip_and_key_scoping(tmp_path):
    cache = PlanCache(tmp_path)
    key = _key(tmp_path)
    assert cache.get(key) is None  # cold miss
    plan = Plan(prefetch=True)
    path = cache.put(key, plan, {"mode": "measured"})
    assert path is not None and Path(path).is_file()
    entry = cache.get(key)
    assert Plan.from_dict(entry["plan"]) == plan
    assert entry["provenance"]["mode"] == "measured"
    # A different tier / device / jaxlib is a different key: no crosstalk
    # (a reassociating-tier winner must never serve a default-tier run).
    assert cache.get(_key(tmp_path, tier="reassociating")) is None
    assert cache.get(cache_key("fp-abc", device_kind="tpu-v5e",
                               jaxlib_version="0.0-test")) is None


def test_cache_orphaned_tmp_cleanup(tmp_path):
    """A writer SIGKILLed before its os.replace leaves ``<entry>.tmp``;
    the next read deletes it and reports a miss (re-tune)."""
    cache = PlanCache(tmp_path)
    key = _key(tmp_path)
    tmp = cache._path(key).with_name(cache._path(key).name + ".tmp")
    tmp.parent.mkdir(parents=True, exist_ok=True)
    tmp.write_text('{"half": "written')
    assert cache.get(key) is None
    assert not tmp.exists()  # cleaned up, not left to accumulate
    # The published entry from a COMPLETED write is unaffected by a later
    # torn .tmp from a killed writer.
    cache.put(key, Plan())
    tmp.write_text("garbage")
    assert cache.get(key) is not None
    assert not tmp.exists()


@pytest.mark.parametrize("poison", [
    "not json at all {{{",
    json.dumps(["a", "list"]),
    json.dumps({"version": PLAN_CACHE_VERSION + 1, "key": {},
                "plan": Plan().as_dict()}),          # future version
    json.dumps({"version": PLAN_CACHE_VERSION, "key": {},
                "plan": {"execution": "warp"}}),     # unparsable plan
    json.dumps({"version": PLAN_CACHE_VERSION, "key": {"other": "key"},
                "plan": Plan().as_dict()}),          # key mismatch
])
def test_cache_corrupt_and_stale_entries_fall_back_to_retune(tmp_path,
                                                             poison):
    cache = PlanCache(tmp_path)
    key = _key(tmp_path)
    cache._path(key).parent.mkdir(parents=True, exist_ok=True)
    cache._path(key).write_text(poison)
    assert cache.get(key) is None  # miss => re-tune; never an exception
    # ...and the slot is recoverable: a fresh put over the bad file wins.
    cache.put(key, Plan(prefetch=True))
    assert Plan.from_dict(cache.get(key)["plan"]).prefetch is True


def test_cache_entries_surface_corruption_and_invalidate(tmp_path):
    cache = PlanCache(tmp_path)
    key = _key(tmp_path)
    cache.put(key, Plan())
    (tmp_path / "deadbeef.json").write_text("torn")
    entries = dict(cache.entries())
    assert entries["deadbeef"] is None  # reported, not hidden
    assert entries[PlanCache.digest(key)] is not None
    removed = cache.invalidate("deadbeef")
    assert removed == ["deadbeef.json"]
    assert cache.invalidate() == [f"{PlanCache.digest(key)}.json"]
    assert cache.entries() == []


def test_cache_cross_process_hit(tmp_path):
    """On-disk persistence across processes: a winner written here is
    served to a separate interpreter (the module is stdlib-only, loaded
    standalone — no jax import in the subprocess)."""
    cache = PlanCache(tmp_path)
    key = _key(tmp_path)
    cache.put(key, Plan(prefetch=True), {"mode": "measured"})
    script = f"""
import importlib.util, json, sys
spec = importlib.util.spec_from_file_location("at_sub", {str(AUTOTUNE_PY)!r})
at = importlib.util.module_from_spec(spec)
sys.modules["at_sub"] = at  # dataclasses resolves fields via sys.modules
spec.loader.exec_module(at)
cache = at.PlanCache({str(tmp_path)!r})
key = at.cache_key("fp-abc", tier="default", device_kind="cpu",
                   jaxlib_version="0.0-test")
entry = cache.get(key)
assert entry is not None, "cross-process miss"
print(json.dumps(entry["plan"]))
"""
    out = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert Plan.from_dict(json.loads(out.stdout)) == Plan(prefetch=True)


def test_show_plan_cli(tmp_path, capsys):
    """tools/show_plan.py: list names winners and flags corrupt entries;
    show dumps the full entry; invalidate removes by digest prefix."""
    from tools.show_plan import main as show_plan_main

    cache = PlanCache(tmp_path)
    key = _key(tmp_path)
    cache.put(key, Plan(prefetch=True), {"mode": "measured",
                                         "winner_id": Plan(prefetch=True)
                                         .plan_id})
    (tmp_path / "deadbeef.json").write_text("torn")
    digest = PlanCache.digest(key)

    assert show_plan_main(["--cache-dir", str(tmp_path)]) == 0
    listing = capsys.readouterr().out
    assert digest[:12] in listing and "CORRUPT/STALE" in listing
    assert Plan(prefetch=True).plan_id in listing

    assert show_plan_main(["--cache-dir", str(tmp_path), "show",
                           digest[:8]]) == 0
    shown = json.loads(capsys.readouterr().out)
    assert Plan.from_dict(shown["plan"]) == Plan(prefetch=True)

    assert show_plan_main(["--cache-dir", str(tmp_path), "invalidate",
                           digest[:8]]) == 0
    capsys.readouterr()
    assert show_plan_main(["--cache-dir", str(tmp_path), "show",
                           digest[:8]]) == 1
    capsys.readouterr()
    assert show_plan_main(["--cache-dir", str(tmp_path), "invalidate",
                           "--all"]) == 0
    assert cache.entries() == []


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------


def test_config_autotune_mode_normalization():
    cfg = tiny_config()
    assert cfg.autotune_mode is None
    for v in (True, 1, "on", "default"):
        cfg.autotune = v
        assert cfg.autotune_mode == "default"
    cfg.autotune = "reassociating"
    assert cfg.autotune_mode == "reassociating"
    for v in (False, None, 0, "off", ""):
        cfg.autotune = v
        assert cfg.autotune_mode is None
    cfg.autotune = "sometimes"
    with pytest.raises(ValueError, match="autotune"):
        cfg.autotune_mode


def test_config_validate_rejects_bad_autotune_settings():
    # Multi-chip tuning is legal since the pod-scale tier (ISSUE 18) —
    # only an EXPLICIT execution='hier' pin conflicts with the tuner.
    cfg = tiny_config()
    cfg.resources(autotune=True, num_devices=2)
    cfg.validate()
    cfg.resources(execution="hier")
    with pytest.raises(ValueError, match="autotune × execution='hier'"):
        cfg.validate()
    cfg2 = tiny_config()
    cfg2.resources(tuned_plan={"execution": "warp"})
    with pytest.raises(ValueError, match="execution"):
        cfg2.validate()
    cfg3 = tiny_config()
    cfg3.resources(mxu_finish="sometimes")
    with pytest.raises(ValueError, match="mxu_finish"):
        cfg3.validate()


# ---------------------------------------------------------------------------
# driver integration: selection, provenance, bit-identity
# ---------------------------------------------------------------------------


def test_heuristic_selection_off_tpu_matches_untuned_resolution(tmp_path):
    """On the CPU backend there is nothing meaningful to time, so the
    deterministic ranked heuristic selects candidates[0] — exactly what
    the hand-written heuristics resolve — and a second build serves the
    SAME plan from the on-disk cache."""
    cfg = tiny_config()
    cfg.resources(autotune=True, autotune_cache_dir=str(tmp_path))
    algo = cfg.build()
    prov = algo.plan_summary
    assert prov["mode"] == "heuristic" and prov["cache_hit"] is False
    assert algo.plan.execution == "dense"
    assert algo.plan.tier == "default"
    assert len(prov["candidates"]) >= 1
    assert prov["candidates"][0]["plan_id"] == algo.plan.plan_id

    cfg2 = tiny_config()
    cfg2.resources(autotune=True, autotune_cache_dir=str(tmp_path))
    algo2 = cfg2.build()
    assert algo2.plan == algo.plan
    assert algo2.plan_summary["mode"] == "cache"
    assert algo2.plan_summary["cache_hit"] is True

    row = algo2.train()
    assert row["plan_id"] == algo2.plan.plan_id
    assert row["autotune_cache_hit"] is True
    assert row["autotune_timed"] is False
    assert row["autotune_candidates"] == len(prov["candidates"])


def test_default_tier_pinned_plan_bit_identical_dense(tmp_path):
    """Acceptance: a NON-baseline default-tier plan (prefetch forced on,
    the dense path's non-default knob) reproduces the untuned trajectory
    bit for bit — not just the trivial heuristic winner."""
    base, rows0 = _run_rounds(tiny_config())
    pin = Plan(prefetch=True).as_dict()
    cfg = tiny_config()
    cfg.resources(autotune=True, tuned_plan=pin,
                  autotune_cache_dir=str(tmp_path))
    tuned, rows1 = _run_rounds(cfg)
    assert tuned.plan_summary["mode"] == "pinned"
    assert tuned._prefetcher is not None  # the plan actually engaged
    for a, b in zip(_params(base), _params(tuned)):
        np.testing.assert_array_equal(a, b)
    for r0, r1 in zip(rows0, rows1):
        assert r0["train_loss"] == r1["train_loss"]


@pytest.mark.slow
@pytest.mark.parametrize("aggregator", ["Median", "Trimmedmean"])
def test_default_tier_chunk_ladder_bit_identical_streamed(tmp_path,
                                                          aggregator):
    """Acceptance zoo (streamed): a default-tier plan moving the chunk
    width off the baseline (2^17 -> 2^16) on a chunk-invariant finish is
    bit-identical to the untuned streamed round, per aggregator."""
    def streamed_cfg():
        return tiny_config(execution="streamed",
                           aggregator={"type": aggregator})

    base, rows0 = _run_rounds(streamed_cfg(), rounds=2)
    pin = Plan(execution="streamed", d_chunk=1 << 16).as_dict()
    cfg = streamed_cfg()
    cfg.resources(autotune=True, tuned_plan=pin,
                  autotune_cache_dir=str(tmp_path))
    tuned, rows1 = _run_rounds(cfg, rounds=2)
    assert tuned.config.d_chunk == 1 << 16
    for a, b in zip(_params(base), _params(tuned)):
        np.testing.assert_array_equal(a, b)
    for r0, r1 in zip(rows0, rows1):
        assert r0["train_loss"] == r1["train_loss"]


def test_plan_space_pins_explicit_knobs(tmp_path):
    """Composition contract: a knob the user set explicitly is never
    varied — prefetch pinned off collapses the dense space to the
    baseline candidate only."""
    cfg = tiny_config()
    cfg.prefetch = "off"
    cfg._explicit.add("prefetch")
    cfg.resources(autotune=True, autotune_cache_dir=str(tmp_path))
    algo = cfg.build()
    assert len(algo.plan_summary["candidates"]) == 1
    assert algo.plan.prefetch is False


def test_stale_cached_window_plan_retunes_not_applies(tmp_path):
    """The config fingerprint cannot see sweep-level window context
    (max_rounds / checkpoint_freq shape the eligible scan windows), so
    a cached winner may carry a rounds_per_dispatch the CURRENT run's
    constraints forbid — e.g. a w=8 window that would overshoot a
    12-round stop criterion or skip checkpoint boundaries.  Such an
    entry must be rejected (re-tune, marked cache_stale), never applied
    verbatim."""
    cfg = tiny_config()
    cfg.resources(autotune=True, autotune_cache_dir=str(tmp_path))
    algo = cfg.build()
    valid_plan = algo.plan
    # Sabotage: overwrite the entry with a windowed winner that is NOT
    # in the direct-API plan space (no sweep => windows stay (1,)).
    cache = PlanCache(tmp_path)
    for _, entry in cache.entries():
        cache.put(entry["key"],
                  Plan(rounds_per_dispatch=8), {"mode": "measured"})
    cfg2 = tiny_config()
    cfg2.resources(autotune=True, autotune_cache_dir=str(tmp_path))
    algo2 = cfg2.build()
    assert algo2.plan == valid_plan  # re-tuned, not the stale w=8 plan
    assert algo2.plan_summary["cache_hit"] is False
    assert algo2.plan_summary["cache_stale"] is True
    assert algo2.config.rounds_per_dispatch == 1
    # ...and the re-tune overwrote the stale entry: third build hits.
    cfg3 = tiny_config()
    cfg3.resources(autotune=True, autotune_cache_dir=str(tmp_path))
    assert cfg3.build().plan_summary["cache_hit"] is True


def test_reassociating_tier_pins_explicit_packing_off(tmp_path):
    """Composition contract: client_packing='off' set EXPLICITLY is
    never varied, even by the reassociating tier — only 'auto' (a
    standing request to resolve) or the untouched default may be."""
    cfg = tiny_config()
    cfg.resources(autotune="reassociating", client_packing="off",
                  autotune_cache_dir=str(tmp_path))
    algo = cfg.build()
    assert "client_packing" in cfg._explicit
    assert all("|p1|" in c["plan_id"]
               for c in algo.plan_summary["candidates"])
    assert algo.plan.client_packing == 1


def test_lanes_gate_uses_normalized_autotune_mode():
    """An explicit autotune: 'off' in a trial config must not knock its
    lane group back to sequential execution (the gate reads the
    NORMALIZED mode, not raw truthiness of the string)."""
    from blades_tpu.tune.sweep import _lanes_eligible

    trial = {
        "dataset_config": {"type": "mnist", "num_clients": 6,
                           "train_bs": 8, "seed": 3},
        "global_model": "mlp",
        "server_config": {"lr": 1.0},
        "autotune": "off",
    }
    assert _lanes_eligible("FEDAVG", trial, [0, 1]) is True
    assert _lanes_eligible("FEDAVG", {**trial, "autotune": "on"},
                           [0, 1]) is False


def test_measured_selection_with_fake_timer_is_deterministic(tmp_path,
                                                             monkeypatch):
    """Drive the MEASURED path off-TPU: timing_available patched true
    and a deterministic fake measure ranking the non-baseline candidate
    fastest — the tuner must pick it, stamp timed provenance, and
    persist it for the next process."""
    from blades_tpu.perf import autotune as at

    monkeypatch.setattr(at, "timing_available", lambda: True)
    fake_times = {False: 0.9, True: 0.4}
    monkeypatch.setattr(
        at, "timed_measure_fn",
        lambda config, **kw: (lambda plan: fake_times[plan.prefetch]))
    cfg = tiny_config()
    cfg.resources(autotune=True, autotune_cache_dir=str(tmp_path))
    algo = cfg.build()
    assert algo.plan.prefetch is True  # the measured winner, not rank 0
    prov = algo.plan_summary
    assert prov["mode"] == "measured" and prov["timed"] is True
    assert [c["median_s"] for c in prov["candidates"]] == [0.9, 0.4]
    row = algo.train()
    assert row["autotune_timed"] is True
    # The winner persisted: an UNPATCHED build in this cache dir serves
    # the measured plan without re-measuring (cross-build cache hit).
    monkeypatch.undo()
    cfg2 = tiny_config()
    cfg2.resources(autotune=True, autotune_cache_dir=str(tmp_path))
    algo2 = cfg2.build()
    assert algo2.plan.prefetch is True
    assert algo2.plan_summary["mode"] == "cache"


# ---------------------------------------------------------------------------
# sweep integration: provenance, schema, kill-and-resume plan pinning
# ---------------------------------------------------------------------------


def _sweep_experiments(rounds=4):
    return {
        "at": {
            "run": "FEDAVG",
            "stop": {"training_iteration": rounds},
            "config": {
                "dataset_config": {"type": "mnist", "num_clients": 6,
                                   "train_bs": 8, "seed": 3},
                "global_model": "mlp",
                "evaluation_interval": 2,
                "server_config": {"lr": 1.0},
            },
        }
    }


def test_sweep_autotune_provenance_and_schema(tmp_path):
    """--autotune end to end: rows stream schema-valid with the plan
    fields stamped, and the summary carries the full selection record."""
    from blades_tpu.obs import validate_jsonl
    from blades_tpu.tune import run_experiments

    summaries = run_experiments(
        _sweep_experiments(), storage_path=str(tmp_path / "sweep"),
        verbose=0, autotune=True, plan_cache_dir=str(tmp_path / "plans"),
        cost_analysis=False,
    )
    (s,) = summaries
    assert "status" not in s
    at = s["autotune"]
    assert at["mode"] in ("heuristic", "measured")
    assert at["winner_id"] and at["candidates"]
    assert at["cache_hit"] is False
    tdir = tmp_path / "sweep" / "at" / "at_00000"
    # Schema-valid stream with the plan fields on every row.
    num_valid, errors = validate_jsonl(tdir / "metrics.jsonl")
    assert errors == [] and num_valid == 4
    rows = [json.loads(l) for l
            in (tdir / "metrics.jsonl").read_text().splitlines()]
    assert all(r["plan_id"] == at["winner_id"] for r in rows)
    assert all(r["autotune_candidates"] == len(at["candidates"])
               for r in rows)
    # The winner persisted: a second identical sweep is a cache hit.
    second = run_experiments(
        _sweep_experiments(), storage_path=str(tmp_path / "sweep2"),
        verbose=0, autotune=True, plan_cache_dir=str(tmp_path / "plans"),
        cost_analysis=False,
    )
    assert second[0]["autotune"]["mode"] == "cache"
    assert second[0]["autotune"]["cache_hit"] is True


def test_checkpoint_records_plan_and_resume_pins_it(tmp_path):
    """Kill-and-resume replays the IDENTICAL plan (the satellite's
    no-silent-re-tune-drift contract): the checkpoint payload records
    the resolved plan, and a --resume sweep pins it back via tuned_plan
    even when the on-disk plan cache now holds a DIFFERENT winner."""
    from blades_tpu.tune import run_experiments
    from blades_tpu.tune.sweep import verify_result_rounds

    plans = tmp_path / "plans"
    first = run_experiments(
        _sweep_experiments(rounds=8), storage_path=str(tmp_path / "s"),
        verbose=0, autotune=True, plan_cache_dir=str(plans),
        checkpoint_freq=2, preempt_after=5, cost_analysis=False,
    )
    assert first[0].get("status") == "ERROR"  # preempted, max_failures=0
    tdir = tmp_path / "s" / "at" / "at_00000"
    ckpts = sorted(tdir.glob("ckpt_*"))
    assert ckpts
    with open(ckpts[-1] / "algorithm_state.pkl", "rb") as f:
        saved = pickle.load(f)
    original_plan = saved["plan"]
    assert original_plan is not None
    assert Plan.from_dict(original_plan).tier == "default"

    # Sabotage: every cache entry now names a DIFFERENT default-tier
    # winner. A resume that consulted the cache would silently re-tune;
    # the checkpoint pin must beat it.
    cache = PlanCache(plans)
    drifted = Plan(**{**original_plan,
                      "prefetch": not original_plan["prefetch"]})
    for digest, entry in cache.entries():
        cache.put(entry["key"], drifted, {"mode": "measured"})

    second = run_experiments(
        _sweep_experiments(rounds=8), storage_path=str(tmp_path / "s"),
        verbose=0, autotune=True, plan_cache_dir=str(plans),
        checkpoint_freq=2, resume=True, cost_analysis=False,
    )
    (s,) = second
    assert "status" not in s and s["rounds"] == 8
    assert s["autotune"]["mode"] == "pinned"
    assert s["autotune"]["winner"] == original_plan
    assert verify_result_rounds(tdir / "result.json") == list(range(1, 9))
    # Every post-resume row ran under the original plan, not the
    # drifted cache winner.
    rows = [json.loads(l) for l
            in (tdir / "metrics.jsonl").read_text().splitlines()]
    assert all(r["plan_id"] == Plan.from_dict(original_plan).plan_id
               for r in rows)


def test_direct_api_resume_warns_on_plan_drift(tmp_path):
    """Fedavg.load_checkpoint (no sweep runner pinning) surfaces plan
    drift instead of silently continuing under a different plan."""
    cfg = tiny_config()
    cfg.resources(autotune=True, autotune_cache_dir=str(tmp_path / "p1"))
    algo = cfg.build()
    algo.train()
    algo.save_checkpoint(str(tmp_path / "ck"))

    pin = Plan(prefetch=not algo.plan.prefetch).as_dict()
    cfg2 = tiny_config()
    cfg2.resources(autotune=True, tuned_plan=pin,
                   autotune_cache_dir=str(tmp_path / "p1"))
    algo2 = cfg2.build()
    with pytest.warns(RuntimeWarning, match="pin the saved plan"):
        algo2.load_checkpoint(str(tmp_path / "ck"))


# ---------------------------------------------------------------------------
# pod-scale plan space (ISSUE 18)
# ---------------------------------------------------------------------------


def test_plan_id_mesh_free_regression_pin():
    """Mesh-free plan ids are byte-identical to the pre-pod format —
    the cache key and every historical round row depend on it."""
    assert Plan().plan_id == "dense|c131072|p1|mxu=off|w1|nopre"
    assert (Plan(execution="streamed", mxu_finish="counts").plan_id
            == "streamed|c131072|p1|mxu=counts|w1|nopre")


def test_plan_id_mesh_markers_only_when_engaged():
    assert (Plan(mesh_shape=(4, 2), tier="reassociating").plan_id
            == "dense|c131072|p1|mxu=off|w1|nopre|mesh=4x2")
    p = Plan(mesh_shape=(4, 2), collective="hier", tier="reassociating")
    assert p.plan_id.endswith("|mesh=4x2|hier")
    assert Plan.from_dict(p.as_dict()) == p
    # JSON round-trips tuples as lists; normalization restores equality.
    assert Plan.from_dict({**p.as_dict(), "mesh_shape": [4, 2]}) == p
    with pytest.raises(ValueError, match="needs a mesh_shape"):
        Plan(collective="hier")
    with pytest.raises(ValueError, match="collective"):
        Plan(collective="mesh")


def test_enumerate_mesh_candidates_require_devices_and_opt_in():
    kw = dict(executions=["dense"], d_chunks=[1 << 17],
              mesh_shapes=[None, (4, 2)], collectives=["ring", "hier"],
              num_devices=8)
    space = enumerate_plans(**kw)  # no opt-in: the mesh tier is absent
    assert space.baseline == Plan()
    assert all(p.mesh_shape is None for p in space.candidates)
    both = enumerate_plans(allow_reassociating=True, **kw)
    assert both.baseline == Plan()  # baseline-first even with the tier
    mesh = [p for p in both.candidates if p.mesh_shape is not None]
    assert mesh and all(p.tier == "reassociating" for p in mesh)
    assert any(p.collective == "hier" for p in mesh)
    for p in mesh:
        if p.collective == "hier":
            # hier never composes with scan windows / packing / prefetch
            # / the window store — the dense per-round program only.
            assert p.rounds_per_dispatch == 1 and p.client_packing == 1
            assert p.prefetch is False and p.state_window is None
    with pytest.raises(ValueError, match="num_devices > 1"):
        enumerate_plans(executions=["dense"], d_chunks=[1 << 17],
                        mesh_shapes=[(4, 2)])
    with pytest.raises(ValueError, match="tile exactly"):
        enumerate_plans(executions=["dense"], d_chunks=[1 << 17],
                        mesh_shapes=[(4, 2)], num_devices=16)


def test_apply_plan_mesh_sets_layout_and_hier_execution():
    cfg = tiny_config()
    apply_plan(cfg, Plan(mesh_shape=(4, 2), tier="reassociating"))
    assert cfg.mesh_shape == (4, 2)
    assert cfg.execution == "dense"
    cfg2 = tiny_config()
    apply_plan(cfg2, Plan(mesh_shape=(4, 2), collective="hier",
                          tier="reassociating"))
    assert cfg2.execution == "hier"
    assert cfg2.mesh_shape == (4, 2)


def test_plan_space_offers_hier_on_multichip_runs():
    """Multi-chip tuning (legal since ISSUE 18): the config's own mesh
    resolution stays candidates[0], and the reassociating tier adds
    exactly one hierarchical candidate on the config's mesh shape
    (defaulting to the flat (n, 1) layout)."""
    cfg = tiny_config(num_clients=8)
    cfg.resources(autotune="reassociating", num_devices=8)
    algo = cfg.build()
    try:
        space = algo._plan_space(allow_reassociating=True)
        assert space.baseline.mesh_shape is None  # today's resolution
        hier = [p for p in space.candidates if p.collective == "hier"]
        assert [p.mesh_shape for p in hier] == [(8, 1)]
    finally:
        algo.stop()
    cfg2 = tiny_config(num_clients=8)
    cfg2.resources(autotune="reassociating", num_devices=8,
                   mesh_shape=(4, 2))
    algo2 = cfg2.build()
    try:
        space2 = algo2._plan_space(allow_reassociating=True)
        assert space2.baseline.mesh_shape == (4, 2)
        assert "|mesh=4x2" in space2.baseline.plan_id
        hier2 = [p for p in space2.candidates if p.collective == "hier"]
        assert [p.mesh_shape for p in hier2] == [(4, 2)]
    finally:
        algo2.stop()
