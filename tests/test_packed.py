"""Client lane-packing (blades_tpu/parallel/packed.py).

Covers the tentpole's acceptance criteria:

- packed (pack_factor=2) FashionCNN and MLP rounds match the unpacked
  dense path per aggregator within fp-reassociation tolerance (the MLP
  case is bit-identical on this backend — pack-axis einsum vs per-lane
  matmul lower to the same contractions; grouped convs reassociate) —
  tier-1 runs the headline aggregators, the rest ride the ``slow`` lane
  exactly like ``tests/test_comm.py``'s identity sweep;
- equivalence holds under ALIE/IPM forging (the adversary reads the
  unpacked ``(n, d)`` matrix, so detection metrics and forged rows are
  the same experiment) and under the identity codec;
- pack/unpack are EXACT pytree inverses (pure layout transforms);
- ``"auto"`` falls back LOUDLY on ineligible configs — ResNet-18's wide
  stages, ``n % P != 0``, training-hook adversaries — and a forced
  ``client_packing`` int that cannot run raises at validate();
- kill-and-resume across a packed -> unpacked layout change via the
  chaos layer's resume harness: RoundState stays in canonical unpacked
  layout, so any pack_factor restores any other and the resumed
  trajectory matches an unpacked run within tolerance;
- ``pack_factor`` / ``packed_lanes`` are schema-registered, stamped
  into metrics.jsonl rows and sweep summaries (sequential and laned).
"""

import json
import warnings
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.adversaries import get_adversary
from blades_tpu.core import FedRound, Server, TaskSpec
from blades_tpu.models import MLP
from blades_tpu.ops.aggregators import AGGREGATORS
from blades_tpu.parallel.packed import (
    ClientPacking,
    PackingUnsupported,
    pack_replicated,
    pack_stacked,
    resolve_client_packing,
    unpack_stacked,
    unpack_tree,
)

_T1_AGGREGATORS = ("Mean",)

# fp-reassociation tolerance for packed-vs-unpacked trajectories
# (documented in README "Client packing"): grouped kernels reassociate
# reductions; over the few rounds tested the drift stays below 1e-4
# relative even through an aggregator's nonlinear selection.
RTOL = 1e-4


def _tiny_round(agg_name, *, model="mlp", adversary="ALIE", codec=None,
                packing=None, forensics=False, num_batches=2):
    if model == "mlp":
        spec = MLP(hidden1=8, hidden2=8, num_classes=4)
        input_shape = (8, 8, 1)
    else:  # the reference FashionCNN on a small spatial grid
        spec, input_shape = "cnn", (12, 12, 1)
    task = TaskSpec(model=spec, input_shape=input_shape, num_classes=4,
                    lr=0.1).build()
    n, f = 6, 2
    server = Server.from_config(aggregator=agg_name, num_byzantine=f, lr=0.5)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, 12) + input_shape), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, size=(n, 12)), jnp.int32)
    ln = jnp.full((n,), 12, jnp.int32)
    mal = jnp.arange(n) < f
    adv = get_adversary({"type": adversary}, num_clients=n, num_byzantine=f)
    fr = FedRound(task=task, server=server, adversary=adv, batch_size=4,
                  num_batches_per_round=num_batches, num_clients=n,
                  codec=codec, forensics=forensics,
                  packing=ClientPacking(2) if packing else None,
                  trusted_data=((x[0, :8], y[0, :8])
                                if agg_name == "FLTrust" else None))
    return fr, (x, y, ln, mal)


def _run_rounds(fr, data, rounds=2, seed=5):
    x, y, ln, mal = data
    state = fr.init(jax.random.PRNGKey(0), 6)
    step = jax.jit(fr.step)
    metrics = []
    for r in range(rounds):
        state, m = step(state, x, y, ln, mal,
                        jax.random.fold_in(jax.random.PRNGKey(seed), r))
    return state, jax.device_get(m)


def _assert_close_trees(a, b, rtol=RTOL, msg=""):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=rtol, atol=rtol, err_msg=msg)


# ---------------------------------------------------------------------------
# pack/unpack: exact pytree inverses
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("model,shape", [
    ("mlp", (8, 8, 1)),
    # cnn/resnet roundtrips compile the conv-model init (~7 s each);
    # tier-1 already exercises the Conv/BSN pack rules end-to-end via
    # test_packed_cnn_ipm_forensics_detection_parity.
    pytest.param("cnn", (12, 12, 1), marks=pytest.mark.slow),
    pytest.param("resnet10", (8, 8, 3), marks=pytest.mark.slow)])
def test_pack_unpack_roundtrip_exact(model, shape):
    spec = MLP(hidden1=8, hidden2=8, num_classes=4) if model == "mlp" \
        else model
    task = TaskSpec(model=spec, input_shape=shape, num_classes=4,
                    momentum=0.9).build()
    params = task.init_params(jax.random.PRNGKey(1))
    stacked = jax.tree.map(
        lambda p: jnp.stack([p + i for i in range(4)]), params)
    rt = unpack_stacked(pack_stacked(stacked, 2), 2)
    for a, b in zip(jax.tree.leaves(stacked), jax.tree.leaves(rt)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # momentum opt state packs by the same path rules
    opt = jax.tree.map(lambda p: jnp.stack([p, p * 2.0]),
                       task.init_client_opt_state(params))
    rt_opt = unpack_stacked(pack_stacked(opt, 2), 2)
    for a, b in zip(jax.tree.leaves(opt), jax.tree.leaves(rt_opt)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # replicated global params unpack to P identical client copies
    per_client = unpack_tree(pack_replicated(params, 2), 2)
    for orig, pc in zip(jax.tree.leaves(params),
                        jax.tree.leaves(per_client)):
        np.testing.assert_array_equal(np.asarray(pc[0]), np.asarray(orig))
        np.testing.assert_array_equal(np.asarray(pc[1]), np.asarray(orig))


# ---------------------------------------------------------------------------
# packed == unpacked per aggregator (ALIE forging, dropout active)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("agg_name", [
    a if a in _T1_AGGREGATORS else pytest.param(a, marks=pytest.mark.slow)
    for a in sorted(AGGREGATORS)])
def test_packed_matches_unpacked_per_aggregator(agg_name):
    """Acceptance: the packed MLP path reproduces the unpacked dense
    round per aggregator — aggregates, metrics, and full end state —
    within the documented fp tolerance, under ALIE forging with
    train-mode dropout active (mask equality is implied: a single
    differing mask would blow the tolerance immediately)."""
    fr_u, data = _tiny_round(agg_name)
    fr_p, _ = _tiny_round(agg_name, packing=True)
    s_u, m_u = _run_rounds(fr_u, data)
    s_p, m_p = _run_rounds(fr_p, data)
    for mk in ("train_loss", "agg_norm", "update_norm_mean"):
        np.testing.assert_allclose(float(m_u[mk]), float(m_p[mk]),
                                   rtol=RTOL, err_msg=(agg_name, mk))
    _assert_close_trees(s_u, s_p, msg=agg_name)


# CNN compile x packing x forensics (~6 s); packed parity and forensics
# detection are each pinned tier-1 separately
# (test_packed_matches_unpacked_per_aggregator[Mean], tests/test_ledger)
# (PR 20 budget rebalance).
@pytest.mark.slow
def test_packed_cnn_ipm_forensics_detection_parity():
    """Acceptance: grouped-conv packed FashionCNN under IPM forging with
    forensics on — the aggregator's per-lane decisions (benign mask,
    detection precision/recall/FPR) are IDENTICAL, adversary behavior
    unchanged, scalar metrics within tolerance."""
    fr_u, data = _tiny_round("Multikrum", model="cnn", adversary="IPM",
                             forensics=True, num_batches=1)
    fr_p, _ = _tiny_round("Multikrum", model="cnn", adversary="IPM",
                          forensics=True, num_batches=1, packing=True)
    s_u, m_u = _run_rounds(fr_u, data)
    s_p, m_p = _run_rounds(fr_p, data)
    for mk in ("byz_precision", "byz_recall", "byz_fpr", "num_flagged"):
        assert float(m_u[mk]) == float(m_p[mk]), mk
    np.testing.assert_array_equal(np.asarray(m_u["lane_benign_mask"]),
                                  np.asarray(m_p["lane_benign_mask"]))
    np.testing.assert_allclose(float(m_u["train_loss"]),
                               float(m_p["train_loss"]), rtol=RTOL)
    _assert_close_trees(s_u, s_p)


# Packing x codec transitivity (~6 s); both halves are tier-1 on their
# own (packed parity above, identity-codec bit-identity in
# tests/test_comm.py) (PR 20 budget rebalance).
@pytest.mark.slow
def test_packed_under_identity_codec():
    """Acceptance: packing composes with the comm layer — the identity
    codec is bit-transparent on the packed path (identical RoundState
    and metrics: the codec consumes the UNPACKED (n, d) matrix, exactly
    as it does today).  Packed+codec == unpacked+codec then follows by
    transitivity from the per-aggregator parity sweep above."""
    from blades_tpu.comm import CodecConfig

    fr_p, data = _tiny_round("Median", packing=True)
    fr_pc, _ = _tiny_round("Median", packing=True,
                           codec=CodecConfig("identity"))
    s_p, m_p = _run_rounds(fr_p, data)
    s_pc, m_pc = _run_rounds(fr_pc, data)
    assert float(m_p["agg_norm"]) == float(m_pc["agg_norm"])
    for a, b in zip(jax.tree.leaves(s_p), jax.tree.leaves(s_pc)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_packed_resnet_forced_equivalence():
    """BasicBlock ResNets have a packed formulation (grouped convs +
    per-channel BatchStatsNorm): forced pack_factor=2 on a tiny
    ResNet-10 round matches unpacked within tolerance.  ('auto' would
    decline — wide stages — which test_auto_fallback covers.)"""
    task = TaskSpec(model="resnet10", input_shape=(8, 8, 3),
                    num_classes=4, lr=0.1).build()
    server = Server.from_config(aggregator="Mean", lr=0.5)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 4, 8, 8, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, size=(2, 4)), jnp.int32)
    ln = jnp.full((2,), 4, jnp.int32)
    mal = jnp.zeros((2,), bool)
    out = {}
    for packing in (None, ClientPacking(2)):
        fr = FedRound(task=task, server=server, batch_size=2,
                      num_clients=2, packing=packing)
        state = fr.init(jax.random.PRNGKey(0), 2)
        state, m = jax.jit(fr.step)(state, x, y, ln, mal,
                                    jax.random.PRNGKey(3))
        out[packing is None] = (state, m)
    (s_p, m_p), (s_u, m_u) = out[False], out[True]
    np.testing.assert_allclose(float(m_u["train_loss"]),
                               float(m_p["train_loss"]), rtol=RTOL)
    _assert_close_trees(s_u, s_p)


# ---------------------------------------------------------------------------
# explicit dropout-key discipline (models/layers.py::keyed_dropout)
# ---------------------------------------------------------------------------


def test_keyed_dropout_discipline():
    """Masks are pure functions of (key, layer index): same key -> same
    output, different keys differ, eval needs no key, train without a
    key fails loudly."""
    m = MLP(hidden1=8, hidden2=8, num_classes=4)
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((2, 16)))["params"]
    x = jnp.ones((2, 16))
    k = jax.random.PRNGKey(7)
    a = m.apply({"params": params}, x, train=True, dropout_key=k)
    b = m.apply({"params": params}, x, train=True, dropout_key=k)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = m.apply({"params": params}, x, train=True,
                dropout_key=jax.random.PRNGKey(8))
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    m.apply({"params": params}, x)  # eval: no key needed
    with pytest.raises(ValueError, match="dropout key"):
        m.apply({"params": params}, x, train=True)


# ---------------------------------------------------------------------------
# eligibility: auto falls back loudly, forced raises
# ---------------------------------------------------------------------------


def _auto_decision(**cfg_kw):
    from blades_tpu.algorithms.config import FedavgConfig

    cfg = FedavgConfig()
    for k, v in cfg_kw.items():
        setattr(cfg, k, v)
    cfg.client_packing = "auto"
    cfg.validate()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        fr = cfg.get_fed_round()
    return fr, cfg._packing_decision, [str(x.message) for x in w]


def test_auto_packs_eligible_cnn():
    fr, dec, warned = _auto_decision(dataset="fashionmnist", num_clients=8,
                                     global_model="cnn")
    assert fr.packing == ClientPacking(2)
    assert dec == {"requested": "auto", "pack_factor": 2,
                   "packed_lanes": 4, "fallback": None}
    assert not any("falling back" in m for m in warned)


@pytest.mark.parametrize("kw,reason", [
    (dict(dataset="cifar10", num_clients=8, global_model="resnet18"),
     "wide stages"),
    (dict(dataset="fashionmnist", num_clients=7, global_model="cnn"),
     "not divisible"),
    (dict(dataset="fashionmnist", num_clients=8, global_model="mlp"),
     "vreg"),
])
def test_auto_fallback_is_loud(kw, reason):
    """Acceptance: 'auto' falls back LOUDLY (warning + recorded reason)
    on ineligible configs — ResNet-18 wide stages, n % P != 0, and
    models whose widths already fill the vector lanes."""
    fr, dec, warned = _auto_decision(**kw)
    assert fr.packing is None
    assert dec["pack_factor"] == 1 and reason in dec["fallback"]
    assert any("falling back" in m and reason in m for m in warned)


def test_auto_fallback_on_training_hook_adversary():
    """Training-side attacks hook per-client local training, which the
    packed lane has no formulation for — auto declines with the reason;
    update-forging adversaries (ALIE/IPM) pack fine."""
    fr, data = _tiny_round("Mean")
    adv = get_adversary({"type": "SignFlip"}, num_clients=6, num_byzantine=2)
    import dataclasses

    fr = dataclasses.replace(fr, adversary=adv)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        fr2, dec = resolve_client_packing(fr, "auto", num_clients=6)
    assert fr2.packing is None and "hooks local training" in dec["fallback"]
    assert any("falling back" in str(x.message) for x in w)
    # forced: same condition is a hard error
    with pytest.raises(PackingUnsupported, match="hooks local training"):
        resolve_client_packing(fr, 2, num_clients=6)


# End-to-end auto-resolution run (~5 s); the resolver's decision logic
# is covered tier-1 by the resolve_client_packing unit tests above
# (PR 20 budget rebalance).
@pytest.mark.slow
def test_auto_fallback_when_auto_execution_resolves_streamed(monkeypatch):
    """'auto' packing keeps its loud-fallback contract when
    execution='auto' itself resolves to the streamed round (HBM-driven,
    invisible to resolve_client_packing): the Fedavg constructor warns,
    strips the packing, records the reason, and trains unpacked instead
    of hard-failing."""
    from blades_tpu.algorithms.config import FedavgConfig
    from blades_tpu.algorithms.fedavg import Fedavg

    monkeypatch.setattr(Fedavg, "dense_matrix_hbm_limit", classmethod(
        lambda cls: 0))
    cfg = (FedavgConfig()
           .data(dataset="fashionmnist", num_clients=8)
           .training(global_model="cnn", aggregator="Median", server_lr=1.0,
                     train_batch_size=8)
           .resources(client_packing="auto"))
    cfg.validate()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        algo = cfg.build()
    assert any("falling back" in str(x.message)
               and "streaming" in str(x.message) for x in w)
    assert algo.fed_round.packing is None
    dec = algo.packing_summary
    assert dec["pack_factor"] == 1 and "streaming" in dec["fallback"]
    assert np.isfinite(algo.train()["train_loss"])


def test_forced_packing_validation_errors():
    from blades_tpu.algorithms.config import FedavgConfig

    with pytest.raises(ValueError, match="does not divide"):
        FedavgConfig().data(num_clients=7).resources(
            client_packing=2).validate()
    with pytest.raises(ValueError, match="int must be >= 2"):
        FedavgConfig().resources(client_packing=0).validate()
    with pytest.raises(ValueError, match="num_devices>1 is an unsupported"):
        c = FedavgConfig().data(num_clients=8)
        c.num_devices = 2
        c.resources(client_packing=2).validate()
    with pytest.raises(ValueError, match="dense round"):
        c = FedavgConfig().data(num_clients=8)
        c.execution = "streamed"
        c.resources(client_packing=2).validate()


# ---------------------------------------------------------------------------
# sweep integration: rows, summaries, kill-and-resume across layouts
# ---------------------------------------------------------------------------


def _packed_experiments(client_packing, rounds=3, **cfg):
    return {
        "packed": {
            "run": "FEDAVG",
            "stop": {"training_iteration": rounds},
            "config": {
                "dataset_config": {"type": "mnist", "num_clients": 6,
                                   "train_bs": 8},
                "global_model": "mlp",
                "evaluation_interval": rounds,
                "server_config": {"lr": 1.0},
                "client_packing": client_packing,
                **cfg,
            },
        }
    }


def test_packed_trial_streams_and_summarises(tmp_path):
    """pack_factor/packed_lanes appear per round in metrics.jsonl
    (schema-valid) and the sweep summary carries the packing decision."""
    from blades_tpu.obs.schema import main as schema_main
    from blades_tpu.tune import run_experiments

    [s] = run_experiments(_packed_experiments(2),
                          storage_path=str(tmp_path), verbose=0,
                          lanes=False, cost_analysis=False)
    assert "status" not in s
    assert s["packing"] == {"requested": 2, "pack_factor": 2,
                            "packed_lanes": 3, "fallback": None}
    tdir = Path(s["dir"])
    assert schema_main([str(tdir / "metrics.jsonl")]) == 0
    rows = [json.loads(l)
            for l in (tdir / "metrics.jsonl").read_text().splitlines()]
    assert len(rows) == 3
    assert all(r["pack_factor"] == 2 and r["packed_lanes"] == 3
               for r in rows)


def test_packed_kill_and_resume_to_unpacked(tmp_path):
    """Acceptance: kill a PACKED run mid-sweep (the chaos layer's
    SimulatedPreemption harness), resume it UNPACKED — RoundState is
    layout-free, so the restore just works, the round sequence has no
    duplicates/gaps, and the whole trajectory matches an end-to-end
    unpacked run within the packed-equivalence tolerance."""
    from blades_tpu.tune import run_experiments
    from blades_tpu.tune.sweep import verify_result_rounds

    base = run_experiments(
        _packed_experiments("off", rounds=6, evaluation_interval=6),
        storage_path=str(tmp_path / "base"), verbose=0, lanes=False,
        cost_analysis=False, scan_window=1)
    kill = run_experiments(
        _packed_experiments(2, rounds=6, evaluation_interval=6),
        storage_path=str(tmp_path / "kill"), verbose=0, lanes=False,
        cost_analysis=False, scan_window=1,
        checkpoint_freq=2, preempt_after=5)
    assert kill[0].get("status") == "ERROR"  # preempted, max_failures=0
    resumed = run_experiments(
        _packed_experiments("off", rounds=6, evaluation_interval=6),
        storage_path=str(tmp_path / "kill"), verbose=0, lanes=False,
        cost_analysis=False, scan_window=1,
        checkpoint_freq=2, resume=True)
    (b,), (r,) = base, resumed
    assert "status" not in r and r["rounds"] == 6
    assert r.get("resumed") == "from round 4"
    tdir = Path(r["dir"])
    assert verify_result_rounds(tdir / "result.json") == list(range(1, 7))
    rows_b = [json.loads(l) for l in
              (Path(b["dir"]) / "result.json").read_text().splitlines()]
    rows_r = [json.loads(l) for l in
              (tdir / "result.json").read_text().splitlines()]
    for rb, rr in zip(rows_b, rows_r):
        assert rb["training_iteration"] == rr["training_iteration"]
        np.testing.assert_allclose(rb["train_loss"], rr["train_loss"],
                                   rtol=RTOL)
    np.testing.assert_allclose(rows_b[-1]["test_acc"],
                               rows_r[-1]["test_acc"], atol=1e-3)


@pytest.mark.slow
def test_laned_trials_carry_packing_stamps(tmp_path):
    """Laned trials (one vmapped program per seed group) run the packed
    local round inside each lane and stamp pack_factor/packed_lanes
    into every row; group summaries surface the packing slice."""
    from blades_tpu.tune import run_experiments

    exps = _packed_experiments(2, rounds=2, evaluation_interval=0)
    exps["packed"]["config"]["dataset_config"]["seed"] = {
        "grid_search": [1, 2]}
    summaries = run_experiments(exps, storage_path=str(tmp_path), verbose=0,
                                lanes=True, cost_analysis=False)
    assert len(summaries) == 2
    for s in summaries:
        assert s.get("lanes") == 2, s
        assert s["packing"] == {"pack_factor": 2, "packed_lanes": 3}
        rows = [json.loads(l) for l in
                (Path(s["dir"]) / "metrics.jsonl").read_text().splitlines()]
        assert rows and all(r["pack_factor"] == 2 for r in rows)
