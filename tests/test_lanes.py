"""Experiment-parallelism tests: vmapped seed lanes vs the sequential
driver (SURVEY.md §2.9 — the reference runs Tune trials concurrently on a
Ray cluster; here the canonical seed sweep is one vmapped program)."""

import numpy as np

from blades_tpu.algorithms import get_algorithm_class
from blades_tpu.tune import run_seed_lanes


def _config():
    _, cfg = get_algorithm_class("FEDAVG", return_config=True)
    cfg.update_from_dict({
        "dataset_config": {"type": "mnist", "num_clients": 6, "train_bs": 16},
        "global_model": "mlp",
        "evaluation_interval": 2,
        "server_config": {"lr": 1.0, "aggregator": {"type": "Mean"}},
    })
    return cfg


def test_seed_lanes_match_sequential_driver():
    """Lane i of the vmapped sweep reproduces the sequential trial for
    seed_i (same key stream, same data partition, same metrics)."""
    seeds = [121, 122]
    rounds = 3
    lanes = run_seed_lanes(_config(), seeds, max_rounds=rounds)
    assert len(lanes) == 2 and all(len(rs) == rounds for rs in lanes)

    # Sequential driver for the first seed.
    cfg = _config()
    cfg.seed = seeds[0]
    algo = cfg.build()
    for r in range(rounds):
        result = algo.train()
        lane_row = lanes[0][r]
        assert lane_row["training_iteration"] == result["training_iteration"]
        np.testing.assert_allclose(
            lane_row["train_loss"], result["train_loss"], rtol=1e-4
        )
        if "test_acc" in result:
            np.testing.assert_allclose(
                lane_row["test_acc"], result["test_acc"], rtol=1e-4
            )

    # Distinct seeds actually produce distinct trials.
    assert lanes[0][0]["train_loss"] != lanes[1][0]["train_loss"]
