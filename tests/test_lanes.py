"""Experiment-parallelism tests: vmapped seed lanes vs the sequential
driver (SURVEY.md §2.9 — the reference runs Tune trials concurrently on a
Ray cluster; here the canonical seed sweep is one vmapped program)."""

import numpy as np
import pytest

from blades_tpu.algorithms import get_algorithm_class
from blades_tpu.tune import run_seed_lanes


def _config():
    _, cfg = get_algorithm_class("FEDAVG", return_config=True)
    cfg.update_from_dict({
        "dataset_config": {"type": "mnist", "num_clients": 6, "train_bs": 16},
        "global_model": "mlp",
        "evaluation_interval": 2,
        "server_config": {"lr": 1.0, "aggregator": {"type": "Mean"}},
    })
    return cfg


def test_seed_lanes_match_sequential_driver():
    """Lane i of the vmapped sweep reproduces the sequential trial for
    seed_i (same key stream, same data partition, same metrics)."""
    seeds = [121, 122]
    rounds = 3
    lanes = run_seed_lanes(_config(), seeds, max_rounds=rounds)
    assert len(lanes) == 2 and all(len(rs) == rounds for rs in lanes)

    # Sequential driver for the first seed.
    cfg = _config()
    cfg.seed = seeds[0]
    algo = cfg.build()
    for r in range(rounds):
        result = algo.train()
        lane_row = lanes[0][r]
        assert lane_row["training_iteration"] == result["training_iteration"]
        np.testing.assert_allclose(
            lane_row["train_loss"], result["train_loss"], rtol=1e-4
        )
        if "test_acc" in result:
            np.testing.assert_allclose(
                lane_row["test_acc"], result["test_acc"], rtol=1e-4
            )

    # Distinct seeds actually produce distinct trials.
    assert lanes[0][0]["train_loss"] != lanes[1][0]["train_loss"]


# ---------------------------------------------------------------------------
# Round-4: the default-on sweep lane path (VERDICT r3 item 2)
# ---------------------------------------------------------------------------


def _dp_experiment(rounds, seeds, epsilons):
    """The canonical DP grid (tuned_examples/fedavg_dp.yaml shape),
    scaled down for CI."""
    return {
        "fedavg_dp_ci": {
            "run": "FEDAVG_DP",
            "stop": {"training_iteration": rounds},
            "config": {
                "dataset_config": {
                    "type": "mnist", "num_clients": 6, "train_bs": 16,
                    "seed": {"grid_search": seeds},
                },
                "global_model": "mlp",
                "evaluation_interval": 2,
                "dp_epsilon": {"grid_search": epsilons},
                "dp_delta": 1.0e-6,
                "dp_clip_threshold": 1.0,
                "server_config": {"lr": 1.0, "aggregator": {"type": "Mean"}},
            },
        }
    }


@pytest.mark.slow  # 3-lane DP grid + sequential replays (~34 s; seed-lane parity stays tier-1)
def test_dp_grid_runs_as_lanes_with_result_parity(tmp_path):
    """The r2 'done' bar: the DP epsilon x seed grid runs as ONE vmapped
    lane group from the YAML-shaped experiment path, with per-row result
    parity against lanes=False."""
    import json

    from blades_tpu.tune.sweep import run_experiments

    rounds = 3
    exp = _dp_experiment(rounds, seeds=[121, 122], epsilons=[1.0, 100.0])
    s_lanes = run_experiments(exp, storage_path=str(tmp_path / "lanes"),
                              verbose=0, lanes=True)
    assert all(s.get("lanes") == 4 for s in s_lanes), s_lanes
    s_seq = run_experiments(exp, storage_path=str(tmp_path / "seq"),
                            verbose=0, lanes=False)
    assert not any("lanes" in s for s in s_seq)

    for sl, ss in zip(s_lanes, s_seq):
        rows_l = [json.loads(line) for line in
                  open(f"{sl['dir']}/result.json")]
        rows_s = [json.loads(line) for line in
                  open(f"{ss['dir']}/result.json")]
        assert len(rows_l) == len(rows_s) == rounds
        for rl, rs in zip(rows_l, rows_s):
            assert rl["training_iteration"] == rs["training_iteration"]
            np.testing.assert_allclose(rl["train_loss"], rs["train_loss"],
                                       rtol=2e-4)
            if "test_acc" in rs:
                np.testing.assert_allclose(rl["test_acc"], rs["test_acc"],
                                           atol=0.02)


def test_lane_groups_mixed_knobs_and_singletons():
    """Static knobs split groups; lane knobs merge them; singletons fall
    through to sequential."""
    from blades_tpu.tune.sweep import _lanes_eligible, lane_groups

    trials = [
        {"global_model": "mlp", "seed": 1, "server_config": {"lr": 1.0}},
        {"global_model": "mlp", "seed": 2, "server_config": {"lr": 1.0}},
        {"global_model": "mlp", "seed": 1, "server_config": {"lr": 0.5}},
        # different STATIC knob -> its own group
        {"global_model": "cnn", "seed": 1, "server_config": {"lr": 1.0}},
    ]
    groups = {tuple(g) for g in lane_groups(trials)}
    # trials 0-2 differ only in (seed, server_lr) -> one group; trial 3 alone
    assert groups == {(0, 1, 2), (3,)}
    assert not _lanes_eligible("FEDAVG", trials[3], [3])  # singleton


def test_lane_signature_seed_path_conflict_stays_sequential():
    """A trial carrying BOTH `seed` and `dataset_config.seed` with
    different values must not be laned (laning would silently pick one)."""
    from blades_tpu.tune.sweep import _lane_signature, lane_groups

    t1 = {"seed": 1, "dataset_config": {"type": "mnist", "seed": 7}}
    t2 = {"seed": 2, "dataset_config": {"type": "mnist", "seed": 9}}
    sig1, ov1 = _lane_signature(t1)
    assert ov1 == {}
    assert "__lane_conflict__" in sig1
    groups = {tuple(g) for g in lane_groups([t1, t2])}
    assert groups == {(0,), (1,)}

    # Aligned values are NOT a conflict.
    t3 = {"seed": 5, "dataset_config": {"type": "mnist", "seed": 5}}
    _, ov3 = _lane_signature(t3)
    assert ov3.get("seed") == 5


def test_lanes_eligible_bounds_update_matrix_hbm():
    """A group whose stacked L x n x d update matrix would exceed the
    dense-HBM budget must not lane (the sequential driver would stream)."""
    from blades_tpu.tune.sweep import _lanes_eligible

    trial = {
        "dataset_config": {"type": "cifar10", "num_clients": 200, "seed": 1},
        "global_model": "resnet18",  # 11.2M params
        "server_config": {"lr": 1.0, "aggregator": {"type": "Mean"}},
    }
    # 200 clients x 11.2M x 4 B = 8.9 GB per lane: even 2 lanes blow the
    # 6 GB dense budget.
    assert not _lanes_eligible("FEDAVG", trial, [0, 1])
    small = {
        "dataset_config": {"type": "mnist", "num_clients": 6, "seed": 1},
        "global_model": "mlp",
        "server_config": {"lr": 1.0, "aggregator": {"type": "Mean"}},
    }
    assert _lanes_eligible("FEDAVG", small, [0, 1])


def test_server_lr_lanes_reject_lr_schedule():
    """A laned server_lr with a configured lr_schedule must fail loudly
    (the schedule interpolation cannot take a traced lr)."""
    import pytest

    from blades_tpu.tune.lanes import run_lanes

    def builder():
        cfg = _config()
        cfg.lr_schedule = [[0, 1.0], [10, 0.1]]
        return cfg

    with pytest.raises(ValueError, match="lr_schedule"):
        run_lanes(builder, [{"server_lr": 1.0}, {"server_lr": 0.5}],
                  max_rounds=1)


def test_lane_group_failure_is_loud(tmp_path, monkeypatch):
    """A lane-group crash must warn, stamp the trials' summaries, and
    still run them sequentially."""
    import warnings

    import blades_tpu.tune.sweep as sweep_mod

    def boom(*a, **k):
        raise RuntimeError("lane boom")

    monkeypatch.setattr(sweep_mod, "_run_lane_group", boom)
    exp = _dp_experiment(2, seeds=[121, 122], epsilons=[1.0])
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        summaries = sweep_mod.run_experiments(
            exp, storage_path=str(tmp_path), verbose=0, lanes=True)
    assert any("fell back to sequential" in str(x.message) for x in w)
    assert all(s.get("lane_fallback", "").endswith("lane boom")
               for s in summaries), summaries
    assert all(s["rounds"] == 2 for s in summaries)
