"""Adversary tests (model: blades/adversaries/tests/test_adversary.py — every
adversary instantiated through the config path, plus semantic checks of the
attack math)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.adversaries import (
    ADVERSARIES,
    ALIEAdversary,
    AdaptiveAdversary,
    AttackclippedclusteringAdversary,
    IPMAdversary,
    LabelFlipAdversary,
    MinMaxAdversary,
    NoiseAdversary,
    SignFlipAdversary,
    SignGuardAdversary,
    benign_mean_std,
    get_adversary,
    make_malicious_mask,
)
from blades_tpu.ops.aggregators import Mean, Signguard

N, D, F = 10, 16, 3
KEY = jax.random.PRNGKey(0)


@pytest.fixture
def updates():
    return jax.random.normal(KEY, (N, D))


@pytest.fixture
def malicious():
    return make_malicious_mask(N, F)


def test_registry_resolves_all(updates, malicious):
    for name in ADVERSARIES:
        adv = get_adversary(
            name, num_clients=N, num_byzantine=F, num_classes=10
        )
        out = adv.on_updates_ready(
            updates, malicious, KEY, aggregator=Mean(), global_params=None
        )
        assert out.shape == updates.shape
        # Benign rows never touched by update-forging attacks.
        assert jnp.array_equal(out[F:], updates[F:])


def test_dotted_path_resolution():
    adv = get_adversary("blades.adversaries.IPMAdversary")
    assert isinstance(adv, IPMAdversary)


def test_benign_mean_std_matches_numpy(updates, malicious):
    mean, std = benign_mean_std(updates, malicious)
    ref = np.asarray(updates[F:])
    assert np.allclose(mean, ref.mean(axis=0), atol=1e-5)
    assert np.allclose(std, ref.std(axis=0, ddof=1), atol=1e-5)


def test_benign_mean_std_immune_to_nonfinite_malicious_rows(updates,
                                                            malicious):
    """A malicious lane whose training diverged must not contaminate
    the BENIGN statistics through the mask (0 * NaN = NaN under a
    multiply-mask) — this is also what keeps the malicious-lane elision
    paths (which never compute the dead rows) bit-equal to the full
    round in the divergence corner."""
    clean_mean, clean_std = benign_mean_std(updates, malicious)
    poisoned = updates.at[0].set(jnp.nan).at[1].set(jnp.inf)
    mean, std = benign_mean_std(poisoned, malicious)
    np.testing.assert_array_equal(np.asarray(mean), np.asarray(clean_mean))
    np.testing.assert_array_equal(np.asarray(std), np.asarray(clean_std))


def test_alie_forges_mean_plus_zmax_std(updates, malicious):
    adv = ALIEAdversary(num_clients=N, num_byzantine=F)
    out = adv.on_updates_ready(updates, malicious, KEY, aggregator=Mean())
    mean, std = benign_mean_std(updates, malicious)
    expect = mean + adv.z_max * std
    assert jnp.allclose(out[0], expect, atol=1e-5)
    assert jnp.allclose(out[0], out[F - 1])  # all malicious rows identical


def test_alie_zmax_value():
    # n=10, f=3: s = 5+1-3 = 3, cdf = (10-3-3)/(10-3) = 4/7
    from statistics import NormalDist

    adv = ALIEAdversary(num_clients=10, num_byzantine=3)
    assert np.isclose(adv.z_max, NormalDist().inv_cdf(4 / 7))


def test_alie_signguard_awareness(updates, malicious):
    adv = ALIEAdversary(num_clients=N, num_byzantine=F)
    plain = adv.on_updates_ready(updates, malicious, KEY, aggregator=Mean())
    aware = adv.on_updates_ready(updates, malicious, KEY, aggregator=Signguard())
    mean, std = benign_mean_std(updates, malicious)
    # First half of std negated (ref: alie_adversary.py:34-39).
    assert jnp.allclose(aware[0][: D // 2], (mean - adv.z_max * std)[: D // 2], atol=1e-5)
    assert jnp.allclose(aware[0][D // 2 :], plain[0][D // 2 :], atol=1e-5)


def test_ipm_negates_scaled_mean(updates, malicious):
    adv = IPMAdversary(scale=0.5)
    out = adv.on_updates_ready(updates, malicious, KEY)
    mean, _ = benign_mean_std(updates, malicious)
    assert jnp.allclose(out[0], -0.5 * mean, atol=1e-6)


def test_noise_rows_are_independent(updates, malicious):
    adv = NoiseAdversary(mean=0.0, std=1.0)
    out = adv.on_updates_ready(updates, malicious, KEY)
    assert not jnp.allclose(out[0], out[1])
    assert jnp.array_equal(out[F:], updates[F:])


def test_minmax_respects_distance_threshold(updates, malicious):
    adv = MinMaxAdversary()
    out = adv.on_updates_ready(updates, malicious, KEY, aggregator=Mean())
    forged = out[0]
    benign = np.asarray(updates[F:])
    threshold = max(
        np.linalg.norm(a - b) for a in benign for b in benign
    )
    max_dist = max(np.linalg.norm(np.asarray(forged) - b) for b in benign)
    assert max_dist <= threshold * 1.05  # binary search converged below threshold


def test_adaptive_pushes_beyond_extremes(updates, malicious):
    adv = AdaptiveAdversary()
    out = adv.on_updates_ready(updates, malicious, KEY)
    forged = np.asarray(out[0])
    benign = np.asarray(updates[F:])
    mean = benign.mean(axis=0)
    mx, mn = benign.max(axis=0), benign.min(axis=0)
    s = np.sign(mean)
    # Where s=-1: forged >= max; where s=+1: forged <= min (the Fang
    # construction pushes outside the benign envelope in the harmful
    # direction, ref: adaptive_adversary.py:33-56).
    assert (forged[s == -1] >= mx[s == -1] - 1e-5).all()
    assert (forged[s == 1] <= mn[s == 1] + 1e-5).all()


def test_signguard_attack_sign_census(updates, malicious):
    adv = SignGuardAdversary()
    out = adv.on_updates_ready(updates, malicious, KEY)
    forged = np.asarray(out[0])
    mean, _ = benign_mean_std(updates, malicious)
    mean = np.asarray(mean)
    assert (forged > 0).sum() == (mean > 0).sum()
    assert (forged < 0).sum() == (mean < 0).sum()
    assert (np.abs(forged) <= 1.0).all()


def test_attackclippedclustering_runs_and_forges(updates, malicious):
    adv = AttackclippedclusteringAdversary()
    out = adv.on_updates_ready(updates, malicious, KEY)
    assert jnp.isfinite(out).all()
    assert not jnp.allclose(out[0], updates[0])


def test_labelflip_hook(malicious):
    adv = LabelFlipAdversary(num_classes=10)
    y = jnp.array([0, 1, 9])
    # Malicious lane flips, benign doesn't.
    _, y_mal = adv.data_hook(None, y, jnp.array(True))
    _, y_ben = adv.data_hook(None, y, jnp.array(False))
    assert jnp.array_equal(y_mal, jnp.array([9, 8, 0]))
    assert jnp.array_equal(y_ben, y)


def test_signflip_hook():
    adv = SignFlipAdversary()
    grads = {"w": jnp.ones((3,)), "b": jnp.array(-2.0)}
    flipped = adv.grad_hook(grads, jnp.array(True))
    kept = adv.grad_hook(grads, jnp.array(False))
    assert jnp.array_equal(flipped["w"], -jnp.ones((3,)))
    assert float(flipped["b"]) == 2.0
    assert jnp.array_equal(kept["w"], grads["w"])


def test_update_attacks_jit_compatible(updates, malicious):
    adv = ALIEAdversary(num_clients=N, num_byzantine=F)

    @jax.jit
    def run(u, m, k):
        return adv.on_updates_ready(u, m, k, aggregator=Mean())

    out = run(updates, malicious, KEY)
    assert out.shape == updates.shape
