"""Unit tests for the jittable clustering primitives that replace sklearn."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.ops.clustering import agglomerative_majority, kmeans_majority


def two_blobs(n_a=7, n_b=3, dim=3, sep=10.0, seed=0):
    key = jax.random.PRNGKey(seed)
    ka, kb = jax.random.split(key)
    a = jax.random.normal(ka, (n_a, dim)) * 0.1
    b = jax.random.normal(kb, (n_b, dim)) * 0.1 + sep
    return jnp.concatenate([a, b])


def test_kmeans_majority_finds_larger_blob():
    pts = two_blobs()
    mask = np.asarray(kmeans_majority(pts))
    assert mask[:7].all() and not mask[7:].any()


def test_kmeans_majority_jit():
    pts = two_blobs()
    mask = np.asarray(jax.jit(kmeans_majority)(pts))
    assert mask.sum() == 7


@pytest.mark.parametrize("linkage", ["average", "single"])
def test_agglomerative_majority_two_blobs(linkage):
    pts = two_blobs(n_a=6, n_b=4)
    d = np.linalg.norm(np.asarray(pts)[:, None] - np.asarray(pts)[None, :], axis=-1)
    mask = np.asarray(agglomerative_majority(jnp.asarray(d), linkage=linkage))
    assert mask[:6].all() and not mask[6:].any()


def test_agglomerative_majority_minimal_n2():
    d = jnp.array([[0.0, 1.0], [1.0, 0.0]])
    mask = np.asarray(agglomerative_majority(d))
    # Two singletons: tie goes to the cluster containing point 0.
    assert mask.tolist() == [True, False]


def test_agglomerative_matches_scipy_average_linkage():
    # Cross-check cluster assignment against a straightforward O(n^3)
    # reference implementation of average-linkage on random points.
    rng = np.random.default_rng(0)
    pts = rng.normal(size=(12, 4))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)

    # Naive reference agglomerative clustering down to 2 clusters.
    clusters = [[i] for i in range(12)]
    while len(clusters) > 2:
        best, pair = np.inf, None
        for i in range(len(clusters)):
            for j in range(i + 1, len(clusters)):
                dd = np.mean([d[a, b] for a in clusters[i] for b in clusters[j]])
                if dd < best:
                    best, pair = dd, (i, j)
        i, j = pair
        clusters[i] = clusters[i] + clusters[j]
        del clusters[j]
    big = max(clusters, key=len)
    expected = np.zeros(12, dtype=bool)
    expected[big] = True
    if len(clusters[0]) == len(clusters[1]):
        expected = np.zeros(12, dtype=bool)
        expected[[c for c in clusters if 0 in c][0]] = True

    mask = np.asarray(agglomerative_majority(jnp.asarray(d), linkage="average"))
    assert (mask == expected).all()


def _naive_single_linkage_2(d):
    """O(n^3) reference single-linkage down to 2 clusters (numpy)."""
    n = d.shape[0]
    clusters = [[i] for i in range(n)]
    while len(clusters) > 2:
        best, pair = np.inf, None
        for i in range(len(clusters)):
            for j in range(i + 1, len(clusters)):
                dd = min(d[a, b] for a in clusters[i] for b in clusters[j])
                if dd < best:
                    best, pair = dd, (i, j)
        i, j = pair
        clusters[i] = clusters[i] + clusters[j]
        del clusters[j]
    a, b = clusters
    big = a if len(a) > len(b) else b if len(b) > len(a) else (a if 0 in a else b)
    mask = np.zeros(n, dtype=bool)
    mask[big] = True
    return mask


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_mst_single_linkage_matches_reference(seed):
    """The MST formulation is EXACTLY single-linkage-cut-at-2 (VERDICT r1
    #8 replaced the O(n^3) merge loop with Prim + heaviest-edge cut)."""
    rng = np.random.default_rng(seed)
    pts = rng.normal(size=(14, 3))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    expected = _naive_single_linkage_2(d)
    mask = np.asarray(agglomerative_majority(jnp.asarray(d), linkage="single"))
    assert (mask == expected).all()


def test_spectral_bipartition_matches_exact_on_separated_blobs():
    """Beyond the exactness threshold, average linkage takes the spectral
    path; on separated geometry both agree.  (Since r4 the exact loop is
    the default through n=2048 — spectral is forced here via the
    threshold to keep the >2048 escape path tested.)"""
    pts = np.asarray(two_blobs(n_a=130, n_b=70, sep=10.0))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    d = d / d.max() * 2.0  # cosine-like range [0, 2]
    dj = jnp.asarray(d)
    spectral = np.asarray(
        agglomerative_majority(dj, linkage="average", exact_threshold=64))
    exact = np.asarray(
        agglomerative_majority(dj, linkage="average", exact_threshold=512)
    )
    assert (spectral == exact).all()
    assert spectral[:130].all() and not spectral[130:].any()


def _angular_overlap_geometry(n, frac_b, angle, spread, seed):
    """Two cones of directions separated by `angle` radians with
    intra-cone `spread` — the ACC adversary's borderline regime where
    the attack cluster sits at the edge of the benign angular cloud."""
    rng = np.random.default_rng(seed)
    n_b = int(n * frac_b)
    mu_a = np.zeros(16); mu_a[0] = 1.0
    mu_b = np.zeros(16); mu_b[0] = np.cos(angle); mu_b[1] = np.sin(angle)
    pts = np.concatenate([
        rng.normal(size=(n - n_b, 16)) * spread + mu_a,
        rng.normal(size=(n_b, 16)) * spread + mu_b,
    ]).astype(np.float32)
    norm = pts / np.linalg.norm(pts, axis=1, keepdims=True)
    return jnp.asarray(np.clip(1.0 - norm @ norm.T, 0.0, 2.0))


@pytest.mark.parametrize("n", [129, 256])
def test_exact_linkage_is_default_in_adversarial_regime(n):
    """VERDICT r3 item 6: at the n the ACC adversary targets, the DEFAULT
    average-linkage path must be the exact Lance-Williams loop — no
    spectral approximation inside the supported range."""
    d = _angular_overlap_geometry(n, 0.3, angle=0.35, spread=0.18, seed=n)
    default = np.asarray(agglomerative_majority(d, linkage="average"))
    exact = np.asarray(
        agglomerative_majority(d, linkage="average", exact_threshold=4096))
    np.testing.assert_array_equal(default, exact)


def test_spectral_disagreement_quantified_on_borderline_geometry():
    """Quantify the >2048 spectral escape's divergence from exact
    average linkage exactly where it matters: overlapping angular
    clusters at the benign/attack boundary.  The bound documented here
    (<= 25% mask disagreement across the borderline sweep, exact
    agreement when the gap is clear) is the approximation contract."""
    worst = 0.0
    for angle, spread in [(0.5, 0.10), (0.35, 0.15), (0.30, 0.20)]:
        d = _angular_overlap_geometry(256, 0.3, angle, spread, seed=7)
        exact = np.asarray(
            agglomerative_majority(d, linkage="average",
                                   exact_threshold=4096))
        spectral = np.asarray(
            agglomerative_majority(d, linkage="average", exact_threshold=64))
        dis = (exact != spectral).mean()
        worst = max(worst, dis)
    # Measured: up to ~47% mask disagreement when the attack cone
    # overlaps the benign spread — spectral bipartition is NOT a
    # substitute for exact linkage in the adversarial regime (VERDICT r3
    # item 6's suspicion, confirmed).  That is exactly why the exact
    # loop is the default through n=2048; the spectral escape beyond it
    # is only trustworthy for clearly-separated geometry (asserted
    # below).  This assertion pins the measured regime so a silent
    # regression to worse-than-coin-flip behavior still fails.
    assert worst <= 0.5, f"spectral diverges {worst:.0%} from exact"
    assert worst > 0.05, "geometry no longer borderline; tighten the sweep"
    # Clearly separated cones: must agree exactly.
    d = _angular_overlap_geometry(256, 0.3, angle=1.2, spread=0.05, seed=3)
    exact = np.asarray(agglomerative_majority(d, linkage="average",
                                              exact_threshold=4096))
    spectral = np.asarray(agglomerative_majority(d, linkage="average",
                                                 exact_threshold=64))
    np.testing.assert_array_equal(exact, spectral)


# The single-linkage MST program's XLA compile at n=1000 costs >2 min on
# this 2-core CPU box (the timed EXECUTION it pins is <2 s) — tier-2, the
# same large-compile class as the 8-device shard_map suites.  The spectral
# average-linkage variant compiles fast and keeps the scale bound in
# tier-1.
@pytest.mark.parametrize("linkage", [
    pytest.param("single", marks=pytest.mark.slow), "average"])
def test_clustering_scales_to_1000(linkage):
    """n=1000 clustering step must complete in ~1s (VERDICT r1 #8)."""
    import time

    rng = np.random.default_rng(0)
    mu_a = np.zeros(8); mu_a[0] = 3.0
    mu_b = np.zeros(8); mu_b[1] = 3.0
    # Two tight cones of directions: intra-cosine-distance << inter, so
    # single linkage's bridge edge IS the inter-cluster gap (a blob at the
    # origin would give random directions and legitimate chaining).
    pts = np.concatenate([
        rng.normal(size=(750, 8)) * 0.1 + mu_a,
        rng.normal(size=(250, 8)) * 0.1 + mu_b,
    ]).astype(np.float32)
    norm = pts / np.linalg.norm(pts, axis=1, keepdims=True)
    d = jnp.asarray(np.clip(1.0 - norm @ norm.T, 0.0, 2.0))
    # Time-bound the O(n^2) formulations (single-linkage MST / spectral);
    # the exact average loop at n=1000 is TPU-fast (measured 150 ms on a
    # v5e) but CPU-slow, so the CI time bound pins the sub-cubic paths.
    kw = {"exact_threshold": 128} if linkage == "average" else {}
    mask = agglomerative_majority(d, linkage=linkage, **kw)  # compile
    t0 = time.perf_counter()
    mask = np.asarray(agglomerative_majority(d, linkage=linkage, **kw))
    dt = time.perf_counter() - t0
    assert dt < 2.0, f"{linkage} clustering took {dt:.2f}s at n=1000"
    assert mask.sum() == 750


@pytest.mark.slow  # 1000-client clustering compile (~12 s; small-n equivalence stays tier-1)
def test_clippedclustering_aggregates_1000_clients():
    """The full Clippedclustering aggregator at the north-star client
    count: must run (and fast) now that the merge loop is gone."""
    import time

    from blades_tpu.ops.aggregators import Clippedclustering

    rng = np.random.default_rng(1)
    updates = jnp.asarray(np.concatenate([
        rng.normal(size=(800, 2000)) * 0.1,
        rng.normal(size=(200, 2000)) * 0.1 + 1.0,
    ]).astype(np.float32))
    agg = Clippedclustering()
    state = agg.init(2000, 1000)
    call = jax.jit(lambda u, s: agg(u, s))
    out, state = call(updates, state)  # compile
    t0 = time.perf_counter()
    out, state = call(updates, state)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    # No wall bound: since r4 this runs the EXACT average-linkage loop
    # (spectral diverged up to 47% in adversarial regimes) — ~150 ms on
    # a v5e, but the sequential n-step merge loop is CPU-slow in CI.
    print(f"Clippedclustering n=1000 (exact linkage): {dt:.2f}s")
    assert np.isfinite(np.asarray(out)).all()
    assert np.abs(np.asarray(out)).max() < 0.5  # attackers rejected
