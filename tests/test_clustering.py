"""Unit tests for the jittable clustering primitives that replace sklearn."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.ops.clustering import agglomerative_majority, kmeans_majority


def two_blobs(n_a=7, n_b=3, dim=3, sep=10.0, seed=0):
    key = jax.random.PRNGKey(seed)
    ka, kb = jax.random.split(key)
    a = jax.random.normal(ka, (n_a, dim)) * 0.1
    b = jax.random.normal(kb, (n_b, dim)) * 0.1 + sep
    return jnp.concatenate([a, b])


def test_kmeans_majority_finds_larger_blob():
    pts = two_blobs()
    mask = np.asarray(kmeans_majority(pts))
    assert mask[:7].all() and not mask[7:].any()


def test_kmeans_majority_jit():
    pts = two_blobs()
    mask = np.asarray(jax.jit(kmeans_majority)(pts))
    assert mask.sum() == 7


@pytest.mark.parametrize("linkage", ["average", "single"])
def test_agglomerative_majority_two_blobs(linkage):
    pts = two_blobs(n_a=6, n_b=4)
    d = np.linalg.norm(np.asarray(pts)[:, None] - np.asarray(pts)[None, :], axis=-1)
    mask = np.asarray(agglomerative_majority(jnp.asarray(d), linkage=linkage))
    assert mask[:6].all() and not mask[6:].any()


def test_agglomerative_majority_minimal_n2():
    d = jnp.array([[0.0, 1.0], [1.0, 0.0]])
    mask = np.asarray(agglomerative_majority(d))
    # Two singletons: tie goes to the cluster containing point 0.
    assert mask.tolist() == [True, False]


def test_agglomerative_matches_scipy_average_linkage():
    # Cross-check cluster assignment against a straightforward O(n^3)
    # reference implementation of average-linkage on random points.
    rng = np.random.default_rng(0)
    pts = rng.normal(size=(12, 4))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)

    # Naive reference agglomerative clustering down to 2 clusters.
    clusters = [[i] for i in range(12)]
    while len(clusters) > 2:
        best, pair = np.inf, None
        for i in range(len(clusters)):
            for j in range(i + 1, len(clusters)):
                dd = np.mean([d[a, b] for a in clusters[i] for b in clusters[j]])
                if dd < best:
                    best, pair = dd, (i, j)
        i, j = pair
        clusters[i] = clusters[i] + clusters[j]
        del clusters[j]
    big = max(clusters, key=len)
    expected = np.zeros(12, dtype=bool)
    expected[big] = True
    if len(clusters[0]) == len(clusters[1]):
        expected = np.zeros(12, dtype=bool)
        expected[[c for c in clusters if 0 in c][0]] = True

    mask = np.asarray(agglomerative_majority(jnp.asarray(d), linkage="average"))
    assert (mask == expected).all()
