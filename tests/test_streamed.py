"""Single-chip streaming round (parallel/streamed.py): equivalence with
the dense FedRound.step at f32 storage, bf16 smoke, capability guards."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.adversaries import get_adversary, make_malicious_mask
from blades_tpu.core import FedRound, Server, TaskSpec
from blades_tpu.parallel.streamed import streamed_step
from blades_tpu.utils.tree import ravel_fn

N = 8
F = 2


def make_fr(aggregator="Median", adversary="ALIE", **kw):
    task = TaskSpec(model="mlp", lr=0.1, input_shape=(28, 28, 1)).build()
    server = Server.from_config(aggregator=aggregator, num_byzantine=F, lr=1.0,
                                **kw.pop("server_kwargs", {}))
    adv = get_adversary(adversary, num_clients=N, num_byzantine=F) if adversary else None
    return FedRound(task=task, server=server, adversary=adv, batch_size=8, **kw)


@pytest.fixture(scope="module")
def data():
    from blades_tpu.data import DatasetCatalog

    ds = DatasetCatalog.get_dataset("mnist", num_clients=N)
    return (
        jnp.array(ds.train.x), jnp.array(ds.train.y), jnp.array(ds.train.lengths),
        make_malicious_mask(N, F),
    )


@pytest.mark.parametrize("aggregator,adversary", [
    # Same streamed-vs-dense fixture at ~7-9 s/case; tier-1 keeps one
    # aggregator/adversary shape (PR 7 rebalance, tightened in PR 20).
    pytest.param("Median", "ALIE", marks=pytest.mark.slow),
    ("Mean", "IPM"),
    pytest.param("Trimmedmean", "ALIE", marks=pytest.mark.slow),
])
def test_streamed_matches_dense_f32(data, aggregator, adversary):
    """f32 storage + deterministic coordinate-wise attacks: the chunked
    pipeline must reproduce the dense round exactly (same key stream)."""
    x, y, ln, mal = data
    fr = make_fr(aggregator, adversary)
    key = jax.random.PRNGKey(3)

    st_a = fr.init(jax.random.PRNGKey(0), N)
    st_a, m_a = jax.jit(fr.step)(st_a, x, y, ln, mal, key)

    st_b = fr.init(jax.random.PRNGKey(0), N)
    step = streamed_step(fr, client_block=4, d_chunk=10_000,
                         update_dtype=jnp.float32)
    st_b, m_b = step(st_b, x, y, ln, mal, key)

    ravel, _, _ = ravel_fn(st_a.server.params)
    np.testing.assert_allclose(
        np.asarray(ravel(st_a.server.params)),
        np.asarray(ravel(st_b.server.params)), atol=1e-6, rtol=1e-5,
    )
    np.testing.assert_allclose(float(m_a["train_loss"]), float(m_b["train_loss"]),
                               rtol=1e-6)
    np.testing.assert_allclose(float(m_a["update_norm_mean"]),
                               float(m_b["update_norm_mean"]), rtol=1e-4)


def test_streamed_bf16_trains(data):
    """bf16 storage: order statistics survive the rounding; multi-round
    training still descends."""
    x, y, ln, mal = data
    fr = make_fr("Median", "ALIE")
    st = fr.init(jax.random.PRNGKey(0), N)
    step = streamed_step(fr, client_block=4, d_chunk=10_000)
    losses = []
    for r in range(8):
        st, m = step(st, x, y, ln, mal, jax.random.fold_in(jax.random.PRNGKey(1), r))
        losses.append(float(m["train_loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    assert int(m["round"]) == 8


def test_streamed_rejects_unsupported_configs():
    """Every registry aggregator AND forger now has a streamed
    formulation; unknown custom aggregators/forgers are rejected with a
    pointer at build time."""
    import dataclasses

    from blades_tpu.adversaries.base import Adversary
    from blades_tpu.ops.aggregators import Aggregator

    @dataclasses.dataclass(frozen=True)
    class CustomAgg(Aggregator):
        def aggregate(self, updates):
            return updates.mean(axis=0)

    fr = make_fr("Mean")
    fr = dataclasses.replace(fr, server=dataclasses.replace(
        fr.server, aggregator=CustomAgg()))
    with pytest.raises(NotImplementedError, match="streamed formulation"):
        streamed_step(fr)

    @dataclasses.dataclass(frozen=True)
    class CustomForger(Adversary):
        def on_updates_ready(self, updates, malicious, key, **kw):
            return updates

    fr = make_fr("Median")
    fr = dataclasses.replace(fr, adversary=CustomForger())
    with pytest.raises(NotImplementedError, match="forge"):
        streamed_step(fr)


def test_streamed_dp_clip_matches_dense_exactly(data):
    """DP clipping on the streamed path uses full-row norms precomputed at
    train time — with f32 storage and noise off it must reproduce the
    dense round (to cross-dispatch float tolerance)."""
    fr_dp = make_fr(dp_clip_threshold=0.05)
    state = fr_dp.init(jax.random.PRNGKey(0), N)
    x, y, ln, mal = data
    key = jax.random.PRNGKey(9)

    dense_state, dm = jax.jit(fr_dp.step)(state, x, y, ln, mal, key)
    step = streamed_step(fr_dp, client_block=4, d_chunk=64,
                         update_dtype=jnp.float32, donate=False)
    st_state, sm = step(state, x, y, ln, mal, key)

    # Same tolerance as the sibling f32 equivalence test: bit-exactness
    # across different dispatch/fusion shapes is backend-dependent.
    np.testing.assert_allclose(
        np.asarray(dm["agg_norm"]), np.asarray(sm["agg_norm"]),
        atol=1e-6, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(dense_state.server.params),
                    jax.tree.leaves(st_state.server.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)


def test_streamed_dp_noise_is_applied(data):
    fr_dp = make_fr(dp_clip_threshold=0.05, dp_noise_factor=2.0)
    state = fr_dp.init(jax.random.PRNGKey(0), N)
    x, y, ln, mal = data
    step = streamed_step(fr_dp, client_block=4, d_chunk=64,
                         update_dtype=jnp.float32, donate=False)
    _, m = step(state, x, y, ln, mal, jax.random.PRNGKey(9))
    # Clipped rows have norm <= 0.05; with sigma = 0.1 noise across d
    # coords the measured mean row norm must sit far above the clip.
    assert float(m["update_norm_mean"]) > 0.05 * 2
    assert np.isfinite(float(m["train_loss"]))


@pytest.mark.parametrize("aggregator,adversary", [
    ("Median", "ALIE"),          # fused-eligible coordinate path (chunked on CPU)
    # The row-geometry combinations compile near-identical streamed
    # programs (~8 s each on this box); tier-1 keeps the headline pair,
    # the full suite runs all three (PR 7 budget rebalance).
    pytest.param("GeoMed", "IPM", marks=pytest.mark.slow),
    pytest.param("Median", "MinMax", marks=pytest.mark.slow),
])
def test_malicious_prefix_elision_is_exact(data, aggregator, adversary):
    """Skipping the dead malicious-lane training blocks must reproduce the
    full round bit-for-bit at f32 storage: same server params, same
    aggregate/metrics, same benign-lane outputs (the forged rows never
    depended on what malicious clients trained)."""
    x, y, ln, mal = data
    fr = make_fr(aggregator, adversary)
    key = jax.random.PRNGKey(7)

    st_a = fr.init(jax.random.PRNGKey(0), N)
    full = streamed_step(fr, client_block=2, d_chunk=10_000,
                         update_dtype=jnp.float32)
    st_a, m_a = full(st_a, x, y, ln, mal, key)

    st_b = fr.init(jax.random.PRNGKey(0), N)
    elided = streamed_step(fr, client_block=2, d_chunk=10_000,
                           update_dtype=jnp.float32, malicious_prefix=F)
    st_b, m_b = elided(st_b, x, y, ln, mal, key)

    for a, b in zip(jax.tree.leaves(st_a.server.params),
                    jax.tree.leaves(st_b.server.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in ("train_loss", "agg_norm", "update_norm_mean"):
        np.testing.assert_array_equal(np.asarray(m_a[k]), np.asarray(m_b[k]))
    # Elision telemetry (VERDICT item 6): the skipped lanes — the basis
    # num_unhealthy can never count — are surfaced; the full round's
    # metrics carry no such key (identity preserved).
    assert int(m_b["elided_lanes"]) == F
    assert "elided_lanes" not in m_a


def test_malicious_prefix_without_forge_trains_everyone(data):
    """No update forge (training-only attack): malicious training is NOT
    dead, and the prefix hint must be ignored."""
    x, y, ln, mal = data
    fr = make_fr("Mean", "SignFlip")
    key = jax.random.PRNGKey(7)

    st_a = fr.init(jax.random.PRNGKey(0), N)
    full = streamed_step(fr, client_block=2, d_chunk=10_000,
                         update_dtype=jnp.float32)
    st_a, m_a = full(st_a, x, y, ln, mal, key)

    st_b = fr.init(jax.random.PRNGKey(0), N)
    hinted = streamed_step(fr, client_block=2, d_chunk=10_000,
                           update_dtype=jnp.float32, malicious_prefix=F)
    st_b, m_b = hinted(st_b, x, y, ln, mal, key)

    for a, b in zip(jax.tree.leaves(st_a.server.params),
                    jax.tree.leaves(st_b.server.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(m_a["train_loss"]),
                                  np.asarray(m_b["train_loss"]))


def test_malicious_prefix_promise_is_validated(data):
    """A mask that disagrees with the promised prefix must fail loudly,
    not silently aggregate zero rows for benign clients."""
    x, y, ln, _ = data
    bad_mask = jnp.arange(N) >= (N - F)  # malicious at the TAIL
    fr = make_fr("Median", "ALIE")
    st = fr.init(jax.random.PRNGKey(0), N)
    step = streamed_step(fr, client_block=2, d_chunk=10_000,
                         update_dtype=jnp.float32, malicious_prefix=F)
    with pytest.raises(ValueError, match="elision"):
        step(st, x, y, ln, bad_mask, jax.random.PRNGKey(7))


def test_malicious_prefix_promise_check_is_per_object(data):
    """The once-per-mask validation cache must hold the validated OBJECT,
    not a recyclable id (ADVICE r4): a freed-and-reallocated DIFFERENT
    mask at the recycled address must still be validated and raise."""
    import gc

    x, y, ln, _ = data
    fr = make_fr("Median", "ALIE")
    st = fr.init(jax.random.PRNGKey(0), N)
    step = streamed_step(fr, client_block=2, d_chunk=10_000,
                         update_dtype=jnp.float32, malicious_prefix=F,
                         donate=False)
    # A locally-created correct mask (the fixture's must stay alive, so
    # its id could never be recycled and the test would prove nothing).
    good = jnp.arange(N) < F
    step(st, x, y, ln, good, jax.random.PRNGKey(7))

    freed_id = id(good)
    del good
    gc.collect()
    # Hunt for a wrong mask landing on the freed address.  Under the
    # fixed cache the slot PINS the validated object, so no collision
    # can occur and every wrong mask is validated; under a reverted
    # bare-id cache a collision would silently skip validation (zeroing
    # benign rows instead of raising) and fail this test.
    for i in range(16):
        bad = jnp.arange(N) >= (N - F)
        with pytest.raises(ValueError, match="elision"):
            step(st, x, y, ln, bad, jax.random.PRNGKey(8 + i))
        if id(bad) == freed_id:
            break  # the regression scenario itself was exercised
        del bad


def test_streamed_multi_round_dispatch_matches_sequential(data):
    """rounds_per_dispatch > 1 on the streamed path: k chained rounds
    (no host sync between them) must equal k sequential streamed_step
    calls bit-for-bit at f32 storage — same split(key, k) stream as the
    dense multi_step."""
    from blades_tpu.parallel.streamed import streamed_multi_step

    x, y, ln, mal = data
    fr = make_fr("Median", "ALIE")
    key = jax.random.PRNGKey(11)
    k = 3

    st_a = fr.init(jax.random.PRNGKey(0), N)
    multi = streamed_multi_step(fr, k, client_block=4, d_chunk=10_000,
                                update_dtype=jnp.float32, donate=False)
    st_a, m_a = multi(st_a, x, y, ln, mal, key)
    assert m_a["train_loss"].shape == (k,)

    st_b = fr.init(jax.random.PRNGKey(0), N)
    step = streamed_step(fr, client_block=4, d_chunk=10_000,
                         update_dtype=jnp.float32, donate=False)
    keys = jax.random.split(key, k)
    losses = []
    for r in range(k):
        st_b, m_b = step(st_b, x, y, ln, mal, keys[r])
        losses.append(m_b["train_loss"])
    for a, b in zip(jax.tree.leaves(st_a.server.params),
                    jax.tree.leaves(st_b.server.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(m_a["train_loss"]),
                                  np.asarray(jnp.stack(losses)))


def test_streamed_rounds_per_dispatch_from_config():
    """execution: streamed + rounds_per_dispatch: 8 builds and trains
    through the Fedavg config path (VERDICT r3 item 4)."""
    from blades_tpu.algorithms import FedavgConfig

    cfg = (
        FedavgConfig()
        .data(dataset="mnist", num_clients=6, seed=1)
        .training(global_model="mlp",
                  aggregator={"type": "Median"}, server_lr=1.0)
        .resources(execution="streamed", client_block=2,
                   update_dtype="float32")
        .evaluation(evaluation_interval=8)
    )
    cfg.rounds_per_dispatch = 8
    algo = cfg.build()
    r = algo.train()
    assert r["training_iteration"] == 8
    assert np.isfinite(r["train_loss"])


def test_malicious_prefix_elision_exact_under_dp(data):
    """Elision + per-row DP: malicious lanes' clip norms differ (0 for
    untrained rows) but are dead — the forge overwrites those rows after
    DP.  Full vs elided must stay bit-equal at f32."""
    x, y, ln, mal = data
    fr = make_fr("Median", "ALIE", dp_clip_threshold=0.05,
                 dp_noise_factor=0.5)
    key = jax.random.PRNGKey(13)

    st_a = fr.init(jax.random.PRNGKey(0), N)
    full = streamed_step(fr, client_block=2, d_chunk=10_000,
                         update_dtype=jnp.float32)
    st_a, m_a = full(st_a, x, y, ln, mal, key)

    st_b = fr.init(jax.random.PRNGKey(0), N)
    elided = streamed_step(fr, client_block=2, d_chunk=10_000,
                           update_dtype=jnp.float32, malicious_prefix=F)
    st_b, m_b = elided(st_b, x, y, ln, mal, key)

    for a, b in zip(jax.tree.leaves(st_a.server.params),
                    jax.tree.leaves(st_b.server.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(m_a["agg_norm"]),
                                  np.asarray(m_b["agg_norm"]))
