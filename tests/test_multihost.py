"""2-process jax.distributed (DCN) smoke test — the multi-process bring-up
the reference's NCCL communicator provided (ref: fllib/communication/
communicator.py:119-184), here via jax.distributed + a global mesh.

Spawns two worker subprocesses, each with 4 virtual CPU devices; the
federated round's collectives cross the process boundary.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

HERE = Path(__file__).parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_round():
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PALLAS_AXON_POOL_IPS"] = ""  # disable the axon TPU relay plugin
    procs = [
        subprocess.Popen(
            [sys.executable, str(HERE / "multihost_worker.py"), coord, "2", str(i)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=str(HERE.parent),
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
        assert f"proc {i}: multihost round OK" in out, out
