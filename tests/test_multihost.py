"""2-process jax.distributed (DCN) smoke test — the multi-process bring-up
the reference's NCCL communicator provided (ref: fllib/communication/
communicator.py:119-184), here via jax.distributed + a global mesh.

Spawns two worker subprocesses, each with 4 virtual CPU devices; the
federated round's collectives cross the process boundary.

The test SKIPS (with the probe's evidence in the reason) on hosts where
only single-process execution is available — e.g. this image's jaxlib,
whose CPU backend aborts cross-process collectives with "Multiprocess
computations aren't implemented on the CPU backend", or a box whose
loopback gRPC handshake cannot form a 2-process group at all.  A cheap
capability probe (a tiny cross-process psum, not the full federated
round) decides; genuine regressions in the round's collectives still
fail the test on capable hosts.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

HERE = Path(__file__).parent

# Substrings that identify "this host cannot do multi-process jax at
# all" — as opposed to a bug in the federated round under test.
_CAPABILITY_ERRORS = (
    "Multiprocess computations aren't implemented",
    "DEADLINE_EXCEEDED",
    "failed to connect to all addresses",
)

_PROBE = r"""
import os
import sys
try:
    import jax
    jax.distributed.initialize(sys.argv[1], num_processes=2,
                               process_id=int(sys.argv[2]))
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    # One tiny cross-process collective: enough to prove (or disprove)
    # that this backend executes multi-process computations.
    mesh = Mesh(jax.devices(), ("d",))
    x = jnp.ones((len(jax.devices()),))
    y = jax.jit(lambda a: a.sum(), out_shardings=NamedSharding(mesh, P()))(
        jax.device_put(x, NamedSharding(mesh, P("d"))))
    print("probe ok", float(y), flush=True)
except Exception as e:
    print("probe err:", repr(e), flush=True)
# Skip the distributed atexit shutdown: after a failed collective the
# barrier hangs forever (observed: the worker survives its own traceback
# by minutes), and all the parent needs is the verdict above.
os._exit(0)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env() -> dict:
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PALLAS_AXON_POOL_IPS"] = ""  # disable the axon TPU relay plugin
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    return env


def _spawn(args, env):
    return subprocess.Popen(
        [sys.executable, *args], env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, cwd=str(HERE.parent),
    )


def _multiprocess_capability() -> str:
    """'' when 2-process jax works here, else the reason it cannot."""
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    env = _worker_env()
    # The probe must answer FAST on relay-dead boxes: without this, a
    # half-present TPU plugin retries GCE metadata fetches for ~30 s per
    # tpu-env variable (~90 s total, measured — the single biggest line
    # in the tier-1 budget) before the coordinator process even starts.
    # Skipping the metadata query does not change the verdict here:
    # locally-discovered chips still initialize, and the CPU fallback
    # fails the collective with the same capability error in ~4 s.
    env["TPU_SKIP_MDS_QUERY"] = "1"
    procs = [_spawn(["-c", _PROBE, coord, str(i)], env) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=150)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            if p.poll() is None:
                p.kill()
        return ("2-process jax.distributed probe timed out forming the "
                "group (single-host-only environment)")
    for out in outs:
        for marker in _CAPABILITY_ERRORS:
            if marker in out:
                return (f"single-process host: the 2-process capability "
                        f"probe failed with {marker!r}")
    # An unrecognised probe failure is NOT treated as a capability gap —
    # the real test runs and reports it.
    return ""


# Spawns two real processes, each paying its own XLA CPU compile (~5 s
# plus interpreter start); the distributed round logic stays tier-1 on
# the in-process simulation tests (PR 20 budget rebalance).
@pytest.mark.slow
def test_two_process_distributed_round():
    reason = _multiprocess_capability()
    if reason:
        pytest.skip(reason)
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    env = _worker_env()
    env.pop("XLA_FLAGS", None)
    procs = [
        _spawn([str(HERE / "multihost_worker.py"), coord, "2", str(i)], env)
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
        assert f"proc {i}: multihost round OK" in out, out
