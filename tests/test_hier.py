"""Pod-scale hierarchical round (ISSUE 18): (clients, d) mesh tests.

The headline tier-1 contract is the one :mod:`blades_tpu.parallel.hier`
pins in its docstring: with ``bucket_size=1`` the hierarchical round is
**bit-identical** to the single-chip dense ``FedRound.step`` — same
batches, same local rounds, same forging, same defense — so the
robustness grid below asserts EXACT equality (tolerance zero), not
allclose.  The ICI reconciliation test checks the trace-time recorder
against :mod:`blades_tpu.parallel.comm_model` in both directions, event
by event, and the 10k-registered-client test is the scaled acceptance
run on the 8 virtual CPU devices.

Budget note: the mesh compiles here ride tier-1 deliberately (the ISSUE
18 acceptance runs the hierarchical path on the CPU tier-1 box); every
federation is kept tiny (MLP(8, 8) on 4x4x1 inputs, d = a few hundred)
and dense/hier trajectories are cached per config so each program
compiles exactly once.  check_tier1_budget.py audits the wall clock.
The full 10-aggregator zoo is slow-marked and rides tier 2.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from blades_tpu.adversaries import get_adversary, make_malicious_mask
from blades_tpu.algorithms import FedavgConfig
from blades_tpu.core import FedRound, Server, TaskSpec
from blades_tpu.models.mlp import MLP
from blades_tpu.ops.preagg import (
    bucket_count,
    bucket_representatives,
    nnm_representatives,
)
from blades_tpu.parallel.comm_model import hier_round_volumes, hier_wire_bytes
from blades_tpu.parallel.hier import hier_kept_counts
from blades_tpu.utils.tree import ravel_fn

N_CLIENTS = 8
N_BYZ = 2
ROWS = 4
SHAPE = (4, 4, 1)
MESH_2D = (4, 2)  # exercises the two-phase (clients, d) gather


def _tiny_round(agg="Median", attack="ALIE", n=N_CLIENTS, f=N_BYZ, seed=0):
    """A raw FedRound on the tiny synthetic task (d = 226 params)."""
    task = TaskSpec(model=MLP(hidden1=8, hidden2=8, num_classes=2),
                    num_classes=2, input_shape=SHAPE, lr=0.1).build()
    server = Server.from_config(aggregator=agg, num_byzantine=f or None,
                                lr=0.5)
    adv = (get_adversary(attack, num_clients=n, num_byzantine=f)
           if attack is not None else None)
    fr = FedRound(task=task, server=server, adversary=adv, batch_size=2,
                  num_batches_per_round=1, num_clients=n)
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(n, ROWS) + SHAPE), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, size=(n, ROWS)), jnp.int32)
    lengths = jnp.full((n,), ROWS, jnp.int32)
    mal = make_malicious_mask(n, f)
    return fr, (x, y, lengths, mal)


def _run_dense(fr, data, rounds):
    """Single-chip dense trajectory: (losses, final server params)."""
    x, y, lengths, mal = data
    state = fr.init(jax.random.PRNGKey(0), N_CLIENTS)
    step = jax.jit(fr.step)
    losses = []
    for r in range(rounds):
        state, m = step(state, x, y, lengths, mal,
                        jax.random.fold_in(jax.random.PRNGKey(9), r))
        losses.append(float(m["train_loss"]))
    return losses, jax.tree.map(np.asarray, state.server.params)


def _run_hier(fr, data, rounds, *, mesh_shape=MESH_2D, preagg="bucket",
              bucket_size=1):
    """Hierarchical trajectory on the 2-D mesh.

    Returns ``(losses, params, recorder, last_metrics)``.
    """
    from blades_tpu.parallel import (hier_step, make_mesh,
                                     replicated_sharding, shard_federation)

    x, y, lengths, mal = data
    mesh = make_mesh(num_devices=int(np.prod(mesh_shape)),
                     mesh_shape=mesh_shape)
    state = fr.init(jax.random.PRNGKey(0), N_CLIENTS)
    state, (x, y, lengths) = shard_federation(mesh, state, (x, y, lengths))
    mal = jax.device_put(mal, replicated_sharding(mesh))
    step, rec = hier_step(fr, mesh, preagg=preagg, bucket_size=bucket_size)
    losses, m = [], None
    for r in range(rounds):
        state, m = step(state, x, y, lengths, mal,
                        jax.random.fold_in(jax.random.PRNGKey(9), r))
        losses.append(float(m["train_loss"]))
    return (losses, jax.tree.map(np.asarray, state.server.params), rec,
            {k: np.asarray(v) for k, v in m.items()})


_DENSE_CACHE = {}
_HIER_CACHE = {}


def _dense(agg, attack, rounds=2):
    key = (agg, attack, rounds)
    if key not in _DENSE_CACHE:
        fr, data = _tiny_round(agg, attack)
        _DENSE_CACHE[key] = _run_dense(fr, data, rounds)
    return _DENSE_CACHE[key]


def _hier(agg, attack, rounds=2, *, mesh_shape=MESH_2D, preagg="bucket",
          bucket_size=1):
    key = (agg, attack, rounds, mesh_shape, preagg, bucket_size)
    if key not in _HIER_CACHE:
        fr, data = _tiny_round(agg, attack)
        _HIER_CACHE[key] = _run_hier(fr, data, rounds, mesh_shape=mesh_shape,
                                     preagg=preagg, bucket_size=bucket_size)
    return _HIER_CACHE[key]


def _assert_bit_identical(dense, hier):
    d_losses, d_params = dense
    h_losses, h_params = hier[0], hier[1]
    assert d_losses == h_losses, (d_losses, h_losses)
    for a, b in zip(jax.tree.leaves(d_params), jax.tree.leaves(h_params)):
        assert np.array_equal(a, b)


# ---------------------------------------------------------------------------
# the robustness grid: >= 3 aggregators x >= 2 attacks, tolerance ZERO
# ---------------------------------------------------------------------------


GRID = [(agg, attack)
        for agg in ("Mean", "Median", "Trimmedmean")
        for attack in ("ALIE", "IPM")]


@pytest.mark.parametrize("agg,attack", GRID,
                         ids=[f"{a}-{k}" for a, k in GRID])
def test_hier_bucket1_grid_bit_identical_to_dense(agg, attack):
    """bucket_size=1 is identity pre-agg: the hierarchical round on the
    (4, 2) mesh must reproduce the single-chip dense trajectory EXACTLY
    (losses and server params) — the pinned tolerance is zero."""
    _assert_bit_identical(_dense(agg, attack), _hier(agg, attack))


def test_hier_nnm_bucket1_bit_identical_to_dense():
    """NNM at bucket_size=1 mixes each lane with only itself — also
    exactly the identity, through the other pre-agg code path."""
    _assert_bit_identical(_dense("Median", "ALIE"),
                          _hier("Median", "ALIE", preagg="nnm"))


def test_hier_bucket2_mean_commutes_to_reassociation():
    """With uniform buckets, no ghosts and no forging, Mean is exactly
    the mean of bucket means — the hierarchical b=2 round differs from
    dense only by float32 reassociation.  Pinned tolerance: 1e-6
    relative (documented in README).  Under an attack the b>1 round
    computes a DIFFERENT (provably tighter) defended statistic by
    design, so the attack-free config is the right commutation pin."""
    d_losses, d_params = _dense("Mean", None)
    h_losses, h_params, _, m = _hier("Mean", None, bucket_size=2)
    np.testing.assert_allclose(d_losses, h_losses, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(d_params), jax.tree.leaves(h_params)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    # 8 clients over 4 client-chips in buckets of 2 -> 4 representatives.
    assert int(m["preagg_kept"]) == 4


# ---------------------------------------------------------------------------
# ICI accounting: recorder <-> comm model, both directions
# ---------------------------------------------------------------------------


def test_ici_reconciles_with_comm_model_both_ways():
    """Every collective the traced hier program counted must appear in
    the analytic inventory with the same (kind, payload, ring), and
    vice versa; the per-chip wire totals must be EQUAL (both sides use
    the same integer ring arithmetic)."""
    _, params = _dense("Median", "ALIE")
    _, _, d = ravel_fn(params)
    for mesh_shape in (MESH_2D, (8, 1)):
        _, _, rec, m = _hier("Median", "ALIE", mesh_shape=mesh_shape)
        vols = hier_round_volumes(N_CLIENTS, d, mesh_shape,
                                  preagg="bucket", bucket_size=1)
        model = sorted((v.kind, v.payload_bytes, k)
                       for v, k in vols for _ in range(v.count))
        recorded = sorted((kind, payload, k)
                          for _, kind, payload, k in rec.ici_events)
        assert recorded == model, (mesh_shape, recorded, model)
        assert rec.ici_bytes == hier_wire_bytes(vols)
        assert int(m["ici_bytes"]) == rec.ici_bytes
        assert int(m["preagg_kept"]) == N_CLIENTS
    # The 2-D torus gathers column-sliced representatives in two phases;
    # the flat ring ships full rows once — the 2-D wire total is strictly
    # smaller for this geometry.
    v2 = hier_wire_bytes(hier_round_volumes(N_CLIENTS, d, MESH_2D))
    v1 = hier_wire_bytes(hier_round_volumes(N_CLIENTS, d, (8, 1)))
    assert v2 < v1


# ---------------------------------------------------------------------------
# pre-agg primitives (pure, no mesh)
# ---------------------------------------------------------------------------


def test_bucket_representatives_math():
    u = jnp.arange(12, dtype=jnp.float32).reshape(6, 2)
    real = jnp.array([True] * 5 + [False])
    # b=1: identity on real lanes.
    r1 = bucket_representatives(u, real, 1)
    assert np.array_equal(np.asarray(r1[:5]), np.asarray(u[:5]))
    # b=2: masked means; the boundary bucket averages only its real lane.
    r2 = bucket_representatives(u, real, 2)
    assert bucket_count(6, 2) == 3
    np.testing.assert_allclose(np.asarray(r2[0]),
                               np.asarray(u[:2].mean(axis=0)))
    np.testing.assert_allclose(np.asarray(r2[2]), np.asarray(u[4]))
    # A NaN ghost lane cannot poison its bucket.
    u_nan = u.at[5].set(jnp.nan)
    r2n = bucket_representatives(u_nan, real, 2)
    assert np.isfinite(np.asarray(r2n)).all()


def test_nnm_representatives_math():
    u = jnp.array([[0.0], [0.1], [10.0], [100.0]], jnp.float32)
    real = jnp.array([True, True, True, False])
    # b=1: identity on REAL lanes (ghost rows emit garbage at their own
    # index — the caller's static ``kept`` slice removes them).
    assert np.array_equal(np.asarray(nnm_representatives(u, real, 1))[:3],
                          np.asarray(u)[:3])
    # b=2: each row mixes with its nearest REAL neighbor; the ghost
    # (100.0) is never selected.
    r = np.asarray(nnm_representatives(u, real, 2))
    np.testing.assert_allclose(r[0], [0.05])
    np.testing.assert_allclose(r[1], [0.05])
    np.testing.assert_allclose(r[2], [5.05])


def test_hier_kept_counts_static_prefix():
    # 10 real clients on 4 chips of 3 lanes (pad 12): reals 3,3,3,1.
    assert hier_kept_counts(10, 3, 4, 1) == [3, 3, 3, 1]
    assert hier_kept_counts(10, 3, 4, 2) == [2, 2, 2, 1]
    assert hier_kept_counts(12, 3, 4, 3) == [1, 1, 1, 1]
    assert sum(hier_kept_counts(8, 2, 4, 1)) == 8


# ---------------------------------------------------------------------------
# the scaled acceptance run: 10k registered clients through the driver
# ---------------------------------------------------------------------------


def _tiny_population_dataset(n_clients, rows_per_client=4, shape=SHAPE,
                             num_classes=2, seed=0):
    from blades_tpu.data.datasets import FLDataset
    from blades_tpu.data.partition import partition_dataset

    rng = np.random.default_rng(seed)
    n = n_clients * rows_per_client
    mus = rng.normal(size=(num_classes,) + shape).astype(np.float32)
    y = rng.integers(0, num_classes, size=n).astype(np.int32)
    x = (mus[y] + 0.5 * rng.normal(size=(n,) + shape)).astype(np.float32)
    train = partition_dataset(x, y, n_clients, iid=True, seed=seed)
    test = partition_dataset(x[: 2 * n_clients], y[: 2 * n_clients],
                             n_clients, iid=True, seed=seed + 1)
    return FLDataset(name="tinypop", train=train, test_x=x[:64],
                     test_y=y[:64], test=test, num_classes=num_classes,
                     input_shape=shape)


def _tiny_driver(n, *, seed=0, faults=None, num_malicious=0):
    cfg = (
        FedavgConfig()
        .data(dataset=_tiny_population_dataset(n, seed=seed), num_clients=n,
              seed=seed)
        .training(global_model=MLP(hidden1=8, hidden2=8, num_classes=2),
                  num_classes=2, input_shape=SHAPE, server_lr=0.5,
                  train_batch_size=4, aggregator={"type": "Median"})
        .client(lr=0.1)
        .evaluation(evaluation_interval=0)
        .resources(num_devices=8, execution="hier")
    )
    if num_malicious:
        cfg.adversary(num_malicious_clients=num_malicious,
                      adversary_config={"type": "ALIE"})
    if faults:
        cfg.fault_tolerance(faults=faults)
    return cfg.build()


# 10k-registered mesh round: shard_map compiles are the most expensive
# tier-1 class (~8 s); the hier path keeps its bit-identity grid and
# kill-and-resume tier-1, the scale acceptance rides the slow lane
# (PR 20 budget rebalance).
@pytest.mark.slow
def test_10k_registered_clients_hier_round_completes():
    """The ISSUE 18 acceptance run, scaled for the CPU tier-1 box:
    10 240 registered clients on the 8-virtual-device mesh complete a
    hierarchical round, and the stamped ici_bytes reconciles exactly
    against the analytic comm model."""
    n = 10_240
    algo = _tiny_driver(n)
    try:
        row = algo.train()
        assert np.isfinite(row["train_loss"])
        assert row["mesh_shape"] == "8x1"
        assert row["preagg_kept"] == n  # bucket_size=1 keeps every client
        _, _, d = ravel_fn(algo.state.server.params)
        vols = hier_round_volumes(n, d, (8, 1), preagg="bucket",
                                  bucket_size=1)
        assert row["ici_bytes"] == hier_wire_bytes(vols)
    finally:
        algo.stop()


def test_hier_kill_and_resume_bit_identical(tmp_path):
    """Kill-and-resume through the faults harness: checkpoint a
    hierarchical run with dropout injection mid-stream, rebuild a fresh
    driver, load, and the continued rounds must be bit-identical to the
    uninterrupted run (round keys and the fault process both derive
    from the stored round counter)."""
    a = _tiny_driver(16, faults={"dropout_rate": 0.25, "seed": 11},
                     num_malicious=4)
    try:
        a.train()
        path = a.save_checkpoint(str(tmp_path))
        r2a = a.train()
        r3a = a.train()
        b = _tiny_driver(16, faults={"dropout_rate": 0.25, "seed": 11},
                         num_malicious=4)
        try:
            b.load_checkpoint(path)
            r2b = b.train()
            r3b = b.train()
            assert r2a["train_loss"] == r2b["train_loss"]
            assert r3a["train_loss"] == r3b["train_loss"]
            for x, y in zip(jax.tree.leaves(a.state.server.params),
                            jax.tree.leaves(b.state.server.params)):
                assert np.array_equal(np.asarray(x), np.asarray(y))
        finally:
            b.stop()
    finally:
        a.stop()


# ---------------------------------------------------------------------------
# validate(): every mesh rejection names the exact pair + knob
# ---------------------------------------------------------------------------


def _check(match, **kw):
    cfg = (
        FedavgConfig()
        .data(dataset="mnist", num_clients=8, seed=0)
        .training(global_model="mlp", aggregator={"type": "Median"})
    )
    for k, v in kw.items():
        setattr(cfg, k, v)
    with pytest.raises(ValueError, match=match):
        cfg.validate()


def test_pod_scale_validation_messages():
    _check("mesh_shape × single-chip is an unsupported pair",
           mesh_shape=(4, 2))
    _check("must tile exactly", mesh_shape=(4, 2), num_devices=16)
    _check(r"mesh_shape must be a \(clients, d\) pair",
           mesh_shape=(4, 2, 1), num_devices=8)
    _check("pre-aggregates per chip and gathers", execution="hier")
    _check("rounds_per_dispatch>1 is an unsupported pair",
           execution="hier", num_devices=8, rounds_per_dispatch=2)
    _check("preagg must be one of", preagg="mean")
    _check("bucket_size must be an int >= 1", bucket_size=0)
    _check("autotune × execution='hier' is an unsupported pair",
           execution="hier", num_devices=8, autotune="on")
    _check("autotune × execution='dsharded' is an unsupported pair",
           execution="dsharded", num_devices=8, autotune="on")
    _check("straggler faults is an unsupported pair",
           execution="hier", num_devices=8,
           fault_config={"dropout_rate": 0.1, "num_stragglers": 1})
    _check("identity-height pre-aggregation",
           execution="hier", num_devices=8, bucket_size=2,
           fault_config={"dropout_rate": 0.1})


def test_hier_step_rejects_unsupported_rounds():
    from blades_tpu.parallel.hier import _check_supported

    fr, _ = _tiny_round()
    with pytest.raises(ValueError, match="unknown preagg flavor"):
        _check_supported(fr, "mean", 1)
    with pytest.raises(ValueError, match="bucket_size must be >= 1"):
        _check_supported(fr, "bucket", 0)


# ---------------------------------------------------------------------------
# the full aggregator zoo (tier 2): b=1 identity for every defense
# ---------------------------------------------------------------------------


ZOO = [
    {"type": "Mean"},
    {"type": "Median"},
    {"type": "Trimmedmean", "num_byzantine": N_BYZ},
    {"type": "GeoMed"},
    {"type": "DnC", "num_byzantine": N_BYZ, "sub_dim": 8, "num_iters": 2},
    {"type": "Multikrum", "num_byzantine": N_BYZ, "k": 2},
    {"type": "Centeredclipping"},
    {"type": "Signguard"},
    {"type": "Clippedclustering"},
    {"type": "FLTrust"},
]


@pytest.mark.parametrize(
    "agg", [pytest.param(a, marks=pytest.mark.slow, id=a["type"])
            for a in ZOO])
def test_hier_bucket1_zoo_bit_identical(agg):
    """Every registered aggregator, hierarchical b=1 vs dense: exact."""
    import dataclasses

    def rounds():
        fr, data = _tiny_round(agg, "ALIE")
        if agg["type"] == "FLTrust":
            x, y = data[0], data[1]
            fr = dataclasses.replace(fr, trusted_data=(x[0], y[0]))
        return fr, data

    fr, data = rounds()
    dense = _run_dense(fr, data, 2)
    fr2, data2 = rounds()
    hier = _run_hier(fr2, data2, 2)
    _assert_bit_identical(dense, hier)
