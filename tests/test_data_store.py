"""Out-of-core training data tests (blades_tpu/data, ISSUE 20):

- store protocol: resident/memmap ``take`` round trips (sorted and
  unsorted cross-shard cohorts), shard reuse on a verified manifest,
  rebuild-from-source on corruption;
- chaos on the shard directory: the strict forensic walk
  (``validate_datastore_dir`` / ``validate_metrics.py --datastore``)
  names torn, corrupt, orphaned and unmanifested files;
- the cross-backend CONTRACT: ``resident`` and ``memmap`` produce
  bit-identical train rows, staged-byte counts and server params for
  the same (seed, cohort schedule) — across Mean (tier-1) +
  Multikrum + GeoMed (slow zoo) — while streaming eval matches the
  monolithic reduction to float tolerance (summation order only);
- streaming eval: chunk math, exact-zero padding, the host-resident
  test stack under the memmap plane;
- kill-and-resume: a SimulatedPreemption under data_store="memmap"
  (+ the disk state store) resumes bit-identically;
- the calibrated-ticks satellite: ``ticks_per_sec`` sizing math and
  its never-touches-the-realization purity guarantee;
- the ``window`` control family: validate()-gate, controller seeding
  and the engine actuation under the out-of-core pair;
- validate()-time gates, and the headline acceptance: 1M registered /
  10k-cohort on CPU with host peak memory asserted a small fraction
  of the population's data bytes.
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.algorithms import FedavgConfig
from blades_tpu.data.store import (
    DATA_STORE_BACKENDS,
    MemmapDataStore,
    make_data_store,
    validate_datastore_dir,
)
from blades_tpu.data.stream import streaming_evaluate

ROW_KEYS = ("train_loss", "agg_norm", "update_norm_mean")


def data_config(backend=None, window=4, *, seed=3, aggregator="Mean",
                momentum=0.9, eval_chunk_clients=None, data_dir=None,
                **overrides):
    """``backend=None`` leaves data_store DEFAULTED (resident)."""
    cfg = (
        FedavgConfig()
        .data(dataset="mnist", num_clients=8, seed=seed)
        .training(global_model="mlp", server_lr=1.0, train_batch_size=8,
                  aggregator={"type": aggregator})
        .client(lr=0.1, momentum=momentum)
        .evaluation(evaluation_interval=0)
        .resources(window=window, data_store=backend, data_dir=data_dir,
                   eval_chunk_clients=eval_chunk_clients)
    )
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


def _server_params(algo):
    return [np.asarray(p) for p in jax.tree.leaves(algo.state.server.params)]


def _source_arrays(n=10, shard=2, feat=(3,), seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, shard) + feat).astype(np.float32)
    y = rng.integers(0, 4, size=(n, shard)).astype(np.int32)
    lengths = rng.integers(1, shard + 1, size=(n,)).astype(np.int32)
    return x, y, lengths


# ---------------------------------------------------------------------------
# store protocol: take round trips + shard cache reuse/rebuild
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("backend", list(DATA_STORE_BACKENDS))
def test_store_take_roundtrip(backend, tmp_path):
    arrays = _source_arrays()
    store = make_data_store(backend, arrays,
                            directory=str(tmp_path / "live"), shard_rows=3)
    try:
        # Sorted cohort ids (the windowed path's sample_cohort output).
        ids = np.array([0, 4, 9], np.int32)
        rows = store.take(ids)
        for got, src in zip(rows, arrays):
            np.testing.assert_array_equal(got, src[ids])
        # Unsorted cross-shard ids (the async engine's FIFO arrival
        # order) — values must honor CALLER order, not shard order.
        ids = np.array([7, 0, 9, 3], np.int32)
        for got, src in zip(store.take(ids), arrays):
            np.testing.assert_array_equal(got, src[ids])
        # gather is take device-put leaf by leaf, values bit-equal.
        for dev, src in zip(store.gather(ids), arrays):
            np.testing.assert_array_equal(np.asarray(dev), src[ids])
        assert store.row_bytes == 2 * 3 * 4 + 2 * 4 + 4
        assert store.total_bytes() == 10 * store.row_bytes
    finally:
        store.close()


def test_memmap_reuse_and_rebuild(tmp_path):
    """A verified shard set under a named directory is REUSED as-is
    (the kill-and-resume path: same files, no rewrite); any corruption
    silently rebuilds the cache from source — data shards are a
    derived cache, not the system of record like the state store."""
    arrays = _source_arrays()
    d = tmp_path / "shards"
    MemmapDataStore(arrays, directory=str(d), shard_rows=4).close()
    stamps = {p.name: p.stat().st_mtime_ns for p in d.glob("shard-*.npy")}
    assert len(stamps) == 3 * 3  # ceil(10/4) shards x 3 leaves

    reopened = MemmapDataStore(arrays, directory=str(d), shard_rows=4)
    try:
        assert {p.name: p.stat().st_mtime_ns
                for p in d.glob("shard-*.npy")} == stamps  # reused, not rewritten
        for got, src in zip(reopened.take(np.arange(10)), arrays):
            np.testing.assert_array_equal(got, src)
    finally:
        reopened.close()

    # Same-size corruption: the CRC reuse-gate fails, the store rebuilds.
    victim = d / "shard-00001.l00.npy"
    blob = bytearray(victim.read_bytes())
    blob[-1] ^= 0xFF
    victim.write_bytes(bytes(blob))
    rebuilt = MemmapDataStore(arrays, directory=str(d), shard_rows=4)
    try:
        for got, src in zip(rebuilt.take(np.arange(10)), arrays):
            np.testing.assert_array_equal(got, src)
    finally:
        rebuilt.close()
    checked, errors = validate_datastore_dir(d)
    assert checked == 9 and errors == []

    # A different shard_rows is a layout mismatch: rebuild, verify clean.
    MemmapDataStore(arrays, directory=str(d), shard_rows=3).close()
    checked, errors = validate_datastore_dir(d)
    assert checked == 12 and errors == []


# ---------------------------------------------------------------------------
# chaos: the strict forensic walk + the CLI mode
# ---------------------------------------------------------------------------


def test_validate_datastore_dir_chaos(tmp_path):
    from tools.validate_metrics import main as validate_main

    arrays = _source_arrays()
    d = tmp_path / "shards"
    MemmapDataStore(arrays, directory=str(d), shard_rows=4).close()
    assert validate_main(["--datastore", str(d)]) == 0
    shard = d / "shard-00001.l00.npy"
    data = shard.read_bytes()

    def errs():
        _, errors = validate_datastore_dir(d)
        return "\n".join(errors)

    # Orphaned .tmp from a killed atomic write.
    orphan = d / "shard-00000.l00.npy.tmp"
    orphan.write_bytes(b"half-written garbage")
    assert "orphaned atomic-write temp file" in errs()
    orphan.unlink()

    # Torn shard: truncation caught by the size check.
    shard.write_bytes(data[: len(data) // 2])
    assert "torn shard" in errs()

    # Same-size bit flip: caught by the CRC, named as corruption.
    flipped = bytearray(data)
    flipped[-1] ^= 0xFF
    shard.write_bytes(bytes(flipped))
    assert "CRC32" in errs()
    assert validate_main(["--datastore", str(d)]) != 0
    shard.write_bytes(data)

    # A stray shard file the manifest never recorded.
    stray = d / "shard-00099.l00.npy"
    np.save(stray, np.zeros(3, np.float32))
    assert "not in manifest" in errs()
    stray.unlink()

    # Kill before the manifest publish.
    (d / "manifest.json").unlink()
    assert "no manifest.json" in errs()


# ---------------------------------------------------------------------------
# the cross-backend contract (train bit-identity, eval float tolerance)
# ---------------------------------------------------------------------------

# Tier-1 runs the headline aggregator; Multikrum/GeoMed run the same
# contract in the slow zoo (each backend arm is its own compile — the
# 870 s tier-1 budget convention of PR 7).
_CONTRACT_AGGREGATORS = ("Mean",)


@pytest.mark.parametrize("aggregator", [
    a if a in _CONTRACT_AGGREGATORS else pytest.param(
        a, marks=pytest.mark.slow)
    for a in ("Mean", "Multikrum", "GeoMed")])
def test_cohort_equivalence_across_data_backends(aggregator):
    """The contract: memmap produces bit-identical train rows, staged
    byte counts and server params to resident for the same (seed,
    cohort schedule) — take/gather move rows without arithmetic.
    Streaming eval (memmap-only) differs from the monolithic reduction
    ONLY in summation order: metrics agree to float tolerance and the
    chunk walk is stamped.  Window 6 of 8 keeps cohort overlap in play
    and satisfies Multikrum's 2f+2 <= window bound at f=2."""
    adv = {"num_malicious_clients": 2, "adversary_config": {"type": "ALIE"}}
    res = data_config("resident", 6, aggregator=aggregator,
                      eval_chunk_clients=3, **adv).build()
    mm = data_config("memmap", 6, aggregator=aggregator,
                     eval_chunk_clients=3, **adv).build()
    try:
        # The memmap plane keeps the test stack HOST-resident; resident
        # keeps the legacy device-put stack.
        assert isinstance(mm._test_arrays[0], np.ndarray)
        assert not isinstance(res._test_arrays[0], np.ndarray)
        for _ in range(4):
            a, b = res.train(), mm.train()
            for k in ROW_KEYS:
                assert a[k] == b[k], (aggregator, k, a[k], b[k])
            assert a["data_store"] == "resident"
            assert b["data_store"] == "memmap"
            assert (a["data_bytes_staged"] == b["data_bytes_staged"]
                    and b["data_bytes_staged"] > 0)
        for p, q in zip(_server_params(res), _server_params(mm)):
            np.testing.assert_array_equal(p, q, err_msg=aggregator)
        ev_res, ev_mm = res.evaluate(), mm.evaluate()
        for k in ("test_loss", "test_acc", "test_acc_top3"):
            np.testing.assert_allclose(ev_res[k], ev_mm[k], rtol=1e-6,
                                       atol=1e-6, err_msg=(aggregator, k))
        assert ev_mm["eval_chunks"] == 3  # ceil(8 clients / 3 per chunk)
        assert "eval_chunks" not in ev_res  # monolithic path unchanged
        summary = mm.data_summary
        assert summary["backend"] == "memmap"
        assert summary["total_bytes"] == mm._data_store.total_bytes() > 0
        assert summary["eval_chunks"] == 3
    finally:
        res.stop()
        mm.stop()


def test_streaming_evaluate_chunk_math():
    """The pure walk: chunk count is ceil(n/chunk), the zero-length
    padding clients of the last chunk contribute EXACT zeros, and the
    final ratios are the monolithic sums' ratios."""
    def chunk_fn(params, cx, cy, lengths):
        m = jnp.asarray(lengths, jnp.float32)
        return {"ce_sum": 2.0 * m.sum(), "top1_sum": m.sum(),
                "top3_sum": m.sum(), "count": m.sum()}

    arrays = (np.zeros((8, 2, 3), np.float32), np.zeros((8, 2), np.int32),
              np.arange(1, 9, dtype=np.int32))
    metrics, n_chunks = streaming_evaluate(chunk_fn, None, arrays,
                                           chunk_clients=3)
    assert n_chunks == 3  # 3 + 3 + (2 real + 1 zero-pad)
    assert metrics["num_samples"] == 36.0  # sum(1..8): padding added nothing
    assert metrics["test_loss"] == 2.0 and metrics["test_acc"] == 1.0
    # chunk_clients beyond the population clamps to one full-set chunk.
    same, one = streaming_evaluate(chunk_fn, None, arrays, chunk_clients=99)
    assert one == 1 and same == metrics


# ---------------------------------------------------------------------------
# kill-and-resume under the memmap data plane
# ---------------------------------------------------------------------------


def _ooc_experiments(stop=8):
    return {
        "ooc": {
            "run": "FEDAVG",
            "stop": {"training_iteration": stop},
            "config": {
                "dataset_config": {"type": "mnist", "num_clients": 8,
                                   "train_bs": 8, "seed": 3},
                "global_model": "mlp",
                "client_config": {"lr": 0.1, "momentum": 0.9},
                "evaluation_interval": 4,
                "server_config": {"lr": 1.0,
                                  "aggregator": {"type": "Median"}},
                "state_store": "disk",
                "state_window": 5,
                "data_store": "memmap",
            },
        }
    }


def _result_rows(tdir, keep_eval_rounds=(4, 8)):
    rows = []
    for ln in (Path(tdir) / "result.json").read_text().strip().splitlines():
        r = json.loads(ln)
        for k in ("timers", "compile_cache_hits", "compile_cache_misses",
                  "state_stage_ms", "state_bytes_staged", "data_stage_ms"):
            r.pop(k, None)  # wall-clock / cache / staging-timing noise
        if r["training_iteration"] not in keep_eval_rounds:
            # Repeat-last-eval rows: _last_eval is not checkpointed —
            # only FRESH eval rounds participate in the bit-identity
            # check (pre-existing driver behavior on every path).
            for k in ("test_loss", "test_acc", "test_acc_top3",
                      "eval_chunks"):
                r.pop(k, None)
        rows.append(r)
    return rows


def test_kill_and_resume_memmap_data_bit_identical(tmp_path):
    """Acceptance: a SimulatedPreemption mid-sweep under
    data_store="memmap" (stacked on the disk state store) retries from
    the latest checkpoint — which references the shard manifest, never
    copies payloads — and reproduces the straight-through rows exactly,
    eval walked by the SAME streaming chunking on both arms."""
    from blades_tpu.tune import run_experiments
    from blades_tpu.tune.sweep import verify_result_rounds

    [straight] = run_experiments(
        _ooc_experiments(), storage_path=str(tmp_path / "a"), verbose=0,
        lanes=False, checkpoint_freq=2)
    [preempted] = run_experiments(
        _ooc_experiments(), storage_path=str(tmp_path / "b"), verbose=0,
        lanes=False, checkpoint_freq=2, max_failures=1, preempt_after=5,
        retry_backoff_base=0.0)
    assert "status" not in preempted and preempted["rounds"] == 8
    tdir = Path(preempted["dir"])
    assert "SimulatedPreemption" in (tdir / "error.txt").read_text()
    assert verify_result_rounds(tdir / "result.json") == list(range(1, 9))
    srows, prows = _result_rows(straight["dir"]), _result_rows(tdir)
    assert srows == prows
    # Fresh eval rounds went through the streaming walk on both arms.
    assert srows[3]["eval_chunks"] >= 1
    # data_bytes_staged is pure data movement — deterministic, so it
    # participates in the bit-identity check above; spot-check it here.
    assert srows[-1]["data_store"] == "memmap"
    assert srows[-1]["data_bytes_staged"] > 0


# ---------------------------------------------------------------------------
# async composition + the calibrated-ticks satellite
# ---------------------------------------------------------------------------


def test_async_event_cohort_through_data_store_and_ticks_purity():
    """execution='async' + host state store: event-cohort data rows are
    gathered per cycle through the DataStore, bit-identical to the
    resident data plane — with the two arms ALSO differing in
    ``ticks_per_sec`` (0 vs calibrated), which must never enter the
    arrival realization.  The memmap arm's row stamps are
    schema-valid."""
    from blades_tpu.obs.schema import ROUND_RECORD_FIELDS, validate_record

    def build(data_backend, ticks):
        cfg = data_config(data_backend, None, aggregator="Median")
        cfg.resources(execution="async", state_store="host")
        cfg.async_config = {"rate": 0.5, "agg_every": 4, "staleness_cap": 4,
                            "ticks_per_sec": ticks}
        return cfg.build()

    res, mm = build(None, 0.0), build("memmap", 25.0)
    try:
        assert mm._data_store.backend == "memmap"
        for _ in range(3):
            a, b = res.train(), mm.train()
            for k in ROW_KEYS + ("tick",):
                assert a[k] == b[k], (k, a[k], b[k])
        stamps = {k: b[k] for k in ("data_store", "data_stage_ms",
                                    "data_bytes_staged", "state_store",
                                    "updates_per_sec")}
        assert stamps["data_store"] == "memmap"
        assert stamps["data_bytes_staged"] > 0
        assert set(stamps) <= set(ROUND_RECORD_FIELDS)
        validate_record({"experiment": "e", "trial": "t",
                         "training_iteration": 1, **stamps})
    finally:
        res.stop()
        mm.stop()


def test_ticks_per_sec_sizing_math():
    """size_for_target derives agg_every/buffer from a wall-clock
    updates_per_sec target against the spec's expected supply, raising
    when the fleet cannot deliver; the realization knobs are
    untouched."""
    import dataclasses

    from blades_tpu.arrivals import (AsyncSpec, expected_arrivals_per_sec,
                                     size_for_target)

    spec = AsyncSpec(seed=11, rate=0.05, slow_fraction=0.5, slow_factor=0.5,
                     agg_every=2, buffer_capacity=4, ticks_per_sec=20.0)
    # 50 fast * .05 + 50 slow * .05 * .5 = 3.75/tick -> 75/s at 20 Hz.
    assert expected_arrivals_per_sec(spec, 100) == pytest.approx(75.0)
    sized = size_for_target(spec, 100, 10.0)
    assert sized.agg_every == 10 and sized.buffer_capacity == 20
    assert sized.seed == 11 and sized.rate == 0.05  # realization untouched
    assert size_for_target(spec, 100, 10.0,
                           agg_interval_sec=2.0).agg_every == 20
    with pytest.raises(ValueError, match="exceeds"):
        size_for_target(spec, 100, 76.0)
    with pytest.raises(ValueError, match="must be > 0"):
        size_for_target(spec, 100, 0.0)
    with pytest.raises(ValueError, match="calibrated"):
        expected_arrivals_per_sec(dataclasses.replace(
            spec, ticks_per_sec=0.0), 100)
    with pytest.raises(ValueError, match="ticks_per_sec"):
        AsyncSpec(ticks_per_sec=-1.0)


# ---------------------------------------------------------------------------
# the window control family under the out-of-core pair
# ---------------------------------------------------------------------------

_QUIET_RULES = {"fpr_collapse": "off", "reputation_collapse": "off",
                "round_time_regression": "off", "ingest_collapse": "off",
                "ingest_stall": "off"}


def _controlled_ooc_config(rules):
    cfg = data_config(None, None, aggregator="Median")
    cfg.resources(execution="async", state_store="host")
    cfg.async_config = {"rate": 0.5, "agg_every": 4, "staleness_cap": 4}
    cfg.control(rules=rules)
    return cfg


def test_window_family_gate_and_actuation():
    """Under state_store != resident, agg_every/buffer control moves
    stay validate()-rejected (they can GROW the staged set) but the
    shrink-only window family is admitted — seeded from the live
    agg_every and actuated as an engine re-geometry."""
    from blades_tpu.control import ControlAction

    # The default table maps staleness_runaway -> agg_every: rejected.
    with pytest.raises(ValueError, match="shrink-only"):
        _controlled_ooc_config(dict(_QUIET_RULES)).validate()
    good = _controlled_ooc_config(
        {**_QUIET_RULES, "staleness_runaway": "window"})
    good.validate()
    algo = good.build()
    try:
        assert algo._controller.values["window"] == 4  # seeded = agg_every
        act = ControlAction(seq=0, round=1, tick=1,
                            rule="staleness_runaway", actuator="window",
                            old=4, new=2, pre={"old": 4})
        algo._apply_control_action(act)
        assert algo._async.agg_every == 2  # the cohort size IS the window
        r = algo.train()
        assert r["cohort_size"] == 2
    finally:
        algo.stop()


# ---------------------------------------------------------------------------
# validate()-time gates
# ---------------------------------------------------------------------------


def test_validate_gates():
    def check(match, **kw):
        with pytest.raises(ValueError, match=match):
            data_config(**kw).validate()

    check("data_store must be one of", backend="ramdisk")
    check("per-cohort staging path", backend="memmap", window=None)
    check("data_dir is set but", backend=None, window=4,
          data_dir="/tmp/nowhere")
    check("eval_chunk_clients", backend="memmap", window=4,
          eval_chunk_clients=0)
    # Legal compositions still validate.
    data_config("memmap", 4).validate()
    async_ooc = data_config("memmap", None)
    async_ooc.resources(execution="async", state_store="host")
    async_ooc.async_config = {"rate": 0.5, "agg_every": 4}
    async_ooc.validate()


# ---------------------------------------------------------------------------
# the headline acceptance: 1M registered / 10k cohort on CPU
# ---------------------------------------------------------------------------


def _memmap_population(root, n_clients, rows_per_client=2, shape=(4, 4, 1),
                       num_classes=2, seed=0):
    """A 1M-client population whose source leaves are DISK memmaps
    written in bounded slices — the host never materialises the full
    partition (numpy's tracemalloc-visible allocations stay
    slice-sized; memmap pages are the OS page cache's problem)."""
    from blades_tpu.data.datasets import FLDataset
    from blades_tpu.data.partition import Partition

    d = Path(root) / "src"
    d.mkdir(parents=True)
    x = np.lib.format.open_memmap(
        d / "x.npy", mode="w+", dtype=np.float32,
        shape=(n_clients, rows_per_client) + shape)
    y = np.lib.format.open_memmap(
        d / "y.npy", mode="w+", dtype=np.int32,
        shape=(n_clients, rows_per_client))
    lengths = np.lib.format.open_memmap(
        d / "len.npy", mode="w+", dtype=np.int32, shape=(n_clients,))
    rng = np.random.default_rng(seed)
    mus = rng.normal(size=(num_classes,) + shape).astype(np.float32)
    step = 131072
    for lo in range(0, n_clients, step):
        hi = min(lo + step, n_clients)
        yy = rng.integers(0, num_classes,
                          size=(hi - lo, rows_per_client)).astype(np.int32)
        y[lo:hi] = yy
        x[lo:hi] = mus[yy] + 0.5 * rng.standard_normal(
            size=(hi - lo, rows_per_client) + shape).astype(np.float32)
    lengths[:] = rows_per_client
    for a in (x, y, lengths):
        a.flush()
    n_test = 64
    ty = rng.integers(0, num_classes,
                      size=(n_test, rows_per_client)).astype(np.int32)
    tx = (mus[ty] + 0.5 * rng.standard_normal(
        size=(n_test, rows_per_client) + shape)).astype(np.float32)
    return FLDataset(
        name="megapop", train=Partition(x=x, y=y, lengths=lengths),
        test_x=tx.reshape((-1,) + shape)[:64], test_y=ty.reshape(-1)[:64],
        test=Partition(x=tx, y=ty,
                       lengths=np.full((n_test,), rows_per_client,
                                       np.int32)),
        num_classes=num_classes, input_shape=shape, synthetic=True)


def test_1m_registered_10k_cohort_memory_ceiling(tmp_path):
    """The acceptance rig (ROADMAP item 2): 1 000 000 registered
    clients / 10 000 sampled per round train through the memmap data
    store on one CPU host, and the asserted host peak allocation is a
    small fraction of the population's data bytes — RSS tracks the
    COHORT, not the registration count.  momentum=0 keeps the state
    row template empty (the resident state store holds (1M, 0) =
    nothing), so the data plane is the quantity under test."""
    import tracemalloc

    from blades_tpu.models.mlp import MLP

    n, w = 1_000_000, 10_000
    ds = _memmap_population(tmp_path, n)
    tracemalloc.start()
    cfg = (
        FedavgConfig()
        .data(dataset=ds, num_clients=n, seed=0)
        .training(global_model=MLP(hidden1=8, hidden2=8, num_classes=2),
                  num_classes=2, input_shape=(4, 4, 1), server_lr=0.5,
                  train_batch_size=2)
        .client(lr=0.1, momentum=0.0)
        .evaluation(evaluation_interval=0)
        .resources(state_store="resident", window=w, data_store="memmap",
                   data_dir=str(tmp_path / "shards"))
    )
    algo = cfg.build()
    try:
        rows = [algo.train() for _ in range(2)]
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        for r in rows:
            assert np.isfinite(r["train_loss"])
        store = algo._data_store
        total = store.total_bytes()
        assert store.n_clients == n and total >= 100_000_000
        # Cohort-proportional staging: exactly the 10k rows' bytes.
        assert rows[-1]["data_bytes_staged"] == w * store.row_bytes
        assert rows[-1]["cohort_size"] == w
        assert rows[-1]["data_store"] == "memmap"
        # The ceiling: host peak traced allocation is a small fraction
        # of the 140 MB the resident plane would have malloc'd up
        # front (measured ~10%; 25% leaves slack for allocator noise).
        assert peak < total // 4, (peak, total)
        # The shard cache really is on disk, split into many files.
        shard_files = list((tmp_path / "shards").glob("shard-*.npy"))
        assert len(shard_files) == 3 * -(-n // store.shard_rows)
    finally:
        algo.stop()
