"""Model zoo tests (model: fllib/models/tests/test_models.py)."""

import jax
import jax.numpy as jnp
import pytest

from blades_tpu.models import MLP, FashionCNN, ModelCatalog, register_model
from blades_tpu.models.layers import BatchStatsNorm


@pytest.mark.parametrize(
    "name,shape",
    [("mlp", (2, 28, 28, 1)), ("cnn", (2, 28, 28, 1)),
     ("resnet10", (2, 32, 32, 3)), ("cct", (2, 32, 32, 3))],
)
def test_catalog_forward_shapes(name, shape):
    m = ModelCatalog.get_model(name)
    params = m.init(jax.random.PRNGKey(0), jnp.zeros(shape))
    out = m.apply(params, jnp.zeros(shape))
    assert out.shape == (shape[0], 10)


def test_catalog_substring_matching():
    # Same matching rule as ref: fllib/models/catalog.py:16-29.
    assert isinstance(ModelCatalog.get_model("mlp_special"), MLP)
    assert isinstance(ModelCatalog.get_model("my_cnn"), FashionCNN)


def test_catalog_passthrough_module():
    m = MLP()
    assert ModelCatalog.get_model(m) is m


def test_custom_model_registration():
    register_model("tinynet", lambda num_classes=10: MLP(hidden1=4, hidden2=4,
                                                         num_classes=num_classes))
    m = ModelCatalog.get_model("tinynet", num_classes=3)
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
    assert m.apply(params, jnp.zeros((1, 28, 28, 1))).shape == (1, 3)


def test_batch_stats_norm_is_stateless_and_normalises():
    m = BatchStatsNorm()
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 8)) * 5.0 + 3.0
    params = m.init(jax.random.PRNGKey(1), x)
    # Pure function of params: no batch_stats collection exists.
    assert set(params.keys()) == {"params"}
    y = m.apply(params, x)
    assert jnp.allclose(y.mean(axis=0), 0.0, atol=1e-4)
    assert jnp.allclose(y.std(axis=0), 1.0, atol=1e-2)


def test_batch_stats_norm_custom_vjp_matches_autodiff():
    """The hand-written BN backward (layers._bn_apply, the ungrouped hot
    path) must reproduce plain-autodiff gradients of the naive two-pass
    formulation for x, scale and bias."""
    key = jax.random.PRNGKey(2)
    x = jax.random.normal(key, (4, 5, 5, 8)) * 2.0 + 1.5
    scale = jax.random.normal(jax.random.fold_in(key, 1), (8,)) + 1.0
    bias = jax.random.normal(jax.random.fold_in(key, 2), (8,))
    eps = 1e-5

    from blades_tpu.models.layers import _bn_apply

    def naive(x, scale, bias):
        axes = (0, 1, 2)
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
        y = (x - mean) * jax.lax.rsqrt(var + eps)
        return y * scale + bias

    def loss_custom(x, s, b):
        y = _bn_apply(x, s, b, eps)
        return jnp.sum(y * jnp.cos(y))

    def loss_naive(x, s, b):
        y = naive(x, s, b)
        return jnp.sum(y * jnp.cos(y))

    y1 = _bn_apply(x, scale, bias, eps)
    y2 = naive(x, scale, bias)
    assert jnp.allclose(y1, y2, atol=1e-5)
    g1 = jax.grad(loss_custom, argnums=(0, 1, 2))(x, scale, bias)
    g2 = jax.grad(loss_naive, argnums=(0, 1, 2))(x, scale, bias)
    for a, b_ in zip(g1, g2):
        assert jnp.allclose(a, b_, atol=2e-4), (
            jnp.abs(a - b_).max())

    # Catastrophic-cancellation regime: huge mean, tiny variance.  A
    # one-pass E[x^2]-mean^2 variance loses ALL significance here in f32
    # (ulp of E[x^2]~2.5e5 exceeds the true var); the two-pass centered
    # formula must still normalize correctly, not just stay finite.
    x_hard = x * 0.01 + 500.0
    y_hard = _bn_apply(x_hard, scale, bias, eps)
    assert jnp.allclose(y_hard, naive(x_hard, scale, bias), atol=1e-3)
    gx = jax.grad(loss_custom)(x_hard, scale, bias)
    assert jnp.isfinite(gx).all()


def test_models_are_pure_no_mutable_collections():
    # The FL-soundness property: track_running_stats=False analogue
    # (ref: fllib/models/cifar10/resnet_cifar.py:14).
    m = ModelCatalog.get_model("resnet10")
    variables = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    assert set(variables.keys()) == {"params"}


@pytest.mark.slow  # compiles every CCT/CVT variant (~27 s; catalog forward shapes stay tier-1)
def test_cct_cvt_variant_zoo():
    """The full named variant surface (ref: cctnets/cct.py:203-658,
    cvt.py:138-321): every 32x32 variant builds and runs forward."""
    from blades_tpu.models.cct import CCT, CVT, VARIANTS

    # Name surface parity with the reference zoo.
    for name in ["cct_2_3x2_32", "cct_4_3x2_32", "cct_6_3x1_32",
                 "cct_6_3x2_32", "cct_7_3x1_32", "cct_7_3x2_32",
                 "cct_7_7x2_224", "cct_14_7x2_224", "cct_14_7x2_384",
                 "cvt_2_4_32", "cvt_7_4_32"]:
        assert name in VARIANTS, name
        assert f"{name}_sine" in VARIANTS, name
    assert "cct_7_3x1_32_c100" in VARIANTS
    assert "cct_7_3x1_32_sine_c100" in VARIANTS

    x = jnp.zeros((2, 32, 32, 3))
    for name in ["cct_6_3x2_32", "cct_7_3x2_32_sine", "cvt_2_4_32",
                 "cvt_6_4_32_sine"]:
        m = VARIANTS[name]()
        assert isinstance(m, (CCT, CVT))
        params = m.init(jax.random.PRNGKey(0), x)
        assert m.apply(params, x).shape == (2, 10)

    # c100 preset defaults to 100 classes.
    m = VARIANTS["cct_7_3x1_32_c100"]()
    params = m.init(jax.random.PRNGKey(0), x)
    assert m.apply(params, x).shape == (2, 100)


def test_catalog_resolves_named_cct_variants():
    from blades_tpu.models.cct import CVT

    m = ModelCatalog.get_model("cvt_2_4_32", num_classes=7)
    assert isinstance(m, CVT)
    x = jnp.zeros((1, 32, 32, 3))
    params = m.init(jax.random.PRNGKey(0), x)
    assert m.apply(params, x).shape == (1, 7)


# Offline weight-import utility, not a round-path contract; the CCT
# forward-shape test above stays tier-1 (~6 s saved, PR 20 budget
# rebalance).
@pytest.mark.slow
def test_cct_pretrained_weight_import(tmp_path):
    """The reference's pretrained-checkpoint hooks (pe_check /
    resize_pos_embed / fc_check, cctnets/utils/helpers.py) in flax form:
    exact round-trip, positional-embedding grid resize, and
    fresh-head transfer to a different class count."""
    import numpy as np

    from blades_tpu.models.cct import (cct_2_3x2_32, load_pretrained_params,
                                       save_params)

    m = cct_2_3x2_32()
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))["params"]
    ckpt = tmp_path / "cct.npz"
    save_params(params, ckpt)

    # Exact round-trip.
    loaded = load_pretrained_params(params, ckpt)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Different image size -> pos_embed token grid resized, not rejected.
    m48 = cct_2_3x2_32().clone(img_size=48)
    p48 = m48.init(jax.random.PRNGKey(1), jnp.zeros((1, 48, 48, 3)))["params"]
    merged = load_pretrained_params(p48, ckpt)
    out = m48.apply({"params": merged}, jnp.zeros((2, 48, 48, 3)))
    assert out.shape == (2, 10)

    # Different class count -> head keeps its fresh init, body loads.
    m100 = cct_2_3x2_32(num_classes=100)
    p100 = m100.init(jax.random.PRNGKey(2),
                     jnp.zeros((1, 32, 32, 3)))["params"]
    merged = load_pretrained_params(p100, ckpt)
    out = m100.apply({"params": merged}, jnp.zeros((2, 32, 32, 3)))
    assert out.shape == (2, 100)

    # A checkpoint from a different model family must fail loudly
    # instead of silently returning fresh init.  (The MLP's Dense_0
    # name-collides with CCT's SeqPool Dense, which since the ADVICE-r4
    # head-only exemption is a BACKBONE leaf -> shape-mismatch error.)
    import pytest

    from blades_tpu.models import MLP
    mlp = MLP()
    mp = mlp.init(jax.random.PRNGKey(3), jnp.zeros((1, 28, 28, 1)))["params"]
    wrong = tmp_path / "mlp.npz"
    save_params(mp, wrong)
    with pytest.raises(ValueError,
                       match="shape mismatch|matched NO parameter"):
        load_pretrained_params(params, wrong)


def test_cct_pretrained_import_msgpack_roundtrip(tmp_path):
    """ADVICE r4: the .msgpack branch raised UnboundLocalError (late
    function-local traverse_util import) and no test exercised it."""
    import numpy as np
    from flax import serialization

    from blades_tpu.models.cct import cct_2_3x2_32, load_pretrained_params

    m = cct_2_3x2_32()
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))["params"]
    ckpt = tmp_path / "cct.msgpack"
    ckpt.write_bytes(serialization.msgpack_serialize(
        jax.tree.map(np.asarray, params)))

    loaded = load_pretrained_params(params, ckpt)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cct_pretrained_import_rejects_wrong_width_backbone(tmp_path):
    """ADVICE r4: the fresh-init exemption is for the classifier HEAD
    only (the reference's fc_check exempts exactly fc) — a trailing-dim
    mismatch in a backbone layer must raise, not silently lose the layer
    to fresh init."""
    import numpy as np
    import pytest
    from flax import traverse_util

    from blades_tpu.models.cct import (cct_2_3x2_32, load_pretrained_params,
                                       save_params)

    m = cct_2_3x2_32()
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))["params"]
    flat = traverse_util.flatten_dict(params)
    # Widen one ENCODER (backbone) Dense kernel's trailing dim.
    bk = next(k for k in flat
              if k[0].startswith("EncoderBlock") and k[-1] == "kernel")
    flat[bk] = np.zeros(flat[bk].shape[:-1] + (flat[bk].shape[-1] + 8,),
                        np.float32)
    bad = tmp_path / "bad.npz"
    save_params(traverse_util.unflatten_dict(flat), bad)

    with pytest.raises(ValueError, match="shape mismatch"):
        load_pretrained_params(params, bad)
