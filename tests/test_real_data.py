"""Real-data loader tests (VERDICT r1 #6: the IDX/pickle readers were dead
code in practice — every accuracy number came from the synthetic fallback).

Fixtures write tiny files in the STANDARD raw formats (IDX for MNIST-like,
CIFAR python pickles) into a temp data root; the loaders must parse them,
normalise, and mark the dataset non-synthetic.
"""

import gzip
import pickle

import numpy as np
import pytest

from blades_tpu.data import DatasetCatalog

N_TRAIN, N_TEST = 48, 16


def _write_idx(path, arr, compress=False):
    header = bytes([0, 0, 0x08, arr.ndim]) + b"".join(
        int(d).to_bytes(4, "big") for d in arr.shape
    )
    payload = header + arr.astype(np.uint8).tobytes()
    if compress:
        path = path.with_suffix(path.suffix + ".gz")
        path.write_bytes(gzip.compress(payload))
    else:
        path.write_bytes(payload)


@pytest.fixture()
def data_root(tmp_path, monkeypatch):
    monkeypatch.setenv("BLADES_TPU_DATA_ROOT", str(tmp_path))
    rng = np.random.default_rng(0)

    # MNIST-like IDX (train gzipped to cover both openers).
    for sub in ("mnist", "fashionmnist"):
        d = tmp_path / sub
        d.mkdir()
        _write_idx(d / "train-images-idx3-ubyte",
                   rng.integers(0, 255, (N_TRAIN, 28, 28)), compress=True)
        _write_idx(d / "train-labels-idx1-ubyte",
                   rng.integers(0, 10, (N_TRAIN,)), compress=True)
        _write_idx(d / "t10k-images-idx3-ubyte",
                   rng.integers(0, 255, (N_TEST, 28, 28)))
        _write_idx(d / "t10k-labels-idx1-ubyte",
                   rng.integers(0, 10, (N_TEST,)))

    # CIFAR-10 python pickles.
    c10 = tmp_path / "cifar10" / "cifar-10-batches-py"
    c10.mkdir(parents=True)
    per = N_TRAIN // 5 + 1
    for i in range(1, 6):
        with open(c10 / f"data_batch_{i}", "wb") as f:
            pickle.dump({b"data": rng.integers(0, 255, (per, 3072), dtype=np.uint8),
                         b"labels": list(rng.integers(0, 10, (per,)))}, f)
    with open(c10 / "test_batch", "wb") as f:
        pickle.dump({b"data": rng.integers(0, 255, (N_TEST, 3072), dtype=np.uint8),
                     b"labels": list(rng.integers(0, 10, (N_TEST,)))}, f)

    # CIFAR-100 python pickles (fine_labels).
    c100 = tmp_path / "cifar100" / "cifar-100-python"
    c100.mkdir(parents=True)
    for split, n in (("train", N_TRAIN), ("test", N_TEST)):
        with open(c100 / split, "wb") as f:
            pickle.dump({b"data": rng.integers(0, 255, (n, 3072), dtype=np.uint8),
                         b"fine_labels": list(rng.integers(0, 100, (n,)))}, f)
    return tmp_path


@pytest.mark.parametrize("name,shape,ncls,n_train", [
    ("mnist", (28, 28, 1), 10, N_TRAIN),
    ("fashionmnist", (28, 28, 1), 10, N_TRAIN),
    ("cifar10", (32, 32, 3), 10, (N_TRAIN // 5 + 1) * 5),
    ("cifar100", (32, 32, 3), 100, N_TRAIN),
])
def test_real_loader(data_root, name, shape, ncls, n_train):
    ds = DatasetCatalog.get_dataset(name, num_clients=4, seed=0)
    assert not ds.synthetic
    assert ds.input_shape == shape
    assert ds.num_classes == ncls
    assert ds.test_x.shape == (N_TEST,) + shape
    assert ds.test_x.dtype == np.float32
    assert int(ds.train.lengths.sum()) == n_train
    assert 0 <= ds.test_y.min() and ds.test_y.max() < ncls
    # Normalisation happened: raw u8 range is gone.
    assert ds.test_x.max() < 20.0 and ds.test_x.min() < 0.0


def test_real_data_trains_end_to_end(data_root):
    from blades_tpu.algorithms import FedavgConfig

    cfg = (
        FedavgConfig()
        .data(dataset="mnist", num_clients=4)
        .training(global_model="mlp", server_lr=1.0, train_batch_size=8)
        .evaluation(evaluation_interval=2)
    )
    algo = cfg.build()
    assert not algo.dataset.synthetic
    r = [algo.train() for _ in range(2)][-1]
    assert np.isfinite(r["train_loss"])
    assert "test_acc" in r


@pytest.mark.slow  # the shrunk ResNet-34 point is still minutes of CPU compile
def test_cifar100_yaml_runs_two_rounds(tmp_path):
    """BASELINE config 5's YAML parses (DnC + FLTrust grid); a shrunk
    DnC instance runs 2 rounds with ResNet-34.  The FLTrust point is
    pinned out of the run — each grid point is its own ~5-minute
    ResNet-34 CPU compile, and FLTrust is exercised end-to-end by
    test_aggregators/test_dsharded."""
    from pathlib import Path

    from blades_tpu.tune import (
        expand_grid,
        load_experiments_from_file,
        run_experiments,
    )

    yml = (Path(__file__).parent.parent / "blades_tpu" / "tuned_examples"
           / "fedavg_cifar100_resnet34.yaml")
    experiments = load_experiments_from_file(str(yml))
    [spec] = experiments.values()
    assert len(expand_grid(spec["config"])) == 2  # DnC, FLTrust
    # Shrink to CI scale: same model family/dataset/adversary, tiny counts.
    # evaluation_interval > max rounds: the eval program is a second
    # ResNet-34 CPU compile (~8 min of pure compile time in CI) and the
    # eval path is covered by every other integration test.
    spec["config"]["dataset_config"].update(num_clients=6, train_bs=4)
    spec["config"]["num_malicious_clients"] = 1
    spec["config"]["rounds_per_dispatch"] = 1
    spec["config"]["evaluation_interval"] = 50
    spec["config"]["server_config"]["aggregator"] = {"type": "DnC"}
    summaries = run_experiments(
        experiments, storage_path=str(tmp_path), verbose=0,
        max_rounds_override=2,
    )
    assert len(summaries) == 1
    for s in summaries:
        assert s["rounds"] == 2
