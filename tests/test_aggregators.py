"""Golden-value and property tests for the robust aggregators.

Pattern (1) of the reference's test strategy (SURVEY.md §4): exact
expectations on a small stacked update matrix, mirroring
ref: fllib/aggregators/tests/test_aggregators.py where its expectations are
valid, plus property tests (Weiszfeld optimality, outlier rejection) where
the reference's expectations depend on torch RNG or are stale.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.ops import (
    Centeredclipping,
    Clippedclustering,
    DnC,
    FLTrust,
    GeoMed,
    Mean,
    Median,
    Multikrum,
    Signguard,
    Trimmedmean,
    get_aggregator,
)

RAW = jnp.array(
    [
        [1.0, 2.0, 3.0],
        [-1.0, 4.0, -1.0],
        [2.0, 2.0, 3.0],
        [3.0, 1.0, 3.0],
    ]
)


def run(agg, updates, state=None, key=None):
    if state is None:
        state = agg.init(updates.shape[1], updates.shape[0])
    out, new_state = agg(updates, state, key=key)
    return np.asarray(out), new_state


def test_mean():
    out, _ = run(Mean(), RAW)
    np.testing.assert_allclose(out, [1.25, 2.25, 2.0], rtol=1e-6)


def test_median():
    out, _ = run(Median(), RAW)
    np.testing.assert_allclose(out, [1.5, 2.0, 3.0], rtol=1e-6)


def test_median_odd_count():
    out, _ = run(Median(), RAW[:3])
    np.testing.assert_allclose(out, [1.0, 2.0, 3.0], rtol=1e-6)


def test_trimmedmean():
    # 6 clients, f=1 -> num_excluded rounds up to 2: drop 2 high + 2 low per
    # coordinate, mean the middle two.
    x = jnp.array(
        [
            [0.0, 10.0],
            [1.0, 20.0],
            [2.0, 30.0],
            [3.0, 40.0],
            [4.0, 50.0],
            [100.0, -100.0],
        ]
    )
    out, _ = run(Trimmedmean(num_byzantine=1), x)
    np.testing.assert_allclose(out, [2.5, 25.0], rtol=1e-6)


def test_trimmedmean_num_excluded_rounds_up_to_even():
    assert Trimmedmean(num_byzantine=3).num_excluded == 4
    assert Trimmedmean(num_byzantine=2).num_excluded == 2
    assert Trimmedmean(num_byzantine=3, filter_frac=0.5).num_excluded == 2


def test_trimmedmean_too_few_clients_raises():
    with pytest.raises(ValueError):
        run(Trimmedmean(num_byzantine=2), RAW)


def test_geomed_optimality_condition():
    # Geometric-median characterization: unit vectors from the median to the
    # points sum to ~0 (same check as the reference test).
    out, _ = run(GeoMed(eps=1e-8, maxiter=1000, ftol=1e-22), RAW)
    diffs = np.asarray(RAW) - out
    units = diffs / np.linalg.norm(diffs, axis=1, keepdims=True)
    # f32 Weiszfeld stalls once the objective stops moving at machine eps;
    # the residual is backend-dependent (TPU ~1e-4, CPU ~2e-3).
    np.testing.assert_allclose(units.sum(axis=0), np.zeros(3), atol=5e-3)


def test_dnc_rejects_outlier():
    key = jax.random.PRNGKey(0)
    benign = jax.random.normal(key, (8, 32)) * 0.1
    outlier = jnp.ones((2, 32)) * 50.0
    x = jnp.concatenate([benign, outlier])
    out, _ = run(DnC(num_byzantine=2, sub_dim=16, num_iters=3), x, key=key)
    benign_mean = np.asarray(benign.mean(axis=0))
    assert np.linalg.norm(out - benign_mean) < 1.0
    assert np.abs(out).max() < 5.0


def test_multikrum_picks_clustered_update():
    rows = [[0.1 * i, 0.0] for i in range(5)] + [[100.0, 100.0]]
    x = jnp.array(rows)
    out, _ = run(Multikrum(num_byzantine=1, k=1), x)
    # k=1 Krum returns one of the clustered updates, never the outlier.
    assert np.abs(out).max() <= 1.0


def test_multikrum_validates():
    with pytest.raises(ValueError):
        run(Multikrum(num_byzantine=2), RAW)


def test_centeredclipping_large_tau_one_iter_is_mean():
    agg = Centeredclipping(tau=1e9, n_iter=1)
    out, new_state = run(agg, RAW)
    np.testing.assert_allclose(out, np.asarray(RAW.mean(axis=0)), rtol=1e-6)
    # The mean is a fixed point of clipping around itself...
    out2, _ = agg(RAW, new_state)
    np.testing.assert_allclose(np.asarray(out2), out, rtol=1e-5)
    # ...but state carries: new data moves the center to the new mean.
    out3, _ = agg(RAW * 3.0, new_state)
    np.testing.assert_allclose(np.asarray(out3), 3.0 * np.asarray(RAW.mean(axis=0)), rtol=1e-5)


def test_centeredclipping_small_tau_bounds_motion():
    out, _ = run(Centeredclipping(tau=0.5, n_iter=1), RAW)
    assert np.linalg.norm(out) <= 0.5 + 1e-6


def test_signguard_filters_sign_flipped():
    key = jax.random.PRNGKey(1)
    benign = jax.random.normal(key, (7, 64)) * 0.1 + 0.05
    malicious = -10.0 * jnp.ones((3, 64))
    x = jnp.concatenate([benign, malicious])
    out, _ = run(Signguard(), x)
    benign_mean = np.asarray(benign.mean(axis=0))
    assert np.linalg.norm(out - benign_mean) < np.linalg.norm(
        np.asarray(x.mean(axis=0)) - benign_mean
    )


def test_clippedclustering_keeps_majority_cluster():
    key = jax.random.PRNGKey(2)
    benign = jax.random.normal(key, (7, 32)) * 0.1 + jnp.ones((32,))
    malicious = jax.random.normal(key, (3, 32)) * 0.1 - jnp.ones((32,))
    x = jnp.concatenate([benign, malicious])
    agg = Clippedclustering(history_rounds=10)
    state = agg.init(32, 10)
    out, new_state = agg(x, state)
    benign_mean = np.asarray(benign.mean(axis=0))
    # Clipping rescales rows, so compare directions: the aggregate should
    # point with the benign cluster, not the poisoned mean.
    cos = out @ benign_mean / (np.linalg.norm(out) * np.linalg.norm(benign_mean))
    assert cos > 0.95
    assert int(new_state["count"]) == 10


def test_fltrust_zeroes_negative_cosine():
    server = jnp.ones((1, 4))
    good = jnp.ones((2, 4)) * 2.0
    bad = -jnp.ones((2, 4))
    x = jnp.concatenate([good, bad, server])
    out, _ = run(FLTrust(), x)
    # Only the two positive-cosine clients contribute, rescaled to |server|.
    np.testing.assert_allclose(out, np.ones(4), rtol=1e-5)


def test_get_aggregator_injects_num_byzantine():
    agg = get_aggregator("Trimmedmean", num_byzantine=3)
    assert agg.num_byzantine == 3
    agg = get_aggregator({"type": "Multikrum", "k": 2}, num_byzantine=1)
    assert agg.num_byzantine == 1 and agg.k == 2
    assert isinstance(get_aggregator("Mean"), Mean)
    with pytest.raises(KeyError):
        get_aggregator("Nope")


@pytest.mark.parametrize(
    "agg",
    [
        Mean(),
        Median(),
        Trimmedmean(num_byzantine=1),
        GeoMed(),
        DnC(num_byzantine=1, sub_dim=4, num_iters=2),
        Multikrum(num_byzantine=1, k=2),
        Centeredclipping(),
        Signguard(),
        Clippedclustering(history_rounds=4),
    ],
    ids=lambda a: a.name,
)
def test_aggregators_jit(agg):
    # Every aggregator must run under jit with explicit threaded state.
    x = jax.random.normal(jax.random.PRNGKey(3), (6, 16))
    state = agg.init(16, 6)

    @jax.jit
    def step(updates, state, key):
        return agg(updates, state, key=key)

    out, new_state = step(x, state, jax.random.PRNGKey(0))
    out2, _ = step(x, new_state, jax.random.PRNGKey(0))
    assert np.asarray(out).shape == (16,)
    assert np.all(np.isfinite(np.asarray(out)))
    assert np.all(np.isfinite(np.asarray(out2)))


# ---- regression tests for review findings ---------------------------------


def test_dnc_empty_keep_set_raises():
    import pytest
    from blades_tpu.ops.aggregators import DnC
    import jax, jax.numpy as jnp

    u = jnp.ones((4, 8))
    with pytest.raises(ValueError, match="keep"):
        DnC(num_byzantine=4, sub_dim=8)(u, key=jax.random.PRNGKey(0))


def test_fltrust_requires_trusted_row_via_server():
    import pytest
    import jax, jax.numpy as jnp
    from blades_tpu.core import Server, TaskSpec

    task = TaskSpec(model="mlp", input_shape=(28, 28, 1)).build()
    params = task.init_params(jax.random.PRNGKey(0))
    server = Server.from_config(aggregator="FLTrust", lr=1.0)
    state = server.init(params, 4)
    from blades_tpu.utils.tree import ravel_fn

    _, _, d = ravel_fn(params)
    updates = jnp.ones((4, d))
    with pytest.raises(ValueError, match="trusted_update"):
        server.step(state, updates)
    # With the trusted row supplied, identical updates aggregate to themselves.
    new_state, agg = server.step(state, updates, trusted_update=jnp.ones((d,)))
    assert jnp.allclose(agg, 1.0, atol=1e-6)


def test_server_momentum_dampening_torch_semantics():
    import jax.numpy as jnp
    from blades_tpu.core.server import _torch_momentum

    tx = _torch_momentum(0.9, dampening=0.5)
    g = {"w": jnp.array(1.0)}
    state = tx.init(g)
    out1, state = tx.update(g, state)
    assert float(out1["w"]) == 1.0  # first step seeds buf = g
    out2, state = tx.update(g, state)
    # buf = 0.9*1 + 0.5*1 = 1.4
    assert abs(float(out2["w"]) - 1.4) < 1e-6
