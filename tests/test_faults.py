"""Failure detection + elastic recovery tests (SURVEY.md §5).

The reference's fault machinery is inherited from Ray
(FaultTolerantActorManager, Tune trial retry — ref:
fllib/core/execution/actor_manager.py:25, worker_group.py:95-127).  The
TPU-native equivalents under test here (blades_tpu/core/health.py):
lane-level detection/neutralisation inside the jitted round, round-level
aggregate guards, and checkpoint-restart trial retry in the sweep runner.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.core import FedRound, Server, TaskSpec
from blades_tpu.core.health import guard_server_state, sanitize_updates


def test_sanitize_updates_zeroes_nonfinite_lanes():
    u = jnp.array([[1.0, 2.0], [jnp.nan, 3.0], [4.0, jnp.inf], [5.0, 6.0]])
    clean, healthy = sanitize_updates(u)
    assert healthy.tolist() == [True, False, False, True]
    assert jnp.isfinite(clean).all()
    # The WHOLE unhealthy lane is zeroed — its finite entries came from
    # the same diverged run and would still poison a Mean.
    assert clean[1].tolist() == [0.0, 0.0]
    assert clean[2].tolist() == [0.0, 0.0]
    assert jnp.array_equal(clean[0], u[0]) and jnp.array_equal(clean[3], u[3])


def test_guard_server_state_keeps_params_advances_round():
    server = Server.from_config(aggregator="Mean", lr=1.0)
    task = TaskSpec(model="mlp", input_shape=(28, 28, 1)).build()
    params = task.init_params(jax.random.PRNGKey(0))
    old = server.init(params, num_clients=4)
    new, _ = server.step(old, jnp.ones((4, sum(
        p.size for p in jax.tree.leaves(params)))))
    bad = guard_server_state(jnp.array(False), new, old)
    assert int(bad.round) == 1  # the round happened
    for a, b in zip(jax.tree.leaves(bad.params), jax.tree.leaves(old.params)):
        assert jnp.array_equal(a, b)  # ...but the update was discarded
    ok = guard_server_state(jnp.array(True), new, old)
    for a, b in zip(jax.tree.leaves(ok.params), jax.tree.leaves(new.params)):
        assert jnp.array_equal(a, b)


@pytest.fixture(scope="module")
def tiny_fr():
    from blades_tpu.models import MLP

    task = TaskSpec(model=MLP(hidden1=8, hidden2=8, num_classes=4),
                    input_shape=(8, 8, 1), num_classes=4, lr=0.1).build()
    server = Server.from_config(aggregator="Mean", lr=0.5)
    fr = FedRound(task=task, server=server, batch_size=4,
                  num_batches_per_round=1, health_check=True)
    rng = np.random.default_rng(0)
    n = 6
    x = jnp.asarray(rng.normal(size=(n, 8, 8, 8, 1)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, size=(n, 8)), jnp.int32)
    ln = jnp.full((n,), 8, jnp.int32)
    state = fr.init(jax.random.PRNGKey(0), n)
    return fr, state, x, y, ln


def test_round_recovers_from_nan_client(tiny_fr):
    """A client with a corrupt (NaN) shard is detected, neutralised, and
    training continues — the lane-health analogue of marking an actor
    unhealthy and routing around it."""
    fr, state, x, y, ln = tiny_fr
    x = x.at[2].set(jnp.nan)  # client 2's data is corrupt
    mal = jnp.zeros(x.shape[0], bool)
    step = jax.jit(fr.step)
    new_state, m = step(state, x, y, ln, mal, jax.random.PRNGKey(1))
    assert int(m["num_unhealthy"]) == 1
    assert bool(m["round_ok"])
    for p in jax.tree.leaves(new_state.server.params):
        assert jnp.isfinite(p).all()
    # And the model actually moved (the 5 healthy lanes still aggregated).
    moved = any(
        not jnp.array_equal(a, b)
        for a, b in zip(jax.tree.leaves(new_state.server.params),
                        jax.tree.leaves(state.server.params))
    )
    assert moved


def test_round_guard_skips_nonfinite_aggregate(tiny_fr):
    """If the aggregate itself is non-finite (here: a post-sanitize forging
    adversary emitting inf), the server update is skipped — params survive
    unchanged, the round counter still advances."""
    from blades_tpu.adversaries import get_adversary

    fr, state, x, y, ln = tiny_fr
    n = x.shape[0]
    adv = get_adversary("IPM", num_clients=n, num_byzantine=2, scale=float("inf"))
    fr_bad = FedRound(task=fr.task, server=fr.server, adversary=adv,
                      batch_size=4, num_batches_per_round=1, health_check=True)
    mal = jnp.arange(n) < 2
    step = jax.jit(fr_bad.step)
    new_state, m = step(state, x, y, ln, mal, jax.random.PRNGKey(1))
    assert not bool(m["round_ok"])
    assert int(m["round"]) == int(state.server.round) + 1
    for a, b in zip(jax.tree.leaves(new_state.server.params),
                    jax.tree.leaves(state.server.params)):
        assert jnp.array_equal(a, b)


# ---------------------------------------------------------------------------
# Sweep-level trial fault tolerance (Tune's max_failures).
# ---------------------------------------------------------------------------


class _FlakyConfig:
    """Minimal config for a fake trainable (the reference registers mock
    trainables for exactly this, ref: blades/algorithms/registry.py:37-48)."""

    crash_state = {"remaining": 0}  # class-level: survives rebuilds

    def update_from_dict(self, d):
        self.cfg = d
        return self

    def build(self):
        return _FlakyAlgo(self.cfg)


class _FlakyAlgo:
    def __init__(self, cfg):
        self._iteration = 0
        self._last_eval = {}
        self.crash_at = cfg.get("crash_at", -1)

    @property
    def iteration(self):
        return self._iteration

    def train(self):
        self._iteration += 1
        if (self._iteration == self.crash_at
                and _FlakyConfig.crash_state["remaining"] > 0):
            _FlakyConfig.crash_state["remaining"] -= 1
            raise RuntimeError("injected fault")
        return {"training_iteration": self._iteration, "test_acc": 0.5}

    def save_checkpoint(self, d):
        import pathlib

        p = pathlib.Path(d)
        p.mkdir(parents=True, exist_ok=True)
        (p / "it.json").write_text(json.dumps({"it": self._iteration}))
        return d

    def load_checkpoint(self, path):
        import pathlib

        self._iteration = json.loads(
            (pathlib.Path(path) / "it.json").read_text())["it"]


@pytest.fixture()
def flaky_registry():
    from blades_tpu.algorithms import registry

    registry.ALGORITHMS["FLAKY"] = lambda: (_FlakyAlgo, _FlakyConfig)
    yield
    registry.ALGORITHMS.pop("FLAKY", None)


def test_sweep_retries_failed_trial_from_checkpoint(tmp_path, flaky_registry):
    from blades_tpu.tune import run_experiments

    _FlakyConfig.crash_state["remaining"] = 1  # crash once, then heal
    experiments = {"exp": {"run": "FLAKY", "stop": {"training_iteration": 8},
                           "config": {"crash_at": 5}}}
    summaries = run_experiments(
        experiments, storage_path=str(tmp_path), verbose=0,
        checkpoint_freq=2, max_failures=2,
    )
    (s,) = summaries
    assert "status" not in s  # recovered, not failed
    assert s["rounds"] == 8
    err = tmp_path / "exp" / "exp_00000" / "error.txt"
    assert err.exists() and "injected fault" in err.read_text()


def test_sweep_marks_trial_failed_and_continues(tmp_path, flaky_registry):
    from blades_tpu.tune import run_experiments

    _FlakyConfig.crash_state["remaining"] = 10  # crashes forever
    experiments = {"exp": {"run": "FLAKY", "stop": {"training_iteration": 8},
                           "config": {"crash_at": {"grid_search": [3, -1]}}}}
    summaries = run_experiments(
        experiments, storage_path=str(tmp_path), verbose=0, max_failures=1,
    )
    assert len(summaries) == 2
    assert summaries[0].get("status") == "ERROR"
    assert "injected fault" in summaries[0]["error"]
    # The second trial (crash_at=-1, never crashes) still ran to completion.
    assert "status" not in summaries[1]
    assert summaries[1]["rounds"] == 8


def test_dsharded_health_check_detects_and_recovers():
    """Cross-shard row health on the width-sharded giant-federation path:
    a NaN client lane is detected via psum over its shards, zeroed, and
    the round still updates the model (SURVEY.md §5 failure detection on
    the multi-chip production path)."""
    import dataclasses

    import jax

    from blades_tpu.adversaries import make_malicious_mask
    from blades_tpu.data import DatasetCatalog
    from blades_tpu.parallel import make_mesh
    from blades_tpu.parallel.dsharded import dsharded_step

    n = 16
    ds = DatasetCatalog.get_dataset("mnist", num_clients=n)
    task = TaskSpec(model="mlp", lr=0.1, input_shape=(28, 28, 1)).build()
    server = Server.from_config(aggregator="Mean", lr=1.0)
    fr = FedRound(task=task, server=server, batch_size=8, health_check=True)
    x = jnp.array(ds.train.x).at[5].set(jnp.nan)  # client 5's shard corrupt
    y, ln = jnp.array(ds.train.y), jnp.array(ds.train.lengths)
    mal = make_malicious_mask(n, 0)
    mesh = make_mesh()
    state = fr.init(jax.random.PRNGKey(0), n)
    step = dsharded_step(fr, mesh)
    new_state, m = step(state, x, y, ln, mal, jax.random.PRNGKey(1))
    assert int(m["num_unhealthy"]) == 1
    assert bool(m["round_ok"])
    for p in jax.tree.leaves(new_state.server.params):
        assert jnp.isfinite(p).all()
    moved = any(
        not jnp.array_equal(a, b)
        for a, b in zip(jax.tree.leaves(new_state.server.params),
                        jax.tree.leaves(state.server.params))
    )
    assert moved
