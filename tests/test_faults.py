"""Failure detection + elastic recovery tests (SURVEY.md §5).

The reference's fault machinery is inherited from Ray
(FaultTolerantActorManager, Tune trial retry — ref:
fllib/core/execution/actor_manager.py:25, worker_group.py:95-127).  The
TPU-native equivalents under test here (blades_tpu/core/health.py):
lane-level detection/neutralisation inside the jitted round, round-level
aggregate guards, and checkpoint-restart trial retry in the sweep runner.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.core import FedRound, Server, TaskSpec
from blades_tpu.core.health import guard_server_state, sanitize_updates


def test_sanitize_updates_zeroes_nonfinite_lanes():
    u = jnp.array([[1.0, 2.0], [jnp.nan, 3.0], [4.0, jnp.inf], [5.0, 6.0]])
    clean, healthy = sanitize_updates(u)
    assert healthy.tolist() == [True, False, False, True]
    assert jnp.isfinite(clean).all()
    # The WHOLE unhealthy lane is zeroed — its finite entries came from
    # the same diverged run and would still poison a Mean.
    assert clean[1].tolist() == [0.0, 0.0]
    assert clean[2].tolist() == [0.0, 0.0]
    assert jnp.array_equal(clean[0], u[0]) and jnp.array_equal(clean[3], u[3])


def test_guard_server_state_keeps_params_advances_round():
    server = Server.from_config(aggregator="Mean", lr=1.0)
    task = TaskSpec(model="mlp", input_shape=(28, 28, 1)).build()
    params = task.init_params(jax.random.PRNGKey(0))
    old = server.init(params, num_clients=4)
    new, _ = server.step(old, jnp.ones((4, sum(
        p.size for p in jax.tree.leaves(params)))))
    bad = guard_server_state(jnp.array(False), new, old)
    assert int(bad.round) == 1  # the round happened
    for a, b in zip(jax.tree.leaves(bad.params), jax.tree.leaves(old.params)):
        assert jnp.array_equal(a, b)  # ...but the update was discarded
    ok = guard_server_state(jnp.array(True), new, old)
    for a, b in zip(jax.tree.leaves(ok.params), jax.tree.leaves(new.params)):
        assert jnp.array_equal(a, b)


@pytest.fixture(scope="module")
def tiny_fr():
    from blades_tpu.models import MLP

    task = TaskSpec(model=MLP(hidden1=8, hidden2=8, num_classes=4),
                    input_shape=(8, 8, 1), num_classes=4, lr=0.1).build()
    server = Server.from_config(aggregator="Mean", lr=0.5)
    fr = FedRound(task=task, server=server, batch_size=4,
                  num_batches_per_round=1, health_check=True)
    rng = np.random.default_rng(0)
    n = 6
    x = jnp.asarray(rng.normal(size=(n, 8, 8, 8, 1)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, size=(n, 8)), jnp.int32)
    ln = jnp.full((n,), 8, jnp.int32)
    state = fr.init(jax.random.PRNGKey(0), n)
    return fr, state, x, y, ln


def test_round_recovers_from_nan_client(tiny_fr):
    """A client with a corrupt (NaN) shard is detected, neutralised, and
    training continues — the lane-health analogue of marking an actor
    unhealthy and routing around it."""
    fr, state, x, y, ln = tiny_fr
    x = x.at[2].set(jnp.nan)  # client 2's data is corrupt
    mal = jnp.zeros(x.shape[0], bool)
    step = jax.jit(fr.step)
    new_state, m = step(state, x, y, ln, mal, jax.random.PRNGKey(1))
    assert int(m["num_unhealthy"]) == 1
    assert bool(m["round_ok"])
    for p in jax.tree.leaves(new_state.server.params):
        assert jnp.isfinite(p).all()
    # And the model actually moved (the 5 healthy lanes still aggregated).
    moved = any(
        not jnp.array_equal(a, b)
        for a, b in zip(jax.tree.leaves(new_state.server.params),
                        jax.tree.leaves(state.server.params))
    )
    assert moved


def test_round_guard_skips_nonfinite_aggregate(tiny_fr):
    """If the aggregate itself is non-finite (here: a post-sanitize forging
    adversary emitting inf), the server update is skipped — params survive
    unchanged, the round counter still advances."""
    from blades_tpu.adversaries import get_adversary

    fr, state, x, y, ln = tiny_fr
    n = x.shape[0]
    adv = get_adversary("IPM", num_clients=n, num_byzantine=2, scale=float("inf"))
    fr_bad = FedRound(task=fr.task, server=fr.server, adversary=adv,
                      batch_size=4, num_batches_per_round=1, health_check=True)
    mal = jnp.arange(n) < 2
    step = jax.jit(fr_bad.step)
    new_state, m = step(state, x, y, ln, mal, jax.random.PRNGKey(1))
    assert not bool(m["round_ok"])
    assert int(m["round"]) == int(state.server.round) + 1
    for a, b in zip(jax.tree.leaves(new_state.server.params),
                    jax.tree.leaves(state.server.params)):
        assert jnp.array_equal(a, b)


# ---------------------------------------------------------------------------
# Sweep-level trial fault tolerance (Tune's max_failures).
# ---------------------------------------------------------------------------


class _FlakyConfig:
    """Minimal config for a fake trainable (the reference registers mock
    trainables for exactly this, ref: blades/algorithms/registry.py:37-48)."""

    crash_state = {"remaining": 0}  # class-level: survives rebuilds

    def update_from_dict(self, d):
        self.cfg = d
        return self

    def build(self):
        return _FlakyAlgo(self.cfg)


class _FlakyAlgo:
    def __init__(self, cfg):
        self._iteration = 0
        self._last_eval = {}
        self.crash_at = cfg.get("crash_at", -1)

    @property
    def iteration(self):
        return self._iteration

    def train(self):
        self._iteration += 1
        if (self._iteration == self.crash_at
                and _FlakyConfig.crash_state["remaining"] > 0):
            _FlakyConfig.crash_state["remaining"] -= 1
            raise RuntimeError("injected fault")
        return {"training_iteration": self._iteration, "test_acc": 0.5}

    def save_checkpoint(self, d):
        import pathlib

        p = pathlib.Path(d)
        p.mkdir(parents=True, exist_ok=True)
        (p / "it.json").write_text(json.dumps({"it": self._iteration}))
        return d

    def load_checkpoint(self, path):
        import pathlib

        self._iteration = json.loads(
            (pathlib.Path(path) / "it.json").read_text())["it"]


@pytest.fixture()
def flaky_registry():
    from blades_tpu.algorithms import registry

    registry.ALGORITHMS["FLAKY"] = lambda: (_FlakyAlgo, _FlakyConfig)
    yield
    registry.ALGORITHMS.pop("FLAKY", None)


def test_sweep_retries_failed_trial_from_checkpoint(tmp_path, flaky_registry):
    from blades_tpu.tune import run_experiments

    _FlakyConfig.crash_state["remaining"] = 1  # crash once, then heal
    experiments = {"exp": {"run": "FLAKY", "stop": {"training_iteration": 8},
                           "config": {"crash_at": 5}}}
    summaries = run_experiments(
        experiments, storage_path=str(tmp_path), verbose=0,
        checkpoint_freq=2, max_failures=2,
    )
    (s,) = summaries
    assert "status" not in s  # recovered, not failed
    assert s["rounds"] == 8
    err = tmp_path / "exp" / "exp_00000" / "error.txt"
    assert err.exists() and "injected fault" in err.read_text()


def test_sweep_marks_trial_failed_and_continues(tmp_path, flaky_registry):
    from blades_tpu.tune import run_experiments

    _FlakyConfig.crash_state["remaining"] = 10  # crashes forever
    experiments = {"exp": {"run": "FLAKY", "stop": {"training_iteration": 8},
                           "config": {"crash_at": {"grid_search": [3, -1]}}}}
    summaries = run_experiments(
        experiments, storage_path=str(tmp_path), verbose=0, max_failures=1,
    )
    assert len(summaries) == 2
    assert summaries[0].get("status") == "ERROR"
    assert "injected fault" in summaries[0]["error"]
    # The second trial (crash_at=-1, never crashes) still ran to completion.
    assert "status" not in summaries[1]
    assert summaries[1]["rounds"] == 8


@pytest.mark.slow
def test_dsharded_health_check_detects_and_recovers():
    """Cross-shard row health on the width-sharded giant-federation path:
    a NaN client lane is detected via psum over its shards, zeroed, and
    the round still updates the model (SURVEY.md §5 failure detection on
    the multi-chip production path)."""
    import dataclasses

    import jax

    from blades_tpu.adversaries import make_malicious_mask
    from blades_tpu.data import DatasetCatalog
    from blades_tpu.parallel import make_mesh
    from blades_tpu.parallel.dsharded import dsharded_step

    n = 16
    ds = DatasetCatalog.get_dataset("mnist", num_clients=n)
    task = TaskSpec(model="mlp", lr=0.1, input_shape=(28, 28, 1)).build()
    server = Server.from_config(aggregator="Mean", lr=1.0)
    fr = FedRound(task=task, server=server, batch_size=8, health_check=True)
    x = jnp.array(ds.train.x).at[5].set(jnp.nan)  # client 5's shard corrupt
    y, ln = jnp.array(ds.train.y), jnp.array(ds.train.lengths)
    mal = make_malicious_mask(n, 0)
    mesh = make_mesh()
    state = fr.init(jax.random.PRNGKey(0), n)
    step = dsharded_step(fr, mesh)
    new_state, m = step(state, x, y, ln, mal, jax.random.PRNGKey(1))
    assert int(m["num_unhealthy"]) == 1
    assert bool(m["round_ok"])
    for p in jax.tree.leaves(new_state.server.params):
        assert jnp.isfinite(p).all()
    moved = any(
        not jnp.array_equal(a, b)
        for a, b in zip(jax.tree.leaves(new_state.server.params),
                        jax.tree.leaves(state.server.params))
    )
    assert moved


# ---------------------------------------------------------------------------
# Chaos layer: deterministic fault injection (blades_tpu/faults).
# ---------------------------------------------------------------------------


def test_fault_injector_validates_config():
    from blades_tpu.faults import FaultInjector

    with pytest.raises(ValueError, match="dropout_rate"):
        FaultInjector(dropout_rate=1.0)
    with pytest.raises(ValueError, match="corrupt_mode"):
        FaultInjector(corrupt_mode="segfault")
    with pytest.raises(ValueError, match="staleness"):
        FaultInjector(staleness=0)
    with pytest.raises(ValueError, match="dropout_schedule"):
        FaultInjector(dropout_schedule=((0, 1.5),))
    # YAML hands lists; the injector normalizes to a hashable tuple.
    inj = FaultInjector(dropout_schedule=[[10, 0.5], [0, 0.1]])
    assert inj.dropout_schedule == ((0, 0.1), (10, 0.5))
    hash(inj)  # static jit config must stay hashable


def test_fault_injector_deterministic_in_seed_and_round():
    """Realizations are pure in (seed, round): same inputs replay the SAME
    failures (the retry/resume determinism contract), different rounds and
    seeds draw different ones."""
    from blades_tpu.faults import FaultInjector

    u = jnp.ones((16, 4))
    inj = FaultInjector(seed=5, dropout_rate=0.5)
    _, _, p1, _, _ = inj.inject(u, None, jnp.int32(3))
    _, _, p2, _, _ = inj.inject(u, None, jnp.int32(3))
    _, _, p3, _, _ = inj.inject(u, None, jnp.int32(4))
    _, _, p4, _, _ = FaultInjector(seed=6, dropout_rate=0.5).inject(
        u, None, jnp.int32(3))
    assert jnp.array_equal(p1, p2)
    assert not jnp.array_equal(p1, p3) or not jnp.array_equal(p1, p4)
    assert bool(p1.any())  # graceful degradation: never an empty round


def test_fault_injector_dropout_schedule():
    from blades_tpu.faults import FaultInjector

    inj = FaultInjector(dropout_rate=0.0, dropout_schedule=((5, 0.9),))
    assert float(inj.dropout_rate_at(jnp.int32(0))) == 0.0
    assert float(inj.dropout_rate_at(jnp.int32(4))) == 0.0
    assert float(inj.dropout_rate_at(jnp.int32(5))) == pytest.approx(0.9)
    assert float(inj.dropout_rate_at(jnp.int32(99))) == pytest.approx(0.9)
    u = jnp.ones((32, 4))
    _, _, early, _, _ = inj.inject(u, None, jnp.int32(0))
    _, _, late, _, _ = inj.inject(u, None, jnp.int32(50))
    assert bool(early.all())
    assert int(late.sum()) < 32


def test_fault_injector_straggler_delivers_stale_update():
    """A straggler lane delivers the update it computed `staleness` rounds
    ago, via the ring buffer threaded through RoundState."""
    from blades_tpu.faults import FaultInjector

    n, d = 4, 3
    inj = FaultInjector(seed=1, num_stragglers=1, staleness=2)
    buf = inj.init_stale_buffer(n, d)
    assert buf.shape == (2, n, d)
    rounds = [jnp.full((n, d), float(t + 1)) for t in range(4)]
    for t, fresh in enumerate(rounds):
        out, buf, part, strag, _ = inj.inject(fresh, buf, jnp.int32(t))
        assert int(strag.sum()) == 1
        assert bool((strag & part).sum() == strag.sum())  # stragglers participate
        lane = int(jnp.argmax(strag))
        if t < 2:  # buffer still cold: stragglers deliver zeros
            assert out[lane].tolist() == [0.0] * d
        else:  # delivers the (t - staleness)'th round's update
            assert out[lane].tolist() == [float(t - 1)] * d
        others = ~strag
        assert jnp.array_equal(out[others], fresh[others])


def test_fault_injector_corruption_caught_by_sanitize():
    """Lane corruption emits exactly what sanitize_updates exists to catch
    (nan/inf); 'overflow' stays finite on arrival and is the aggregate
    guard's problem instead."""
    from blades_tpu.faults import FaultInjector

    u = jnp.ones((16, 4))
    for mode, finite_on_arrival in (("nan", False), ("inf", False),
                                    ("overflow", True)):
        inj = FaultInjector(seed=2, corrupt_rate=0.5, corrupt_mode=mode)
        out, _, part, _, corr = inj.inject(u, None, jnp.int32(0))
        assert int(corr.sum()) > 0
        assert bool((corr & part).sum() == corr.sum())  # only participants
        assert bool(jnp.isfinite(out[corr]).all()) == finite_on_arrival
        clean, healthy = sanitize_updates(out, part)
        assert jnp.isfinite(clean).all()
        if not finite_on_arrival:
            assert jnp.array_equal(~healthy, corr)


def test_sanitize_updates_participation_restricts_unhealthy_count():
    """A dropped lane cannot be unhealthy — it delivered nothing — but its
    non-finite row is still zeroed (it never enters the aggregate)."""
    u = jnp.array([[1.0, 2.0], [jnp.nan, 3.0], [jnp.inf, 0.0], [5.0, 6.0]])
    part = jnp.array([True, True, False, True])
    clean, healthy = sanitize_updates(u, part)
    assert healthy.tolist() == [True, False, True, True]
    assert jnp.isfinite(clean).all()


def test_detection_metrics_conditioned_on_participation():
    """A malicious client that dropped out was neither caught nor missed:
    with participation given, it leaves the confusion matrix entirely."""
    from blades_tpu.obs.forensics import detection_metrics

    benign_mask = jnp.array([True, True, True, True])  # nothing flagged
    malicious = jnp.array([True, False, False, False])
    part = jnp.array([False, True, True, True])  # the malicious lane dropped
    dense = detection_metrics(benign_mask, malicious)
    cond = detection_metrics(benign_mask, malicious, participation=part)
    assert float(dense["byz_recall"]) == 0.0   # missed the malicious lane
    assert float(cond["byz_recall"]) == 1.0    # ...which never reported
    # And a flagged dropped lane is not a false positive either.
    flagged_dropped = jnp.array([False, True, True, True])
    cond2 = detection_metrics(flagged_dropped, malicious, participation=part)
    assert float(cond2["byz_fpr"]) == 0.0
    assert int(cond2["num_flagged"]) == 0


# ---------------------------------------------------------------------------
# Participation-aware aggregation (ops/aggregators.py masked_call).
# ---------------------------------------------------------------------------


def _mk_aggregator(name):
    from blades_tpu.ops.aggregators import AGGREGATORS

    cls = AGGREGATORS[name]
    if name in ("Trimmedmean", "Multikrum", "DnC"):
        return cls(num_byzantine=1)
    return cls()


def _with_trusted(name, updates, mask):
    """FLTrust judges against an appended trusted row that always
    'participates' (the server's own update)."""
    if name != "FLTrust":
        return updates, mask
    return (jnp.concatenate([updates, updates.mean(0, keepdims=True)]),
            jnp.concatenate([mask, jnp.ones((1,), bool)]))


@pytest.fixture(scope="module")
def faulty_round():
    """Chaos-layer fixture: a tiny-MLP federation plus a REAL update matrix
    (one local round's output) and a FedRound factory parameterized by
    aggregator + FaultInjector — shared by the property sweep and the
    end-to-end chaos tests."""
    from blades_tpu.models import MLP

    task = TaskSpec(model=MLP(hidden1=8, hidden2=8, num_classes=4),
                    input_shape=(8, 8, 1), num_classes=4, lr=0.1).build()
    rng = np.random.default_rng(7)
    n = 8
    x = jnp.asarray(rng.normal(size=(n, 8, 8, 8, 1)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, size=(n, 8)), jnp.int32)
    ln = jnp.full((n,), 8, jnp.int32)

    def make(aggregator, faults=None, **kw):
        server = Server.from_config(aggregator=aggregator, lr=0.5)
        return FedRound(task=task, server=server, batch_size=4,
                        num_clients=n, faults=faults, **kw)

    # One real update matrix for aggregator-level property tests.
    fr = make("Mean")
    state = fr.init(jax.random.PRNGKey(0), n)
    from blades_tpu.core.task import (identity_data_hook, identity_grad_hook,
                                      identity_round_begin_hook,
                                      identity_round_end_hook)
    from blades_tpu.data.sampler import sample_client_batches

    k1, k2 = jax.random.split(jax.random.PRNGKey(3))
    bx, by = sample_client_batches(k1, x, y, ln, 4, 1)
    updates, _, _ = fr.task.local_round_batched(
        state.server.params, state.client_opt, bx, by,
        jax.random.split(k2, n), jnp.zeros((n,), bool),
        identity_data_hook, identity_grad_hook,
        identity_round_begin_hook, identity_round_end_hook,
    )
    return {"task": task, "n": n, "x": x, "y": y, "ln": ln, "make": make,
            "updates": updates}


@pytest.mark.parametrize("name", sorted(
    __import__("blades_tpu.ops.aggregators",
               fromlist=["AGGREGATORS"]).AGGREGATORS))
def test_dropout_sweep_finite_and_shape_stable(faulty_round, name):
    """Property sweep (satellite): dropout in {0, 0.3, 0.7} x every
    registered aggregator — the participation-aware aggregate stays finite
    and shape-stable, diag never keeps a dropped lane, on a real tiny-MLP
    update matrix with the dropout realizations drawn by the FaultInjector
    itself.  ONE jitted program per aggregator (rates reuse it)."""
    from blades_tpu.faults import FaultInjector

    updates = faulty_round["updates"]
    n, d = updates.shape
    agg = _mk_aggregator(name)
    state = agg.init(d, n)
    key = jax.random.PRNGKey(11)

    @jax.jit
    def run(u, m):
        out, _ = agg.masked_call(u, m, state, key=key)
        _, _, diag = agg.masked_diagnose(u, m, state, key=key)
        return out, diag["benign_mask"]

    for rate in (0.0, 0.3, 0.7):
        inj = FaultInjector(seed=13, dropout_rate=rate)
        _, _, part, _, _ = inj.inject(updates, None, jnp.int32(1))
        if rate == 0.0:
            assert bool(part.all())
        u, m = _with_trusted(name, updates, part)
        out, benign = run(u, m)
        assert out.shape == (d,), (name, rate)
        assert jnp.isfinite(out).all(), (name, rate)
        # no aggregator may 'keep' a lane that never reported
        assert benign.shape == (n,), (name, rate)
        assert not bool((benign & ~part[:n]).any()), (name, rate)


@pytest.mark.parametrize("name", sorted(
    __import__("blades_tpu.ops.aggregators",
               fromlist=["AGGREGATORS"]).AGGREGATORS))
def test_full_participation_bit_identical_to_dense(faulty_round, name):
    """Regression (acceptance): with full participation the masked path
    dispatches to the EXACT dense trace — aggregates bit-identical for
    every registered aggregator — and the diag bundle matches diagnose().
    All four entry points share ONE jitted program so the comparison is
    compile-for-compile fair."""
    updates = faulty_round["updates"]
    n, d = updates.shape
    agg = _mk_aggregator(name)
    state = agg.init(d, n)
    key = jax.random.PRNGKey(5)
    u, ones = _with_trusted(name, updates, jnp.ones((n,), bool))

    @jax.jit
    def run(uu, mm):
        dense, _ = agg(uu, state, key=key)
        msk, _ = agg.masked_call(uu, mm, state, key=key)
        _, _, ddiag = agg.diagnose(uu, state, key=key)
        _, _, mdiag = agg.masked_diagnose(uu, mm, state, key=key)
        return dense, msk, ddiag, mdiag

    dense, msk, ddiag, mdiag = run(u, ones)
    assert jnp.array_equal(dense, msk), name
    assert jnp.array_equal(ddiag["benign_mask"], mdiag["benign_mask"]), name
    assert jnp.array_equal(ddiag["scores"], mdiag["scores"]), name


def test_noop_injector_round_params_bit_identical(faulty_round):
    """faults=None and an all-disabled FaultInjector produce bit-identical
    round outputs: the full-participation mask takes the dense aggregation
    trace via lax.cond."""
    from blades_tpu.faults import FaultInjector

    fx = faulty_round
    mal = jnp.zeros((fx["n"],), bool)
    fr0 = fx["make"]("Mean")
    fr1 = fx["make"]("Mean", faults=FaultInjector(seed=0))
    s0 = fr0.init(jax.random.PRNGKey(0), fx["n"])
    s1 = fr1.init(jax.random.PRNGKey(0), fx["n"])
    s0, m0 = jax.jit(fr0.step)(s0, fx["x"], fx["y"], fx["ln"], mal,
                               jax.random.PRNGKey(1))
    s1, m1 = jax.jit(fr1.step)(s1, fx["x"], fx["y"], fx["ln"], mal,
                               jax.random.PRNGKey(1))
    for a, b in zip(jax.tree.leaves(s0.server.params),
                    jax.tree.leaves(s1.server.params)):
        assert jnp.array_equal(a, b)
    assert int(m1["num_participating"]) == fx["n"]
    assert int(m1["num_dropped"]) == 0
    assert float(m0["train_loss"]) == float(m1["train_loss"])


@pytest.mark.parametrize("aggregator", [
    "Mean",
    {"type": "Trimmedmean", "num_byzantine": 1},
    {"type": "Multikrum", "num_byzantine": 1},
])
def test_chaos_run_20_rounds_stays_finite(faulty_round, aggregator):
    """Acceptance: 30% Bernoulli dropout + 1 straggler with staleness 2,
    20 rounds on the tiny MLP — finite params, num_participating logged
    per round, detection metrics conditioned on participation."""
    import functools

    from blades_tpu.faults import FaultInjector

    fx = faulty_round
    n = fx["n"]
    inj = FaultInjector(seed=21, dropout_rate=0.3, num_stragglers=1,
                        staleness=2)
    fr = fx["make"](aggregator, faults=inj, forensics=True)
    mal = jnp.arange(n) < 1
    state = fr.init(jax.random.PRNGKey(0), n)
    assert state.stale.shape == (2, n, state.stale.shape[-1])
    step = jax.jit(functools.partial(fr.multi_step, num_rounds=20))
    state, m = step(state, fx["x"], fx["y"], fx["ln"], mal,
                    jax.random.PRNGKey(2))
    for p in jax.tree.leaves(state.server.params):
        assert jnp.isfinite(p).all()
    part = m["num_participating"]
    assert part.shape == (20,)
    assert bool((part >= 1).all()) and bool((part <= n).all())
    assert bool((part < n).any())  # dropout actually fired
    assert bool((m["num_straggled"] == 1).all())
    assert m["num_dropped"].tolist() == (n - part).tolist()
    # Detection metrics present and valid (conditioned on participation).
    for k in ("byz_precision", "byz_recall", "byz_fpr"):
        assert jnp.isfinite(m[k]).all()
        assert bool((m[k] >= 0).all()) and bool((m[k] <= 1).all())
    # Fault realizations are seed-driven: identical across aggregators.
    assert part.tolist() == faulty_round.setdefault(
        "_part_trace", part.tolist())


# ---------------------------------------------------------------------------
# Host layer: atomic checkpoints, retry backoff, preemption simulation.
# ---------------------------------------------------------------------------


def test_atomic_checkpoint_publishes_or_leaves_orphan_tmp(tmp_path):
    from blades_tpu.faults.host import atomic_checkpoint
    from blades_tpu.tune.sweep import _latest_checkpoint

    def good_save(d):
        import pathlib

        p = pathlib.Path(d)
        p.mkdir(parents=True)
        (p / "it.json").write_text('{"it": 4}')

    atomic_checkpoint(good_save, tmp_path / "ckpt_000004")
    assert (tmp_path / "ckpt_000004" / "it.json").exists()
    assert not (tmp_path / "ckpt_000004.tmp").exists()

    def killed_mid_write(d):
        import pathlib

        p = pathlib.Path(d)
        p.mkdir(parents=True)
        (p / "it.json").write_text('{"it":')  # torn payload
        raise KeyboardInterrupt("SIGKILL stand-in")

    with pytest.raises(KeyboardInterrupt):
        atomic_checkpoint(killed_mid_write, tmp_path / "ckpt_000006")
    # The kill left an orphaned .tmp, never a torn ckpt_000006 ...
    assert (tmp_path / "ckpt_000006.tmp").exists()
    assert not (tmp_path / "ckpt_000006").exists()
    # ... and restore skips AND deletes the orphan.
    latest = _latest_checkpoint(tmp_path)
    assert latest is not None and latest.name == "ckpt_000004"
    assert not (tmp_path / "ckpt_000006.tmp").exists()


def test_atomic_checkpoint_rewrites_same_round(tmp_path):
    """Re-checkpointing a round after a resume replaces the old dir."""
    from blades_tpu.faults.host import atomic_checkpoint

    def save(tag):
        def _s(d):
            import pathlib

            p = pathlib.Path(d)
            p.mkdir(parents=True)
            (p / "v.txt").write_text(tag)
        return _s

    atomic_checkpoint(save("old"), tmp_path / "ckpt_000002")
    atomic_checkpoint(save("new"), tmp_path / "ckpt_000002")
    assert (tmp_path / "ckpt_000002" / "v.txt").read_text() == "new"


def test_retry_backoff_deterministic_exponential_capped():
    from blades_tpu.faults.host import retry_backoff

    a = [retry_backoff(i, "trial:0", base=0.5, cap=30.0) for i in (1, 2, 3, 9)]
    b = [retry_backoff(i, "trial:0", base=0.5, cap=30.0) for i in (1, 2, 3, 9)]
    assert a == b  # deterministic jitter (reproducible retry timeline)
    assert a[0] < a[1] < a[2]  # exponential growth
    # jitter in [0.5, 1.5) around min(cap, base * 2^(n-1))
    assert 0.25 <= a[0] < 0.75
    assert 15.0 <= a[3] < 45.0  # capped at 30s before jitter
    # distinct trials de-synchronize
    assert retry_backoff(1, "trial:1") != retry_backoff(1, "trial:0")
    with pytest.raises(ValueError):
        retry_backoff(0, "trial:0")


def test_sweep_retries_back_off_between_restarts(tmp_path, flaky_registry,
                                                 monkeypatch):
    from blades_tpu.tune import run_experiments

    sleeps = []
    monkeypatch.setattr("time.sleep", lambda s: sleeps.append(s))
    _FlakyConfig.crash_state["remaining"] = 2
    experiments = {"exp": {"run": "FLAKY", "stop": {"training_iteration": 6},
                           "config": {"crash_at": 3}}}
    summaries = run_experiments(
        experiments, storage_path=str(tmp_path), verbose=0,
        checkpoint_freq=2, max_failures=3,
        retry_backoff_base=0.25, retry_backoff_cap=8.0,
    )
    (s,) = summaries
    assert "status" not in s and s["rounds"] == 6
    assert len(sleeps) == 2  # one backoff per restart
    assert sleeps[1] > sleeps[0]  # exponential


def test_preempt_after_kill_and_resume_in_process(tmp_path, flaky_registry):
    """Acceptance: a SimulatedPreemption landing between the result write
    and the checkpoint save is retried from the latest checkpoint with no
    duplicated or skipped rounds in result.json."""
    from blades_tpu.faults.host import SimulatedPreemption  # noqa: F401
    from blades_tpu.tune import run_experiments
    from blades_tpu.tune.sweep import verify_result_rounds

    _FlakyConfig.crash_state["remaining"] = 0  # never self-crashes
    experiments = {"exp": {"run": "FLAKY", "stop": {"training_iteration": 8},
                           "config": {"crash_at": -1}}}
    summaries = run_experiments(
        experiments, storage_path=str(tmp_path), verbose=0,
        checkpoint_freq=2, max_failures=1, preempt_after=5,
        retry_backoff_base=0.0,
    )
    (s,) = summaries
    assert "status" not in s and s["rounds"] == 8
    tdir = tmp_path / "exp" / "exp_00000"
    assert "SimulatedPreemption" in (tdir / "error.txt").read_text()
    # No-duplicate/no-gap round sequence despite the mid-trial kill.
    assert verify_result_rounds(tdir / "result.json") == list(range(1, 9))
    # metrics stream was truncated + re-entered consistently too.
    its = [json.loads(l)["training_iteration"]
           for l in (tdir / "metrics.jsonl").read_text().splitlines()]
    assert its == list(range(1, 9))


def test_preempt_after_resume_in_second_sweep(tmp_path, flaky_registry):
    """Kill-and-resume across sweep invocations: the preempted trial is
    marked failed (max_failures=0), then a --resume sweep restores from
    its latest checkpoint and completes the sequence exactly."""
    from blades_tpu.tune import run_experiments
    from blades_tpu.tune.sweep import verify_result_rounds

    _FlakyConfig.crash_state["remaining"] = 0
    experiments = {"exp": {"run": "FLAKY", "stop": {"training_iteration": 8},
                           "config": {"crash_at": -1}}}
    first = run_experiments(
        experiments, storage_path=str(tmp_path), verbose=0,
        checkpoint_freq=2, preempt_after=5,
    )
    assert first[0].get("status") == "ERROR"
    second = run_experiments(
        experiments, storage_path=str(tmp_path), verbose=0,
        checkpoint_freq=2, resume=True,
    )
    (s,) = second
    assert "status" not in s and s["rounds"] == 8
    assert s.get("resumed") == "from round 4"  # ckpt_000004, not round 5
    tdir = tmp_path / "exp" / "exp_00000"
    assert verify_result_rounds(tdir / "result.json") == list(range(1, 9))


def test_verify_result_rounds_rejects_duplicates_and_gaps(tmp_path):
    from blades_tpu.tune.sweep import verify_result_rounds

    p = tmp_path / "result.json"
    p.write_text("".join(json.dumps({"training_iteration": i}) + "\n"
                         for i in (1, 2, 2, 3)))
    with pytest.raises(ValueError, match="duplicates or gaps"):
        verify_result_rounds(p)
    p.write_text("".join(json.dumps({"training_iteration": i}) + "\n"
                         for i in (1, 2, 4)))
    with pytest.raises(ValueError, match="duplicates or gaps"):
        verify_result_rounds(p)
    p.write_text("".join(json.dumps({"training_iteration": i}) + "\n"
                         for i in (2, 4, 6)))  # rounds_per_dispatch stride
    assert verify_result_rounds(p) == [2, 4, 6]


# ---------------------------------------------------------------------------
# Obs schema: chaos-run metrics are first-class records.
# ---------------------------------------------------------------------------


def test_schema_accepts_fault_event_fields(tmp_path):
    from blades_tpu.obs.schema import validate_jsonl, validate_record

    rec = {
        "experiment": "chaos", "trial": "chaos_00000",
        "training_iteration": 3, "train_loss": 1.2, "agg_norm": 0.4,
        "update_norm_mean": 0.6, "num_participating": 6, "num_dropped": 2,
        "num_straggled": 1, "fault_seed": 21, "byz_precision": 1.0,
        "byz_recall": 0.5, "byz_fpr": 0.0, "num_flagged": 1,
    }
    assert validate_record(rec) is rec
    p = tmp_path / "metrics.jsonl"
    p.write_text(json.dumps(rec) + "\n")
    num_valid, errors = validate_jsonl(p)
    assert num_valid == 1 and not errors


def test_chaos_trial_streams_schema_valid_metrics(tmp_path):
    """End-to-end: a fault-injected FEDAVG trial through the sweep runner
    emits a metrics.jsonl the validator CLI accepts, with participation
    logged per round."""
    from blades_tpu.obs.schema import main as schema_main
    from blades_tpu.tune import run_experiments
    from blades_tpu.tune.sweep import verify_result_rounds

    experiments = {"chaos": {
        "run": "FEDAVG", "stop": {"training_iteration": 3},
        "config": {
            "dataset_config": {"type": "mnist", "num_clients": 6},
            "global_model": "mlp", "train_batch_size": 8,
            "evaluation_interval": 3,
            "fault_config": {"dropout_rate": 0.3, "num_stragglers": 1,
                             "staleness": 2, "seed": 5},
        },
    }}
    summaries = run_experiments(experiments, storage_path=str(tmp_path),
                                verbose=0, cost_analysis=False)
    (s,) = summaries
    assert "status" not in s
    tdir = tmp_path / "chaos" / "chaos_00000"
    assert schema_main([str(tdir / "metrics.jsonl")]) == 0
    rows = [json.loads(l)
            for l in (tdir / "metrics.jsonl").read_text().splitlines()]
    assert len(rows) == 3
    for r in rows:
        assert 1 <= r["num_participating"] <= 6
        assert r["num_participating"] + r["num_dropped"] == 6
        assert r["fault_seed"] == 5
    assert verify_result_rounds(tdir / "result.json") == [1, 2, 3]


# Dropout x Byzantine x lanes composition (~6 s); dropout imputation and
# Byzantine robustness are each pinned tier-1 separately in this file
# (PR 20 budget rebalance).
@pytest.mark.slow
def test_robustness_survives_dropout_with_byzantine_lanes():
    """Graceful degradation must not break Byzantine robustness: with 2
    poison lanes (100x) present and 20% of the benign cohort dropped,
    every robust aggregator stays at the benign scale.  Guards the
    imputation strategy — imputing dropped rows with the active-lane MEAN
    (corruptible) minted copies of the poison and captured GeoMed; the
    masked-median imputation keeps imputed rows in the benign cluster."""
    from blades_tpu.ops import get_aggregator

    key = jax.random.PRNGKey(0)
    d, nb, nm = 64, 8, 2
    benign = jax.random.normal(key, (nb, d)) * 0.1
    updates = jnp.concatenate([100.0 * jnp.ones((nm, d)), benign])
    mask = jnp.concatenate([jnp.ones((nm,), bool),  # poison lanes present
                            jax.random.uniform(key, (nb,)) > 0.3])
    assert int(mask.sum()) < nb + nm
    for name in ("Median", "Trimmedmean", "GeoMed", "Multikrum", "DnC",
                 "Signguard", "Clippedclustering", "Centeredclipping"):
        agg = get_aggregator(name, num_byzantine=nm)
        out, _ = agg.masked_call(updates, mask, agg.init(d, nb + nm), key=key)
        assert float(jnp.abs(out).max()) < 1.0, name
    # ... and the non-robust baseline still collapses (the test has teeth).
    mean = get_aggregator("Mean")
    out, _ = mean.masked_call(updates, mask, (), key=key)
    assert float(jnp.abs(out).max()) > 10.0
