"""Client callback chain (ref: fllib/clients/callbacks.py) + the benign
clipping callback (ref: blades/clients/callbacks.py:10-15)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.core import FedRound, Server, TaskSpec
from blades_tpu.core.callbacks import (
    CallbackChain,
    ClientCallback,
    ClippingCallback,
    get_callback,
)


def test_clipping_callback_scales_global_norm():
    cb = ClippingCallback(clip_threshold=1.0)
    grads = {"a": jnp.full((3,), 3.0), "b": jnp.full((4,), 4.0)}
    out = cb.on_backward_end(grads, jnp.array(False))
    total = jnp.sqrt(sum(jnp.sum(g**2) for g in jax.tree.leaves(out)))
    np.testing.assert_allclose(float(total), 1.0, rtol=1e-5)
    # Direction preserved.
    ratio = out["b"][0] / out["a"][0]
    np.testing.assert_allclose(float(ratio), 4.0 / 3.0, rtol=1e-5)
    # Under the threshold: untouched.
    small = {"a": jnp.full((3,), 0.01)}
    out2 = cb.on_backward_end(small, jnp.array(False))
    np.testing.assert_array_equal(np.asarray(out2["a"]), np.asarray(small["a"]))


def test_chain_folds_in_order():
    calls = []

    @dataclasses.dataclass(frozen=True)
    class Tag(ClientCallback):
        tag: str = ""

        def on_batch_begin(self, x, y, malicious):
            calls.append(self.tag)
            return x + 1.0, y

    chain = CallbackChain((Tag("a"), Tag("b")))
    x, y = chain.on_batch_begin(jnp.zeros(2), jnp.zeros(2), jnp.array(False))
    assert calls == ["a", "b"]
    assert float(x[0]) == 2.0


def test_get_callback_resolution():
    cb = get_callback({"type": "Clipping", "clip_threshold": 5.0})
    assert isinstance(cb, ClippingCallback) and cb.clip_threshold == 5.0
    assert get_callback(cb) is cb


def test_round_end_hook_edits_update():
    """on_round_end sees the flat pseudo-gradient, like the reference's
    on_train_round_end sees pseudo_grad_vec."""

    @dataclasses.dataclass(frozen=True)
    class ZeroUpdate(ClientCallback):
        def on_round_end(self, update, malicious):
            del malicious
            return jnp.zeros_like(update)

    task = TaskSpec(model="mlp", input_shape=(8, 8, 1)).build()
    fr = FedRound(task=task, server=Server.from_config(lr=1.0), batch_size=4,
                  client_callbacks=(ZeroUpdate(),))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 8, 8, 8, 1)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=(4, 8)), jnp.int32)
    ln = jnp.full((4,), 8, jnp.int32)
    mal = jnp.zeros((4,), bool)
    st = fr.init(jax.random.PRNGKey(0), 4)
    st2, m = jax.jit(fr.step)(st, x, y, ln, mal, jax.random.PRNGKey(1))
    assert float(m["update_norm_mean"]) == 0.0  # every update zeroed


@pytest.mark.slow  # full sweep from YAML (~9 s; the callback chain itself stays tier-1)
def test_clipping_from_yaml_config(tmp_path):
    """The reference's local20 envelope: clipping configurable from YAML
    (client_config.callbacks), and it measurably bounds update norms."""
    import yaml

    from blades_tpu.tune import load_experiments_from_file, run_experiments

    def run_with(callbacks):
        spec = {
            "clip_check": {
                "run": "FEDAVG",
                "stop": {"training_iteration": 2},
                "config": {
                    "dataset_config": {"type": "mnist", "num_clients": 4,
                                       "train_bs": 8},
                    "global_model": "mlp",
                    "client_config": {"lr": 50.0, "num_batch_per_round": 3,
                                      **callbacks},
                    "evaluation_interval": 0,
                },
            }
        }
        f = tmp_path / "exp.yaml"
        f.write_text(yaml.safe_dump(spec))
        experiments = load_experiments_from_file(str(f))
        [s] = run_experiments(experiments, storage_path=str(tmp_path / "out"),
                              verbose=0)
        import json
        from pathlib import Path

        lines = (Path(s["dir"]) / "result.json").read_text().splitlines()
        return [json.loads(ln)["update_norm_mean"] for ln in lines]

    clipped = run_with(
        {"callbacks": [{"type": "Clipping", "clip_threshold": 1e-4}]})
    free = run_with({})
    # lr=50 makes unclipped updates explode (the free run diverges after
    # round 1); tight grad clipping bounds each SGD step to
    # lr * threshold.  Compare round 1, before the divergence.
    assert max(clipped) <= 50.0 * 1e-4 * 3 + 1e-6
    assert clipped[0] < free[0] / 100
