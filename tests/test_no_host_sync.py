"""Hot-path host-sync lint (perf layer, tier-1).

The round pipeline's throughput rests on the jitted-round modules never
blocking the dispatch queue: every ``device_get`` / ``np.asarray`` /
``block_until_ready`` inside them is a host↔device round trip that
through a remote-execution relay costs more than the round itself, and
such stalls creep back in silently (a debug fetch left behind, a
"harmless" numpy conversion).  This lint greps the DEVICE-SIDE modules —
the ones whose code runs inside (or builds) the jitted round — for
host-sync calls.  The sanctioned flush points all live in HOST modules
(``algorithms/fedavg.py`` finalize/flush, ``tune/sweep.py``'s batched
emit, ``perf/async_metrics.py``), which are deliberately not scanned.

A device-side line that must sync (e.g. the streamed path's
once-per-mask-object promise validation) carries an explicit
``# host-sync: ok — <why>`` pragma; anything else fails here.
"""

import re
from pathlib import Path

ROOT = Path(__file__).parent.parent / "blades_tpu"

# Modules whose code runs inside (or traces into) the jitted round.
DEVICE_SIDE = [
    "core/round.py",
    "core/server.py",
    "core/task.py",
    "core/health.py",
    "core/callbacks.py",
    "data/sampler.py",
    "data/augment.py",
    "adversaries/base.py",
    "adversaries/update_attacks.py",
    "adversaries/training_attacks.py",
    "faults/injector.py",
    "comm/codecs.py",
    "ops/aggregators.py",
    "ops/clustering.py",
    "ops/layout.py",
    "ops/masked.py",
    "ops/pallas_round.py",
    "ops/pallas_select.py",
    "parallel/streamed.py",
    "parallel/streamed_geometry.py",
    "parallel/sharded.py",
    "parallel/dsharded.py",
]

# Host-sync calls that stall the dispatch pipeline.  The numpy patterns
# use a lookbehind so jnp.asarray/jnp.array (device ops) don't match.
HOST_SYNC = re.compile(
    r"jax\.device_get\("
    r"|\.block_until_ready\("
    r"|jax\.block_until_ready\("
    r"|(?<![\w.])np\.asarray\("
    r"|(?<![\w.])np\.array\("
)
PRAGMA = "# host-sync: ok"


def test_device_side_modules_have_no_host_sync():
    offenders = []
    for rel in DEVICE_SIDE:
        path = ROOT / rel
        assert path.exists(), f"lint list is stale: {path} is gone"
        for lineno, line in enumerate(path.read_text().splitlines(), 1):
            stripped = line.strip()
            if stripped.startswith("#"):
                continue
            if HOST_SYNC.search(line) and PRAGMA not in line:
                offenders.append(f"blades_tpu/{rel}:{lineno}: {stripped}")
    assert not offenders, (
        "host-sync call(s) in jitted-round modules (each one stalls the "
        "dispatch pipeline every round; move the fetch to a sanctioned "
        "flush point — fedavg finalize_row / sweep batched emit — or, if "
        "it is genuinely setup-time/once-per-object, mark the line with "
        "'# host-sync: ok — <why>'):\n  " + "\n  ".join(offenders)
    )


def test_pragmas_carry_a_reason():
    """A bare pragma defeats the lint's audit trail — require the why."""
    bad = []
    for rel in DEVICE_SIDE:
        for lineno, line in enumerate((ROOT / rel).read_text().splitlines(), 1):
            if PRAGMA in line:
                tail = line.split(PRAGMA, 1)[1].strip(" -—")
                if len(tail) < 8:
                    bad.append(f"blades_tpu/{rel}:{lineno}")
    assert not bad, f"host-sync pragmas without a reason: {bad}"
