"""FedSGD merged-batch fast path vs vmapped local_round equivalence.

The fast path (core/fedsgd.py) must reproduce the vmapped per-client
round — same updates, losses, and opt states — up to floating-point
reduction order (the grouped program sums in a different association).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _enable_fast_path(monkeypatch):
    """The fast path is opt-in; enable it for this module only (the flag
    is read per call, so monkeypatch scoping is enough)."""
    monkeypatch.setenv("BLADES_TPU_FEDSGD", "1")

from blades_tpu.core.fedsgd import supports_fedsgd
from blades_tpu.core.task import (
    TaskSpec,
    identity_data_hook,
    identity_grad_hook,
    identity_round_begin_hook,
    identity_round_end_hook,
)

G, B = 4, 4


def _mk(task, key=0, nb=1):
    params = task.init_params(jax.random.PRNGKey(key))
    opt0 = task.init_client_opt_state(params)
    opts = jax.tree.map(lambda a: jnp.broadcast_to(a, (G,) + a.shape), opt0)
    rng = np.random.default_rng(key)
    bx = jnp.asarray(rng.normal(size=(G, nb, B, 32, 32, 3)), jnp.float32)
    by = jnp.asarray(rng.integers(0, 10, size=(G, nb, B)), jnp.int32)
    keys = jax.random.split(jax.random.PRNGKey(key + 1), G)
    return params, opts, bx, by, keys


def _vmapped(task, params, opts, bx, by, keys, mal, hooks=None):
    h = hooks or (identity_data_hook, identity_grad_hook,
                  identity_round_begin_hook, identity_round_end_hook)

    def one(o, cbx, cby, k, m):
        return task.local_round(params, o, cbx, cby, k, m, *h)

    return jax.vmap(one)(opts, bx, by, keys, mal)


def _fast(task, params, opts, bx, by, keys, mal, hooks=None):
    h = hooks or (identity_data_hook, identity_grad_hook,
                  identity_round_begin_hook, identity_round_end_hook)
    assert supports_fedsgd(task, bx.shape[1], h[2]), "fast path not taken"
    return task.local_round_batched(params, opts, bx, by, keys, mal, *h)


def _check(task, mal=None, hooks=None, atol=2e-5):
    params, opts, bx, by, keys = _mk(task)
    if mal is None:
        mal = jnp.zeros((G,), bool)
    u_ref, o_ref, l_ref = jax.jit(
        lambda *a: _vmapped(task, *a, hooks=hooks)
    )(params, opts, bx, by, keys, mal)
    u_fast, o_fast, l_fast = jax.jit(
        lambda *a: _fast(task, *a, hooks=hooks)
    )(params, opts, bx, by, keys, mal)
    np.testing.assert_allclose(np.asarray(l_fast), np.asarray(l_ref),
                               atol=atol, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(u_fast), np.asarray(u_ref),
                               atol=atol, rtol=1e-3)
    for a, b in zip(jax.tree.leaves(o_fast), jax.tree.leaves(o_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=atol, rtol=1e-3)


def test_resnet_plain():
    task = TaskSpec(model="resnet10", input_shape=(32, 32, 3),
                    num_classes=10, lr=0.1).build()
    _check(task)


def test_resnet_momentum_and_augment():
    task = TaskSpec(model="resnet10", input_shape=(32, 32, 3),
                    num_classes=10, lr=0.1, momentum=0.9,
                    augment="cifar").build()
    _check(task)


def test_resnet_hooks_and_malicious():
    from blades_tpu.adversaries.training_attacks import (
        LabelFlipAdversary,
        SignFlipAdversary,
    )

    task = TaskSpec(model="resnet10", input_shape=(32, 32, 3),
                    num_classes=10, lr=0.1).build()
    lf = LabelFlipAdversary(num_classes=10)
    sf = SignFlipAdversary()
    mal = jnp.array([True, True, False, False])

    hooks = (lf.data_hook, sf.grad_hook,
             identity_round_begin_hook, identity_round_end_hook)
    _check(task, mal=mal, hooks=hooks)


def test_round_end_hook_applies():
    task = TaskSpec(model="resnet10", input_shape=(32, 32, 3),
                    num_classes=10, lr=0.1).build()

    def double_end(update, malicious):
        return jnp.where(malicious, 2.0 * update, update)

    mal = jnp.array([True, False, False, False])
    hooks = (identity_data_hook, identity_grad_hook,
             identity_round_begin_hook, double_end)
    _check(task, mal=mal, hooks=hooks)


def test_fallbacks():
    # dropout model (MLP) is not grouped_safe
    mlp = TaskSpec(model="mlp", input_shape=(28, 28, 1), num_classes=10).build()
    assert not supports_fedsgd(mlp, 1, identity_round_begin_hook)
    # multi-batch rounds fall back
    rn = TaskSpec(model="resnet10", input_shape=(32, 32, 3)).build()
    assert not supports_fedsgd(rn, 2, identity_round_begin_hook)
    # opt-in switch: off unless the env flag is exactly "1"
    os.environ["BLADES_TPU_FEDSGD"] = "0"  # monkeypatched; auto-restored
    assert not supports_fedsgd(rn, 1, identity_round_begin_hook)
    os.environ["BLADES_TPU_FEDSGD"] = "1"
    # non-identity round-begin hook falls back
    assert not supports_fedsgd(rn, 1, lambda p, o, m: (p, o))


def test_multibatch_fallback_matches_vmap():
    """nb=2 routes through vmap(local_round) — identical by construction."""
    task = TaskSpec(model="resnet10", input_shape=(32, 32, 3), lr=0.1).build()
    params, opts, _, _, keys = _mk(task)
    rng = np.random.default_rng(7)
    bx = jnp.asarray(rng.normal(size=(G, 2, B, 32, 32, 3)), jnp.float32)
    by = jnp.asarray(rng.integers(0, 10, size=(G, 2, B)), jnp.int32)
    mal = jnp.zeros((G,), bool)
    u_ref, _, _ = _vmapped(task, params, opts, bx, by, keys, mal)
    u_b, _, _ = task.local_round_batched(params, opts, bx, by, keys, mal)
    np.testing.assert_array_equal(np.asarray(u_ref), np.asarray(u_b))


def test_bf16_fast_path_close():
    task = TaskSpec(model="resnet10", input_shape=(32, 32, 3), lr=0.1,
                    compute_dtype="bfloat16").build()
    _check(task, atol=5e-3)
