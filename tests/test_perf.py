"""Round-pipeline perf layer tests (blades_tpu/perf + data/prefetch):

- compile-count regression: N identically-shaped sweep trials lower and
  compile the round program exactly once (the AOT executable cache);
- donation: the pre-step RoundState's buffers are invalidated after a
  donated dispatch (and stay alive with ``donate_buffers=False``);
- bit-identity: prefetch on/off, deferred metric fetches, and the
  sweep's chained scan windows all reproduce the eager path exactly,
  per aggregator.
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.algorithms import FedavgConfig
from blades_tpu.ops.aggregators import AGGREGATORS
from blades_tpu.perf import cache_stats, clear_cache, fingerprint
from blades_tpu.tune import run_experiments


def tiny_config(**overrides):
    cfg = (
        FedavgConfig()
        .data(dataset="mnist", num_clients=6, seed=3)
        .training(global_model="mlp", server_lr=1.0, train_batch_size=8,
                  aggregator={"type": "Mean"})
        .client(lr=0.1)
        .evaluation(evaluation_interval=0)
    )
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


def _params(algo):
    return [np.asarray(p) for p in jax.tree.leaves(algo.state.server.params)]


# ---------------------------------------------------------------------------
# AOT compile cache
# ---------------------------------------------------------------------------


def _seed_sweep(tmp_path, seeds, **kw):
    experiments = {
        "cc": {
            "run": "FEDAVG",
            "stop": {"training_iteration": 4},
            "config": {
                "dataset_config": {"type": "mnist", "num_clients": 6,
                                   "train_bs": 8,
                                   "seed": {"grid_search": list(seeds)}},
                "global_model": "mlp",
                "evaluation_interval": 2,
                "server_config": {"lr": 1.0},
            },
        }
    }
    return run_experiments(experiments, storage_path=str(tmp_path),
                           verbose=0, lanes=False, **kw)


# Three full sweep trials through run_experiments (~11 s of XLA CPU
# compile); the cache-counter contract itself is asserted by the cheaper
# prefetch/driver tests below (PR 20 budget rebalance, same rule as PR 7).
@pytest.mark.slow
def test_identically_shaped_trials_compile_once(tmp_path):
    """The acceptance criterion: a sweep of >= 3 identically-shaped
    trials compiles the round program exactly once; the other trials
    are cache hits, surfaced both in the summaries and in the metrics
    stream."""
    clear_cache()
    summaries = _seed_sweep(tmp_path, seeds=(1, 2, 3))
    stats = cache_stats()
    assert stats["by_role"]["step"]["misses"] == 1, stats
    assert stats["by_role"]["step"]["hits"] >= 2, stats
    # Per-trial summary deltas: first trial owns every miss.
    assert summaries[0]["compile_cache"]["misses"] >= 1
    for s in summaries[1:]:
        assert s["compile_cache"]["misses"] == 0, s
        assert s["compile_cache"]["hits"] >= 1, s
    # The obs stream carries the counters (schema-registered fields).
    first = json.loads(
        (Path(summaries[1]["dir"]) / "metrics.jsonl").read_text()
        .splitlines()[0])
    assert first["compile_cache_misses"] == 0
    assert first["compile_cache_hits"] >= 1


@pytest.mark.slow
def test_shape_change_recompiles(tmp_path):
    """Different geometry must NOT share an executable."""
    clear_cache()
    _seed_sweep(tmp_path / "a", seeds=(1,))
    misses_6 = cache_stats()["by_role"]["step"]["misses"]
    experiments = {
        "cc8": {
            "run": "FEDAVG",
            "stop": {"training_iteration": 2},
            "config": {
                "dataset_config": {"type": "mnist", "num_clients": 8,
                                   "train_bs": 8},
                "global_model": "mlp",
                "evaluation_interval": 2,
                "server_config": {"lr": 1.0},
            },
        }
    }
    run_experiments(experiments, storage_path=str(tmp_path / "b"),
                    verbose=0, lanes=False)
    assert cache_stats()["by_role"]["step"]["misses"] == misses_6 + 1


def test_fingerprint_stability():
    assert fingerprint({"a": 1, "b": [2, 3]}) == fingerprint({"b": [2, 3], "a": 1})
    assert fingerprint({"a": 1}) != fingerprint({"a": 2})


def test_fingerprint_excludes_seed_only():
    """Two configs differing only in seed share a program fingerprint;
    differing in a baked-in static (server lr) must not."""
    a = tiny_config().build()
    b = tiny_config(seed=99).build()
    c = tiny_config(server_lr=0.5).build()
    assert a._program_fingerprint() == b._program_fingerprint()
    assert a._program_fingerprint() != c._program_fingerprint()


# ---------------------------------------------------------------------------
# buffer donation
# ---------------------------------------------------------------------------


def test_donated_step_invalidates_pre_step_state():
    algo = tiny_config().build()
    leaves = jax.tree.leaves(algo.state.server.params)
    algo.train()
    assert all(l.is_deleted() for l in leaves), (
        "RoundState was not donated into the round dispatch"
    )
    # The CURRENT state is alive and usable (next round, checkpoints).
    assert all(not l.is_deleted()
               for l in jax.tree.leaves(algo.state.server.params))


def test_donation_opt_out_keeps_state_alive():
    algo = tiny_config(donate_buffers=False).build()
    leaves = jax.tree.leaves(algo.state.server.params)
    algo.train()
    assert all(not l.is_deleted() for l in leaves)


# ---------------------------------------------------------------------------
# prefetch
# ---------------------------------------------------------------------------


def test_batch_prefetcher_contract():
    from blades_tpu.data.prefetch import BatchPrefetcher

    calls = []

    def sample(key):
        calls.append(int(key))
        return ("batch", int(key))

    pf = BatchPrefetcher(sample)
    assert pf.take(0, 7) == ("batch", 7)        # cold: sync draw
    pf.stage(1, 8)
    assert pf.take(1, 8) == ("batch", 8)        # warm: staged, no redraw
    assert calls == [7, 8]
    pf.stage(2, 9)
    assert pf.take(5, 11) == ("batch", 11)      # index mismatch: redraw
    pf.stage(6, 12)
    pf.invalidate()
    assert pf.take(6, 12) == ("batch", 12)      # invalidated: redraw
    assert calls == [7, 8, 9, 11, 12, 12]


def test_prefetch_to_device_order_and_values():
    from blades_tpu.data.prefetch import prefetch_to_device

    items = [np.full((3,), i, np.float32) for i in range(5)]
    out = list(prefetch_to_device(iter(items), size=2))
    assert len(out) == 5
    for i, a in enumerate(out):
        assert isinstance(a, jax.Array)
        np.testing.assert_array_equal(np.asarray(a), items[i])


def test_prefetch_bit_identity_fedavg_driver():
    """The full driver surface: 5 Fedavg rounds with prefetch forced on
    (staged batches + prebatched program + donation + AOT cache) vs
    prefetch off — rows and params bit-equal."""
    def build(prefetch):
        cfg = tiny_config(prefetch=prefetch)
        cfg.update_from_dict({
            "num_malicious_clients": 2,
            "adversary_config": {"type": "ALIE"},
            "server_config": {"aggregator": {"type": "Median"}},
        })
        return cfg.build()

    on, off = build(True), build(False)
    assert on._prefetcher is not None and off._prefetcher is None
    rows_on = [on.train() for _ in range(5)]
    rows_off = [off.train() for _ in range(5)]
    for r_on, r_off in zip(rows_on, rows_off):
        for k in ("train_loss", "agg_norm", "update_norm_mean"):
            assert r_on[k] == r_off[k], (k, r_on[k], r_off[k])
    for p_on, p_off in zip(_params(on), _params(off)):
        np.testing.assert_array_equal(p_on, p_off)


# Tier-1 runs the headline aggregator only; the rest of the registry
# runs the identical check in the full suite (`pytest tests/`) — two
# separately compiled programs per aggregator is the irreducible cost
# (~10-14 s/case here), and the 870 s tier-1 budget on this 2-core box
# cannot absorb them (PR 7 rebalance; this box's wall-clock swings ~2x
# run to run, so tier-1 must carry real headroom under the cap).
# PR 20 rebalance: the whole grid is slow-lane now — tier-1 prefetch
# bit-identity rides test_prefetch_bit_identity_fedavg_driver instead.
_T1_AGGREGATORS = ()


@pytest.mark.parametrize("agg_name", [
    a if a in _T1_AGGREGATORS else pytest.param(a, marks=pytest.mark.slow)
    for a in sorted(AGGREGATORS)])
def test_prefetch_bit_identity_per_aggregator(agg_name):
    """5 rounds of prefetch-split execution (sample_round_batches +
    step_prebatched, the prefetch-ON program pair) vs the fused step
    (prefetch OFF): params and round metrics bit-equal.  FedRound-level
    on a deliberately tiny task so the compiles stay cheap; the
    driver-level staging/donation path is covered by
    test_prefetch_bit_identity_fedavg_driver above."""
    from blades_tpu.adversaries import get_adversary, make_malicious_mask
    from blades_tpu.core import FedRound, Server, TaskSpec

    n, f, rounds = 6, 2, 5
    task = TaskSpec(model="mlp", input_shape=(8, 8, 1), num_classes=4,
                    lr=0.1).build()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, 12, 8, 8, 1)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, size=(n, 12)), jnp.int32)
    ln = jnp.full((n,), 12, jnp.int32)
    mal = make_malicious_mask(n, f)
    adv = get_adversary({"type": "ALIE"}, num_clients=n, num_byzantine=f)

    server = Server.from_config(aggregator=agg_name, num_byzantine=f, lr=0.5)
    fr = FedRound(task=task, server=server, adversary=adv, batch_size=4,
                  trusted_data=((x[0, :8], y[0, :8])
                                if agg_name == "FLTrust" else None))
    fused = jax.jit(fr.step)
    sample = jax.jit(fr.sample_round_batches)
    split = jax.jit(fr.step_prebatched)
    s_f = s_s = fr.init(jax.random.PRNGKey(0), n)
    key = jax.random.PRNGKey(5)
    for r in range(rounds):
        k = jax.random.fold_in(key, r)
        s_f, m_f = fused(s_f, x, y, ln, mal, k)
        bx, by = sample(x, y, ln, k)
        s_s, m_s = split(s_s, bx, by, mal, k)
        for mk in ("train_loss", "agg_norm", "update_norm_mean"):
            assert float(m_f[mk]) == float(m_s[mk]), (agg_name, r, mk)
    for a, b in zip(jax.tree.leaves(s_f.server.params),
                    jax.tree.leaves(s_s.server.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=agg_name)


# ---------------------------------------------------------------------------
# chained scan windows + deferred metric fetches (sweep loop)
# ---------------------------------------------------------------------------


# Same scanned-key contract as tests/test_core.py's
# test_multi_step_matches_sequential_steps, which stays tier-1; this
# variant adds the carry-chaining angle at ~5 s of extra compile
# (PR 20 budget rebalance).
@pytest.mark.slow
def test_multi_step_chained_matches_sequential_chain():
    """The scanned key discipline reproduces the host driver's chain:
    state AND the advanced carry match the sequential run bitwise."""
    algo = tiny_config(prefetch=False).build()
    fr, state0 = algo.fed_round, algo.state
    arrays, mal = algo._train_arrays, algo.malicious
    key0 = jax.random.PRNGKey(11)

    seq_state, seq_key = state0, key0
    step = jax.jit(fr.step)
    for _ in range(4):
        rk, seq_key = jax.random.split(seq_key)
        seq_state, _ = step(seq_state, *arrays, mal, rk)

    from functools import partial

    win_state, win_key, metrics = jax.jit(
        partial(fr.multi_step_chained, num_rounds=4)
    )(state0, *arrays, mal, key0)
    np.testing.assert_array_equal(np.asarray(seq_key), np.asarray(win_key))
    for a, b in zip(jax.tree.leaves(seq_state.server.params),
                    jax.tree.leaves(win_state.server.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.asarray(metrics["train_loss"]).shape == (4,)


def _result_rows(summary):
    rows = []
    for ln in (Path(summary["dir"]) / "result.json").read_text().strip().splitlines():
        r = json.loads(ln)
        r.pop("timers", None)
        r.pop("compile_cache_hits", None)
        r.pop("compile_cache_misses", None)
        rows.append(r)
    return rows


def _bi_experiments():
    return {
        "bi": {
            "run": "FEDAVG",
            "stop": {"training_iteration": 6},
            "config": {
                "dataset_config": {"type": "mnist", "num_clients": 6,
                                   "train_bs": 8},
                "global_model": "mlp",
                "evaluation_interval": 3,
                "num_malicious_clients": 2,
                "adversary_config": {"type": "ALIE"},
                "server_config": {"lr": 1.0,
                                  "aggregator": {"type": "Median"}},
            },
        }
    }


@pytest.fixture(scope="module")
def sequential_rows(tmp_path_factory):
    """The eager round-per-dispatch baseline both identity tests compare
    against (one shared run keeps tier-1 inside its wall-clock budget)."""
    tmp = tmp_path_factory.mktemp("seq")
    [seq] = run_experiments(_bi_experiments(), storage_path=str(tmp),
                            verbose=0, lanes=False, scan_window=1)
    return _result_rows(seq)


def test_scan_window_rows_bit_identical_to_sequential(tmp_path,
                                                      sequential_rows):
    [win] = run_experiments(_bi_experiments(), storage_path=str(tmp_path),
                            verbose=0, lanes=False, scan_window="auto")
    assert win.get("scan_window", 1) > 1, "auto window did not engage"
    win_rows = _result_rows(win)
    assert len(sequential_rows) == len(win_rows) == 6  # one row per round
    assert sequential_rows == win_rows


def test_deferred_metric_rows_bit_identical(tmp_path, sequential_rows):
    [dfr] = run_experiments(_bi_experiments(), storage_path=str(tmp_path),
                            verbose=0, lanes=False, scan_window=1,
                            metrics_every=4)
    assert sequential_rows == _result_rows(dfr)


def test_scan_window_respects_checkpoint_and_stop(tmp_path):
    """Windows must divide eval/checkpoint cadence and the stop round —
    checkpoints land on the same rounds as sequential execution."""
    exps = _bi_experiments()
    [s] = run_experiments(exps, storage_path=str(tmp_path), verbose=0,
                          lanes=False, checkpoint_freq=3,
                          scan_window="auto")
    assert s["rounds"] == 6
    tdir = Path(s["dir"])
    assert (tdir / "ckpt_000003").exists() and (tdir / "ckpt_000006").exists()
    from blades_tpu.tune.sweep import verify_result_rounds

    assert verify_result_rounds(tdir / "result.json") == [1, 2, 3, 4, 5, 6]


def test_auto_window_stays_off_for_pinned_dispatch(tmp_path):
    """User-pinned rounds_per_dispatch keeps its classic one-row-per-
    dispatch cadence (back-compat with the chunked driver)."""
    exps = _bi_experiments()
    exps["bi"]["config"]["rounds_per_dispatch"] = 3
    [s] = run_experiments(exps, storage_path=str(tmp_path), verbose=0,
                          lanes=False, scan_window="auto")
    assert "scan_window" not in s
    rows = _result_rows(s)
    assert [r["training_iteration"] for r in rows] == [3, 6]


@pytest.mark.slow
def test_streamed_chained_dispatch_matches_streamed_sequential():
    """chained_dispatch on the streamed path: windowed rounds consume
    the exact keys the sequential driver would, so a chained 2-round
    window reproduces two sequential streamed dispatches bitwise."""
    def cfg(**kw):
        c = tiny_config(prefetch=False)
        c.update_from_dict({"update_dtype": "float32", "client_block": 3,
                            "execution": "streamed", **kw})
        return c

    seq = cfg().build()
    win = cfg(rounds_per_dispatch=2, chained_dispatch=True).build()
    assert win._chained
    for _ in range(4):
        seq.train()
    win.train()  # 2 windows of 2 rounds
    win.train()
    assert seq.iteration == win.iteration == 4
    for a, b in zip(_params(seq), _params(win)):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# persistent compilation cache wiring
# ---------------------------------------------------------------------------


def test_persistent_cache_wiring(tmp_path, monkeypatch):
    from blades_tpu.perf import enable_persistent_compilation_cache

    target = tmp_path / "xla_cache"
    assert enable_persistent_compilation_cache(str(target)) == str(target)
    assert target.is_dir()
    # Idempotent, and the env fallback resolves when no arg is given.
    assert enable_persistent_compilation_cache(str(target)) == str(target)
