# blades-lint: disable-file=streamed-pass-discipline — property/equivalence tests exercise the raw reference primitives against the planner on purpose
"""Row-geometry pass fusion (ISSUE 9): the planner's request/plan/execute
lifecycle, the fused pallas row-stats kernel, and the ``hbm_passes``
accounting.

Four layers:

1. **Overlap discipline** — randomized ``(d, c)`` property tests of the
   tail-chunk scheme every fused pass inherits: accumulating passes see
   each column exactly once (``new_cols`` masks the overlap), idempotent
   writes see each column at least once.
2. **Fusion equivalence** — per-aggregator fused-vs-unfused results
   (bit-comparable on CPU: same chunk values, same updaters) including
   ALIE/IPM-forged buffers and the empty-benign-mask degradation, plus
   the planned-traversal regression pins: a refactor that silently
   de-fuses a bundle fails the exact ``(executed, unfused)`` counts.
3. **Kernel** — ``ops/pallas_rowstats`` in interpret mode against the
   chunk path (f32 + bf16, ragged widths, row padding, true-width sign
   counts), per the ``test_pallas_*`` convention.
4. **Whole rounds** — streamed rounds with ``fuse_rowgeom`` on/off match
   and stamp ``hbm_passes``/``hbm_passes_unfused`` (headline case
   tier-1, per-aggregator zoo slow-marked per the PR 7 budget
   convention).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.ops.aggregators import (
    Centeredclipping,
    Clippedclustering,
    DnC,
    FLTrust,
    GeoMed,
    Multikrum,
    Signguard,
)
from blades_tpu.parallel.streamed_geometry import (
    PassPlanner,
    PassRecorder,
    _masked_mean_w,
    aggregate_streamed,
    chunk_grid,
    new_cols,
    row_sq_norms,
    weighted_row_sum,
)


def _buf(n=8, d=210, seed=1, outliers=True):
    rng = np.random.default_rng(seed)
    B = rng.normal(size=(n, d)).astype(np.float32)
    if outliers:
        B[n - 2:] = B[:2].mean(0) * 5 + 1.0
    return jnp.asarray(B), B


# ---------------------------------------------------------------------------
# 1. tail-chunk overlap discipline (property tests)
# ---------------------------------------------------------------------------


def test_chunk_overlap_exactly_once_property():
    """Randomized (d, c): the union of ``new_cols`` masks covers every
    column EXACTLY once (accumulating passes never double-count the
    overlapped tail), and the chunk ranges cover every column at least
    once (overwrite passes see everything)."""
    rng = np.random.default_rng(0)
    for _ in range(40):
        d = int(rng.integers(1, 400))
        c_req = int(rng.integers(1, d + 16))
        c, k, starts = chunk_grid(d, c_req)
        starts = np.asarray(starts)
        counted = np.zeros(d, np.int64)
        touched = np.zeros(d, bool)
        for i, s in enumerate(starts):
            mask = np.asarray(new_cols(int(s), i, c))
            cols = np.arange(s, s + c)
            counted[cols[mask]] += 1
            touched[cols] = True
        assert (counted == 1).all(), (d, c_req)
        assert touched.all(), (d, c_req)


def test_accumulating_and_overwrite_passes_respect_overlap():
    """End-to-end on random ragged (d, c): an accumulating request (row
    norms) and an idempotent-overwrite request (weighted row sum) both
    come out exact despite the overlapping tail chunk."""
    rng = np.random.default_rng(3)
    for _ in range(8):
        n = int(rng.integers(2, 7))
        d = int(rng.integers(3, 150))
        c = int(rng.integers(1, d + 5))
        B = rng.normal(size=(n, d)).astype(np.float32)
        w = rng.normal(size=(n,)).astype(np.float32)
        buf = jnp.asarray(B)
        p = PassPlanner(buf, c)
        h_sq, h_ws = p.sq_norms(), p.weighted_sum(jnp.asarray(w))
        p.execute()
        np.testing.assert_allclose(np.asarray(h_sq.value), (B**2).sum(1),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(h_ws.value), w @ B,
                                   rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# 2. fused bundles: equivalence + planned-traversal regression pins
# ---------------------------------------------------------------------------


def test_fused_bundle_matches_reference_primitives():
    buf, B = _buf(n=9, d=333, seed=0, outliers=False)
    c = 64
    v = jnp.asarray(np.linspace(-1, 1, 333), jnp.float32)
    w = jnp.asarray(np.linspace(0.1, 1, 9), jnp.float32)
    rec = PassRecorder()
    p = PassPlanner(buf, c, recorder=rec)
    h_sq, h_g = p.sq_norms(), p.gram()
    h_d, h_ws, h_gd = p.dots(v), p.weighted_sum(w), p.gram_dot(w)
    h_s = p.sign_counts()
    p.execute()
    assert (rec.executed, rec.unfused) == (1, 6)
    np.testing.assert_array_equal(np.asarray(h_sq.value),
                                  np.asarray(row_sq_norms(buf, c)))
    np.testing.assert_array_equal(np.asarray(h_ws.value),
                                  np.asarray(weighted_row_sum(buf, w, c)))
    np.testing.assert_allclose(np.asarray(h_g.value), B @ B.T,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(h_d.value), B @ np.asarray(v),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(h_gd.value),
                               B @ (B.T @ np.asarray(w)),
                               rtol=1e-3, atol=1e-3)
    sc = np.asarray(h_s.value)
    np.testing.assert_array_equal(sc[:, 0], (B > 0).sum(1))
    np.testing.assert_array_equal(sc[:, 2], (B == 0).sum(1))


_AGG_CASES = [
    # (name, aggregator, state, extra kwargs, executed, unfused) for the
    # read-only path (sq fused into the first statistics bundle).  The
    # regression pins: a silently de-fused bundle changes `executed`.
    ("GeoMed", GeoMed(maxiter=5), (), {}, 6, 13),
    ("Multikrum", Multikrum(num_byzantine=2, k=3), (), {}, 2, 3),
    ("DnC", DnC(num_byzantine=2, sub_dim=32, num_iters=2), (),
     {"key": True}, 2, 3),
    ("Centeredclipping", Centeredclipping(n_iter=3), (), {}, 4, 8),
    ("Signguard-mean", Signguard(agg="mean"), (), {}, 2, 3),
    ("Signguard-median", Signguard(agg="median"), (), {}, 2, 3),
    ("Clippedclustering", Clippedclustering(signguard=True), (), {}, 2, 4),
    ("FLTrust", FLTrust(), (), {"trusted": True}, 2, 3),
]


@pytest.mark.parametrize("name,agg,state,extra,n_exec,n_unfused",
                         _AGG_CASES, ids=[c[0] for c in _AGG_CASES])
def test_fused_vs_unfused_equivalence_and_planned_passes(
        name, agg, state, extra, n_exec, n_unfused):
    """Per aggregator: the fused plan (a) matches the unfused
    one-traversal-per-request path within the chunk-path tolerances,
    (b) plans strictly fewer traversals (the ISSUE 9 acceptance:
    Multikrum/SignGuard statistics 2->1, GeoMed/Centeredclipping
    per-iteration 2->1), (c) pins the exact planned counts."""
    buf, B = _buf()
    kw = {}
    if extra.get("key"):
        kw["key"] = jax.random.PRNGKey(3)
    if extra.get("trusted"):
        kw["trusted"] = jnp.asarray(
            np.random.default_rng(5).normal(size=(210,)), jnp.float32)
    rec_f, rec_u = PassRecorder(), PassRecorder()
    out_f, st_f, sq_f = aggregate_streamed(
        agg, buf, None, state, d_chunk=64, recorder=rec_f, **kw)
    out_u, st_u, sq_u = aggregate_streamed(
        agg, buf, None, state, d_chunk=64, recorder=rec_u, fuse=False, **kw)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_u),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(sq_f), (B**2).sum(1), rtol=1e-5)
    assert (rec_f.executed, rec_f.unfused) == (n_exec, n_unfused)
    # The unfused comparator really runs one traversal per request.
    assert rec_u.executed == rec_u.unfused == n_unfused
    # The acceptance criterion: fused plans strictly fewer traversals.
    assert rec_f.executed < rec_f.unfused


def test_precomputed_sq_drops_the_norms_request():
    """With sq from the materialization pass, the first bundle shrinks
    by exactly the norms request."""
    buf, B = _buf()
    sq = jnp.asarray((B**2).sum(1))
    rec = PassRecorder()
    out, _, sq_out = aggregate_streamed(
        Multikrum(num_byzantine=2, k=3), buf, sq, (), d_chunk=64,
        recorder=rec)
    assert (rec.executed, rec.unfused) == (2, 2)
    assert sq_out is sq
    rec2 = PassRecorder()
    out2, _, _ = aggregate_streamed(
        Multikrum(num_byzantine=2, k=3), buf, None, (), d_chunk=64,
        recorder=rec2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2),
                               rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("adversary", ["ALIE", "IPM"])
@pytest.mark.parametrize("name,agg", [
    ("Multikrum", Multikrum(num_byzantine=2, k=3)),
    ("GeoMed", GeoMed(maxiter=5)),
    ("Signguard", Signguard(agg="mean")),
])
def test_fused_vs_unfused_on_forged_buffers(adversary, name, agg):
    """Forged rounds: buffers carrying real ALIE/IPM attack rows (the
    dense forge applied to the matrix, as the materialization pass
    leaves it) aggregate identically under the fused and unfused plans."""
    from blades_tpu.adversaries import get_adversary, make_malicious_mask

    buf, _ = _buf(n=8, d=210, seed=7, outliers=False)
    mal = make_malicious_mask(8, 2)
    adv = get_adversary(adversary, num_clients=8, num_byzantine=2)
    forged = adv.on_updates_ready(buf, mal, jax.random.PRNGKey(11),
                                  aggregator=agg, global_params=None)
    out_f, _, _ = aggregate_streamed(agg, forged, None, (), d_chunk=48)
    out_u, _, _ = aggregate_streamed(agg, forged, None, (), d_chunk=48,
                                     fuse=False)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_u),
                               rtol=2e-5, atol=2e-6)


def test_empty_benign_mask_degrades_to_all_rows():
    """The masked-mean finish weights degrade to ALL rows when the
    defense keeps nobody (masked._nonempty) — identically under both
    plans."""
    buf, B = _buf(n=6, d=90, seed=9, outliers=False)
    scale = jnp.asarray(np.linspace(0.5, 1.0, 6), jnp.float32)
    empty = jnp.zeros((6,), bool)
    w = _masked_mean_w(empty, scale)
    for fuse in (True, False):
        p = PassPlanner(buf, 32, fuse=fuse)
        h = p.weighted_sum(w)
        p.execute()
        np.testing.assert_allclose(
            np.asarray(h.value),
            (np.asarray(scale)[:, None] * B).sum(0) / 6, rtol=1e-4,
            atol=1e-5)


# ---------------------------------------------------------------------------
# 3. pallas row-stats kernel (interpret mode, per test_pallas_* convention)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_rowstats_kernel_matches_chunk_path(dtype):
    from blades_tpu.ops.pallas_rowstats import row_stats_bundle

    rng = np.random.default_rng(4)
    n, d = 9, 700  # ragged: row pad to 16, column pad to 1024
    B = rng.normal(size=(n, d)).astype(np.float32)
    B[2, 17] = 0.0
    buf = jnp.asarray(B, dtype)
    v = jnp.asarray(rng.normal(size=(2, d)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(2, n)), jnp.float32)
    out = row_stats_bundle(buf, sq=True, gram=True, signs=True, dots=v,
                           weights=w, gram_dot=w, interpret=True)
    ref = PassPlanner(buf, 256)
    h_sq, h_g, h_s = ref.sq_norms(), ref.gram(), ref.sign_counts()
    h_d0, h_d1 = ref.dots(v[0]), ref.dots(v[1])
    h_w0, h_w1 = ref.weighted_sum(w[0]), ref.weighted_sum(w[1])
    h_g0, h_g1 = ref.gram_dot(w[0]), ref.gram_dot(w[1])
    ref.execute()
    tol = dict(rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(out["sq"], h_sq.value, **tol)
    np.testing.assert_allclose(out["gram"], h_g.value, **tol)
    np.testing.assert_array_equal(np.asarray(out["signs"]),
                                  np.asarray(h_s.value))
    np.testing.assert_allclose(out["dots"][:, 0], h_d0.value, **tol)
    np.testing.assert_allclose(out["dots"][:, 1], h_d1.value, **tol)
    np.testing.assert_allclose(out["wsum"][0], h_w0.value, **tol)
    np.testing.assert_allclose(out["wsum"][1], h_w1.value, **tol)
    np.testing.assert_allclose(out["gram_dot"][:, 0], h_g0.value, **tol)
    np.testing.assert_allclose(out["gram_dot"][:, 1], h_g1.value, **tol)


def test_rowstats_kernel_true_width_sign_counts():
    """A buffer carrying stripe-alignment padding columns (zeros past
    d_true) must count signs over the TRUE width only — zeros derive
    from d_true, not the padded width."""
    from blades_tpu.ops.pallas_rowstats import row_stats_bundle

    rng = np.random.default_rng(6)
    n, d_true, d_alloc = 8, 300, 512
    B = np.zeros((n, d_alloc), np.float32)
    B[:, :d_true] = rng.normal(size=(n, d_true))
    B[0, 5] = 0.0
    out = row_stats_bundle(jnp.asarray(B), signs=True, sq=True,
                           d_true=d_true, interpret=True)
    sc = np.asarray(out["signs"])
    np.testing.assert_array_equal(sc[:, 0], (B[:, :d_true] > 0).sum(1))
    np.testing.assert_array_equal(sc[:, 1], (B[:, :d_true] < 0).sum(1))
    np.testing.assert_array_equal(sc[:, 2], (B[:, :d_true] == 0).sum(1))
    np.testing.assert_allclose(out["sq"], (B**2).sum(1), rtol=1e-5)


def test_planner_forced_through_kernel_matches_chunk():
    """The planner's kernel dispatch (forced, interpret mode) agrees
    with its chunk loop for a full aggregator run."""
    buf, _ = _buf()
    agg = Multikrum(num_byzantine=2, k=3)
    out_k, _, sq_k = aggregate_streamed(agg, buf, None, (), d_chunk=64,
                                        use_kernel=True, interpret=True)
    out_c, _, sq_c = aggregate_streamed(agg, buf, None, (), d_chunk=64,
                                        use_kernel=False)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_c),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(sq_k), np.asarray(sq_c),
                               rtol=1e-5)


def test_rowstats_kernel_gate_rejects_ineligible_shapes():
    from blades_tpu.ops.pallas_rowstats import kernel_applicable

    # CPU backend (tier-1 runs JAX_PLATFORMS=cpu): never applicable.
    assert not kernel_applicable(1000, 1 << 23)
    # Mixed-bundle requests (gather/mean_std/median) are not kernel
    # kinds: the planner chunk-loops such bundles in ONE traversal.
    buf, _ = _buf()
    p = PassPlanner(buf, 64, use_kernel=True, interpret=True)
    p.sq_norms()
    p.col_mean_std(jnp.zeros((8,), bool))
    assert not p._kernel_ok(p._pending)


# ---------------------------------------------------------------------------
# 4. whole streamed rounds: fuse_rowgeom A/B + hbm_passes stamping
# ---------------------------------------------------------------------------


def _round_setup(aggregator, adversary, n=8, f=2):
    from blades_tpu.adversaries import get_adversary, make_malicious_mask
    from blades_tpu.core import FedRound, Server, TaskSpec

    task = TaskSpec(model="mlp", input_shape=(8, 8, 1), num_classes=10,
                    lr=0.1).build()
    server = Server.from_config(aggregator=aggregator, num_byzantine=f,
                                lr=0.5)
    adv = (get_adversary(adversary, num_clients=n, num_byzantine=f)
           if adversary else None)
    fr = FedRound(task=task, server=server, adversary=adv, batch_size=4,
                  num_batches_per_round=1)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, 8, 8, 8, 1)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=(n, 8)), jnp.int32)
    lengths = jnp.full((n,), 8, jnp.int32)
    return fr, x, y, lengths, make_malicious_mask(n, f)


def _run_round(fr, x, y, ln, mal, fused):
    from blades_tpu.parallel.streamed import streamed_step

    step = streamed_step(fr, client_block=4, d_chunk=1 << 17,
                         update_dtype=jnp.float32, donate=False,
                         fuse_rowgeom=fused)
    st = fr.init(jax.random.PRNGKey(0), x.shape[0])
    return step(st, x, y, ln, mal, jax.random.PRNGKey(7))


def test_round_stamps_hbm_passes_and_fusion_drops_them():
    """Headline tier-1 whole-round case: a read-only Multikrum round
    stamps the planned counts (norms+Gram fused: 2 executed vs 3
    unfused) and the fused/unfused rounds produce the same result."""
    fr, x, y, ln, mal = _round_setup("Multikrum", adversary=None)
    st_f, m_f = _run_round(fr, x, y, ln, mal, fused=True)
    st_u, m_u = _run_round(fr, x, y, ln, mal, fused=False)
    assert int(m_f["hbm_passes"]) == 2
    assert int(m_f["hbm_passes_unfused"]) == 3
    assert int(m_u["hbm_passes"]) == 3  # the A/B comparator de-fuses
    assert int(m_f["hbm_passes"]) < int(m_f["hbm_passes_unfused"])
    for a, b in zip(jax.tree.leaves(st_f.server.params),
                    jax.tree.leaves(st_u.server.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    for k in ("train_loss", "agg_norm", "update_norm_mean"):
        np.testing.assert_allclose(float(m_f[k]), float(m_u[k]), rtol=1e-5)


@pytest.mark.slow
@pytest.mark.parametrize("aggregator,adversary,expect_hbm", [
    # Coordinate-wise forge -> materialization traversal (+1) and free
    # norms; the per-aggregator statistics bundles follow.
    ("Multikrum", "ALIE", 3),
    ("Signguard", "ALIE", 3),
    ("Clippedclustering", "IPM", 3),
    ("Centeredclipping", "IPM", 1 + 1 + 5),   # mat + dots-init + n_iter
    ("GeoMed", "ALIE", 1 + 1 + 100),          # mat + init + maxiter bound
    ("DnC", "IPM", 3),
    # Row-geometry forge on a read-only buffer: forge bundles + scatter.
    ("Multikrum", "MinMax", 2 + 1 + 2),       # forge(2) + scatter + agg(2)
])
def test_round_hbm_passes_per_aggregator_zoo(aggregator, adversary,
                                             expect_hbm):
    """Planned pass-count regression across the zoo: a refactor that
    silently de-fuses any bundle changes the stamped count."""
    fr, x, y, ln, mal = _round_setup(aggregator, adversary)
    _, m = _run_round(fr, x, y, ln, mal, fused=True)
    assert int(m["hbm_passes"]) == expect_hbm, aggregator
    assert int(m["hbm_passes"]) <= int(m["hbm_passes_unfused"])


@pytest.mark.slow
@pytest.mark.parametrize("aggregator,adversary", [
    ("GeoMed", "ALIE"),
    ("Centeredclipping", "IPM"),
    ("Signguard", "ALIE"),
    ("Clippedclustering", "ALIE"),
    ("DnC", "IPM"),
    ("Multikrum", "MinMax"),
])
def test_round_fused_vs_unfused_zoo(aggregator, adversary):
    """Fused-vs-unfused whole-round equivalence across the zoo
    (forged rounds included)."""
    fr, x, y, ln, mal = _round_setup(aggregator, adversary)
    st_f, m_f = _run_round(fr, x, y, ln, mal, fused=True)
    st_u, m_u = _run_round(fr, x, y, ln, mal, fused=False)
    for a, b in zip(jax.tree.leaves(st_f.server.params),
                    jax.tree.leaves(st_u.server.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(float(m_f["update_norm_mean"]),
                               float(m_u["update_norm_mean"]), rtol=1e-4)
