"""Test harness: force CPU JAX with 8 virtual devices.

The TPU-native analogue of the reference's "multi-node simulation without a
cluster" (SURVEY.md §4): multi-chip sharding tests run on a virtual 8-device
CPU mesh via ``--xla_force_host_platform_device_count``.  Must run before
jax is imported anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
