"""Test harness: force CPU JAX with 8 virtual devices.

The TPU-native analogue of the reference's "multi-node simulation without a
cluster" (SURVEY.md §4): multi-chip sharding tests run on a virtual
8-device CPU mesh via ``--xla_force_host_platform_device_count``.

This image registers an ``axon`` TPU PJRT plugin from ``sitecustomize`` at
interpreter start, which force-sets ``jax.config.jax_platforms="axon,cpu"``
— so the env-var route (``JAX_PLATFORMS=cpu``) is silently overridden.  The
reliable override is a ``jax.config.update`` after import but before the
first backend use (pytest imports this conftest before any test module, so
no backend exists yet).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# NOT wired here: the perf layer's persistent compilation cache
# (enable_persistent_compilation_cache).  Measured on this image's
# jaxlib 0.4.37 CPU backend, a warm cache SEGFAULTS the process on
# executable deserialization (cold writes are fine) — so the suite must
# not depend on it.  The wiring stays opt-in (--compile-cache /
# $BLADES_TPU_COMPILE_CACHE_DIR) for real TPU sweeps.


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: tier-2 tests (multi-device shard_map compiles, large-model "
        "CPU compiles) excluded from the tier-1 `-m 'not slow'` budget",
    )
