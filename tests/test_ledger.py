"""Client-lifetime ledger tests (blades_tpu/obs/ledger.py): the
longitudinal per-client record fold, backend/checkpoint parity, the
cohort-shaped integration across the dense, windowed and buffered-async
paths, and the fleet-view surfaces (watchdog rules, flight-recorder
digests, report CLI).

The acceptance contracts under test:

- dense full-participation diagnosis is BIT-identical with the ledger
  armed (the ledger is a pure host-side consumer of already-fetched
  lanes);
- cohort-shaped rounds (windowed / async) map lane decisions back to
  the correct registered client ids, and the ledger's lifetime counts
  reconcile exactly with the per-row lane stream;
- a 100k-registered disk ledger runs under a bounded host-memory
  ceiling (memmapped columns — page cache, not RSS);
- kill-and-resume restores the ledger bit-identically through the
  faults harness (streaming CRC-verified shard checkpoints).
"""

import json
import tracemalloc
from pathlib import Path

import numpy as np
import pytest

from blades_tpu.obs.ledger import (
    DEFAULT_SHARD_ROWS,
    LEDGER_COLUMNS,
    LEDGER_EWMA_ALPHA,
    LedgerError,
    make_ledger,
    read_ledger,
    validate_ledger_checkpoint,
)

N = 8  # tiny-federation size for the driver tests


# ---------------------------------------------------------------------------
# observe(): the one cohort-shaped update per round
# ---------------------------------------------------------------------------


def test_observe_counts_recency_and_first_participation():
    led = make_ledger("resident", N)
    led.observe([0, 2, 5], round=1, tick=7)
    for cid, expect in ((0, 1), (1, 0), (2, 1), (5, 1)):
        rec = led.client_record(cid)
        assert rec["participation"] == expect
        assert rec["last_round"] == (1 if expect else -1)
        assert rec["last_tick"] == (7 if expect else -1)
    led.observe([2], round=4)
    rec = led.client_record(2)
    assert rec["participation"] == 2 and rec["last_round"] == 4
    # tick omitted: recency keeps the last stamped value.
    assert rec["last_tick"] == 7


def test_score_ewma_first_sample_then_exact_binary_update():
    led = make_ledger("resident", N)
    led.observe([3], round=1, scores=[2.0])
    assert led.client_record(3)["score_ewma"] == 2.0  # first = raw score
    led.observe([3], round=2, scores=[4.0])
    a = LEDGER_EWMA_ALPHA
    assert a == 0.125  # power of two -> the update below is exact
    assert led.client_record(3)["score_ewma"] == (1 - a) * 2.0 + a * 4.0


def test_welford_running_stats_match_two_sample_population():
    led = make_ledger("resident", N)
    led.observe([1], round=1, staleness=[1.0], norms=[10.0])
    led.observe([1], round=2, staleness=[3.0], norms=[20.0])
    rec = led.client_record(1)
    assert rec["stale_count"] == 2
    assert rec["stale_mean"] == 2.0
    assert rec["stale_var"] == 1.0  # population variance of {1, 3}
    assert rec["norm_count"] == 2
    assert rec["norm_mean"] == 15.0
    assert rec["norm_var"] == 25.0


def test_flagged_churn_is_vs_each_clients_own_history():
    led = make_ledger("resident", N)
    # First-timers baseline "not flagged": two of three flip on entry.
    led.observe([0, 1, 2], round=1, flagged=[True, True, False])
    assert led.round_fields()["flagged_churn"] == 2
    # Client 1 flips back; 0 and 2 hold steady.
    led.observe([0, 1, 2], round=2, flagged=[True, False, False])
    assert led.round_fields()["flagged_churn"] == 1
    rec0 = led.client_record(0)
    assert rec0["flagged"] == 2 and rec0["last_flagged"] is True
    rec1 = led.client_record(1)
    assert rec1["flagged"] == 1 and rec1["last_flagged"] is False


def test_round_fields_fleet_statistics_and_top_suspects():
    led = make_ledger("resident", N)
    led.observe([0, 1, 2, 3], round=1, flagged=[1, 1, 1, 0],
                scores=[5.0, 1.0, 2.0, 0.0])
    led.observe([0, 1], round=2, flagged=[0, 1], scores=[0.0, 1.0])
    rf = led.round_fields()
    # flag rates: 0 -> 0.5, 1 -> 1.0, 2 -> 1.0, 3 -> 0.0
    assert rf["ledger_clients_seen"] == 4
    assert rf["suspected_fraction"] == 0.5  # ids 1, 2 of 4 seen
    rep = np.array([0.5, 0.0, 0.0, 1.0])  # 1 - lifetime flag rate
    for q, key in ((10, "reputation_p10"), (50, "reputation_p50"),
                   (90, "reputation_p90")):
        assert rf[key] == pytest.approx(float(np.percentile(rep, q)))
    # Rate ties broken by score EWMA (id 2 ewma 2.0 > id 1 ewma 1.0),
    # zero-flag-rate clients never listed as suspects.
    assert rf["ledger_top_suspects"] == [2, 1, 0]
    sus = led.top_suspects(2)
    assert [r["client"] for r in sus] == [2, 1]
    assert sus[0]["flag_rate"] == 1.0
    summary = led.summary()
    assert summary["backend"] == "resident"
    assert summary["clients_seen"] == 4 and summary["total_flagged"] == 4
    assert summary["total_bytes"] == led.row_bytes * N


def test_empty_ledger_round_fields_are_inert():
    rf = make_ledger("resident", N).round_fields()
    assert rf["ledger_clients_seen"] == 0
    assert rf["suspected_fraction"] == 0.0
    assert rf["ledger_top_suspects"] == []
    assert rf["reputation_p50"] == 1.0


def test_observe_rejects_malformed_cohorts():
    led = make_ledger("resident", N)
    with pytest.raises(LedgerError, match="non-empty 1-D"):
        led.observe([], round=1)
    with pytest.raises(LedgerError, match="non-empty 1-D"):
        led.observe([[0, 1]], round=1)
    with pytest.raises(LedgerError, match="out of range"):
        led.observe([0, N], round=1)
    with pytest.raises(LedgerError, match="out of range"):
        led.observe([-1], round=1)
    with pytest.raises(LedgerError, match="duplicates"):
        led.observe([0, 3, 3], round=1)
    with pytest.raises(LedgerError, match="out of range"):
        led.client_record(N)
    with pytest.raises(ValueError, match="backend"):
        make_ledger("hbm", N)


# ---------------------------------------------------------------------------
# backends: resident vs disk parity, checkpoint roundtrip + chaos
# ---------------------------------------------------------------------------


def _fold_cohorts(led):
    led.observe([0, 2, 5], round=1, tick=3, flagged=[1, 0, 1],
                scores=[2.0, -1.0, 0.5], staleness=[0, 1, 2],
                norms=[1.0, 2.0, 3.0])
    led.observe([1, 2], round=2, tick=5, flagged=[0, 1],
                scores=[0.25, 4.0], staleness=[1, 0], norms=[5.0, 0.5])
    return led


def test_disk_backend_matches_resident_bit_for_bit(tmp_path):
    res = _fold_cohorts(make_ledger("resident", N))
    disk = _fold_cohorts(make_ledger("disk", N,
                                     directory=str(tmp_path / "led")))
    d_res, d_disk = res.digest(), disk.digest()
    assert d_res.pop("backend") == "resident"
    assert d_disk.pop("backend") == "disk"
    assert d_res == d_disk  # totals AND the full-column CRC32
    assert disk.host_bytes() == 0  # memmaps: page cache, not RSS
    assert res.host_bytes() == res.total_bytes()
    for cid in range(N):
        assert res.client_record(cid) == disk.client_record(cid)
    disk.close()
    assert (tmp_path / "led").exists()  # caller-owned dir survives close


def test_disk_ledger_owns_and_removes_its_temp_dir():
    led = make_ledger("disk", N)
    private = led._dir
    assert private.exists()
    led.observe([0], round=1)
    led.close()
    assert not private.exists()


def test_checkpoint_roundtrip_and_cross_backend_restore(tmp_path):
    led = _fold_cohorts(make_ledger("resident", N))
    ck = tmp_path / "ledger"
    led.save(ck, shard_rows=3)  # 3 shards -> multi-shard layout on CPU
    num_ok, errors = validate_ledger_checkpoint(ck)
    assert errors == []
    assert num_ok == 3 * len(LEDGER_COLUMNS)
    # read_ledger materialises a ResidentLedger regardless of writer.
    back = read_ledger(ck)
    assert back.digest()["crc32"] == led.digest()["crc32"]
    assert back.client_record(5) == led.client_record(5)
    # The same shard set restores under the disk backend.
    disk = make_ledger("disk", N, directory=str(tmp_path / "live"))
    disk.load(ck)
    assert disk.digest()["crc32"] == led.digest()["crc32"]
    disk.close()
    # Population mismatch is a refusal, not a silent partial restore.
    with pytest.raises(LedgerError, match="registered clients"):
        make_ledger("resident", N + 1).load(ck)


def test_checkpoint_chaos_torn_corrupt_missing(tmp_path):
    led = _fold_cohorts(make_ledger("resident", N))
    ck = tmp_path / "ledger"
    led.save(ck, shard_rows=4)

    # Torn shard (size mismatch): reported, named, and load() refuses.
    victim = ck / "shard-00000.l03.npy"
    data = victim.read_bytes()
    victim.write_bytes(data[:-5])
    _, errors = validate_ledger_checkpoint(ck)
    assert any("torn shard" in e and victim.name in e for e in errors)
    with pytest.raises(LedgerError, match="torn"):
        make_ledger("resident", N).load(ck)

    # Same size, flipped payload byte: the CRC catches it.
    corrupt = bytearray(data)
    corrupt[-1] ^= 0xFF
    victim.write_bytes(bytes(corrupt))
    _, errors = validate_ledger_checkpoint(ck)
    assert any("CRC32 mismatch" in e for e in errors)
    with pytest.raises(LedgerError, match="CRC32"):
        make_ledger("resident", N).load(ck)
    victim.write_bytes(data)

    # Missing shard file.
    gone = ck / "shard-00001.l00.npy"
    gone.unlink()
    _, errors = validate_ledger_checkpoint(ck)
    assert any("missing shard file" in e for e in errors)
    with pytest.raises(LedgerError, match="missing shard"):
        make_ledger("resident", N).load(ck)

    # Manifest drift: an entry naming a file outside the layout.
    manifest = json.loads((ck / "manifest.json").read_text())
    manifest["files"]["shard-00099.l00.npy"] = {"bytes": 1, "crc32": 0}
    (ck / "manifest.json").write_text(json.dumps(manifest))
    _, errors = validate_ledger_checkpoint(ck)
    assert any("not part of the shard layout" in e for e in errors)

    # No manifest at all: the shard set was never published.
    (ck / "manifest.json").unlink()
    num_ok, errors = validate_ledger_checkpoint(ck)
    assert num_ok == 0 and "no manifest.json" in errors[0]
    with pytest.raises(LedgerError, match="manifest"):
        read_ledger(ck)


def test_save_is_rerunnable_and_clears_orphaned_tmps(tmp_path):
    led = _fold_cohorts(make_ledger("resident", N))
    ck = tmp_path / "ledger"
    led.save(ck)
    (ck / "shard-00000.l00.npy.tmp").write_bytes(b"interrupted")
    led.observe([4], round=3)
    led.save(ck)  # overwrite in place, orphan deleted
    assert not list(ck.glob("*.tmp"))
    assert validate_ledger_checkpoint(ck)[1] == []
    assert read_ledger(ck).client_record(4)["participation"] == 1


# ---------------------------------------------------------------------------
# offline CLIs: validate_metrics --ledger, ledger_report
# ---------------------------------------------------------------------------


def test_validate_metrics_ledger_mode(tmp_path, capsys):
    from tools.validate_metrics import main as vm

    led = _fold_cohorts(make_ledger("resident", N))
    ck = tmp_path / "ledger"
    led.save(ck)
    assert vm(["--ledger", str(ck)]) == 0
    out = capsys.readouterr().out
    assert "valid shard file(s), 0 error(s)" in out

    # Orphaned .tmp inside the directory: noted, still rc 0 (the
    # published shard set next to it is complete).
    (ck / "manifest.json.tmp").write_bytes(b"x")
    assert vm(["--ledger", str(ck)]) == 0
    assert "orphaned manifest.json.tmp" in capsys.readouterr().out
    (ck / "manifest.json.tmp").unlink()

    # A torn shard is a reported error and a nonzero exit.
    victim = ck / "shard-00000.l00.npy"
    victim.write_bytes(victim.read_bytes()[:-3])
    assert vm(["--ledger", str(ck)]) == 1
    assert "torn shard" in capsys.readouterr().out
    assert vm(["--ledger", str(tmp_path / "nope")]) == 1


def test_ledger_report_fleet_and_client_views(tmp_path, capsys):
    from tools.ledger_report import main as report

    led = _fold_cohorts(make_ledger("resident", N))
    ck = tmp_path / "ledger"
    led.save(ck)

    assert report([str(ck)]) == 0
    out = capsys.readouterr().out
    assert f"{N} registered, 4 seen" in out
    assert "suspected_fraction" in out and "top" in out

    assert report([str(ck), "--json", "--top", "2"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["summary"]["clients_seen"] == 4
    assert len(payload["top_suspects"]) == 2
    assert payload["top_suspects"][0]["flag_rate"] == 1.0

    # Per-client view joined against a cohort-shaped metrics stream:
    # membership in lane_forensics["clients"], not lane position.
    metrics = tmp_path / "metrics.jsonl"
    rows = [
        {"training_iteration": 1, "tick": 3,
         "lane_forensics": {"clients": [0, 2, 5],
                            "benign_mask": [False, True, False],
                            "scores": [2.0, -1.0, 0.5],
                            "update_norms": [1.0, 2.0, 3.0]}},
        {"training_iteration": 2,
         "lane_forensics": {"clients": [1, 2],
                            "benign_mask": [True, False],
                            "scores": [0.25, 4.0],
                            "update_norms": [5.0, 0.5]}},
        {"training_iteration": 3, "train_loss": 0.1},  # no lanes: skipped
    ]
    metrics.write_text("\n".join(json.dumps(r) for r in rows)
                       + "\n{torn line")
    assert report([str(ck), "--client", "2", "--metrics", str(metrics),
                   "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["record"]["participation"] == 2
    tl = payload["timeline"]
    assert [ev["round"] for ev in tl] == [1, 2]
    assert [ev["flagged"] for ev in tl] == [False, True]
    assert tl[0]["tick"] == 3 and tl[1]["update_norm"] == 0.5

    assert report([str(ck), "--client", "2", "--metrics",
                   str(metrics)]) == 0
    out = capsys.readouterr().out
    assert "timeline (2 diagnosed round(s)" in out and "FLAGGED" in out

    assert report([str(ck), "--client", str(N)]) == 1  # out of range
    assert report([str(tmp_path / "nope")]) == 1  # no manifest
    assert "manifest" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------


def _base_cfg(**overrides):
    from blades_tpu.algorithms.config import FedavgConfig

    cfg = (FedavgConfig()
           .data(dataset="mnist", num_clients=N, seed=3)
           .training(global_model="mlp",
                     aggregator={"type": "Median"}))
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


def test_ledger_backend_normalization_and_gates():
    cfg = _base_cfg()
    for raw, want in ((False, None), (None, None), ("off", None),
                      ("", None), (True, "resident"),
                      ("resident", "resident"), ("disk", "disk")):
        cfg.ledger = raw
        assert cfg.ledger_backend == want
    cfg.ledger = "hbm"
    with pytest.raises(ValueError, match="off|resident|disk"):
        cfg.ledger_backend

    _base_cfg().observability(ledger=True).validate()
    with pytest.raises(ValueError, match="unsupported pair"):
        _base_cfg(num_devices=2).observability(ledger=True).validate()
    with pytest.raises(ValueError, match="ledger_dir"):
        _base_cfg().observability(ledger_dir="/tmp/led").validate()


# ---------------------------------------------------------------------------
# dense integration: forensics equivalence + armed row fields
# ---------------------------------------------------------------------------

N_CLIENTS, N_BYZ = 10, 3


def _dense_cfg(ledger=False, seed=3):
    from blades_tpu.algorithms import get_algorithm_class

    _, cfg = get_algorithm_class("FEDAVG", return_config=True)
    cfg.update_from_dict({
        "dataset_config": {"type": "mnist", "num_clients": N_CLIENTS,
                           "train_bs": 8, "seed": seed},
        "global_model": "mlp",
        "evaluation_interval": 10,
        "num_malicious_clients": N_BYZ,
        "adversary_config": {"type": "ALIE"},
        "server_config": {"lr": 1.0, "aggregator": "Median"},
        "forensics": True,
        "ledger": ledger,
    })
    return cfg


def test_dense_diagnosis_bit_identical_with_ledger_armed():
    """Acceptance: arming the ledger must not perturb training or the
    diagnosis — it is a pure host-side consumer of the fetched lanes."""
    from blades_tpu.obs import validate_record

    bare = _dense_cfg(ledger=False).build()
    armed = _dense_cfg(ledger=True).build()
    for rnd in range(1, 4):
        r0, r1 = bare.train(), armed.train()
        assert r0["train_loss"] == r1["train_loss"]  # bit-identical
        assert r0["lane_forensics"]["benign_mask"] == \
            r1["lane_forensics"]["benign_mask"]
        assert r0["lane_forensics"]["scores"] == \
            r1["lane_forensics"]["scores"]
        # Dense full participation: the cohort id-vector is the
        # identity arange, so pre-cohort consumers read unchanged.
        assert r1["lane_forensics"]["clients"] == list(range(N_CLIENTS))
        assert len(r1["lane_forensics"]["update_norms"]) == N_CLIENTS
        # Armed rows carry the schema-registered fleet fields.
        for key in ("suspected_fraction", "flagged_churn",
                    "reputation_p10", "reputation_p50", "reputation_p90",
                    "ledger_clients_seen", "ledger_top_suspects"):
            assert key in r1 and key not in r0
        assert r1["ledger_clients_seen"] == N_CLIENTS
        validate_record({"experiment": "e", "trial": "t",
                         "training_iteration": rnd, **r1})

    led = armed.client_ledger
    assert bare.client_ledger is None
    # Every client participated every round; flag counts reconcile
    # with the per-row masks the same rows emitted.
    part = np.asarray(led._column("participation"))
    assert part.tolist() == [3] * N_CLIENTS
    summary = armed.ledger_summary
    assert summary["backend"] == "resident"
    assert summary["clients_seen"] == N_CLIENTS
    assert bare.ledger_summary is None


# ---------------------------------------------------------------------------
# cohort-shaped integration: windowed sampling and buffered-async cycles
# ---------------------------------------------------------------------------


def _reconcile_rows_against_ledger(rows, led, n_registered):
    """Rebuild per-client lifetime tallies from the rows' cohort-shaped
    lanes and demand the ledger agrees exactly."""
    part = np.zeros(n_registered, np.int64)
    flagged = np.zeros(n_registered, np.int64)
    for row in rows:
        lanes = row["lane_forensics"]
        ids = lanes["clients"]
        assert len(set(ids)) == len(ids)  # distinct within a round
        assert all(0 <= c < n_registered for c in ids)
        for c, ok in zip(ids, lanes["benign_mask"]):
            part[c] += 1
            flagged[c] += not ok
    np.testing.assert_array_equal(
        part, np.asarray(led._column("participation")))
    np.testing.assert_array_equal(
        flagged, np.asarray(led._column("flagged")))
    return part


def test_windowed_cohort_diagnosis_feeds_ledger(tmp_path):
    """Participation-window rounds diagnose the SAMPLED cohort: lane i
    maps to registered client clients[i], and the ledger's lifetime
    tallies reconcile with the emitted lanes round for round."""
    from blades_tpu.algorithms.config import FedavgConfig

    w = 4
    cfg = (FedavgConfig()
           .data(dataset="mnist", num_clients=N, seed=3)
           .training(global_model="mlp", server_lr=1.0,
                     train_batch_size=8,
                     aggregator={"type": "Median"})
           .client(lr=0.1, momentum=0.9)
           .evaluation(evaluation_interval=0)
           .resources(state_store="host", window=w)
           .observability(forensics=True,
                          ledger="disk",
                          ledger_dir=str(tmp_path / "led")))
    algo = cfg.build()
    rows = [algo.train() for _ in range(6)]
    led = algo.client_ledger
    assert led.backend == "disk"
    for row in rows:
        assert len(row["lane_forensics"]["clients"]) == w
        assert row["ledger_clients_seen"] >= w
    part = _reconcile_rows_against_ledger(rows, led, N)
    assert part.sum() == 6 * w
    # Cohorts rotate: more registered clients seen than one window.
    assert (part > 0).sum() > w
    algo.stop()
    assert (tmp_path / "led").exists()  # caller-owned live dir survives


def test_async_cycles_diagnose_events_and_feed_ledger():
    """Buffered-async cycles diagnose the staleness-scaled event
    matrix: lanes are the cycle's buffered arrivals (distinct clients
    by take_cycle's contract), and the ledger folds the engine's
    staleness column alongside the diagnosis."""
    from blades_tpu.algorithms.config import FedavgConfig

    agg_every = 4
    cfg = (FedavgConfig()
           .data(dataset="mnist", num_clients=N, seed=7)
           .training(global_model="mlp",
                     aggregator={"type": "Median"})
           .resources(execution="async")
           .arrivals(rate=0.4, agg_every=agg_every, staleness_cap=4)
           .observability(forensics=True, ledger=True))
    cfg.validate()
    algo = cfg.build()
    rows = [algo.train() for _ in range(4)]
    led = algo.client_ledger
    for row in rows:
        lanes = row["lane_forensics"]
        assert len(lanes["clients"]) == agg_every
        assert "byz_precision" in row and "num_flagged" in row
        assert row["tick"] >= 1
        assert "suspected_fraction" in row
    part = _reconcile_rows_against_ledger(rows, led, N)
    assert part.sum() == 4 * agg_every
    # The engine's per-event staleness column lands in the running
    # stats: every participation folded exactly one staleness sample.
    np.testing.assert_array_equal(
        part, np.asarray(led._column("stale_count")))
    seen = part > 0
    stale_means = np.asarray(led._column("stale_mean"))[seen]
    assert np.all(stale_means >= 0)
    # Recency tracks the async clock, not the round counter.
    ticks = np.asarray(led._column("last_tick"))[seen]
    assert ticks.max() == max(row["tick"] for row in rows)


# ---------------------------------------------------------------------------
# scale: 100k registered clients on the disk backend, bounded host RAM
# ---------------------------------------------------------------------------


def test_100k_registered_disk_ledger_bounded_host_memory(tmp_path):
    """Acceptance: a 100k-registered disk ledger observes cohorts,
    computes fleet views, checkpoints and digests with host allocations
    a small fraction of the population's column bytes (the memmaps are
    page cache, not RSS)."""
    n, cohort = 100_000, 512
    rng = np.random.default_rng(0)
    tracemalloc.start()
    try:
        led = make_ledger("disk", n, directory=str(tmp_path / "led"))
        for rnd in range(1, 4):
            ids = rng.choice(n, size=cohort, replace=False)
            led.observe(np.sort(ids), round=rnd,
                        flagged=rng.random(cohort) < 0.3,
                        scores=rng.normal(size=cohort),
                        norms=np.abs(rng.normal(size=cohort)))
        rf = led.round_fields()
        assert 0 < rf["ledger_clients_seen"] <= 3 * cohort
        ck = tmp_path / "ckpt"
        led.save(ck)
        digest = led.digest()
        _, peak = tracemalloc.get_traced_memory()
    finally:
        tracemalloc.stop()
    assert led.host_bytes() == 0
    assert led.total_bytes() == n * led.row_bytes
    # Bounded host memory: far below the resident column footprint.
    assert peak < led.total_bytes() // 4, (
        f"peak {peak} bytes vs {led.total_bytes()} resident-equivalent")
    num_shards = -(-n // DEFAULT_SHARD_ROWS)
    num_ok, errors = validate_ledger_checkpoint(ck)
    assert errors == [] and num_ok == num_shards * len(LEDGER_COLUMNS)
    assert digest["n_registered"] == n
    assert digest["clients_seen"] == rf["ledger_clients_seen"]
    led.close()


# ---------------------------------------------------------------------------
# kill-and-resume: the ledger restores bit-identically mid-sweep
# ---------------------------------------------------------------------------


def _ledger_experiments(stop=8):
    return {
        "led": {
            "run": "FEDAVG",
            "stop": {"training_iteration": stop},
            "config": {
                "dataset_config": {"type": "mnist", "num_clients": N,
                                   "train_bs": 8, "seed": 3},
                "global_model": "mlp",
                "client_config": {"lr": 0.1, "momentum": 0.9},
                "evaluation_interval": 4,
                "server_config": {"lr": 1.0,
                                  "aggregator": {"type": "Median"}},
                "state_store": "disk",
                "state_window": 5,
                "forensics": True,
                "ledger": True,
            },
        }
    }


def _rows(tdir, keep_eval_rounds=(4, 8)):
    rows = []
    for ln in (Path(tdir) / "result.json").read_text().strip().splitlines():
        r = json.loads(ln)
        for k in ("timers", "compile_cache_hits", "compile_cache_misses",
                  "state_stage_ms", "state_bytes_staged", "data_stage_ms"):
            r.pop(k, None)  # wall-clock / cache / staging-timing noise
        if r["training_iteration"] not in keep_eval_rounds:
            for k in ("test_loss", "test_acc", "test_acc_top3"):
                r.pop(k, None)  # repeat-last-eval rows (not checkpointed)
        rows.append(r)
    return rows


def test_kill_and_resume_ledger_bit_identical(tmp_path):
    """Acceptance: a SimulatedPreemption mid-sweep restores the ledger
    from its streaming shard checkpoint and reproduces the
    straight-through rows — INCLUDING the longitudinal fleet fields
    (suspected_fraction, flagged_churn, reputation percentiles) and the
    end-of-trial summary["ledger"] block — bit for bit."""
    from blades_tpu.tune import run_experiments

    [straight] = run_experiments(
        _ledger_experiments(), storage_path=str(tmp_path / "a"),
        verbose=0, lanes=False, checkpoint_freq=2)
    [preempted] = run_experiments(
        _ledger_experiments(), storage_path=str(tmp_path / "b"),
        verbose=0, lanes=False, checkpoint_freq=2, max_failures=1,
        preempt_after=5, retry_backoff_base=0.0)
    assert "status" not in preempted and preempted["rounds"] == 8
    tdir = Path(preempted["dir"])
    assert "SimulatedPreemption" in (tdir / "error.txt").read_text()

    rows_a, rows_b = _rows(straight["dir"]), _rows(tdir)
    assert len(rows_a) == len(rows_b) == 8
    for ra, rb in zip(rows_a, rows_b):
        assert ra == rb
        for key in ("suspected_fraction", "flagged_churn",
                    "reputation_p50", "ledger_clients_seen"):
            assert key in ra
    assert straight["ledger"] == preempted["ledger"]
    assert straight["ledger"]["clients_seen"] >= 5
    # The checkpoint the retry restored from carries the shard set.
    manifests = sorted(tdir.glob("ckpt_*/ledger/manifest.json"))
    assert manifests, "checkpoints must embed the ledger shard set"
    num_ok, errors = validate_ledger_checkpoint(manifests[-1].parent)
    assert errors == []


# ---------------------------------------------------------------------------
# fleet surfaces: watchdog rules, flight-recorder digests, CSV sink
# ---------------------------------------------------------------------------


def test_watchdog_reputation_collapse_and_flagger_churn():
    from blades_tpu.obs.watchdog import Watchdog

    wd = Watchdog()
    names = {r.name for r in wd.rules}
    assert {"reputation_collapse", "flagger_churn"} <= names

    # Warm the rolling medians with healthy rounds.
    steady = [{"training_iteration": i, "train_loss": 0.5,
               "reputation_p50": 0.9, "flagged_churn": 2}
              for i in range(1, 6)]
    for row in steady:
        assert wd.observe(row) == []
    # Median reputation halves in one round: collapse fires.
    events = wd.observe({"training_iteration": 6, "train_loss": 0.5,
                         "reputation_p50": 0.4, "flagged_churn": 2})
    assert [e.rule for e in events] == ["reputation_collapse"]
    assert "reputation_p50" in events[0].message
    # Churn spikes past 4x the rolling median: thrash alarm.
    events = wd.observe({"training_iteration": 7, "train_loss": 0.5,
                         "reputation_p50": 0.9, "flagged_churn": 9})
    assert [e.rule for e in events] == ["flagger_churn"]

    # Ledger off -> fields absent -> both rules inert.
    wd2 = Watchdog()
    for i in range(1, 10):
        assert wd2.observe({"training_iteration": i,
                            "train_loss": 0.5}) == []


def test_watchdog_warm_replays_rows_with_ledger_fields():
    """Kill-and-resume: warm() rebuilds the new rules' rolling windows
    from on-disk rows WITHOUT re-firing events, and the warmed state
    matches a straight-through observer's."""
    from blades_tpu.obs.watchdog import Watchdog

    rows = [{"training_iteration": i, "train_loss": 0.5,
             "reputation_p50": 0.9, "flagged_churn": 2,
             "watchdog_events": []}
            for i in range(1, 6)]
    rows[2]["watchdog_events"] = [
        {"rule": "flagger_churn", "kind": "spike",
         "field": "flagged_churn", "round": 3, "value": 9.0,
         "limit": 8.0, "message": "churn spike"}]
    warmed = Watchdog()
    warmed.warm(rows)
    # The durable event log came from the rows, not re-evaluation.
    assert [e.rule for e in warmed.events] == ["flagger_churn"]
    straight = Watchdog()
    for row in rows:
        straight.observe(row)
    nxt = {"training_iteration": 6, "train_loss": 0.5,
           "reputation_p50": 0.4, "flagged_churn": 2}
    assert ([e.rule for e in warmed.observe(nxt)]
            == [e.rule for e in straight.observe(nxt)]
            == ["reputation_collapse"])


def test_flightrec_dump_carries_ledger_digest(tmp_path):
    from blades_tpu.obs.flightrec import FlightRecorder, validate_flightrec

    fr = FlightRecorder(tmp_path / "flightrec.json", capacity=4,
                        trial="t", algo="FEDAVG", config={"seed": 3})
    fr.ledger = _fold_cohorts(make_ledger("resident", N))
    for i in range(1, 4):
        fr.record({"training_iteration": i, "train_loss": 0.5,
                   "suspected_fraction": 0.25, "flagged_churn": 1})
    fr.dump({"kind": "exception", "round": 3})
    dump = json.loads((tmp_path / "flightrec.json").read_text())
    assert dump["ledger"]["crc32"] == fr.ledger.digest()["crc32"]
    assert dump["ledger"]["clients_seen"] == 4
    # The digested rows keep the ledger's scalar fleet fields.
    assert dump["rounds"][-1]["suspected_fraction"] == 0.25
    _, errors = validate_flightrec(tmp_path / "flightrec.json")
    assert errors == []

    # A torn ledger must not lose the dump: the digest degrades to an
    # error marker, the dump itself still lands.
    class _Torn:
        def digest(self):
            raise LedgerError("torn mid-read")

    fr.ledger = _Torn()
    dump = fr.as_dump({"kind": "preemption"})
    assert "LedgerError" in dump["ledger"]["error"]


def test_csv_sink_skips_list_typed_ledger_field(tmp_path):
    """The CSV header carries the scalar ledger fields and — by the
    list-filter construction — never the list-typed suspects column."""
    import csv

    from blades_tpu.obs.metrics import _CSV_COLUMNS, CsvSink

    assert "suspected_fraction" in _CSV_COLUMNS
    assert "flagged_churn" in _CSV_COLUMNS
    assert "reputation_p50" in _CSV_COLUMNS
    assert "ledger_clients_seen" in _CSV_COLUMNS
    assert "ledger_top_suspects" not in _CSV_COLUMNS
    assert "watchdog_events" not in _CSV_COLUMNS

    path = tmp_path / "progress.csv"
    sink = CsvSink(path)
    sink.emit({"trial": "t", "training_iteration": 1, "train_loss": 0.5,
               "suspected_fraction": 0.25, "flagged_churn": 3,
               "reputation_p50": 0.9, "ledger_clients_seen": 8,
               "ledger_top_suspects": [2, 1, 0],
               "watchdog_events": [{"rule": "flagger_churn"}]})
    sink.close()
    with open(path, newline="") as f:
        header, row = list(csv.reader(f))
    assert "ledger_top_suspects" not in header
    got = dict(zip(header, row))
    assert got["suspected_fraction"] == "0.25"
    assert got["flagged_churn"] == "3"
    assert got["ledger_clients_seen"] == "8"
