"""d-sharded (all-to-all) giant-federation round tests on the 8-device
CPU mesh — exactness vs the all_gather formulation (SURVEY.md §7.3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.adversaries import get_adversary, make_malicious_mask
from blades_tpu.core import FedRound, Server, TaskSpec
from blades_tpu.parallel import make_mesh, shard_federation, shard_map_step
from blades_tpu.parallel.dsharded import dsharded_step, psum_pairwise_sq_dists

N = 16
F = 4


def make_fr(aggregator, adversary=None):
    task = TaskSpec(model="mlp", lr=0.1, input_shape=(28, 28, 1)).build()
    server = Server.from_config(aggregator=aggregator, num_byzantine=F, lr=1.0)
    adv = get_adversary(adversary, num_clients=N, num_byzantine=F) if adversary else None
    return FedRound(task=task, server=server, adversary=adv, batch_size=8)


@pytest.fixture(scope="module")
def data():
    from blades_tpu.data import DatasetCatalog

    ds = DatasetCatalog.get_dataset("mnist", num_clients=N)
    return (
        jnp.array(ds.train.x), jnp.array(ds.train.y), jnp.array(ds.train.lengths),
        make_malicious_mask(N, F),
    )


def test_psum_pairwise_matches_dense():
    mesh = make_mesh()
    rows = jax.random.normal(jax.random.PRNGKey(0), (6, 64))

    from functools import partial

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    @partial(shard_map, mesh=mesh, in_specs=(P(None, "clients"),),
             out_specs=P(), check_vma=False)
    def sharded(rows_shard):
        return psum_pairwise_sq_dists(rows_shard)

    d2 = sharded(rows)
    dense = ((rows[:, None, :] - rows[None, :, :]) ** 2).sum(-1)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(dense), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("aggregator", ["Mean", "Median", "Trimmedmean",
                                        "Multikrum", "GeoMed"])
def test_dsharded_matches_gather_path(data, aggregator):
    x, y, ln, mal = data
    mesh = make_mesh()
    fr = make_fr(aggregator, adversary="ALIE")
    key = jax.random.PRNGKey(42)

    st_a = fr.init(jax.random.PRNGKey(0), N)
    st_a, (x_a, y_a, ln_a, mal_a) = shard_federation(mesh, st_a, (x, y, ln, mal))
    step_a = shard_map_step(fr, mesh)
    st_a, m_a = step_a(st_a, x_a, y_a, ln_a, mal_a, key)

    st_b = fr.init(jax.random.PRNGKey(0), N)
    st_b, (x_b, y_b, ln_b, mal_b) = shard_federation(mesh, st_b, (x, y, ln, mal))
    step_b = dsharded_step(fr, mesh)
    st_b, m_b = step_b(st_b, x_b, y_b, ln_b, mal_b, key)

    from blades_tpu.utils.tree import ravel_fn

    ravel, _, _ = ravel_fn(st_a.server.params)
    # Same keys -> same local training; aggregation math must agree up to
    # float reassociation (GeoMed: fixed iters vs early-stop tolerance).
    tol = 2e-3 if aggregator == "GeoMed" else 2e-5
    np.testing.assert_allclose(
        np.asarray(ravel(st_a.server.params)),
        np.asarray(ravel(st_b.server.params)), atol=tol, rtol=1e-3,
    )
    np.testing.assert_allclose(float(m_a["train_loss"]), float(m_b["train_loss"]),
                               rtol=1e-5)


def test_dsharded_trains_under_attack(data):
    x, y, ln, mal = data
    mesh = make_mesh()
    fr = make_fr("Median", adversary="IPM")
    st = fr.init(jax.random.PRNGKey(0), N)
    st, (x, y, ln, mal) = shard_federation(mesh, st, (x, y, ln, mal))
    step = dsharded_step(fr, mesh)
    losses = []
    for r in range(10):
        st, m = step(st, x, y, ln, mal, jax.random.fold_in(jax.random.PRNGKey(5), r))
        losses.append(float(m["train_loss"]))
    assert losses[-1] < losses[0]
    assert int(m["round"]) == 10


def test_dsharded_rejects_geometry_adversaries(data):
    mesh = make_mesh()
    fr = make_fr("Median", adversary="MinMax")
    with pytest.raises(NotImplementedError, match="geometry"):
        dsharded_step(fr, mesh)


def test_dsharded_rejects_unsupported_server(data):
    mesh = make_mesh()
    task = TaskSpec(model="mlp", input_shape=(28, 28, 1)).build()
    server = Server.from_config(aggregator="Median", lr=1.0, momentum=0.9)
    fr = FedRound(task=task, server=server)
    with pytest.raises(NotImplementedError, match="plain-SGD"):
        dsharded_step(fr, mesh)
