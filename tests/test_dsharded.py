"""d-sharded (all-to-all) giant-federation round tests on the 8-device
CPU mesh — exactness vs the all_gather formulation (SURVEY.md §7.3).

The d-sharded path must cover the FULL aggregator suite (all 10) and the
full adversary suite: every combination here compares end-round server
params against :func:`shard_map_step` (same keys -> same local training,
so any difference is aggregation/forging math).

Tier-2 (``slow``): the 33 aggregator x adversary combinations each
compile an 8-virtual-device shard_map program — minutes of wall clock on
a 2-core CPU host, far past the tier-1 budget.  Tier-1 keeps a d-sharded
end-to-end signal via ``test_faults.py``'s d-sharded health-check round.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.adversaries import get_adversary, make_malicious_mask
from blades_tpu.algorithms import get_algorithm_class
from blades_tpu.core import FedRound, Server, TaskSpec
from blades_tpu.parallel import make_mesh, shard_federation, shard_map_step
from blades_tpu.ops import layout as L
from blades_tpu.parallel.dsharded import dsharded_step
from blades_tpu.utils.tree import ravel_fn

pytestmark = pytest.mark.slow

N = 16
F = 4

ALL_AGGREGATORS = [
    "Mean", "Median", "Trimmedmean", "GeoMed", "DnC", "Multikrum",
    "Centeredclipping", "Signguard", "Clippedclustering", "FLTrust",
]


def make_fr(aggregator, adversary=None, server_kwargs=None):
    task = TaskSpec(model="mlp", lr=0.1, input_shape=(28, 28, 1)).build()
    server = Server.from_config(aggregator=aggregator, num_byzantine=F, lr=1.0,
                                **(server_kwargs or {}))
    adv = get_adversary(adversary, num_clients=N, num_byzantine=F) if adversary else None
    fr = FedRound(task=task, server=server, adversary=adv, batch_size=8)
    if aggregator == "FLTrust":
        rng = np.random.default_rng(7)
        tx = jnp.asarray(rng.normal(size=(32, 28, 28, 1)), jnp.float32)
        ty = jnp.asarray(rng.integers(0, 10, size=(32,)), jnp.int32)
        fr = dataclasses.replace(fr, trusted_data=(tx, ty))
    return fr


@pytest.fixture(scope="module")
def data():
    from blades_tpu.data import DatasetCatalog

    ds = DatasetCatalog.get_dataset("mnist", num_clients=N)
    return (
        jnp.array(ds.train.x), jnp.array(ds.train.y), jnp.array(ds.train.lengths),
        make_malicious_mask(N, F),
    )


def run_both_paths(fr, data, key=42, rounds=1):
    x, y, ln, mal = data
    mesh = make_mesh()
    results = []
    for step_fn in (shard_map_step, dsharded_step):
        st = fr.init(jax.random.PRNGKey(0), N)
        st, (xs, ys, lns, mals) = shard_federation(mesh, st, (x, y, ln, mal))
        step = step_fn(fr, mesh)
        for r in range(rounds):
            st, m = step(st, xs, ys, lns, mals,
                         jax.random.fold_in(jax.random.PRNGKey(key), r))
        results.append((st, m))
    return results


def assert_paths_match(fr, data, tol=2e-5, rounds=1):
    (st_a, m_a), (st_b, m_b) = run_both_paths(fr, data, rounds=rounds)
    ravel, _, _ = ravel_fn(st_a.server.params)
    np.testing.assert_allclose(
        np.asarray(ravel(st_a.server.params)),
        np.asarray(ravel(st_b.server.params)), atol=tol, rtol=1e-3,
    )
    np.testing.assert_allclose(float(m_a["train_loss"]), float(m_b["train_loss"]),
                               rtol=1e-5)


def test_psum_pairwise_matches_dense():
    mesh = make_mesh()
    rows = jax.random.normal(jax.random.PRNGKey(0), (6, 64))

    from functools import partial

    from jax.sharding import PartitionSpec as P

    from blades_tpu.parallel.compat import shard_map

    shard = L.ShardInfo(axis="clients", num_shards=8, global_d=64, width=8)

    @partial(shard_map, mesh=mesh, in_specs=(P(None, "clients"),),
             out_specs=P(), check_vma=False)
    def sharded(rows_shard):
        return L.pairwise_sq_dists(rows_shard, shard)

    d2 = sharded(rows)
    dense = ((rows[:, None, :] - rows[None, :, :]) ** 2).sum(-1)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(dense), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("aggregator", ALL_AGGREGATORS)
def test_dsharded_matches_gather_path(data, aggregator):
    fr = make_fr(aggregator, adversary="ALIE")
    # Same keys -> same local training; aggregation math must agree up to
    # float reassociation (GeoMed: fixed iters vs early-stop tolerance).
    tol = 2e-3 if aggregator == "GeoMed" else 2e-5
    assert_paths_match(fr, data, tol=tol)


@pytest.mark.parametrize("aggregator", ["Centeredclipping", "Clippedclustering"])
def test_dsharded_stateful_aggregator_state_matches(data, aggregator):
    """Multi-round: the threaded aggregator state (momentum / norm history)
    must evolve identically on both paths — and stays layout-compatible
    (replicated), so checkpoints are interchangeable."""
    fr = make_fr(aggregator, adversary="IPM")
    (st_a, _), (st_b, _) = run_both_paths(fr, data, rounds=3)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=1e-3
        ),
        st_a.server.agg_state, st_b.server.agg_state,
    )


# The VERDICT r1 landmine: SignGuard-evading attacks negate the GLOBAL
# first half of the coordinate axis — per-shard local negation would be a
# different attack.  These combinations force that code path.
@pytest.mark.parametrize("adversary,aggregator", [
    ("ALIE", "Signguard"),          # _negate_first_half under sharding
    ("MinMax", "Signguard"),        # psum'd distances + negate
    ("MinMax", "Median"),           # psum'd distances, no negate
    ("Adaptive", "Trimmedmean"),    # global-width uniform draw, sliced
    ("SignGuard", "Signguard"),     # psum'd sign census + global perm
    ("Attackclippedclustering", "Clippedclustering"),  # psum'd cosine geometry
    ("IPM", "Multikrum"),
])
def test_dsharded_adversaries_match_gather_path(data, adversary, aggregator):
    fr = make_fr(aggregator, adversary=adversary)
    assert_paths_match(fr, data, tol=5e-5)


def test_dsharded_noise_adversary_runs(data):
    """Noise draws are i.i.d. per layout (keys fold the shard index), so
    paths are not bit-equal — both must still train finite."""
    fr = make_fr("Median", adversary="Noise")
    (_, m_a), (_, m_b) = run_both_paths(fr, data)
    assert np.isfinite(float(m_a["train_loss"]))
    assert np.isfinite(float(m_b["train_loss"]))


def test_dsharded_full_server_optimizer_matches(data):
    """momentum + weight decay + LR schedule: the d-sharded server step is
    the identical replicated optax program (round-1 restricted this path
    to plain SGD)."""
    fr = make_fr("Median", adversary="ALIE", server_kwargs=dict(
        momentum=0.9, weight_decay=1e-4,
        lr_schedule_points=[[0, 1.0], [2, 0.1]],
    ))
    assert_paths_match(fr, data, rounds=3, tol=5e-5)


def test_dsharded_multi_round_dispatch_matches_sequential(data):
    """rounds_per_dispatch on the d-sharded path (VERDICT r4 weak #5): k
    lax.scan-chained shard_map rounds must equal k sequential
    dsharded_step calls bit-for-bit — same split(key, k) stream as every
    other multi path."""
    from blades_tpu.parallel.dsharded import dsharded_multi_step

    x, y, ln, mal = data
    mesh = make_mesh()
    fr = make_fr("Median", adversary="ALIE")
    key = jax.random.PRNGKey(13)
    k = 3

    st_a = fr.init(jax.random.PRNGKey(0), N)
    st_a, (xs, ys, lns, mals) = shard_federation(mesh, st_a, (x, y, ln, mal))
    multi = dsharded_multi_step(fr, mesh, k)
    st_a, m_a = multi(st_a, xs, ys, lns, mals, key)
    assert m_a["train_loss"].shape == (k,)

    st_b = fr.init(jax.random.PRNGKey(0), N)
    st_b, _ = shard_federation(mesh, st_b, (x, y, ln, mal))
    step = dsharded_step(fr, mesh)
    for r, kr in enumerate(jax.random.split(key, k)):
        st_b, m_b = step(st_b, xs, ys, lns, mals, kr)
        np.testing.assert_array_equal(
            np.asarray(m_a["train_loss"][r]), np.asarray(m_b["train_loss"]))

    for a, b in zip(jax.tree.leaves(st_a.server.params),
                    jax.tree.leaves(st_b.server.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_elision_client_order_layout():
    from blades_tpu.parallel.dsharded import elision_client_order

    # Even split: every chip [1 malicious | 1 benign].
    order = elision_client_order(16, 8, 8)
    mal = np.arange(16) < 8  # canonical prefix mask
    m = mal[order].reshape(8, 2)
    assert m[:, 0].all() and not m[:, 1:].any()
    assert sorted(order.tolist()) == list(range(16))

    # Remainder: f=10 over 8 chips -> fl=1 everywhere, the 2 leftover
    # malicious clients train in the first chips' tails.
    order = elision_client_order(32, 10, 8)
    m = (np.arange(32) < 10)[order].reshape(8, 4)
    assert m[:, 0].all()              # every elided prefix is malicious
    assert m[:, 1:].sum() == 2        # the remainder trains in tails
    assert sorted(order.tolist()) == list(range(32))

    with pytest.raises(ValueError, match="divide"):
        elision_client_order(17, 8, 8)


@pytest.mark.parametrize("aggregator,adversary", [
    ("Median", "ALIE"),
    ("GeoMed", "IPM"),
    ("Signguard", "MinMax"),
])
def test_dsharded_elision_is_exact(data, aggregator, adversary):
    """Skipping the dead malicious-lane training on the strided layout
    must reproduce the full d-sharded round bit-for-bit: forged rows
    come from benign statistics only and replace whatever the malicious
    lanes trained.  F=8 over the 8-chip mesh -> one elided lane per
    chip (f < n_dev would elide nothing)."""
    from blades_tpu.parallel.dsharded import elision_client_order

    F = 8
    x, y, ln, _ = data
    order = jnp.asarray(elision_client_order(N, F, 8))
    mal = (jnp.arange(N) < F)[order]
    x, y, ln = x[order], y[order], ln[order]
    mesh = make_mesh()
    fr = make_fr(aggregator, adversary=adversary)
    key = jax.random.PRNGKey(23)

    results = []
    for prefix in (None, F):
        st = fr.init(jax.random.PRNGKey(0), N)
        st, (xs, ys, lns, mals) = shard_federation(mesh, st, (x, y, ln, mal))
        step = dsharded_step(fr, mesh, malicious_prefix=prefix)
        for r in range(2):
            st, m = step(st, xs, ys, lns, mals, jax.random.fold_in(key, r))
        results.append((st, m))
    (st_a, m_a), (st_b, m_b) = results
    for a, b in zip(jax.tree.leaves(st_a.server.params),
                    jax.tree.leaves(st_b.server.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in ("train_loss", "agg_norm", "update_norm_mean"):
        np.testing.assert_array_equal(np.asarray(m_a[k]), np.asarray(m_b[k]))
    # Elision telemetry (VERDICT item 6): floor(F/n_dev) lanes elided on
    # each of the 8 chips; the non-elided round carries no such key.
    assert int(m_b["elided_lanes"]) == (F // 8) * 8
    assert "elided_lanes" not in m_a


def test_dsharded_elision_ignored_for_training_attacks(data):
    """SignFlip trains for real — the prefix hint must not skip it."""
    from blades_tpu.parallel.dsharded import _build_dsharded_body

    fr = make_fr("Mean", adversary="SignFlip")
    body = _build_dsharded_body(fr, make_mesh(), malicious_prefix=8)
    assert body.f_local == 0  # gate: no update forge -> no elision
    # The forging counterpart DOES elide at the same prefix.
    fr2 = make_fr("Median", adversary="ALIE")
    assert _build_dsharded_body(fr2, make_mesh(),
                                malicious_prefix=8).f_local == 1


def test_dsharded_elision_validates_mask(data):
    x, y, ln, _ = data
    mesh = make_mesh()
    fr = make_fr("Median", adversary="ALIE")
    st = fr.init(jax.random.PRNGKey(0), N)
    bad_mask = jnp.arange(N) < 8  # contiguous prefix, NOT strided
    st, (xs, ys, lns, mals) = shard_federation(mesh, st, (x, y, ln, bad_mask))
    step = dsharded_step(fr, mesh, malicious_prefix=8)
    with pytest.raises(ValueError, match="elision"):
        step(st, xs, ys, lns, mals, jax.random.PRNGKey(1))


def test_dsharded_elision_composes_with_multi_dispatch(data):
    """malicious_prefix + rounds_per_dispatch together: the scanned
    elided rounds must equal sequential elided steps bit-for-bit."""
    from blades_tpu.parallel.dsharded import (dsharded_multi_step,
                                              elision_client_order)

    F = 8
    x, y, ln, _ = data
    order = jnp.asarray(elision_client_order(N, F, 8))
    mal = (jnp.arange(N) < F)[order]
    x, y, ln = x[order], y[order], ln[order]
    mesh = make_mesh()
    fr = make_fr("Median", adversary="ALIE")
    key = jax.random.PRNGKey(29)
    k = 2

    st_a = fr.init(jax.random.PRNGKey(0), N)
    st_a, (xs, ys, lns, mals) = shard_federation(mesh, st_a, (x, y, ln, mal))
    multi = dsharded_multi_step(fr, mesh, k, malicious_prefix=F)
    st_a, m_a = multi(st_a, xs, ys, lns, mals, key)

    st_b = fr.init(jax.random.PRNGKey(0), N)
    st_b, _ = shard_federation(mesh, st_b, (x, y, ln, mal))
    step = dsharded_step(fr, mesh, malicious_prefix=F)
    for kr in jax.random.split(key, k):
        st_b, _ = step(st_b, xs, ys, lns, mals, kr)
    for a, b in zip(jax.tree.leaves(st_a.server.params),
                    jax.tree.leaves(st_b.server.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dsharded_elision_through_config():
    """The Fedavg driver auto-applies the strided layout + elision for a
    forging adversary on execution='dsharded'."""
    _, cfg = get_algorithm_class("FEDAVG", return_config=True)
    cfg.update_from_dict({
        "dataset_config": {"type": "mnist", "num_clients": 16, "train_bs": 8},
        "global_model": "mlp",
        "evaluation_interval": 2,
        "execution": "dsharded",
        "num_malicious_clients": 8,
        "adversary_config": {"type": "ALIE"},
        "server_config": {"lr": 1.0, "aggregator": {"type": "Median"}},
    })
    cfg.resources(num_devices=8)
    algo = cfg.build()
    # The mask is strided per chip: [1 malicious | 1 benign] x 8.
    m = np.asarray(algo.malicious).reshape(8, 2)
    assert m[:, 0].all() and not m[:, 1].any()
    r = algo.train()
    assert np.isfinite(r["train_loss"])
    assert 0.0 <= algo.evaluate()["test_acc"] <= 1.0


def test_checkpoint_realigns_client_state_across_layouts(tmp_path):
    """A checkpoint saved in natural client order (dense run) resumed
    on the d-sharded elision layout must remap per-client optimizer
    state to the permuted rows — not silently pair client i's momentum
    with client j's data."""
    from blades_tpu.parallel.dsharded import elision_client_order

    def build(execution, num_devices=None):
        _, cfg = get_algorithm_class("FEDAVG", return_config=True)
        cfg.update_from_dict({
            "dataset_config": {"type": "mnist", "num_clients": 16,
                               "train_bs": 8},
            "global_model": "mlp",
            "evaluation_interval": 100,
            "execution": execution,
            "num_malicious_clients": 8,
            "adversary_config": {"type": "ALIE"},
            "client_config": {"lr": 0.1, "momentum": 0.9},
            "server_config": {"lr": 1.0, "aggregator": {"type": "Median"}},
        })
        if num_devices:
            cfg.resources(num_devices=num_devices)
        return cfg.build()

    a = build("dense")
    a.train()  # client momentum becomes client-distinct
    ckpt = a.save_checkpoint(str(tmp_path))

    b = build("dsharded", num_devices=8)
    b.load_checkpoint(ckpt)
    order = elision_client_order(16, 8, 8)
    for src, dst in zip(jax.tree.leaves(a.state.client_opt),
                        jax.tree.leaves(b.state.client_opt)):
        np.testing.assert_array_equal(np.asarray(src)[order],
                                      np.asarray(dst))
    # And the realigned state trains on.
    r = b.train()
    assert np.isfinite(r["train_loss"])


def test_dsharded_trains_under_attack(data):
    x, y, ln, mal = data
    mesh = make_mesh()
    fr = make_fr("Median", adversary="IPM")
    st = fr.init(jax.random.PRNGKey(0), N)
    st, (x, y, ln, mal) = shard_federation(mesh, st, (x, y, ln, mal))
    step = dsharded_step(fr, mesh)
    losses = []
    for r in range(10):
        st, m = step(st, x, y, ln, mal, jax.random.fold_in(jax.random.PRNGKey(5), r))
        losses.append(float(m["train_loss"]))
    assert losses[-1] < losses[0]
    assert int(m["round"]) == 10
