"""d-sharded (all-to-all) giant-federation round tests on the 8-device
CPU mesh — exactness vs the all_gather formulation (SURVEY.md §7.3).

The d-sharded path must cover the FULL aggregator suite (all 10) and the
full adversary suite: every combination here compares end-round server
params against :func:`shard_map_step` (same keys -> same local training,
so any difference is aggregation/forging math).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.adversaries import get_adversary, make_malicious_mask
from blades_tpu.core import FedRound, Server, TaskSpec
from blades_tpu.parallel import make_mesh, shard_federation, shard_map_step
from blades_tpu.ops import layout as L
from blades_tpu.parallel.dsharded import dsharded_step
from blades_tpu.utils.tree import ravel_fn

N = 16
F = 4

ALL_AGGREGATORS = [
    "Mean", "Median", "Trimmedmean", "GeoMed", "DnC", "Multikrum",
    "Centeredclipping", "Signguard", "Clippedclustering", "FLTrust",
]


def make_fr(aggregator, adversary=None, server_kwargs=None):
    task = TaskSpec(model="mlp", lr=0.1, input_shape=(28, 28, 1)).build()
    server = Server.from_config(aggregator=aggregator, num_byzantine=F, lr=1.0,
                                **(server_kwargs or {}))
    adv = get_adversary(adversary, num_clients=N, num_byzantine=F) if adversary else None
    fr = FedRound(task=task, server=server, adversary=adv, batch_size=8)
    if aggregator == "FLTrust":
        rng = np.random.default_rng(7)
        tx = jnp.asarray(rng.normal(size=(32, 28, 28, 1)), jnp.float32)
        ty = jnp.asarray(rng.integers(0, 10, size=(32,)), jnp.int32)
        fr = dataclasses.replace(fr, trusted_data=(tx, ty))
    return fr


@pytest.fixture(scope="module")
def data():
    from blades_tpu.data import DatasetCatalog

    ds = DatasetCatalog.get_dataset("mnist", num_clients=N)
    return (
        jnp.array(ds.train.x), jnp.array(ds.train.y), jnp.array(ds.train.lengths),
        make_malicious_mask(N, F),
    )


def run_both_paths(fr, data, key=42, rounds=1):
    x, y, ln, mal = data
    mesh = make_mesh()
    results = []
    for step_fn in (shard_map_step, dsharded_step):
        st = fr.init(jax.random.PRNGKey(0), N)
        st, (xs, ys, lns, mals) = shard_federation(mesh, st, (x, y, ln, mal))
        step = step_fn(fr, mesh)
        for r in range(rounds):
            st, m = step(st, xs, ys, lns, mals,
                         jax.random.fold_in(jax.random.PRNGKey(key), r))
        results.append((st, m))
    return results


def assert_paths_match(fr, data, tol=2e-5, rounds=1):
    (st_a, m_a), (st_b, m_b) = run_both_paths(fr, data, rounds=rounds)
    ravel, _, _ = ravel_fn(st_a.server.params)
    np.testing.assert_allclose(
        np.asarray(ravel(st_a.server.params)),
        np.asarray(ravel(st_b.server.params)), atol=tol, rtol=1e-3,
    )
    np.testing.assert_allclose(float(m_a["train_loss"]), float(m_b["train_loss"]),
                               rtol=1e-5)


def test_psum_pairwise_matches_dense():
    mesh = make_mesh()
    rows = jax.random.normal(jax.random.PRNGKey(0), (6, 64))

    from functools import partial

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    shard = L.ShardInfo(axis="clients", num_shards=8, global_d=64, width=8)

    @partial(shard_map, mesh=mesh, in_specs=(P(None, "clients"),),
             out_specs=P(), check_vma=False)
    def sharded(rows_shard):
        return L.pairwise_sq_dists(rows_shard, shard)

    d2 = sharded(rows)
    dense = ((rows[:, None, :] - rows[None, :, :]) ** 2).sum(-1)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(dense), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("aggregator", ALL_AGGREGATORS)
def test_dsharded_matches_gather_path(data, aggregator):
    fr = make_fr(aggregator, adversary="ALIE")
    # Same keys -> same local training; aggregation math must agree up to
    # float reassociation (GeoMed: fixed iters vs early-stop tolerance).
    tol = 2e-3 if aggregator == "GeoMed" else 2e-5
    assert_paths_match(fr, data, tol=tol)


@pytest.mark.parametrize("aggregator", ["Centeredclipping", "Clippedclustering"])
def test_dsharded_stateful_aggregator_state_matches(data, aggregator):
    """Multi-round: the threaded aggregator state (momentum / norm history)
    must evolve identically on both paths — and stays layout-compatible
    (replicated), so checkpoints are interchangeable."""
    fr = make_fr(aggregator, adversary="IPM")
    (st_a, _), (st_b, _) = run_both_paths(fr, data, rounds=3)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=1e-3
        ),
        st_a.server.agg_state, st_b.server.agg_state,
    )


# The VERDICT r1 landmine: SignGuard-evading attacks negate the GLOBAL
# first half of the coordinate axis — per-shard local negation would be a
# different attack.  These combinations force that code path.
@pytest.mark.parametrize("adversary,aggregator", [
    ("ALIE", "Signguard"),          # _negate_first_half under sharding
    ("MinMax", "Signguard"),        # psum'd distances + negate
    ("MinMax", "Median"),           # psum'd distances, no negate
    ("Adaptive", "Trimmedmean"),    # global-width uniform draw, sliced
    ("SignGuard", "Signguard"),     # psum'd sign census + global perm
    ("Attackclippedclustering", "Clippedclustering"),  # psum'd cosine geometry
    ("IPM", "Multikrum"),
])
def test_dsharded_adversaries_match_gather_path(data, adversary, aggregator):
    fr = make_fr(aggregator, adversary=adversary)
    assert_paths_match(fr, data, tol=5e-5)


def test_dsharded_noise_adversary_runs(data):
    """Noise draws are i.i.d. per layout (keys fold the shard index), so
    paths are not bit-equal — both must still train finite."""
    fr = make_fr("Median", adversary="Noise")
    (_, m_a), (_, m_b) = run_both_paths(fr, data)
    assert np.isfinite(float(m_a["train_loss"]))
    assert np.isfinite(float(m_b["train_loss"]))


def test_dsharded_full_server_optimizer_matches(data):
    """momentum + weight decay + LR schedule: the d-sharded server step is
    the identical replicated optax program (round-1 restricted this path
    to plain SGD)."""
    fr = make_fr("Median", adversary="ALIE", server_kwargs=dict(
        momentum=0.9, weight_decay=1e-4,
        lr_schedule_points=[[0, 1.0], [2, 0.1]],
    ))
    assert_paths_match(fr, data, rounds=3, tol=5e-5)


def test_dsharded_multi_round_dispatch_matches_sequential(data):
    """rounds_per_dispatch on the d-sharded path (VERDICT r4 weak #5): k
    lax.scan-chained shard_map rounds must equal k sequential
    dsharded_step calls bit-for-bit — same split(key, k) stream as every
    other multi path."""
    from blades_tpu.parallel.dsharded import dsharded_multi_step

    x, y, ln, mal = data
    mesh = make_mesh()
    fr = make_fr("Median", adversary="ALIE")
    key = jax.random.PRNGKey(13)
    k = 3

    st_a = fr.init(jax.random.PRNGKey(0), N)
    st_a, (xs, ys, lns, mals) = shard_federation(mesh, st_a, (x, y, ln, mal))
    multi = dsharded_multi_step(fr, mesh, k)
    st_a, m_a = multi(st_a, xs, ys, lns, mals, key)
    assert m_a["train_loss"].shape == (k,)

    st_b = fr.init(jax.random.PRNGKey(0), N)
    st_b, _ = shard_federation(mesh, st_b, (x, y, ln, mal))
    step = dsharded_step(fr, mesh)
    for r, kr in enumerate(jax.random.split(key, k)):
        st_b, m_b = step(st_b, xs, ys, lns, mals, kr)
        np.testing.assert_array_equal(
            np.asarray(m_a["train_loss"][r]), np.asarray(m_b["train_loss"]))

    for a, b in zip(jax.tree.leaves(st_a.server.params),
                    jax.tree.leaves(st_b.server.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dsharded_trains_under_attack(data):
    x, y, ln, mal = data
    mesh = make_mesh()
    fr = make_fr("Median", adversary="IPM")
    st = fr.init(jax.random.PRNGKey(0), N)
    st, (x, y, ln, mal) = shard_federation(mesh, st, (x, y, ln, mal))
    step = dsharded_step(fr, mesh)
    losses = []
    for r in range(10):
        st, m = step(st, x, y, ln, mal, jax.random.fold_in(jax.random.PRNGKey(5), r))
        losses.append(float(m["train_loss"]))
    assert losses[-1] < losses[0]
    assert int(m["round"]) == 10
