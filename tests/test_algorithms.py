"""Algorithm-layer tests (model: blades/algorithms/fedavg/tests/
test_fedavg.py — full config.build() + train() loops on tiny fixtures)."""

import json
from pathlib import Path

import jax
import numpy as np
import pytest

from blades_tpu.algorithms import Fedavg, FedavgConfig, FedavgDPConfig, get_algorithm_class


def tiny_config(**overrides):
    cfg = (
        FedavgConfig()
        .data(dataset="mnist", num_clients=8, seed=7)
        .training(global_model="mlp", server_lr=1.0, train_batch_size=16,
                  aggregator={"type": "Mean"})
        .client(lr=0.1)
        .evaluation(evaluation_interval=5)
    )
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


def test_config_fluent_build_and_freeze():
    cfg = tiny_config()
    algo = cfg.build()
    assert isinstance(algo, Fedavg)
    with pytest.raises(RuntimeError, match="frozen"):
        cfg.data(num_clients=10)


def test_config_copy_retarget_reinfers_dataset_fields():
    """validate() infers input_shape/num_classes from the dataset; a
    copy() retargeted at another dataset must re-infer instead of
    keeping the stale values (VERDICT r1 weak #8), while explicit user
    settings survive a retarget."""
    from blades_tpu.algorithms import FedavgConfig

    cfg = FedavgConfig().data(dataset="cifar100", num_clients=4)
    cfg.validate()
    assert cfg.input_shape == (32, 32, 3)
    assert cfg.num_classes == 100
    c2 = cfg.copy().data(dataset="mnist")
    c2.validate()
    assert c2.input_shape == (28, 28, 1)
    assert c2.num_classes == 10
    # Explicit settings are kept.
    c3 = FedavgConfig().training(input_shape=(8, 8, 3), num_classes=7)
    c3.data(dataset="mnist", num_clients=4)
    c3.validate()
    assert c3.input_shape == (8, 8, 3)
    assert c3.num_classes == 7
    # The dict-merge path retargets identically.
    c4 = cfg.copy().update_from_dict({"dataset": "mnist"})
    c4.validate()
    assert c4.input_shape == (28, 28, 1)
    assert c4.num_classes == 10
    # A frozen config is not corrupted by the (rejected) retarget.
    cfg.freeze()
    with pytest.raises(RuntimeError, match="frozen"):
        cfg.data(dataset="mnist")
    assert cfg.input_shape == (32, 32, 3)
    assert cfg.num_classes == 100


def test_config_validation_rejects_majority_byzantine():
    cfg = tiny_config()
    cfg.num_malicious_clients = 5  # > 8 // 2
    cfg.adversary_config = {"type": "IPM"}
    with pytest.raises(ValueError, match="majority"):
        cfg.build()


def test_config_validation_requires_adversary_config():
    cfg = tiny_config()
    cfg.num_malicious_clients = 2
    with pytest.raises(ValueError, match="adversary_config"):
        cfg.build()


def test_config_dict_shim_and_update_from_dict():
    cfg = FedavgConfig()
    cfg.update_from_dict({
        "dataset_config": {"type": "mnist", "num_clients": 12, "train_bs": 8},
        "client_config": {"lr": 0.5, "num_batch_per_round": 3},
        "server_config": {"lr": 0.2, "aggregator": {"type": "Median"}},
        "num_malicious_clients": 2,
        "adversary_config": {"type": "ALIE"},
    })
    assert cfg["num_clients"] == 12
    assert cfg.get("client_lr") == 0.5
    assert cfg.num_batch_per_round == 3
    assert dict(cfg.items())["server_lr"] == 0.2
    with pytest.raises(KeyError):
        cfg.update_from_dict({"nonexistent_key": 1})


def test_train_loop_learns_and_reports():
    algo = tiny_config().build()
    results = [algo.train() for _ in range(10)]
    assert results[0]["training_iteration"] == 1
    assert results[-1]["training_iteration"] == 10
    assert results[-1]["train_loss"] < results[0]["train_loss"]
    assert "test_acc" in results[-1]  # eval interval 5 fired
    assert results[-1]["test_acc"] > 0.5
    assert results[-1]["timers"]["training_step"]["count"] == 10


def test_train_with_adversary_and_robust_agg():
    cfg = tiny_config()
    cfg.aggregator = {"type": "Median"}
    cfg.num_malicious_clients = 2
    cfg.adversary_config = {"type": "ALIE"}
    algo = cfg.build()
    for _ in range(8):
        r = algo.train()
    assert np.isfinite(r["train_loss"])
    assert algo.evaluate()["test_acc"] > 0.5


def test_checkpoint_roundtrip(tmp_path):
    algo = tiny_config().build()
    for _ in range(3):
        algo.train()
    ckpt = algo.save_checkpoint(str(tmp_path / "ck"))
    ref = algo.train()  # round 4 from the original

    algo2 = tiny_config().build()
    algo2.load_checkpoint(ckpt)
    assert algo2.iteration == 3
    res = algo2.train()  # round 4 from the checkpoint
    # Full-state checkpoint (params + opt + RNG): identical continuation.
    assert res["training_iteration"] == ref["training_iteration"]
    np.testing.assert_allclose(res["train_loss"], ref["train_loss"], rtol=1e-6)


def test_registry():
    cls = get_algorithm_class("FEDAVG")
    assert cls is Fedavg
    cls, cfg = get_algorithm_class("fedavg_dp", return_config=True)
    assert isinstance(cfg, FedavgDPConfig)
    with pytest.raises(KeyError):
        get_algorithm_class("nope")


def test_dp_noise_factor_formula():
    cfg = FedavgDPConfig()
    assert cfg.dp_epsilon == 1.0  # ref default, fedavg_dp.py:17
    cfg.dp_epsilon, cfg.dp_delta, cfg.dp_clip_threshold = 10.0, 1e-6, 1.0
    cfg.train_batch_size = 32
    # ref fedavg_dp.py:44-46: sensitivity = 2*clip/train_bs;
    # sigma = sensitivity * sqrt(2 ln(1.25/delta)) / eps; factor = sigma/clip
    import math

    expect = (2.0 / 32.0) * math.sqrt(2 * math.log(1.25 / 1e-6)) / 10.0
    assert np.isclose(cfg.noise_factor, expect)


def test_dp_training_runs():
    cfg = FedavgDPConfig()
    cfg.update_from_dict({
        "dataset_config": {"type": "mnist", "num_clients": 8, "train_bs": 16},
        "global_model": "mlp",
        "dp_epsilon": 100.0,
        "evaluation_interval": 0,
        "server_config": {"lr": 1.0},
    })
    algo = cfg.build()
    assert algo.fed_round.dp_clip_threshold == 1.0
    assert algo.fed_round.dp_noise_factor is not None
    r = [algo.train() for _ in range(5)][-1]
    assert np.isfinite(r["train_loss"])


def test_multi_device_algorithm(tmp_path):
    cfg = tiny_config()
    cfg.num_devices = 8
    cfg.num_clients = 16
    algo = cfg.build()
    assert algo.mesh is not None
    for _ in range(5):
        r = algo.train()
    assert np.isfinite(r["train_loss"])
    assert algo.evaluate()["test_acc"] > 0.3


def test_fltrust_trains_via_config():
    cfg = tiny_config()
    cfg.aggregator = {"type": "FLTrust"}
    cfg.num_malicious_clients = 2
    cfg.adversary_config = {"type": "IPM", "scale": 100.0}
    algo = cfg.build()
    assert algo.fed_round.trusted_data is not None
    for _ in range(6):
        r = algo.train()
    assert np.isfinite(r["train_loss"])
    # Strong IPM would wreck a plain mean; FLTrust's trust weighting holds.
    assert algo.evaluate()["test_acc"] > 0.5


def test_cifar_config_gets_augmentation():
    from blades_tpu.algorithms import FedavgConfig

    cfg = FedavgConfig().data(dataset="cifar10", num_clients=4)
    cfg.validate()
    assert cfg.get_task_spec().augment == "cifar"
    # Dict catalog specs resolve the same way (ADVICE r3: a
    # {"type": "cifar10", ...} spec silently disabled crop+flip).
    cfg_d = FedavgConfig().data(
        dataset={"type": "cifar10", "synthetic_noise": 3.0}, num_clients=4)
    cfg_d.validate()
    assert cfg_d.get_task_spec().augment == "cifar"
    cfg2 = FedavgConfig().data(dataset="mnist", num_clients=4)
    cfg2.validate()
    assert cfg2.get_task_spec().augment is None


def test_auto_augment_disabled_on_synthetic_fallback():
    """'auto' augmentation must resolve to none when the loaded data is
    the synthetic fallback — random crops of its Gaussian class patterns
    destroy the signal (measured 0.93 -> 0.19 benign accuracy)."""
    from blades_tpu.algorithms import FedavgConfig

    import pytest

    algo = (FedavgConfig()
            .data(dataset="cifar10", num_clients=4, seed=0)
            .training(global_model="mlp", input_shape=(32, 32, 3),
                      aggregator={"type": "Mean"}, server_lr=1.0)
            .build())
    if not algo.dataset.synthetic:
        pytest.skip("raw CIFAR present on this machine")
    assert algo.fed_round.task.spec.augment is None


def test_rounds_per_dispatch_chunked_driver():
    cfg = tiny_config()
    cfg.rounds_per_dispatch = 5
    cfg.evaluation_interval = 5
    algo = cfg.build()
    r = algo.train()
    assert r["training_iteration"] == 5
    assert "test_acc" in r  # eval fired at iteration 5
    r = algo.train()
    assert r["training_iteration"] == 10


# Driver-level duplicate of tests/test_streamed.py's streamed-vs-dense
# fixture (which keeps a tier-1 arm); ~6 s of repeat compile rides the
# slow lane (PR 20 budget rebalance).
@pytest.mark.slow
def test_streamed_execution_matches_dense():
    """execution='streamed' with f32 storage reproduces the dense path
    bit-for-bit through the full Fedavg API (parallel/streamed.py's
    equivalence contract, here exercised end-to-end)."""
    import jax
    import numpy as np

    def build(execution):
        _, cfg = get_algorithm_class("FEDAVG", return_config=True)
        cfg.update_from_dict({
            "dataset_config": {"type": "mnist", "num_clients": 8,
                               "train_bs": 8},
            "global_model": "mlp",
            "evaluation_interval": 0,
            "execution": execution,
            "client_block": 4,
            "update_dtype": "float32",
            "server_config": {"lr": 1.0, "aggregator": {"type": "Median"}},
        })
        return cfg.build()

    dense, streamed = build("dense"), build("streamed")
    for _ in range(2):
        rd = dense.train()
        rs = streamed.train()
        np.testing.assert_allclose(rs["train_loss"], rd["train_loss"],
                                   rtol=1e-6)
    for a, b in zip(jax.tree.leaves(dense.state.server.params),
                    jax.tree.leaves(streamed.state.server.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_streamed_execution_validation():
    import pytest

    # rounds_per_dispatch > 1 is SUPPORTED on the streamed path since r4
    # (streamed_multi_step chains the rounds with no host sync).
    _, cfg = get_algorithm_class("FEDAVG", return_config=True)
    cfg.update_from_dict({"execution": "streamed", "rounds_per_dispatch": 4})
    cfg.validate()
    _, cfg = get_algorithm_class("FEDAVG", return_config=True)
    cfg.update_from_dict({"execution": "bogus"})
    with pytest.raises(ValueError, match="execution"):
        cfg.validate()


def test_evaluation_num_samples_caps_test_shards():
    """VERDICT r1 weak #7: per-client eval subsampling bounds device
    memory/eval cost; metrics still compute over the reduced count."""
    _, cfg = get_algorithm_class("FEDAVG", return_config=True)
    cfg.update_from_dict({
        "dataset_config": {"type": "mnist", "num_clients": 6, "train_bs": 8},
        "global_model": "mlp",
        "evaluation_interval": 1,
        "evaluation_num_samples": 3,
    })
    algo = cfg.build()
    assert algo._test_arrays[0].shape[1] == 3
    ev = algo._evaluate(algo.state, *algo._test_arrays)
    assert float(ev["num_samples"]) <= 6 * 3
    result = algo.train()
    assert 0.0 <= result["test_acc"] <= 1.0


def test_dsharded_execution_through_config():
    """execution='dsharded' drives the width-sharded giant-federation
    round through the standard Fedavg API on the 8-device mesh."""
    _, cfg = get_algorithm_class("FEDAVG", return_config=True)
    cfg.update_from_dict({
        "dataset_config": {"type": "mnist", "num_clients": 16, "train_bs": 8},
        "global_model": "mlp",
        "evaluation_interval": 2,
        "execution": "dsharded",
        "health_check": True,
        "num_malicious_clients": 4,
        "adversary_config": {"type": "ALIE"},
        "server_config": {"lr": 1.0, "aggregator": {"type": "Median"}},
    })
    cfg.resources(num_devices=8)
    algo = cfg.build()
    losses = []
    for _ in range(2):
        r = algo.train()
        losses.append(r["train_loss"])
        assert r["round_ok"] and r["num_unhealthy"] == 0
    assert all(np.isfinite(l) for l in losses)
    assert 0.0 <= algo.evaluate()["test_acc"] <= 1.0


def test_dsharded_execution_requires_mesh():
    _, cfg = get_algorithm_class("FEDAVG", return_config=True)
    cfg.update_from_dict({"execution": "dsharded"})
    with pytest.raises(ValueError, match="num_devices"):
        cfg.validate()


def test_dsharded_rounds_per_dispatch_through_config():
    """rounds_per_dispatch > 1 on execution='dsharded' (forced to 1
    through round 4): one train() call advances the round counter by the
    chunk and reduces health over the whole chunk."""
    _, cfg = get_algorithm_class("FEDAVG", return_config=True)
    cfg.update_from_dict({
        "dataset_config": {"type": "mnist", "num_clients": 16, "train_bs": 8},
        "global_model": "mlp",
        "evaluation_interval": 4,
        "execution": "dsharded",
        "health_check": True,
        "rounds_per_dispatch": 3,
        "num_malicious_clients": 4,
        "adversary_config": {"type": "ALIE"},
        "server_config": {"lr": 1.0, "aggregator": {"type": "Median"}},
    })
    cfg.resources(num_devices=8)
    algo = cfg.build()
    r = algo.train()
    assert r["training_iteration"] == 3
    assert r["round_ok"] and r["num_unhealthy"] == 0
    assert np.isfinite(r["train_loss"])
    assert int(algo.state.server.round) == 3


def test_dense_matrix_hbm_limit_is_device_derived(monkeypatch):
    """'auto' execution's dense budget: env override > device
    memory_stats > the 16 GB-chip fallback (VERDICT r3 item 7)."""
    from blades_tpu.algorithms.fedavg import Fedavg

    class FakeDev:
        def __init__(self, stats):
            self._stats = stats

        def memory_stats(self):
            return self._stats

    # The override knob must not leak in from the ambient environment.
    monkeypatch.delenv("BLADES_TPU_DENSE_MATRIX_LIMIT_GB", raising=False)

    # Device reports 95 GB (e.g. a v4p/v5p-class chip): the budget scales.
    monkeypatch.setattr(
        jax, "devices", lambda *a: [FakeDev({"bytes_limit": 95 * (1 << 30)})])
    assert Fedavg.dense_matrix_hbm_limit() == int(95 * (1 << 30) * 3 / 8)

    # No stats (CPU / remote relay): the tuned 6 GB fallback.
    monkeypatch.setattr(jax, "devices", lambda *a: [FakeDev(None)])
    assert Fedavg.dense_matrix_hbm_limit() == 6 * (1 << 30)

    # Env override wins over everything.
    monkeypatch.setenv("BLADES_TPU_DENSE_MATRIX_LIMIT_GB", "2.5")
    assert Fedavg.dense_matrix_hbm_limit() == int(2.5 * (1 << 30))
