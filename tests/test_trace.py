"""Observability layer (ISSUE 12): span tracing, flight recorder,
anomaly watchdog.

Four layers of coverage:

1. **Unit** — span tree nesting + the old ``Timers`` aggregation
   contract, Chrome-trace export/validation, watchdog rules (schema
   gate, NaN, spike, ceiling, round-time) + warm(), flight-recorder
   ring/check/dump semantics, the offline validator CLI's three modes.
2. **Bit-identity** — per execution path (dense, streamed, packed,
   wire): arming tracing + watchdog + flight recorder changes NOTHING
   in the emitted rows but ``timers``/``watchdog_events`` (the device
   program is untouched; ``jax.named_scope`` is metadata only).
3. **Postmortem** — a chaos run with injected NaN lane corruption dumps
   ``flightrec.json``, and ``tools/replay_round.py`` reproduces the
   recorded round's digest bit-identically from (config, seed, tick).
4. **Resilience** — kill-and-resume under an armed watchdog keeps the
   no-duplicate/no-gap row contract and replays the trajectory
   identically; the preemption itself leaves a flight-recorder dump.
"""

import json
import math
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
import sys  # noqa: E402

sys.path.insert(0, str(REPO))

from blades_tpu.obs.flightrec import (  # noqa: E402
    FlightRecorder,
    validate_flightrec,
)
from blades_tpu.obs.trace import (  # noqa: E402
    Timers,
    Tracer,
    validate_chrome_trace,
)
from blades_tpu.obs.watchdog import (  # noqa: E402
    Watchdog,
    WatchdogRule,
    default_rules,
)
from blades_tpu.tune import run_experiments  # noqa: E402
from blades_tpu.tune.sweep import verify_result_rounds  # noqa: E402


# ---------------------------------------------------------------------------
# span layer
# ---------------------------------------------------------------------------


def test_tracer_summary_keeps_timers_contract():
    """An un-armed tracer IS the PR-1 Timers object: same time() context
    manager, same summary shape, same mean()."""
    t = Timers()
    fake = iter(range(100))
    t._clock = lambda: next(fake)
    with t.time("round"):
        with t.time("training_step"):
            pass
    with t.time("round"):
        pass
    s = t.summary()
    assert set(s) == {"round", "training_step"}
    assert s["round"]["count"] == 2
    assert s["round"]["total_s"] == (3 - 0) + (5 - 4)
    assert t.mean("training_step") == 1.0
    # Un-armed: no tree retained.
    assert t._roots == [] and t.record is False


def test_tracer_records_nested_tree_and_attrs():
    tr = Tracer(record=True)
    root = tr.start("trial", trial="t0")
    with tr.span("round", step=1) as sp:
        with tr.span("training_step"):
            pass
        tr.annotate(extra=7)  # lands on the OPEN round span
    tr.stamp_latest("round", {"plan_id": "p"})
    tr.stamp_latest_of(("round", "compile"), {"hbm_passes": 2})
    tr.finish(root)
    assert [c.name for c in tr._roots[0].children] == ["round"]
    assert tr._roots[0].children[0].children[0].name == "training_step"
    assert sp.attrs["extra"] == 7
    assert sp.attrs["plan_id"] == "p" and sp.attrs["hbm_passes"] == 2
    assert sp.step == 1
    assert root.duration >= sp.duration >= 0


def test_chrome_export_is_valid_and_atomic(tmp_path):
    tr = Tracer(record=True)
    with tr.span("trial", trial="t"):
        with tr.span("round", step=3, plan_id="x"):
            pass
    out = tmp_path / "t.trace.json"
    tr.export(out)
    assert not (tmp_path / "t.trace.json.tmp").exists()
    n, errors = validate_chrome_trace(out)
    assert n == 2 and errors == []
    doc = json.loads(out.read_text())
    spans = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
    assert spans["round"]["args"] == {"plan_id": "x", "step": 3}
    assert doc["metadata"]["spans_recorded"] == 2


def test_chrome_validator_tolerates_torn_file(tmp_path):
    torn = tmp_path / "torn.trace.json"
    torn.write_text('{"traceEvents": [{"name": "x", "ph": "X", "ts"')
    n, errors = validate_chrome_trace(torn)
    assert n == 0 and len(errors) == 1
    assert "unreadable" in errors[0]


def test_timers_shims_still_import():
    """The consolidation satellite keeps both PR-1 modules importable."""
    from blades_tpu.utils.profiling import annotate, trace, xla_dump_flags
    from blades_tpu.utils.timers import Timers as ShimTimers

    assert ShimTimers is Timers
    assert callable(trace) and callable(annotate)
    assert "--xla_dump_to=/x" in xla_dump_flags("/x")


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


def _row(i, **kw):
    base = {"training_iteration": i, "train_loss": 1.0, "agg_norm": 0.5,
            "update_norm_mean": 1.0 + 0.01 * i}
    base.update(kw)
    return base


def test_watchdog_rules_are_schema_driven():
    with pytest.raises(ValueError, match="not registered"):
        WatchdogRule(name="bogus", kind="ceiling", field="no_such_field")
    with pytest.raises(ValueError, match="kind"):
        WatchdogRule(name="bogus", kind="wat", field="agg_norm")
    # Every default rule names a registered field by construction.
    assert {r.name for r in default_rules()} == {
        "nan_aggregate", "nan_loss", "update_norm_spike",
        "fpr_collapse", "round_time_regression",
        "staleness_runaway", "ingest_collapse", "ingest_stall",
        "reputation_collapse", "flagger_churn"}


def test_watchdog_nonfinite_spike_and_ceiling():
    wd = Watchdog()
    for i in range(1, 7):
        assert wd.observe(_row(i)) == []
    ev = wd.observe(_row(7, update_norm_mean=1e4))
    assert [e.rule for e in ev] == ["update_norm_spike"]
    assert ev[0].value == 1e4 and ev[0].limit < 1e4
    ev = wd.observe(_row(8, agg_norm=float("nan"),
                         train_loss=float("inf")))
    assert {e.rule for e in ev} == {"nan_aggregate", "nan_loss"}
    ev = wd.observe(_row(9, byz_fpr=0.9))
    assert [e.rule for e in ev] == ["fpr_collapse"]
    assert len(wd.events) == 4


def test_watchdog_round_time_regression_from_row_timers():
    wd = Watchdog([WatchdogRule(name="rt", kind="round_time_regression",
                                field="timers", window=4, min_points=3,
                                factor=3.0)])
    total = 0.0
    for i in range(1, 6):
        total += 0.1
        assert wd.observe(_row(i, timers={"training_step":
                                          {"total_s": total}})) == []
    total += 10.0  # a 100x round
    ev = wd.observe(_row(6, timers={"training_step": {"total_s": total}}))
    assert [e.rule for e in ev] == ["rt"]


def test_watchdog_warm_matches_straight_through():
    """Kill-and-resume contract: warming from on-disk rows reproduces
    the rolling windows a straight-through run would hold."""
    rows = [_row(i) for i in range(1, 7)]
    straight = Watchdog()
    for r in rows:
        straight.observe(r)
    resumed = Watchdog()
    resumed.observe(rows[0])  # partial progress before the "kill"
    resumed.warm(rows)        # restore replays the stream
    spike = _row(7, update_norm_mean=1e4)
    assert ([e.rule for e in straight.observe(spike)]
            == [e.rule for e in resumed.observe(spike)]
            == ["update_norm_spike"])


def test_watchdog_nan_never_poisons_spike_window():
    wd = Watchdog([WatchdogRule(name="s", kind="spike",
                                field="update_norm_mean", window=4,
                                min_points=2, factor=10.0)])
    wd.observe(_row(1))
    wd.observe(_row(2, update_norm_mean=float("nan")))
    wd.observe(_row(3))
    assert all(math.isfinite(v) for v in wd._windows["s"])


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flightrec_ring_bound_check_and_dump(tmp_path):
    path = tmp_path / "flightrec.json"
    fr = FlightRecorder(path, capacity=3, experiment="e", trial="t",
                        algo="FEDAVG", config={"seed": 9}, max_rounds=50)
    for i in range(1, 11):
        fr.record(_row(i, timers={"training_step": {"total_s": 1.0}}))
    assert fr.check(_row(11)) is None
    trig = fr.check(_row(11, agg_norm=float("inf")))
    assert trig == {"kind": "nonfinite", "field": "agg_norm",
                    "value": float("inf"), "round": 11}
    assert fr.dump(trig) == str(path)
    assert fr.dump(trig) is None  # rate-limited per kind
    assert fr.dump({"kind": "exception", "error": "x"}) == str(path)
    assert not (tmp_path / "flightrec.json.tmp").exists()
    num, errors = validate_flightrec(path)
    assert errors == [] and num == 3  # ring bound held
    doc = json.loads(path.read_text())
    assert [r["training_iteration"] for r in doc["rounds"]] == [8, 9, 10]
    assert doc["rng"] == {"seed": 9, "tick": 10,
                          "discipline": doc["rng"]["discipline"]}
    assert "timers" not in doc["rounds"][0]  # wall-clock stays out


def test_flightrec_rewind_rebuilds_ring_and_rearms_dump(tmp_path):
    """Checkpoint-restore contract: rewinding to the truncated rows
    leaves no stale ticks from the failed attempt (ascending order
    holds, so replay accepts the post-resume dump) and re-arms the
    per-kind dump rate limit."""
    path = tmp_path / "flightrec.json"
    fr = FlightRecorder(path, capacity=8, algo="FEDAVG",
                        config={"seed": 1})
    for i in range(1, 6):
        fr.record(_row(i))
    assert fr.dump({"kind": "exception", "error": "boom"}) is not None
    # Restore at round 3: rows 4-5 were truncated from disk.
    fr.rewind([_row(i) for i in range(1, 4)])
    for i in range(4, 6):  # re-executed rounds
        fr.record(_row(i))
    trig = {"kind": "nonfinite", "field": "agg_norm",
            "value": float("nan"), "round": 5}
    assert fr.dump(trig) is not None  # rate limit re-armed
    num, errors = validate_flightrec(path)
    assert errors == []
    doc = json.loads(path.read_text())
    assert [r["training_iteration"] for r in doc["rounds"]] \
        == [1, 2, 3, 4, 5]


def test_watchdog_warm_rebuilds_event_log_from_stamps():
    """summary["watchdog"] parity across kill-and-resume: warm()
    restores the event log from the rows' durable watchdog_events
    stamps instead of re-firing rules (which would double-count)."""
    stamped = _row(3, watchdog_events=[
        {"rule": "fpr_collapse", "kind": "ceiling", "field": "byz_fpr",
         "round": 3, "value": 0.9, "limit": 0.5, "message": "m"}])
    wd = Watchdog()
    wd.observe(_row(1, byz_fpr=0.9))  # pre-kill firing, then restore
    wd.warm([_row(1), _row(2), stamped])
    assert [e.rule for e in wd.events] == ["fpr_collapse"]
    assert wd.events[0].round == 3 and wd.events[0].value == 0.9


def test_chrome_export_keeps_children_of_open_spans(tmp_path):
    """A mid-run export (or a forgotten finish() on an explicit start()
    span) must still salvage the finished subtree."""
    tr = Tracer(record=True)
    tr.start("trial")  # never finished
    with tr.span("round", step=1):
        with tr.span("training_step"):
            pass
    out = tmp_path / "open.trace.json"
    tr.export(out)
    doc = json.loads(out.read_text())
    names = [e["name"] for e in doc["traceEvents"] if e["ph"] == "X"]
    assert "trial" not in names  # still open: no event of its own
    assert "round" in names and "training_step" in names


def test_validate_flightrec_reports_torn_and_malformed(tmp_path):
    torn = tmp_path / "flightrec.json"
    torn.write_text('{"version": 1, "rounds": [{')
    num, errors = validate_flightrec(torn)
    assert num == 0 and "unreadable" in errors[0]
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"version": 99, "rounds": [{"x": 1}]}))
    num, errors = validate_flightrec(bad)
    assert any("version" in e for e in errors)
    assert any("training_iteration" in e for e in errors)


def test_validate_metrics_cli_three_modes(tmp_path, capsys):
    from tools.validate_metrics import main as vm

    # metrics mode: valid line + torn tail is reported, not raised.
    m = tmp_path / "metrics.jsonl"
    m.write_text(json.dumps({"experiment": "e", "trial": "t",
                             "training_iteration": 1}) + "\n"
                 + '{"experiment": "e", "tr')
    assert vm([str(m)]) == 1
    out = capsys.readouterr().out
    assert "1 valid record(s), 1 error(s)" in out
    # flightrec mode.
    fr = FlightRecorder(tmp_path / "fr.json", capacity=2, algo="FEDAVG")
    fr.record(_row(1))
    fr.dump({"kind": "exception", "error": "boom"})
    assert vm(["--flightrec", str(tmp_path / "fr.json")]) == 0
    # trace mode + orphaned .tmp note (torn-write contract).
    tr = Tracer(record=True)
    with tr.span("trial"):
        pass
    tr.export(tmp_path / "t.trace.json")
    (tmp_path / "t.trace.json.tmp").write_text("{")
    assert vm(["--trace", str(tmp_path / "t.trace.json")]) == 0
    assert "orphaned" in capsys.readouterr().out
    assert vm(["--trace", str(tmp_path / "missing.json")]) == 1


# ---------------------------------------------------------------------------
# driver integration
# ---------------------------------------------------------------------------

_BASE_CFG = {
    "dataset_config": {"type": "mnist", "num_clients": 4, "train_bs": 8},
    "global_model": "mlp",
    "evaluation_interval": 2,
}


def _experiments(name, rounds=2, **cfg_over):
    cfg = {**_BASE_CFG, **cfg_over}
    return {name: {"run": "FEDAVG", "stop": {"training_iteration": rounds},
                   "config": cfg}}


def _rows(tdir) -> list:
    return [json.loads(line) for line in
            (Path(tdir) / "metrics.jsonl").read_text().splitlines()]


def _strip(rows, drop=("timers", "watchdog_events",
                       # Process-history-dependent (the AOT executable
                       # cache is process-wide, so a second identical
                       # run hits it) — pre-existing behavior, not an
                       # observability effect.
                       "compile_cache_hits", "compile_cache_misses")):
    return [{k: v for k, v in r.items() if k not in drop} for r in rows]


def test_sweep_trace_dir_exports_per_trial_tree(tmp_path):
    trace_dir = tmp_path / "traces"
    [s] = run_experiments(
        _experiments("traced", rounds=3), storage_path=str(tmp_path),
        verbose=0, cost_analysis=False, scan_window=1,
        trace_dir=str(trace_dir), watchdog=True)
    out = trace_dir / "traced_00000.trace.json"
    assert out.exists()
    n, errors = validate_chrome_trace(out)
    assert errors == [] and n >= 5  # trial + 3 dispatches + phases
    doc = json.loads(out.read_text())
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    names = {e["name"] for e in spans}
    assert {"trial", "compile", "round", "training_step",
            "evaluate"} <= names
    # Round provenance rides the dispatch spans' args.
    stamped = [e for e in spans if e["name"] in ("round", "compile")
               and "training_iteration" in e["args"]]
    assert stamped, "no dispatch span carries row provenance"
    # The phase spans nest INSIDE their dispatch span's interval (the
    # export emits depth-first, so the first training_step belongs to
    # the first dispatch — the "compile" span).
    comp = next(e for e in spans if e["name"] == "compile")
    tstep = next(e for e in spans if e["name"] == "training_step")
    assert comp["ts"] <= tstep["ts"]
    assert tstep["ts"] + tstep["dur"] <= comp["ts"] + comp["dur"] + 1e-3
    # Summary keeps the sweep-phase contract.
    assert s["timers"]["compile"]["count"] == 1
    assert s["timers"]["round"]["count"] == 2


_IDENTITY_PATHS = {
    "dense": {},
    "streamed": {"execution": "streamed",
                 "server_config": {"aggregator": {"type": "Median"},
                                   "lr": 1.0}},
    "packed": {"client_packing": 2},
    "wire": {"codec_config": {"type": "quant", "bits": 8},
             "agg_domain": "wire"},
}


@pytest.mark.parametrize("path_name", ["dense"])
def test_observability_off_rows_bit_identical(tmp_path, path_name):
    """The acceptance gate: arming tracer + watchdog + flight recorder
    changes NOTHING in the emitted rows except timers/watchdog_events —
    the device program and every metric value are untouched.  (The
    headline dense path rides tier-1; streamed/packed/wire are the slow
    zoo below, per the budget convention.)"""
    _assert_identity(tmp_path, path_name)


@pytest.mark.slow  # three extra compile-heavy paths (~3-10 s each; budget convention)
@pytest.mark.parametrize("path_name", ["streamed", "packed", "wire"])
def test_observability_off_rows_bit_identical_zoo(tmp_path, path_name):
    _assert_identity(tmp_path, path_name)


def _assert_identity(tmp_path, path_name):
    over = _IDENTITY_PATHS[path_name]
    kw = dict(verbose=0, cost_analysis=False, scan_window=1, lanes=False)
    exps = _experiments("ab", rounds=3, **over)
    run_experiments(exps, storage_path=str(tmp_path / "off"),
                    flightrec_rounds=0, **kw)
    run_experiments(exps, storage_path=str(tmp_path / "on"),
                    trace_dir=str(tmp_path / "traces"), watchdog=True,
                    flightrec_rounds=8, **kw)
    off = _rows(tmp_path / "off" / "ab" / "ab_00000")
    on = _rows(tmp_path / "on" / "ab" / "ab_00000")
    off_cmp = [{k: v for k, v in r.items() if k != "trial"}
               for r in _strip(off)]
    on_cmp = [{k: v for k, v in r.items() if k != "trial"}
              for r in _strip(on)]
    assert off_cmp == on_cmp, f"{path_name}: rows diverged"


def test_chaos_nan_dump_replays_bit_identically(tmp_path):
    """Satellite acceptance: a chaos run with injected NaN lane
    corruption dumps flightrec.json, and tools/replay_round.py
    reproduces the recorded round's digest bit-identically from
    (config, seed, tick)."""
    from tools.replay_round import main as replay_main

    exps = _experiments(
        "chaos", rounds=2, evaluation_interval=0,
        fault_config={"corrupt_rate": 0.9, "corrupt_mode": "nan",
                      "seed": 7})
    [s] = run_experiments(exps, storage_path=str(tmp_path), verbose=0,
                          cost_analysis=False, watchdog=True)
    dump = tmp_path / "chaos" / "chaos_00000" / "flightrec.json"
    assert dump.exists()
    doc = json.loads(dump.read_text())
    assert doc["trigger"]["kind"] == "nonfinite"
    assert doc["trigger"]["field"] == "agg_norm"
    assert math.isnan(doc["rounds"][-1]["agg_norm"])
    assert s["flightrec"]["dumps"] >= 1
    assert "nan_aggregate" in s["watchdog"]["rules"]
    # The NaN round must be stamped into the rows as watchdog_events.
    rows = _rows(tmp_path / "chaos" / "chaos_00000")
    assert any("watchdog_events" in r for r in rows)
    ev = next(r["watchdog_events"] for r in rows
              if "watchdog_events" in r)
    assert any(e["rule"] == "nan_aggregate" for e in ev)
    # Replay: bit-identical digest (NaN == NaN) from (config, seed, tick).
    assert replay_main([str(dump), "--quiet"]) == 0
    # A tick outside the recorded ring fails loudly, not silently.
    assert replay_main([str(dump), "--tick", "99", "--quiet"]) == 1


@pytest.mark.slow  # two 6-round sweeps + a retry rebuild (~4.5 s; budget convention)
def test_kill_and_resume_with_armed_watchdog(tmp_path):
    """Acceptance: a kill-and-resume under an armed watchdog replays
    identically — no-duplicate/no-gap rows equal to the un-preempted
    run's, the preemption leaves a flight-recorder dump, and the
    watchdog windows are rebuilt from disk on restore."""
    exps = _experiments("wd", rounds=6, evaluation_interval=0)
    run_experiments(exps, storage_path=str(tmp_path / "ref"), verbose=0,
                    cost_analysis=False, scan_window=1, watchdog=True)
    [s] = run_experiments(
        exps, storage_path=str(tmp_path / "preempted"), verbose=0,
        cost_analysis=False, scan_window=1, watchdog=True,
        checkpoint_freq=2, max_failures=1, preempt_after=3)
    tdir = tmp_path / "preempted" / "wd" / "wd_00000"
    assert verify_result_rounds(tdir / "result.json") == list(range(1, 7))
    assert s["rounds"] == 6 and "status" not in s
    # The preemption dumped the ring before the retry.
    doc = json.loads((tdir / "flightrec.json").read_text())
    assert doc["trigger"]["kind"] == "preemption"
    # Identical trajectory vs the straight-through reference.
    ref = _rows(tmp_path / "ref" / "wd" / "wd_00000")
    got = _rows(tdir)
    assert (_strip([{k: v for k, v in r.items() if k != "trial"}
                    for r in ref])
            == _strip([{k: v for k, v in r.items() if k != "trial"}
                       for r in got]))


@pytest.mark.slow  # per-seed vmapped lane compile (~7 s; budget convention)
def test_lane_group_traces_watchdog_and_rows(tmp_path):
    """Laned trials get the same observability surface: one exported
    trace per group, per-trial watchdog/flightrec over the post-hoc
    rows, schema-valid streams."""
    from blades_tpu.obs.schema import main as schema_main

    cfg = {**_BASE_CFG,
           "dataset_config": {**_BASE_CFG["dataset_config"],
                              "seed": {"grid_search": [0, 1]}}}
    exps = {"laned": {"run": "FEDAVG",
                      "stop": {"training_iteration": 2}, "config": cfg}}
    # A stale dump from a "previous run" in the same storage path must
    # not survive next to this run's fresh artifacts.
    stale = tmp_path / "laned" / "laned_00000" / "flightrec.json"
    stale.parent.mkdir(parents=True)
    stale.write_text("{}")
    summaries = run_experiments(
        exps, storage_path=str(tmp_path), verbose=0, cost_analysis=False,
        trace_dir=str(tmp_path / "traces"), watchdog=True)
    assert not stale.exists()
    assert len(summaries) == 2
    assert all(s.get("lanes") == 2 for s in summaries)
    traces = list((tmp_path / "traces").glob("laned_lanes_*.trace.json"))
    assert len(traces) == 1
    n, errors = validate_chrome_trace(traces[0])
    assert errors == []
    doc = json.loads(traces[0].read_text())
    names = [e["name"] for e in doc["traceEvents"] if e["ph"] == "X"]
    assert "lane_group" in names and "compile" in names \
        and "round" in names and "fetch" in names
    for s in summaries:
        assert schema_main([str(Path(s["dir"]) / "metrics.jsonl")]) == 0


def test_run_experiments_defaults_write_no_observability_artifacts(
        tmp_path):
    """Default sweep (no trace_dir, no watchdog, healthy run): no trace
    files, no flightrec.json, no watchdog_events — the pre-ISSUE-12
    on-disk surface exactly."""
    run_experiments(_experiments("plain", rounds=2,
                                 evaluation_interval=0),
                    storage_path=str(tmp_path), verbose=0,
                    cost_analysis=False)
    tdir = tmp_path / "plain" / "plain_00000"
    assert not (tdir / "flightrec.json").exists()
    assert not list(tmp_path.rglob("*.trace.json"))
    assert all("watchdog_events" not in r for r in _rows(tdir))
