"""Buffered-async execution (ISSUE 14, blades_tpu/arrivals).

Layers under test:

1. **Arrival realizations** — pure in ``(seed, tick)``, windowed
   realization bit-identical to per-tick, schedule/slow-cohort shaping.
2. **Buffer + weights** — bounded FIFO with unique-client cycles,
   staleness weight schedules and the Mean-exact normalized scale.
3. **The async driver** — determinism across rebuilds, kill-and-resume
   bit-identity of the buffer + version vector + params-history ring,
   chaos (dropout / corruption) composing with arrivals, the Lazy
   free-rider adversary, the ≥3-aggregator acceptance zoo.
4. **Observability** — schema-valid tick-indexed rows, watchdog
   staleness/ingest rules (warm-on-resume), flight-recorder replay to a
   recorded tick, the sync straggler path's staleness stamps.
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.arrivals import (
    ArrivalEvent,
    ArrivalProcess,
    AsyncSpec,
    UpdateBuffer,
    normalized_row_scale,
    staleness_weights,
)

N = 8  # tiny-federation size for the driver tests


def _async_config(**over):
    from blades_tpu.algorithms.config import FedavgConfig

    arrivals = {"rate": 0.4, "agg_every": 4, "staleness_cap": 4}
    arrivals.update(over.pop("arrivals", {}))
    cfg = (FedavgConfig()
           .data(dataset="mnist", num_clients=N, seed=7)
           .training(global_model="mlp",
                     aggregator=over.pop("aggregator", {"type": "Median"}))
           .resources(execution="async")
           .arrivals(**arrivals))
    for k, v in over.items():
        setattr(cfg, k, v)
    return cfg


def _close_or_both_nan(a, b):
    return (a == b) or (np.isnan(a) and np.isnan(b))


# ---------------------------------------------------------------------------
# arrival process
# ---------------------------------------------------------------------------


def test_arrival_realizations_pure_in_seed_and_tick():
    p = ArrivalProcess(seed=3, rate=0.5)
    a = np.asarray(p.arrivals_at(17, 16))
    b = np.asarray(ArrivalProcess(seed=3, rate=0.5).arrivals_at(17, 16))
    assert np.array_equal(a, b)
    # Ticks decorrelate, seeds decorrelate.
    assert not np.array_equal(a, np.asarray(p.arrivals_at(18, 16)))
    assert not np.array_equal(
        a, np.asarray(ArrivalProcess(seed=4, rate=0.5).arrivals_at(17, 16)))
    # The arrival stream is independent of the TRAINING key: nothing
    # here consumes global state, so interleaving draws changes nothing.
    jax.random.normal(jax.random.PRNGKey(123), (4,))
    assert np.array_equal(a, np.asarray(p.arrivals_at(17, 16)))


def test_arrival_window_matches_per_tick():
    p = ArrivalProcess(seed=9, rate=0.3)
    win = np.asarray(p.arrivals_window(5, 6, 12))
    singles = np.stack([np.asarray(p.arrivals_at(5 + t, 12))
                        for t in range(6)])
    assert np.array_equal(win, singles)


def test_arrival_rate_schedule_and_slow_cohort():
    p = ArrivalProcess(seed=0, rate=0.2,
                       rate_schedule=((10, 0.9), (20, 0.05)))
    assert float(p.rate_at(0)) == pytest.approx(0.2)
    assert float(p.rate_at(10)) == pytest.approx(0.9)
    assert float(p.rate_at(19)) == pytest.approx(0.9)
    assert float(p.rate_at(25)) == pytest.approx(0.05)
    slow = ArrivalProcess(seed=0, rate=0.8, slow_fraction=0.5,
                          slow_factor=0.25)
    rates = np.asarray(slow.client_rates(0, 8))
    assert np.allclose(rates[:4], 0.8) and np.allclose(rates[4:], 0.2)
    # Over many ticks the slow suffix really arrives less.
    win = np.asarray(slow.arrivals_window(0, 200, 8))
    assert win[:, :4].mean() > 2.5 * win[:, 4:].mean()


def test_arrival_process_validation():
    with pytest.raises(ValueError, match="rate"):
        ArrivalProcess(rate=0.0)
    with pytest.raises(ValueError, match="slow_factor"):
        ArrivalProcess(slow_factor=1.5)
    with pytest.raises(ValueError, match="rate_schedule"):
        ArrivalProcess(rate_schedule=((5, 1.7),))


# ---------------------------------------------------------------------------
# buffer + weights
# ---------------------------------------------------------------------------


def test_update_buffer_fifo_overflow_and_unique_clients():
    buf = UpdateBuffer(capacity=4)
    assert buf.push(ArrivalEvent(0, 1, 0)) == 0
    assert buf.push(ArrivalEvent(1, 1, 0)) == 0
    assert buf.push(ArrivalEvent(0, 2, 0)) == 0  # duplicate buffers fine
    assert buf.push(ArrivalEvent(2, 2, 0)) == 0
    # Full + distinct client: ONE event is lost — the oldest duplicate
    # (client 0's tick-1) evicts so the unique set still grows.
    assert buf.push(ArrivalEvent(3, 3, 0)) == 1
    assert buf.fill == 4 and buf.unique_clients() == 4
    cycle = buf.take_cycle(3)
    # FIFO over the survivors: client 1's tick-1, client 0's tick-2,
    # client 2's tick-2.
    assert [e.client for e in cycle] == [1, 0, 2]
    assert [e.tick for e in cycle] == [1, 2, 2]
    assert buf.fill == 1 and buf._events[0].client == 3
    with pytest.raises(ValueError, match="unique-client"):
        buf.take_cycle(2)


def test_update_buffer_eviction_prevents_unique_client_deadlock():
    """A full buffer below k unique clients must not be absorbing: a new
    DISTINCT client's arrival evicts the oldest duplicate-client event
    (counted as an overflow loss), so the unique set can always grow to
    a fireable cycle; a duplicate arrival on a full buffer still drops."""
    buf = UpdateBuffer(capacity=4)
    for tick in range(4):
        assert buf.push(ArrivalEvent(tick % 2, tick, 0)) == 0
    assert buf.fill == 4 and buf.unique_clients() == 2
    # Duplicate client on a full buffer: the NEW event drops.
    assert buf.push(ArrivalEvent(0, 9, 0)) == 1
    assert buf.unique_clients() == 2
    # Distinct clients on a full buffer: oldest duplicates evict, one
    # loss each, and the unique set grows until a 4-cycle can fire.
    assert buf.push(ArrivalEvent(2, 10, 0)) == 1
    assert buf.push(ArrivalEvent(3, 11, 0)) == 1
    assert buf.unique_clients() == 4
    # Oldest duplicates (client 0's tick-0, client 1's tick-1 events)
    # were the evictees; survivors stay FIFO.
    assert [e.client for e in buf.take_cycle(4)] == [0, 1, 2, 3]


def test_async_engine_slow_client_does_not_starve():
    """The reviewer scenario: agg_every == num_clients with a slow-lane
    cohort — the fast clients fill the buffer long before the slow one
    first arrives.  Eviction keeps a slot reachable, so cycles fire
    instead of spinning into the starvation guard."""
    def build():
        return _async_config(
            arrivals={"rate": 0.6, "agg_every": 8, "staleness_cap": 4,
                      "slow_fraction": 0.125, "slow_factor": 0.05})

    algo = build().build()
    rows = [algo.train() for _ in range(2)]
    assert rows[-1]["training_iteration"] == 2
    assert rows[-1]["buffer_overflow"] >= 0  # losses counted, no deadlock


def test_update_buffer_state_roundtrip():
    buf = UpdateBuffer(capacity=8)
    buf.push(ArrivalEvent(3, 11, 2, True))
    buf.push(ArrivalEvent(1, 12, 4, False))
    clone = UpdateBuffer(capacity=8)
    clone.restore(buf.state())
    assert clone.state() == buf.state()
    assert clone._events[0] == ArrivalEvent(3, 11, 2, True)


def test_staleness_weight_schedules():
    k = jnp.asarray([0, 1, 3, 20])
    assert np.allclose(staleness_weights("constant", k), 1.0)
    assert np.allclose(staleness_weights("polynomial", k, power=0.5),
                       [1.0, 2 ** -0.5, 0.5, 21 ** -0.5])
    assert np.allclose(staleness_weights("inverse", k),
                       [1.0, 0.5, 0.25, 1 / 21])
    assert np.allclose(staleness_weights("cutoff", k, cutoff=3),
                       [1.0, 1.0, 1.0, 0.0])
    with pytest.raises(ValueError, match="schedule"):
        staleness_weights("wat", k)
    # Mean-exactness: scaled rows through a plain mean == weighted mean.
    u = jnp.asarray(np.random.default_rng(0).normal(size=(4, 5)),
                    jnp.float32)
    w = staleness_weights("polynomial", k)
    scaled = u * normalized_row_scale(w)[:, None]
    want = (u * w[:, None]).sum(0) / w.sum()
    assert np.allclose(scaled.mean(0), want, rtol=1e-6)
    # Constant weights are the exact identity (bit-for-bit).
    ident = u * normalized_row_scale(jnp.ones(4))[:, None]
    assert np.array_equal(np.asarray(ident), np.asarray(u))


def test_async_spec_validation():
    with pytest.raises(ValueError, match="buffer_capacity"):
        AsyncSpec(agg_every=8, buffer_capacity=4)
    with pytest.raises(ValueError, match="weight_schedule"):
        AsyncSpec(weight_schedule="nope")
    with pytest.raises(ValueError, match="staleness_cap"):
        AsyncSpec(staleness_cap=0)
    assert AsyncSpec(agg_every=8).effective_capacity == 16


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------


def test_config_gates():
    from blades_tpu.algorithms.config import FedavgConfig

    with pytest.raises(ValueError, match="async_config is set"):
        FedavgConfig().arrivals(rate=0.5).validate()
    # Forensics composes since the cohort-shaped re-index (ISSUE 16):
    # the buffered cycle diagnoses the staleness-scaled event matrix.
    _async_config(forensics=True).validate()
    with pytest.raises(ValueError, match="codec"):
        _async_config(codec_config={"type": "quant", "bits": 8}).validate()
    with pytest.raises(ValueError, match="agg_every"):
        _async_config(arrivals={"agg_every": 64}).validate()
    with pytest.raises(ValueError, match="straggler"):
        _async_config(
            fault_config={"num_stragglers": 1, "staleness": 2}).validate()
    with pytest.raises(ValueError, match="autotuner"):
        _async_config(autotune=True).validate()
    # Dropout/corruption chaos composes — validates clean.
    _async_config(fault_config={"dropout_rate": 0.2,
                                "corrupt_rate": 0.1}).validate()
    # The arrival seed defaults to the trial seed; an explicit one pins.
    assert _async_config().get_async_spec().seed == 7
    assert _async_config(
        arrivals={"seed": 42}).get_async_spec().seed == 42


# ---------------------------------------------------------------------------
# lazy / free-rider adversary
# ---------------------------------------------------------------------------


def test_lazy_adversary_copy_and_replay():
    from blades_tpu.adversaries import get_adversary

    rng = np.random.default_rng(1)
    updates = jnp.asarray(rng.normal(size=(6, 16)), jnp.float32)
    mal = jnp.arange(6) < 2
    key = jax.random.PRNGKey(5)

    adv = get_adversary("Lazy", mode="copy", noise_std=0.0)
    out = np.asarray(adv.on_updates_ready(updates, mal, key))
    # Benign rows untouched; malicious rows are a copy of ONE benign row.
    assert np.array_equal(out[2:], np.asarray(updates)[2:])
    victims = [i for i in range(2, 6)
               if np.array_equal(out[0], np.asarray(updates)[i])]
    assert len(victims) == 1 and np.array_equal(out[0], out[1])
    # Deterministic in the key.
    again = np.asarray(adv.on_updates_ready(updates, mal, key))
    assert np.array_equal(out, again)

    replay = get_adversary("Lazy", mode="replay", copy_scale=0.5,
                           noise_std=0.0)
    assert replay.wants_stale_replay
    out2 = np.asarray(replay.on_updates_ready(updates, mal, key))
    assert np.allclose(out2[:2], 0.5 * np.asarray(updates)[:2])
    assert np.array_equal(out2[2:], np.asarray(updates)[2:])
    with pytest.raises(ValueError, match="mode"):
        get_adversary("Lazy", mode="sloth")


# ---------------------------------------------------------------------------
# the async driver: determinism, resume, chaos, adversaries
# ---------------------------------------------------------------------------


def _run_rows(cfg_builder, rounds):
    algo = cfg_builder().build()
    return algo, [algo.train() for _ in range(rounds)]


_REPLAYABLE = ("train_loss", "agg_norm", "update_norm_mean", "tick",
               "staleness_mean", "staleness_max", "buffer_fill",
               "buffer_overflow", "arrivals_dropped")


@pytest.mark.slow  # the resume test below pins replay determinism in tier-1
def test_async_rows_deterministic_across_rebuilds():
    _, rows_a = _run_rows(_async_config, 4)
    _, rows_b = _run_rows(_async_config, 4)
    for ra, rb in zip(rows_a, rows_b):
        for k in _REPLAYABLE:
            assert ra[k] == rb[k], k
    # Ticks never go backwards; staleness summaries are coherent.
    ticks = [r["tick"] for r in rows_a]
    assert ticks == sorted(ticks)
    for r in rows_a:
        assert r["staleness_mean"] <= r["staleness_max"]
        assert sum(r["staleness_hist"]) == 4  # agg_every events


def test_async_kill_and_resume_bit_identical(tmp_path):
    """The acceptance contract: buffer + version vector + params-history
    ring checkpointed like the EF residual and stale ring — a restored
    trial replays rows AND full RoundState bit-for-bit."""
    algo_a, rows_a = _run_rows(_async_config, 6)

    b = _async_config().build()
    for _ in range(3):
        b.train()
    b.save_checkpoint(str(tmp_path))
    c = _async_config().build()
    c.load_checkpoint(str(tmp_path))
    # Host state restored exactly (version vector, buffer, counters).
    assert c._async.host_state() == b._async.host_state()
    rows_c = [c.train() for _ in range(3)]
    for ra, rc in zip(rows_a[3:], rows_c):
        for k in _REPLAYABLE:
            assert ra[k] == rc[k], k
    for pa, pc in zip(jax.tree.leaves(algo_a.state),
                      jax.tree.leaves(c.state)):
        assert np.array_equal(np.asarray(pa), np.asarray(pc))


# Second kill-and-resume in this file (~10 s): the core async resume
# contract stays tier-1 via test_async_kill_and_resume_bit_identical;
# this arm pins the rate_schedule rewind specifically (PR 20 budget
# rebalance).
@pytest.mark.slow
def test_rate_schedule_resume_reenters_at_restored_tick(tmp_path):
    """ISSUE 17 regression: a kill-and-resume mid-``rate_schedule`` must
    re-enter the schedule at the RESTORED tick, not tick 0 — campaign
    adversaries ride arrival schedules, so a schedule that rewound on
    resume would silently decouple the attack from the traffic shape.
    ``rate_at`` is pure in the absolute tick, so the contract reduces to
    the engine restoring its tick exactly; rows across the schedule
    boundary must match a straight-through run bit-for-bit."""
    sched = {"arrivals": {"rate": 0.9,
                          "rate_schedule": ((4, 0.1),)}}

    def cfg():
        return _async_config(**json.loads(json.dumps(sched)))

    _, rows_a = _run_rows(cfg, 10)
    # The run must actually cross the schedule boundary for the test to
    # bite: the high->low rate flip at tick 4 stretches the tick gaps.
    assert rows_a[-1]["tick"] > 4 > rows_a[0]["tick"]

    b = cfg().build()
    for _ in range(4):
        b.train()
    b.save_checkpoint(str(tmp_path))
    c = cfg().build()
    c.load_checkpoint(str(tmp_path))
    restored_tick = c._async.host_state()["tick"]
    # The restored engine evaluates the schedule at its restored tick —
    # a rewound process would read the pre-boundary 0.9 after tick 12.
    proc = c._async.spec.process()
    assert float(proc.rate_at(restored_tick)) == float(
        proc.rate_at(b._async.host_state()["tick"]))
    rows_c = [c.train() for _ in range(6)]
    for ra, rc in zip(rows_a[4:], rows_c):
        for k in _REPLAYABLE:
            assert ra[k] == rc[k], k
    # And the post-boundary regime is visibly the scheduled one: at rate
    # 0.1 the virtual clock must advance faster per cycle than the
    # rate-0.9 opening (more ticks to buffer agg_every events).
    assert float(proc.rate_at(rows_c[-1]["tick"])) == pytest.approx(0.1)


# Chaos x async composition (~6 s compile): dropout and corruption are
# each covered tier-1 on the sync path; the composed arm rides the slow
# lane (PR 20 budget rebalance).
@pytest.mark.slow
def test_async_chaos_dropout_and_corruption_compose():
    """Chaos composes with arrivals: dropout deterministically thins the
    ingest stream (counted, replayable), NaN corruption rides an event
    into the buffer and the robust aggregator survives it."""
    def chaotic():
        return _async_config(
            fault_config={"dropout_rate": 0.3, "corrupt_rate": 0.15,
                          "corrupt_mode": "nan", "seed": 11})

    algo, rows = _run_rows(chaotic, 4)
    assert rows[-1]["arrivals_dropped"] > 0
    assert rows[-1]["fault_seed"] == 11
    # Median over a partially-NaN buffer stays finite (robustness), and
    # the realization replays identically (NaN == NaN: a corrupt
    # event's NaN row makes the ALL-rows update_norm_mean NaN by
    # design, exactly like the sync corruption path).
    for r in rows:
        assert np.isfinite(r["agg_norm"])
    _, rows_b = _run_rows(chaotic, 4)
    for ra, rb in zip(rows, rows_b):
        for k in _REPLAYABLE:
            assert _close_or_both_nan(ra[k], rb[k]), k
    # The corruption stream actually fired somewhere in the window
    # (deterministically — corrupt events are pure in (seed, tick,
    # client)), visible as corrupted buffer rows: train_loss of a cycle
    # with a corrupt benign event excludes it, so just pin determinism
    # plus the dropout accounting above.
    assert rows[-1]["arrivals_dropped"] == rows_b[-1]["arrivals_dropped"]


@pytest.mark.slow  # two extra cycle compiles; tier-1 keeps the copy-mode zoo
def test_async_lazy_replay_uses_stale_params():
    """mode='replay' free-riders compute against the OLDEST retained
    params version: with distinct history rows, a fresh (staleness-0)
    malicious event's update changes while every benign event's stays
    bit-identical — the substitution only an async server can express."""
    from blades_tpu.adversaries import get_adversary, make_malicious_mask
    from blades_tpu.arrivals.cycle import build_cycle, init_history
    from blades_tpu.core import FedRound, Server, TaskSpec
    from blades_tpu.models import MLP
    from blades_tpu.utils.tree import ravel_fn

    task = TaskSpec(model=MLP(hidden1=8, hidden2=8, num_classes=4),
                    input_shape=(8, 8, 1), num_classes=4, lr=0.1).build()
    server = Server.from_config(aggregator="Mean", lr=0.5)
    H = 3

    def make(adv):
        fr = FedRound(task=task, server=server, adversary=adv,
                      batch_size=4, num_batches_per_round=1)
        cyc = build_cycle(fr, staleness_cap=H,
                          weight_schedule="constant", weight_power=0.5,
                          weight_cutoff=16)
        state = fr.init(jax.random.PRNGKey(0), N)
        hist = init_history(state.server.params, H)
        # Distinct history rows: version j-ago params = init + 0.01*j.
        hist = hist + 0.01 * jnp.arange(H + 1)[:, None]
        import dataclasses as _dc

        return cyc, _dc.replace(state, arrivals=hist)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(N, 8, 8, 8, 1)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, size=(N, 8)), jnp.int32)
    ln = jnp.full((N,), 8, jnp.int32)
    ev_clients = jnp.asarray([0, 2, 3, 4], jnp.int32)  # client 0 malicious
    ev_ticks = jnp.asarray([5, 5, 6, 6], jnp.int32)
    ev_stale = jnp.zeros(4, jnp.int32)                 # all claim fresh
    mal = np.asarray(make_malicious_mask(N, 1))
    ev_mal = jnp.asarray(mal[np.asarray(ev_clients)])
    ev_corr = jnp.zeros(4, bool)
    kb = jax.random.PRNGKey(9)
    ka = jax.random.PRNGKey(11)

    lazy = get_adversary("Lazy", mode="replay", noise_std=0.0)
    cyc_lazy, st = make(lazy)
    cyc_honest, st2 = make(None)
    _, m_lazy = cyc_lazy(st, x, y, ln, ev_clients, ev_ticks, ev_stale,
                         ev_mal, ev_corr, kb, ka)
    _, m_honest = cyc_honest(st2, x, y, ln, ev_clients, ev_ticks,
                             ev_stale, ev_mal, ev_corr, kb, ka)
    # The malicious event trained against hist[H] instead of hist[0]:
    # the aggregate (Mean over the 4 rows) must differ.
    assert float(m_lazy["agg_norm"]) != float(m_honest["agg_norm"])
    # Sanity: with NO malicious event in the cycle the two programs are
    # bit-identical (the override touches malicious lanes only).
    ev_clients_b = jnp.asarray([2, 3, 4, 5], jnp.int32)
    ev_mal_b = jnp.asarray(mal[np.asarray(ev_clients_b)])
    _, mb_lazy = cyc_lazy(st, x, y, ln, ev_clients_b, ev_ticks, ev_stale,
                          ev_mal_b, ev_corr, kb, ka)
    _, mb_honest = cyc_honest(st2, x, y, ln, ev_clients_b, ev_ticks,
                              ev_stale, ev_mal_b, ev_corr, kb, ka)
    assert float(mb_lazy["agg_norm"]) == float(mb_honest["agg_norm"])


@pytest.mark.parametrize("aggregator", [
    {"type": "Median"},
    # Budget convention: one aggregator headlines tier-1, the rest of
    # the zoo (plus the CNN protocol below) rides the slow tier.
    pytest.param({"type": "Multikrum", "k": 2}, marks=pytest.mark.slow),
    pytest.param({"type": "GeoMed"}, marks=pytest.mark.slow),
])
def test_async_aggregator_zoo_with_lazy_clients(aggregator):
    """≥3 robust aggregators under the lazy-client adversary on the
    async path (the tiny-MLP slice of the acceptance protocol; the
    32-client CNN version is the slow marker below).  agg_every=6:
    f-dependent aggregators (Multikrum) see the BUFFER as their row
    axis, so the 2f+2 <= K feasibility bound is a buffer-size bound
    under async (documented in the README interaction matrix)."""
    def build():
        return _async_config(
            aggregator=aggregator, num_malicious_clients=2,
            adversary_config={"type": "Lazy", "mode": "copy"},
            arrivals={"agg_every": 6})

    _, rows = _run_rows(build, 3)
    for r in rows:
        assert np.isfinite(r["train_loss"]) and np.isfinite(r["agg_norm"])
    assert rows[-1]["training_iteration"] == 3


@pytest.mark.slow
def test_async_cnn_protocol_acceptance():
    """The acceptance protocol at full size: 32-client CNN, Poisson
    arrivals, lazy free-riders, three robust aggregators."""
    from blades_tpu.algorithms.config import FedavgConfig

    for agg in ({"type": "Median"}, {"type": "Multikrum", "k": 8},
                {"type": "GeoMed"}):
        cfg = (FedavgConfig()
               .data(dataset="cifar10", num_clients=32, seed=3)
               .training(global_model="cnn", aggregator=agg,
                         train_batch_size=8)
               .adversary(num_malicious_clients=8,
                          adversary_config={"type": "Lazy",
                                            "mode": "replay"})
               .resources(execution="async")
               # agg_every=24: Multikrum's 2f+2 <= K bound at f=8.
               .arrivals(rate=0.25, agg_every=24, staleness_cap=8))
        algo = cfg.build()
        rows = [algo.train() for _ in range(2)]
        for r in rows:
            assert np.isfinite(r["train_loss"])
            assert np.isfinite(r["agg_norm"])
            assert r["updates_per_sec"] > 0


# ---------------------------------------------------------------------------
# sync staleness stamps (satellite)
# ---------------------------------------------------------------------------


def test_sync_straggler_path_stamps_staleness():
    from blades_tpu.algorithms.config import FedavgConfig

    cfg = (FedavgConfig()
           .data(dataset="mnist", num_clients=N, seed=7)
           .training(global_model="mlp")
           .fault_tolerance(faults={"num_stragglers": 2, "staleness": 3,
                                    "seed": 5}))
    algo = cfg.build()
    rows = [algo.train() for _ in range(2)]
    for r in rows:
        assert r["staleness_max"] == 3  # 2 stragglers deliver 3-old work
        want = 3.0 * r["num_straggled"] / r["num_participating"]
        assert r["staleness_mean"] == pytest.approx(want)
    # And a fault-free run stamps neither (schema stays lean).
    clean = (FedavgConfig().data(dataset="mnist", num_clients=N, seed=7)
             .training(global_model="mlp")).build()
    row = clean.train()
    assert "staleness_mean" not in row and "tick" not in row


# ---------------------------------------------------------------------------
# observability: schema, sweep, watchdog, replay
# ---------------------------------------------------------------------------


def test_async_sweep_schema_valid_rows_and_summary(tmp_path):
    from blades_tpu.obs.schema import validate_jsonl
    from blades_tpu.tune import run_experiments

    experiments = {
        "async_smoke": {
            "run": "FEDAVG",
            "stop": {"training_iteration": 4},
            "config": {
                "dataset_config": {"type": "mnist", "num_clients": N,
                                   "train_bs": 8, "seed": 7},
                "global_model": "mlp",
                "evaluation_interval": 2,
                "execution": "async",
                "async_config": {"rate": 0.4, "agg_every": 4,
                                 "staleness_cap": 4},
            },
        }
    }
    summaries = run_experiments(experiments, storage_path=str(tmp_path),
                                verbose=0, watchdog=True)
    (s,) = summaries
    assert "status" not in s, s.get("error")
    assert s["arrivals"]["tick"] > 0
    assert "updates_per_sec" in s["arrivals"]
    stream = Path(s["dir"]) / "metrics.jsonl"
    num_valid, errors = validate_jsonl(stream)
    assert errors == [] and num_valid == 4
    rows = [json.loads(l) for l in stream.read_text().splitlines()]
    ticks = [r["tick"] for r in rows]
    assert ticks == sorted(ticks)
    # The one front door agrees (tick order included).
    from tools.validate_metrics import main as validate_main

    assert validate_main([str(stream)]) == 0


def test_validate_metrics_rejects_backwards_ticks(tmp_path, capsys):
    from tools.validate_metrics import main as validate_main

    p = tmp_path / "metrics.jsonl"
    base = {"experiment": "e", "trial": "t"}
    rows = [dict(base, training_iteration=1, tick=5),
            dict(base, training_iteration=2, tick=3)]
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    assert validate_main([str(p)]) == 1
    assert "tick went backwards" in capsys.readouterr().out


def test_watchdog_staleness_and_ingest_rules():
    from blades_tpu.obs.watchdog import Watchdog

    wd = Watchdog()

    def row(i, ups=100.0, smax=2):
        return {"training_iteration": i, "train_loss": 1.0,
                "agg_norm": 1.0, "update_norm_mean": 1.0,
                "updates_per_sec": ups, "staleness_max": smax}

    for i in range(1, 6):
        assert wd.observe(row(i)) == []
    ev = wd.observe(row(6, ups=10.0))  # 10 < 100/4 => ingest collapse
    assert [e.rule for e in ev] == ["ingest_collapse"]
    ev = wd.observe(row(7, smax=100))
    assert [e.rule for e in ev] == ["staleness_runaway"]
    # warm() replays the window without re-firing events.
    wd2 = Watchdog()
    wd2.warm([row(i) for i in range(1, 6)])
    assert wd2.events == []
    ev = wd2.observe(row(6, ups=10.0))
    assert [e.rule for e in ev] == ["ingest_collapse"]


def test_replay_rejects_ambiguous_duplicate_ticks():
    """Cycles fired from leftover buffered events share a virtual tick;
    --tick against a duplicated tick must error loudly (pointing at the
    round index), never silently pick one of the rows."""
    from tools.replay_round import replay

    dump = {
        "algo": "FEDAVG", "config": {}, "capacity": 4,
        "rounds": [
            {"training_iteration": 1, "tick": 7, "train_loss": 1.0},
            {"training_iteration": 2, "tick": 7, "train_loss": 2.0},
        ],
    }
    with pytest.raises(ValueError, match="matches 2 recorded rounds"):
        replay(dump, tick=7)


def test_async_cutoff_all_stale_batch_warns():
    """An all-over-cutoff buffer is a zero-step cycle by contract — but
    the host engine must say so loudly instead of silently stalling."""
    def build():
        return _async_config(
            arrivals={"weight_schedule": "cutoff", "weight_cutoff": 0})

    algo = build().build()
    algo.train()  # cycle 1: staleness 0 everywhere, no warning
    with pytest.warns(RuntimeWarning, match="fully discarded"):
        row = algo.train()  # backlog => staleness >= 1 > cutoff=0
    assert row["staleness_mean"] >= 1.0


# Flight-recorder replay through the async cycle (~5 s): the replay
# contract is tier-1 on the sync path (tools/replay_round.py tests);
# the async arm rides the slow lane (PR 20 budget rebalance).
@pytest.mark.slow
def test_flightrec_replay_async_round(tmp_path):
    """tools/replay_round understands tick-indexed async rows: replay to
    a recorded virtual tick reproduces the digest bit-identically."""
    from blades_tpu.obs.flightrec import FlightRecorder
    from tools.replay_round import main as replay_main

    trial_cfg = {
        "dataset_config": {"type": "mnist", "num_clients": N, "seed": 7},
        "global_model": "mlp",
        "execution": "async",
        "async_config": {"rate": 0.4, "agg_every": 4, "staleness_cap": 4},
    }
    from blades_tpu.algorithms import get_algorithm_class

    _, config = get_algorithm_class("FEDAVG", return_config=True)
    config.update_from_dict(json.loads(json.dumps(trial_cfg)))
    algo = config.build()
    rec = FlightRecorder(tmp_path / "flightrec.json", capacity=8,
                         experiment="e", trial="t", algo="FEDAVG",
                         config=trial_cfg, max_rounds=3)
    rows = [algo.train() for _ in range(3)]
    for r in rows:
        rec.record(json.loads(json.dumps(dict(r, trial="t"),
                                         default=float)))
    rec.dump({"kind": "exception", "round": rows[-1]["training_iteration"]})
    # Replay by server round (the default trigger path)...
    assert replay_main([str(tmp_path / "flightrec.json"), "--quiet"]) == 0
    # ...and by the recorded VIRTUAL tick (async rows are tick-indexed).
    vtick = rows[1]["tick"]
    if vtick not in (r["training_iteration"] for r in rows):
        assert replay_main([str(tmp_path / "flightrec.json"), "--quiet",
                            "--tick", str(vtick)]) == 0
