"""bench.py's graded-environment robustness (VERDICT r4 weak #1).

Round 4's bench produced rc=124 and NO output because the backend probe
retried ``jax.devices()`` in-process while each call hung ~26 minutes.
These tests pin the hardened contract: the probe is subprocess-based
with a hard deadline, the total wait is bounded, and the failure path
emits a parseable single-line error JSON.
"""

import json
import subprocess
import sys

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import bench


class _FakeClock:
    """Deterministic monotonic clock: each hung probe 'takes' its full
    timeout, sleeps advance by their argument — no real waiting."""

    def __init__(self):
        self.t = 0.0

    def monotonic(self):
        return self.t

    def sleep(self, s):
        self.t += s


def _patch_clock(monkeypatch):
    clk = _FakeClock()
    monkeypatch.setattr(bench.time, "monotonic", clk.monotonic)
    monkeypatch.setattr(bench.time, "sleep", clk.sleep)
    return clk


def test_probe_bounded_when_every_probe_hangs(monkeypatch):
    clk = _patch_clock(monkeypatch)
    calls = []

    def fake_run(cmd, capture_output, text, timeout):
        calls.append(timeout)
        clk.t += timeout  # the hang consumes the probe's full deadline
        raise subprocess.TimeoutExpired(cmd, timeout)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)

    err = bench._wait_for_backend(total_budget_s=300.0, probe_timeout_s=75.0)
    assert err is not None and "hung" in err
    # 75s probe + 20s sleep per iteration within a 300s budget.
    assert 2 <= len(calls) <= 4
    # Every probe got a hard deadline no larger than the per-probe cap,
    # and none was launched with less than the 5s-minimum remaining.
    assert all(5.0 <= t <= 75.0 for t in calls)
    assert clk.t <= 300.0 + 75.0  # bounded overshoot: one probe width max


def test_probe_returns_none_when_backend_reachable(monkeypatch):
    def fake_run(cmd, capture_output, text, timeout):
        return subprocess.CompletedProcess(cmd, 0, stdout="TPU\n", stderr="")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    assert bench._wait_for_backend(total_budget_s=10.0) is None


def test_probe_rejects_cpu_fallback(monkeypatch):
    """A fast-failing axon plugin falls back to the CPU backend; that
    must read as 'backend unavailable', not success (the bench's configs
    only run on TPU)."""
    _patch_clock(monkeypatch)
    monkeypatch.delenv("BLADES_BENCH_ALLOW_CPU", raising=False)

    def fake_run(cmd, capture_output, text, timeout):
        return subprocess.CompletedProcess(cmd, 0, stdout="cpu\n", stderr="")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    err = bench._wait_for_backend(total_budget_s=30.0)
    assert err is not None and "fallback" in err

    monkeypatch.setenv("BLADES_BENCH_ALLOW_CPU", "1")
    assert bench._wait_for_backend(total_budget_s=30.0) is None


def test_probe_surfaces_child_error_text(monkeypatch):
    _patch_clock(monkeypatch)

    def fake_run(cmd, capture_output, text, timeout):
        return subprocess.CompletedProcess(
            cmd, 1, stdout="", stderr="RuntimeError: relay said no")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    err = bench._wait_for_backend(total_budget_s=30.0)
    assert err is not None and "relay said no" in err


def _reset_emit():
    bench._emitted["done"] = False
    bench._emitted["ok"] = False


def test_error_json_is_single_parseable_line(capsys):
    _reset_emit()
    bench._emit(bench._error_json("backend_unavailable", "x" * 2000))
    out = capsys.readouterr().out
    lines = [l for l in out.splitlines() if l.strip()]
    assert len(lines) == 1
    obj = json.loads(lines[0])
    assert obj["metric"] == bench.METRIC_NAME
    assert obj["value"] is None
    assert obj["error"] == "backend_unavailable"
    assert len(obj["detail"]) <= 800


def test_emit_is_once_only(capsys):
    _reset_emit()
    bench._emit({"a": 1})
    bench._emit({"b": 2})  # watchdog racing the result: second is dropped
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert lines == [json.dumps({"a": 1})]
    # A success line flips the ok flag, which the watchdog uses to decide
    # between exit 0 (late teardown hang) and exit 3 (no result).
    assert bench._emitted["ok"]
    _reset_emit()
    bench._emit(bench._error_json("backend_unavailable", "d"))
    capsys.readouterr()
    assert not bench._emitted["ok"]
