"""Core train-step layer tests: local rounds, server step, full FL round.

Model: the reference's tiny-fixture integration tests
(ref: blades/algorithms/fedavg/tests/test_fedavg.py) — a small synthetic
dataset + small model driven end-to-end, asserting learning happens and
state flows correctly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.core import FedRound, Server, TaskSpec
from blades_tpu.data import DatasetCatalog
from blades_tpu.data.sampler import sample_batch, sample_client_batches
from blades_tpu.utils.tree import ravel_fn


@pytest.fixture(scope="module")
def tiny():
    ds = DatasetCatalog.get_dataset("mnist", num_clients=6)
    task = TaskSpec(model="mlp", lr=0.1, input_shape=(28, 28, 1)).build()
    server = Server.from_config(aggregator="Mean", lr=1.0)
    fr = FedRound(task=task, server=server, batch_size=16, num_batches_per_round=2)
    state = fr.init(jax.random.PRNGKey(0), 6)
    arrays = (
        jnp.array(ds.train.x), jnp.array(ds.train.y), jnp.array(ds.train.lengths),
    )
    return ds, fr, state, arrays


def test_sampler_never_selects_padding():
    x = jnp.arange(20.0).reshape(10, 2)
    y = jnp.arange(10)
    # true length 4: indices must stay < 4
    for s in range(5):
        bx, by = sample_batch(jax.random.PRNGKey(s), x, y, jnp.array(4), 8)
        assert (by < 4).all()


def test_sampler_shapes_and_decorrelation():
    x = jnp.zeros((3, 50, 2))
    y = jnp.broadcast_to(jnp.arange(50), (3, 50))
    ln = jnp.array([50, 50, 50])
    bx, by = sample_client_batches(jax.random.PRNGKey(0), x, y, ln, 8, 4)
    assert bx.shape == (3, 4, 8, 2) and by.shape == (3, 4, 8)
    assert not jnp.array_equal(by[0], by[1])  # lanes decorrelated


def test_local_round_update_is_param_delta(tiny):
    ds, fr, state, (x, y, ln) = tiny
    task = fr.task
    ravel, _, d = ravel_fn(state.server.params)
    bx, by = sample_client_batches(jax.random.PRNGKey(3), x, y, ln, 16, 2)
    upd, opt, loss = task.local_round(
        state.server.params, jax.tree.map(lambda a: a[0], state.client_opt),
        bx[0], by[0], jax.random.PRNGKey(4), jnp.array(False),
    )
    assert upd.shape == (d,)
    assert jnp.isfinite(upd).all() and float(jnp.linalg.norm(upd)) > 0
    assert float(loss) > 0


def test_server_step_applies_update_direction(tiny):
    ds, fr, state, _ = tiny
    ravel, _, d = ravel_fn(state.server.params)
    # A constant update vector must move params by lr * update under plain SGD.
    upd = jnp.ones((3, d)) * 0.5
    new_state, agg = fr.server.step(state.server, upd)
    assert jnp.allclose(agg, 0.5)
    delta = ravel(new_state.params) - ravel(state.server.params)
    assert jnp.allclose(delta, 1.0 * 0.5, atol=1e-6)  # server lr = 1.0
    assert int(new_state.round) == 1


def test_full_round_learns(tiny):
    ds, fr, state, (x, y, ln) = tiny
    mal = jnp.zeros(6, bool)
    step = jax.jit(fr.step)
    losses = []
    for r in range(25):
        state, m = step(state, x, y, ln, mal, jax.random.fold_in(jax.random.PRNGKey(7), r))
        losses.append(float(m["train_loss"]))
    assert losses[-1] < losses[0] * 0.5
    ev = jax.jit(fr.evaluate)(
        state, jnp.array(ds.test.x), jnp.array(ds.test.y), jnp.array(ds.test.lengths)
    )
    assert float(ev["test_acc"]) > 0.8
    assert float(ev["num_samples"]) == float(jnp.array(ds.test.lengths).sum())


def test_round_determinism_same_seed(tiny):
    ds, fr, _, (x, y, ln) = tiny
    mal = jnp.zeros(6, bool)
    ravel, _, _ = ravel_fn(fr.init(jax.random.PRNGKey(0), 6).server.params)

    def run():
        st = fr.init(jax.random.PRNGKey(0), 6)
        step = jax.jit(fr.step)
        for r in range(3):
            st, _ = step(st, x, y, ln, mal, jax.random.fold_in(jax.random.PRNGKey(9), r))
        return ravel(st.server.params)

    a, b = run(), run()
    assert jnp.array_equal(a, b)


def test_lr_schedule_piecewise():
    from blades_tpu.core.server import lr_schedule

    sched = lr_schedule(0.1, [(0, 0.1), (100, 0.01)])
    assert np.isclose(float(sched(0)), 0.1)
    assert np.isclose(float(sched(100)), 0.01, atol=1e-4)
    # Linear interpolation midway.
    assert 0.01 < float(sched(50)) < 0.1


def test_multi_step_matches_sequential_steps(tiny):
    """multi_step(k) must advance the same state machine as k step() calls
    with the same per-round keys (jax.random.split of the chunk key)."""
    ds, fr, _, (x, y, ln) = tiny
    from functools import partial

    mal = jnp.zeros(6, bool)
    chunk_key = jax.random.PRNGKey(11)
    st_a = fr.init(jax.random.PRNGKey(1), 6)
    st_b = fr.init(jax.random.PRNGKey(1), 6)

    st_a, ms = jax.jit(partial(fr.multi_step, num_rounds=3))(
        st_a, x, y, ln, mal, chunk_key
    )
    step = jax.jit(fr.step)
    keys = jax.random.split(chunk_key, 3)
    for i in range(3):
        st_b, m = step(st_b, x, y, ln, mal, keys[i])

    ravel, _, _ = ravel_fn(st_b.server.params)
    np.testing.assert_allclose(
        np.asarray(ravel(st_a.server.params)),
        np.asarray(ravel(st_b.server.params)), rtol=1e-6,
    )
    assert ms["train_loss"].shape == (3,)
    np.testing.assert_allclose(float(ms["train_loss"][-1]), float(m["train_loss"]),
                               rtol=1e-6)
    assert int(st_a.server.round) == 3


def test_bf16_compute_learns(tiny):
    ds, _, _, (x, y, ln) = tiny
    from blades_tpu.core import FedRound, Server, TaskSpec

    task = TaskSpec(model="mlp", lr=0.1, input_shape=(28, 28, 1),
                    compute_dtype="bfloat16").build()
    fr = FedRound(task=task, server=Server.from_config(aggregator="Mean", lr=1.0),
                  batch_size=16)
    st = fr.init(jax.random.PRNGKey(0), 6)
    # Params stay f32 masters.
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(st.server.params))
    step = jax.jit(fr.step)
    losses = []
    mal = jnp.zeros(6, bool)
    for r in range(20):
        st, m = step(st, x, y, ln, mal, jax.random.fold_in(jax.random.PRNGKey(3), r))
        losses.append(float(m["train_loss"]))
    assert losses[-1] < losses[0] * 0.6
