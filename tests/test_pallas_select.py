"""Pallas rank-select kernel tests (interpret mode on the CPU mesh).

The TPU kernels must match the jnp.sort-based paths bit-for-bit for the
median and to f32 accumulation tolerance for the trimmed mean — including
duplicate values, non-sublane-aligned client counts (row padding), odd
column counts (column padding), and +/-inf entries.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from blades_tpu.ops import masked
from blades_tpu.ops import pallas_select as ps


def _matrix(n, d, seed=0, dupes=True):
    rng = np.random.default_rng(seed)
    x = (rng.normal(size=(n, d)) * 10).astype(np.float32)
    if dupes:
        x[: n // 3] = np.round(x[: n // 3])  # force ties
        x[0] = x[-1]
    return x


@pytest.mark.parametrize("n", [7, 8, 25, 100])
@pytest.mark.parametrize("d", [5, 128, 300])
def test_column_median_matches_sort_path_exactly(n, d):
    x = _matrix(n, d, seed=n * 1000 + d)
    got = ps.column_median(jnp.asarray(x), interpret=True)
    want = masked.median(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_column_median_with_infs():
    x = _matrix(10, 64, seed=3)
    x[0, :] = np.inf
    x[1, :8] = -np.inf
    got = ps.column_median(jnp.asarray(x), interpret=True)
    want = masked.median(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("n,k", [(9, 1), (16, 3), (100, 10)])
def test_column_trimmed_mean_matches_sort_path(n, k):
    x = _matrix(n, 200, seed=n)
    got = ps.column_trimmed_mean(jnp.asarray(x), k, interpret=True)
    s = np.sort(x, axis=0)
    want = s[k : n - k].mean(axis=0)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


def test_column_trimmed_mean_all_ties():
    # Whole retained window one duplicate run: the vlo==vhi guard.
    x = np.ones((12, 130), np.float32) * 2.5
    x[0] = -100.0
    x[-1] = 100.0
    got = ps.column_trimmed_mean(jnp.asarray(x), 2, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.full(130, 2.5, np.float32))


def test_should_use_is_conservative_on_cpu():
    # CPU backend (the test mesh): never routes to pallas, so the
    # aggregator tests exercise the jnp paths unchanged.
    assert not ps.should_use(jnp.zeros((1000, 8192), jnp.float32))


def test_column_median_negative_nan_matches_sort_order():
    """Sign-bit NaNs must follow jnp.sort's NaN-LAST order (a raw key map
    would sort them first and shift every selected rank)."""
    x = _matrix(9, 64, seed=11)
    neg_nan = np.uint32(0xFFC00000).view(np.float32)
    x[0, :16] = neg_nan
    x[1, :8] = np.nan
    got = ps.column_median(jnp.asarray(x), interpret=True)
    want = masked.median(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_should_use_caps_client_count(monkeypatch):
    """Even on a TPU backend, a federation too tall for the full-height
    VMEM stripe must fall back to the sort path."""
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert ps.should_use(jnp.zeros((1000, 8192), jnp.float32))
    assert not ps.should_use(jnp.zeros((4096, 4096), jnp.float32))
    assert not ps.should_use(jnp.zeros((4, 1 << 21), jnp.float32))
