"""Fused streamed-finish kernel (ops/pallas_round.py) in interpret mode.

The kernel is TPU-only in production (``should_use`` gates on the
backend); these tests run it through the pallas interpreter on the CPU
mesh and check it against the plain-jnp reference semantics the chunked
finish implements: forge (ALIE/IPM) -> aggregate (Mean/Median/
Trimmedmean), stripe-local sanitize, row norms.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.adversaries.base import benign_mean_std
from blades_tpu.ops.pallas_round import fused_finish

STRIPE = 512  # pallas_select._BLOCK_D


def _ref_forge(x, mal, forge, round_bf16=False):
    mean, std = benign_mean_std(x, mal)
    if forge is None:
        return x
    if forge[0] == "alie":
        forged = mean + forge[1] * std
    else:
        forged = -forge[1] * mean
    if round_bf16:
        forged = forged.astype(jnp.bfloat16).astype(jnp.float32)
    return jnp.where(mal[:, None], forged, x)


def _ref_agg(x, agg):
    n = x.shape[0]
    if agg[0] == "mean":
        return x.mean(axis=0)
    s = jnp.sort(x, axis=0)
    if agg[0] == "median":
        return (s[(n - 1) // 2] + s[n // 2]) / 2
    k = agg[1]
    return s[k:n - k].mean(axis=0)


@pytest.mark.parametrize("n,d", [(24, 1000), (17, 700), (64, 2048)])
@pytest.mark.parametrize(
    "forge,agg",
    [
        (("alie", 0.7), ("median",)),
        (("ipm", 1.5), ("trimmed", 3)),
        (None, ("mean",)),
    ],
)
def test_fused_matches_reference(n, d, forge, agg):
    rng = np.random.default_rng(seed=n + d)
    x = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
    mal = jnp.asarray(rng.random(n) < 0.25)
    ref = _ref_forge(x, mal, forge)
    agg_vec, sq, bad = fused_finish(x, mal, forge=forge, agg=agg,
                                    interpret=True)
    np.testing.assert_allclose(agg_vec, _ref_agg(ref, agg),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(sq, (ref ** 2).sum(axis=1),
                               rtol=1e-4, atol=1e-4)
    assert not bool(bad.any())


@pytest.mark.parametrize("forge", [("alie", 0.7), ("ipm", 2.0), None])
def test_fused_bf16_sixteen_step_radix(forge):
    """bf16 storage: forged rows round to storage precision, selection is
    exact in the 16-bit key space."""
    n, d = 32, 1500
    rng = np.random.default_rng(seed=5)
    x16 = jnp.asarray(rng.normal(size=(n, d)), jnp.float32).astype(jnp.bfloat16)
    mal = jnp.asarray(rng.random(n) < 0.25)
    ref = _ref_forge(x16.astype(jnp.float32), mal, forge, round_bf16=True)
    agg_vec, _, _ = fused_finish(x16, mal, forge=forge, agg=("median",),
                                 interpret=True)
    np.testing.assert_array_equal(
        np.asarray(agg_vec), np.asarray(_ref_agg(ref, ("median",)))
    )


def test_fused_adaptive_matches_adversary_hook():
    """('adaptive', b) with pre-drawn uniforms reproduces the dense
    AdaptiveAdversary.on_updates_ready forge exactly (same key)."""
    from blades_tpu.adversaries import get_adversary

    n, d = 24, 900
    rng = np.random.default_rng(seed=11)
    x = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
    mal = jnp.asarray(rng.random(n) < 0.25)
    key = jax.random.PRNGKey(42)
    adv = get_adversary({"type": "Adaptive", "b": 2.0},
                        num_clients=n, num_byzantine=int(mal.sum()))
    ref = adv.on_updates_ready(x, mal, key)
    noise = jax.random.uniform(key, (d,), jnp.float32)
    agg_vec, _, _ = fused_finish(x, mal, noise, forge=("adaptive", 2.0),
                                 agg=("median",), interpret=True)
    np.testing.assert_allclose(agg_vec, _ref_agg(ref, ("median",)),
                               rtol=1e-5, atol=1e-5)


def test_fused_adaptive_bf16_matches_rounded_reference():
    """The production combination: adaptive forge + bf16 storage — the
    forged row rounds to bf16 and the 16-step radix selects among the
    rounded values exactly."""
    from blades_tpu.adversaries import get_adversary

    n, d = 24, 900
    rng = np.random.default_rng(seed=13)
    x16 = jnp.asarray(rng.normal(size=(n, d)), jnp.float32).astype(jnp.bfloat16)
    mal = jnp.asarray(rng.random(n) < 0.25)
    key = jax.random.PRNGKey(21)
    adv = get_adversary({"type": "Adaptive", "b": 2.0},
                        num_clients=n, num_byzantine=int(mal.sum()))
    xf = x16.astype(jnp.float32)
    ref = adv.on_updates_ready(xf, mal, key)
    # forged rows round to storage precision in the kernel
    ref = jnp.where(mal[:, None], ref.astype(jnp.bfloat16).astype(jnp.float32),
                    ref)
    noise = jax.random.uniform(key, (d,), jnp.float32)
    agg_vec, _, _ = fused_finish(x16, mal, noise, forge=("adaptive", 2.0),
                                 agg=("median",), interpret=True)
    np.testing.assert_array_equal(
        np.asarray(agg_vec), np.asarray(_ref_agg(ref, ("median",)))
    )


def test_fused_adaptive_requires_noise():
    x = jnp.zeros((8, 600), jnp.float32)
    with pytest.raises(ValueError, match="forge_noise"):
        fused_finish(x, jnp.zeros((8,), bool), forge=("adaptive", 2.0),
                     agg=("mean",), interpret=True)


def test_fused_sanitize_stripe_local():
    """A non-finite value zeroes its row within that 512-wide stripe only
    (same chunk-local semantics as the streamed chunk path), and the row
    is reported unhealthy."""
    n, d = 16, STRIPE + 40
    rng = np.random.default_rng(seed=7)
    x = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
    x = x.at[3, 2].set(jnp.inf)
    mal = jnp.zeros((n,), bool)
    agg_vec, sq, bad = fused_finish(x, mal, forge=None, agg=("mean",),
                                    sanitize=True, interpret=True)
    clean = x.at[3, :STRIPE].set(0.0)
    np.testing.assert_allclose(agg_vec, clean.mean(axis=0), rtol=1e-5,
                               atol=1e-6)
    assert list(np.nonzero(np.asarray(bad))[0]) == [3]


def test_fused_rejects_overtrimming():
    x = jnp.zeros((8, 600), jnp.float32)
    with pytest.raises(ValueError, match="trimmed"):
        fused_finish(x, jnp.zeros((8,), bool), agg=("trimmed", 4),
                     interpret=True)


# Full streamed-round compile twice over (~5 s); the kernel-level fused
# equivalence grid above stays tier-1 in interpret mode (PR 20 budget
# rebalance).
@pytest.mark.slow
def test_streamed_step_fused_branch_matches_chunked(monkeypatch):
    """Force the streamed round onto the fused finish (interpret mode)
    and check the whole round matches the chunked finish."""
    import functools

    from blades_tpu import parallel
    from blades_tpu.adversaries import get_adversary, make_malicious_mask
    from blades_tpu.core import FedRound, Server, TaskSpec
    from blades_tpu.ops import pallas_round

    monkeypatch.setattr(pallas_round, "should_use", lambda n, d: True)
    monkeypatch.setattr(
        pallas_round, "fused_finish",
        functools.partial(pallas_round.fused_finish.__wrapped__,
                          interpret=True),
    )

    n, f = 12, 3
    task = TaskSpec(model="mlp", input_shape=(8, 8, 1), num_classes=10,
                    lr=0.1).build()
    server = Server.from_config(aggregator="Median", lr=0.5)
    adv = get_adversary("ALIE", num_clients=n, num_byzantine=f)
    fr = FedRound(task=task, server=server, adversary=adv, batch_size=4,
                  num_batches_per_round=1)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, 8, 8, 8, 1)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=(n, 8)), jnp.int32)
    lengths = jnp.full((n,), 8, jnp.int32)
    mal = make_malicious_mask(n, f)
    key = jax.random.PRNGKey(3)

    state0 = fr.init(jax.random.PRNGKey(0), n)
    step_fused = parallel.streamed.streamed_step(
        fr, client_block=4, update_dtype=jnp.float32, donate=False)
    s1, m1 = step_fused(state0, x, y, lengths, mal, key)

    monkeypatch.setattr(pallas_round, "should_use", lambda n, d: False)
    state0 = fr.init(jax.random.PRNGKey(0), n)
    step_chunked = parallel.streamed.streamed_step(
        fr, client_block=4, update_dtype=jnp.float32, donate=False)
    s2, m2 = step_chunked(state0, x, y, lengths, mal, key)

    for k in ("train_loss", "agg_norm", "update_norm_mean"):
        np.testing.assert_allclose(float(m1[k]), float(m2[k]), rtol=1e-5)
    p1 = jax.tree.leaves(s1.server.params)
    p2 = jax.tree.leaves(s2.server.params)
    for a, b in zip(p1, p2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-6)


# ---------------------------------------------------------------------------
# Benign-compacted finish (virtual forged-row multiplicity)
# ---------------------------------------------------------------------------


# Two of the four shape rows ride the slow lane — the measured-slowest
# arms of this grid (PR 20 budget rebalance); tier-1 keeps the largest
# and the highest-multiplicity shapes across all forge/agg pairs.
@pytest.mark.parametrize("nb,mult,d", [
    (24, 8, 1000),
    pytest.param(17, 5, 700, marks=pytest.mark.slow),
    pytest.param(18, 6, 600, marks=pytest.mark.slow),
    (11, 13, 520)])
@pytest.mark.parametrize(
    "forge,agg",
    [
        (("alie", 0.7), ("median",)),
        (("alie", 0.7), ("mean",)),
        (("ipm", 1.5), ("trimmed", 3)),
        (("ipm", 1.5), ("median",)),
    ],
)
def test_compact_matches_full_kernel(nb, mult, d, forge, agg):
    """The compact kernel over nb benign rows + a virtual forged row of
    multiplicity `mult` must equal the FULL kernel over the
    (nb + mult, d) matrix whose first `mult` rows are malicious."""
    from blades_tpu.ops.pallas_round import fused_finish_compact

    if agg[0] == "trimmed" and nb + mult <= 2 * agg[1]:
        pytest.skip("overtrimmed")
    rng = np.random.default_rng(seed=nb * 31 + d)
    xb = jnp.asarray(rng.normal(size=(nb, d)), jnp.float32)
    # Full matrix: malicious prefix rows hold garbage the forge replaces.
    garbage = jnp.asarray(rng.normal(size=(mult, d)) * 50.0, jnp.float32)
    x_full = jnp.concatenate([garbage, xb], axis=0)
    mal = jnp.arange(nb + mult) < mult

    a_full, sq_full, bad_full = fused_finish(
        x_full, mal, forge=forge, agg=agg, sanitize=True, interpret=True)
    a_c, sq_c, bad_c, forged = fused_finish_compact(
        xb, forged_mult=mult, forge=forge, agg=agg, sanitize=True,
        interpret=True)

    np.testing.assert_allclose(np.asarray(a_full), np.asarray(a_c),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(sq_full[mult:]), np.asarray(sq_c),
                               rtol=1e-6)
    # Malicious rows' norms are ||forged||^2 — reconstructable outside.
    np.testing.assert_allclose(
        np.asarray(sq_full[:mult]),
        np.full(mult, float(forged @ forged)), rtol=1e-5)
    assert not np.asarray(bad_c).any()


@pytest.mark.slow  # duplicate compact-kernel compile fixture (~10 s; the f32 compact/full equivalence stays tier-1)
def test_compact_bf16_matches_full_bf16():
    from blades_tpu.ops.pallas_round import fused_finish_compact

    nb, mult, d = 24, 8, 800
    rng = np.random.default_rng(3)
    xb = jnp.asarray(rng.normal(size=(nb, d)), jnp.bfloat16)
    x_full = jnp.concatenate(
        [jnp.zeros((mult, d), jnp.bfloat16), xb], axis=0)
    mal = jnp.arange(nb + mult) < mult
    for agg in (("median",), ("trimmed", 5), ("mean",)):
        a_full, _, _ = fused_finish(x_full, mal, forge=("alie", 1.2),
                                    agg=agg, interpret=True)
        a_c, _, _, _ = fused_finish_compact(
            xb, forged_mult=mult, forge=("alie", 1.2), agg=agg,
            interpret=True)
        np.testing.assert_allclose(np.asarray(a_full), np.asarray(a_c),
                                   atol=2e-4, rtol=1e-4)


def test_compact_adaptive_matches_full():
    from blades_tpu.ops.pallas_round import fused_finish_compact

    nb, mult, d = 16, 6, 520
    rng = np.random.default_rng(5)
    xb = jnp.asarray(rng.normal(size=(nb, d)), jnp.float32)
    x_full = jnp.concatenate([jnp.ones((mult, d)) * 9.0, xb], axis=0)
    mal = jnp.arange(nb + mult) < mult
    noise = jnp.asarray(rng.random(d), jnp.float32)
    a_full, _, _ = fused_finish(x_full, mal, noise,
                                forge=("adaptive", 2.0), agg=("median",),
                                interpret=True)
    a_c, _, _, _ = fused_finish_compact(
        xb, noise, forged_mult=mult, forge=("adaptive", 2.0),
        agg=("median",), interpret=True)
    np.testing.assert_allclose(np.asarray(a_full), np.asarray(a_c),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.slow  # interpret-mode MXU variant sweep (~43 s; PR 7 budget rebalance)
def test_compact_mxu_variants_match_default():
    """The MXU radix-count formulation must be BIT-exact vs the VPU one
    (the per-step counts are small integers, exact in f32); the MXU
    stats formulation matches up to f32 reassociation ulps.  These are
    the round-5 radix-headroom candidates (PERF_NOTES_r4: the radix is
    ~43 ms of the ~80 ms compact finish, VPU-bound)."""
    from blades_tpu.ops.pallas_round import fused_finish_compact

    nb, mult, d = 40, 12, 1100
    rng = np.random.default_rng(17)
    for dtype in (jnp.float32, jnp.bfloat16):
        xb = jnp.asarray(rng.normal(size=(nb, d)), dtype)
        for agg in (("median",), ("trimmed", 7), ("mean",)):
            base = fused_finish_compact(
                xb, forged_mult=mult, forge=("alie", 1.5), agg=agg,
                sanitize=True, interpret=True,
                radix_mxu=False, stats_mxu=False)
            counts = fused_finish_compact(
                xb, forged_mult=mult, forge=("alie", 1.5), agg=agg,
                sanitize=True, interpret=True,
                radix_mxu=True, stats_mxu=False)
            # radix_mxu alone: identical selection -> identical outputs.
            for a, b in zip(base, counts):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            allmxu = fused_finish_compact(
                xb, forged_mult=mult, forge=("alie", 1.5), agg=agg,
                sanitize=True, interpret=True,
                radix_mxu=True, stats_mxu=True)
            for a, b in zip(base[:2], allmxu[:2]):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           atol=2e-4, rtol=1e-4)


def test_compact_rejects_forgeless():
    from blades_tpu.ops.pallas_round import fused_finish_compact

    with pytest.raises(ValueError, match="forge"):
        fused_finish_compact(jnp.zeros((8, 600)), forged_mult=2,
                             forge=None, interpret=True)


def test_mxu_finish_env_resolved_per_call(monkeypatch):
    """ADVICE r5 #1: BLADES_TPU_MXU_FINISH is resolved in the un-jitted
    wrapper on EVERY call — toggling the env after the first call must
    switch the mode (the old trace-time read cached the first call's
    resolution under the None statics and silently kept it)."""
    from blades_tpu.ops import pallas_round

    seen = []

    def spy(updates, noise=None, **kw):
        seen.append((kw["radix_mxu"], kw["stats_mxu"]))
        return "sentinel"

    monkeypatch.setattr(pallas_round, "_fused_finish_compact_jit", spy)
    x = jnp.zeros((8, 600))

    monkeypatch.delenv("BLADES_TPU_MXU_FINISH", raising=False)
    assert pallas_round.fused_finish_compact(
        x, forged_mult=2, forge=("alie", 1.5)) == "sentinel"
    monkeypatch.setenv("BLADES_TPU_MXU_FINISH", "counts")
    pallas_round.fused_finish_compact(x, forged_mult=2, forge=("alie", 1.5))
    monkeypatch.setenv("BLADES_TPU_MXU_FINISH", "all")
    pallas_round.fused_finish_compact(x, forged_mult=2, forge=("alie", 1.5))
    monkeypatch.setenv("BLADES_TPU_MXU_FINISH", "")
    pallas_round.fused_finish_compact(x, forged_mult=2, forge=("alie", 1.5))
    assert seen == [(False, False), (True, False), (True, True),
                    (False, False)]
    # Explicit arguments always beat the env.
    monkeypatch.setenv("BLADES_TPU_MXU_FINISH", "all")
    pallas_round.fused_finish_compact(x, forged_mult=2, forge=("alie", 1.5),
                                      radix_mxu=False, stats_mxu=False)
    assert seen[-1] == (False, False)


def test_mxu_finish_config_path_resolved_per_call(monkeypatch):
    """The first-class ``resources(mxu_finish=...)`` path (ISSUE 10
    satellite, extending the PR 4 toggle test): with the env UNSET the
    caller's config-resolved ``mxu_finish`` string selects the mode per
    call; a SET env var — even set AFTER the first call — overrides the
    config value (the explicit per-process escape hatch)."""
    from blades_tpu.ops import pallas_round

    seen = []

    def spy(updates, noise=None, **kw):
        seen.append((kw["radix_mxu"], kw["stats_mxu"]))
        return "sentinel"

    monkeypatch.setattr(pallas_round, "_fused_finish_compact_jit", spy)
    x = jnp.zeros((8, 600))
    monkeypatch.delenv("BLADES_TPU_MXU_FINISH", raising=False)

    for mode in ("", "counts", "all", None):
        pallas_round.fused_finish_compact(
            x, forged_mult=2, forge=("alie", 1.5), mxu_finish=mode)
    assert seen == [(False, False), (True, False), (True, True),
                    (False, False)]
    # A SET env var beats the config value, toggled after first call.
    monkeypatch.setenv("BLADES_TPU_MXU_FINISH", "all")
    pallas_round.fused_finish_compact(
        x, forged_mult=2, forge=("alie", 1.5), mxu_finish="counts")
    assert seen[-1] == (True, True)
    # Even env="" (set-but-empty) is an explicit override, not a fall-
    # through to the config value.
    monkeypatch.setenv("BLADES_TPU_MXU_FINISH", "")
    pallas_round.fused_finish_compact(
        x, forged_mult=2, forge=("alie", 1.5), mxu_finish="all")
    assert seen[-1] == (False, False)


# Same shape as the fused-branch variant above: two full streamed-round
# compiles (~8 s) to pin a branch the compact kernel grid already covers
# tier-1 in interpret mode (PR 20 budget rebalance).
@pytest.mark.slow
def test_streamed_step_compact_branch_matches_chunked(monkeypatch):
    """Force the streamed round onto the benign-compacted fused finish
    (elided malicious prefix + virtual-multiplicity kernel, interpret
    mode) and check the whole round matches the chunked finish."""
    import functools

    from blades_tpu import parallel
    from blades_tpu.adversaries import get_adversary, make_malicious_mask
    from blades_tpu.core import FedRound, Server, TaskSpec
    from blades_tpu.ops import pallas_round, pallas_select

    monkeypatch.setattr(pallas_round, "should_use", lambda n, d: True)
    monkeypatch.setattr(pallas_select, "kernel_applicable",
                        lambda n, d: True)
    # fused_finish_compact is an un-jitted wrapper (it resolves the
    # BLADES_TPU_MXU_FINISH env per call, ADVICE r5 #1) — partial the
    # wrapper itself to force interpret mode.
    monkeypatch.setattr(
        pallas_round, "fused_finish_compact",
        functools.partial(pallas_round.fused_finish_compact,
                          interpret=True),
    )

    n, f = 12, 4  # f divisible by client_block -> compact path
    task = TaskSpec(model="mlp", input_shape=(8, 8, 1), num_classes=10,
                    lr=0.1).build()
    server = Server.from_config(aggregator="Median", lr=0.5)
    adv = get_adversary("ALIE", num_clients=n, num_byzantine=f)
    fr = FedRound(task=task, server=server, adversary=adv, batch_size=4,
                  num_batches_per_round=1)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, 8, 8, 8, 1)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=(n, 8)), jnp.int32)
    lengths = jnp.full((n,), 8, jnp.int32)
    mal = make_malicious_mask(n, f)
    key = jax.random.PRNGKey(3)

    state0 = fr.init(jax.random.PRNGKey(0), n)
    step_compact = parallel.streamed.streamed_step(
        fr, client_block=4, update_dtype=jnp.float32, donate=False,
        malicious_prefix=f)
    s1, m1 = step_compact(state0, x, y, lengths, mal, key)

    monkeypatch.setattr(pallas_round, "should_use", lambda n, d: False)
    monkeypatch.setattr(pallas_select, "kernel_applicable",
                        lambda n, d: False)
    state0 = fr.init(jax.random.PRNGKey(0), n)
    step_chunked = parallel.streamed.streamed_step(
        fr, client_block=4, update_dtype=jnp.float32, donate=False)
    s2, m2 = step_chunked(state0, x, y, lengths, mal, key)

    for k in ("train_loss", "agg_norm", "update_norm_mean"):
        np.testing.assert_allclose(float(m1[k]), float(m2[k]), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s1.server.params),
                    jax.tree.leaves(s2.server.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-6)


@pytest.mark.slow  # duplicate compact-kernel compile fixture (~8 s; matches_full_kernel stays tier-1)
def test_compact_caller_prepadded_rows_match_autopad():
    """num_real + caller +inf padding (the no-copy giant-scale path) must
    equal the concat-padding path."""
    from blades_tpu.ops.pallas_round import fused_finish_compact

    nb, mult, d = 11, 5, 600  # nb % 8 != 0
    rng = np.random.default_rng(9)
    xb = jnp.asarray(rng.normal(size=(nb, d)), jnp.float32)
    npad = -(-nb // 8) * 8
    x_pad = jnp.concatenate(
        [xb, jnp.full((npad - nb, d), jnp.inf, jnp.float32)], axis=0)
    for agg in (("median",), ("trimmed", 3), ("mean",)):
        a1, sq1, bad1, f1 = fused_finish_compact(
            xb, forged_mult=mult, forge=("alie", 0.9), agg=agg,
            sanitize=True, interpret=True)
        a2, sq2, bad2, f2 = fused_finish_compact(
            x_pad, forged_mult=mult, forge=("alie", 0.9), agg=agg,
            sanitize=True, num_real=nb, interpret=True)
        # 1-ulp tolerance: the two wrappers build wb differently (concat
        # vs arange-compare), and XLA's CPU pipeline reassociates the
        # forge-stat reductions differently around them.
        np.testing.assert_allclose(np.asarray(a1), np.asarray(a2),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(sq1), np.asarray(sq2),
                                   rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(bad1), np.asarray(bad2))
        assert not np.asarray(bad2).any()  # pad +inf rows must not flag
        np.testing.assert_allclose(np.asarray(f1), np.asarray(f2),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # duplicate compact-kernel compile fixture (~6 s)
def test_streamed_step_compact_with_row_padding(monkeypatch):
    """Compact streamed round where nb is NOT a sublane multiple: the
    pre-padded +inf rows must be invisible (parity vs chunked)."""
    import functools

    from blades_tpu import parallel
    from blades_tpu.adversaries import get_adversary, make_malicious_mask
    from blades_tpu.core import FedRound, Server, TaskSpec
    from blades_tpu.ops import pallas_round
    from blades_tpu.ops import pallas_select

    monkeypatch.setattr(pallas_round, "should_use", lambda n, d: True)
    monkeypatch.setattr(pallas_select, "kernel_applicable",
                        lambda n, d: True)
    # fused_finish_compact is an un-jitted wrapper (it resolves the
    # BLADES_TPU_MXU_FINISH env per call, ADVICE r5 #1) — partial the
    # wrapper itself to force interpret mode.
    monkeypatch.setattr(
        pallas_round, "fused_finish_compact",
        functools.partial(pallas_round.fused_finish_compact,
                          interpret=True),
    )

    n, f = 16, 4  # nb = 12 -> padded to 16 rows
    task = TaskSpec(model="mlp", input_shape=(8, 8, 1), num_classes=10,
                    lr=0.1).build()
    server = Server.from_config(aggregator="Median", lr=0.5)
    adv = get_adversary("ALIE", num_clients=n, num_byzantine=f)
    fr = FedRound(task=task, server=server, adversary=adv, batch_size=4,
                  num_batches_per_round=1)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, 8, 8, 8, 1)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=(n, 8)), jnp.int32)
    lengths = jnp.full((n,), 8, jnp.int32)
    mal = make_malicious_mask(n, f)
    key = jax.random.PRNGKey(3)

    state0 = fr.init(jax.random.PRNGKey(0), n)
    step_compact = parallel.streamed.streamed_step(
        fr, client_block=4, update_dtype=jnp.float32, donate=False,
        malicious_prefix=f)
    s1, m1 = step_compact(state0, x, y, lengths, mal, key)

    monkeypatch.setattr(pallas_round, "should_use", lambda n, d: False)
    monkeypatch.setattr(pallas_select, "kernel_applicable",
                        lambda n, d: False)
    state0 = fr.init(jax.random.PRNGKey(0), n)
    step_chunked = parallel.streamed.streamed_step(
        fr, client_block=4, update_dtype=jnp.float32, donate=False)
    s2, m2 = step_chunked(state0, x, y, lengths, mal, key)

    for k in ("train_loss", "agg_norm", "update_norm_mean"):
        np.testing.assert_allclose(float(m1[k]), float(m2[k]), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s1.server.params),
                    jax.tree.leaves(s2.server.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-6)
