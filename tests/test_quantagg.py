# blades-lint: disable-file=streamed-pass-discipline — equivalence tests exercise dequantize/raw references against the wire path on purpose
"""Wire-domain robust aggregation (ISSUE 11): int8 defense geometry.

Five layers:

1. **Deferred decode** — ``decode_deferred``'s packed payload decodes
   bit-identically to ``encode_decode`` (one quantization source of
   truth), for int8 and int4 grids; forged rows re-enter the wire via
   ``requantize_rows`` with benign payloads untouched.
2. **int8 bundle kernel** — ``ops/pallas_rowstats`` on int8 input in
   interpret mode: ragged tail widths, row padding to the int8 sublane
   multiple, true-width sign counts on padded stripes, exact integer
   Gram/norms.
3. **Scale algebra** — a ``row_scale`` planner's accumulated statistics
   match a plain planner over the dequantized matrix, per request kind,
   on both the chunk path and the forced interpret-mode kernel.
4. **Aggregators** — ``aggregate_wire`` vs decode-then-f32 for ALL 10
   aggregators within the pinned tolerance (``WIRE_RTOL``;
   Median/Trimmedmean exact — order statistics rank identical decoded
   values).
5. **Rounds + config + autotuner** — identity codec bit-identical
   through the wire branch, quant wire rounds within tolerance of f32
   rounds, post-codec (quantized-domain) forging, validate() gates,
   schema-valid driver stamps, and the reassociating-tier-only
   ``agg_domain`` plan knob with pack factors {2, 4, 8} probed at
   enumeration.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.comm.codecs import CodecConfig, dequantize
from blades_tpu.ops.aggregators import (
    Centeredclipping,
    Clippedclustering,
    DnC,
    FLTrust,
    GeoMed,
    Mean,
    Median,
    Multikrum,
    Signguard,
    Trimmedmean,
)
from blades_tpu.ops.pallas_rowstats import row_stats_bundle
from blades_tpu.parallel.streamed_geometry import (
    PassPlanner,
    PassRecorder,
    WIRE_AGGREGATORS,
    aggregate_wire,
)

# The pinned wire-domain equivalence tolerance (documented in README
# "Communication codecs"): scale algebra is exact on the int8 grid, so
# the only divergence vs decode-then-f32 is f32 reduction reassociation
# — the same class the streamed chunk path carries.
WIRE_RTOL = 1e-4


def _payload(n=16, d=403, seed=0, bits=8):
    rng = np.random.default_rng(seed)
    u = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    codec = CodecConfig(name="quant", bits=bits)
    q, scales, _ = codec.decode_deferred(u, None, jax.random.PRNGKey(7))
    return u, codec, q, scales


# ---------------------------------------------------------------------------
# 1. deferred decode
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("bits", [8, 4])
def test_decode_deferred_bit_identical_to_encode_decode(bits):
    u, codec, q, scales = _payload(bits=bits)
    dec, _ = codec.encode_decode(u, None, jax.random.PRNGKey(7))
    assert q.dtype == jnp.int8
    smax = 2 ** (bits - 1) - 1
    assert int(jnp.max(jnp.abs(q))) <= smax
    np.testing.assert_array_equal(np.asarray(dequantize(q, scales)),
                                  np.asarray(dec))


def test_identity_decode_deferred_is_f32_passthrough():
    u = jnp.asarray(np.random.default_rng(0).normal(size=(4, 9)),
                    jnp.float32)
    codec = CodecConfig(name="identity")
    q, scales, _ = codec.decode_deferred(u, None, jax.random.PRNGKey(0))
    assert scales is None
    assert q is u
    np.testing.assert_array_equal(np.asarray(dequantize(q, scales)),
                                  np.asarray(u))


def test_topk_has_no_deferred_mode():
    codec = CodecConfig(name="topk", topk_ratio=0.5)
    assert not codec.supports_deferred
    with pytest.raises(ValueError, match="sparse f32"):
        codec.decode_deferred(jnp.zeros((2, 8)), None, jax.random.PRNGKey(0))


def test_requantize_rows_keeps_benign_payloads_exact():
    u, codec, q, scales = _payload(n=8, d=57)
    forged = dequantize(q, scales).at[:2].set(3.3)
    mal = jnp.asarray([True, True] + [False] * 6)
    q2, s2 = codec.requantize_rows(forged, q, scales, mal)
    # Benign rows: untouched packed payloads, bit for bit.
    np.testing.assert_array_equal(np.asarray(q2[2:]), np.asarray(q[2:]))
    np.testing.assert_array_equal(np.asarray(s2[2:]), np.asarray(scales[2:]))
    # Malicious rows: on-grid (round-to-nearest of a constant row is the
    # top grid level, so the decode is exact here).
    np.testing.assert_allclose(np.asarray(dequantize(q2, s2)[:2]),
                               np.full((2, 57), 3.3), rtol=1e-6)


# ---------------------------------------------------------------------------
# 2. int8 bundle kernel (interpret mode)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n,d", [(12, 777), (8, 512), (5, 130)])
def test_int8_bundle_interpret_matches_numpy(n, d):
    """Ragged widths (777 = stripe + tail, 130 << stripe), row counts
    off the int8 sublane multiple (12, 5 pad to 32): the int8 kernel's
    integer accumulators match exact integer arithmetic."""
    rng = np.random.default_rng(3)
    q = rng.integers(-127, 128, size=(n, d)).astype(np.int8)
    v = rng.normal(size=(2, d)).astype(np.float32)
    w = rng.normal(size=(1, n)).astype(np.float32)
    out = row_stats_bundle(jnp.asarray(q), sq=True, gram=True, signs=True,
                           dots=jnp.asarray(v), weights=jnp.asarray(w),
                           gram_dot=jnp.asarray(w), d_true=d,
                           interpret=True)
    qf = q.astype(np.float64)
    # Self-contractions are EXACT (int32 stripe sums): compare tight.
    np.testing.assert_allclose(np.asarray(out["sq"]), (qf * qf).sum(1),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out["gram"]), qf @ qf.T, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out["dots"]),
                               qf @ v.astype(np.float64).T, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(out["wsum"]),
                               w.astype(np.float64) @ qf, rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(out["gram_dot"]),
        qf @ (w.astype(np.float64) @ qf).T, rtol=1e-4)


def test_int8_sign_counts_true_width_on_padded_stripes():
    """d_true < allocated width: zero counts derive from d_true, so the
    stripe-alignment padding columns never miscount — and an all-zero
    row reports d_true zeros."""
    rng = np.random.default_rng(5)
    n, d_true, d_alloc = 6, 100, 512
    q = np.zeros((n, d_alloc), np.int8)
    q[:, :d_true] = rng.integers(-3, 4, size=(n, d_true))
    q[0, :] = 0  # all-zero row (scale 0 in the wire payload)
    out = row_stats_bundle(jnp.asarray(q), signs=True, d_true=d_true,
                           interpret=True)
    ref = np.stack([(q[:, :d_true] > 0).sum(1), (q[:, :d_true] < 0).sum(1),
                    (q[:, :d_true] == 0).sum(1)], axis=1)
    np.testing.assert_array_equal(np.asarray(out["signs"]), ref)
    assert np.asarray(out["signs"])[0, 2] == d_true


def test_kernel_gate_int8_row_alignment():
    from blades_tpu.ops.pallas_rowstats import kernel_applicable

    # The envelope itself is backend-gated; on CPU everything is False,
    # so only assert the int8-specific row-alignment DIFFERENCE: an n
    # that passes the float gate must fail the integer gate unless it is
    # a multiple of 32.
    for n in (8, 24, 40):
        assert not kernel_applicable(n, 1 << 20, integer=True) or n % 32 == 0


# ---------------------------------------------------------------------------
# 3. planner scale algebra
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("use_kernel", [False, True])
def test_row_scale_planner_matches_dequantized_planner(use_kernel):
    u, codec, q, scales = _payload(n=16, d=403)
    dec = dequantize(q, scales)
    rng = np.random.default_rng(1)
    v = jnp.asarray(rng.normal(size=(403,)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))
    kinds = ("sq", "gram", "signs", "dots", "wsum", "gram_dot")
    pw = PassPlanner(q, 97, row_scale=scales, use_kernel=use_kernel,
                     interpret=use_kernel)
    pf = PassPlanner(dec, 97, use_kernel=False)
    hw = [pw.sq_norms(), pw.gram(), pw.sign_counts(), pw.dots(v),
          pw.weighted_sum(w), pw.gram_dot(w)]
    hf = [pf.sq_norms(), pf.gram(), pf.sign_counts(), pf.dots(v),
          pf.weighted_sum(w), pf.gram_dot(w)]
    pw.execute()
    pf.execute()
    for kind, a, b in zip(kinds, hw, hf):
        np.testing.assert_allclose(
            np.asarray(a.value), np.asarray(b.value),
            rtol=2e-4, atol=1e-3, err_msg=kind)


def test_row_scale_chunk_only_requests_dequantize_in_flight():
    u, codec, q, scales = _payload(n=10, d=211)
    dec = dequantize(q, scales)
    mal = jnp.asarray([True] * 3 + [False] * 7)
    idx = jnp.asarray([0, 5, 210, 100], jnp.int32)
    ones = jnp.ones((10,), jnp.float32)
    pw = PassPlanner(q, 64, row_scale=scales)
    pf = PassPlanner(dec, 64)
    kw = dict(mask=~mal, row_scale=ones)
    hw = [pw.gather(idx), pw.col_mean_std(mal),
          pw.masked_median(**kw), pw.coordwise(Median())]
    hf = [pf.gather(idx), pf.col_mean_std(mal),
          pf.masked_median(**kw), pf.coordwise(Median())]
    pw.execute()
    pf.execute()
    np.testing.assert_allclose(np.asarray(hw[0].value),
                               np.asarray(hf[0].value), rtol=1e-6)
    for a, b in zip(hw[1].value, hf[1].value):  # (mean, std)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)
    # Order statistics rank the identical decoded values: exact.
    np.testing.assert_array_equal(np.asarray(hw[2].value),
                                  np.asarray(hf[2].value))
    np.testing.assert_array_equal(np.asarray(hw[3].value),
                                  np.asarray(hf[3].value))


def test_dequant_rows_accounting():
    u, codec, q, scales = _payload(n=10, d=211)
    rec = PassRecorder()
    p = PassPlanner(q, 64, row_scale=scales, recorder=rec)
    p.weighted_sum(jnp.ones((10,), jnp.float32))
    p.sq_norms()
    p.gram()
    p.execute()
    # Only the weighted sum materializes a decoded row; the algebraic
    # statistics count zero.
    assert rec.dequant_rows == 1
    assert (rec.executed, rec.unfused) == (1, 3)


# ---------------------------------------------------------------------------
# 4. per-aggregator equivalence (the pinned tolerance)
# ---------------------------------------------------------------------------


def _agg_zoo():
    return [Mean(), Median(), Trimmedmean(num_byzantine=2), GeoMed(),
            Multikrum(num_byzantine=2, k=3),
            DnC(num_byzantine=2, sub_dim=50), Centeredclipping(),
            Signguard(), Clippedclustering(), FLTrust()]


@pytest.mark.parametrize("agg", _agg_zoo(), ids=lambda a: type(a).__name__)
def test_aggregate_wire_matches_decode_then_f32(agg):
    n, d = 16, 403
    u, codec, q, scales = _payload(n=n, d=d)
    dec = dequantize(q, scales)
    key = jax.random.PRNGKey(3)
    trusted = jnp.asarray(
        np.random.default_rng(9).normal(size=(d,)).astype(np.float32))
    st = agg.init(d, n)
    if isinstance(agg, FLTrust):
        ref, _ = agg(jnp.concatenate([dec, trusted[None]], 0), st, key=key)
    else:
        ref, _ = agg(dec, st, key=key)
    out, _, sq = aggregate_wire(agg, q, scales, state=st, key=key,
                                trusted=trusted, d_chunk=128)
    if isinstance(agg, (Median, Trimmedmean)):
        # Order statistics over identical decoded values: EXACT.
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    else:
        scale = float(jnp.max(jnp.abs(ref))) + 1e-12
        err = float(jnp.max(jnp.abs(out - ref))) / scale
        assert err <= WIRE_RTOL, (type(agg).__name__, err)
    np.testing.assert_allclose(np.asarray(sq), (np.asarray(dec) ** 2).sum(1),
                               rtol=2e-4)


def test_aggregate_wire_identity_payload_runs_unscaled():
    """scales=None (the identity wire): the planner runs plain f32
    statistics — same values as a row_scale of ones, no scaling steps."""
    u, _, _, _ = _payload(n=8, d=100)
    out, _, sq = aggregate_wire(Mean(), u, None, d_chunk=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(u).mean(0),
                               rtol=1e-5, atol=1e-6)


def test_wire_aggregators_covers_all_ten():
    assert len(WIRE_AGGREGATORS) == 10
    for agg in _agg_zoo():
        assert isinstance(agg, WIRE_AGGREGATORS)


# ---------------------------------------------------------------------------
# 5. rounds, config gates, driver stamps, autotuner knob
# ---------------------------------------------------------------------------


def _round_pair(aggname, codec, n=8, f=2, adversary=None):
    from blades_tpu.adversaries import get_adversary, make_malicious_mask
    from blades_tpu.core import FedRound, Server, TaskSpec

    task = TaskSpec(model="mlp", input_shape=(8, 8, 1), num_classes=10,
                    lr=0.1).build()
    server = Server.from_config(aggregator=aggname, num_byzantine=f, lr=0.5)
    adv = (get_adversary(adversary, num_clients=n, num_byzantine=f)
           if adversary else None)
    base = dict(task=task, server=server, adversary=adv, batch_size=4,
                num_batches_per_round=1, codec=codec, agg_d_chunk=1 << 10)
    fr32 = FedRound(**base, agg_domain="f32")
    frw = FedRound(**base, agg_domain="wire")
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, 12, 8, 8, 1)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=(n, 12)), jnp.int32)
    ln = jnp.full((n,), 12, jnp.int32)
    mal = make_malicious_mask(n, f)
    st = fr32.init(jax.random.PRNGKey(0), n)
    k = jax.random.PRNGKey(1)
    s32, m32 = jax.jit(fr32.step)(st, x, y, ln, mal, k)
    sw, mw = jax.jit(frw.step)(st, x, y, ln, mal, k)
    return (s32, m32), (sw, mw)


def test_identity_codec_wire_round_bit_identical():
    (s32, m32), (sw, mw) = _round_pair("Multikrum",
                                       CodecConfig(name="identity"))
    for a, b in zip(jax.tree.leaves(s32), jax.tree.leaves(sw)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in m32:
        np.testing.assert_array_equal(np.asarray(m32[k]), np.asarray(mw[k]))


# Whole-round compiles are the expensive part (PR 7 budget convention):
# Multikrum is the headline tier-1 case (geometry + selection through the
# planner); the Mean variant re-proves what the per-aggregator
# equivalence layer already covers, so it rides the slow zoo.
@pytest.mark.parametrize("aggname", [
    pytest.param("Mean", marks=pytest.mark.slow), "Multikrum"])
def test_quant_wire_round_matches_f32_round(aggname):
    (s32, m32), (sw, mw) = _round_pair(aggname,
                                       CodecConfig(name="quant", bits=8))
    for a, b in zip(jax.tree.leaves(s32.server.params),
                    jax.tree.leaves(sw.server.params)):
        scale = float(jnp.max(jnp.abs(a))) + 1e-12
        assert float(jnp.max(jnp.abs(a - b))) / scale <= WIRE_RTOL
    assert float(m32["train_loss"]) == float(mw["train_loss"])
    np.testing.assert_allclose(float(m32["update_norm_mean"]),
                               float(mw["update_norm_mean"]), rtol=1e-4)
    # The wire round stamps the planner's traversal accounting.
    assert int(mw["hbm_passes"]) < int(mw["hbm_passes_unfused"])
    assert int(mw["dequant_rows"]) >= 1
    assert "hbm_passes" not in m32


def test_wire_round_forges_post_codec_in_quantized_domain():
    """ALIE under the wire domain: the forge reads the full quantized
    geometry (dequant_rows includes the n-row materialization) and the
    round stays finite and robust-aggregated."""
    (_, m32), (sw, mw) = _round_pair(
        "Multikrum", CodecConfig(name="quant", bits=8), adversary="ALIE")
    assert np.isfinite(float(mw["agg_norm"]))
    assert int(mw["dequant_rows"]) >= 8  # the forge's full decode
    # Quantized forged rows differ from the f32 domain's full-precision
    # ones by at most the wire grid's resolution — the aggregate stays
    # in the same place.
    np.testing.assert_allclose(float(mw["agg_norm"]), float(m32["agg_norm"]),
                               rtol=0.05)


def _wire_config(**over):
    from blades_tpu.algorithms import FedavgConfig

    cfg = (FedavgConfig()
           .data(dataset="mnist", num_clients=8, seed=1)
           .training(global_model="mlp",
                     aggregator={"type": "Multikrum", "num_byzantine": 2,
                                 "k": 3})
           .adversary(num_malicious_clients=2,
                      adversary_config={"type": "ALIE"})
           .communication(codec={"type": "quant", "bits": 8},
                          agg_domain="wire")
           .evaluation(evaluation_interval=0))
    for k, v in over.items():
        setattr(cfg, k, v)
    return cfg


def test_validate_gates_wire_domain():
    from blades_tpu.algorithms import FedavgConfig

    with pytest.raises(ValueError, match="deferrable codec"):
        _wire_config(codec_config=None).validate()
    with pytest.raises(ValueError, match="deferrable codec"):
        _wire_config(codec_config={"type": "topk"}).validate()
    with pytest.raises(ValueError, match="fault injection"):
        _wire_config(fault_config={"dropout_rate": 0.3}).validate()
    with pytest.raises(ValueError, match="health check"):
        _wire_config(health_check=True).validate()
    with pytest.raises(ValueError, match="forensics"):
        _wire_config(forensics=True).validate()
    with pytest.raises(ValueError, match="DP"):
        _wire_config(dp_clip_threshold=1.0).validate()
    with pytest.raises(ValueError, match="agg_domain"):
        _wire_config(agg_domain="int8").validate()
    # f32 domain with any codec stays valid (the pre-PR surface).
    cfg = _wire_config()
    cfg.agg_domain = "f32"
    cfg.validate()


def test_driver_stamps_wire_provenance_schema_valid():
    from blades_tpu.obs.schema import validate_record

    algo = _wire_config().build()
    row = algo.train()
    assert row["agg_domain"] == "wire"
    assert row["agg_domain_bits"] == 8
    assert row["dequant_rows"] >= 8
    assert row["hbm_passes"] >= 1
    validate_record({"experiment": "e", "trial": "t",
                     **{k: v for k, v in row.items()}})
    # f32-domain rows under the same codec stamp the domain too, with
    # no dequant counter (nothing was packed).
    cfg = _wire_config()
    cfg.agg_domain = "f32"
    row32 = cfg.build().train()
    assert row32["agg_domain"] == "f32"
    assert row32["agg_domain_bits"] == 32
    assert "dequant_rows" not in row32


def test_autotune_agg_domain_reassociating_tier_only():
    from blades_tpu.perf import autotune as at

    space = at.enumerate_plans(
        executions=["dense"], d_chunks=[1 << 17],
        agg_domains=("f32", "wire"), allow_reassociating=True)
    wire = [p for p in space.candidates if p.agg_domain == "wire"]
    assert wire and all(p.tier == at.REASSOCIATING_TIER for p in wire)
    assert space.baseline.agg_domain == "f32"
    # plan_id stays byte-identical for f32 plans; wire plans are marked.
    assert "|wire" not in space.baseline.plan_id
    assert all(p.plan_id.endswith("|wire") for p in wire)
    # The default tier can never be handed a wire plan.
    space_def = at.enumerate_plans(
        executions=["dense"], d_chunks=[1 << 17],
        agg_domains=("f32", "wire"), allow_reassociating=False)
    assert all(p.agg_domain == "f32" for p in space_def.candidates)
    # apply_plan materialises the knob.
    cfg = _wire_config()
    cfg.agg_domain = "f32"
    at.apply_plan(cfg, wire[0])
    assert cfg.agg_domain == "wire"


def test_driver_plan_space_offers_wire_and_probed_packs():
    """The built driver's reassociating plan space: agg_domain=wire
    appears (quant codec, no f32-only features), pack factors come from
    the {2,4,8} probe with impossible factors dropped at enumeration
    (8 clients: every probed factor divides, but the resolver vetoes
    what the model cannot pack), and heuristic selection on CPU stays
    rank 0 — the f32 baseline."""
    from blades_tpu.algorithms import FedavgConfig

    cfg = (FedavgConfig()
           .data(dataset="mnist", num_clients=8, seed=1)
           .training(global_model="mlp",
                     aggregator={"type": "Multikrum", "num_byzantine": 2,
                                 "k": 3})
           .adversary(num_malicious_clients=2,
                      adversary_config={"type": "ALIE"})
           .communication(codec={"type": "quant", "bits": 8})
           .evaluation(evaluation_interval=0))
    algo = cfg.build()
    space = algo._plan_space(allow_reassociating=True)
    domains = {p.agg_domain for p in space.candidates}
    assert domains == {"f32", "wire"}
    assert space.baseline.agg_domain == "f32"
    assert all(p.tier == "reassociating" for p in space.candidates
               if p.agg_domain == "wire")
    assert all(p.client_packing in (1, 2, 4, 8) for p in space.candidates)
    # Default tier never offers wire.
    space_def = algo._plan_space(allow_reassociating=False)
    assert {p.agg_domain for p in space_def.candidates} == {"f32"}
    # Explicit agg_domain pins the list even under the opt-in tier (the
    # fluent setter records explicitness; _wire_config set it to "wire"
    # then we flip the value back, keeping the explicit mark).
    cfg2 = _wire_config()
    cfg2.agg_domain = "f32"
    assert "agg_domain" in cfg2._explicit
    algo2 = cfg2.build()
    space2 = algo2._plan_space(allow_reassociating=True)
    assert {p.agg_domain for p in space2.candidates} == {"f32"}
