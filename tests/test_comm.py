"""Comm subsystem (blades_tpu/comm): compressed update codecs under
Byzantine-robust aggregation.

Covers the tentpole's acceptance criteria:

- the ``identity`` codec is bit-transparent per aggregator (aggregates,
  metrics, AND the full RoundState that checkpoints pickle) — tier-1
  runs the headline aggregators, the rest of the registry rides the
  ``slow`` lane exactly like ``tests/test_perf.py``'s identity sweep;
- stochastic uniform quantization is unbiased in expectation
  (statistical test over PRNG keys) and lands exactly on the
  ``scale * int`` wire grid;
- top-k with error feedback transmits exactly ``k`` coordinates per
  client and conserves mass (``sent + residual == pre-image``), the
  residual survives kill-and-resume bit-identically (the chaos layer's
  resume harness, extended), and the compressed run converges near the
  uncompressed baseline on the 32-client CNN smoke config (slow);
- ``comm_bytes_up`` / ``codec_bits`` / ``comm_compression_ratio`` are
  schema-registered, appear in ``metrics.jsonl`` and sweep summaries
  (sequential AND laned trials), and reconcile with
  ``parallel/comm_model.uplink_bytes``;
- the codec composes with the chaos layer (corruption lands on encoded
  payloads and is still caught by the health machinery).
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.comm import CodecConfig, get_codec
from blades_tpu.core import FedRound, Server, TaskSpec
from blades_tpu.ops.aggregators import AGGREGATORS


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------


def test_codec_config_validates():
    with pytest.raises(ValueError, match="name"):
        CodecConfig("gzip")
    with pytest.raises(ValueError, match="bits"):
        CodecConfig("quant", bits=3)
    with pytest.raises(ValueError, match="topk_ratio"):
        CodecConfig("topk", topk_ratio=0.0)
    with pytest.raises(ValueError, match="topk_ratio"):
        CodecConfig("topk", topk_ratio=1.5)
    hash(CodecConfig("topk", topk_ratio=0.1))  # static jit config


def test_get_codec_resolution():
    assert get_codec(None) is None
    c = get_codec({"type": "quant", "bits": 4})
    assert c.name == "quant" and c.bits == 4
    assert get_codec("identity").name == "identity"
    inst = CodecConfig("topk", topk_ratio=0.5)
    assert get_codec(inst) is inst
    with pytest.raises(ValueError, match="type"):
        get_codec({"bits": 8})


def test_config_builder_validates_codec_and_placement():
    from blades_tpu.algorithms import FedavgConfig

    cfg = FedavgConfig().data(dataset="mnist", num_clients=4)
    cfg.communication(codec={"type": "quant", "bits": 3})
    with pytest.raises(ValueError, match="bits"):
        cfg.validate()
    cfg2 = (FedavgConfig().data(dataset="mnist", num_clients=4)
            .communication(codec={"type": "topk"})
            .resources(execution="streamed"))
    with pytest.raises(ValueError, match="codec"):
        cfg2.validate()
    cfg3 = (FedavgConfig().data(dataset="mnist", num_clients=8)
            .communication(codec={"type": "topk"})
            .resources(num_devices=2))
    with pytest.raises(ValueError, match="codec"):
        cfg3.validate()


# ---------------------------------------------------------------------------
# codec math
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("bits", [8, 4])
def test_quantization_unbiased_in_expectation(bits):
    """Acceptance: E[decode(encode(u))] == u over the rounding keys.

    With K keys the per-coordinate standard error is <= scale / (2*sqrt(K))
    (Bernoulli rounding variance <= scale^2/4); the tolerance sits at
    ~6 sigma, and the deterministic-floor control below shows the test
    has teeth at the same tolerance."""
    codec = CodecConfig("quant", bits=bits)
    u = jax.random.normal(jax.random.PRNGKey(0), (3, 257)) * 2.0
    K = 4096
    keys = jax.random.split(jax.random.PRNGKey(7), K)
    dec = jax.jit(jax.vmap(
        lambda k: codec.encode_decode(u, None, k)[0]))(keys)
    scale = np.asarray(jnp.max(jnp.abs(u), axis=1, keepdims=True)) / (
        2 ** (bits - 1) - 1)
    err = np.asarray(dec.mean(axis=0)) - np.asarray(u)
    tol = 6.0 * scale / (2.0 * np.sqrt(K))
    assert (np.abs(err) <= tol).all(), np.abs(err / scale).max()
    # Teeth: deterministic floor-rounding is biased low by ~scale/2.
    floor_dec = np.floor(np.asarray(u) / scale) * scale
    floor_err = floor_dec - np.asarray(u)
    assert (np.abs(floor_err) > tol).mean() > 0.9


def test_quantization_lands_on_wire_grid():
    """Decoded values are exactly scale * integer in [-s, s] — the codec
    simulates a real int8/int4 wire, not a lossy float blur."""
    for bits in (8, 4):
        codec = CodecConfig("quant", bits=bits)
        s = 2 ** (bits - 1) - 1
        u = jax.random.normal(jax.random.PRNGKey(3), (5, 130))
        dec = codec.encode_decode(u, None, jax.random.PRNGKey(4))[0]
        scale = np.asarray(jnp.max(jnp.abs(u), axis=1, keepdims=True)) / s
        grid = np.asarray(dec) / scale
        np.testing.assert_allclose(grid, np.round(grid), atol=1e-4)
        assert np.abs(grid).max() <= s + 1e-4
    # All-zero rows survive (no 0/0 scale blowup).
    z = codec.encode_decode(jnp.zeros((2, 16)), None, jax.random.PRNGKey(5))[0]
    assert np.asarray(z).tolist() == np.zeros((2, 16)).tolist()


def test_topk_exact_k_and_error_feedback():
    n, d = 4, 200
    codec = CodecConfig("topk", topk_ratio=0.05)  # k = 10
    k = codec.topk_k(d)
    assert k == 10
    u = jax.random.normal(jax.random.PRNGKey(1), (n, d))
    res0 = codec.init_residual(n, d)
    assert res0.shape == (n, d) and not np.asarray(res0).any()
    sent, res1 = codec.encode_decode(u, res0, jax.random.PRNGKey(2))
    # Exactly k transmitted coordinates per client, the k largest.
    nz = np.asarray((sent != 0).sum(axis=1))
    assert nz.tolist() == [k] * n
    thr = np.sort(np.abs(np.asarray(u)), axis=1)[:, -k]
    assert (np.abs(np.asarray(u))[np.asarray(sent) != 0]
            >= np.repeat(thr, k) - 1e-7).all()
    # Error feedback conserves mass: sent + residual == pre-image.
    np.testing.assert_allclose(np.asarray(sent + res1), np.asarray(u),
                               rtol=1e-6)
    # The residual is re-injected: a coordinate too small to transmit
    # accumulates until it wins a later round's selection.
    tiny = jnp.zeros((1, d)).at[0, 0].set(0.3)
    big = jnp.zeros((1, d)).at[0, 1:k + 1].set(1.0)  # exactly k winners
    r = codec.init_residual(1, d)
    sent1, r = codec.encode_decode(tiny + big, r, jax.random.PRNGKey(0))
    assert float(sent1[0, 0]) == 0.0 and float(r[0, 0]) == pytest.approx(0.3)
    # Feed zero fresh updates: the carried 0.3 beats the zeros and ships.
    sent2, r = codec.encode_decode(jnp.zeros((1, d)), r, jax.random.PRNGKey(0))
    assert float(sent2[0, 0]) == pytest.approx(0.3)
    assert float(r[0, 0]) == pytest.approx(0.0)
    # Without error feedback there is no residual state at all.
    nof = CodecConfig("topk", topk_ratio=0.05, error_feedback=False)
    assert not nof.needs_residual and nof.init_residual(n, d) is None
    sent_nof, res_nof = nof.encode_decode(u, None, jax.random.PRNGKey(2))
    assert res_nof is None
    assert np.asarray((sent_nof != 0).sum(axis=1)).tolist() == [k] * n


# ---------------------------------------------------------------------------
# byte accounting: metric <-> analytic model reconciliation
# ---------------------------------------------------------------------------


def test_payload_bytes_reconciles_with_comm_model():
    """The codec's payload_bytes and comm_model.uplink_bytes are two
    INDEPENDENT arithmetics of the same wire — they must agree for every
    codec, and the compressed d-sharded what-if must shrink the swap."""
    from blades_tpu.parallel.comm_model import (dsharded_round_volumes,
                                                uplink_bytes)

    n, d = 32, 136_074
    for codec in (CodecConfig("identity"),
                  CodecConfig("quant", bits=8),
                  CodecConfig("quant", bits=4),
                  CodecConfig("topk", topk_ratio=0.01),
                  CodecConfig("topk", topk_ratio=0.5, error_feedback=False)):
        assert codec.payload_bytes(n, d) == uplink_bytes(n, d, codec), codec
    assert uplink_bytes(n, d) == n * d * 4
    # int8 quant ~4x down, topk-1% ~50x down vs the dense f32 wire.
    dense = uplink_bytes(n, d)
    assert dense / uplink_bytes(n, d, CodecConfig("quant", bits=8)) > 3.9
    assert dense / uplink_bytes(n, d, CodecConfig("topk", topk_ratio=0.01)) > 40
    # The analytic ICI model covers compressed rounds: the axis swap
    # carries the codec payload, every other collective is unchanged.
    base = dsharded_round_volumes(1000, d, 8, update_bytes=4)
    comp = dsharded_round_volumes(1000, d, 8, update_bytes=4,
                                  codec=CodecConfig("quant", bits=8))
    swap_b = next(v for v in base if v.label == "update_matrix_swap")
    swap_c = next(v for v in comp if v.label == "update_matrix_swap")
    assert swap_b.payload_bytes / swap_c.payload_bytes > 3.9
    rest_b = sorted((v.label, v.payload_bytes) for v in base
                    if v.label != "update_matrix_swap")
    rest_c = sorted((v.label, v.payload_bytes) for v in comp
                    if v.label != "update_matrix_swap")
    assert rest_b == rest_c


def test_round_metrics_fields_schema_valid():
    from blades_tpu.obs.schema import validate_record

    m = CodecConfig("quant", bits=4).round_metrics(32, 100_000)
    assert m["comm_bytes_up"] == 32 * (50_000 + 4)
    assert m["codec_bits"] == 4
    assert m["comm_compression_ratio"] == pytest.approx(8.0, rel=1e-3)
    rec = {"experiment": "e", "trial": "t", "training_iteration": 1, **m,
           "elided_lanes": 4}
    assert validate_record(rec) is rec


# ---------------------------------------------------------------------------
# identity codec: bit-transparent per aggregator
# ---------------------------------------------------------------------------

# Tier-1 runs ONE headline aggregator (PR 7 budget rebalance: each case
# compiles two MLP round programs, ~8 s here); the rest of the registry
# runs the identical check in the full suite (`pytest tests/`).
_T1_AGGREGATORS = ("Mean",)


def _tiny_round(agg_name, codec=None, faults=None, **kw):
    from blades_tpu.models import MLP

    task = TaskSpec(model=MLP(hidden1=8, hidden2=8, num_classes=4),
                    input_shape=(8, 8, 1), num_classes=4, lr=0.1).build()
    n, f = 6, 2
    server = Server.from_config(aggregator=agg_name, num_byzantine=f, lr=0.5)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, 12, 8, 8, 1)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, size=(n, 12)), jnp.int32)
    ln = jnp.full((n,), 12, jnp.int32)
    mal = jnp.arange(n) < f
    from blades_tpu.adversaries import get_adversary

    adv = get_adversary({"type": "ALIE"}, num_clients=n, num_byzantine=f)
    fr = FedRound(task=task, server=server, adversary=adv, batch_size=4,
                  num_clients=n, codec=codec, faults=faults,
                  trusted_data=((x[0, :8], y[0, :8])
                                if agg_name == "FLTrust" else None), **kw)
    return fr, (x, y, ln, mal)


@pytest.mark.parametrize("agg_name", [
    a if a in _T1_AGGREGATORS else pytest.param(a, marks=pytest.mark.slow)
    for a in sorted(AGGREGATORS)])
def test_identity_codec_bit_identical_per_aggregator(agg_name):
    """Acceptance: the identity codec reproduces the codec-free round
    bit-for-bit — aggregates, metrics, and the full RoundState that
    checkpoints pickle — for every registered aggregator."""
    fr_off, data = _tiny_round(agg_name, codec=None)
    fr_id, _ = _tiny_round(agg_name, codec=CodecConfig("identity"))
    x, y, ln, mal = data
    s_off = fr_off.init(jax.random.PRNGKey(0), 6)
    s_id = fr_id.init(jax.random.PRNGKey(0), 6)
    # Identity carries no residual: pytrees (and thus checkpoints,
    # sharding specs, donation layouts) are structurally unchanged.
    assert s_id.residual is None and s_id.stale is None
    step_off, step_id = jax.jit(fr_off.step), jax.jit(fr_id.step)
    key = jax.random.PRNGKey(5)
    for r in range(3):
        k = jax.random.fold_in(key, r)
        s_off, m_off = step_off(s_off, x, y, ln, mal, k)
        s_id, m_id = step_id(s_id, x, y, ln, mal, k)
        for mk in ("train_loss", "agg_norm", "update_norm_mean"):
            assert float(m_off[mk]) == float(m_id[mk]), (agg_name, r, mk)
    for a, b in zip(jax.tree.leaves(s_off), jax.tree.leaves(s_id)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=agg_name)


def test_compressing_codec_changes_the_geometry():
    """The inverse control: a real codec must actually alter what the
    aggregator sees (otherwise the identity test proves nothing)."""
    fr_off, data = _tiny_round("Median")
    fr_q, _ = _tiny_round("Median", codec=CodecConfig("quant", bits=4))
    x, y, ln, mal = data
    s_off = fr_off.init(jax.random.PRNGKey(0), 6)
    s_q = fr_q.init(jax.random.PRNGKey(0), 6)
    k = jax.random.PRNGKey(5)
    _, m_off = jax.jit(fr_off.step)(s_off, x, y, ln, mal, k)
    _, m_q = jax.jit(fr_q.step)(s_q, x, y, ln, mal, k)
    assert float(m_off["agg_norm"]) != float(m_q["agg_norm"])
    assert np.isfinite(float(m_q["train_loss"]))


def test_codec_composes_with_fault_injection():
    """Chaos x comm: lane corruption lands on ENCODED payloads (the
    codec runs first) and the health machinery still catches and
    neutralises it; the straggler ring replays post-codec rows."""
    from blades_tpu.faults import FaultInjector

    inj = FaultInjector(seed=3, dropout_rate=0.2, corrupt_rate=0.4,
                        corrupt_mode="nan", num_stragglers=1, staleness=1)
    fr, data = _tiny_round("Median", codec=CodecConfig("topk", topk_ratio=0.1),
                           faults=inj, health_check=True)
    x, y, ln, mal = data
    state = fr.init(jax.random.PRNGKey(0), 6)
    assert state.residual is not None and state.stale is not None
    import functools

    step = jax.jit(functools.partial(fr.multi_step, num_rounds=6))
    state, m = step(state, x, y, ln, mal, jax.random.PRNGKey(2))
    for p in jax.tree.leaves(state.server.params):
        assert jnp.isfinite(p).all()
    assert jnp.isfinite(state.residual).all()
    assert bool((m["num_unhealthy"] >= 0).all())
    assert bool((m["num_participating"] <= 6).all())
    assert bool((m["num_unhealthy"] > 0).any())  # corruption actually fired


# ---------------------------------------------------------------------------
# sweep integration: metrics stream, summaries, laned trials
# ---------------------------------------------------------------------------


def _codec_experiments(codec, rounds=3, **cfg):
    return {
        "comm": {
            "run": "FEDAVG",
            "stop": {"training_iteration": rounds},
            "config": {
                "dataset_config": {"type": "mnist", "num_clients": 6,
                                   "train_bs": 8},
                "global_model": "mlp",
                "evaluation_interval": rounds,
                "server_config": {"lr": 1.0},
                "codec_config": codec,
                **cfg,
            },
        }
    }


def test_compressed_trial_streams_and_summarises_comm_metrics(tmp_path):
    """Acceptance: comm_bytes_up appears per round in metrics.jsonl
    (schema-valid), in the sweep summary, and reconciles with the
    analytic uplink model for a compressed config."""
    from blades_tpu.obs.schema import main as schema_main
    from blades_tpu.parallel.comm_model import uplink_bytes
    from blades_tpu.tune import run_experiments

    codec = {"type": "quant", "bits": 8}
    [s] = run_experiments(_codec_experiments(codec),
                          storage_path=str(tmp_path), verbose=0,
                          lanes=False, cost_analysis=False)
    assert "status" not in s
    d = 136_074  # mnist MLP width (784-128-256-10 + biases)
    want = uplink_bytes(6, d, get_codec(codec))
    assert s["comm"] == {"comm_bytes_up": want, "codec_bits": 8,
                         "comm_compression_ratio":
                             round(6 * d * 4 / want, 4),
                         # Aggregation-domain provenance (ISSUE 11):
                         # stamped whenever a codec is configured so
                         # f32/wire A/B rows are separable.
                         "agg_domain": "f32", "agg_domain_bits": 32}
    tdir = Path(s["dir"])
    assert schema_main([str(tdir / "metrics.jsonl")]) == 0
    rows = [json.loads(l)
            for l in (tdir / "metrics.jsonl").read_text().splitlines()]
    assert len(rows) == 3
    for r in rows:
        assert r["comm_bytes_up"] == want
        assert r["codec_bits"] == 8
        assert r["comm_compression_ratio"] > 3.9


@pytest.mark.slow
def test_laned_trials_carry_comm_metrics(tmp_path):
    """Laned trials (one vmapped program per seed group) stamp the same
    comm fields into every lane's rows — the codec is static shared
    config, so a seed grid lanes exactly as before."""
    from blades_tpu.tune import run_experiments

    exps = _codec_experiments({"type": "topk", "topk_ratio": 0.02},
                              rounds=2, evaluation_interval=0)
    exps["comm"]["config"]["dataset_config"]["seed"] = {
        "grid_search": [1, 2]}
    summaries = run_experiments(exps, storage_path=str(tmp_path), verbose=0,
                                lanes=True, cost_analysis=False)
    assert len(summaries) == 2
    for s in summaries:
        assert s.get("lanes") == 2, s  # actually ran as a lane group
        assert s["comm"]["codec_bits"] == 32
        rows = [json.loads(l) for l in
                (Path(s["dir"]) / "metrics.jsonl").read_text().splitlines()]
        assert rows and all(r["comm_bytes_up"] == s["comm"]["comm_bytes_up"]
                            for r in rows)


# ---------------------------------------------------------------------------
# error-feedback residual across kill-and-resume (satellite)
# ---------------------------------------------------------------------------


def _rows_no_timing(tdir):
    rows = []
    for ln in (Path(tdir) / "result.json").read_text().splitlines():
        r = json.loads(ln)
        r.pop("timers", None)
        r.pop("compile_cache_hits", None)
        r.pop("compile_cache_misses", None)
        rows.append(r)
    return rows


def test_error_feedback_residual_survives_kill_and_resume(tmp_path):
    """Satellite: checkpoint mid-sweep with the top-k codec on, get
    killed (SimulatedPreemption between the result write and the
    checkpoint save), resume from an OLDER checkpoint — the re-run
    rounds must replay the interrupted trajectory bit-identically,
    which only holds if the checkpoint carries the EF residual and
    load_checkpoint restores it (extends tests/test_faults.py's resume
    harness to the comm subsystem)."""
    from blades_tpu.tune import run_experiments
    from blades_tpu.tune.sweep import verify_result_rounds

    # Eval on the FINAL round only: the repeat-last-eval keys rows carry
    # between evals are driver-session state a rebuilt (post-kill) driver
    # does not replay — a cosmetic resume artifact predating the comm
    # subsystem; the trajectory itself (losses, norms, final eval) is
    # what the residual restore must reproduce exactly.
    codec = {"type": "topk", "topk_ratio": 0.02, "error_feedback": True}
    base = run_experiments(
        _codec_experiments(codec, rounds=6, evaluation_interval=6),
        storage_path=str(tmp_path / "base"), verbose=0, lanes=False,
        cost_analysis=False, scan_window=1)
    kill = run_experiments(
        _codec_experiments(codec, rounds=6, evaluation_interval=6),
        storage_path=str(tmp_path / "kill"), verbose=0, lanes=False,
        cost_analysis=False, scan_window=1,
        checkpoint_freq=2, max_failures=1, preempt_after=5,
        retry_backoff_base=0.0)
    (b,), (k,) = base, kill
    assert "status" not in b and "status" not in k
    # The kill really happened and restore came from round 4's checkpoint.
    assert "SimulatedPreemption" in (
        Path(k["dir"]) / "error.txt").read_text()
    assert verify_result_rounds(Path(k["dir"]) / "result.json") == \
        list(range(1, 7))
    # Bit-identical trajectory: every row (losses, norms, eval) equal.
    assert _rows_no_timing(b["dir"]) == _rows_no_timing(k["dir"])


@pytest.mark.slow
def test_load_checkpoint_cold_starts_missing_residual(tmp_path):
    """A checkpoint from a codec-free run resumed under top-k+EF starts
    the residual cold (zeros), exactly like a fresh init — the stale-
    ring-buffer convention.  Slow lane: two fresh Fedavg builds for a
    migration edge path; the residual-restore contract itself is tier-1
    via the kill-and-resume bit-identity test above."""
    from blades_tpu.algorithms import FedavgConfig

    def cfg(codec):
        c = (FedavgConfig().data(dataset="mnist", num_clients=6, seed=3)
             .training(global_model="mlp", server_lr=1.0, train_batch_size=8)
             .client(lr=0.1).evaluation(evaluation_interval=0))
        if codec:
            c.communication(codec=codec)
        return c.build()

    plain = cfg(None)
    plain.train()
    path = plain.save_checkpoint(str(tmp_path / "ck"))
    ef = cfg({"type": "topk", "topk_ratio": 0.05})
    ef.load_checkpoint(path)
    assert ef.state.residual is not None
    assert not np.asarray(ef.state.residual).any()
    ef.train()  # and the compressed round runs from the restored state
    assert np.asarray(ef.state.residual).any()


# ---------------------------------------------------------------------------
# convergence: top-k + EF near the uncompressed baseline (acceptance)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_topk_error_feedback_converges_cnn_smoke():
    """Acceptance: top-k (1%) + error feedback on the 32-client CNN
    smoke config reaches within tolerance of the uncompressed baseline
    in a <= 20-round run — error feedback re-injects the 99% it never
    shipped, so the compressed trajectory tracks the dense one."""
    from blades_tpu.algorithms import FedavgConfig

    def run(codec):
        cfg = (FedavgConfig()
               .data(dataset="mnist", num_clients=32, seed=1)
               .training(global_model="cnn", server_lr=1.0,
                         train_batch_size=32)
               .client(lr=0.1)
               .evaluation(evaluation_interval=20))
        if codec:
            cfg.communication(codec=codec)
        algo = cfg.build()
        row = {}
        for _ in range(20):
            row = algo.train()
        return row

    base = run(None)
    comp = run({"type": "topk", "topk_ratio": 0.01, "error_feedback": True})
    assert np.isfinite(comp["train_loss"])
    assert comp["comm_compression_ratio"] > 40
    # Within tolerance of the uncompressed baseline after 20 rounds.
    assert comp["test_acc"] >= base["test_acc"] - 0.10, (base, comp)
    assert comp["train_loss"] <= base["train_loss"] + 0.5, (base, comp)
