"""The two driver-graded entry points.

Round 1 shipped a broken ``dryrun_multichip`` precisely because no test
imported ``__graft_entry__`` — these tests close that gap:

- ``entry()`` must return ``(fn, example_args)`` that jit-compiles.
- ``dryrun_multichip(8)`` must run in-process (conftest's 8-device CPU
  mesh) AND self-provision its own mesh in a clean subprocess with no
  ``XLA_FLAGS`` — the exact environment the driver calls it from, where
  only one real device is visible and a PJRT relay may pin
  ``jax_platforms``.
"""

import os
import subprocess
import sys

import jax
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_entry_compiles_and_runs():
    sys.path.insert(0, REPO_ROOT)
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 10)
    assert bool(jax.numpy.isfinite(out).all())


@pytest.mark.slow  # full ResNet-18 round on an 8-virtual-device mesh:
# minutes of XLA CPU compile on a 2-core host
def test_dryrun_multichip_inprocess():
    sys.path.insert(0, REPO_ROOT)
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)  # raises on failure


@pytest.mark.slow  # same program compiled from scratch in a clean subprocess
def test_dryrun_multichip_self_provisions_clean_process():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as ge; ge.dryrun_multichip(8)"],
        # Generous: the subprocess compiles the full round from scratch and
        # shares the machine with whatever else the suite is running.
        cwd=REPO_ROOT, env=env, capture_output=True, text=True, timeout=2400,
    )
    assert proc.returncode == 0, f"stderr:\n{proc.stderr}\nstdout:\n{proc.stdout}"
    assert "dryrun_multichip(8): OK" in proc.stdout
