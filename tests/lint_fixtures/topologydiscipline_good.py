"""Clean twin of topologydiscipline_bad.py: table-building without raw
collectives — every value here is host-side graph math, and the actual
exchange goes through the gossip program's counted entry points.  (The
other no-finding direction — raw collectives in files that never touch
the topology tables, e.g. parallel/hier.py's counted gathers — is
covered by the repo-tree scan staying at zero findings.)"""

import numpy as np

from blades_tpu.topology import TopologyConfig, get_topology


def build_tables(spec):
    # Host-side graph math only — no wire traffic to count.
    topo = get_topology(spec, 8)
    tables = topo.neighbor_tables()
    return tables, topo.mixing_matrix(), topo.spectral_gap


def provenance_row(graph="ring"):
    topo = TopologyConfig(graph=graph, num_nodes=8)
    prov = topo.provenance()
    return {k: prov[k] for k in ("topology", "graph_seed", "spectral_gap")}


def degree_stats(spec):
    a = get_topology(spec, 8).adjacency()
    return int(np.max(a.sum(axis=1)))
