"""Clean controller code (blades-lint fixture, never imported): policy
decisions consume the stamped host row the driver already fetched,
cooldowns count ROUNDS (virtual time), and nothing reads a device array
or a wall clock — the shape ``replay_round.py --action`` can re-derive
bit-identically."""


def disciplined_decide(policy, row):
    # The sensor row is host data by contract: the driver stamps
    # suspected_fraction / ledger_top_suspects from its own batched
    # fetch before the controller ever sees the row.
    fired = float(row.get("suspected_fraction") or 0.0)
    suspects = [int(c) for c in row.get("ledger_top_suspects") or ()]
    return (suspects[:policy.quarantine_max]
            if fired > policy.threshold else [])


def disciplined_cooldown(controller, round_idx, family):
    # Round-indexed cooldown: pure in the round counter, so a resumed
    # trial re-derives the identical gate from the checkpointed state.
    if round_idx < controller.cooldown_until.get(family, -1):
        return False
    controller.cooldown_until[family] = \
        round_idx + controller.policy.cooldown_rounds
    return True
