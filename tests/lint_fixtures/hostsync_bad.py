"""Seeded host-sync violations (blades-lint fixture, never imported).

Scanned only when the test instantiates HostSyncPass with this path in
its module list (the real pass scans the DEVICE_SIDE round modules).
"""
import jax
import jax.numpy as jnp
import numpy as np


def leaky_round(state, updates):
    agg = jnp.mean(updates, axis=0)
    norm = float(jnp.linalg.norm(agg))  # BAD: device sync per round
    host = np.asarray(updates)  # BAD: numpy conversion
    scalar = updates.sum().item()  # BAD: .item()
    fetched = jax.device_get(agg)  # BAD: explicit fetch
    agg.block_until_ready()  # BAD: queue drain
    return agg, norm, host, scalar, fetched
