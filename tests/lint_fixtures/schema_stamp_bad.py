"""Round-record stamps with one unregistered key (blades-lint fixture)."""


def fill_round_metrics(row, metrics):
    row["train_loss"] = metrics["train_loss"]
    row["mystery_key"] = 1.0  # BAD: not in ROUND_RECORD_FIELDS
    for k in ("test_acc",):
        row[k] = metrics[k]
    return row
