"""Violations silenced by well-formed pragmas (blades-lint fixture)."""
import jax
import jax.numpy as jnp
import numpy as np


def sanctioned_sync(updates):
    mal = np.asarray(updates)  # blades-lint: disable=host-sync — fixture: once-per-mask-object fetch, sanctioned by design
    fetched = jax.device_get(updates)  # blades-lint: disable=all — fixture: everything sanctioned on this line
    return mal, fetched, jnp.mean(updates)
