"""Malformed pragmas (blades-lint fixture, never imported)."""
import numpy as np


def bare_pragma(updates):
    return np.asarray(updates)  # blades-lint: disable=host-sync


def typod_pragma(updates):
    return np.asarray(updates)  # blades-lint: disable=host-sink — the pass name is misspelled
