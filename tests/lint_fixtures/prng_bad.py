"""Seeded PRNG-reuse violations (blades-lint fixture, never imported)."""
import jax


def double_consume(key, shape):
    a = jax.random.normal(key, shape)
    b = jax.random.uniform(key, shape)  # BAD: same key, second draw
    return a + b


def loop_invariant(key, n):
    total = 0.0
    for _ in range(n):
        total = total + jax.random.normal(key, ())  # BAD: invariant key
    return total


def dropout_reuse(key, x):
    y = keyed_dropout(key, x, 0.5)
    z = keyed_dropout(key, x, 0.5)  # BAD: identical dropout masks
    return y + z


def keyed_dropout(k, x, rate):
    return x
