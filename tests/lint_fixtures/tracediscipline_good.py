"""Clean twin of tracediscipline_bad.py: every timing need met through
the span layer, plus the legal non-measurement uses of ``time``."""

import time

from blades_tpu.obs.trace import Timers, now


def span_timed():
    timers = Timers()
    with timers.time("phase"):
        busy = sum(range(10))
    return timers.summary(), busy


def sanctioned_clock_delta():
    t0 = now()                   # THE sanctioned raw clock
    busy = sum(range(10))
    return now() - t0, busy


def sleeping_is_not_measuring():
    time.sleep(0)                # not a clock read


def injectable_clock_default(clock=time.perf_counter):
    # A clock REFERENCE as an injectable default (the autotuner's
    # measure-fn pattern) is legal; only calls are findings.
    return clock


def pragmad_metadata_stamp():
    return {"created_unix": time.time()}  # blades-lint: disable=trace-discipline — wall-clock metadata stamp, not a duration measurement
