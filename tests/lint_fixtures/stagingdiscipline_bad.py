"""Seeded staging-discipline violations (blades-lint fixture, never
imported): blocking device syncs inside the participation-window
staging hot path, OUTSIDE the sanctioned prefetcher boundary.  Scanned
only when the test instantiates HostSyncPass with this path in its
module list (the real pass scans blades_tpu/state/ via DEVICE_SIDE).
"""
import jax
import jax.numpy as jnp
import numpy as np


def leaky_stage(store, ids, prev_rows):
    rows = store.gather(ids)
    checksum = float(jnp.abs(rows).sum())  # BAD: blocks staging on the device
    host_rows = np.asarray(rows)  # BAD: numpy conversion mid-stage
    return rows, checksum, host_rows


def leaky_writeback_probe(new_state):
    # BAD: fetching per-row norms on the DRIVER thread stalls the
    # dispatch pipeline — the write-back fetch belongs on the worker.
    norms = jax.device_get(jnp.linalg.norm(new_state, axis=1))
    count = new_state.sum().item()  # BAD: .item()
    new_state.block_until_ready()  # BAD: queue drain in the hot path
    return norms, count
