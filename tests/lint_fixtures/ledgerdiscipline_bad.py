"""Seeded ledger-discipline violations (blades-lint fixture, never
imported): device fetches inside a ledger-style per-round update —
the observe() path must consume ALREADY-FETCHED host rows, never pull
from the device itself.  Scanned only when the test instantiates
HostSyncPass with this path in its module list (the real pass scans
blades_tpu/obs/ledger.py via DEVICE_SIDE)."""
import jax
import jax.numpy as jnp
import numpy as np


def leaky_observe(ledger, diag, updates):
    flagged = np.asarray(diag["benign_mask"] <= 0.5)  # BAD: fetches the device mask on the driver thread
    scores = jax.device_get(diag["scores"])  # BAD: per-round device_get outside the batched flush
    norms = jnp.linalg.norm(updates, axis=1)
    worst = float(norms.max())  # BAD: blocks the dispatch pipeline on a reduction
    ledger.observe(np.arange(len(scores)), round=0,
                   flagged=flagged, scores=scores)
    return worst


def leaky_round_fields(ledger, last_agg):
    last_agg.block_until_ready()  # BAD: queue drain before a fleet stat
    seen = int(jnp.count_nonzero(last_agg))  # BAD: int() on a device expression
    return {"ledger_clients_seen": seen}
