"""Device-resident round code (blades-lint fixture, never imported)."""
import jax.numpy as jnp


def clean_round(state, updates, lengths):
    agg = jnp.mean(updates, axis=0)
    arr = jnp.asarray(lengths)  # device op, not a host sync
    k = int(0.2 * updates.shape[0])  # python scalars: fine
    scale = float(2 ** 3 - 1)
    return agg * scale, arr, k
