"""Key discipline done right (blades-lint fixture, never imported)."""
import jax


def split_between(key, shape):
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, shape)
    b = jax.random.uniform(k2, shape)
    return a + b


def resplit_contract(key):
    # Deriving twice from one key (the step/step_prebatched re-split
    # contract) is NOT consumption.
    k_sample = jax.random.split(key, 5)[0]
    k_again = jax.random.split(key, 5)[0]
    return k_sample, k_again


def loop_folded(key, n):
    total = 0.0
    for i in range(n):
        total = total + jax.random.normal(jax.random.fold_in(key, i), ())
    return total


def branch_exclusive(key, flag, shape):
    if flag:
        return jax.random.normal(key, shape)
    else:
        return jax.random.uniform(key, shape)  # exclusive: fine
