"""Clean twin of passdiscipline_bad.py: the same statistics submitted as
planner requests — one fused traversal — plus a same-named helper from a
DIFFERENT module (ops/layout.py's shard math), which must not
false-positive, and the wire-domain dispatch (aggregate_wire: scale
algebra instead of a full decode) with a deferred-decode method call on
the codec CONFIG object (``decode_deferred`` returns the packed payload
— it is not the raw decode primitive)."""

from blades_tpu.comm.codecs import CodecConfig
from blades_tpu.ops.layout import row_sq_norms as layout_row_sq_norms
from blades_tpu.parallel.streamed_geometry import (
    PassPlanner,
    aggregate_wire,
    chunk_grid,
)


def stats(buf, w):
    planner = PassPlanner(buf, 1024)
    h_sq = planner.sq_norms()
    h_g = planner.gram()
    h_ws = planner.weighted_sum(w)
    h_signs = planner.sign_counts()
    planner.execute()  # ONE traversal serves the whole bundle
    return h_sq.value, h_g.value, h_ws.value, h_signs.value


def shard_norms(rows):
    # layout.py's row_sq_norms is per-shard math, not a buffer traversal.
    return layout_row_sq_norms(rows)


def wire_round(agg, updates, residual, key):
    # The sanctioned wire path: the payload stays packed; the planner's
    # scale algebra dequantizes per STATISTIC, never the matrix.
    codec = CodecConfig(name="quant", bits=8)
    q, scales, residual = codec.decode_deferred(updates, residual, key)
    out, state, sq = aggregate_wire(agg, q, scales)
    return out, state, sq, residual


def grid(d, c):
    return chunk_grid(d, c)
