"""Clean data-plane code (blades-lint fixture, never imported): the
sanctioned per-chunk scalar fetch carries a justification pragma; the
shard assembly itself is host numpy over memmaps (no device in sight)
and the staged cohort moves device-ward exactly once."""
import jax.numpy as jnp
import numpy as np


def gather_cohort(maps, order, rows_out):
    for shard, pos, rel in order:
        rows_out[pos] = maps[shard][rel]  # memmap read: host IO, not a sync
    return tuple(jnp.asarray(a) for a in rows_out)  # one host->device move


def accumulate_chunk(chunk_fn, params, cx, cy, lengths, totals):
    sums = chunk_fn(params, cx, cy, lengths)
    for k in ("ce_sum", "top1_sum", "top3_sum", "count"):
        totals[k] += float(sums[k])  # blades-lint: disable=host-sync — sanctioned eval sync: four scalars per chunk, fetched so the full per-client stack never materializes on device
    return totals
