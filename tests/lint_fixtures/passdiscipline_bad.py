"""Seeded streamed-pass-discipline violations: raw traversal primitives
called outside the planner module — each call is a full HBM pass the
planner can no longer fuse (bare import, aliased import, and attribute
access through a module alias) — plus the wire domain's decode-to-f32
primitive (each call dequantizes the full packed matrix, reverting the
wire domain's 4x HBM saving)."""

from blades_tpu.comm import codecs as cc
from blades_tpu.comm.codecs import dequantize
from blades_tpu.parallel.streamed_geometry import gram, row_sq_norms
from blades_tpu.parallel.streamed_geometry import weighted_row_sum as wrs
from blades_tpu.parallel import streamed_geometry as sg


def stats(buf, w):
    sq = row_sq_norms(buf, 1024)        # BAD: dedicated norms pass
    g = gram(buf, 1024)                 # BAD: dedicated Gram pass
    out = wrs(buf, w, 1024)             # BAD: aliased primitive
    signs = sg.sign_counts(buf, 1024)   # BAD: module-attribute primitive
    return sq, g, out, signs


def decode_all(q, scales):
    dense = dequantize(q, scales)       # BAD: full-matrix decode to f32
    dense2 = cc.dequantize(q, scales)   # BAD: module-attribute decode
    return dense, dense2
