"""Seeded arrival-realization IMPURITY (never imported; excluded from
the default tree scan): an arrival process whose "tick" comes from the
wall clock.  The buffered-async determinism contract (blades_tpu/
arrivals) requires realizations pure in (seed, tick) with tick a
VIRTUAL counter — every Date-style clock read below must be caught by
the trace-discipline pass."""

import time
from time import monotonic as mono


def tick_from_wall_clock(epoch_start):
    # A wall-clock-derived tick: two runs of the same seed would realize
    # DIFFERENT arrival masks — kill-and-resume could never replay.
    return int(time.time() - epoch_start)


def arrivals_at_now(process, num_clients, epoch_start):
    tick = int(mono() - epoch_start)   # aliased from-import form
    return process.arrivals_at(tick, num_clients)


def ingest_rate_raw(events):
    # Even the rate measurement must flow through the span layer's
    # sanctioned clock, not a raw perf counter.
    t0 = time.perf_counter()
    return events / max(time.perf_counter() - t0, 1e-9)
