"""Round-record stamps fully covered by the mini schema (fixture)."""


def fill_round_metrics(row, metrics):
    row["train_loss"] = metrics["train_loss"]
    row.update({"test_acc": metrics["test_acc"]})
    return row


def never_stamped_consumer(row):
    # Loads don't count as stamps: reading a key is always safe.
    return row["never_stamped"]
