"""Clean twin of arrivalpurity_bad.py: the arrival process advances a
VIRTUAL tick counter (pure in (seed, tick) — no wall clock anywhere in
the realization path), and the one sanctioned wall-clock need — the
ingest-rate measurement — reads the span layer's ``obs.trace.now()``."""

from blades_tpu.obs.trace import now


def advance_virtual_tick(tick):
    # The ONLY clock the arrival model knows: an integer the engine
    # increments — checkpointed, replayed, bit-identical on resume.
    return tick + 1


def arrivals_at_tick(process, tick, num_clients):
    return process.arrivals_at(tick, num_clients)


def ingest_rate_spanned(events, cycle_start):
    # updates_per_sec through the sanctioned clock (the driver's
    # pattern in algorithms/fedavg.py).
    return events / max(now() - cycle_start, 1e-9)
