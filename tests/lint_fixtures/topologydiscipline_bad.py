"""Seeded topology-discipline violations: a file that builds topology
neighbor tables AND spells raw cross-device collectives — each one an
UNCOUNTED neighborhood exchange, so the round's ``gossip_ici_bytes``
stamp stops reconciling against ``comm_model.gossip_round_volumes``
(bare ``lax.`` call, fully dotted ``jax.lax.`` call, and a psum)."""

import jax
from jax import lax

from blades_tpu.topology import TopologyConfig
from blades_tpu.topology.graph import get_topology


def uncounted_exchange(theta, axis):
    topo = TopologyConfig(graph="ring", num_nodes=8)
    tables = topo.neighbor_tables()
    everyone = lax.all_gather(theta, axis, tiled=True)     # BAD: uncounted
    total = jax.lax.psum(theta, axis)                      # BAD: uncounted
    return everyone[tables.nbr_idx], total


def resolve_and_mix(spec, theta, axis):
    topo = get_topology(spec, 8)
    shifted = jax.lax.ppermute(                            # BAD: uncounted
        theta, axis, [(i, (i + 1) % 8) for i in range(8)])
    return topo.mixing_matrix(), shifted
