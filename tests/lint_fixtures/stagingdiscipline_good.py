"""Clean staging-path code (blades-lint fixture, never imported): the
sanctioned prefetcher-boundary syncs carry justification pragmas; the
assembly itself stays device-side."""
import jax
import jax.numpy as jnp
import numpy as np


def sample_ids(key, n, window):
    ids = jax.random.permutation(key, n)[:window]
    ids = np.asarray(jax.device_get(ids))  # blades-lint: disable=host-sync — sanctioned staging boundary: cohort ids must be host ints to index the store; runs in the prefetcher worker
    return np.sort(ids)


def assemble(new_rows, new_pos, prev_rows, prev_pos, window):
    buf = jnp.zeros((window,) + new_rows.shape[1:], new_rows.dtype)
    buf = buf.at[jnp.asarray(new_pos)].set(new_rows)  # device op, not a sync
    return buf.at[jnp.asarray(prev_pos)].set(prev_rows)


def writeback(store, ids, rows):
    host = np.asarray(rows)  # blades-lint: disable=host-sync — sanctioned staging boundary: the write-back fetch, executed on the prefetcher worker while the next round computes
    store.put(ids, host)
