"""Donation discipline done right (blades-lint fixture, never imported)."""
from functools import partial

import jax


def rebind_form(state, x):
    step = jax.jit(lambda s, v: (s, v), donate_argnums=(0,))
    state, m = step(state, x)
    return state.server  # fine: the donated name was rebound


@partial(jax.jit, donate_argnums=(0,))
def train(s, k):
    return s


def loop_rebind(s0, keys):
    for k in keys:
        s0 = train(s0, k)  # fine: rebound every iteration
    return s0


def no_donation(state, x):
    step = jax.jit(lambda s, v: s)
    _ = step(state, x)
    return state  # fine: nothing was donated
