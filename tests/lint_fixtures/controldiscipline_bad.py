"""Seeded control-discipline violations (blades-lint fixture, never
imported): device fetches and raw wall-clock inside a controller-style
policy decision — decisions must be pure functions of (policy,
pre-state, already-fetched sensor row, round, tick), or the journal
stops being re-derivable by ``replay_round.py --action``.  Scanned only
when the test instantiates the passes with this path (the real passes
scan blades_tpu/control/ via DEVICE_SIDE / the trace-discipline
prefix)."""
import time

import jax
import jax.numpy as jnp
import numpy as np


def leaky_decide(policy, row, scores):
    suspects = np.asarray(row["lane_scores"])  # BAD: fetches the device lanes instead of reading the stamped host row
    worst = float(jnp.max(scores))  # BAD: device reduction blocks the dispatch pipeline mid-decision
    fired = jax.device_get(row["suspected_fraction"])  # BAD: per-round device_get in a decision
    return suspects, worst, fired


def leaky_cooldown(controller, events):
    now = time.time()  # BAD: wall-clock cooldown — actions stop being pure in (round, tick), resume diverges
    stamp = time.perf_counter()  # BAD: raw clock read invisible to the span tree
    controller.last_fire = now
    return [e for e in events if now - controller.last_fire > 5], stamp
