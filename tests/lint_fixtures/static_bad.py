"""Seeded static-config violations (blades-lint fixture, never imported)."""
import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class UnfrozenConfig:  # BAD: mutable jit cache key
    rate: float = 0.0


@dataclasses.dataclass(frozen=True, eq=False)
class IdentityHashConfig:  # BAD: eq=False splits the jit cache
    rate: float = 0.0


@dataclasses.dataclass(frozen=True)
class UnhashableFieldsConfig:
    schedule: List[int] = ()  # BAD: unhashable annotation
    table: Optional[Dict[str, int]] = None  # BAD: dict inside Optional
    hooks: list = dataclasses.field(default_factory=list)  # BAD: twice
