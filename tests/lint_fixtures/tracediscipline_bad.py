"""Seeded trace-discipline violations (never imported; excluded from the
default tree scan).  Every raw clock call here must be caught."""

import time
from time import monotonic as mono
from time import perf_counter


def raw_wall_clock():
    # time.time() — a wall-clock read outside the trace layer.
    return time.time()


def raw_duration():
    t0 = perf_counter()          # from-import form
    busy = sum(range(10))
    return perf_counter() - t0, busy


def raw_monotonic_alias():
    return mono()                # aliased from-import form


def raw_ns_variant():
    return time.perf_counter_ns()
