"""Host state resolved outside the trace (blades-lint fixture)."""
import os

import jax


def resolve_mode():
    # Host read in an UN-jitted wrapper; the result is passed in as a
    # static value — the r5 pallas_round pattern.
    return os.environ.get("BLADES_TPU_FIXTURE_MODE", "fast") == "fast"


def dispatch(x):
    fast = resolve_mode()
    return _step(x, fast)


@jax.jit
def _step(x, fast):
    return x if fast else -x


def host_logger(x):
    print("not traced anywhere", x)  # fine: unreachable from jit
    return x


@jax.jit
def outer_with_host_closure(x):
    def debug_dump(v):  # never referenced: NOT traced with outer
        print("host-only helper", v)

    del debug_dump
    return x * 2
