"""Mini obs-schema module (blades-lint fixture, never imported)."""

ROUND_RECORD_FIELDS = {
    "train_loss": ((int, float), True),
    "test_acc": ((int, float), False),
    "never_stamped": ((int,), False),  # -> registered-but-unstamped WARNING
}
