"""Seeded impure-jit-body violations (blades-lint fixture, never imported)."""
import os

import jax

_MODE = {"value": 0}


@jax.jit
def env_in_jit(x):
    mode = os.environ.get("BLADES_TPU_FIXTURE_MODE", "fast")  # BAD
    return x if mode == "fast" else -x


def helper(x):
    print("tracing", x)  # BAD: reachable from body_jit
    return x * 2


def body_jit(x):
    return helper(x)


def mutating_body(c, x):
    global _MODE  # BAD: trace-time mutation
    _MODE["value"] += 1
    return c + x


def build(fn=mutating_body):
    return jax.jit(mutating_body)
