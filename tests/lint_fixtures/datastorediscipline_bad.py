"""Seeded data-store-discipline violations (blades-lint fixture, never
imported): blocking device syncs inside the out-of-core data plane —
the cohort gather and the streaming evaluator — OUTSIDE the sanctioned
per-chunk scalar fetch.  Scanned only when the test instantiates
HostSyncPass with this path in its module list (the real pass scans
blades_tpu/data/store.py + stream.py via DEVICE_SIDE).
"""
import jax
import jax.numpy as jnp
import numpy as np


def leaky_gather(store, ids):
    rows = store.take(ids)
    checksum = float(jnp.abs(rows[0]).sum())  # BAD: blocks the gather on the device
    host_rows = np.asarray(rows[0])  # BAD: numpy conversion mid-gather
    return rows, checksum, host_rows


def leaky_chunk_eval(chunk_fn, params, cx, cy, lengths):
    sums = chunk_fn(params, cx, cy, lengths)
    # BAD: fetching the whole per-client tensor defeats the chunked
    # evaluator — only the four reduced scalars are sanctioned.
    per_client = jax.device_get(sums)
    count = sums["count"].item()  # BAD: .item()
    sums["ce_sum"].block_until_ready()  # BAD: queue drain in the hot path
    return per_client, count
