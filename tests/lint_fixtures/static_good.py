"""A well-formed static jit-arg config (blades-lint fixture)."""
import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class WellFormedConfig:
    rate: float = 0.0
    schedule: Tuple[Tuple[int, float], ...] = ()
    label: Optional[str] = None


class NotADataclassConfig:
    """Builder-style configs are out of this pass's scope."""

    def __init__(self):
        self.values = {}
