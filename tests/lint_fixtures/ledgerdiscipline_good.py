"""Clean ledger-update code (blades-lint fixture, never imported): the
sanctioned boundary coerces rows the driver ALREADY fetched — numpy in,
numpy out, with justification pragmas on the coercion lines — and the
fleet stats reduce host columns, not device arrays."""
import numpy as np


def disciplined_observe(ledger, row_lanes, cohort_ids):
    ids = np.asarray(cohort_ids, np.int64)  # blades-lint: disable=host-sync — sanctioned ledger boundary: cohort ids arrive as already-fetched host data
    flagged = np.asarray(row_lanes["benign_mask"], np.float64) <= 0.5  # blades-lint: disable=host-sync — sanctioned ledger boundary: the mask is a slice of the row the driver already fetched
    scores = np.asarray(row_lanes["scores"], np.float64)  # blades-lint: disable=host-sync — sanctioned ledger boundary: already-fetched row slice
    ledger.observe(ids, round=0, flagged=flagged, scores=scores)


def disciplined_fleet_view(participation):
    seen = participation > 0  # host column: ledger state never lives on device
    return {"ledger_clients_seen": int(seen.sum())}  # blades-lint: disable=host-sync — sanctioned ledger boundary: numpy reduction over a host column
