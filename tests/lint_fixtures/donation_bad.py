"""Seeded use-after-donate violations (blades-lint fixture, never imported)."""
from functools import partial

import jax


def assigned_form(state, x):
    step = jax.jit(lambda s, v: (s, v), donate_argnums=(0,))
    new_state, m = step(state, x)
    return state.server  # BAD: read after donation (line 10)


@partial(jax.jit, donate_argnums=(0,))
def train(s, k):
    return s


def loop_form(s0, keys):
    out = None
    for k in keys:
        out = train(s0, k)  # BAD: s0 donated in iteration 1, read in 2
    return out


def conditional_donate(state, x, fast):
    donate = (0,) if fast else ()
    step = jax.jit(lambda s, v: s, donate_argnums=donate)
    _ = step(state, x)
    return state  # BAD: state may have been donated
