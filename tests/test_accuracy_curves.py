"""The accuracy-curve harness (VERDICT r1 #10): one command -> PNG + JSON."""

import json

import pytest


@pytest.mark.slow  # full curve-harness sweep (~20 s; the harness is also driven by the resume test in the slow lane)
def test_accuracy_curves_one_command(tmp_path):
    from blades_tpu.benchmarks.accuracy_curves import main

    rc = main([
        "--dataset", "mnist", "--rounds", "6", "--num-clients", "8",
        "--aggregators", "Mean", "Median", "--malicious", "0", "2",
        "--rounds-per-dispatch", "3", "--out", str(tmp_path),
    ])
    assert rc == 0
    table = json.loads((tmp_path / "curves.json").read_text())
    assert len(table["rows"]) == 4
    assert "SYNTHETIC" in table["source"]  # no raw files in CI
    for row in table["rows"]:
        assert row["rounds"] == 6
        assert 0.0 <= row["final_test_acc"] <= 1.0
    # "complete" means the full REFERENCE grid (9 aggregators x 0-30%),
    # which this 2x2 smoke run is NOT; "planned_complete" tracks the
    # invocation's own rows (VERDICT r4 weak #6).
    assert table["planned_complete"] is True
    assert table["complete"] is False
    assert "Centeredclipping@0" in table["reference_cells_missing"]
    png = (tmp_path / "curves.png").read_bytes()
    assert png[:8] == b"\x89PNG\r\n\x1a\n"


@pytest.mark.slow  # second full grid run (~16 s; the one-command path stays tier-1)
def test_resume_from_completes_a_grid(tmp_path):
    """--resume-from seeds prior cells, skips them, and the stitched
    table/plot cover the union (the mechanism for completing the IPM
    grids to the reference matrix without re-running finished cells)."""
    from blades_tpu.benchmarks.accuracy_curves import main

    first = tmp_path / "a"
    rc = main(["--dataset", "mnist", "--rounds", "4", "--num-clients", "8",
               "--aggregators", "Mean", "--malicious", "0", "2",
               "--rounds-per-dispatch", "2", "--out", str(first)])
    assert rc == 0

    second = tmp_path / "b"
    rc = main(["--dataset", "mnist", "--rounds", "4", "--num-clients", "8",
               "--aggregators", "Mean", "Median", "--malicious", "0", "2",
               "--rounds-per-dispatch", "2", "--out", str(second),
               "--resume-from", str(first / "curves.json")])
    assert rc == 0
    table = json.loads((second / "curves.json").read_text())
    cells = {(r["aggregator"], r["num_malicious"]) for r in table["rows"]}
    assert cells == {("Mean", 0), ("Mean", 2), ("Median", 0), ("Median", 2)}
    assert table["planned_complete"] is True
    # Seeded cells were not re-run: their results carry over verbatim.
    prior = json.loads((first / "curves.json").read_text())["rows"]
    for r in prior:
        assert r in table["rows"]

    # A mismatched configuration refuses to stitch.
    import pytest

    with pytest.raises(SystemExit, match="mismatch"):
        main(["--dataset", "mnist", "--rounds", "6", "--num-clients", "8",
              "--aggregators", "Mean", "--malicious", "0",
              "--out", str(tmp_path / "c"),
              "--resume-from", str(first / "curves.json")])


def test_synthetic_heterogeneity_widens_benign_spread():
    """The per-client drift dial must actually widen the benign update
    spread (the mechanism VERDICT r4 #3 asks for): with h > 0 the
    per-client class-conditional means differ, so client gradients
    disagree more — measured here directly on the data: the
    across-client dispersion of per-class feature means grows, while
    h=0 reproduces the historical generator bit-for-bit."""
    import numpy as np

    from blades_tpu.data import DatasetCatalog

    base = DatasetCatalog.get_dataset(
        {"type": "cifar10", "synthetic_noise": 3.0}, num_clients=12, seed=3)
    het = DatasetCatalog.get_dataset(
        {"type": "cifar10", "synthetic_noise": 3.0,
         "synthetic_heterogeneity": 2.0}, num_clients=12, seed=3)
    zero = DatasetCatalog.get_dataset(
        {"type": "cifar10", "synthetic_noise": 3.0,
         "synthetic_heterogeneity": 0.0}, num_clients=12, seed=3)

    assert base.synthetic and het.synthetic
    # h=0 is exactly the historical generator.
    np.testing.assert_array_equal(base.train.x, zero.train.x)
    np.testing.assert_array_equal(base.train.y, zero.train.y)
    # Labels (the Dirichlet/IID partition) are untouched by h.
    np.testing.assert_array_equal(base.train.y, het.train.y)
    np.testing.assert_array_equal(base.train.lengths, het.train.lengths)

    def class_mean_dispersion(part):
        # Per-COORDINATE across-client std of each class's per-client
        # mean vector (a scalar all-coordinate mean would cancel the
        # zero-mean directional shifts), averaged over coords + classes.
        disps = []
        for c in range(10):
            per_client = []
            for i in range(part.num_clients):
                n = int(part.lengths[i])
                yi, xi = part.y[i, :n], part.x[i, :n]
                if (yi == c).any():
                    per_client.append(
                        xi[yi == c].reshape(-1, xi[0].size).mean(axis=0))
            if len(per_client) >= 2:
                disps.append(np.std(np.stack(per_client), axis=0).mean())
        return float(np.mean(disps))

    assert class_mean_dispersion(het.train) > \
        3.0 * class_mean_dispersion(base.train)
