"""The accuracy-curve harness (VERDICT r1 #10): one command -> PNG + JSON."""

import json


def test_accuracy_curves_one_command(tmp_path):
    from blades_tpu.benchmarks.accuracy_curves import main

    rc = main([
        "--dataset", "mnist", "--rounds", "6", "--num-clients", "8",
        "--aggregators", "Mean", "Median", "--malicious", "0", "2",
        "--rounds-per-dispatch", "3", "--out", str(tmp_path),
    ])
    assert rc == 0
    table = json.loads((tmp_path / "curves.json").read_text())
    assert len(table["rows"]) == 4
    assert "SYNTHETIC" in table["source"]  # no raw files in CI
    for row in table["rows"]:
        assert row["rounds"] == 6
        assert 0.0 <= row["final_test_acc"] <= 1.0
    png = (tmp_path / "curves.png").read_bytes()
    assert png[:8] == b"\x89PNG\r\n\x1a\n"
