"""Out-of-core per-client state tests (blades_tpu/state, ISSUE 15):

- store protocol: gather/scatter round trips, shard-checkpoint
  streaming, cross-backend restore;
- chaos on the store: torn/corrupt shard fail-fast, orphaned ``.tmp``
  cleanup, missing-manifest fail-fast;
- the cohort-equivalence CONTRACT: ``resident`` / ``host`` / ``disk``
  produce bit-identical rows, aggregates and server params for the
  same (seed, cohort schedule) — staging forced on for the host arm,
  so prefetch on/off identity rides the same check — across Mean
  (tier-1) + Multikrum + GeoMed (slow zoo), including a topk+EF codec
  run whose residual round-trips through the store;
- kill-and-resume: a mid-sweep SimulatedPreemption under
  ``state_store="disk"`` resumes bit-identically from the streaming
  shard checkpoints;
- the window=0 stateless degenerate case, validate()-time gates, the
  autotune plan knobs, schema registration, and the scaled-down
  acceptance demo: 10k registered / 256 sampled clients on CPU with
  the asserted window-proportional peak-HBM bound.
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.algorithms import FedavgConfig
from blades_tpu.state import (
    DiskStore,
    HostStore,
    ResidentStore,
    StateStoreError,
    make_store,
    sample_cohort,
)

ROW_KEYS = ("train_loss", "agg_norm", "update_norm_mean")


def windowed_config(backend=None, window=4, *, seed=3, prefetch=False,
                    aggregator="Mean", codec=None, momentum=0.9, **overrides):
    """``backend=None`` leaves state_store DEFAULTED (resident) so the
    autotuner's composition contract sees an un-pinned knob."""
    cfg = (
        FedavgConfig()
        .data(dataset="mnist", num_clients=8, seed=seed)
        .training(global_model="mlp", server_lr=1.0, train_batch_size=8,
                  aggregator={"type": aggregator})
        .client(lr=0.1, momentum=momentum)
        .evaluation(evaluation_interval=0)
        .resources(state_store=backend, window=window)
    )
    cfg.prefetch = prefetch
    if codec is not None:
        cfg.communication(codec=codec)
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


def _server_params(algo):
    return [np.asarray(p) for p in jax.tree.leaves(algo.state.server.params)]


def _store_rows(algo):
    """Every registered client's state rows, fetched through the store."""
    algo._state_pf.flush()
    rows = algo._state_store.gather(np.arange(algo.config.num_clients))
    return [np.asarray(l) for l in jax.tree.leaves(rows)]


# ---------------------------------------------------------------------------
# cohort sampling
# ---------------------------------------------------------------------------


def test_sample_cohort_deterministic_sorted_distinct():
    k = jax.random.PRNGKey(7)
    a = sample_cohort(k, 1000, 64)
    b = sample_cohort(k, 1000, 64)
    np.testing.assert_array_equal(a, b)          # pure in the round key
    assert a.dtype == np.int32
    assert np.all(np.diff(a) > 0)                # sorted, distinct
    assert a.min() >= 0 and a.max() < 1000
    c = sample_cohort(jax.random.PRNGKey(8), 1000, 64)
    assert not np.array_equal(a, c)              # key steers the draw
    full = sample_cohort(k, 16, 16)
    np.testing.assert_array_equal(full, np.arange(16))  # window == n
    with pytest.raises(ValueError):
        sample_cohort(k, 10, 11)


# ---------------------------------------------------------------------------
# store protocol: round trips + shard checkpoints + chaos
# ---------------------------------------------------------------------------


def _template():
    return {"opt": {"buf": jnp.zeros((5,), jnp.float32)},
            "residual": jnp.zeros((3,), jnp.float32)}


@pytest.mark.parametrize("backend", ["resident", "host", "disk"])
def test_store_gather_scatter_roundtrip(backend, tmp_path):
    store = make_store(backend, 12, _template(),
                       directory=str(tmp_path / "live"))
    try:
        ids = np.array([1, 4, 9], np.int32)
        rows = {"opt": {"buf": jnp.arange(15, dtype=jnp.float32)
                        .reshape(3, 5)},
                "residual": -jnp.ones((3, 3), jnp.float32)}
        store.scatter(ids, rows)
        got = store.gather(ids)
        np.testing.assert_array_equal(np.asarray(got["opt"]["buf"]),
                                      np.asarray(rows["opt"]["buf"]))
        np.testing.assert_array_equal(np.asarray(got["residual"]),
                                      np.asarray(rows["residual"]))
        # Untouched rows keep the template values.
        other = store.gather(np.array([0, 11], np.int32))
        np.testing.assert_array_equal(np.asarray(other["opt"]["buf"]),
                                      np.zeros((2, 5), np.float32))
        assert store.row_bytes == (5 + 3) * 4
        assert store.total_bytes() == 12 * 8 * 4
        assert (store.device_bytes() == store.total_bytes()
                if backend == "resident" else store.device_bytes() == 0)
    finally:
        store.close()


def test_disk_store_unsorted_ids_across_shards(tmp_path):
    """Regression (review): the async engine gathers event clients in
    FIFO arrival order — a multi-shard DiskStore must honor ARBITRARY
    id order on both gather and scatter, not just the sorted ids the
    sync cohort path produces."""
    template = {"buf": jnp.zeros((2,), jnp.float32)}
    store = DiskStore(10, template, directory=str(tmp_path / "live"),
                      shard_rows=3)  # ids span 4 shards
    try:
        ids = np.array([7, 0, 9, 3], np.int32)  # unsorted, cross-shard
        rows = {"buf": jnp.asarray(
            [[70.0, 71.0], [0.0, 1.0], [90.0, 91.0], [30.0, 31.0]])}
        store.scatter(ids, rows)
        got = store.gather(ids)
        np.testing.assert_array_equal(np.asarray(got["buf"]),
                                      np.asarray(rows["buf"]))
        # Sorted view agrees row-for-row with the unsorted write.
        sorted_got = store.gather(np.array([0, 3, 7, 9], np.int32))
        np.testing.assert_array_equal(
            np.asarray(sorted_got["buf"]),
            np.asarray(rows["buf"])[np.argsort(ids)])
    finally:
        store.close()


def test_prefetcher_surfaces_writeback_failure():
    """Regression (review): a store scatter that fails on the staging
    worker must re-raise on the driver thread (writeback reap / flush),
    never silently serve stale rows."""
    from blades_tpu.state import StatePrefetcher

    class ExplodingStore(HostStore):
        def scatter(self, ids, rows):
            raise OSError("disk full")

    store = ExplodingStore(8, _template())
    data = (np.zeros((8, 2, 2), np.float32), np.zeros((8, 2), np.int32),
            np.full((8,), 2, np.int32))
    pf = StatePrefetcher(store, data, np.zeros(8, bool),
                         lambda k: np.arange(4, dtype=np.int32),
                         async_staging=True)
    try:
        pf.writeback(np.arange(4, dtype=np.int32),
                     store.gather(np.arange(4)))
        with pytest.raises(OSError, match="disk full"):
            pf.flush()
    finally:
        pf.close()


def test_shard_checkpoint_cross_backend_restore(tmp_path):
    """A checkpoint streamed from one backend restores into any other,
    rows bit-equal — shards are the one on-disk format."""
    src = make_store("host", 10, _template())
    ids = np.arange(10, dtype=np.int32)
    rows = {"opt": {"buf": jnp.arange(50, dtype=jnp.float32)
                    .reshape(10, 5)},
            "residual": jnp.arange(30, dtype=jnp.float32).reshape(10, 3)}
    src.scatter(ids, rows)
    src.save(tmp_path / "ckpt", shard_rows=3)  # forces multiple shards
    manifest = json.loads((tmp_path / "ckpt" / "manifest.json").read_text())
    assert manifest["num_shards"] == 4 and len(manifest["files"]) == 8
    for backend in ("resident", "disk"):
        dst = make_store(backend, 10, _template(),
                         directory=str(tmp_path / f"live-{backend}"))
        try:
            dst.load(tmp_path / "ckpt")
            got = dst.gather(ids)
            np.testing.assert_array_equal(np.asarray(got["opt"]["buf"]),
                                          np.asarray(rows["opt"]["buf"]))
            np.testing.assert_array_equal(np.asarray(got["residual"]),
                                          np.asarray(rows["residual"]))
        finally:
            dst.close()


def test_torn_shard_and_orphan_tmp_chaos(tmp_path):
    """Chaos on the store checkpoint: a truncated shard and a
    bit-flipped shard both fail fast naming the file; an orphaned
    ``.tmp`` (killed atomic write) is cleaned up; a missing manifest —
    the kill-before-publish state — fails fast too."""
    store = make_store("host", 8, _template())
    store.save(tmp_path / "ckpt", shard_rows=4)
    shard = tmp_path / "ckpt" / "shard-00001.l00.npy"

    # Orphaned .tmp from a killed write: cleaned, restore succeeds.
    orphan = tmp_path / "ckpt" / "shard-00000.l00.npy.tmp"
    orphan.write_bytes(b"half-written garbage")
    make_store("host", 8, _template()).load(tmp_path / "ckpt")
    assert not orphan.exists()

    # Torn shard (truncation): loud failure naming the shard.
    data = shard.read_bytes()
    shard.write_bytes(data[: len(data) // 2])
    with pytest.raises(StateStoreError, match="shard-00001.l00.npy"):
        make_store("host", 8, _template()).load(tmp_path / "ckpt")

    # Same-size corruption: the CRC catches what the size check cannot.
    flipped = bytearray(data)
    flipped[-1] ^= 0xFF
    shard.write_bytes(bytes(flipped))
    with pytest.raises(StateStoreError, match="CRC32"):
        make_store("host", 8, _template()).load(tmp_path / "ckpt")
    shard.write_bytes(data)

    # Kill before the manifest publish: no manifest, no restore.
    (tmp_path / "ckpt" / "manifest.json").unlink()
    with pytest.raises(StateStoreError, match="manifest"):
        make_store("host", 8, _template()).load(tmp_path / "ckpt")

    # Population / layout drift fail fast as their own errors.
    store.save(tmp_path / "ckpt2", shard_rows=4)
    with pytest.raises(StateStoreError, match="registered clients"):
        make_store("host", 9, _template()).load(tmp_path / "ckpt2")


# ---------------------------------------------------------------------------
# the cohort-equivalence contract
# ---------------------------------------------------------------------------

# Tier-1 runs the headline aggregator; Multikrum/GeoMed run the same
# contract in the slow zoo (each backend arm is its own compile — the
# 870 s tier-1 budget convention of PR 7).
_CONTRACT_AGGREGATORS = ("Mean",)


@pytest.mark.parametrize("aggregator", [
    a if a in _CONTRACT_AGGREGATORS else pytest.param(
        a, marks=pytest.mark.slow)
    for a in ("Mean", "Multikrum", "GeoMed")])
def test_cohort_equivalence_across_backends(aggregator):
    """The contract: host and disk stores produce bit-identical rows,
    aggregates and server params to resident for the same (seed,
    cohort schedule).  The host arm runs with staging forced ON, so
    the double-buffered prefetcher (overlap patching included — window
    6 of 8 guarantees cohort overlap; 6 also satisfies Multikrum's
    2f+2 <= window bound at f=2) is part of the identity."""
    adv = {"num_malicious_clients": 2, "adversary_config": {"type": "ALIE"}}
    algos = {
        "resident": windowed_config("resident", 6, aggregator=aggregator,
                                    **adv).build(),
        "host": windowed_config("host", 6, aggregator=aggregator,
                                prefetch=True, **adv).build(),
        "disk": windowed_config("disk", 6, aggregator=aggregator,
                                **adv).build(),
    }
    try:
        rows = {k: [a.train() for _ in range(4)] for k, a in algos.items()}
        for r_res, r_host, r_disk in zip(rows["resident"], rows["host"],
                                         rows["disk"]):
            for k in ROW_KEYS:
                assert r_res[k] == r_host[k] == r_disk[k], (
                    aggregator, k, r_res[k], r_host[k], r_disk[k])
        params = {k: _server_params(a) for k, a in algos.items()}
        stores = {k: _store_rows(a) for k, a in algos.items()}
        for k in ("host", "disk"):
            for a, b in zip(params["resident"], params[k]):
                np.testing.assert_array_equal(a, b, err_msg=(aggregator, k))
            for a, b in zip(stores["resident"], stores[k]):
                np.testing.assert_array_equal(a, b, err_msg=(aggregator, k))
    finally:
        for a in algos.values():
            a.stop()


# Codec-EF x store composition (~8 s compile); the store's headline
# cross-backend equivalence stays tier-1 via
# test_cohort_equivalence_across_backends[Mean] (PR 20 budget rebalance).
@pytest.mark.slow
def test_topk_ef_residual_through_store():
    """topk+EF codec under the window: the per-client error-feedback
    residual lives in the store (windowed like the opt state) and the
    compressed trajectory is backend-invariant bit for bit."""
    codec = {"type": "topk", "topk_ratio": 0.1, "error_feedback": True}
    res = windowed_config("resident", 5, aggregator="Median",
                          codec=codec).build()
    host = windowed_config("host", 5, aggregator="Median", codec=codec,
                           prefetch=True).build()
    try:
        assert "residual" in res._row_template
        for _ in range(4):
            a, b = res.train(), host.train()
            for k in ROW_KEYS:
                assert a[k] == b[k], (k, a[k], b[k])
        for x, y in zip(_store_rows(res), _store_rows(host)):
            np.testing.assert_array_equal(x, y)
        # The residual genuinely accumulated (EF is active, not zeros).
        full = res._state_store.gather(np.arange(8))
        assert float(np.abs(np.asarray(full["residual"])).sum()) > 0.0
    finally:
        res.stop()
        host.stop()


# ---------------------------------------------------------------------------
# kill-and-resume on the windowed store
# ---------------------------------------------------------------------------


def _ooc_experiments(stop=8):
    return {
        "ooc": {
            "run": "FEDAVG",
            "stop": {"training_iteration": stop},
            "config": {
                "dataset_config": {"type": "mnist", "num_clients": 8,
                                   "train_bs": 8, "seed": 3},
                "global_model": "mlp",
                "client_config": {"lr": 0.1, "momentum": 0.9},
                "evaluation_interval": 4,
                "server_config": {"lr": 1.0,
                                  "aggregator": {"type": "Median"}},
                "state_store": "disk",
                "state_window": 5,
            },
        }
    }


def _result_rows(tdir, keep_eval_rounds=(4, 8)):
    rows = []
    for ln in (Path(tdir) / "result.json").read_text().strip().splitlines():
        r = json.loads(ln)
        for k in ("timers", "compile_cache_hits", "compile_cache_misses",
                  "state_stage_ms", "state_bytes_staged", "data_stage_ms"):
            r.pop(k, None)  # wall-clock / cache / staging-timing noise
        if r["training_iteration"] not in keep_eval_rounds:
            # Repeat-last-eval rows: _last_eval is not checkpointed (a
            # restored trial repeats nothing until its next fresh eval)
            # — pre-existing driver behavior on every path, so only
            # FRESH eval rounds participate in the bit-identity check.
            for k in ("test_loss", "test_acc", "test_acc_top3"):
                r.pop(k, None)
        rows.append(r)
    return rows


def test_kill_and_resume_disk_store_bit_identical(tmp_path):
    """Acceptance: a SimulatedPreemption mid-sweep under
    state_store="disk" retries from the latest STREAMING shard
    checkpoint and reproduces the straight-through rows exactly (the
    faults/ preemption harness, pointed at the windowed store)."""
    from blades_tpu.tune import run_experiments
    from blades_tpu.tune.sweep import verify_result_rounds

    [straight] = run_experiments(
        _ooc_experiments(), storage_path=str(tmp_path / "a"), verbose=0,
        lanes=False, checkpoint_freq=2)
    [preempted] = run_experiments(
        _ooc_experiments(), storage_path=str(tmp_path / "b"), verbose=0,
        lanes=False, checkpoint_freq=2, max_failures=1, preempt_after=5,
        retry_backoff_base=0.0)
    assert "status" not in preempted and preempted["rounds"] == 8
    tdir = Path(preempted["dir"])
    assert "SimulatedPreemption" in (tdir / "error.txt").read_text()
    assert verify_result_rounds(tdir / "result.json") == list(range(1, 9))
    # The resumed trajectory IS the straight-through one, row for row.
    assert _result_rows(straight["dir"]) == _result_rows(tdir)
    # Checkpoints hold streaming shards, not monolithic stacks.
    ckpts = sorted(tdir.glob("ckpt_*/client_state/manifest.json"))
    assert ckpts, "windowed checkpoints must carry shard files"


# ---------------------------------------------------------------------------
# stateless degenerate case + resident default + validate gates
# ---------------------------------------------------------------------------


def test_stateless_window0_and_resident_default():
    """window=0: round 1 matches the stateful run bit for bit (momentum
    buffers start at zero either way), round 2 diverges (the buffer was
    reset).  The default config builds NO store and keeps the cohort
    leaf None — the pre-PR pytree."""
    stateful = windowed_config(window=None).build()
    stateless = windowed_config("resident", 0).build()
    assert stateful._state_store is None
    assert getattr(stateful.state, "cohort", None) is None
    assert stateless._state_store is None  # nothing to store
    assert stateless.fed_round.stateless_clients
    a1, b1 = stateful.train(), stateless.train()
    for k in ROW_KEYS:
        assert a1[k] == b1[k], (k, a1[k], b1[k])
    a2, b2 = stateful.train(), stateless.train()
    assert a2["agg_norm"] != b2["agg_norm"]


def test_stateless_auto_execution_stays_dense(monkeypatch):
    """Regression (review): with window=0, execution='auto' must NOT
    resolve to the streamed path — streamed threads client_opt through
    its own block loop and would silently train STATEFUL clients."""
    monkeypatch.setenv("BLADES_TPU_DENSE_MATRIX_LIMIT_GB", "0.000001")
    stateful = windowed_config(window=None, prefetch=False).build()
    assert stateful._use_streamed()  # the tiny budget DOES trip auto...
    stateless = windowed_config("resident", 0, prefetch=False).build()
    assert not stateless._use_streamed()  # ...but stateless stays dense
    assert stateless.fed_round.stateless_clients
    r = stateless.train()
    assert np.isfinite(r["train_loss"])


def test_validate_gates():
    def check(match, **kw):
        with pytest.raises(ValueError, match=match):
            cfg = windowed_config(**kw)
            cfg.validate()

    check("needs a participation window", backend="host", window=None)
    check("cohort samples without replacement", backend="host", window=9)
    check("no windowed formulation", backend="host", window=4,
          execution="streamed")
    check("num_devices>1 is an unsupported", backend="host", window=4,
          num_devices=2)
    check("fault injection", backend="host", window=4,
          fault_config={"dropout_rate": 0.3})
    check("rounds_per_dispatch", backend="host", window=4,
          rounds_per_dispatch=2)
    check("nothing for a 'host' store", backend="host", window=0)
    check("num_devices>1 is an unsupported", backend="resident", window=0,
          num_devices=2)
    check("top-k error-feedback", backend="resident", window=0,
          codec={"type": "topk", "topk_ratio": 0.1,
                 "error_feedback": True})
    check("state_store must be one of", backend="ramdisk", window=4)
    check("no windowed formulation", backend="host", window=4,
          execution="async")
    # Legal compositions still validate.
    windowed_config("disk", 4, health_check=True).validate()
    windowed_config("host", 4,
                    codec={"type": "quant", "bits": 8}).validate()
    # Forensics composes since the cohort-shaped re-index (ISSUE 16):
    # the windowed round diagnoses the (window, d) cohort matrix.
    windowed_config("host", 4, forensics=True).validate()


# ---------------------------------------------------------------------------
# async out-of-core composition
# ---------------------------------------------------------------------------


def test_async_event_cohort_through_store():
    """execution='async' + host store: the event cohort's opt rows are
    gathered/scattered per cycle (cohort-windowed cycle buffers) and
    the buffered trajectory is bit-identical to the resident engine."""
    spec = {"rate": 0.5, "agg_every": 4, "staleness_cap": 4}
    def build(backend):
        cfg = windowed_config(window=None, aggregator="Median")
        cfg.resources(execution="async")
        if backend != "resident":
            cfg.resources(state_store=backend)
        cfg.async_config = spec
        return cfg.build()

    res, host = build("resident"), build("host")
    try:
        assert host._state_store is not None and host._async is not None
        for _ in range(3):
            a, b = res.train(), host.train()
            for k in ROW_KEYS + ("tick",):
                assert a[k] == b[k], (k, a[k], b[k])
        assert b["state_store"] == "host" and b["cohort_size"] == 4
        # The driver-side RoundState never carries the full opt stack.
        assert host.state.client_opt is None
    finally:
        res.stop()
        host.stop()


# ---------------------------------------------------------------------------
# obs schema + autotune plan knobs
# ---------------------------------------------------------------------------


def test_windowed_row_stamps_schema_valid():
    from blades_tpu.obs.schema import ROUND_RECORD_FIELDS, validate_record

    algo = windowed_config("host", 4).build()
    try:
        row = algo.train()
    finally:
        algo.stop()
    stamps = {k: row[k] for k in ("state_store", "cohort_size",
                                  "state_stage_ms", "state_bytes_staged",
                                  "state_peak_hbm_bytes")}
    assert stamps["state_store"] == "host" and stamps["cohort_size"] == 4
    assert stamps["state_bytes_staged"] > 0
    assert set(stamps) <= set(ROUND_RECORD_FIELDS)
    validate_record({"experiment": "e", "trial": "t",
                     "training_iteration": 1, **stamps})


def test_plan_state_knobs():
    from blades_tpu.perf.autotune import Plan, apply_plan, enumerate_plans

    # Store-free plans keep the byte-identical pre-knob id format.
    assert Plan().plan_id == "dense|c131072|p1|mxu=off|w1|nopre"
    windowed = Plan(state_store="host", state_window=256)
    assert windowed.plan_id.endswith("|ss=hostw256")
    with pytest.raises(ValueError):
        Plan(state_store="ramdisk")
    # Backend alternates are reassociating-tier; the window is pinned.
    space = enumerate_plans(
        executions=["dense"], d_chunks=[1 << 17],
        state_stores=["disk", "host", "resident"], state_windows=[16],
        allow_reassociating=True)
    assert space.baseline.state_store == "disk"
    tiers = {p.state_store: p.tier for p in space.candidates}
    assert tiers["disk"] == "default"
    assert tiers["host"] == tiers["resident"] == "reassociating"
    default_only = enumerate_plans(
        executions=["dense"], d_chunks=[1 << 17],
        state_stores=["disk", "host"], state_windows=[16],
        allow_reassociating=False)
    assert [p.state_store for p in default_only.candidates] == ["disk"]
    cfg = windowed_config("disk", 16)
    apply_plan(cfg, Plan(state_store="host", state_window=16,
                         tier="reassociating"))
    assert cfg.state_store == "host" and cfg.state_window == 16


def test_driver_plan_space_probes_backends():
    """The reassociating tier offers the alternate store backends for a
    windowed trial whose backend was left DEFAULTED (window pinned
    either way); an explicitly-set backend pins the list and the
    default tier never varies it — the composition contract."""
    cfg = windowed_config(window=4, autotune="on")  # backend defaulted
    algo = cfg.build()
    try:
        assert "state_store" not in cfg._explicit
        default = algo._plan_space(allow_reassociating=False)
        assert {p.state_store for p in default.candidates} == {"resident"}
        re = algo._plan_space(allow_reassociating=True)
        assert {p.state_store for p in re.candidates} == {"resident",
                                                          "host"}
        assert {p.state_window for p in re.candidates} == {4}
        assert re.baseline.state_store == "resident"
    finally:
        algo.stop()
    pinned = windowed_config("disk", 4, autotune="on").build()
    try:
        re = pinned._plan_space(allow_reassociating=True)
        assert {p.state_store for p in re.candidates} == {"disk"}
    finally:
        pinned.stop()


# ---------------------------------------------------------------------------
# the scaled-down acceptance demo: 10k registered / 256 sampled on CPU
# ---------------------------------------------------------------------------


def _tiny_population_dataset(n_clients, rows_per_client=4, shape=(4, 4, 1),
                             num_classes=2, seed=0):
    from blades_tpu.data.datasets import FLDataset
    from blades_tpu.data.partition import partition_dataset

    rng = np.random.default_rng(seed)
    n = n_clients * rows_per_client
    mus = rng.normal(size=(num_classes,) + shape).astype(np.float32)
    y = rng.integers(0, num_classes, size=n).astype(np.int32)
    x = (mus[y] + 0.5 * rng.normal(size=(n,) + shape)).astype(np.float32)
    train = partition_dataset(x, y, n_clients, iid=True, seed=seed)
    test = partition_dataset(x[: 2 * n_clients], y[: 2 * n_clients],
                             n_clients, iid=True, seed=seed + 1)
    return FLDataset(name="tinypop", train=train, test_x=x[:64],
                     test_y=y[:64], test=test, num_classes=num_classes,
                     input_shape=shape)


def test_10k_registered_256_sampled_memory_ceiling():
    """The acceptance demo, scaled for CPU tier-1: 10 000 registered
    clients / 256 sampled per round train through the host store, and
    the asserted peak device-resident state is WINDOW-proportional —
    a small multiple of the cohort working set, an order of magnitude
    under the O(n_registered * d) resident stack this store removes."""
    from blades_tpu.models.mlp import MLP

    n, w = 10_000, 256
    cfg = (
        FedavgConfig()
        .data(dataset=_tiny_population_dataset(n), num_clients=n, seed=0)
        .training(global_model=MLP(hidden1=8, hidden2=8, num_classes=2),
                  num_classes=2, input_shape=(4, 4, 1), server_lr=0.5,
                  train_batch_size=4)
        .client(lr=0.1, momentum=0.9)
        .evaluation(evaluation_interval=0)
        .resources(state_store="host", window=w)
    )
    algo = cfg.build()
    try:
        rows = [algo.train() for _ in range(2)]
        for r in rows:
            assert np.isfinite(r["train_loss"])
        row_bytes = algo._state_store.row_bytes
        assert row_bytes > 0
        data_bytes = sum(np.asarray(a[:w]).nbytes
                         for a in algo._host_train)
        peak = rows[-1]["state_peak_hbm_bytes"]
        # Window-proportional: the staged + live + write-back cohort
        # slots plus the cohort's data shards...
        assert peak <= 3 * w * row_bytes + data_bytes
        # ...and nowhere near the resident stack it replaces.
        assert peak < n * row_bytes // 4
        assert algo._state_store.total_bytes() == n * row_bytes
        assert rows[-1]["cohort_size"] == w
    finally:
        algo.stop()
