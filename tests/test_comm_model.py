"""The analytic ICI byte model vs the ACTUAL compiled d-sharded program.

VERDICT r4 weak #5: the multi-chip projection used an arbitrary 0.7
discount.  The replacement (parallel/comm_model.py) is only credible if
its collective inventory matches what XLA emits — so these tests lower
:func:`dsharded_step` on the 8-device virtual mesh, scrape every
collective op (kind + payload bytes) out of the compiled HLO, and
reconcile the multiset against :func:`dsharded_round_volumes`.

Coverage (ADVICE r5 #2): the HLO reconciliation runs over EVERY
registered aggregator.  Tier-1 keeps the four headline configurations
(Median/Multikrum under the bench adversaries + the health-check and
fori-loop structural cases); the remaining aggregators carry
``@pytest.mark.slow`` — each is another 8-virtual-device shard_map
compile, minutes of wall clock this 2-core box's tier-1 budget cannot
absorb — and run in the full suite (``pytest tests/``).
"""

import dataclasses
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.adversaries import get_adversary, make_malicious_mask
from blades_tpu.core import FedRound, Server, TaskSpec
from blades_tpu.ops.aggregators import AGGREGATORS
from blades_tpu.parallel import make_mesh, shard_federation
from blades_tpu.parallel.comm_model import (
    CollectiveVolume,
    dsharded_round_volumes,
    ici_seconds,
    project_multichip_rounds_per_sec,
    wire_bytes_per_chip,
)
from blades_tpu.parallel.dsharded import dsharded_step

N, F = 16, 4

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2, "s16": 2,
                "u16": 2, "f32": 4, "s32": 4, "u32": 4, "f64": 8, "s64": 8,
                "u64": 8}

# f32[1,2,17010] -> bytes; tuples handled by summing all shapes in the
# operand list of the op line.
_SHAPE_RE = re.compile(r"(pred|bf16|f16|f32|f64|s8|u8|s16|u16|s32|u32|s64|u64)"
                       r"\[([\d,]*)\]")


def _shape_bytes(m: re.Match) -> int:
    dt, dims = m.group(1), m.group(2)
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES[dt]


def hlo_collectives(txt: str):
    """(kind, payload_bytes) for every PROGRAM-ISSUED collective in a
    compiled HLO.

    The payload is read from the op's RESULT shape(s) — for all-gather
    that is the gathered size, for all-to-all the (tuple) total equals
    the per-chip payload, for all-reduce the reduced buffer.

    One class of op is excluded: all-reduces whose ``op_name`` metadata
    ends in ``/sort``.  Those are the CPU SPMD partitioner's chosen
    IMPLEMENTATION of a *replicated* sort inside the shard_map body
    (``argsort`` in the clustering aggregators,
    ``jax.random.permutation``'s ``_shuffle`` in DnC): every chip holds
    identical data, the partitioner splits the sort anyway and merges
    with count all-reduces.  They are a backend lowering strategy for
    redundantly-replicated work — not collectives the round's math
    issues, and not something the one-axis TPU ring model should charge
    wire time for (a replicated sort needs no exchange).  The explicit
    program collectives all carry ``psum``/``all_gather``/``all_to_all``
    op_names from the shard_map body and are counted in full.
    """
    out = []
    for line in txt.splitlines():
        line = line.strip()
        m = re.match(r"%?\S+\s*=\s*(.*?)\s*(all-to-all|all-gather|all-reduce|"
                     r"reduce-scatter|collective-permute)\(", line)
        if not m:
            continue
        op_name = re.search(r'op_name="([^"]*)"', line)
        if (m.group(2) == "all-reduce" and op_name
                and op_name.group(1).endswith("/sort")):
            continue  # replicated-sort lowering artifact (see docstring)
        kind = {"all-to-all": "all_to_all", "all-gather": "all_gather",
                "all-reduce": "psum", "reduce-scatter": "reduce_scatter",
                "collective-permute": "permute"}[m.group(2)]
        payload = sum(_shape_bytes(s) for s in _SHAPE_RE.finditer(m.group(1)))
        out.append((kind, payload))
    return out


def make_fr(aggregator, adversary, **fr_kw):
    task = TaskSpec(model="mlp", lr=0.1, input_shape=(28, 28, 1)).build()
    server = Server.from_config(aggregator=aggregator, num_byzantine=F, lr=1.0)
    adv = get_adversary(adversary, num_clients=N, num_byzantine=F)
    fr = FedRound(task=task, server=server, adversary=adv, batch_size=8,
                  **fr_kw)
    if aggregator == "FLTrust":
        rng = np.random.default_rng(7)
        tx = jnp.asarray(rng.normal(size=(32, 28, 28, 1)), jnp.float32)
        ty = jnp.asarray(rng.integers(0, 10, size=(32,)), jnp.int32)
        fr = dataclasses.replace(fr, trusted_data=(tx, ty))
    return fr


def model_kwargs_for(aggregator_obj, d: int) -> dict:
    """The comm model's per-aggregator knobs, read off the INSTANCE the
    compiled program actually closes over — so the reconciliation tests
    cannot drift from aggregator defaults."""
    kw = {}
    if type(aggregator_obj).__name__ == "GeoMed":
        kw["geomed_maxiter"] = aggregator_obj.maxiter
    elif type(aggregator_obj).__name__ == "DnC":
        kw["dnc_num_iters"] = aggregator_obj.num_iters
        kw["dnc_sub_dim"] = min(aggregator_obj.sub_dim, d)
    elif type(aggregator_obj).__name__ == "Centeredclipping":
        kw["cc_n_iter"] = aggregator_obj.n_iter
    return kw


@pytest.fixture(scope="module")
def fed_data():
    from blades_tpu.data import DatasetCatalog

    ds = DatasetCatalog.get_dataset("mnist", num_clients=N)
    return (jnp.array(ds.train.x), jnp.array(ds.train.y),
            jnp.array(ds.train.lengths), make_malicious_mask(N, F))


def compiled_collectives(fr, fed_data):
    mesh = make_mesh()
    st = fr.init(jax.random.PRNGKey(0), N)
    st, arrs = shard_federation(mesh, st, fed_data)
    step = dsharded_step(fr, mesh)
    txt = step.lower(st, *arrs, jax.random.PRNGKey(1)).compile().as_text()
    return hlo_collectives(txt)


# Tier-1: the four headline configurations.  The rest of the registry
# runs the identical reconciliation in the full suite (slow lane): each
# case is another 8-virtual-device shard_map compile.
_T1_CASES = [
    ("Median", "ALIE", False),   # the bench headline round
    ("Median", "ALIE", True),
    ("Multikrum", "IPM", False),
    ("Median", "MinMax", False),  # grounds the 12-step bisection count
]
_T1_AGGS = {a for a, _, _ in _T1_CASES}


@pytest.mark.parametrize("aggregator,adversary,health", _T1_CASES + [
    pytest.param(a, "ALIE", False, marks=pytest.mark.slow)
    for a in sorted(set(AGGREGATORS) - _T1_AGGS)
])
def test_model_inventory_matches_compiled_hlo(fed_data, aggregator,
                                              adversary, health):
    fr = make_fr(aggregator, adversary, health_check=health)
    d = sum(p.size for p in jax.tree.leaves(
        fr.task.init_params(jax.random.PRNGKey(0))))
    got = compiled_collectives(fr, fed_data)

    vols = dsharded_round_volumes(
        N, d, 8, update_bytes=4,  # f32 updates on the CPU test config
        aggregator=aggregator, adversary=adversary, health_check=health,
        **model_kwargs_for(fr.server.aggregator, d))

    # Two structural caveats make per-op matching impossible:
    # - XLA's all-reduce combiner may MERGE independent psums into one
    #   op (seen: Multikrum's pairwise 1024 B + metrics row_norms 64 B
    #   -> a single 1088 B all-reduce);
    # - a psum inside a lax.fori_loop body appears ONCE in the static
    #   HLO while executing `count` times (MinMax's 12 bisection steps).
    # So reconcile STATIC total payload bytes per collective kind; the
    # wire model separately scales loop-resident ops by their dynamic
    # count (CollectiveVolume.in_loop documents which is which).
    def totals(pairs):
        t = {}
        for kind, b in pairs:
            t[kind] = t.get(kind, 0) + b
        return t

    want = totals((v.kind, v.static_bytes) for v in vols)
    assert totals(got) == want, (
        f"compiled HLO collectives {sorted(got)} != model {sorted(want.items())}"
    )


def test_comm_model_covers_every_registered_name():
    """Every registered adversary and every d-sharded aggregator must
    resolve to a volume inventory — the model may never crash a
    projection over a runnable configuration."""
    from blades_tpu.adversaries import ADVERSARIES
    from blades_tpu.ops.aggregators import AGGREGATORS

    for adv in [None, *ADVERSARIES]:
        for agg in AGGREGATORS:
            vols = dsharded_round_volumes(16, 5000, 8, aggregator=agg,
                                          adversary=adv)
            assert vols and all(v.payload_bytes >= 0 for v in vols)


def test_wire_bytes_ring_factors():
    # 1 MB payloads, k=8: a2a/ag send 7/8, psum sends 2*7/8.
    MB = 1 << 20
    assert CollectiveVolume("x", "all_to_all", MB).wire_bytes(8) == MB * 7 // 8
    assert CollectiveVolume("x", "all_gather", MB).wire_bytes(8) == MB * 7 // 8
    assert CollectiveVolume("x", "psum", MB).wire_bytes(8) == MB * 7 // 4
    assert CollectiveVolume("x", "psum", MB, count=3).wire_bytes(8) == \
        3 * MB * 7 // 4


def test_projection_is_dominated_by_the_axis_swap():
    """At the ResNet-18 n=1000 v5e-8 configuration the all-to-all of the
    bf16 update matrix must dominate the wire bytes, and the derived
    projection must sit between the naive perfect-scaling number and a
    number acknowledging comm is not free."""
    d = 11_173_962
    vols = dsharded_round_volumes(1000, d, 8, update_bytes=2,
                                  aggregator="Median", adversary="ALIE")
    by_wire = sorted(vols, key=lambda v: -v.wire_bytes(8))
    assert by_wire[0].label == "update_matrix_swap"
    # 125 rows x ~11.17M f16 coords ~ 2.8 GB payload per chip.
    assert 2.0e9 < by_wire[0].payload_bytes < 3.5e9

    proj = project_multichip_rounds_per_sec(
        measured_rps=1.1, n_benign_measured=576,
        n_target=1000, n_dev=8, d=d, num_malicious=250)
    # Comm-free bound: 576 trained-client-rounds/s per chip over the
    # 125 - floor(250/8) = 94 lanes each chip trains under d-sharded
    # elision (the 250 mod 8 = 2 remainder lanes train in tails).
    assert proj["trained_lanes_per_chip"] == 94
    perfect = 1.1 * 576 / 94
    assert proj["rounds_per_sec"] < perfect
    assert proj["rounds_per_sec"] > perfect * 0.5

    # The elision discount only applies under the runtime's own gates:
    # a non-forging adversary (or f < n_dev) trains every lane.
    no_forge = project_multichip_rounds_per_sec(
        measured_rps=1.1, n_benign_measured=576,
        n_target=1000, n_dev=8, d=d, adversary="SignFlip",
        num_malicious=250)
    assert no_forge["trained_lanes_per_chip"] == 125
    assert no_forge["rounds_per_sec"] < proj["rounds_per_sec"]
    assert proj["dominant_collective"] == "update_matrix_swap"
    assert proj["t_ici_s"] > 0
    # The comm term actually derives from the volumes.
    np.testing.assert_allclose(
        proj["t_ici_s"], ici_seconds(vols, 8), rtol=0.02)
    assert proj["wire_bytes_per_chip"] == wire_bytes_per_chip(vols, 8)
