"""tools/check_tier1_budget.py — the tier-1 wall-time guard + slow-marker
audit.  Running the audit here against the REAL test tree is the CI
enforcement: an unmarked 8-device-mesh test lands as a tier-1 failure.
"""

import subprocess
import sys
import textwrap
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))

import check_tier1_budget as guard  # noqa: E402

REPO = Path(__file__).resolve().parents[1]

_LOG_OK = textwrap.dedent("""\
    ........ [100%]
    ============ slowest 3 durations ============
    46.46s call     tests/test_obs.py::test_sweep
    12.00s call     tests/test_x.py::test_y
    0.50s setup    tests/test_x.py::test_y
    ====== 358 passed, 1 skipped in 500.27s (0:08:20) ======
""")

_LOG_OVER = _LOG_OK.replace("in 500.27s (0:08:20)", "in 850.00s (0:14:10)")


def test_parse_durations_and_total():
    rows = guard.parse_durations(_LOG_OK)
    assert rows == [(46.46, "call", "tests/test_obs.py::test_sweep"),
                    (12.0, "call", "tests/test_x.py::test_y"),
                    (0.5, "setup", "tests/test_x.py::test_y")]
    assert guard.parse_total_seconds(_LOG_OK) == 500.27


def test_projection_prefers_summary_then_durations():
    proj, src = guard.projected_tier1_seconds(_LOG_OK)
    assert proj == 500.27 and "summary" in src
    no_summary = "\n".join(l for l in _LOG_OK.splitlines()
                           if "passed" not in l)
    proj, src = guard.projected_tier1_seconds(no_summary)
    assert abs(proj - 58.96) < 1e-6 and "durations" in src
    proj, src = guard.projected_tier1_seconds("nothing useful")
    assert proj is None


def test_budget_guard_thresholds(tmp_path):
    log = tmp_path / "t1.log"
    log.write_text(_LOG_OK)
    assert guard.check_budget(log, cap=870.0, threshold=0.85) == []
    log.write_text(_LOG_OVER)
    problems = guard.check_budget(log, cap=870.0, threshold=0.85)
    assert len(problems) == 1 and "850.0s exceeds" in problems[0]
    # the hotspot hints name the heaviest test
    assert "test_obs.py::test_sweep" in problems[0]
    # a missing log is a violation (the guard must not silently pass)
    assert guard.check_budget(tmp_path / "absent.log", 870.0, 0.85)


def test_marker_audit_flags_unmarked_mesh_tests(tmp_path):
    bad = tmp_path / "test_bad.py"
    bad.write_text(textwrap.dedent("""\
        import pytest
        from blades_tpu.parallel import make_mesh

        @pytest.fixture(scope="module")
        def setup():
            mesh = make_mesh()
            return mesh

        def test_uses_fixture(setup):
            pass

        def test_direct_call():
            m = make_mesh(num_devices=8)

        @pytest.mark.slow
        def test_marked_is_fine():
            m = make_mesh()

        def test_unrelated():
            pass
    """))
    msgs = guard.audit_file(bad)
    assert len(msgs) == 2
    assert any("test_uses_fixture" in m and "fixture 'setup'" in m
               for m in msgs)
    assert any("test_direct_call" in m for m in msgs)
    # module-level pytestmark covers everything
    marked = tmp_path / "test_marked.py"
    marked.write_text("import pytest\npytestmark = pytest.mark.slow\n"
                      + bad.read_text().split("\n", 1)[1])
    assert guard.audit_file(marked) == []


def test_repo_test_tree_passes_the_audit():
    """CI enforcement: every test in THIS repo that builds the 8-device
    mesh must be slow-marked."""
    assert guard.check_markers(REPO / "tests") == []


def test_cli_end_to_end(tmp_path):
    log = tmp_path / "t1.log"
    log.write_text(_LOG_OK)
    r = subprocess.run(
        [sys.executable, str(REPO / "tools" / "check_tier1_budget.py"),
         "--log", str(log), "--tests-dir", str(REPO / "tests")],
        capture_output=True, text=True, cwd=str(REPO))
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout
    log.write_text(_LOG_OVER)
    r = subprocess.run(
        [sys.executable, str(REPO / "tools" / "check_tier1_budget.py"),
         "--log", str(log), "--budget-only"],
        capture_output=True, text=True, cwd=str(REPO))
    assert r.returncode == 1
    assert "exceeds" in r.stderr
