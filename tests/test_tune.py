"""Sweep / CLI tests (model: blades/train.py behavior, SURVEY.md §2.1)."""

import json
from pathlib import Path

import pytest
import yaml

from blades_tpu.tune import expand_grid, load_experiments_from_file, run_experiments


def test_expand_grid_no_grids():
    cfg = {"a": 1, "b": {"c": 2}}
    assert expand_grid(cfg) == [cfg]


def test_expand_grid_cartesian_product():
    cfg = {
        "x": {"grid_search": [1, 2]},
        "nested": {"y": {"grid_search": ["a", "b", "c"]}},
        "fixed": 0,
    }
    trials = expand_grid(cfg)
    assert len(trials) == 6
    assert {(t["x"], t["nested"]["y"]) for t in trials} == {
        (i, s) for i in (1, 2) for s in "abc"
    }
    assert all(t["fixed"] == 0 for t in trials)


def test_expand_grid_dict_values():
    cfg = {"agg": {"grid_search": [{"type": "Mean"}, {"type": "Median"}]}}
    trials = expand_grid(cfg)
    assert [t["agg"]["type"] for t in trials] == ["Mean", "Median"]


def test_load_experiments_requires_run(tmp_path):
    f = tmp_path / "bad.yaml"
    f.write_text(yaml.safe_dump({"exp": {"config": {}}}))
    with pytest.raises(ValueError, match="run"):
        load_experiments_from_file(str(f))


def test_run_experiments_end_to_end(tmp_path):
    experiments = {
        "smoke": {
            "run": "FEDAVG",
            "stop": {"training_iteration": 6},
            "config": {
                "dataset_config": {"type": "mnist", "num_clients": 6, "train_bs": 16},
                "global_model": "mlp",
                "evaluation_interval": 3,
                "server_config": {"lr": 1.0,
                                  "aggregator": {"grid_search": [
                                      {"type": "Mean"}, {"type": "Median"}]}},
            },
        }
    }
    summaries = run_experiments(
        experiments, storage_path=str(tmp_path), verbose=0, checkpoint_at_end=True
    )
    assert len(summaries) == 2  # aggregator grid
    for s in summaries:
        tdir = Path(s["dir"])
        lines = (tdir / "result.json").read_text().strip().splitlines()
        assert len(lines) == 6
        last = json.loads(lines[-1])
        assert last["training_iteration"] == 6
        assert "test_acc" in last
        assert (tdir / "ckpt_final" / "algorithm_state.pkl").exists()
        assert (tdir / "params.json").exists()
        assert s["best_test_acc"] > 0.3


def test_cli_file_command(tmp_path):
    from blades_tpu.train import main

    exp = {
        "cli_smoke": {
            "run": "FEDAVG",
            "stop": {"training_iteration": 3},
            "config": {
                "dataset_config": {"type": "mnist", "num_clients": 4, "train_bs": 8},
                "global_model": "mlp",
                "evaluation_interval": 3,
                "server_config": {"lr": 1.0},
            },
        }
    }
    f = tmp_path / "exp.yaml"
    f.write_text(yaml.safe_dump(exp))
    rc = main(["file", str(f), "--storage-path", str(tmp_path / "out")])
    assert rc == 0
    assert (tmp_path / "out" / "cli_smoke").exists()


def test_tuned_examples_parse_and_expand():
    """Every shipped YAML grid must load and expand (the reference's
    tuned_examples are its canonical envelope, SURVEY.md §6)."""
    root = Path(__file__).parent.parent / "blades_tpu" / "tuned_examples"
    yamls = sorted(root.glob("*.yaml"))
    assert len(yamls) >= 5
    for y in yamls:
        exps = load_experiments_from_file(str(y))
        for name, spec in exps.items():
            trials = expand_grid(spec["config"])
            assert len(trials) >= 1


def test_run_experiments_counts_rounds_not_calls(tmp_path):
    """With rounds_per_dispatch > 1, the stop criterion is FL rounds."""
    experiments = {
        "chunked": {
            "run": "FEDAVG",
            "stop": {"training_iteration": 6},
            "config": {
                "dataset_config": {"type": "mnist", "num_clients": 4, "train_bs": 8},
                "global_model": "mlp",
                "rounds_per_dispatch": 3,
                "evaluation_interval": 3,
                "server_config": {"lr": 1.0},
            },
        }
    }
    [s] = run_experiments(experiments, storage_path=str(tmp_path), verbose=0)
    assert s["rounds"] == 6
    lines = (Path(s["dir"]) / "result.json").read_text().strip().splitlines()
    assert len(lines) == 2  # two dispatches of 3 rounds
    assert json.loads(lines[-1])["training_iteration"] == 6


def _resume_experiments(rounds):
    return {
        "resumable": {
            "run": "FEDAVG",
            "stop": {"training_iteration": rounds},
            "config": {
                "dataset_config": {"type": "mnist", "num_clients": 4, "train_bs": 8},
                "global_model": "mlp",
                "evaluation_interval": 2,
                "server_config": {"lr": 1.0},
            },
        }
    }


def test_sweep_resume_kill_and_rerun(tmp_path):
    """The reference CLI's --restore/resume semantics (ref: blades/
    train.py:154,228): a killed grid continues from checkpoints without
    redoing finished trials."""
    # Phase 1: "killed" after 4 of 8 rounds (checkpoint every 2).
    run_experiments(_resume_experiments(4), storage_path=str(tmp_path),
                    verbose=0, checkpoint_freq=2)
    tdir = tmp_path / "resumable" / "resumable_00000"
    assert (tdir / "ckpt_000004").exists()

    # Phase 2: resume to 8 rounds — must restore from round 4, not restart.
    [s] = run_experiments(_resume_experiments(8), storage_path=str(tmp_path),
                          verbose=0, checkpoint_freq=2, resume=True)
    assert s["resumed"] == "from round 4"
    assert s["rounds"] == 8
    lines = (tdir / "result.json").read_text().strip().splitlines()
    iters = [json.loads(ln)["training_iteration"] for ln in lines]
    assert iters == [1, 2, 3, 4, 5, 6, 7, 8]  # appended, no rework

    # Phase 3: rerun — the finished trial is skipped untouched.
    mtime = (tdir / "result.json").stat().st_mtime
    [s2] = run_experiments(_resume_experiments(8), storage_path=str(tmp_path),
                           verbose=0, resume=True)
    assert s2["resumed"] == "skipped"
    assert s2["rounds"] == 8
    assert (tdir / "result.json").stat().st_mtime == mtime


def test_sweep_checkpoint_keep_num(tmp_path):
    run_experiments(_resume_experiments(8), storage_path=str(tmp_path),
                    verbose=0, checkpoint_freq=2, checkpoint_keep_num=2)
    tdir = tmp_path / "resumable" / "resumable_00000"
    kept = sorted(p.name for p in tdir.glob("ckpt_*"))
    assert kept == ["ckpt_000006", "ckpt_000008"]


def test_centralized_benchmark_smoke(capsys):
    """The standalone centralized baseline (benchmarks/main.py, ref:
    blades/benchmarks/main.py) runs end-to-end on a tiny config."""
    from blades_tpu.benchmarks.main import main

    rc = main(["--model", "mlp", "--dataset", "mnist", "--epochs", "1",
               "--batch-size", "32"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "test_acc" in out
