"""Observability subsystem tests (blades_tpu/obs/): the device half
(aggregator diagnostics + detection forensics inside the jitted round) and
the host half (schema-validated metrics pipeline in the sweep runner)."""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.obs import (
    CsvSink,
    JsonlSink,
    MetricsLogger,
    SchemaError,
    StdoutSink,
    validate_jsonl,
    validate_record,
)
from blades_tpu.obs.forensics import detection_metrics
from blades_tpu.ops.aggregators import (
    Centeredclipping,
    Clippedclustering,
    DnC,
    FLTrust,
    GeoMed,
    Mean,
    Median,
    Multikrum,
    Signguard,
    Trimmedmean,
)

KEY = jax.random.PRNGKey(0)


# ---------------------------------------------------------------------------
# forensics: detection confusion-matrix scalars
# ---------------------------------------------------------------------------


def test_detection_metrics_known_confusion():
    # lanes:      0  1  2  3  4  5
    benign = jnp.array([1, 1, 0, 0, 1, 0], bool)   # flagged: 2, 3, 5
    malicious = jnp.array([0, 0, 1, 0, 0, 1], bool)  # truth: 2, 5
    m = detection_metrics(benign, malicious)
    assert np.isclose(float(m["byz_precision"]), 2 / 3)  # tp=2 of 3 flags
    assert np.isclose(float(m["byz_recall"]), 1.0)       # both caught
    assert np.isclose(float(m["byz_fpr"]), 1 / 4)        # lane 3 of 4 benign
    assert int(m["num_flagged"]) == 3


def test_detection_metrics_degenerate_edges():
    # Nothing flagged, nothing malicious: perfect by convention.
    benign = jnp.ones(5, bool)
    none = jnp.zeros(5, bool)
    m = detection_metrics(benign, none)
    assert float(m["byz_precision"]) == 1.0
    assert float(m["byz_recall"]) == 1.0
    assert float(m["byz_fpr"]) == 0.0
    assert int(m["num_flagged"]) == 0
    # Keep-all defense vs a real attack: recall honestly 0.
    m = detection_metrics(benign, jnp.array([1, 1, 0, 0, 0], bool))
    assert float(m["byz_recall"]) == 0.0
    assert float(m["byz_precision"]) == 1.0  # no false alarms either


def test_detection_metrics_runs_under_jit():
    f = jax.jit(detection_metrics)
    m = f(jnp.array([1, 0, 1], bool), jnp.array([0, 1, 0], bool))
    assert float(m["byz_recall"]) == 1.0
    assert float(m["byz_fpr"]) == 0.0


# ---------------------------------------------------------------------------
# aggregator diagnostics: diagnose() must be bit-identical to __call__
# ---------------------------------------------------------------------------

_PARITY_AGGS = [
    Mean(),
    Median(),
    Trimmedmean(num_byzantine=1),
    GeoMed(),
    DnC(num_byzantine=1, sub_dim=8, num_iters=2),
    Multikrum(num_byzantine=1, k=2),
    Centeredclipping(),
    Signguard(),
    Clippedclustering(history_rounds=4),
]


@pytest.mark.parametrize("agg", _PARITY_AGGS, ids=lambda a: a.name)
def test_diagnose_aggregate_bit_identical(agg):
    """Acceptance: with diagnostics enabled the aggregate (and threaded
    state) must be BIT-identical to the plain __call__ path."""
    x = jax.random.normal(jax.random.PRNGKey(3), (8, 32))
    state = agg.init(32, 8)
    key = jax.random.PRNGKey(7)

    plain, plain_state = jax.jit(lambda u, s, k: agg(u, s, key=k))(x, state, key)
    diag_agg, diag_state, diag = jax.jit(
        lambda u, s, k: agg.diagnose(u, s, key=k)
    )(x, state, key)

    np.testing.assert_array_equal(np.asarray(plain), np.asarray(diag_agg))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        plain_state, diag_state,
    )
    assert diag["benign_mask"].shape == (8,) and diag["benign_mask"].dtype == bool
    assert diag["scores"].shape == (8,) and diag["scores"].dtype == jnp.float32
    assert np.all(np.isfinite(np.asarray(diag["scores"])))


def test_fltrust_diagnose_parity_and_client_axis():
    """FLTrust's diag covers CLIENT rows only (the appended trusted row is
    the yardstick), one row shorter than its input matrix."""
    agg = FLTrust()
    x = jax.random.normal(jax.random.PRNGKey(5), (9, 16))  # 8 clients + root
    plain, _ = agg(x)
    diag_agg, _, diag = agg.diagnose(x)
    np.testing.assert_array_equal(np.asarray(plain), np.asarray(diag_agg))
    assert diag["benign_mask"].shape == (8,)
    assert diag["scores"].shape == (8,)


def test_multikrum_mask_selects_k_and_flags_outlier():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 16)) * 0.1)
    x = x.at[0].set(100.0)  # isolated lane
    agg = Multikrum(num_byzantine=2, k=3)
    _, _, diag = agg.diagnose(x)
    mask = np.asarray(diag["benign_mask"])
    assert mask.sum() == 3
    assert not mask[0]  # the outlier is never among the k selected
    assert np.asarray(diag["scores"])[0] == np.asarray(diag["scores"]).max()


def test_trimmedmean_mask_flags_always_trimmed_lane():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(8, 16)) * 0.1)
    x = x.at[0].set(50.0)  # max on every coordinate -> always trimmed
    agg = Trimmedmean(num_byzantine=1)
    _, _, diag = agg.diagnose(x)
    assert not bool(diag["benign_mask"][0])
    assert float(diag["scores"][0]) == 1.0  # trimmed on 100% of coords


def test_signguard_mask_flags_sign_flipped_large_lane():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(10, 32)) * 0.1 + 1.0)
    x = x.at[0].set(-40.0 * jnp.abs(x[0]))  # sign-flipped, huge norm
    agg = Signguard()
    _, _, diag = agg.diagnose(x)
    assert not bool(diag["benign_mask"][0])
    # Clip factor: benign lanes untouched (1.0), the huge lane clipped hard.
    scores = np.asarray(diag["scores"])
    assert scores[0] < 0.2 and np.all(scores[1:] > 0.5)


def test_fltrust_mask_flags_negative_cosine():
    server = jnp.ones((1, 8))
    clients = jnp.concatenate([jnp.ones((3, 8)), -jnp.ones((1, 8))])
    _, _, diag = FLTrust().diagnose(jnp.concatenate([clients, server]))
    mask = np.asarray(diag["benign_mask"])
    assert list(mask) == [True, True, True, False]
    assert float(diag["scores"][-1]) < 0  # raw cosine, pre-ReLU


# ---------------------------------------------------------------------------
# schema validation
# ---------------------------------------------------------------------------


def _good_record(**over):
    rec = {
        "experiment": "exp",
        "trial": "exp_00000",
        "training_iteration": 3,
        "train_loss": 1.25,
        "agg_norm": 0.5,
        "update_norm_mean": 0.7,
        "timers": {"training_step": {"mean_s": 0.1, "total_s": 0.3, "count": 3}},
    }
    rec.update(over)
    return rec


def test_validate_record_accepts_full_record():
    rec = _good_record(
        test_loss=2.0, test_acc=0.4, test_acc_top3=0.8,
        num_unhealthy=0, round_ok=True,
        byz_precision=1.0, byz_recall=0.5, byz_fpr=0.0, num_flagged=2,
        lane_forensics={
            "benign_mask": [True, False], "healthy": [True, True],
            "scores": [0.1, 9.0],
        },
        seed=7, client_lr=0.1,
    )
    assert validate_record(rec) is rec


def test_validate_record_rejects_unknown_key():
    with pytest.raises(SchemaError, match="unknown keys.*brand_new_metric"):
        validate_record(_good_record(brand_new_metric=1.0))


def test_validate_record_rejects_missing_required_and_bad_type():
    rec = _good_record()
    del rec["training_iteration"]
    with pytest.raises(SchemaError,
                       match="missing required key 'training_iteration'"):
        validate_record(rec)
    with pytest.raises(SchemaError, match="'training_iteration' must be"):
        validate_record(_good_record(training_iteration="3"))
    # bool is not a number (int-subclass leak).
    with pytest.raises(SchemaError, match="'train_loss' must be"):
        validate_record(_good_record(train_loss=True))


def test_validate_record_rejects_lane_length_mismatch():
    with pytest.raises(SchemaError, match="disagree on lane count"):
        validate_record(_good_record(lane_forensics={
            "benign_mask": [True, False], "scores": [0.1],
        }))


def test_validate_jsonl_reports_line_numbers(tmp_path):
    p = tmp_path / "m.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps(_good_record()) + "\n")
        f.write("\n")  # blank lines tolerated
        f.write(json.dumps(_good_record(bogus=1)) + "\n")
        f.write('{"torn": ')  # killed-run torn final line
    num_valid, errors = validate_jsonl(p)
    assert num_valid == 1
    assert [ln for ln, _ in errors] == [3, 4]


def test_schema_cli_validator(tmp_path, capsys):
    from blades_tpu.obs.schema import main as schema_main

    good = tmp_path / "good.jsonl"
    good.write_text(json.dumps(_good_record()) + "\n")
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps(_good_record(oops=1)) + "\n")
    assert schema_main([str(good)]) == 0
    assert schema_main([str(bad)]) == 1
    assert "unknown keys" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# sinks + logger
# ---------------------------------------------------------------------------


def test_jsonl_sink_round_trips_and_enforces_schema(tmp_path):
    p = tmp_path / "m.jsonl"
    sink = JsonlSink(p)
    sink.emit(_good_record())
    with pytest.raises(SchemaError):
        sink.emit(_good_record(not_registered=1))
    sink.close()
    num_valid, errors = validate_jsonl(p)
    assert (num_valid, errors) == (1, [])


def test_csv_sink_schema_columns_capture_late_eval_keys(tmp_path):
    """Columns come from the SCHEMA, not the first record — eval metrics
    that first appear mid-run must land in their column, not be dropped."""
    p = tmp_path / "m.csv"
    sink = CsvSink(p)
    sink.emit({"trial": "a,b", "training_iteration": 1, "train_loss": 0.5,
               "timers": {"skipped": {}}})
    sink.emit({"trial": "t", "training_iteration": 2, "train_loss": 0.25,
               "test_acc": 0.75,  # absent from record 1: still has a column
               "late_key": 9})    # unregistered: dropped
    sink.close()
    lines = p.read_text().splitlines()
    header = lines[0].split(",")
    assert {"trial", "training_iteration", "train_loss", "test_acc",
            "byz_recall"} <= set(header)
    assert "timers" not in header and "lane_forensics" not in header
    assert "late_key" not in header
    row2 = dict(zip(header, lines[2].split(",")))
    assert row2["test_acc"] == "0.75"
    assert '"a,b"' in lines[1]  # comma cell quoted


def test_truncate_csv_keeps_rows_it_cannot_parse(tmp_path):
    """A quoted comma cell or torn final line must never make truncation
    destroy the rest of the stream."""
    from blades_tpu.tune.sweep import _truncate_csv

    p = tmp_path / "m.csv"
    p.write_text('experiment,trial,training_iteration\n'
                 '"a,b",t,1\n'
                 '"a,b",t,2\n'
                 '"a,b",t,3\n'
                 '"a,b",t\n')  # torn final line: kept
    _truncate_csv(p, upto_round=2)
    lines = p.read_text().splitlines()
    assert len(lines) == 4  # header + rounds 1,2 + torn line; round 3 gone
    assert lines[1].startswith('"a,b"')
    assert lines[-1] == '"a,b",t'


def test_stdout_sink_heartbeat_cadence(capsys):
    sink = StdoutSink(every=2)
    for i in range(1, 4):
        sink.emit({"experiment": "e", "trial": "t", "training_iteration": i,
                   "train_loss": 0.5})
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 2  # records 1 (always) and 2 (every=2); 3 skipped
    assert "round 1" in out[0] and "loss=0.5000" in out[0]


def test_metrics_logger_stamps_base_and_fans_out(tmp_path):
    p = tmp_path / "m.jsonl"
    with MetricsLogger(
        [JsonlSink(p)], base={"experiment": "e", "trial": "t"}
    ) as logger:
        rec = logger.log({"training_iteration": 1, "train_loss": 0.5})
    assert rec["experiment"] == "e"
    row = json.loads(p.read_text())
    assert row["trial"] == "t" and row["train_loss"] == 0.5


# ---------------------------------------------------------------------------
# the jitted round end-to-end (Fedavg + forensics)
# ---------------------------------------------------------------------------

N_CLIENTS, N_BYZ = 10, 3


def _forensics_config(aggregator, forensics=True, seed=3):
    from blades_tpu.algorithms import get_algorithm_class

    _, cfg = get_algorithm_class("FEDAVG", return_config=True)
    cfg.update_from_dict({
        "dataset_config": {"type": "mnist", "num_clients": N_CLIENTS,
                           "train_bs": 8, "seed": seed},
        "global_model": "mlp",
        "evaluation_interval": 10,
        "num_malicious_clients": N_BYZ,
        "adversary_config": {"type": "ALIE"},
        "server_config": {"lr": 1.0, "aggregator": aggregator},
        "forensics": forensics,
    })
    return cfg


def test_forensics_metrics_consistent_with_emitted_mask():
    """The scalar precision/recall the round emits must agree with a host
    recomputation from the per-lane mask it emits alongside (malicious =
    the first num_malicious lanes, adversaries/base.py)."""
    algo = _forensics_config({"type": "Multikrum", "k": 5}).build()
    r = algo.train()
    lanes = r["lane_forensics"]
    assert len(lanes["benign_mask"]) == N_CLIENTS
    assert len(lanes["healthy"]) == N_CLIENTS
    assert len(lanes["scores"]) == N_CLIENTS
    flagged = np.asarray([not b for b in lanes["benign_mask"]])
    truth = np.arange(N_CLIENTS) < N_BYZ
    tp = (flagged & truth).sum()
    exp_prec = tp / flagged.sum() if flagged.sum() else 1.0
    exp_rec = tp / truth.sum()
    assert np.isclose(r["byz_precision"], exp_prec)
    assert np.isclose(r["byz_recall"], exp_rec)
    assert r["num_flagged"] == int(flagged.sum())
    assert r["num_unhealthy"] == 0 and all(lanes["healthy"])
    assert 0.0 <= r["byz_fpr"] <= 1.0


def test_forensics_off_training_is_bit_identical():
    """Acceptance: diagnostics disabled -> the training trajectory (params
    and losses) is bit-identical to the forensics run, round for round."""
    algo_off = _forensics_config("Median", forensics=False).build()
    algo_on = _forensics_config("Median", forensics=True).build()
    for _ in range(3):
        r_off, r_on = algo_off.train(), algo_on.train()
        assert r_off["train_loss"] == r_on["train_loss"]
        assert "byz_recall" in r_on and "byz_recall" not in r_off
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        algo_off.state.server.params, algo_on.state.server.params,
    )


def test_forensics_rejects_sharded_paths():
    cfg = _forensics_config("Median")
    cfg.resources(num_devices=8)
    with pytest.raises(ValueError, match="unsupported pair"):
        cfg.validate()
    cfg2 = _forensics_config("Median")
    cfg2.update_from_dict({"execution": "streamed"})
    with pytest.raises(ValueError, match="dense"):
        cfg2.validate()


# ---------------------------------------------------------------------------
# the metrics pipeline end-to-end (acceptance criterion)
# ---------------------------------------------------------------------------


@pytest.mark.slow  # compile-heavy end-to-end sweep (~45 s tier-1 time; PR 7 budget rebalance)
def test_sweep_alie_emits_schema_valid_forensics_jsonl(tmp_path):
    """20-round synthetic ALIE sweep over Krum/DnC/SignGuard/trimmed-mean:
    every trial streams 20 schema-valid JSONL records carrying per-round
    detection precision/recall, plus phase timers and compiled cost in the
    summary."""
    from blades_tpu.tune import run_experiments

    experiments = {
        "forensics_alie": {
            "run": "FEDAVG",
            "stop": {"training_iteration": 20},
            "config": {
                "dataset_config": {"type": "mnist", "num_clients": N_CLIENTS,
                                   "train_bs": 8, "seed": 3},
                "global_model": "mlp",
                "evaluation_interval": 10,
                "num_malicious_clients": N_BYZ,
                "adversary_config": {"type": "ALIE"},
                "forensics": True,
                "server_config": {
                    "lr": 1.0,
                    "aggregator": {"grid_search": [
                        {"type": "Multikrum", "k": 5},   # Krum family
                        {"type": "DnC", "sub_dim": 64, "num_iters": 2},
                        {"type": "Signguard"},
                        {"type": "Trimmedmean"},
                    ]},
                },
            },
        }
    }
    summaries = run_experiments(
        experiments, storage_path=str(tmp_path), verbose=0, metrics_csv=True
    )
    assert len(summaries) == 4
    for s in summaries:
        assert "status" not in s, s.get("error")
        stream = Path(s["dir"]) / "metrics.jsonl"
        num_valid, errors = validate_jsonl(stream)
        assert errors == [] and num_valid == 20
        rows = [json.loads(l) for l in stream.read_text().splitlines()]
        assert [r["training_iteration"] for r in rows] == list(range(1, 21))
        for r in rows:
            assert 0.0 <= r["byz_precision"] <= 1.0
            assert 0.0 <= r["byz_recall"] <= 1.0
            assert len(r["lane_forensics"]["benign_mask"]) == N_CLIENTS
        # Phase timers (satellite: compile/round/eval/checkpoint wiring).
        tm = s["timers"]
        assert tm["compile"]["count"] == 1
        assert tm["round"]["count"] == 19
        assert "eval" in tm
        # Compiled-cost analysis from XLA.
        assert s["cost"]["flops"] > 0
        # CSV sibling carries the scalar columns.
        csv_lines = (Path(s["dir"]) / "metrics.csv").read_text().splitlines()
        assert len(csv_lines) == 21
        assert "byz_recall" in csv_lines[0].split(",")


def test_sweep_laned_trials_emit_schema_valid_jsonl(tmp_path):
    """The vmapped lane path writes the same schema-valid stream, with the
    lane knobs (seed) stamped per row."""
    from blades_tpu.tune import run_experiments

    experiments = {
        "laned": {
            "run": "FEDAVG",
            "stop": {"training_iteration": 2},
            "config": {
                "dataset_config": {"type": "mnist", "num_clients": 4,
                                   "train_bs": 8,
                                   "seed": {"grid_search": [0, 1]}},
                "global_model": "mlp",
                "evaluation_interval": 2,
                "server_config": {"lr": 1.0},
            },
        }
    }
    summaries = run_experiments(experiments, storage_path=str(tmp_path),
                                verbose=0)
    assert [s.get("lanes") for s in summaries] == [2, 2]
    for s in summaries:
        num_valid, errors = validate_jsonl(Path(s["dir"]) / "metrics.jsonl")
        assert errors == [] and num_valid == 2
        row = json.loads(
            (Path(s["dir"]) / "metrics.jsonl").read_text().splitlines()[0])
        assert "seed" in row and row["experiment"] == "laned"


@pytest.mark.slow  # CLI end-to-end with tracing (~17 s; the run-subcommand surface stays covered by test_tune)
def test_cli_run_honours_trace_and_metrics_csv(tmp_path, monkeypatch):
    """Satellite: the run subcommand used to silently ignore --trace."""
    import blades_tpu.tune as tune_mod
    from blades_tpu.train import main

    seen = {}

    def fake_run_experiments(experiments, **kw):
        seen["experiments"] = experiments
        seen["kw"] = kw
        return [{"trial": "t", "best_test_acc": 0.0}]

    monkeypatch.setattr(tune_mod, "run_experiments", fake_run_experiments)
    trace_dir = tmp_path / "trace"
    rc = main(["run", "FEDAVG", "--rounds", "2",
               "--trace", str(trace_dir), "--metrics-csv"])
    assert rc == 0
    assert seen["kw"]["metrics_csv"] is True
    assert seen["experiments"]["fedavg_run"]["stop"]["training_iteration"] == 2
    assert trace_dir.exists()  # the profiler actually started/stopped
