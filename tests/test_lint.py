"""blades-lint (tools/lint): the tier-1 static-analysis gate.

Three layers:

1. **Fixture coverage** — every pass has a known-bad / known-good pair
   under ``tests/lint_fixtures/`` (deliberately-seeded violations of
   each invariant: donation reuse, key reuse, env-read-in-jit, host
   sync, unfrozen static config, unregistered metric key, unmarked mesh
   test, stale artifact stamp), pragma-suppression behavior, and the
   ``--changed`` file filter.
2. **CLI contract** — ``python -m tools.lint --json`` emits the
   machine-readable findings the sweep/bench harnesses consume.
3. **CI enforcement** — every pass over THIS repo's full tree must
   report zero unsuppressed error findings (the test that makes lint
   regressions tier-1 failures), inside the lint wall-time budget.
"""

import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from tools.lint import ERROR, run_passes  # noqa: E402
from tools.lint.cli import main as lint_main  # noqa: E402
from tools.lint import core  # noqa: E402
from tools.lint.core import changed_files, collect_files  # noqa: E402
from tools.lint.passes import ALL_PASSES  # noqa: E402
from tools.lint.passes.artifacts import (  # noqa: E402
    ArtifactStampsPass,
    recompute_stamps,
)
from tools.lint.passes.donation import DonationPass  # noqa: E402
from tools.lint.passes.host_sync import HostSyncPass  # noqa: E402
from tools.lint.passes.pass_discipline import PassDisciplinePass  # noqa: E402
from tools.lint.passes.prng import PrngPass  # noqa: E402
from tools.lint.passes.purity import PurityPass  # noqa: E402
from tools.lint.passes.schema_drift import SchemaDriftPass  # noqa: E402
from tools.lint.passes.slow_markers import audit_path  # noqa: E402
from tools.lint.passes.static_args import StaticArgsPass  # noqa: E402
from tools.lint.passes.topology_discipline import (  # noqa: E402
    TopologyDisciplinePass,
)
from tools.lint.passes.trace_discipline import TraceDisciplinePass  # noqa: E402
from tools.lint.core import LintContext  # noqa: E402

FIX = "tests/lint_fixtures"


def run_fixture(passes, *names):
    """Run `passes` over the named fixture files only."""
    only = [REPO / FIX / n for n in names]
    return run_passes(REPO, passes, only=only)


def errors_of(findings, pass_name=None):
    return [f for f in findings if f.severity == ERROR
            and (pass_name is None or f.pass_name == pass_name)]


# ---------------------------------------------------------------------------
# per-pass fixture pairs (seeded violations must be caught; clean twins
# must stay clean)
# ---------------------------------------------------------------------------


def test_donation_fixtures():
    bad = errors_of(run_fixture([DonationPass()], "donation_bad.py"),
                    "use-after-donate")
    msgs = "\n".join(f.message for f in bad)
    assert "'state' is read after being donated" in msgs
    assert "'s0' is read after being donated" in msgs  # the loop form
    assert "'state' is read after being donated to step()" in msgs
    assert len(bad) >= 3
    assert run_fixture([DonationPass()], "donation_good.py") == []


def test_prng_fixtures():
    bad = errors_of(run_fixture([PrngPass()], "prng_bad.py"), "prng-reuse")
    msgs = "\n".join(f.message for f in bad)
    assert "key 'key' already consumed" in msgs
    assert "loop-invariant key 'key'" in msgs
    assert sum("dropout" not in m for m in [f.message for f in bad]) >= 2
    assert len(bad) == 3  # double draw, loop invariant, dropout reuse
    assert run_fixture([PrngPass()], "prng_good.py") == []


def test_purity_fixtures():
    bad = errors_of(run_fixture([PurityPass()], "purity_bad.py"),
                    "jit-purity")
    msgs = "\n".join(f.message for f in bad)
    assert "`os.environ.get` read inside `env_in_jit`" in msgs
    assert "`print()` call inside `helper`" in msgs  # via _jit reachability
    assert "`global` statement" in msgs  # via jax.jit(mutating_body)
    assert run_fixture([PurityPass()], "purity_good.py") == []


def test_host_sync_fixtures():
    hs = HostSyncPass(modules=[f"{FIX}/hostsync_bad.py"])
    bad = errors_of(run_fixture([hs], "hostsync_bad.py"), "host-sync")
    msgs = "\n".join(f.message for f in bad)
    assert "float() on an array expression" in msgs
    assert "np.asarray()" in msgs
    assert ".item()" in msgs
    assert "jax.device_get()" in msgs
    assert ".block_until_ready()" in msgs
    assert len(bad) == 5
    hs_good = HostSyncPass(modules=[f"{FIX}/hostsync_good.py"])
    assert run_fixture([hs_good], "hostsync_good.py") == []


def test_staging_discipline_fixtures():
    """ISSUE 15: the host-sync pass covers the out-of-core staging hot
    path (blades_tpu/state/ rides DEVICE_SIDE) — a blocking fetch
    anywhere but the pragma'd prefetcher boundary is a finding."""
    from tools.lint.passes.host_sync import DEVICE_SIDE

    assert "blades_tpu/state/store.py" in DEVICE_SIDE
    assert "blades_tpu/state/prefetch.py" in DEVICE_SIDE
    hs = HostSyncPass(modules=[f"{FIX}/stagingdiscipline_bad.py"])
    bad = errors_of(run_fixture([hs], "stagingdiscipline_bad.py"),
                    "host-sync")
    msgs = "\n".join(f.message for f in bad)
    assert "float() on an array expression" in msgs
    assert "np.asarray()" in msgs
    assert "jax.device_get()" in msgs
    assert ".item()" in msgs
    assert ".block_until_ready()" in msgs
    assert len(bad) == 5
    hs_good = HostSyncPass(modules=[f"{FIX}/stagingdiscipline_good.py"])
    assert run_fixture([hs_good], "stagingdiscipline_good.py") == []


def test_datastore_discipline_fixtures():
    """ISSUE 20: the host-sync pass covers the out-of-core data plane
    (blades_tpu/data/store.py + stream.py ride DEVICE_SIDE) — cohort
    gathers are host IO by construction and the streaming evaluator's
    only sanctioned sync is the pragma'd four-scalar per-chunk fetch;
    any other blocking fetch is a finding."""
    from tools.lint.passes.host_sync import DEVICE_SIDE
    from tools.lint.passes.purity import TRACED_MODULES

    assert "blades_tpu/data/store.py" in DEVICE_SIDE
    assert "blades_tpu/data/stream.py" in DEVICE_SIDE
    # ...and both in jit-purity's whole-module set: the chunked eval
    # program traces, and the shard writer's file IO is pragma'd.
    assert "blades_tpu/data/store.py" in TRACED_MODULES
    assert "blades_tpu/data/stream.py" in TRACED_MODULES
    hs = HostSyncPass(modules=[f"{FIX}/datastorediscipline_bad.py"])
    bad = errors_of(run_fixture([hs], "datastorediscipline_bad.py"),
                    "host-sync")
    msgs = "\n".join(f.message for f in bad)
    assert "float() on an array expression" in msgs
    assert "np.asarray()" in msgs
    assert "jax.device_get()" in msgs
    assert ".item()" in msgs
    assert ".block_until_ready()" in msgs
    assert len(bad) == 5
    hs_good = HostSyncPass(modules=[f"{FIX}/datastorediscipline_good.py"])
    assert run_fixture([hs_good], "datastorediscipline_good.py") == []


def test_ledger_discipline_fixtures():
    """ISSUE 16: the host-sync pass covers the client ledger's
    per-round update path (blades_tpu/obs/ledger.py rides DEVICE_SIDE)
    — observe() must consume already-fetched host rows; any device
    fetch outside the pragma'd coercion boundary is a finding."""
    from tools.lint.passes.host_sync import DEVICE_SIDE
    from tools.lint.passes.purity import TRACED_MODULES

    assert "blades_tpu/obs/ledger.py" in DEVICE_SIDE
    # ...but NOT in jit-purity's whole-module set: the ledger is host
    # code by construction and its checkpoint I/O is legitimate.
    assert "blades_tpu/obs/ledger.py" not in TRACED_MODULES
    hs = HostSyncPass(modules=[f"{FIX}/ledgerdiscipline_bad.py"])
    bad = errors_of(run_fixture([hs], "ledgerdiscipline_bad.py"),
                    "host-sync")
    msgs = "\n".join(f.message for f in bad)
    assert "np.asarray()" in msgs
    assert "jax.device_get()" in msgs
    assert "float() on an array expression" in msgs
    assert "int() on an array expression" in msgs
    assert ".block_until_ready()" in msgs
    assert len(bad) == 5
    hs_good = HostSyncPass(modules=[f"{FIX}/ledgerdiscipline_good.py"])
    assert run_fixture([hs_good], "ledgerdiscipline_good.py") == []


def test_control_discipline_fixtures():
    """ISSUE 17: the host-sync pass covers the control plane's decision
    path (blades_tpu/control/ rides DEVICE_SIDE) — policy decisions must
    be pure over already-fetched sensor rows, so a device fetch mid-
    decision is a finding, and a wall-clock cooldown (actions no longer
    pure in (round, tick) ⇒ the journal stops re-deriving) is the
    trace-discipline half of the same contract."""
    from tools.lint.passes.host_sync import DEVICE_SIDE

    assert "blades_tpu/control/policy.py" in DEVICE_SIDE
    assert "blades_tpu/control/controller.py" in DEVICE_SIDE
    hs = HostSyncPass(modules=[f"{FIX}/controldiscipline_bad.py"])
    bad = errors_of(run_fixture([hs], "controldiscipline_bad.py"),
                    "host-sync")
    msgs = "\n".join(f.message for f in bad)
    assert "np.asarray()" in msgs
    assert "float() on an array expression" in msgs
    assert "jax.device_get()" in msgs
    assert len(bad) == 3
    tp = TraceDisciplinePass(prefixes=[f"{FIX}/controldiscipline_bad.py"])
    clocks = errors_of(run_fixture([tp], "controldiscipline_bad.py"),
                       "trace-discipline")
    cmsgs = "\n".join(f.message for f in clocks)
    assert "time.time()" in cmsgs
    assert "time.perf_counter()" in cmsgs
    assert len(clocks) == 2
    # Clean twin: host-row reads + round-indexed cooldowns are silent
    # under BOTH passes.
    hs_good = HostSyncPass(modules=[f"{FIX}/controldiscipline_good.py"])
    assert run_fixture([hs_good], "controldiscipline_good.py") == []
    tp_good = TraceDisciplinePass(
        prefixes=[f"{FIX}/controldiscipline_good.py"])
    assert run_fixture([tp_good], "controldiscipline_good.py") == []


def test_static_args_fixtures():
    sa = StaticArgsPass(prefixes=[f"{FIX}/static_bad.py"])
    bad = errors_of(run_fixture([sa], "static_bad.py"), "static-config")
    msgs = "\n".join(f.message for f in bad)
    assert "UnfrozenConfig is not frozen=True" in msgs
    assert "IdentityHashConfig sets eq=False" in msgs
    assert "UnhashableFieldsConfig.schedule" in msgs
    assert "UnhashableFieldsConfig.table" in msgs  # dict inside Optional
    assert "defaults to a mutable list()" in msgs
    sa_good = StaticArgsPass(prefixes=[f"{FIX}/static_good.py"])
    assert run_fixture([sa_good], "static_good.py") == []


def test_schema_drift_fixtures():
    sd = SchemaDriftPass(schema_module=f"{FIX}/schema_mod.py",
                         stamp_modules=[f"{FIX}/schema_stamp_bad.py"])
    findings = run_fixture([sd], "schema_mod.py", "schema_stamp_bad.py")
    bad = errors_of(findings, "schema-drift")
    assert len(bad) == 1 and "mystery_key" in bad[0].message
    warns = [f for f in findings if f.severity != ERROR]
    assert len(warns) == 1 and "never_stamped" in warns[0].message
    # The clean twin: every stamp registered; only the warning remains.
    sd_good = SchemaDriftPass(schema_module=f"{FIX}/schema_mod.py",
                              stamp_modules=[f"{FIX}/schema_stamp_good.py"])
    findings = run_fixture([sd_good], "schema_mod.py",
                           "schema_stamp_good.py")
    assert errors_of(findings) == []
    assert any("never_stamped" in f.message for f in findings)


def test_pass_discipline_fixtures():
    bad = errors_of(run_fixture([PassDisciplinePass()],
                                "passdiscipline_bad.py"),
                    "streamed-pass-discipline")
    msgs = "\n".join(f.message for f in bad)
    assert "row_sq_norms()" in msgs
    assert "gram()" in msgs
    assert "wrs()" in msgs           # aliased import resolves
    assert "sg.sign_counts()" in msgs  # module-attribute access
    # Wire-domain decode discipline: the raw decode-to-f32 primitive is
    # flagged through both the bare import and a codec-module alias.
    assert "dequantize()" in msgs
    assert "cc.dequantize()" in msgs
    assert len(bad) == 6
    # Clean twin: planner requests, layout.py's SAME-NAMED shard helper
    # (a different module), and the sanctioned wire path (decode_deferred
    # + aggregate_wire) produce nothing.
    assert run_fixture([PassDisciplinePass()],
                       "passdiscipline_good.py") == []


def test_topology_discipline_fixtures():
    """ISSUE 19 fixture pair: a file that builds topology neighbor
    tables and spells a raw cross-device collective is an UNCOUNTED
    neighborhood exchange (gossip_ici_bytes stops reconciling); the
    host-side-graph-math twin stays silent."""
    bad = errors_of(run_fixture([TopologyDisciplinePass()],
                                "topologydiscipline_bad.py"),
                    "topology-discipline")
    msgs = "\n".join(f.message for f in bad)
    assert "lax.all_gather()" in msgs
    assert "jax.lax.psum()" in msgs
    assert "jax.lax.ppermute()" in msgs
    assert len(bad) == 3
    assert run_fixture([TopologyDisciplinePass()],
                       "topologydiscipline_good.py") == []


def test_topology_discipline_repo_tree_clean():
    """The real tree is clean: gossip.py's counted gathers are exempt by
    construction (the one sanctioned module), and collective-using files
    that never build tables (parallel/hier.py) must not false-positive."""
    findings = errors_of(run_passes(REPO, [TopologyDisciplinePass()]),
                         "topology-discipline")
    assert findings == [], "\n".join(f.render() for f in findings)


def test_trace_discipline_fixtures():
    tp = TraceDisciplinePass(prefixes=[f"{FIX}/tracediscipline_bad.py"])
    bad = errors_of(run_fixture([tp], "tracediscipline_bad.py"),
                    "trace-discipline")
    msgs = "\n".join(f.message for f in bad)
    assert "time.time()" in msgs
    assert "perf_counter()" in msgs          # from-import form
    assert "mono()" in msgs                  # aliased from-import
    assert "time.perf_counter_ns()" in msgs  # _ns variant
    assert len(bad) == 5
    # Clean twin: spans, obs.trace.now(), time.sleep, an injectable
    # clock REFERENCE, and a pragma'd wall-clock stamp all stay silent.
    tg = TraceDisciplinePass(prefixes=[f"{FIX}/tracediscipline_good.py"])
    assert run_fixture([tg], "tracediscipline_good.py") == []


def test_arrival_purity_fixtures():
    """ISSUE 14 fixture pair: arrival realizations must be pure in
    (seed, tick) — a wall-clock-derived tick (or a raw-clock ingest
    measurement) in an arrival process is exactly the trace-discipline
    violation class, and the virtual-tick/\\ ``obs.trace.now()`` twin
    stays silent."""
    ap = TraceDisciplinePass(prefixes=[f"{FIX}/arrivalpurity_bad.py"])
    bad = errors_of(run_fixture([ap], "arrivalpurity_bad.py"),
                    "trace-discipline")
    msgs = "\n".join(f.message for f in bad)
    assert "time.time()" in msgs             # wall-clock tick derivation
    assert "mono()" in msgs                  # aliased from-import form
    assert "time.perf_counter()" in msgs     # raw ingest-rate measurement
    assert len(bad) == 4
    # Clean twin: the virtual tick counter and the sanctioned
    # obs.trace.now() ingest measurement produce zero findings.
    ag = TraceDisciplinePass(prefixes=[f"{FIX}/arrivalpurity_good.py"])
    assert run_fixture([ag], "arrivalpurity_good.py") == []


def test_trace_discipline_allows_timer_modules():
    """The span layer itself (and its shims) are the sanctioned homes of
    raw clock reads — the default-configured pass must skip them while
    still scanning the rest of blades_tpu/."""
    findings = errors_of(run_passes(REPO, [TraceDisciplinePass()]),
                         "trace-discipline")
    assert findings == [], "\n".join(f.render() for f in findings)


def test_slow_markers_fixture(tmp_path):
    bad = tmp_path / "probe.py"
    bad.write_text(
        "import pytest\n"
        "from blades_tpu.parallel import make_mesh\n\n"
        "@pytest.fixture\n"
        "def setup():\n"
        "    return make_mesh()\n\n"
        "def test_uses_fixture(setup):\n"
        "    pass\n\n"
        "@pytest.mark.slow\n"
        "def test_marked():\n"
        "    make_mesh()\n"
    )
    findings = audit_path(bad)
    assert len(findings) == 1
    assert "test_uses_fixture" in findings[0].message
    assert "fixture 'setup'" in findings[0].message


def test_artifact_stamps_fixture(tmp_path):
    # A miniature repo: the reference-grid constants + one stale artifact.
    curves = tmp_path / "blades_tpu" / "benchmarks"
    curves.mkdir(parents=True)
    (curves / "accuracy_curves.py").write_text(
        'REFERENCE_AGGREGATORS = ["Mean", "Median"]\n'
        "REFERENCE_MALICIOUS_FRACS = [0.0, 0.5]\n")
    art = tmp_path / "artifacts" / "smoke"
    art.mkdir(parents=True)
    rows = [{"aggregator": "Mean", "num_malicious": 0}]
    (art / "curves.json").write_text(json.dumps(
        {"num_clients": 10, "complete": True, "rows": rows}))
    findings = list(ArtifactStampsPass().run(LintContext(tmp_path, [])))
    assert len(findings) == 1 and "stale complete: True" in findings[0].message
    # Re-stamped under reference-grid semantics the artifact is accepted.
    data = json.loads((art / "curves.json").read_text())
    data.update(recompute_stamps(data, ["Mean", "Median"], [0.0, 0.5]))
    assert data["complete"] is False
    assert data["reference_cells_missing"] == ["Mean@5", "Median@0",
                                               "Median@5"]
    (art / "curves.json").write_text(json.dumps(data))
    assert list(ArtifactStampsPass().run(LintContext(tmp_path, []))) == []


def test_restamp_curves_cli(tmp_path):
    """The fixer round-trips: --check flags, a rewrite silences."""
    stale = tmp_path / "curves.json"
    stale.write_text(json.dumps({
        "num_clients": 60, "complete": True,
        "rows": [{"aggregator": "Mean", "num_malicious": 0}]}))
    cmd = [sys.executable, str(REPO / "tools" / "restamp_curves.py")]
    r = subprocess.run(cmd + ["--check", str(stale)],
                       capture_output=True, text=True, cwd=str(REPO))
    assert r.returncode == 1 and "True -> False" in r.stdout
    r = subprocess.run(cmd + [str(stale)],
                       capture_output=True, text=True, cwd=str(REPO))
    assert r.returncode == 0, r.stderr
    data = json.loads(stale.read_text())
    assert data["complete"] is False and data["reference_cells_missing"]
    r = subprocess.run(cmd + ["--check", str(stale)],
                       capture_output=True, text=True, cwd=str(REPO))
    assert r.returncode == 0  # stamps now current


# ---------------------------------------------------------------------------
# pragma allowlist
# ---------------------------------------------------------------------------


def test_pragma_suppresses_with_reason():
    hs = HostSyncPass(modules=[f"{FIX}/pragma_suppressed.py"])
    findings = run_fixture([hs], "pragma_suppressed.py")
    # Both violations suppressed (named pass + `all`), pragmas carry
    # reasons, so nothing at all is reported.
    assert findings == []


def test_pragma_requires_reason_and_real_pass_name():
    hs = HostSyncPass(modules=[f"{FIX}/pragma_bad.py"])
    findings = run_fixture([hs], "pragma_bad.py")
    pragma = [f for f in findings if f.pass_name == "pragma"]
    assert any("without a justification" in f.message for f in pragma)
    assert any("unknown pass(es) ['host-sink']" in f.message
               for f in pragma)
    # The bare-but-parsed pragma still suppresses its line; the typo'd
    # one suppresses nothing, so its host-sync violation survives.
    hs_findings = errors_of(findings, "host-sync")
    assert len(hs_findings) == 1 and hs_findings[0].line == 10


def test_pragma_in_string_is_not_live(tmp_path):
    # A pragma spelled inside a docstring/string (e.g. a module
    # documenting the grammar) must register nothing — neither a
    # suppression nor a pragma-audit finding.
    f = tmp_path / "docstrings.py"
    f.write_text(
        '"""Grammar doc:\n'
        "``# blades-lint: disable-file=host-sync — example``\n"
        '"""\n'
        'S = "# blades-lint: disable=all — in a string"\n'
        "x = 1  # blades-lint: disable=host-sync — a REAL comment pragma\n"
    )
    sf = core.SourceFile(f, tmp_path)
    assert len(sf.pragmas) == 1 and sf.pragmas[0].line == 5
    assert not sf.disabled("host-sync", 2)


# ---------------------------------------------------------------------------
# --changed filtering + CLI
# ---------------------------------------------------------------------------


def test_changed_file_filtering(tmp_path):
    def git(*args):
        subprocess.run(["git", "-c", "user.email=t@t", "-c", "user.name=t",
                        *args], cwd=tmp_path, check=True,
                       capture_output=True)

    git("init", "-q")
    committed = tmp_path / "committed.py"
    committed.write_text("import jax\n\ndef f(key):\n"
                         "    a = jax.random.normal(key, ())\n"
                         "    return a + jax.random.normal(key, ())\n")
    git("add", "committed.py")
    git("commit", "-qm", "seed")
    fresh = tmp_path / "fresh.py"
    fresh.write_text(committed.read_text())
    changed = changed_files(tmp_path)
    assert changed == [fresh]
    # Only the changed file is linted: committed.py's identical
    # violation stays invisible to a --changed run.
    findings = run_passes(tmp_path, [PrngPass()], only=changed)
    assert {f.path for f in findings} == {"fresh.py"}
    assert errors_of(findings, "prng-reuse")


def test_cli_json_machine_readable():
    r = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--json",
         f"{FIX}/prng_bad.py", f"{FIX}/donation_bad.py"],
        capture_output=True, text=True, cwd=str(REPO))
    assert r.returncode == 1
    payload = json.loads(r.stdout)
    assert payload["summary"]["errors"] >= 4
    by_pass = {f["pass_name"] for f in payload["findings"]}
    assert {"prng-reuse", "use-after-donate"} <= by_pass
    sample = payload["findings"][0]
    assert {"pass_name", "path", "line", "message", "fix_hint",
            "severity"} <= set(sample)


def test_cli_lists_all_passes():
    r = subprocess.run([sys.executable, "-m", "tools.lint",
                        "--list-passes"],
                       capture_output=True, text=True, cwd=str(REPO))
    assert r.returncode == 0
    names = [line.split()[0] for line in r.stdout.splitlines() if line]
    assert len(names) >= 7  # ISSUE 8: at least 6 passes + the folded audit
    for expected in ("use-after-donate", "prng-reuse", "jit-purity",
                     "host-sync", "static-config", "schema-drift",
                     "streamed-pass-discipline", "trace-discipline",
                     "slow-markers", "artifact-stamps"):
        assert expected in names


# ---------------------------------------------------------------------------
# CI enforcement: the real tree is clean, inside the wall-time budget
# ---------------------------------------------------------------------------


def test_repo_tree_is_clean():
    """Every pass over blades_tpu/, bench.py, tests/ and tools/: zero
    unsuppressed ERROR findings — new violations land as tier-1
    failures with file:line + fix-hint."""
    t0 = time.perf_counter()
    findings = run_passes(REPO, ALL_PASSES)
    elapsed = time.perf_counter() - t0
    bad = errors_of(findings)
    assert not bad, "\n" + "\n".join(f.render() for f in bad)
    # Warnings must stay actionable, not accumulate as noise: the
    # dynamically-stamped schema keys are pragma'd, so a clean tree
    # reports NO warnings either.
    assert findings == [], "\n".join(f.render() for f in findings)
    # ISSUE 8 budget: the full-tree lint stays well under 60 s so it
    # rides tier-1 without denting the 870 s cap.
    assert elapsed < 60.0, f"lint took {elapsed:.1f}s"


def test_fixture_dir_is_excluded_from_tree_scan():
    files = {f.rel for f in collect_files(REPO)}
    assert not any("lint_fixtures" in rel for rel in files)
    assert "blades_tpu/core/round.py" in files
    assert "bench.py" in files
    assert "tools/lint/core.py" in files


@pytest.mark.parametrize("seeded", [
    "donation_bad.py", "prng_bad.py", "purity_bad.py", "hostsync_bad.py",
    "static_bad.py", "schema_stamp_bad.py", "passdiscipline_bad.py",
    "tracediscipline_bad.py"])
def test_every_seeded_violation_class_is_caught(seeded):
    """ISSUE 8 acceptance (+ ISSUE 9's pass discipline, ISSUE 12's
    trace discipline): donation reuse, key reuse, env-read-in-jit, host
    sync, unfrozen static config, unregistered metric key,
    raw-traversal-outside-planner, raw-clock-outside-trace-layer — each
    deliberately-seeded class is caught by its pass."""
    passes = [
        DonationPass(), PrngPass(), PurityPass(),
        HostSyncPass(modules=[f"{FIX}/hostsync_bad.py"]),
        StaticArgsPass(prefixes=[f"{FIX}/static_bad.py"]),
        SchemaDriftPass(schema_module=f"{FIX}/schema_mod.py",
                        stamp_modules=[f"{FIX}/schema_stamp_bad.py"]),
        PassDisciplinePass(),
        TraceDisciplinePass(prefixes=[f"{FIX}/tracediscipline_bad.py"]),
    ]
    extra = (["schema_mod.py"] if seeded == "schema_stamp_bad.py" else [])
    findings = run_fixture(passes, seeded, *extra)
    assert errors_of(findings), f"no pass caught {seeded}"
