"""Closed-loop control plane (ISSUE 17, blades_tpu/control).

Layers under test:

1. **Policy** — the pure decision functions: every actuator move
   bounded and one-directional, ``rederive_action`` bit-identical to
   the live decision, fail-fast config parsing.
2. **Controller** — per-family cooldown hysteresis (no oscillation by
   construction), the quarantine -> probe -> readmit/requarantine
   lifecycle, ``state()``/``restore()`` byte-identity.
3. **Config gates** — campaign x sync, quarantine's forensics/ledger
   prerequisites, the agg starvation ceiling, ``--watchdog-rules``
   CLI fail-fast.
4. **Driver integration** — a controlled async run whose journal is
   byte-identical across straight / kill-and-resume, re-derivable
   offline by ``replay_round.py --action``, schema-valid rows.
5. **Acceptance (slow)** — a multi-day diurnal simulation under two
   campaign adversaries where the controlled config beats every
   static config in its comparison sweep on final accuracy.
"""

import copy
import json

import pytest

from blades_tpu.control import (
    ControlAction,
    ControlPolicy,
    Controller,
    LIFECYCLE_RULE,
    rederive_action,
)
from blades_tpu.control.policy import (
    decide_agg_every,
    decide_buffer,
    decide_probation,
    decide_probe,
    decide_quarantine,
    decide_replan,
    decide_window,
)

N = 8  # tiny-federation size for the driver tests


# ---------------------------------------------------------------------------
# policy: actions + config parsing
# ---------------------------------------------------------------------------


def test_control_action_validation_and_roundtrip():
    with pytest.raises(ValueError, match="actuator"):
        ControlAction(seq=0, round=1, tick=2, rule="r", actuator="warp")
    act = ControlAction(seq=3, round=7, tick=11, rule="staleness_runaway",
                        actuator="agg_every", old=8, new=4,
                        pre={"old": 8}, message="shrink")
    d = act.as_dict()
    assert d["clients"] == [] and isinstance(d["clients"], list)
    assert ControlAction.from_dict(d) == act
    # json round-trip (the journal is json-serialized into checkpoints).
    assert ControlAction.from_dict(json.loads(json.dumps(d))) == act


def test_policy_from_config_fail_fast_and_rules_merge():
    assert ControlPolicy.from_config(None) == ControlPolicy()
    p = ControlPolicy(cooldown_rounds=3)
    assert ControlPolicy.from_config(p) is p
    with pytest.raises(ValueError, match="must be a dict"):
        ControlPolicy.from_config([1, 2])
    with pytest.raises(ValueError, match="unknown key"):
        ControlPolicy.from_config({"cool_down": 4})
    with pytest.raises(ValueError, match="rule names to actuator"):
        ControlPolicy.from_config({"rules": ["staleness_runaway"]})
    # "enabled" is the config-side arming knob, not a policy field
    # (from_config normalizes the table order, so compare as dicts).
    armed = ControlPolicy.from_config({"enabled": True})
    assert armed.as_config() == ControlPolicy().as_config()
    # rules merge over the default table; "off" removes a response.
    p = ControlPolicy.from_config(
        {"rules": {"staleness_runaway": "off", "suspect_ceiling": "quarantine"}})
    table = dict(p.rule_table)
    assert "staleness_runaway" not in table
    assert table["suspect_ceiling"] == "quarantine"
    assert table["ingest_collapse"] == "buffer"  # default survived
    with pytest.raises(ValueError, match="unknown actuator"):
        ControlPolicy.from_config({"rules": {"x": "teleport"}})
    # as_config() round-trips through from_config() for additive rule
    # overrides ("off" removals re-merge over the defaults, so a
    # removal round-trips as the default mapping, not as absence).
    q = ControlPolicy.from_config(
        {"cooldown_rounds": 3, "rules": {"suspect_ceiling": "quarantine"}})
    assert ControlPolicy.from_config(q.as_config()) == q


@pytest.mark.parametrize("bad", [
    {"cooldown_rounds": 0},
    {"quarantine_rounds": -1},
    {"quarantine_max": 0},
    {"max_quarantine_fraction": 0.0},
    {"max_quarantine_fraction": 1.5},
    {"agg_every_factor": 1},
    {"buffer_factor": 1},
    {"cutoff_factor": 1},
    {"min_agg_every": 0},
    {"window_factor": 1},
    {"min_window": 0},
])
def test_policy_knob_validation(bad):
    with pytest.raises(ValueError):
        ControlPolicy(**bad)


def test_decide_agg_every_bounded_one_directional():
    p = ControlPolicy(min_agg_every=2, agg_every_factor=2)
    act = decide_agg_every(p, seq=0, round_idx=5, tick=9,
                           rule="staleness_runaway", pre={"old": 8})
    assert (act.actuator, act.old, act.new) == ("agg_every", 8, 4)
    # At the floor: bounded means silent, not clamped re-fires.
    assert decide_agg_every(p, seq=0, round_idx=5, tick=9,
                            rule="staleness_runaway", pre={"old": 2}) is None
    # Sync driver has no agg cadence.
    assert decide_agg_every(p, seq=0, round_idx=5, tick=9,
                            rule="staleness_runaway", pre={"old": None}) is None


def test_decide_window_bounded_one_directional():
    """ISSUE 20: the out-of-core window family mirrors agg_every —
    shrink-only toward min_window, silent at the floor, None on
    drivers without a window to move."""
    p = ControlPolicy(min_window=4, window_factor=2)
    act = decide_window(p, seq=0, round_idx=5, tick=9,
                        rule="staleness_runaway", pre={"old": 16})
    assert (act.actuator, act.old, act.new) == ("window", 16, 8)
    # Factor overshooting the floor clamps TO the floor, once.
    act = decide_window(p, seq=0, round_idx=5, tick=9,
                        rule="staleness_runaway", pre={"old": 6})
    assert act.new == 4
    # At the floor: bounded means silent, not clamped re-fires.
    assert decide_window(p, seq=0, round_idx=5, tick=9,
                         rule="staleness_runaway", pre={"old": 4}) is None
    assert decide_window(p, seq=0, round_idx=5, tick=9,
                         rule="staleness_runaway", pre={"old": None}) is None


def test_decide_buffer_grows_then_relaxes_cutoff():
    p = ControlPolicy(buffer_factor=2, max_buffer_capacity=16,
                      cutoff_factor=2, max_weight_cutoff=8)
    act = decide_buffer(p, seq=0, round_idx=1, tick=2, rule="ingest_collapse",
                        pre={"old": 8, "cutoff": 4})
    assert (act.actuator, act.old, act.new) == ("buffer_capacity", 8, 16)
    # At the capacity cap the fallback relaxes the staleness cutoff.
    act = decide_buffer(p, seq=0, round_idx=1, tick=2, rule="ingest_collapse",
                        pre={"old": 16, "cutoff": 4})
    assert (act.actuator, act.old, act.new) == ("weight_cutoff", 4, 8)
    # Both bounds hit -> no further relief.
    assert decide_buffer(p, seq=0, round_idx=1, tick=2, rule="ingest_collapse",
                         pre={"old": 16, "cutoff": 8}) is None
    assert decide_buffer(p, seq=0, round_idx=1, tick=2, rule="ingest_collapse",
                         pre={"old": None, "cutoff": None}) is None


def test_decide_quarantine_ceiling_and_exclusions():
    p = ControlPolicy(quarantine_rounds=5, quarantine_max=3,
                      max_quarantine_fraction=0.5)
    # Suspects may be bare ids or (id, score) pairs; held ids skipped.
    act = decide_quarantine(p, seq=2, round_idx=10, tick=20, rule="fpr_collapse",
                            pre={"excluded": [4], "active": 1},
                            suspects=[(4, 0.9), (1, 0.8), 6, (2, 0.5)],
                            num_clients=8)
    assert act.clients == (1, 6, 2)  # ceiling 4 - active 1 = room 3; 4 held
    assert act.until == 15 and (act.old, act.new) == (1, 4)
    # Room at the fleet ceiling truncates below quarantine_max.
    act = decide_quarantine(p, seq=2, round_idx=10, tick=20, rule="fpr_collapse",
                            pre={"excluded": [4], "active": 2},
                            suspects=[(4, 0.9), (1, 0.8), 6, (2, 0.5)],
                            num_clients=8)
    assert act.clients == (1, 6)
    # quarantine_rounds=0 disables the family entirely.
    p0 = ControlPolicy(quarantine_rounds=0)
    assert decide_quarantine(p0, seq=0, round_idx=0, tick=0, rule="fpr_collapse",
                             pre={}, suspects=[1], num_clients=8) is None
    # No room at the fleet ceiling.
    act = decide_quarantine(p, seq=0, round_idx=0, tick=0, rule="fpr_collapse",
                            pre={"excluded": [0, 1, 2, 3], "active": 4},
                            suspects=[5, 6], num_clients=8)
    assert act is None


def test_decide_replan_gated_on_allowed():
    p = ControlPolicy()
    assert decide_replan(p, seq=0, round_idx=0, tick=0,
                         rule="round_time_regression",
                         pre={"allowed": False}) is None
    act = decide_replan(p, seq=0, round_idx=0, tick=0,
                        rule="round_time_regression", pre={"allowed": True})
    assert act.actuator == "replan"


def test_decide_probe_and_probation_lifecycle():
    p = ControlPolicy(quarantine_rounds=4)
    assert decide_probe(p, seq=0, round_idx=3, tick=0, pre={"due": []}) is None
    act = decide_probe(p, seq=5, round_idx=3, tick=7,
                       pre={"due": [2, 6], "active": 3})
    assert (act.rule, act.actuator) == (LIFECYCLE_RULE, "probe")
    assert act.clients == (2, 6) and (act.old, act.new) == (3, 1)
    # Probation: flagged probationers requarantined, clean ones
    # readmitted, consecutive seqs in (requarantine, readmit) order.
    pre = {"probation": [2, 6], "participants": [1, 2, 6], "flagged": [6]}
    acts = decide_probation(p, round_idx=10, tick=0, pre=pre, seq0=8)
    assert [(a.seq, a.actuator, a.clients) for a in acts] == [
        (8, "requarantine", (6,)), (9, "readmit", (2,))]
    assert acts[0].until == 14
    # No probationer participated -> nothing to diagnose.
    assert decide_probation(p, round_idx=10, tick=0, seq0=0,
                            pre={"probation": [2], "participants": [5],
                                 "flagged": []}) == []


def test_rederive_action_every_actuator():
    p = ControlPolicy(quarantine_rounds=5, quarantine_max=2)
    suspects = [(3, 0.9), (5, 0.7)]
    cases = [
        decide_agg_every(p, seq=0, round_idx=1, tick=2,
                         rule="staleness_runaway", pre={"old": 8}),
        decide_buffer(p, seq=1, round_idx=2, tick=3, rule="ingest_collapse",
                      pre={"old": 8, "cutoff": 4}),
        decide_quarantine(p, seq=2, round_idx=3, tick=4, rule="fpr_collapse",
                          pre={"excluded": [], "active": 0},
                          suspects=suspects, num_clients=8),
        decide_replan(p, seq=3, round_idx=4, tick=5,
                      rule="round_time_regression", pre={"allowed": True}),
        decide_probe(p, seq=4, round_idx=5, tick=6,
                     pre={"due": [3], "active": 2}),
        decide_window(p, seq=7, round_idx=8, tick=9,
                      rule="staleness_runaway", pre={"old": 16}),
    ] + decide_probation(p, round_idx=6, tick=7, seq0=5,
                         pre={"probation": [3, 5], "participants": [3, 5],
                              "flagged": [3]})
    assert len(cases) == 8  # probation emitted the (requarantine, readmit) pair
    for act in cases:
        d = act.as_dict()
        re = rederive_action(p, json.loads(json.dumps(d)),
                             suspects=suspects, num_clients=8)
        assert json.dumps(re, sort_keys=True) == json.dumps(d, sort_keys=True)
    with pytest.raises(ValueError, match="unknown actuator"):
        rederive_action(p, dict(cases[0].as_dict(), actuator="warp"))


# ---------------------------------------------------------------------------
# controller: hysteresis, lifecycle, checkpoint state
# ---------------------------------------------------------------------------


def _ctl(**kw):
    policy = kw.pop("policy", None) or ControlPolicy(**kw.pop("knobs", {}))
    defaults = dict(num_clients=8, agg_every=16, buffer_capacity=8,
                    weight_cutoff=4)
    defaults.update(kw)
    return Controller(policy, **defaults)


def test_controller_cooldown_prevents_oscillation():
    c = _ctl(knobs=dict(cooldown_rounds=4, min_agg_every=2))
    ev = {"rule": "staleness_runaway"}
    fired = []
    for r in range(12):
        # The sensor fires EVERY round; the family cooldown must thin
        # that to one bounded move per window.
        acts = c.step(round_idx=r, tick=r, events=[ev])
        fired += [(a.round, a.old, a.new) for a in acts]
    assert fired == [(0, 16, 8), (4, 8, 4), (8, 4, 2)]
    assert c.values["agg_every"] == 2
    # At the floor further fires are silent: no clamped re-moves, and
    # by construction no move exists that could grow agg_every back —
    # an A->B->A oscillation is structurally impossible.
    assert c.step(round_idx=12, tick=12, events=[ev]) == []
    assert len(c.journal) == 3
    # Unmapped rules and rules mapped "off" produce no action at all.
    assert c.step(round_idx=13, tick=13, events=[{"rule": "nan_loss"}]) == []


def test_controller_window_family_rides_cooldown():
    """ISSUE 20: a rule mapped to the window family drives bounded
    shrink-only moves on the controller's ``window`` view, with the
    same per-family cooldown hysteresis as agg_every; an unseeded
    window (non-ooc driver) stays silent."""
    policy = ControlPolicy(
        rule_table=(("staleness_runaway", "window"),),
        cooldown_rounds=4, min_window=4)
    c = Controller(policy, num_clients=8, window=16)
    ev = {"rule": "staleness_runaway"}
    fired = []
    for r in range(9):
        acts = c.step(round_idx=r, tick=r, events=[ev])
        fired += [(a.round, a.actuator, a.old, a.new) for a in acts]
    assert fired == [(0, "window", 16, 8), (4, "window", 8, 4)]
    assert c.values["window"] == 4
    assert c.step(round_idx=9, tick=9, events=[ev]) == []  # at the floor
    # The window view rides state()/restore() with the other values.
    resumed = Controller(policy, num_clients=8, window=16)
    resumed.restore(json.loads(json.dumps(c.state())))
    assert resumed.values["window"] == 4
    # Unseeded window (sync / resident drivers): nothing to move.
    idle = Controller(policy, num_clients=8)
    assert idle.step(round_idx=0, tick=0, events=[ev]) == []


def test_controller_quarantine_probe_readmit_cycle():
    c = _ctl(knobs=dict(cooldown_rounds=1, quarantine_rounds=2,
                        quarantine_max=2, max_quarantine_fraction=0.5))
    ev = {"rule": "fpr_collapse"}
    (q,) = c.step(round_idx=0, tick=0, events=[ev],
                  suspects=[(3, 0.9), (5, 0.8)])
    assert q.actuator == "quarantine" and q.clients == (3, 5) and q.until == 2
    assert c.quarantined_clients() == {3, 5}
    # While held, a re-fire has no fresh suspects to pick.
    assert c.step(round_idx=1, tick=1, events=[ev], suspects=[(3, 0.9)]) == []
    # Expiry releases to probation (probe on next participation).
    (probe,) = c.step(round_idx=2, tick=2)
    assert probe.actuator == "probe" and probe.clients == (3, 5)
    assert c.quarantine == {} and set(c.probation) == {3, 5}
    # Diagnosis: 5 flagged again -> requarantined; 3 clean -> readmitted.
    acts = c.step(round_idx=3, tick=3, participants=[1, 3, 5], flagged=[5])
    assert [a.actuator for a in acts] == ["requarantine", "readmit"]
    assert c.quarantined_clients() == {5} and c.probation == {}
    # Seqs are strictly consecutive across the whole journal.
    assert [a["seq"] for a in c.journal] == list(range(len(c.journal)))


def test_controller_state_restore_resumes_exact_journal():
    def drive(c, rounds):
        ev_q = {"rule": "fpr_collapse"}
        ev_s = {"rule": "staleness_runaway"}
        for r in rounds:
            c.step(round_idx=r, tick=2 * r, events=[ev_q, ev_s],
                   suspects=[(r % 8, 0.9), ((r + 3) % 8, 0.8)],
                   participants=[r % 8, (r + 1) % 8],
                   flagged=[(r + 1) % 8] if r % 3 == 0 else [])

    knobs = dict(cooldown_rounds=2, quarantine_rounds=2, quarantine_max=1,
                 max_quarantine_fraction=0.5)
    straight = _ctl(knobs=dict(knobs))
    drive(straight, range(10))

    first = _ctl(knobs=dict(knobs))
    drive(first, range(5))
    snap = json.loads(json.dumps(first.state()))  # checkpoint round-trip
    resumed = _ctl(knobs=dict(knobs))
    resumed.restore(copy.deepcopy(snap))
    drive(resumed, range(5, 10))
    assert json.dumps(resumed.journal, sort_keys=True) == \
        json.dumps(straight.journal, sort_keys=True)
    assert resumed.state() == straight.state()


# ---------------------------------------------------------------------------
# config gates + CLI fail-fast
# ---------------------------------------------------------------------------


_SUSPECT_RULE = {"name": "suspect_ceiling", "kind": "ceiling",
                 "field": "suspected_fraction", "threshold": 0.05,
                 "min_points": 1}


def _controlled_config(**over):
    from blades_tpu.algorithms.config import FedavgConfig

    arrivals = {"rate": 0.4, "agg_every": 4, "staleness_cap": 4, "seed": 7}
    arrivals.update(over.pop("arrivals", {}))
    control = {"cooldown_rounds": 2, "quarantine_rounds": 3,
               "quarantine_max": 2, "rules": {"suspect_ceiling": "quarantine"}}
    control.update(over.pop("control", {}))
    cfg = (FedavgConfig()
           .data(dataset="mnist", num_clients=N, seed=7)
           .training(global_model="mlp", aggregator={"type": "Signguard"})
           .adversary(num_malicious_clients=3,
                      adversary_config=over.pop("adversary", {
                          "type": "DiurnalALIE", "period": 8, "duty": 0.99,
                          "high": 1.5}))
           .resources(execution="async")
           .arrivals(**arrivals)
           .observability(forensics=True, ledger=True,
                          watchdog_rules=[dict(_SUSPECT_RULE)])
           .control(**control))
    for k, v in over.items():
        setattr(cfg, k, v)
    cfg.validate()  # the tune-runner step: infers shapes, runs the gates
    return cfg


def test_config_control_gates():
    from blades_tpu.algorithms.config import FedavgConfig

    # control_enabled: None disarmed, bare .control() arms defaults,
    # enabled=False disarms an otherwise-populated spec.
    assert not FedavgConfig().control_enabled
    assert FedavgConfig().control().control_enabled
    cfg = FedavgConfig().control(cooldown_rounds=4).control(enabled=False)
    assert not cfg.control_enabled and cfg.get_control_policy() is None
    # Unknown policy keys in a raw control_config dict (the builder's
    # keywords can't typo) die at validate(), not mid-run.
    cfg = _controlled_config()
    cfg.control_config = dict(cfg.control_config, warp_factor=9)
    with pytest.raises(ValueError, match="unknown key"):
        cfg.validate()
    # Campaign adversaries need the async tick clock.
    with pytest.raises(ValueError, match="tick clock"):
        (FedavgConfig()
         .data(dataset="mnist", num_clients=N, seed=7)
         .training(global_model="mlp")
         .adversary(num_malicious_clients=3,
                    adversary_config={"type": "DiurnalALIE", "period": 8,
                                      "duty": 0.5})
         .validate())
    # Quarantine moves need forensics + ledger + async ingest.
    with pytest.raises(ValueError, match="forensics"):
        _controlled_config().observability(forensics=False).validate()
    with pytest.raises(ValueError, match="ledger"):
        _controlled_config().observability(ledger=False).validate()
    # The fleet ceiling may not starve the aggregation trigger.
    with pytest.raises(ValueError, match="starving"):
        _controlled_config(
            control={"max_quarantine_fraction": 0.9}).validate()
    # Fused dispatch gives the controller no host-visible rounds.
    with pytest.raises(ValueError, match="rounds_per_dispatch"):
        _controlled_config(rounds_per_dispatch=2).validate()
    # The tuned recipe itself validates clean.
    _controlled_config().validate()


def test_campaign_schedule_validation():
    from blades_tpu.adversaries.campaigns import (
        DiurnalALIECampaign,
        LazyRampCampaign,
    )

    with pytest.raises(ValueError, match="period"):
        DiurnalALIECampaign(period=1)
    for duty in (0.0, 1.0, -0.1):
        with pytest.raises(ValueError, match="duty"):
            DiurnalALIECampaign(period=8, duty=duty)
    adv = DiurnalALIECampaign(num_clients=8, num_byzantine=3, period=8,
                              duty=0.5)
    assert adv.wants_ticks and adv.requires_virtual_time
    with pytest.raises(ValueError, match="start at tick 0"):
        LazyRampCampaign(ramp=((4, 0.5),))
    with pytest.raises(ValueError, match="strictly increasing"):
        LazyRampCampaign(ramp=((0, 0.0), (8, 0.5), (8, 1.0)))
    with pytest.raises(ValueError, match="non-empty"):
        LazyRampCampaign(ramp=())
    with pytest.raises(ValueError, match="in \\[0, 1\\]"):
        LazyRampCampaign(ramp=((0, 1.5),))
    ramp = LazyRampCampaign(num_clients=8, num_byzantine=3,
                            ramp=((0, 0.0), (8, 1.0)))
    assert ramp.wants_ticks and ramp.requires_virtual_time


def test_watchdog_rules_cli_fail_fast(tmp_path, capsys):
    from blades_tpu.train import main

    base = ["run", "FEDAVG", "--storage-path", str(tmp_path)]
    # Invalid JSON, non-list JSON, and a bad rule kind all die in
    # argparse (SystemExit 2) before any experiment is built.
    for bad, msg in (("{not json", "not valid JSON"),
                     ('{"name": "x"}', "must be a JSON list"),
                     ('[{"name": "x", "kind": "warp", "field": "tick"}]',
                      "kind")):
        with pytest.raises(SystemExit):
            main(base + ["--watchdog-rules", bad])
        err = capsys.readouterr().err
        assert "--watchdog-rules" in err and msg in err, err
    assert not any(tmp_path.iterdir()), "an experiment was built anyway"


# ---------------------------------------------------------------------------
# driver integration: journal determinism, offline rederivation, schema
# ---------------------------------------------------------------------------


_CONTROL_REPLAY = ("tick", "cycle_ticks", "arrivals_quarantined",
                   "control_actions_total", "quarantine_size",
                   "train_loss", "agg_norm", "suspected_fraction")


def _run_controlled(cfg_builder, rounds):
    from blades_tpu.algorithms.fedavg import Fedavg

    algo = Fedavg(cfg_builder())
    try:
        return [algo.train() for _ in range(rounds)], algo
    except BaseException:
        algo.stop()
        raise


def _journal_of(rows):
    return [a for r in rows for a in (r.get("control_actions") or [])]


def test_controlled_run_journal_resume_bit_identity(tmp_path):
    from blades_tpu.algorithms.fedavg import Fedavg

    rows_a, algo_a = _run_controlled(_controlled_config, 12)
    journal_a = _journal_of(rows_a)
    assert len(journal_a) >= 4, "scenario lost its control activity"
    assert [a["seq"] for a in journal_a] == list(range(len(journal_a)))
    algo_a.stop()

    # Kill after 5 rounds, restore into a FRESH build, finish to 12.
    rows_b, algo_b = _run_controlled(_controlled_config, 5)
    path = algo_b.save_checkpoint(str(tmp_path))
    algo_b.stop()
    algo_c = Fedavg(_controlled_config())
    algo_c.load_checkpoint(path)
    try:
        rows_c = [algo_c.train() for _ in range(7)]
    finally:
        algo_c.stop()

    resumed = _journal_of(rows_b) + _journal_of(rows_c)
    assert json.dumps(resumed, sort_keys=True) == \
        json.dumps(journal_a, sort_keys=True)
    for ra, rb in zip(rows_a, rows_b + rows_c):
        for f in _CONTROL_REPLAY:
            assert ra.get(f) == rb.get(f), f


def test_rederive_actions_and_report_roundtrip(tmp_path, capsys):
    from tools.control_report import main as report_main
    from tools.replay_round import rederive_actions

    rows, algo = _run_controlled(_controlled_config, 12)
    cfg = algo.config
    algo.stop()
    # Mirror the real flightrec artifact shape: the fleet size lives
    # under dataset_config, not at the top level of the dumped config.
    dump = {
        "config": {"dataset_config": {"type": "mnist",
                                      "num_clients": cfg.num_clients},
                   "control_config": dict(cfg.control_config)},
        "rounds": [{k: v for k, v in r.items()
                    if k in ("training_iteration", "tick", "control_actions",
                             "ledger_top_suspects")} for r in rows],
    }
    assert sum(len(r.get("control_actions") or []) for r in dump["rounds"]) > 0
    # Every journaled action re-derives bit-identically from (policy,
    # pre, suspects) alone — the replay contract's control half.
    assert rederive_actions(dump, quiet=True) == 0
    # A tampered journal is caught, not replayed over.
    bad = json.loads(json.dumps(dump))
    for r in bad["rounds"]:
        for a in r.get("control_actions") or []:
            if a["actuator"] == "quarantine":
                a["clients"] = [c + 1 for c in a["clients"]]
    assert bad != dump
    assert rederive_actions(bad, quiet=True) == 1
    # The forensics report reads the same artifact.
    p = tmp_path / "dump.json"
    p.write_text(json.dumps(dump))
    assert report_main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "quarantine" in out
    assert report_main([str(p), "--json"]) == 0


def test_controlled_rows_schema_valid():
    from blades_tpu.obs.schema import validate_record

    rows, algo = _run_controlled(_controlled_config, 4)
    algo.stop()
    for i, row in enumerate(rows):
        rec = dict(row, experiment="ctl", trial="t0", training_iteration=i + 1)
        validate_record(rec)
        assert rec["control_actions_total"] >= 0
        assert rec["quarantine_size"] >= 0


# ---------------------------------------------------------------------------
# acceptance (slow): the controller beats every static under campaigns
# ---------------------------------------------------------------------------


def _campaign_config(adversary, *, controlled, aggregator=None, rounds=50):
    """The 24h-simulation scenario: one simulated day = 24 virtual
    ticks; 50 rounds cover several days of the campaign schedule.  The
    synthetic task is hardened (noise/heterogeneity) so attack damage
    is visible in final accuracy instead of saturating at 1.0."""
    from blades_tpu.algorithms.config import FedavgConfig

    cfg = (FedavgConfig()
           .data(dataset={"type": "mnist", "synthetic_noise": 3.0,
                          "synthetic_heterogeneity": 0.6},
                 num_clients=N, seed=7)
           .training(global_model="mlp", num_classes=10,
                     input_shape=(28, 28, 1),
                     aggregator=aggregator or {"type": "Signguard"},
                     server_lr=0.5, train_batch_size=32,
                     num_batch_per_round=2)
           .client(lr=0.1)
           .adversary(num_malicious_clients=3, adversary_config=adversary)
           .evaluation(evaluation_interval=rounds)
           .resources(execution="async")
           .arrivals(rate=0.4, agg_every=4, staleness_cap=4, seed=7)
           .observability(forensics=True, ledger=True,
                          watchdog_rules=[dict(_SUSPECT_RULE)]))
    if controlled:
        cfg = cfg.control(cooldown_rounds=2, quarantine_rounds=100,
                          quarantine_max=3, max_quarantine_fraction=0.4,
                          rules={"suspect_ceiling": "quarantine"})
    return cfg


_DIURNAL = {"type": "DiurnalALIE", "period": 24, "duty": 0.9, "high": 8.0}
_RAMP = {"type": "LazyRamp", "ramp": ((0, 0.0), (16, 1.0)),
         "copy_scale": 8.0, "noise_std": 0.05}


def _final_acc(cfg, rounds=50):
    from blades_tpu.algorithms.fedavg import Fedavg

    algo = Fedavg(cfg)
    try:
        rows = [algo.train() for _ in range(rounds)]
    finally:
        algo.stop()
    acc = next(r["test_acc"] for r in reversed(rows)
               if r.get("test_acc") is not None)
    return float(acc), rows


@pytest.mark.slow
def test_campaign_acceptance_controlled_beats_every_static(tmp_path):
    """Two campaign adversaries, one controller, a static comparison
    sweep along the axes the controller tunes (the identical config
    uncontrolled, and the defense-axis Median static).  The controlled
    config must win on final accuracy under EVERY campaign — the
    static configs each have a regime they lose."""
    from blades_tpu.algorithms.fedavg import Fedavg
    from tools.replay_round import rederive_actions

    margins = {}
    for name, adv in (("diurnal", _DIURNAL), ("ramp", _RAMP)):
        acc_ctl, rows_ctl = _final_acc(
            _campaign_config(dict(adv), controlled=True))
        # The controller actually acted: campaign attackers quarantined.
        assert rows_ctl[-1]["quarantine_size"] == 3
        statics = {
            "static_signguard": _campaign_config(dict(adv), controlled=False),
            "static_median": _campaign_config(
                dict(adv), controlled=False, aggregator={"type": "Median"}),
        }
        for label, cfg in statics.items():
            acc_static, _ = _final_acc(cfg)
            margins[(name, label)] = acc_ctl - acc_static
            assert acc_ctl > acc_static, (
                f"{name}: controlled {acc_ctl:.3f} lost to {label} "
                f"{acc_static:.3f}")
        if name == "diurnal":
            journal_straight = _journal_of(rows_ctl)
            # Kill mid-campaign (inside the second simulated day),
            # resume from the checkpoint, and the journal continues
            # byte-identically.
            algo = Fedavg(_campaign_config(dict(adv), controlled=True))
            try:
                rows_b = [algo.train() for _ in range(20)]
                path = algo.save_checkpoint(str(tmp_path))
            finally:
                algo.stop()
            algo2 = Fedavg(_campaign_config(dict(adv), controlled=True))
            algo2.load_checkpoint(path)
            try:
                rows_c = [algo2.train() for _ in range(30)]
                cfg_resumed = algo2.config
            finally:
                algo2.stop()
            resumed = _journal_of(rows_b) + _journal_of(rows_c)
            assert json.dumps(resumed, sort_keys=True) == \
                json.dumps(journal_straight, sort_keys=True)
            # Every action in the resumed journal re-derives offline.
            dump = {"config": {
                        "dataset_config": {
                            "type": "mnist",
                            "num_clients": cfg_resumed.num_clients},
                        "control_config": dict(cfg_resumed.control_config)},
                    "rounds": rows_b + rows_c}
            assert rederive_actions(dump, quiet=True) == 0
    # The wins are decisive, not numerical noise.
    assert min(margins.values()) > 0.05, margins
