"""Multi-chip sharding tests on the 8-virtual-device CPU mesh
(the analogue of the reference's multi-node-without-a-cluster testing,
SURVEY.md §4; conftest.py forces the device count).

Tier-2 (``slow``): each 8-virtual-device shard_map compile costs ~10s of
wall clock on a 2-core CPU host; the tier-1 budget keeps the dense-path
suites instead."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.adversaries import get_adversary, make_malicious_mask
from blades_tpu.core import FedRound, Server, TaskSpec
from blades_tpu.data import DatasetCatalog
from blades_tpu.parallel import (
    make_mesh,
    shard_federation,
    shard_map_step,
    sharded_step,
)
from blades_tpu.parallel.sharded import sharded_evaluate

pytestmark = pytest.mark.slow

N_CLIENTS = 16  # 2 per device


@pytest.fixture(scope="module")
def setup():
    assert jax.device_count() == 8, "conftest must provide 8 virtual devices"
    ds = DatasetCatalog.get_dataset("mnist", num_clients=N_CLIENTS)
    task = TaskSpec(model="mlp", lr=0.1, input_shape=(28, 28, 1)).build()
    server = Server.from_config(aggregator="Median", lr=1.0)
    adv = get_adversary("ALIE", num_clients=N_CLIENTS, num_byzantine=4)
    fr = FedRound(task=task, server=server, adversary=adv, batch_size=16)
    mesh = make_mesh()
    state = fr.init(jax.random.PRNGKey(0), N_CLIENTS)
    arrays = (
        jnp.array(ds.train.x), jnp.array(ds.train.y),
        jnp.array(ds.train.lengths), make_malicious_mask(N_CLIENTS, 4),
    )
    state, arrays = shard_federation(mesh, state, arrays)
    return ds, fr, mesh, state, arrays


def test_mesh_construction():
    mesh = make_mesh()
    assert mesh.devices.shape == (8,)
    assert mesh.axis_names == ("clients",)
    small = make_mesh(num_devices=4)
    assert small.devices.shape == (4,)


def test_shard_placement(setup):
    _, _, mesh, state, arrays = setup
    x = arrays[0]
    # Client data sharded over 8 devices; server params replicated.
    assert len(x.sharding.device_set) == 8
    p = jax.tree.leaves(state.server.params)[0]
    assert p.sharding.is_fully_replicated


def test_gspmd_step_runs_and_learns(setup):
    ds, fr, mesh, state, (x, y, ln, mal) = setup
    step = sharded_step(fr, mesh, donate=False)
    losses = []
    for r in range(15):
        state, m = step(state, x, y, ln, mal, jax.random.fold_in(jax.random.PRNGKey(5), r))
        losses.append(float(m["train_loss"]))
    assert losses[-1] < losses[0]
    ev = sharded_evaluate(fr, mesh)(
        state,
        *shard_federation(mesh, state, (
            jnp.array(ds.test.x), jnp.array(ds.test.y), jnp.array(ds.test.lengths)
        ))[1],
    )
    assert float(ev["test_acc"]) > 0.5


def test_shard_map_step_matches_semantics(setup):
    ds, fr, mesh, state, (x, y, ln, mal) = setup
    step = shard_map_step(fr, mesh)
    st = state
    for r in range(10):
        st, m = step(st, x, y, ln, mal, jax.random.fold_in(jax.random.PRNGKey(6), r))
    assert np.isfinite(float(m["train_loss"]))
    assert int(m["round"]) == 10
    # Forged rows present: ALIE makes malicious updates identical.
    # (indirect check: training still converges under the attack+defense)
    ev = sharded_evaluate(fr, mesh)(
        st,
        *shard_federation(mesh, st, (
            jnp.array(ds.test.x), jnp.array(ds.test.y), jnp.array(ds.test.lengths)
        ))[1],
    )
    assert float(ev["test_acc"]) > 0.5


def test_gspmd_matches_single_device_numerics(setup):
    """The sharded GSPMD program must be bit-identical (up to float assoc)
    to the unsharded jit of the same function with the same keys."""
    ds, fr, mesh, state, (x, y, ln, mal) = setup
    plain_state = fr.init(jax.random.PRNGKey(0), N_CLIENTS)
    step_sharded = sharded_step(fr, mesh, donate=False)
    step_plain = jax.jit(fr.step)
    s1, s2 = state, plain_state
    for r in range(3):
        key = jax.random.fold_in(jax.random.PRNGKey(8), r)
        s1, m1 = step_sharded(s1, x, y, ln, mal, key)
        s2, m2 = step_plain(
            s2, jnp.array(ds.train.x), jnp.array(ds.train.y),
            jnp.array(ds.train.lengths), mal, key,
        )
    from blades_tpu.utils.tree import ravel_fn

    ravel, _, _ = ravel_fn(s2.server.params)
    np.testing.assert_allclose(
        np.asarray(ravel(s1.server.params)), np.asarray(ravel(s2.server.params)),
        rtol=2e-4, atol=2e-5,
    )
