"""Client-count padding to a mesh multiple (VERDICT r1 #6): uneven
federations shard by zero-padding ghost lanes that must never leak into
forging/aggregation/metrics.

Tier-2 (``slow``): every case compiles an 8-virtual-device shard_map
program — too slow for the tier-1 budget on a 2-core CPU host."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.adversaries import get_adversary, make_malicious_mask
from blades_tpu.core import FedRound, Server, TaskSpec
from blades_tpu.parallel import (
    make_mesh,
    shard_federation,
    shard_map_step,
    sharded_step,
)
from blades_tpu.parallel.mesh import pad_to_multiple
from blades_tpu.utils.tree import ravel_fn

pytestmark = pytest.mark.slow

N = 10  # deliberately NOT divisible by the 8-device mesh


def make_fr(**kw):
    task = TaskSpec(model="mlp", lr=0.1, input_shape=(28, 28, 1)).build()
    server = Server.from_config(aggregator="Median", num_byzantine=2, lr=1.0)
    adv = get_adversary("ALIE", num_clients=N, num_byzantine=2)
    return FedRound(task=task, server=server, adversary=adv, batch_size=8,
                    num_clients=N, **kw)


@pytest.fixture(scope="module")
def data():
    from blades_tpu.data import DatasetCatalog

    ds = DatasetCatalog.get_dataset("mnist", num_clients=N)
    return (
        jnp.array(ds.train.x), jnp.array(ds.train.y), jnp.array(ds.train.lengths),
        make_malicious_mask(N, 2),
    )


def test_pad_to_multiple():
    a = jnp.ones((10, 3))
    p = pad_to_multiple(a, 8)
    assert p.shape == (16, 3)
    assert float(p[10:].sum()) == 0.0
    assert pad_to_multiple(a, 5) is a  # already a multiple


@pytest.mark.parametrize("step_fn", [sharded_step, shard_map_step])
def test_uneven_federation_rounds_run(data, step_fn):
    x, y, ln, mal = data
    mesh = make_mesh()
    fr = make_fr()
    st = fr.init(jax.random.PRNGKey(0), N)
    st, (xs, ys, lns, mals) = shard_federation(mesh, st, (x, y, ln, mal))
    assert xs.shape[0] == 16  # padded to the mesh multiple
    kwargs = {"donate": False} if step_fn is sharded_step else {}
    step = step_fn(fr, mesh, **kwargs)
    losses = []
    for r in range(5):
        st, m = step(st, xs, ys, lns, mals,
                     jax.random.fold_in(jax.random.PRNGKey(1), r))
        losses.append(float(m["train_loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_ghost_lanes_do_not_leak_into_aggregate(data):
    """Two padded runs differing ONLY in ghost-lane data (zeros vs garbage)
    must produce identical server params — proof the slice excludes them."""
    x, y, ln, mal = data
    fr = make_fr()
    key = jax.random.PRNGKey(9)

    def run(ghost_value):
        xp = pad_to_multiple(x, 8)
        xp = xp.at[N:].set(ghost_value)
        yp = pad_to_multiple(y, 8)
        lnp = pad_to_multiple(ln, 8)          # ghost lengths = 0
        malp = pad_to_multiple(mal, 8)        # ghosts benign
        st = fr.init(jax.random.PRNGKey(0), 16)
        st, m = jax.jit(fr.step)(st, xp, yp, lnp, malp, key)
        return st, m

    st_a, m_a = run(0.0)
    st_b, m_b = run(1e6)
    ravel, _, _ = ravel_fn(st_a.server.params)
    np.testing.assert_array_equal(
        np.asarray(ravel(st_a.server.params)),
        np.asarray(ravel(st_b.server.params)),
    )
    assert float(m_a["train_loss"]) == float(m_b["train_loss"])
    assert float(m_a["update_norm_mean"]) == float(m_b["update_norm_mean"])


def test_fedavg_driver_uneven_clients_on_mesh():
    """End-to-end: the config path pads automatically and trains."""
    from blades_tpu.algorithms import FedavgConfig

    cfg = (
        FedavgConfig()
        .data(dataset="mnist", num_clients=N)
        .training(global_model="mlp", aggregator="Median", server_lr=1.0)
        .adversary(num_malicious_clients=2, adversary_config={"type": "ALIE"})
        .evaluation(evaluation_interval=4)
        .resources(num_devices=8)
    )
    algo = cfg.build()
    for _ in range(4):
        r = algo.train()
    assert np.isfinite(r["train_loss"])
    assert r["test_acc"] > 0.2
