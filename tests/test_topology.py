"""Decentralized gossip federation (ISSUE 19): topology + gossip tests.

The headline tier-1 contract is the one :mod:`blades_tpu.topology.gossip`
pins in its docstring: on the **complete graph with Mean**, the gossip
round — per-node local training, neighborhood exchange, per-node
aggregation, doubly-stochastic mixing — is **bit-identical** to the
centralized dense ``FedRound.step`` (tolerance ZERO: every node's
replica equals the dense server params, losses and agg norms match
bitwise).  The ICI reconciliation test checks the trace-time recorder
against :mod:`blades_tpu.parallel.comm_model.gossip_round_volumes` in
both directions, event by event; partition tolerance pins the
deterministic edge-dropout realization and the loud per-node
breakdown-bound degradation; and the driver tests run the full
``execution="gossip"`` surface including kill-and-resume bit-identity.

Budget note: gossip compiles ride tier-1 deliberately (the ISSUE 19
acceptance runs the decentralized path on the CPU tier-1 box); every
federation is tiny (MLP(8, 8) on 4x4x1 inputs, d = 226) and dense/
gossip trajectories are cached per config so each program compiles
exactly once.  The full graph x aggregator x attack zoo is slow-marked
and rides tier 2.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from blades_tpu.adversaries import get_adversary, make_malicious_mask
from blades_tpu.adversaries.topology_attacks import TopologyAttackAdversary
from blades_tpu.algorithms import FedavgConfig
from blades_tpu.core import FedRound, Server, TaskSpec
from blades_tpu.faults import FaultInjector
from blades_tpu.models.mlp import MLP
from blades_tpu.obs.schema import validate_record
from blades_tpu.parallel.comm_model import (
    gossip_round_volumes,
    gossip_wire_bytes,
)
from blades_tpu.parallel.mesh import make_mesh
from blades_tpu.topology import (
    GRAPHS,
    TopologyConfig,
    get_topology,
    gossip_evaluate,
    gossip_federation,
    gossip_step,
)
from blades_tpu.utils.tree import ravel_fn

N_CLIENTS = 8
N_BYZ = 2
ROWS = 4
SHAPE = (4, 4, 1)
TOPO_ALIE = {"type": "TopologyAttack", "base": "ALIE"}


def _tiny_round(agg="Median", attack="ALIE", n=N_CLIENTS, f=N_BYZ, seed=0,
                faults=None, health=False):
    """A raw FedRound on the tiny synthetic task (d = 226 params)."""
    task = TaskSpec(model=MLP(hidden1=8, hidden2=8, num_classes=2),
                    num_classes=2, input_shape=SHAPE, lr=0.1).build()
    server = Server.from_config(aggregator=agg, num_byzantine=f or None,
                                lr=0.5)
    adv = (get_adversary(attack, num_clients=n, num_byzantine=f)
           if attack is not None else None)
    fr = FedRound(task=task, server=server, adversary=adv, batch_size=2,
                  num_batches_per_round=1, num_clients=n, faults=faults,
                  health_check=health)
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(n, ROWS) + SHAPE), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, size=(n, ROWS)), jnp.int32)
    lengths = jnp.full((n,), ROWS, jnp.int32)
    mal = make_malicious_mask(n, f)
    return fr, (x, y, lengths, mal)


def _run_dense(fr, data, rounds):
    """Single-chip dense trajectory: (losses, aggns, final params)."""
    x, y, lengths, mal = data
    state = fr.init(jax.random.PRNGKey(0), N_CLIENTS)
    step = jax.jit(fr.step)
    losses, aggns = [], []
    for r in range(rounds):
        state, m = step(state, x, y, lengths, mal,
                        jax.random.fold_in(jax.random.PRNGKey(9), r))
        losses.append(float(m["train_loss"]))
        aggns.append(float(m["agg_norm"]))
    return losses, aggns, jax.tree.map(np.asarray, state.server.params)


def _mesh8():
    """The 8-virtual-device 1-D mesh (kept out of test bodies so the
    slow-markers pass only bills tests that actually COMPILE on it —
    the build-gate tests below raise before tracing)."""
    return make_mesh(8)


def _run_gossip(fr, data, rounds, graph, *, n=N_CLIENTS, **topo_kw):
    """Gossip trajectory on the 8-device mesh.

    Returns ``(losses, aggns, per-node params stack, recorder,
    last metrics)``.
    """
    x, y, lengths, mal = data
    mesh = make_mesh(8)
    topo = TopologyConfig(graph=graph, num_nodes=n, **topo_kw)
    state = fr.init(jax.random.PRNGKey(0), n)
    state, arrays = gossip_federation(mesh, state, (x, y, lengths))
    step, rec = gossip_step(fr, mesh, topo)
    losses, aggns, m = [], [], None
    for r in range(rounds):
        state, m = step(state, *arrays, mal,
                        jax.random.fold_in(jax.random.PRNGKey(9), r))
        losses.append(float(m["train_loss"]))
        aggns.append(float(m["agg_norm"]))
    return (losses, aggns, jax.tree.map(np.asarray, state.server.params),
            rec, {k: np.asarray(v) for k, v in m.items()})


_DENSE_CACHE = {}
_GOSSIP_CACHE = {}


def _dense(agg, attack, rounds=2, f=N_BYZ):
    key = (agg, str(attack), rounds, f)
    if key not in _DENSE_CACHE:
        fr, data = _tiny_round(agg, attack, f=f)
        _DENSE_CACHE[key] = _run_dense(fr, data, rounds)
    return _DENSE_CACHE[key]


def _gossip(agg, attack, graph, rounds=2, f=N_BYZ, **topo_kw):
    key = (agg, str(attack), graph, rounds, f,
           tuple(sorted(topo_kw.items())))
    if key not in _GOSSIP_CACHE:
        fr, data = _tiny_round(agg, attack, f=f)
        _GOSSIP_CACHE[key] = _run_gossip(fr, data, rounds, graph, **topo_kw)
    return _GOSSIP_CACHE[key]


# ---------------------------------------------------------------------------
# graph family unit tests (host-side numpy, no mesh)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("graph", GRAPHS)
def test_graph_adjacency_and_mixing_contracts(graph):
    """Every family: symmetric adjacency, no self loops, connected-by-
    construction mixing that is symmetric doubly-stochastic with a
    positive spectral gap."""
    topo = TopologyConfig(graph=graph, num_nodes=8, k=4, p=0.3)
    a = topo.adjacency()
    assert a.dtype == bool and a.shape == (8, 8)
    assert np.array_equal(a, a.T)
    assert not a.diagonal().any()
    assert (a.sum(axis=1) >= 1).all()  # no isolated nodes
    w = topo.mixing_matrix()
    np.testing.assert_allclose(w.sum(axis=0), 1.0, atol=1e-12)
    np.testing.assert_allclose(w.sum(axis=1), 1.0, atol=1e-12)
    assert (w >= 0).all()
    np.testing.assert_allclose(w, w.T, atol=1e-15)
    assert 0.0 < topo.spectral_gap <= 1.0


def test_erdos_seeded_and_complete_gap():
    """The one random family is pure in graph_seed (two processes build
    the same graph); complete's mixing is the uniform average — the
    largest possible gap — and denser graphs mix no slower than ring."""
    a1 = TopologyConfig(graph="erdos", num_nodes=12, p=0.4,
                        graph_seed=7).adjacency()
    a2 = TopologyConfig(graph="erdos", num_nodes=12, p=0.4,
                        graph_seed=7).adjacency()
    a3 = TopologyConfig(graph="erdos", num_nodes=12, p=0.4,
                        graph_seed=8).adjacency()
    assert np.array_equal(a1, a2)
    assert not np.array_equal(a1, a3)
    gaps = {g: TopologyConfig(graph=g, num_nodes=8, k=4).spectral_gap
            for g in ("ring", "kregular", "complete")}
    assert gaps["complete"] == pytest.approx(1.0)
    assert gaps["ring"] < gaps["kregular"] <= gaps["complete"]


def test_neighbor_tables_slot_contract():
    """The bit-identity pin rests on this ordering: closed neighborhoods
    in ASCENDING global index (so complete-graph rows reproduce the
    dense matrix), pad slots pointing at the node itself, w_slot zero on
    self and pad slots."""
    topo = TopologyConfig(graph="ring", num_nodes=6)
    t = topo.neighbor_tables()
    n, k1 = t.nbr_idx.shape
    assert (n, k1) == (6, 3)
    w = topo.mixing_matrix()
    for i in range(n):
        d_i = int(t.valid[i].sum())
        real = t.nbr_idx[i, :d_i]
        assert list(real) == sorted(real)  # ascending global index
        assert i in real
        assert (t.nbr_idx[i, d_i:] == i).all()  # ghost slots = self
        assert t.nbr_idx[i, t.self_slot[i]] == i
        assert t.w_slot[i, t.self_slot[i]] == 0.0
        assert (t.w_slot[i, d_i:] == 0.0).all()
        for s in range(d_i):
            j = int(real[s])
            if j != i:
                assert t.w_slot[i, s] == pytest.approx(w[i, j], rel=1e-6)
    # Complete graph: every row is the identity permutation 0..n-1.
    tc = TopologyConfig(graph="complete", num_nodes=6).neighbor_tables()
    assert np.array_equal(tc.nbr_idx,
                          np.tile(np.arange(6, dtype=np.int32), (6, 1)))


def test_graph_validation_messages():
    with pytest.raises(ValueError, match="unknown topology graph"):
        TopologyConfig(graph="smallworld", num_nodes=8)
    with pytest.raises(ValueError, match="unknown mixing scheme"):
        TopologyConfig(graph="ring", num_nodes=8, mixing="lazy")
    with pytest.raises(ValueError, match="num_nodes >= 2"):
        TopologyConfig(graph="ring", num_nodes=1)
    with pytest.raises(ValueError, match="must be even with 2 <= k"):
        TopologyConfig(graph="kregular", num_nodes=8, k=3)
    with pytest.raises(ValueError, match="p=1.5 must be in"):
        TopologyConfig(graph="erdos", num_nodes=8, p=1.5)
    with pytest.raises(ValueError, match="torus needs a 2-D grid"):
        TopologyConfig(graph="torus", num_nodes=7)
    # get_topology resolution: name, dict, instance (pinning num_nodes).
    assert get_topology("kregular", 8).graph == "kregular"
    assert get_topology({"graph": "erdos", "p": 0.5}, 8).p == 0.5
    t = TopologyConfig(graph="ring", num_nodes=4)
    assert get_topology(t, 99) is t


# ---------------------------------------------------------------------------
# the headline pin: complete graph + Mean == centralized dense, bitwise
# ---------------------------------------------------------------------------


def test_complete_mean_gossip_bit_identical_to_dense():
    """Tolerance ZERO: with the complete graph and Mean every node's
    neighborhood matrix IS the dense matrix in dense row order, mixing
    is a no-op on consensus replicas, and the RNG discipline mirrors the
    dense split chain — so every node's replica must equal the dense
    server params bitwise, along with losses and agg norms."""
    d_losses, d_aggns, d_params = _dense("Mean", "ALIE", rounds=3)
    g_losses, g_aggns, g_params, _, m = _gossip("Mean", "ALIE", "complete",
                                                rounds=3)
    assert g_losses == d_losses
    assert g_aggns == d_aggns
    for stack, ref in zip(jax.tree.leaves(g_params),
                          jax.tree.leaves(d_params)):
        for i in range(N_CLIENTS):
            assert np.array_equal(stack[i], ref)
    # Consensus never breaks on the complete graph.
    assert float(m["consensus_dist"]) == 0.0
    assert int(m["num_partitioned_nodes"]) == 0


# ---------------------------------------------------------------------------
# ICI accounting: recorder <-> comm model, both directions
# ---------------------------------------------------------------------------


# Byte-accounting reconciliation over a full gossip compile (~6 s); the
# gossip round path itself stays tier-1 via the complete-graph + Mean
# centralized-equivalence test (PR 20 budget rebalance).
@pytest.mark.slow
def test_gossip_ici_reconciles_with_comm_model_both_ways():
    """Every collective the traced gossip program counted must appear in
    the analytic inventory with the same (kind, payload, ring), and vice
    versa; the per-chip wire totals must be EQUAL (same integer ring
    arithmetic on both sides) and match the stamped metric."""
    _, _, d_params = _dense("Mean", "ALIE")
    _, _, d = ravel_fn(d_params)
    # Fault-free round: the partition psum is absent on both sides.
    _, _, _, rec, m = _gossip("Mean", "ALIE", "complete")
    vols = gossip_round_volumes(N_CLIENTS, d, (8, 1))
    model = sorted((v.kind, v.payload_bytes, k)
                   for v, k in vols for _ in range(v.count))
    recorded = sorted((kind, payload, k)
                      for _, kind, payload, k in rec.ici_events)
    assert recorded == model, (recorded, model)
    assert rec.ici_bytes == gossip_wire_bytes(vols)
    assert int(m["gossip_ici_bytes"]) == rec.ici_bytes
    # Fault-armed round: the partitioned-count psum joins the inventory.
    fr, data = _tiny_round("Median", "SignFlip",
                           faults=FaultInjector(seed=5, dropout_rate=0.3))
    _, _, _, rec_f, m_f = _run_gossip(fr, data, 1, "ring")
    vols_f = gossip_round_volumes(N_CLIENTS, d, (8, 1), faults=True)
    model_f = sorted((v.kind, v.payload_bytes, k)
                     for v, k in vols_f for _ in range(v.count))
    recorded_f = sorted((kind, payload, k)
                        for _, kind, payload, k in rec_f.ici_events)
    assert recorded_f == model_f, (recorded_f, model_f)
    assert rec_f.ici_bytes == gossip_wire_bytes(vols_f)
    assert int(m_f["gossip_ici_bytes"]) == rec_f.ici_bytes
    # The exchange volume does not depend on graph density (replica
    # gathers ship the full stack; the topology selects locally).
    assert rec.ici_bytes == gossip_wire_bytes(
        gossip_round_volumes(N_CLIENTS, d, (8, 1), faults=False))


# ---------------------------------------------------------------------------
# robustness grid: graph x aggregator x attack
# ---------------------------------------------------------------------------


def _assert_cell_healthy(agg, attack, graph, f=N_BYZ, **topo_kw):
    losses, _, params, _, m = _gossip(agg, attack, graph, f=f, **topo_kw)
    assert all(np.isfinite(v) for v in losses), (graph, agg, losses)
    for leaf in jax.tree.leaves(params):
        assert np.isfinite(leaf[:N_CLIENTS]).all()
    assert int(m["gossip_ici_bytes"]) > 0
    assert float(m["consensus_dist"]) >= 0.0
    assert int(m["num_partitioned_nodes"]) == 0  # no faults armed


# Headline tier-1 subset: one cell per graph family + the Multikrum
# static-gate survivor, covering both attack flavors.  Multikrum cells
# run f=1: Krum scoring needs 2f+2 rows per neighborhood matrix, so
# f=2 on kregular's k1=5 matrices is structurally out (the f=2 ring
# rejection is pinned by the breakdown-gate test below).
GRID_HEADLINE = [
    ("Median", TOPO_ALIE, "ring", {}, N_BYZ),
    ("Mean", "SignFlip", "ring", {}, N_BYZ),
    ("Multikrum", "SignFlip", "kregular", {"k": 4}, 1),
    ("Median", TOPO_ALIE, "complete", {}, N_BYZ),
]

# The slow zoo: every remaining supported (graph, aggregator, attack)
# cell — ring excludes Multikrum (the breakdown gate rejects it, pinned
# below); kregular/complete run all three aggregators.
GRID_ZOO = [
    (agg, attack, graph, ({"k": 4} if graph == "kregular" else {}),
     (1 if agg == "Multikrum" else N_BYZ))
    for graph in ("ring", "kregular", "complete")
    for agg in ("Mean", "Median", "Multikrum")
    for attack in (TOPO_ALIE, "SignFlip")
    if not (graph == "ring" and agg == "Multikrum")
    and (agg, attack, graph) not in [(a, k, g) for a, k, g, _, _ in
                                     GRID_HEADLINE]
]


@pytest.mark.parametrize(
    "agg,attack,graph,kw,f", GRID_HEADLINE,
    ids=[f"{g}-{a}-{k if isinstance(k, str) else 'TopoALIE'}"
         for a, k, g, _, _ in GRID_HEADLINE])
def test_gossip_grid_headline(agg, attack, graph, kw, f):
    """>= 3 aggregators x 2 attacks across ring/kregular/complete: the
    per-node robust round stays finite and stamps sane telemetry."""
    _assert_cell_healthy(agg, attack, graph, f=f, **kw)


@pytest.mark.slow
@pytest.mark.parametrize(
    "agg,attack,graph,kw,f", GRID_ZOO,
    ids=[f"{g}-{a}-{k if isinstance(k, str) else 'TopoALIE'}"
         for a, k, g, _, _ in GRID_ZOO])
def test_gossip_grid_zoo(agg, attack, graph, kw, f):
    _assert_cell_healthy(agg, attack, graph, f=f, **kw)


def test_multikrum_ring_breakdown_gate():
    """Static build-time gate: Multikrum(f=2) needs f+3 = 5 neighborhood
    rows; ring's closed neighborhoods hold 3 — the pair must be rejected
    BEFORE tracing, naming the graph, the aggregator, and the fix."""
    fr, _ = _tiny_round("Multikrum", None)
    with pytest.raises(ValueError,
                       match=r"Multikrum\(num_byzantine=2\) needs "
                             r"neighborhood matrices of >= 5 rows"):
        gossip_step(fr, _mesh8(),
                    TopologyConfig(graph="ring", num_nodes=N_CLIENTS))


# ---------------------------------------------------------------------------
# topology-scoped adversaries
# ---------------------------------------------------------------------------


def test_topology_attack_receiver_mask():
    adv = get_adversary(TOPO_ALIE, num_clients=6, num_byzantine=2)
    assert isinstance(adv, TopologyAttackAdversary)
    assert adv.topology_scoped
    a = TopologyConfig(graph="ring", num_nodes=6).adjacency()
    mask = adv.receiver_mask(a)
    # Out-edge poisoning: receiver i sees forged rows from its IN-edges
    # (column view of the adjacency) — for symmetric graphs, a.T == a.
    assert mask.dtype == bool and mask.shape == (6, 6)
    assert np.array_equal(mask, a.T)
    # Eclipse focuses the forged rows on one receiver only.
    adv_e = get_adversary({**TOPO_ALIE, "eclipse_target": 3},
                          num_clients=6, num_byzantine=2)
    mask_e = adv_e.receiver_mask(a)
    assert mask_e[3].any()
    assert not np.delete(mask_e, 3, axis=0).any()


def test_topology_attack_validation():
    with pytest.raises(ValueError, match="eclipse_target"):
        get_adversary({**TOPO_ALIE, "eclipse_target": 99},
                      num_clients=6, num_byzantine=2)
    with pytest.raises(ValueError, match="TopologyAttack"):
        # Wrapping itself is a config error, not infinite recursion.
        get_adversary({"type": "TopologyAttack", "base": "TopologyAttack"},
                      num_clients=6, num_byzantine=2)
    adv = get_adversary(TOPO_ALIE, num_clients=8, num_byzantine=2)
    with pytest.raises(ValueError, match="num_clients"):
        adv.receiver_mask(np.zeros((4, 4), bool))


def test_eclipse_focuses_poison_on_target():
    """One gossip round from consensus init on the complete graph with
    an eclipse on node 5: only node 5's neighborhood matrix carries
    forged rows (receiver_mask restricts the poison-slot select), so
    every OTHER node aggregates the identical clean full matrix from
    identical mixed params — all 7 replicas bit-identical to each
    other — while the eclipsed target's replica diverges."""
    fr, data = _tiny_round("Mean", {**TOPO_ALIE, "eclipse_target": 5})
    _, _, params, _, _ = _run_gossip(fr, data, 1, "complete")
    leaves = jax.tree.leaves(params)
    others = [i for i in range(N_CLIENTS) if i != 5]
    for leaf in leaves:
        for i in others[1:]:
            assert np.array_equal(leaf[others[0]], leaf[i])
    assert any(not np.array_equal(leaf[5], leaf[others[0]])
               for leaf in leaves)


# ---------------------------------------------------------------------------
# partition tolerance: deterministic edge dropout, loud degradation
# ---------------------------------------------------------------------------


def _dropout_run(rounds=3):
    fr, data = _tiny_round("Median", "SignFlip",
                           faults=FaultInjector(seed=5, dropout_rate=0.6),
                           health=True)
    x, y, lengths, mal = data
    mesh = make_mesh(8)
    topo = TopologyConfig(graph="ring", num_nodes=N_CLIENTS)
    state = fr.init(jax.random.PRNGKey(0), N_CLIENTS)
    state, arrays = gossip_federation(mesh, state, (x, y, lengths))
    step, _ = gossip_step(fr, mesh, topo)
    parts, m = [], None
    for r in range(rounds):
        state, m = step(state, *arrays, mal,
                        jax.random.fold_in(jax.random.PRNGKey(11), r))
        parts.append(int(m["num_partitioned_nodes"]))
    return parts, jax.tree.map(np.asarray, state.server.params), m


def test_partition_tolerance_degrades_loudly_and_deterministically():
    """Edge dropout at 0.6 on a ring partitions nodes below Median's
    breakdown bound (2f+1 live rows): the round keeps running, each
    degraded node falls back to self-trust (params stay finite), the
    count lands LOUDLY in num_partitioned_nodes, and the realization is
    pure in (fault_seed, round) — a rebuilt run reproduces the counts
    and the params bitwise."""
    parts, params, m = _dropout_run()
    assert any(p > 0 for p in parts), parts
    assert all(0 <= p <= N_CLIENTS for p in parts)
    for leaf in jax.tree.leaves(params):
        assert np.isfinite(leaf[:N_CLIENTS]).all()
    assert bool(m["round_ok"])  # degraded != unhealthy
    parts2, params2, _ = _dropout_run()
    assert parts2 == parts
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(params2)):
        assert np.array_equal(a, b)


@pytest.mark.slow
def test_dropout_never_fires_without_faults():
    """The fault-free program contains no partition psum and stamps a
    hard zero — covered by the grid cells asserting
    num_partitioned_nodes == 0 — and a zero-rate injector on a clean
    federation keeps every edge alive and every node above its bound.
    (With attackers present the per-node breakdown check is live even
    at rate 0: adjacent ring attackers degrade their OWN 3-row
    neighborhoods, f_i=2 -> need 5 — that loudness is the feature.)"""
    fr, data = _tiny_round("Median", None, f=0,
                           faults=FaultInjector(seed=5, dropout_rate=0.0))
    parts = []
    x, y, lengths, mal = data
    mesh = make_mesh(8)
    state = fr.init(jax.random.PRNGKey(0), N_CLIENTS)
    state, arrays = gossip_federation(mesh, state, (x, y, lengths))
    step, _ = gossip_step(fr, mesh,
                          TopologyConfig(graph="ring", num_nodes=N_CLIENTS))
    for r in range(2):
        state, m = step(state, *arrays, mal,
                        jax.random.fold_in(jax.random.PRNGKey(3), r))
        parts.append(int(m["num_partitioned_nodes"]))
    assert parts == [0, 0]


# ---------------------------------------------------------------------------
# driver surface: config gates, schema row, kill-and-resume
# ---------------------------------------------------------------------------


def _tiny_population_dataset(n_clients, rows_per_client=4, shape=SHAPE,
                             num_classes=2, seed=0):
    from blades_tpu.data.datasets import FLDataset
    from blades_tpu.data.partition import partition_dataset

    rng = np.random.default_rng(seed)
    n = n_clients * rows_per_client
    mus = rng.normal(size=(num_classes,) + shape).astype(np.float32)
    y = rng.integers(0, num_classes, size=n).astype(np.int32)
    x = (mus[y] + 0.5 * rng.normal(size=(n,) + shape)).astype(np.float32)
    train = partition_dataset(x, y, n_clients, iid=True, seed=seed)
    test = partition_dataset(x[: 2 * n_clients], y[: 2 * n_clients],
                             n_clients, iid=True, seed=seed + 1)
    return FLDataset(name="tinypop", train=train, test_x=x[:64],
                     test_y=y[:64], test=test, num_classes=num_classes,
                     input_shape=shape)


def _gossip_driver(n=N_CLIENTS, *, graph="ring", agg="Median", adv=None,
                   nm=0, faults=None, seed=0, **topo_kw):
    cfg = (
        FedavgConfig()
        .data(dataset=_tiny_population_dataset(n, seed=seed), num_clients=n,
              seed=seed)
        .training(global_model=MLP(hidden1=8, hidden2=8, num_classes=2),
                  num_classes=2, input_shape=SHAPE, server_lr=0.5,
                  train_batch_size=4, aggregator={"type": agg})
        .client(lr=0.1)
        .evaluation(evaluation_interval=0)
        .resources(num_devices=8, execution="gossip")
        .topology(graph=graph, **topo_kw)
    )
    if nm:
        cfg.adversary(num_malicious_clients=nm, adversary_config=adv)
    if faults:
        cfg.fault_tolerance(faults=faults)
    return cfg.build()


def test_gossip_driver_row_stamps_and_schema():
    """The full driver round stamps the six gossip fields together
    (validate_metrics' partial-stamp contract) and the row passes the
    round-record schema."""
    algo = _gossip_driver(nm=2, adv=TOPO_ALIE)
    try:
        row = algo.train()
        validate_record(dict(row, experiment="gossip", trial="t0",
                             training_iteration=1))
        assert row["topology"] == "ring"
        assert row["graph_seed"] == 0
        assert 0.0 < row["spectral_gap"] <= 1.0
        assert row["gossip_ici_bytes"] > 0
        assert row["num_partitioned_nodes"] == 0
        assert row["consensus_dist"] >= 0.0
        ev = algo.evaluate()
        assert np.isfinite(ev["test_loss"])
    finally:
        algo.stop()


def test_gossip_kill_and_resume_bit_identical(tmp_path):
    """Kill-and-resume through the faults harness: checkpoint a gossip
    run with edge dropout mid-stream, rebuild a fresh driver, load, and
    the continued rounds must be bit-identical to the uninterrupted run
    (round keys and the edge realization both derive from the stored
    round counter; the per-node params stack rides the checkpoint
    verbatim through reshard_gossip_state)."""
    kw = dict(graph="kregular", k=4, nm=2, adv={"type": "SignFlip"},
              faults={"dropout_rate": 0.4, "seed": 11})
    a = _gossip_driver(**kw)
    try:
        a.train()
        path = a.save_checkpoint(str(tmp_path))
        r2a = a.train()
        r3a = a.train()
        b = _gossip_driver(**kw)
        try:
            b.load_checkpoint(path)
            r2b = b.train()
            r3b = b.train()
            assert r2a["train_loss"] == r2b["train_loss"]
            assert r3a["train_loss"] == r3b["train_loss"]
            assert (r3a["num_partitioned_nodes"]
                    == r3b["num_partitioned_nodes"])
            for x, y in zip(jax.tree.leaves(a.state.server.params),
                            jax.tree.leaves(b.state.server.params)):
                assert np.array_equal(np.asarray(x), np.asarray(y))
        finally:
            b.stop()
    finally:
        a.stop()


# ---------------------------------------------------------------------------
# validate(): every gossip rejection names the exact pair + knob
# ---------------------------------------------------------------------------


def _check(match, *, topology=None, adversary=None, **kw):
    cfg = (
        FedavgConfig()
        .data(dataset="mnist", num_clients=8, seed=0)
        .training(global_model="mlp", aggregator={"type": "Median"})
    )
    if topology is not None:
        cfg.topology(**topology)
    if adversary is not None:
        cfg.adversary(num_malicious_clients=2, adversary_config=adversary)
    for k, v in kw.items():
        setattr(cfg, k, v)
    with pytest.raises(ValueError, match=match):
        cfg.validate()


def test_gossip_validation_messages():
    _check("topology_config is set but execution='dense'",
           topology={"graph": "ring"}, execution="dense")
    _check(r"execution='gossip' × update codecs", execution="gossip",
           codec_config={"name": "quant", "bits": 8})
    _check(r"execution='gossip' × defense forensics", execution="gossip",
           forensics=True)
    _check(r"execution='gossip' × 2-D mesh_shape",
           execution="gossip", mesh_shape=(4, 2))
    _check(r"execution='gossip' × straggler faults", execution="gossip",
           fault_config={"num_stragglers": 2, "staleness": 1})
    _check(r"execution='gossip' × corruption faults", execution="gossip",
           fault_config={"corrupt_rate": 0.1})
    # A bad graph knob dies at validate() time, not at trace time.
    _check("kregular degree k=3", execution="gossip",
           topology={"graph": "kregular", "k": 3})
    # Topology-scoped adversaries need the peer graph.
    _check("topology-scoped", adversary=TOPO_ALIE, execution="dense")


@pytest.mark.slow
def test_flightrec_replay_gossip_round(tmp_path):
    """tools/replay_round on a gossip dump: the peer graph rebuilds from
    topology_config, the edge-dropout realization is pure in
    (fault_seed, round), and the gossip digest fields (gossip_ici_bytes,
    num_partitioned_nodes, consensus_dist, spectral_gap, graph_seed)
    compare bit-for-bit."""
    import json

    from blades_tpu.algorithms import get_algorithm_class
    from blades_tpu.obs.flightrec import FlightRecorder
    from tools.replay_round import main as replay_main

    trial_cfg = {
        "dataset_config": {"type": "mnist", "num_clients": N_CLIENTS,
                           "seed": 7},
        "global_model": "mlp",
        "num_devices": 8,
        "execution": "gossip",
        "topology_config": {"graph": "ring"},
        "fault_config": {"dropout_rate": 0.5, "seed": 11},
        "adversary_config": {"type": "SignFlip"},
        "num_malicious_clients": 2,
    }
    _, config = get_algorithm_class("FEDAVG", return_config=True)
    config.update_from_dict(json.loads(json.dumps(trial_cfg)))
    algo = config.build()
    rec = FlightRecorder(tmp_path / "flightrec.json", capacity=8,
                         experiment="e", trial="t", algo="FEDAVG",
                         config=trial_cfg, max_rounds=3)
    try:
        rows = [algo.train() for _ in range(3)]
    finally:
        algo.stop()
    assert any(r["num_partitioned_nodes"] > 0 for r in rows)
    for r in rows:
        rec.record(json.loads(json.dumps(dict(r, trial="t"),
                                         default=float)))
    rec.dump({"kind": "exception",
              "round": rows[-1]["training_iteration"]})
    assert replay_main([str(tmp_path / "flightrec.json"), "--quiet"]) == 0


@pytest.mark.slow
def test_gossip_evaluate_reads_node0_head():
    fr, data = _tiny_round("Median", None)
    x, y, lengths, _ = data
    mesh = make_mesh(8)
    state = fr.init(jax.random.PRNGKey(0), N_CLIENTS)
    state, arrays = gossip_federation(mesh, state, (x, y, lengths))
    step, _ = gossip_step(fr, mesh,
                          TopologyConfig(graph="complete",
                                         num_nodes=N_CLIENTS))
    state, _ = step(state, *arrays, make_malicious_mask(N_CLIENTS, 0),
                    jax.random.PRNGKey(1))
    ev = gossip_evaluate(fr)(state, x, y, lengths)
    assert np.isfinite(float(ev["test_loss"]))
    assert 0.0 <= float(ev["test_acc"]) <= 1.0
