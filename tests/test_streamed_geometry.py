"""Streamed row-geometry aggregation vs the dense round.

The streamed path re-expresses every row-geometry aggregator as chunked
full-matrix passes (:mod:`blades_tpu.parallel.streamed_geometry`).  With
f32 storage the only divergence from the dense ``FedRound.step`` is
chunk-level reduction reassociation, so whole-round equivalence holds to
tight tolerances.  d and d_chunk are chosen so the matrix spans several
chunks including a ragged overlapping tail.

Tier-2 (``slow``): the many-chunk geometry makes each case compile-heavy
(~2 min of wall clock for the file on a 2-core CPU host); tier-1 keeps
the streamed path covered via ``test_streamed.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.adversaries import get_adversary, make_malicious_mask
from blades_tpu.core import FedRound, Server, TaskSpec
from blades_tpu.parallel.streamed import streamed_step

pytestmark = pytest.mark.slow

N, F = 12, 3
D_CHUNK = 1024  # model d ~ 44k -> dozens of chunks + ragged tail


def _setup(aggregator, adversary=None, trusted=False, **fr_kw):
    task = TaskSpec(model="mlp", input_shape=(8, 8, 1), num_classes=10,
                    lr=0.1).build()
    server = Server.from_config(aggregator=aggregator, num_byzantine=F, lr=0.5)
    adv = (get_adversary(adversary, num_clients=N, num_byzantine=F)
           if adversary else None)
    rng = np.random.default_rng(0)
    extra = {}
    if trusted:
        extra["trusted_data"] = (
            jnp.asarray(rng.normal(size=(16, 8, 8, 1)), jnp.float32),
            jnp.asarray(rng.integers(0, 10, size=(16,)), jnp.int32),
        )
    fr = FedRound(task=task, server=server, adversary=adv, batch_size=4,
                  num_batches_per_round=1, **extra, **fr_kw)
    x = jnp.asarray(rng.normal(size=(N, 8, 8, 8, 1)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=(N, 8)), jnp.int32)
    lengths = jnp.full((N,), 8, jnp.int32)
    mal = make_malicious_mask(N, F)
    return fr, x, y, lengths, mal


def _run_both(fr, x, y, lengths, mal, rounds=2):
    dense = jax.jit(fr.step)
    streamed = streamed_step(fr, client_block=4, d_chunk=D_CHUNK,
                             update_dtype=jnp.float32, donate=False)
    sd = fr.init(jax.random.PRNGKey(0), N)
    ss = fr.init(jax.random.PRNGKey(0), N)
    for r in range(rounds):
        k = jax.random.fold_in(jax.random.PRNGKey(7), r)
        sd, md = dense(sd, x, y, lengths, mal, k)
        ss, ms = streamed(ss, x, y, lengths, mal, k)
    return sd, md, ss, ms


AGGS = ["GeoMed", "Multikrum", "DnC", "Centeredclipping", "Signguard",
        "Clippedclustering"]


@pytest.mark.parametrize("aggregator", AGGS)
def test_rowgeom_matches_dense(aggregator):
    fr, x, y, lengths, mal = _setup(aggregator, adversary="ALIE")
    sd, md, ss, ms = _run_both(fr, x, y, lengths, mal)
    for k in ("train_loss", "agg_norm", "update_norm_mean"):
        np.testing.assert_allclose(float(ms[k]), float(md[k]), rtol=2e-4,
                                   atol=1e-5)
    for a, b in zip(jax.tree.leaves(ss.server.params),
                    jax.tree.leaves(sd.server.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-5)


def test_rowgeom_fltrust_matches_dense():
    fr, x, y, lengths, mal = _setup("FLTrust", adversary="IPM", trusted=True)
    sd, md, ss, ms = _run_both(fr, x, y, lengths, mal)
    for a, b in zip(jax.tree.leaves(ss.server.params),
                    jax.tree.leaves(sd.server.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-5)


def test_rowgeom_stateful_state_advances():
    """Centeredclipping's momentum and Clippedclustering's norm history
    thread through the streamed round like the dense one."""
    fr, x, y, lengths, mal = _setup("Centeredclipping")
    sd, _, ss, _ = _run_both(fr, x, y, lengths, mal)
    np.testing.assert_allclose(np.asarray(ss.server.agg_state),
                               np.asarray(sd.server.agg_state),
                               rtol=2e-4, atol=2e-5)
    fr, x, y, lengths, mal = _setup("Clippedclustering")
    sd, _, ss, _ = _run_both(fr, x, y, lengths, mal)
    assert int(ss.server.agg_state["count"]) == int(sd.server.agg_state["count"])
    np.testing.assert_allclose(
        np.sort(np.asarray(ss.server.agg_state["norm_history"])),
        np.sort(np.asarray(sd.server.agg_state["norm_history"])),
        rtol=2e-4, atol=2e-5,
    )


def test_rowgeom_alie_signguard_negates_global_half():
    """The round-1 landmine: ALIE's SignGuard evasion must negate the
    GLOBAL first half of the std under the chunked layout."""
    fr, x, y, lengths, mal = _setup("Signguard", adversary="ALIE")
    sd, _, ss, _ = _run_both(fr, x, y, lengths, mal, rounds=1)
    for a, b in zip(jax.tree.leaves(ss.server.params),
                    jax.tree.leaves(sd.server.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-5)


def test_rowgeom_dp_overlap_columns_not_reprocessed():
    """The tail chunk overlaps its predecessor; DP clip (non-idempotent)
    must not be applied twice to the overlap columns.  d_model (~44k) is
    not a multiple of D_CHUNK, so the tail overlap exists here."""
    fr, x, y, lengths, mal = _setup(
        "GeoMed", dp_clip_threshold=0.05, dp_noise_factor=0.0
    )
    sd, md, ss, ms = _run_both(fr, x, y, lengths, mal, rounds=1)
    for a, b in zip(jax.tree.leaves(ss.server.params),
                    jax.tree.leaves(sd.server.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-5)


def test_rowgeom_health_check_survives_nan_lane():
    fr, x, y, lengths, mal = _setup("Multikrum", health_check=True)
    streamed = streamed_step(fr, client_block=4, d_chunk=D_CHUNK,
                             update_dtype=jnp.float32, donate=False)
    st = fr.init(jax.random.PRNGKey(0), N)
    x_bad = x.at[2].set(jnp.nan)
    st, m = streamed(st, x_bad, y, lengths, mal, jax.random.PRNGKey(1))
    assert int(m["num_unhealthy"]) >= 1
    assert bool(m["round_ok"])
    assert all(bool(jnp.isfinite(p).all()) for p in
               jax.tree.leaves(st.server.params))


@pytest.mark.parametrize("adversary,aggregator", [
    ("MinMax", "Median"),
    ("MinMax", "Signguard"),          # SignGuard-evasion negate-half path
    ("SignGuard", "Mean"),
    ("Attackclippedclustering", "Clippedclustering"),
    ("MinMax", "Multikrum"),          # rowgeom forger + rowgeom aggregator
])
def test_rowgeom_forgers_match_dense(adversary, aggregator):
    """MinMax / SignGuard-attack / Attackclippedclustering forge via
    stats passes + a scatter; whole rounds match the dense path."""
    fr, x, y, lengths, mal = _setup(aggregator, adversary=adversary)
    rtol = 5e-3 if adversary == "MinMax" else 2e-4
    sd, md, ss, ms = _run_both(fr, x, y, lengths, mal)
    for a, b in zip(jax.tree.leaves(ss.server.params),
                    jax.tree.leaves(sd.server.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=rtol,
                                   atol=5e-5)


def test_config_streamed_execution_accepts_rowgeom_aggregator():
    """execution='streamed' at the algorithm layer drives a row-geometry
    aggregator end-to-end."""
    from blades_tpu.algorithms import FedavgConfig

    algo = (
        FedavgConfig()
        .data(dataset="mnist", num_clients=8)
        .training(global_model="mlp", server_lr=0.5,
                  aggregator={"type": "Multikrum"}, train_batch_size=4)
        .adversary(num_malicious_clients=2,
                   adversary_config={"type": "IPM"})
        .resources(execution="streamed", client_block=4)
        .build()
    )
    r = algo.train()
    assert np.isfinite(r["train_loss"])


def test_rowgeom_rejects_ghost_lanes():
    fr, x, y, lengths, mal = _setup("GeoMed")
    fr = FedRound(task=fr.task, server=fr.server, adversary=fr.adversary,
                  batch_size=4, num_batches_per_round=1, num_clients=N - 2)
    streamed = streamed_step(fr, client_block=4, d_chunk=D_CHUNK,
                             update_dtype=jnp.float32, donate=False)
    st = fr.init(jax.random.PRNGKey(0), N)
    with pytest.raises(ValueError, match="ghost"):
        streamed(st, x, y, lengths, mal, jax.random.PRNGKey(1))
