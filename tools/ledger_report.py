"""Query CLI over client-ledger checkpoints: fleet summary, top-N
suspects, per-client longitudinal records and timelines.

The ledger (:mod:`blades_tpu.obs.ledger`) persists ONE record per
registered client; this tool is the offline read path over a saved
``ledger/`` shard directory (``<ckpt>/ledger`` under a trial, or
whatever ``--ledger-dir`` pointed the disk backend at).  Three views:

- default: the fleet summary plus the top-N suspect table (lifetime
  flag rate, score EWMA, staleness/norm running stats);
- ``--client ID``: that client's full record; add ``--metrics
  <trial>/metrics.jsonl`` to join the per-round forensics lanes into a
  round-by-round timeline (round, flagged, score, update norm) — the
  lanes are cohort-shaped, so the join matches ``ID`` against each
  row's ``lane_forensics["clients"]`` id-vector;
- ``--json``: machine-readable export of whichever view was selected.

Usage::

    python -m tools.ledger_report <ckpt>/ledger
    python -m tools.ledger_report <ckpt>/ledger --top 20
    python -m tools.ledger_report <ckpt>/ledger --client 17 \\
        --metrics <trial>/metrics.jsonl
    python -m tools.ledger_report <ckpt>/ledger --json > fleet.json
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def client_timeline(metrics_path, client_id: int):
    """Scan a metrics.jsonl stream for rounds whose forensics lanes
    cover ``client_id``: the lanes are cohort-shaped (lane ``i``
    diagnoses ``lane_forensics["clients"][i]``), so membership — not
    position — decides whether the client appears in a round.  Torn or
    unparseable lines are skipped (the schema validator's findings)."""
    events = []
    with open(metrics_path) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            lanes = rec.get("lane_forensics") if isinstance(rec, dict) \
                else None
            if not isinstance(lanes, dict):
                continue
            clients = lanes.get("clients")
            masks = lanes.get("benign_mask")
            if not isinstance(clients, list) or not isinstance(masks, list):
                continue
            try:
                lane = clients.index(client_id)
            except ValueError:
                continue  # client not in this round's cohort
            ev = {
                "round": rec.get("training_iteration"),
                "flagged": bool(masks[lane] <= 0.5),
            }
            if rec.get("tick") is not None:
                ev["tick"] = rec["tick"]
            scores = lanes.get("scores")
            if isinstance(scores, list) and lane < len(scores):
                ev["score"] = scores[lane]
            norms = lanes.get("update_norms")
            if isinstance(norms, list) and lane < len(norms):
                ev["update_norm"] = norms[lane]
            events.append(ev)
    return events


def _fmt_suspect_row(rec) -> str:
    return (f"  {rec['client']:>8d}  {rec['participation']:>6d}  "
            f"{rec['flagged']:>7d}  {rec['flag_rate']:>9.4f}  "
            f"{rec['score_ewma']:>10.4f}  {rec['stale_mean']:>10.3f}  "
            f"{rec['norm_mean']:>10.4f}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tools.ledger_report",
        description="query a client-ledger checkpoint: fleet summary, "
                    "top-N suspects, per-client records/timelines",
    )
    p.add_argument("ledger_dir",
                   help="ledger checkpoint directory (holds manifest.json "
                        "+ shard files)")
    p.add_argument("--top", type=int, default=10, metavar="K",
                   help="suspects to list in the fleet view (default 10)")
    p.add_argument("--client", type=int, default=None, metavar="ID",
                   help="print one client's longitudinal record instead "
                        "of the fleet view")
    p.add_argument("--metrics", default=None, metavar="JSONL",
                   help="with --client: join this metrics.jsonl stream "
                        "into a round-by-round timeline")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the selected view as JSON on stdout")
    args = p.parse_args(argv)

    from blades_tpu.obs.ledger import LedgerError, read_ledger

    try:
        ledger = read_ledger(args.ledger_dir)
    except LedgerError as exc:
        print(f"{args.ledger_dir}: {exc}", file=sys.stderr)
        return 1

    try:
        if args.client is not None:
            try:
                record = ledger.client_record(args.client)
            except LedgerError as exc:
                print(f"{args.ledger_dir}: {exc}", file=sys.stderr)
                return 1
            out = {"ledger": str(args.ledger_dir), "record": record}
            if args.metrics:
                out["timeline"] = client_timeline(args.metrics, args.client)
            if args.as_json:
                print(json.dumps(out, indent=2, sort_keys=True))
                return 0
            print(f"client {record['client']} "
                  f"({args.ledger_dir}):")
            for key in ("participation", "flagged", "flag_rate",
                        "last_flagged", "score_ewma", "last_round",
                        "last_tick", "stale_count", "stale_mean",
                        "stale_var", "norm_count", "norm_mean",
                        "norm_var"):
                print(f"  {key:>13s}: {record[key]}")
            if args.metrics:
                tl = out["timeline"]
                print(f"timeline ({len(tl)} diagnosed round(s) in "
                      f"{args.metrics}):")
                for ev in tl:
                    bits = [f"round {ev['round']}"]
                    if "tick" in ev:
                        bits.append(f"tick {ev['tick']}")
                    bits.append("FLAGGED" if ev["flagged"] else "benign")
                    if "score" in ev:
                        bits.append(f"score {ev['score']:.4f}")
                    if "update_norm" in ev:
                        bits.append(f"norm {ev['update_norm']:.4f}")
                    print("  " + "  ".join(bits))
            return 0

        summary = ledger.summary()
        suspects = ledger.top_suspects(args.top)
        if args.as_json:
            print(json.dumps(
                {"ledger": str(args.ledger_dir), "summary": summary,
                 "top_suspects": suspects},
                indent=2, sort_keys=True))
            return 0
        print(f"{args.ledger_dir}: {summary['n_registered']} registered, "
              f"{summary['clients_seen']} seen, "
              f"{summary['total_flagged']} lifetime flag(s)")
        print(f"  suspected_fraction: {summary['suspected_fraction']:.4f}  "
              f"reputation p10/p50/p90: {summary['reputation_p10']:.4f}/"
              f"{summary['reputation_p50']:.4f}/"
              f"{summary['reputation_p90']:.4f}")
        if suspects:
            print(f"top {len(suspects)} suspect(s):")
            print("    client   part.  flagged  flag_rate  score_ewma  "
                  "stale_mean   norm_mean")
            for rec in suspects:
                print(_fmt_suspect_row(rec))
        else:
            print("no clients flagged yet")
        return 0
    finally:
        ledger.close()


if __name__ == "__main__":
    sys.exit(main())
