"""Replay a flight-recorder dump's round, bit-identically.

Every execution path is deterministic in ``(config, seed)``: the
training stream is the split chain of ``PRNGKey(seed)`` and the fault
stream is pure in ``(fault_seed, round)``.  That includes decentralized
gossip rounds (``execution="gossip"``): the peer graph rebuilds from
``topology_config`` (``graph_seed`` pins the random families), the
edge-dropout realization is pure in ``(fault_seed, round)``, and the
per-node replica stack replays through the same round keys — so
``gossip_ici_bytes`` / ``num_partitioned_nodes`` / ``consensus_dist``
compare bit-for-bit like every other digest field.  A flight-recorder dump
(:mod:`blades_tpu.obs.flightrec`) therefore carries everything needed
to re-execute the failing round in isolation — no model state rides
the dump.  This CLI rebuilds the trial config from the dump, re-runs
the trajectory to the recorded tick, and compares the replayed round's
digest against the recorded one BIT-for-bit (NaN matches NaN; exact
float equality everywhere else — the replay either reproduces the
divergence exactly or the determinism contract is broken, which is
itself the finding).

Usage::

    python -m tools.replay_round <flightrec.json> [--tick N] [--quiet]

``--tick`` defaults to the dump's trigger round (falling back to the
newest recorded round).  Exit code 0 = every compared field matched
bit-identically; 1 = mismatch or unusable dump.
"""

from __future__ import annotations

import argparse
import json
import math
import struct
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def _bits(x: float) -> int:
    return struct.unpack("<Q", struct.pack("<d", float(x)))[0]


def bit_equal(a, b) -> bool:
    """Bit-identical float comparison: NaN == NaN (a NaN-corrupted round
    must replay as the same NaN), otherwise exact representation
    equality."""
    fa, fb = float(a), float(b)
    if math.isnan(fa) and math.isnan(fb):
        return True
    return _bits(fa) == _bits(fb)


def replay(dump: dict, tick=None):
    """Re-run the dump's trajectory to ``tick``; returns
    ``(replayed row, recorded digest)``.  Raises ``ValueError`` when the
    dump records nothing usable.

    Async rows (blades_tpu/arrivals) are TICK-indexed on top of
    round-indexed: ``tick`` first matches a recorded row's
    ``training_iteration`` (every execution path), then — async rows
    only — a row's virtual arrival-clock ``tick`` field; either way the
    replay re-runs server rounds to the matched row's
    ``training_iteration`` (the virtual clock advances deterministically
    alongside, so reaching the round IS reaching the recorded tick)."""
    from blades_tpu.algorithms import get_algorithm_class

    rounds = dump.get("rounds") or []
    by_iter = {r.get("training_iteration"): r for r in rounds
               if isinstance(r, dict)}
    # Virtual-tick index: consecutive cycles CAN share a tick (a cycle
    # fired from leftover buffered events does not advance the clock),
    # so only unambiguous ticks resolve — a duplicated one is an
    # explicit error pointing at the round index, never a silent
    # pick-the-last.
    vtick_rows: dict = {}
    for r in rounds:
        if isinstance(r, dict) and isinstance(r.get("tick"), int):
            vtick_rows.setdefault(r["tick"], []).append(r)
    by_vtick = {t: rs[0] for t, rs in vtick_rows.items() if len(rs) == 1}
    if tick is None:
        trig = dump.get("trigger") or {}
        tick = trig.get("round") or (dump.get("rng") or {}).get("tick")
    recorded = by_iter.get(tick)
    if recorded is None and tick in vtick_rows and tick not in by_vtick:
        raise ValueError(
            f"virtual tick {tick} matches {len(vtick_rows[tick])} "
            "recorded rounds "
            f"{[r.get('training_iteration') for r in vtick_rows[tick]]} "
            "(cycles fired from leftover buffered events share a tick) "
            "— disambiguate with --tick <training_iteration>")
    if recorded is None:
        recorded = by_vtick.get(tick)
    if recorded is None:
        window = sorted(by_iter)
        vwindow = sorted(by_vtick)
        raise ValueError(
            f"tick {tick!r} is not in the dump's recorded window "
            f"(rounds {window}"
            + (f", arrival ticks {vwindow}" if vwindow else "")
            + f") — the ring only holds the last "
            f"{dump.get('capacity')} rounds")
    target = recorded["training_iteration"]

    _, config = get_algorithm_class(dump["algo"], return_config=True)
    config.update_from_dict(json.loads(json.dumps(dump.get("config", {}))))
    algo = config.build()
    row = None
    while algo.iteration < target:
        row = algo.train()
    if row is None or row.get("training_iteration") != target:
        raise ValueError(
            f"replay stopped at iteration {algo.iteration} "
            f"(rounds_per_dispatch overshoots round {target}?)")
    return row, recorded


def compare(row: dict, recorded: dict):
    """(matches, mismatches, skipped) over the replay-comparable digest
    fields present in the recording."""
    from blades_tpu.obs.flightrec import REPLAY_FIELDS

    matches, mismatches, skipped = [], [], []
    for field in REPLAY_FIELDS:
        if field not in recorded:
            continue
        want = recorded[field]
        if not isinstance(want, (int, float)) or isinstance(want, bool):
            skipped.append(field)
            continue
        have = row.get(field)
        if not isinstance(have, (int, float)) or isinstance(have, bool):
            mismatches.append((field, want, have))
        elif bit_equal(want, have):
            matches.append(field)
        else:
            mismatches.append((field, want, have))
    return matches, mismatches, skipped


def rederive_actions(dump: dict, quiet: bool = False) -> int:
    """``--action``: re-derive every journaled control action from the
    dump's policy config and each action's recorded decision inputs
    (``pre`` + the row's ledger suspects), and diff against the journal
    entry — byte-for-byte over the serialized dicts.  No training is
    re-run: actions are pure in (policy, pre-state, sensor data, round,
    tick), so a diff here means the control plane's determinism contract
    is broken, independent of the numeric replay.  Returns an exit code
    (0 = every action re-derived identically)."""
    from blades_tpu.control import ControlPolicy, rederive_action

    cfg = dump.get("config") or {}
    control_cfg = cfg.get("control_config")
    if not control_cfg:
        print("dump's config has no control_config — nothing to "
              "re-derive (run was uncontrolled)", file=sys.stderr)
        return 1
    policy = ControlPolicy.from_config(dict(control_cfg))
    # The flight recorder nests the fleet size under dataset_config
    # (it dumps the run's serialized config); accept the flat key too so
    # hand-built forensic dumps keep working.
    num_clients = int(
        cfg.get("num_clients")
        or (cfg.get("dataset_config") or {}).get("num_clients")
        or 0)
    checked = diverged = 0
    for row in dump.get("rounds") or []:
        if not isinstance(row, dict):
            continue
        suspects = row.get("ledger_top_suspects") or ()
        for entry in row.get("control_actions") or []:
            rederived = rederive_action(
                policy, entry, suspects=suspects,
                num_clients=num_clients)
            checked += 1
            want = json.dumps(entry, sort_keys=True)
            have = (None if rederived is None
                    else json.dumps(rederived, sort_keys=True))
            if want != have:
                diverged += 1
                print(f"  round {row.get('training_iteration')} seq "
                      f"{entry.get('seq')} [{entry.get('actuator')}]: "
                      f"recorded {want}\n    != rederived {have}  "
                      "MISMATCH")
            elif not quiet:
                print(f"  round {row.get('training_iteration')} seq "
                      f"{entry.get('seq')} [{entry.get('actuator')}] "
                      f"{entry.get('rule')}: rederived OK")
    if diverged:
        print(f"{diverged}/{checked} control action(s) DIVERGED — the "
              "control plane's determinism contract is broken",
              file=sys.stderr)
        return 1
    if not checked:
        print("no control actions recorded in the dump's window "
              "(controlled run, but every ring round was action-free)")
        return 0
    print(f"all {checked} control action(s) re-derived bit-identically")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tools.replay_round",
        description="re-execute a flight-recorded round from (config, "
                    "seed, tick) and verify the digest bit-identically",
    )
    p.add_argument("dump", help="path to a flightrec.json dump")
    p.add_argument("--tick", type=int, default=None,
                   help="round to replay (default: the trigger round)")
    p.add_argument("--action", action="store_true",
                   help="instead of re-running the round, re-derive "
                   "every journaled control action (blades_tpu/control) "
                   "from the dump's policy config + each action's "
                   "recorded decision inputs and diff against the "
                   "journal — the control plane's half of the replay "
                   "contract; no training happens")
    p.add_argument("--quiet", action="store_true")
    args = p.parse_args(argv)

    from blades_tpu.obs.flightrec import validate_flightrec

    num_rounds, errors = validate_flightrec(args.dump)
    if errors:
        for e in errors:
            print(f"{args.dump}: {e}", file=sys.stderr)
        return 1
    with open(args.dump) as f:
        dump = json.load(f)
    if args.action:
        return rederive_actions(dump, quiet=args.quiet)
    try:
        row, recorded = replay(dump, tick=args.tick)
    except (ValueError, KeyError) as exc:
        print(f"replay failed: {exc}", file=sys.stderr)
        return 1
    matches, mismatches, skipped = compare(row, recorded)
    tick = recorded.get("training_iteration")
    if not args.quiet:
        trig = (dump.get("trigger") or {}).get("kind", "?")
        print(f"{args.dump}: trial {dump.get('trial')!r}, trigger "
              f"{trig!r}, replayed round {tick} "
              f"({num_rounds} recorded round(s) in the ring)")
        for field in matches:
            print(f"  {field}: {recorded[field]!r}  == replay  OK")
        for field, want, have in mismatches:
            print(f"  {field}: recorded {want!r} != replayed {have!r}  "
                  "MISMATCH")
        if skipped:
            print(f"  (skipped non-scalar fields: {skipped})")
    if mismatches:
        print(f"replay DIVERGED on {len(mismatches)} field(s) — the "
              "determinism contract is broken for this config",
              file=sys.stderr)
        return 1
    if not matches:
        print("nothing to compare (recorded digest has no replay "
              "fields)", file=sys.stderr)
        return 1
    print(f"replay of round {tick} is bit-identical "
          f"({len(matches)} field(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
