"""Offline artifact validator: metrics streams, flight-recorder dumps,
span traces.

The PR-1 offline validator (``python -m blades_tpu.obs.schema``) grew
two artifact classes in ISSUE 12; this CLI is the one front door:

- default: ``metrics.jsonl`` streams against the round-record schema
  (delegates to :func:`blades_tpu.obs.schema.validate_jsonl`), plus the
  async-row ordering contract: rows stamped by the buffered-async path
  (blades_tpu/arrivals) are TICK-indexed on top of round-indexed, and
  the virtual arrival clock only moves forward — a ``tick`` that goes
  backwards between consecutive records means interleaved or
  re-ordered streams and is reported as an error — and the pod-scale
  row contract: ``ici_bytes`` / ``preagg_kept`` / ``mesh_shape`` are
  stamped together by the hierarchical driver, so a partial stamp is
  an error — and the decentralized-round contract: ``gossip_ici_bytes``
  travels with the topology provenance and the per-round gossip
  counters (blades_tpu/topology), same partial-stamp rule;
- ``--flightrec``: ``flightrec.json`` dumps
  (:func:`blades_tpu.obs.flightrec.validate_flightrec`);
- ``--trace``: Chrome/Perfetto span-trace exports
  (:func:`blades_tpu.obs.trace.validate_chrome_trace`);
- ``--ledger``: client-ledger checkpoint directories
  (:func:`blades_tpu.obs.ledger.validate_ledger_checkpoint`) —
  manifest CRCs against the shard files, layout drift, torn shards.

Torn-write tolerance matches the metrics.jsonl contract everywhere: a
torn final JSONL line (a killed writer) or an unreadable JSON artifact
is a REPORTED error with a nonzero exit code, never an exception —
and an orphaned ``*.tmp`` sibling (an atomic write a SIGKILL
interrupted) is flagged as exactly that, since the published file next
to it is still the newest complete artifact.

Usage::

    python -m tools.validate_metrics <trial>/metrics.jsonl ...
    python -m tools.validate_metrics --flightrec <trial>/flightrec.json
    python -m tools.validate_metrics --trace traces/*.trace.json
    python -m tools.validate_metrics --ledger <ckpt>/ledger
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def _async_tick_errors(path):
    """Tick-monotonicity over a metrics.jsonl stream: the virtual
    arrival clock (async rows' ``tick``) must be non-decreasing in file
    order.  Rows without a ``tick`` (synchronous trials) are ignored;
    unparseable lines are the schema validator's findings, not ours."""
    import json

    errors = []
    last = None
    last_line = None
    with open(path) as f:
        for lineno, line in enumerate(f, start=1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            tick = rec.get("tick") if isinstance(rec, dict) else None
            if not isinstance(tick, int) or isinstance(tick, bool):
                continue
            if last is not None and tick < last:
                errors.append((lineno,
                               f"async tick went backwards: {tick} after "
                               f"{last} (line {last_line}) — the virtual "
                               "arrival clock only moves forward"))
            last, last_line = tick, lineno
    return errors


def _mesh_row_errors(path):
    """Pod-scale row consistency over a metrics.jsonl stream: the three
    hierarchical-round stamps travel together (a row with ``ici_bytes``
    must carry ``preagg_kept`` and a ``"CxD"``-shaped ``mesh_shape``),
    and both counters are non-negative — a partial stamp means the
    driver and the recorder disagreed about which path ran."""
    import json
    import re

    errors = []
    with open(path) as f:
        for lineno, line in enumerate(f, start=1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict) or "ici_bytes" not in rec:
                continue
            missing = [k for k in ("preagg_kept", "mesh_shape")
                       if k not in rec]
            if missing:
                errors.append((lineno,
                               f"hierarchical row missing {missing}: "
                               "ici_bytes/preagg_kept/mesh_shape are "
                               "stamped together by the hier driver"))
                continue
            if rec["ici_bytes"] < 0 or rec["preagg_kept"] < 1:
                errors.append((lineno,
                               f"hierarchical counters out of range: "
                               f"ici_bytes={rec['ici_bytes']}, "
                               f"preagg_kept={rec['preagg_kept']}"))
            if not re.fullmatch(r"\d+x\d+", str(rec["mesh_shape"])):
                errors.append((lineno,
                               f"mesh_shape must be 'CxD', got "
                               f"{rec['mesh_shape']!r}"))
    return errors


def _gossip_row_errors(path):
    """Decentralized-round row consistency over a metrics.jsonl stream:
    the six gossip stamps travel together (a row with
    ``gossip_ici_bytes`` must carry the topology provenance and both
    per-round counters), counters are in range, and the graph family is
    one the topology subsystem builds — a partial stamp means the driver
    and the gossip recorder disagreed about which path ran."""
    import json

    errors = []
    with open(path) as f:
        for lineno, line in enumerate(f, start=1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict) or "gossip_ici_bytes" not in rec:
                continue
            missing = [k for k in ("topology", "graph_seed", "spectral_gap",
                                   "num_partitioned_nodes", "consensus_dist")
                       if k not in rec]
            if missing:
                errors.append((lineno,
                               f"gossip row missing {missing}: the six "
                               "gossip stamps are stamped together by the "
                               "gossip driver"))
                continue
            if (rec["gossip_ici_bytes"] < 0
                    or rec["num_partitioned_nodes"] < 0):
                errors.append((lineno,
                               "gossip counters out of range: "
                               f"gossip_ici_bytes={rec['gossip_ici_bytes']}, "
                               "num_partitioned_nodes="
                               f"{rec['num_partitioned_nodes']}"))
            if not 0.0 <= float(rec["spectral_gap"]) <= 1.0:
                errors.append((lineno,
                               "spectral_gap must be in [0, 1], got "
                               f"{rec['spectral_gap']!r}"))
            # graph.py is host-side numpy — no jax import for a validator
            from blades_tpu.topology.graph import GRAPHS

            if rec["topology"] not in GRAPHS:
                errors.append((lineno,
                               f"unknown topology {rec['topology']!r}; "
                               f"the subsystem builds {GRAPHS}"))
    return errors


def _report(path, num_ok: int, what: str, errors) -> int:
    print(f"{path}: {num_ok} valid {what}, {len(errors)} error(s)")
    for err in errors:
        if isinstance(err, tuple):
            lineno, msg = err
            print(f"  line {lineno}: {msg}")
        else:
            print(f"  {err}")
    p = Path(path)
    if p.is_dir():
        # Directory artifacts (ledger checkpoints): any *.tmp inside is
        # an interrupted shard/manifest write the atomic-rename protocol
        # never published — the named files are still complete.
        orphans = sorted(t.name for t in p.glob("*.tmp"))
        if orphans:
            print(f"  note: orphaned {', '.join(orphans)} inside (atomic "
                  "writes were interrupted; the published files are the "
                  "newest complete artifacts)")
    else:
        tmp = Path(str(path) + ".tmp")
        if tmp.exists():
            print(f"  note: orphaned {tmp.name} alongside (an atomic write "
                  "was interrupted; the published file is the newest "
                  "complete artifact)")
    return 1 if errors else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tools.validate_metrics",
        description="schema-check observability artifacts: metrics.jsonl "
                    "(default), flight-recorder dumps (--flightrec), "
                    "span traces (--trace), ledger checkpoints "
                    "(--ledger), data-store shard dirs (--datastore)",
    )
    mode = p.add_mutually_exclusive_group()
    mode.add_argument("--flightrec", action="store_true",
                      help="validate flightrec.json dump(s)")
    mode.add_argument("--trace", action="store_true",
                      help="validate Chrome/Perfetto trace export(s)")
    mode.add_argument("--ledger", action="store_true",
                      help="validate client-ledger checkpoint "
                           "director(ies)")
    mode.add_argument("--datastore", action="store_true",
                      help="validate out-of-core data-store shard "
                           "director(ies): manifest walk + per-shard "
                           "size/dtype/CRC checks")
    p.add_argument("paths", nargs="+")
    args = p.parse_args(argv)

    rc = 0
    for path in args.paths:
        if not Path(path).exists():
            print(f"{path}: no such file")
            rc = 1
            continue
        if args.flightrec:
            from blades_tpu.obs.flightrec import validate_flightrec

            num, errors = validate_flightrec(path)
            rc |= _report(path, num, "recorded round(s)", errors)
        elif args.trace:
            from blades_tpu.obs.trace import validate_chrome_trace

            num, errors = validate_chrome_trace(path)
            rc |= _report(path, num, "span event(s)", errors)
        elif args.ledger:
            from blades_tpu.obs.ledger import validate_ledger_checkpoint

            num, errors = validate_ledger_checkpoint(path)
            rc |= _report(path, num, "shard file(s)", errors)
        elif args.datastore:
            from blades_tpu.data.store import validate_datastore_dir

            num, errors = validate_datastore_dir(path)
            rc |= _report(path, num, "shard file(s)", errors)
        else:
            from blades_tpu.obs.schema import validate_jsonl

            num, errors = validate_jsonl(path)
            errors = (list(errors) + _async_tick_errors(path)
                      + _mesh_row_errors(path) + _gossip_row_errors(path))
            rc |= _report(path, num, "record(s)", errors)
    return rc


if __name__ == "__main__":
    sys.exit(main())
