#!/usr/bin/env python
"""Gossip-vs-centralized convergence curves (ISSUE 19 acceptance).

Runs the SAME tiny synthetic federation through the centralized dense
path and the decentralized gossip path (``execution="gossip"``) on each
requested peer graph, evaluating every round, and writes the curves to
``artifacts/gossip_convergence/curves.json`` in the accuracy-curves
table format — each row additionally carries ``topology``,
``spectral_gap`` and the per-round ``test_acc_curve``/``loss_curve`` so
the consensus penalty of a sparse graph is visible round by round, not
just at the final accuracy.

The artifact is a *gossip* study, not a reference-grid reproduction, so
its completeness stamps are recomputed by ``tools/restamp_curves.py``
(run automatically after writing): ``complete: false`` with the honest
``reference_cells_missing`` list is the expected steady state, and the
``artifact-stamps`` lint pass keeps it that way.

Usage::

    JAX_PLATFORMS=cpu python tools/gossip_curves.py
    python tools/gossip_curves.py --rounds 40 --graphs ring,complete
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

DEFAULT_OUT = REPO / "artifacts" / "gossip_convergence" / "curves.json"
NUM_CLIENTS = 16
NUM_MALICIOUS = 4
N_DEVICES = 8


def _provision_devices(n: int) -> None:
    import os

    os.environ.setdefault("XLA_FLAGS", "")
    if f"--xla_force_host_platform_device_count={n}" not in os.environ["XLA_FLAGS"]:
        os.environ["XLA_FLAGS"] += f" --xla_force_host_platform_device_count={n}"


def _dataset(n_clients: int, seed: int):
    import numpy as np

    from blades_tpu.data.datasets import FLDataset
    from blades_tpu.data.partition import partition_dataset

    shape, num_classes, rows = (6, 6, 1), 4, 16
    rng = np.random.default_rng(seed)
    n = n_clients * rows
    mus = rng.normal(size=(num_classes,) + shape).astype(np.float32)
    y = rng.integers(0, num_classes, size=n).astype(np.int32)
    x = (mus[y] + 0.8 * rng.normal(size=(n,) + shape)).astype(np.float32)
    train = partition_dataset(x, y, n_clients, iid=True, seed=seed)
    test = partition_dataset(x[: 4 * n_clients], y[: 4 * n_clients],
                             n_clients, iid=True, seed=seed + 1)
    return FLDataset(name="synthcluster", train=train, test_x=x[:128],
                     test_y=y[:128], test=test, num_classes=num_classes,
                     input_shape=shape)


def _config(graph, *, aggregator, adversary, num_malicious, seed):
    from blades_tpu.algorithms import FedavgConfig
    from blades_tpu.models.mlp import MLP

    cfg = (
        FedavgConfig()
        .data(dataset=_dataset(NUM_CLIENTS, seed), num_clients=NUM_CLIENTS,
              seed=seed)
        .training(global_model=MLP(hidden1=16, hidden2=16, num_classes=4),
                  num_classes=4, input_shape=(6, 6, 1), server_lr=1.0,
                  train_batch_size=8, aggregator={"type": aggregator})
        .client(lr=0.05)
        .evaluation(evaluation_interval=1)
    )
    if graph is not None:
        cfg.resources(num_devices=N_DEVICES, execution="gossip")
        cfg.topology(graph=graph, k=4)
    if num_malicious:
        cfg.adversary(num_malicious_clients=num_malicious,
                      adversary_config=adversary)
    return cfg


def _run_arm(graph, *, aggregator, adversary, num_malicious, rounds, seed):
    """One (path, aggregator, adversary) trajectory -> a curves row."""
    label = "centralized" if graph is None else f"gossip_{graph}"
    adv_name = adversary["type"] if isinstance(adversary, dict) else adversary
    algo = _config(graph, aggregator=aggregator, adversary=adversary,
                   num_malicious=num_malicious, seed=seed).build()
    accs, losses = [], []
    t0 = time.perf_counter()
    try:
        for _ in range(rounds):
            row = algo.train()
            losses.append(round(float(row["train_loss"]), 5))
            accs.append(round(float(row["test_acc"]), 4))
        wall = time.perf_counter() - t0
        out = {
            "dataset": "synthcluster",
            "model": "mlp",
            "aggregator": aggregator,
            "adversary": adv_name if num_malicious else None,
            "num_malicious": num_malicious,
            "rounds": rounds,
            "topology": None if graph is None else graph,
            "path": label,
            "final_test_acc": accs[-1],
            "best_test_acc": max(accs),
            "synthetic_data": True,
            "wall_s": round(wall, 1),
            "test_acc_curve": accs,
            "loss_curve": losses,
        }
        if graph is not None:
            out["spectral_gap"] = round(float(row["spectral_gap"]), 4)
            out["gossip_ici_bytes"] = int(row["gossip_ici_bytes"])
            out["consensus_dist"] = round(float(row["consensus_dist"]), 5)
        return out
    finally:
        algo.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--rounds", type=int, default=25)
    p.add_argument("--graphs", default="ring,kregular,complete",
                   help="comma-separated gossip graphs (centralized "
                        "baseline always runs)")
    p.add_argument("--aggregator", default="Median")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", type=Path, default=DEFAULT_OUT)
    args = p.parse_args(argv)
    _provision_devices(N_DEVICES)

    adversary = {"type": "TopologyAttack", "base": "ALIE"}
    arms = [None] + [g for g in args.graphs.split(",") if g]
    rows = []
    for graph in arms:
        # Gossip arms carry the topology-scoped attack; the centralized
        # baseline uses the same forged content via its wrapped base
        # (TopologyAttack itself is gossip-only by the validate() gate).
        adv = adversary if graph is not None else {"type": "ALIE"}
        for nm in (0, NUM_MALICIOUS):
            row = _run_arm(graph, aggregator=args.aggregator,
                           adversary=adv, num_malicious=nm,
                           rounds=args.rounds, seed=args.seed)
            rows.append(row)
            print(f"{row['path']:18s} f={nm}: final={row['final_test_acc']:.3f}"
                  f" best={row['best_test_acc']:.3f} wall={row['wall_s']}s")

    table = {
        "source": "SYNTHETIC gossip-vs-centralized study (tools/"
                  "gossip_curves.py; smoke shape, not a reproduction)",
        "dataset": "synthcluster",
        "model": "mlp",
        "adversary": "TopologyAttack[ALIE]",
        "rounds": args.rounds,
        "num_clients": NUM_CLIENTS,
        "client_lr": 0.05,
        "server_lr": 1.0,
        "batch_size": 8,
        "compute_dtype": None,
        "complete": False,
        "rows": rows,
    }
    args.out.parent.mkdir(parents=True, exist_ok=True)
    args.out.write_text(json.dumps(table, indent=2) + "\n")
    print(f"wrote {args.out} ({len(rows)} rows)")

    from tools.restamp_curves import main as restamp_main

    return restamp_main([str(args.out)])


if __name__ == "__main__":
    sys.exit(main())
