"""Offline report over a controlled run's action journal: the action
timeline, per-actuator/per-rule tallies, and per-client quarantine
lifecycle histories.

The control plane (:mod:`blades_tpu.control`) journals every runtime
action into the metrics rows as ``control_actions`` — and the flight
recorder's digests retain them — so this tool reads EITHER artifact:

- ``<trial>/metrics.jsonl``: the full run's journal, one row per round;
- ``<trial>/flightrec.json``: the last-K-rounds ring (crash forensics —
  what was the controller doing when the run died?).

Three views:

- default: the chronological action timeline (round, tick, rule,
  actuator, old -> new / clients) plus per-actuator and per-rule
  tallies;
- ``--client ID``: that client's quarantine lifecycle — every
  quarantine / probe / readmit / requarantine interval it appears in;
- ``--json``: machine-readable export of whichever view was selected.

Verification is ``replay_round.py --action``'s job (re-derive each
action from its recorded inputs); this tool only reads and arranges.

Usage::

    python -m tools.control_report <trial>/metrics.jsonl
    python -m tools.control_report <trial>/flightrec.json --client 4
    python -m tools.control_report <trial>/metrics.jsonl --json
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def load_rows(path: str):
    """Rows carrying journal entries, from either artifact.  A
    ``.jsonl`` suffix selects the metrics-stream reader (torn lines
    skipped, the validator's findings); anything else is parsed as a
    flight-recorder dump and its ``rounds`` ring is returned."""
    if str(path).endswith(".jsonl"):
        rows = []
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    rows.append(rec)
        return rows
    with open(path) as f:
        dump = json.load(f)
    if not isinstance(dump, dict) or "rounds" not in dump:
        raise ValueError(f"{path} is neither a metrics.jsonl stream nor "
                         "a flight-recorder dump (no 'rounds' key)")
    return [r for r in dump["rounds"] if isinstance(r, dict)]


def collect_actions(rows):
    """Flatten the per-row journals into one seq-ordered action list."""
    actions = []
    for row in rows:
        for entry in row.get("control_actions") or []:
            if isinstance(entry, dict):
                actions.append(entry)
    actions.sort(key=lambda a: a.get("seq", 0))
    return actions


def client_history(actions, client_id: int):
    """The quarantine-lifecycle events naming ``client_id``."""
    return [a for a in actions
            if client_id in (a.get("clients") or ())]


def tallies(actions):
    by_actuator: dict = {}
    by_rule: dict = {}
    for a in actions:
        by_actuator[a.get("actuator")] = \
            by_actuator.get(a.get("actuator"), 0) + 1
        by_rule[a.get("rule")] = by_rule.get(a.get("rule"), 0) + 1
    return by_actuator, by_rule


def _fmt_action(a) -> str:
    bits = [f"round {a.get('round'):>4}", f"tick {a.get('tick'):>5}",
            f"seq {a.get('seq'):>3}",
            f"{a.get('rule')} -> {a.get('actuator')}"]
    if a.get("old") is not None or a.get("new") is not None:
        bits.append(f"{a.get('old')} -> {a.get('new')}")
    if a.get("clients"):
        bits.append(f"clients {list(a['clients'])}")
    if a.get("until", -1) >= 0:
        bits.append(f"until round {a['until']}")
    return "  ".join(str(b) for b in bits)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tools.control_report",
        description="report over a controlled run's action journal: "
                    "timeline, tallies, per-client quarantine history",
    )
    p.add_argument("path",
                   help="<trial>/metrics.jsonl or <trial>/flightrec.json")
    p.add_argument("--client", type=int, default=None, metavar="ID",
                   help="print one client's quarantine-lifecycle events "
                        "instead of the full timeline")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the selected view as JSON on stdout")
    args = p.parse_args(argv)

    try:
        rows = load_rows(args.path)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"{args.path}: {exc}", file=sys.stderr)
        return 1
    actions = collect_actions(rows)
    controlled = any("control_actions" in r for r in rows)
    if not controlled:
        print(f"{args.path}: no control journal in any row — was the "
              "run controlled? (.control() / control_config)",
              file=sys.stderr)
        return 1

    if args.client is not None:
        history = client_history(actions, args.client)
        if args.as_json:
            print(json.dumps({"path": args.path, "client": args.client,
                              "history": history},
                             indent=2, sort_keys=True))
            return 0
        print(f"client {args.client} ({args.path}): "
              f"{len(history)} lifecycle event(s)")
        for a in history:
            print("  " + _fmt_action(a))
        return 0

    by_actuator, by_rule = tallies(actions)
    events_total = sum(len(r.get("watchdog_events") or [])
                      for r in rows)
    last = rows[-1] if rows else {}
    summary = {
        "rows": len(rows),
        "actions": len(actions),
        "watchdog_events": events_total,
        "by_actuator": by_actuator,
        "by_rule": by_rule,
        "final_quarantine_size": last.get("quarantine_size"),
        "final_actions_total": last.get("control_actions_total"),
    }
    if args.as_json:
        print(json.dumps({"path": args.path, "summary": summary,
                          "timeline": actions},
                         indent=2, sort_keys=True))
        return 0
    print(f"{args.path}: {len(rows)} row(s), {len(actions)} action(s), "
          f"{events_total} watchdog event(s)")
    if last.get("control_actions_total") is not None:
        print(f"  final journal length {last['control_actions_total']}, "
              f"final quarantine size {last.get('quarantine_size')}")
    if by_actuator:
        print("  by actuator: " + ", ".join(
            f"{k}={v}" for k, v in sorted(by_actuator.items())))
        print("  by rule:     " + ", ".join(
            f"{k}={v}" for k, v in sorted(by_rule.items())))
    print(f"timeline ({len(actions)} action(s)):")
    for a in actions:
        print("  " + _fmt_action(a))
    return 0


if __name__ == "__main__":
    sys.exit(main())
