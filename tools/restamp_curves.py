#!/usr/bin/env python
"""Re-stamp accuracy-curve artifacts under reference-grid semantics.

``complete: true`` in a ``curves.json`` means the REFERENCE grid ran —
all nine reference aggregators at {0,10,20,30}% malicious for the
artifact's client count (``blades_tpu/benchmarks/accuracy_curves.py``'s
``write_table`` has stamped this since round 4; VERDICT r4 weak #6) —
not merely "the rows the invocation planned".  Artifacts committed
before that change still carry planned-rows-era ``complete: true``
stamps (VERDICT r5 weak #2 named ``cifar10_ipm100``/``mnist_ipm100``).

This tool recomputes the completeness block — ``complete``,
``reference_grid``, ``reference_cells_missing``, and
``planned_complete`` where a plan is recorded — from the artifact's own
rows, REWRITING only those stamps (rows and run-config keys are
untouched).  The ``artifact-stamps`` lint pass
(``python -m tools.lint``) refuses stale stamps; this is its fixer.

Usage::

    python tools/restamp_curves.py artifacts/accuracy_curves/*/curves.json
    python tools/restamp_curves.py --all          # every curves.json under artifacts/
    python tools/restamp_curves.py --check <...>  # report, do not rewrite
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from tools.lint.passes.artifacts import recompute_stamps, reference_grid  # noqa: E402


def restamp(path: Path, aggregators, fracs, check: bool) -> bool:
    """Returns True when the artifact was (or would be) changed."""
    data = json.loads(path.read_text())
    if not isinstance(data, dict) or "rows" not in data:
        print(f"{path}: not a curves table, skipped")
        return False
    want = recompute_stamps(data, aggregators, fracs)
    changed = any(data.get(k) != v for k, v in want.items())
    old = data.get("complete")
    if not changed:
        print(f"{path}: stamps already current (complete={old})")
        return False
    missing = want["reference_cells_missing"]
    print(f"{path}: complete {old} -> {want['complete']} "
          f"({len(missing)} reference cell(s) missing"
          + (f", e.g. {missing[0]}" if missing else "") + ")")
    if check:
        return True
    data.update(want)
    path.write_text(json.dumps(data, indent=2) + "\n")
    return True


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="*", type=Path)
    p.add_argument("--all", action="store_true",
                   help="restamp every curves.json under artifacts/")
    p.add_argument("--check", action="store_true",
                   help="report stale stamps without rewriting (exit 1 "
                        "when any are stale)")
    args = p.parse_args(argv)
    grid = reference_grid(REPO)
    if grid is None:
        print("cannot read the reference grid from "
              "blades_tpu/benchmarks/accuracy_curves.py", file=sys.stderr)
        return 2
    paths = list(args.paths)
    if args.all:
        paths.extend(sorted((REPO / "artifacts").rglob("curves.json")))
    if not paths:
        p.error("pass artifact paths or --all")
    changed = sum(restamp(path, *grid, check=args.check) for path in paths)
    return 1 if (args.check and changed) else 0


if __name__ == "__main__":
    sys.exit(main())
