"""Small shared AST helpers for the blades-lint passes."""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` as a string for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    """The dotted callee of a Call, else None (lambdas, subscripts...)."""
    return dotted(call.func)


def walk_calls(node: ast.AST) -> Iterator[ast.Call]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def literal_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """``(0, 1)`` / ``0`` / ``()`` as a tuple of ints, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, int)
                    and not isinstance(el.value, bool)):
                return None
            out.append(el.value)
        return tuple(out)
    return None


def assign_target_paths(stmt: ast.stmt) -> List[str]:
    """Every dotted path a statement (re)binds: plain/tuple/starred
    assignment targets, aug/ann-assign, for-targets, with-as, del."""
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        targets = [i.optional_vars for i in stmt.items
                   if i.optional_vars is not None]
    elif isinstance(stmt, ast.Delete):
        targets = list(stmt.targets)
    out: List[str] = []

    def flatten(t: ast.AST) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                flatten(el)
        elif isinstance(t, ast.Starred):
            flatten(t.value)
        else:
            d = dotted(t)
            if d is not None:
                out.append(d)

    for t in targets:
        flatten(t)
    # Walrus targets anywhere in the statement rebind too.
    for sub in ast.walk(stmt):
        if isinstance(sub, ast.NamedExpr):
            d = dotted(sub.target)
            if d is not None:
                out.append(d)
    return out


_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                ast.Lambda)


def scope_nodes(scope: ast.AST, prune=_SCOPE_NODES) -> List[ast.AST]:
    """Descendants of ``scope`` that are not inside a nested scope of a
    pruned kind (``ast.walk`` cannot prune, so passes that must not
    attribute a nested def's contents to its parent use this)."""
    out: List[ast.AST] = []
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        if isinstance(node, prune):
            continue
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def function_defs(tree: ast.AST) -> List[ast.FunctionDef]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def decorator_names(fn: ast.AST) -> List[str]:
    """Dotted names of decorators, looking through Call decorators into
    both the callee and its arguments (``@partial(jax.jit, ...)`` yields
    ``partial`` and ``jax.jit``)."""
    names: List[str] = []
    for d in getattr(fn, "decorator_list", []):
        n = dotted(d)
        if n:
            names.append(n)
        if isinstance(d, ast.Call):
            n = dotted(d.func)
            if n:
                names.append(n)
            for a in d.args:
                n = dotted(a)
                if n:
                    names.append(n)
    return names
