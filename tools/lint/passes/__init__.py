"""blades-lint pass registry.

Adding a pass: subclass :class:`tools.lint.core.LintPass` in a module
here, set ``name`` (the pragma token) and ``doc``, implement ``run``,
and append an instance to :data:`ALL_PASSES`.  Fixture coverage in
``tests/test_lint.py`` (a known-bad + known-good pair under
``tests/lint_fixtures/``) is part of the definition of done.
"""

from tools.lint.passes.artifacts import ArtifactStampsPass
from tools.lint.passes.donation import DonationPass
from tools.lint.passes.host_sync import HostSyncPass
from tools.lint.passes.pass_discipline import PassDisciplinePass
from tools.lint.passes.prng import PrngPass
from tools.lint.passes.purity import PurityPass
from tools.lint.passes.schema_drift import SchemaDriftPass
from tools.lint.passes.slow_markers import SlowMarkersPass
from tools.lint.passes.static_args import StaticArgsPass
from tools.lint.passes.topology_discipline import TopologyDisciplinePass
from tools.lint.passes.trace_discipline import TraceDisciplinePass

ALL_PASSES = (
    DonationPass(),
    PrngPass(),
    PurityPass(),
    HostSyncPass(),
    StaticArgsPass(),
    SchemaDriftPass(),
    PassDisciplinePass(),
    TopologyDisciplinePass(),
    TraceDisciplinePass(),
    SlowMarkersPass(),
    ArtifactStampsPass(),
)
