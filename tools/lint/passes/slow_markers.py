"""slow-markers: tier-1 tests that build the 8-device mesh unmarked.

Folded in from ``tools/check_tier1_budget.py`` so all static analysis
runs through one framework (that tool now delegates here and keeps only
the wall-time budget guard, which needs a pytest log, not an AST).

Mesh compiles are the single most expensive test class on the tier-1
box; a test (or a fixture it requests) calling ``make_mesh`` /
``shard_federation`` without a ``slow`` marker silently eats the 870 s
budget.  Module-level ``pytestmark = pytest.mark.slow`` covers a whole
file.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable, List

from tools.lint.core import Finding, LintContext, LintPass

MESH_CALLS = {"make_mesh", "shard_federation", "hier_step"}


def _has_slow_mark(deco_list) -> bool:
    for d in deco_list:
        for node in ast.walk(d):
            if isinstance(node, ast.Attribute) and node.attr == "slow":
                return True
    return False


def _is_fixture(deco_list) -> bool:
    for d in deco_list:
        for node in ast.walk(d):
            if isinstance(node, ast.Attribute) and node.attr == "fixture":
                return True
            if isinstance(node, ast.Name) and node.id == "fixture":
                return True
    return False


def _module_slow(tree: ast.Module) -> bool:
    """``pytestmark = pytest.mark.slow`` (or a list containing it)."""
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "pytestmark"
            for t in node.targets
        ):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Attribute) and sub.attr == "slow":
                    return True
    return False


def _calls_mesh(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if name in MESH_CALLS:
                return True
    return False


def audit_tree(tree: ast.Module, display_name: str) -> List[Finding]:
    """Unmarked mesh tests in one parsed test module."""
    if _module_slow(tree):
        return []
    functions = [n for n in ast.walk(tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    mesh_fixtures = {fn.name for fn in functions
                     if _is_fixture(fn.decorator_list) and _calls_mesh(fn)}
    findings = []
    for fn in functions:
        if not fn.name.startswith("test"):
            continue
        if _has_slow_mark(fn.decorator_list):
            continue
        args = {a.arg for a in fn.args.args}
        if not (_calls_mesh(fn) or (args & mesh_fixtures)):
            continue
        via = (f"fixture {sorted(args & mesh_fixtures)[0]!r}"
               if args & mesh_fixtures else "direct mesh call")
        findings.append(Finding(
            "slow-markers", display_name, fn.lineno,
            f"{fn.name} builds the 8-device mesh ({via}) without "
            "@pytest.mark.slow",
            fix_hint="mark it slow (or module-level pytestmark) so it "
                     "rides the tier-2 lane, not the 870 s tier-1 budget"))
    return findings


def audit_path(path: Path) -> List[Finding]:
    """Standalone-file entry point (check_tier1_budget delegates here)."""
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except SyntaxError as exc:
        return [Finding("slow-markers", str(path), exc.lineno or 1,
                        f"unparseable ({exc.msg})")]
    return audit_tree(tree, str(path))


class SlowMarkersPass(LintPass):
    name = "slow-markers"
    doc = "tier-1 tests building the 8-device mesh without @pytest.mark.slow"

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for src in ctx.matching(["tests"]):
            if src.tree is None or not src.path.name.startswith("test_"):
                continue
            findings.extend(audit_tree(src.tree, src.rel))
        return findings
