"""streamed-pass-discipline: raw chunk-traversal primitives stay behind
the pass planner.

Every raw statistics primitive in
``blades_tpu/parallel/streamed_geometry.py`` (``row_sq_norms``,
``gram``, ``row_dots``, ...) is a FULL HBM traversal of the ~10 GB
streamed update matrix.  The pass planner (``PassPlanner``) exists so
that statistics live at the same point of an aggregator's dataflow fuse
into ONE traversal; a direct primitive call from outside the planner
module silently re-introduces a dedicated pass per statistic — the exact
regression the ``hbm_passes`` metric was added to catch, enforced here
statically like donation and host-sync.

The same discipline covers the wire domain's decode-to-f32 primitive
(``blades_tpu.comm.codecs.dequantize``): a wire-domain round aggregates
the PACKED int8 payload, and a stray full-matrix ``dequantize()`` call
outside the codec module and the planner module silently reverts its 4x
HBM saving — the regression the ``dequant_rows`` metric counts.  The
one sanctioned non-planner site (the round's forge materialization in
``core/round.py``) carries the pragma with its justification.

Detection is import-based, so same-named helpers in other modules
(``ops/layout.py`` has its own ``row_sq_norms``/``row_dots`` for the
d-sharded shard math) never false-positive: a call is flagged only when
the name was imported from the planner module (or the codec module, for
``dequantize``), or accessed as an attribute of it.  Reference/property
tests that exercise the raw primitives on purpose carry the unified
pragma (``# blades-lint: disable=streamed-pass-discipline — <why>``).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from tools.lint import astutil
from tools.lint.core import Finding, LintContext, LintPass

#: The planner module — the only place raw traversals may be spelled.
PLANNER_MODULE = "blades_tpu/parallel/streamed_geometry.py"
_MODULE_DOTTED = "blades_tpu.parallel.streamed_geometry"
_PARENT_DOTTED = "blades_tpu.parallel"

#: The codec module — home of the wire domain's decode-to-f32 primitive.
#: ``dequantize`` may be spelled there and in the planner module (whose
#: scale algebra IS the sanctioned dequantization); anywhere else a call
#: is a full-matrix f32 materialization that defeats the wire domain.
CODEC_MODULE = "blades_tpu/comm/codecs.py"
_CODEC_DOTTED = "blades_tpu.comm.codecs"
_CODEC_PARENT = "blades_tpu.comm"
RAW_DECODERS = frozenset({"dequantize"})

#: Raw single-statistic traversal primitives (each call = one full HBM
#: pass).  ``aggregate_streamed`` / ``forge_streamed`` /
#: ``aggregate_coordwise`` are sanctioned planner-counted entry points
#: and deliberately absent.
RAW_PRIMITIVES = frozenset({
    "row_sq_norms",
    "gram",
    "row_dots",
    "row_dots2",
    "weighted_row_sum",
    "sign_counts",
    "gather_columns",
    "benign_col_mean_std",
    "masked_scaled_median",
    "_pass",
    "_single",
})

_HINT = ("submit the statistic as a PassPlanner request "
         "(streamed_geometry.PassPlanner) so it fuses with the round's "
         "other traversals, or pragma the line if it is a deliberate "
         "reference-path use")

_DECODE_HINT = ("aggregate the packed payload through "
                "streamed_geometry.aggregate_wire (the planner applies "
                "the wire scales algebraically, per statistic) instead "
                "of materializing the dense f32 matrix, or pragma the "
                "line if the full decode is deliberate and counted")


class PassDisciplinePass(LintPass):
    name = "streamed-pass-discipline"
    doc = ("raw streamed_geometry traversal primitives (and the codec "
           "decode-to-f32 primitive) called outside the pass planner / "
           "codec modules")

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for src in ctx.files:
            if src.rel == PLANNER_MODULE or src.tree is None:
                continue
            in_codec = src.rel == CODEC_MODULE
            fn_aliases, mod_aliases, dec_aliases, codec_mods = \
                self._imports(src.tree)
            if in_codec:
                dec_aliases, codec_mods = {}, set()
            if not (fn_aliases or mod_aliases or dec_aliases or codec_mods):
                continue
            for call in astutil.walk_calls(src.tree):
                cn = astutil.call_name(call)
                if cn is None:
                    continue
                if cn in fn_aliases:
                    findings.append(Finding(
                        self.name, src.rel, call.lineno,
                        f"direct raw-traversal call {cn}() (one full HBM "
                        "pass) outside the pass planner module",
                        fix_hint=_HINT))
                    continue
                if cn in dec_aliases:
                    findings.append(Finding(
                        self.name, src.rel, call.lineno,
                        f"raw decode-to-f32 call {cn}() (full-matrix "
                        "dequantization) outside the codec/planner "
                        "modules", fix_hint=_DECODE_HINT))
                    continue
                head, _, tail = cn.rpartition(".")
                if tail in RAW_PRIMITIVES and head in mod_aliases:
                    findings.append(Finding(
                        self.name, src.rel, call.lineno,
                        f"direct raw-traversal call {cn}() (one full HBM "
                        "pass) outside the pass planner module",
                        fix_hint=_HINT))
                elif tail in RAW_DECODERS and head in codec_mods:
                    findings.append(Finding(
                        self.name, src.rel, call.lineno,
                        f"raw decode-to-f32 call {cn}() (full-matrix "
                        "dequantization) outside the codec/planner "
                        "modules", fix_hint=_DECODE_HINT))
        return findings

    @staticmethod
    def _imports(tree: ast.Module) -> tuple:
        """(primitive aliases, planner-module aliases, decoder aliases,
        codec-module aliases) bound in this file — including
        ``import ... as`` renames and the dotted module paths."""
        fn_aliases: Dict[str, str] = {}
        mod_aliases: Set[str] = set()
        dec_aliases: Dict[str, str] = {}
        codec_mods: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                if node.module == _MODULE_DOTTED:
                    for alias in node.names:
                        if alias.name in RAW_PRIMITIVES:
                            fn_aliases[alias.asname or alias.name] = alias.name
                elif node.module == _PARENT_DOTTED:
                    for alias in node.names:
                        if alias.name == "streamed_geometry":
                            mod_aliases.add(alias.asname or alias.name)
                elif node.module == _CODEC_DOTTED:
                    for alias in node.names:
                        if alias.name in RAW_DECODERS:
                            dec_aliases[alias.asname or alias.name] = alias.name
                elif node.module == _CODEC_PARENT:
                    for alias in node.names:
                        if alias.name == "codecs":
                            codec_mods.add(alias.asname or alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == _MODULE_DOTTED:
                        mod_aliases.add(alias.asname or alias.name)
                    elif alias.name == _CODEC_DOTTED:
                        codec_mods.add(alias.asname or alias.name)
        return fn_aliases, mod_aliases, dec_aliases, codec_mods
