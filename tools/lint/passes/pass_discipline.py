"""streamed-pass-discipline: raw chunk-traversal primitives stay behind
the pass planner.

Every raw statistics primitive in
``blades_tpu/parallel/streamed_geometry.py`` (``row_sq_norms``,
``gram``, ``row_dots``, ...) is a FULL HBM traversal of the ~10 GB
streamed update matrix.  The pass planner (``PassPlanner``) exists so
that statistics live at the same point of an aggregator's dataflow fuse
into ONE traversal; a direct primitive call from outside the planner
module silently re-introduces a dedicated pass per statistic — the exact
regression the ``hbm_passes`` metric was added to catch, enforced here
statically like donation and host-sync.

Detection is import-based, so same-named helpers in other modules
(``ops/layout.py`` has its own ``row_sq_norms``/``row_dots`` for the
d-sharded shard math) never false-positive: a call is flagged only when
the name was imported from the planner module, or accessed as an
attribute of it.  Reference/property tests that exercise the raw
primitives on purpose carry the unified pragma
(``# blades-lint: disable=streamed-pass-discipline — <why>``).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from tools.lint import astutil
from tools.lint.core import Finding, LintContext, LintPass

#: The planner module — the only place raw traversals may be spelled.
PLANNER_MODULE = "blades_tpu/parallel/streamed_geometry.py"
_MODULE_DOTTED = "blades_tpu.parallel.streamed_geometry"
_PARENT_DOTTED = "blades_tpu.parallel"

#: Raw single-statistic traversal primitives (each call = one full HBM
#: pass).  ``aggregate_streamed`` / ``forge_streamed`` /
#: ``aggregate_coordwise`` are sanctioned planner-counted entry points
#: and deliberately absent.
RAW_PRIMITIVES = frozenset({
    "row_sq_norms",
    "gram",
    "row_dots",
    "row_dots2",
    "weighted_row_sum",
    "sign_counts",
    "gather_columns",
    "benign_col_mean_std",
    "masked_scaled_median",
    "_pass",
    "_single",
})

_HINT = ("submit the statistic as a PassPlanner request "
         "(streamed_geometry.PassPlanner) so it fuses with the round's "
         "other traversals, or pragma the line if it is a deliberate "
         "reference-path use")


class PassDisciplinePass(LintPass):
    name = "streamed-pass-discipline"
    doc = ("raw streamed_geometry traversal primitives called outside "
           "the pass planner module")

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for src in ctx.files:
            if src.rel == PLANNER_MODULE or src.tree is None:
                continue
            fn_aliases, mod_aliases = self._imports(src.tree)
            if not fn_aliases and not mod_aliases:
                continue
            for call in astutil.walk_calls(src.tree):
                cn = astutil.call_name(call)
                if cn is None:
                    continue
                if cn in fn_aliases:
                    findings.append(Finding(
                        self.name, src.rel, call.lineno,
                        f"direct raw-traversal call {cn}() (one full HBM "
                        "pass) outside the pass planner module",
                        fix_hint=_HINT))
                    continue
                head, _, tail = cn.rpartition(".")
                if tail in RAW_PRIMITIVES and head in mod_aliases:
                    findings.append(Finding(
                        self.name, src.rel, call.lineno,
                        f"direct raw-traversal call {cn}() (one full HBM "
                        "pass) outside the pass planner module",
                        fix_hint=_HINT))
        return findings

    @staticmethod
    def _imports(tree: ast.Module) -> tuple:
        """(primitive-name aliases, planner-module aliases) bound in this
        file — including ``import ... as`` renames and the dotted module
        path itself."""
        fn_aliases: Dict[str, str] = {}
        mod_aliases: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                if node.module == _MODULE_DOTTED:
                    for alias in node.names:
                        if alias.name in RAW_PRIMITIVES:
                            fn_aliases[alias.asname or alias.name] = alias.name
                elif node.module == _PARENT_DOTTED:
                    for alias in node.names:
                        if alias.name == "streamed_geometry":
                            mod_aliases.add(alias.asname or alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == _MODULE_DOTTED:
                        mod_aliases.add(alias.asname or alias.name)
        return fn_aliases, mod_aliases
