"""prng-reuse: a PRNG key consumed twice, or loop-invariantly.

PR 7's ``keyed_dropout`` refactor made key discipline explicit: every
random draw must consume a FRESH key (``split`` / ``fold_in`` fold), or
two "independent" draws silently correlate — packed dropout masks that
equal each other, DP noise that repeats across rounds.  This pass
flags:

1. **double consumption** — the same key name passed to two
   ``jax.random.<draw>`` / ``*dropout*`` call sites without an
   intervening rebind (``split``/``fold_in`` reassignment or any other
   store).  Exclusive ``if/else`` branches don't cross-report.
2. **loop-invariant keys** — a ``for``/``while`` body that consumes a
   key neither rebound inside the loop nor bound by the loop target:
   every iteration draws the identical stream.

``split`` and ``fold_in`` are *derivers*, not consumers — calling
``split(key)`` twice is the documented step/step_prebatched re-split
contract, not a bug.  Tests are out of scope: re-consuming a key to
assert bit-identity is the POINT of half the regression suite.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.lint import astutil
from tools.lint.core import Finding, LintContext, LintPass

# jax.random.* that derive new keys rather than consuming entropy.
_DERIVERS = {"split", "fold_in", "PRNGKey", "key", "key_data",
             "wrap_key_data", "clone", "key_impl"}


def _consumed_key(call: ast.Call) -> Optional[str]:
    """The dotted key-name this call CONSUMES, if any."""
    cn = astutil.call_name(call)
    if cn is None:
        return None
    parts = cn.split(".")
    is_draw = (len(parts) >= 2 and parts[-2] == "random"
               and parts[-1] not in _DERIVERS)
    is_dropout = "dropout" in parts[-1].lower()
    if not (is_draw or is_dropout):
        return None
    # The key rides arg 0 by convention (jax.random API, keyed_dropout).
    for cand in (call.args[0] if call.args else None,
                 *[kw.value for kw in call.keywords if kw.arg == "key"]):
        if cand is not None:
            path = astutil.dotted(cand)
            if path is None:
                continue
            if is_draw:
                return path
            # Dropout helpers: only a key-ish first argument counts (a
            # `Dropout(rate)` constructor's float is not a key).
            base = path.split(".")[-1]
            if base == "k" or base.startswith(("k_", "key", "rng")) \
                    or "key" in base or "rng" in base:
                return path
    return None


class _Scope:
    def __init__(self, owner: "PrngPass", rel: str):
        self.owner = owner
        self.rel = rel
        self.consumed: Dict[str, int] = {}  # key path -> first consume line
        self.findings: List[Finding] = []
        self._seen: Set[Tuple[int, str]] = set()

    def walk(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._statement(stmt)

    def _statement(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.If):
            self._header_consumes(stmt.test)
            before = dict(self.consumed)
            self.walk(list(stmt.body))
            after_body = dict(self.consumed)
            self.consumed = dict(before)
            self.walk(list(stmt.orelse))
            # Exclusive branches: merge by keeping the EARLIEST record so
            # later statements still see both branches' consumption, but
            # the branches never cross-report against each other.
            for k, v in after_body.items():
                self.consumed[k] = min(v, self.consumed.get(k, v))
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            self._loop(stmt)
            return
        if isinstance(stmt, ast.Try):
            self.walk(list(stmt.body))
            for h in stmt.handlers:
                self.walk(list(h.body))
            self.walk(list(stmt.orelse))
            self.walk(list(stmt.finalbody))
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._header_consumes(*[i.context_expr for i in stmt.items])
            for path in astutil.assign_target_paths(stmt):
                self._rebind(path)
            self.walk(list(stmt.body))
            return
        self._header_consumes(stmt)
        for path in astutil.assign_target_paths(stmt):
            self._rebind(path)

    def _loop(self, stmt) -> None:
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._header_consumes(stmt.iter)
        else:
            self._header_consumes(stmt.test)
        bound: Set[str] = set()
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.stmt):
                bound.update(astutil.assign_target_paths(sub))
        consumed_in_body: List[Tuple[str, int]] = []
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call):
                path = _consumed_key(sub)
                if path is not None:
                    consumed_in_body.append((path, sub.lineno))
        for path, line in consumed_in_body:
            root = path.split(".")[0]
            if path in bound or root in bound:
                continue
            key = (line, path)
            if key in self._seen:
                continue
            self._seen.add(key)
            self.findings.append(Finding(
                self.owner.name, self.rel, line,
                f"loop consumes the loop-invariant key '{path}': every "
                "iteration draws the identical random stream",
                fix_hint="fold the loop index in (key = fold_in(key, i)) "
                         "or split per iteration"))
        # Body consumption also counts toward straight-line double use
        # after the loop, and rebinds inside the body revive.
        self.walk(list(getattr(stmt, "body", [])))
        self.walk(list(getattr(stmt, "orelse", [])))

    def _header_consumes(self, *nodes: ast.AST) -> None:
        for node in nodes:
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                path = _consumed_key(sub)
                if path is None:
                    continue
                first = self.consumed.get(path)
                if first is not None and (sub.lineno, path) not in self._seen:
                    self._seen.add((sub.lineno, path))
                    self.findings.append(Finding(
                        self.owner.name, self.rel, sub.lineno,
                        f"key '{path}' already consumed at line {first} is "
                        "consumed again without an intervening "
                        "split/fold_in: the two draws are identical streams",
                        fix_hint="split the key (k1, k2 = split(key)) or "
                                 "fold a distinct constant in per site"))
                else:
                    self.consumed.setdefault(path, sub.lineno)

    def _rebind(self, path: str) -> None:
        self.consumed.pop(path, None)
        for p in [p for p in self.consumed if p.startswith(path + ".")]:
            self.consumed.pop(p, None)


class PrngPass(LintPass):
    name = "prng-reuse"
    doc = "a key consumed by two draws without split/fold_in in between"

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for src in ctx.files:
            # test_*.py is out of scope: re-consuming a key to assert
            # bit-identity is the POINT of half the regression suite.
            if src.tree is None or src.path.name.startswith("test_"):
                continue
            for fn in astutil.function_defs(src.tree):
                scope = _Scope(self, src.rel)
                scope.walk(list(fn.body))
                findings.extend(scope.findings)
            scope = _Scope(self, src.rel)
            scope.walk(list(src.tree.body))
            findings.extend(scope.findings)
        return findings
