"""artifact-stamps: committed curves.json completeness claims must hold.

``complete: true`` in an accuracy-curve artifact means THE REFERENCE
GRID ran — all nine reference aggregators at {0,10,20,30}% malicious
for the artifact's client count (VERDICT r4 weak #6 semantics) — not
merely "the rows this invocation planned".  VERDICT r5 weak #2: two
committed artifacts still carried planned-rows-era ``complete: true``
stamps.  This pass recomputes the claim from the artifact's own rows
and refuses stale stamps; ``tools/restamp_curves.py`` rewrites them.

The reference-grid constants are read from
``blades_tpu/benchmarks/accuracy_curves.py`` by AST (single source of
truth, no jax import at lint time).
"""

from __future__ import annotations

import ast
import json
from pathlib import Path
from typing import Iterable, List, Optional, Tuple

from tools.lint.core import Finding, LintContext, LintPass

CURVES_MODULE = "blades_tpu/benchmarks/accuracy_curves.py"


def reference_grid(root: Path) -> Optional[Tuple[List[str], List[float]]]:
    """(REFERENCE_AGGREGATORS, REFERENCE_MALICIOUS_FRACS) parsed from the
    curves module, or None when the module is absent/unreadable."""
    path = root / CURVES_MODULE
    if not path.is_file():
        return None
    try:
        tree = ast.parse(path.read_text())
    except SyntaxError:
        return None
    found = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if name in ("REFERENCE_AGGREGATORS", "REFERENCE_MALICIOUS_FRACS"):
                try:
                    found[name] = ast.literal_eval(node.value)
                except ValueError:
                    return None
    if len(found) != 2:
        return None
    return found["REFERENCE_AGGREGATORS"], found["REFERENCE_MALICIOUS_FRACS"]


def reference_cells(aggregators: List[str], fracs: List[float],
                    num_clients: int) -> List[Tuple[str, int]]:
    mal = sorted({int(round(f * num_clients)) for f in fracs})
    return [(a, m) for a in aggregators for m in mal]


def recompute_stamps(data: dict, aggregators: List[str],
                     fracs: List[float]) -> dict:
    """The completeness stamps this artifact SHOULD carry, from its rows."""
    n = int(data.get("num_clients") or 0)
    cells = reference_cells(aggregators, fracs, n)
    ran = {(r.get("aggregator"), r.get("num_malicious"))
           for r in data.get("rows", [])}
    missing = sorted(f"{a}@{m}" for a, m in cells if (a, m) not in ran)
    stamps = {
        "reference_grid": {
            "aggregators": list(aggregators),
            "malicious": sorted({int(round(f * n)) for f in fracs}),
        },
        "reference_cells_missing": missing,
        "complete": not missing,
    }
    planned = data.get("planned")
    if isinstance(planned, dict):
        stamps["planned_complete"] = all(
            (a, m) in ran for a in planned.get("aggregators", [])
            for m in planned.get("malicious", []))
    return stamps


class ArtifactStampsPass(LintPass):
    name = "artifact-stamps"
    doc = "curves.json completeness stamps recomputed against their rows"

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        # Artifacts are repo-wide state, not files in the scanned set: a
        # partial scan (--changed, explicit paths) must not fail on a
        # curves.json nobody asked about — e.g. one a running sweep is
        # legitimately mid-rewrite.
        if ctx.partial:
            return []
        grid = reference_grid(ctx.root)
        art_dir = ctx.root / "artifacts"
        if grid is None or not art_dir.is_dir():
            return []
        aggregators, fracs = grid
        findings: List[Finding] = []
        for path in sorted(art_dir.rglob("curves.json")):
            rel = str(path.relative_to(ctx.root))
            try:
                data = json.loads(path.read_text())
            except (OSError, json.JSONDecodeError) as exc:
                findings.append(Finding(
                    self.name, rel, 1, f"unreadable artifact: {exc}"))
                continue
            if not isinstance(data, dict) or "rows" not in data:
                continue
            want = recompute_stamps(data, aggregators, fracs)
            if "complete" not in data:
                findings.append(Finding(
                    self.name, rel, 1,
                    "artifact predates completeness stamping",
                    fix_hint="python tools/restamp_curves.py " + rel))
                continue
            if bool(data["complete"]) != want["complete"]:
                findings.append(Finding(
                    self.name, rel, 1,
                    f"stale complete: {data['complete']} stamp — the "
                    f"reference grid has {len(want['reference_cells_missing'])}"
                    " missing cell(s) "
                    f"{want['reference_cells_missing'][:4]}...",
                    fix_hint="python tools/restamp_curves.py " + rel))
            elif "reference_cells_missing" not in data:
                findings.append(Finding(
                    self.name, rel, 1,
                    "complete stamp predates reference-grid semantics "
                    "(no reference_cells_missing provenance)",
                    fix_hint="python tools/restamp_curves.py " + rel))
            elif sorted(data["reference_cells_missing"]) != \
                    want["reference_cells_missing"]:
                findings.append(Finding(
                    self.name, rel, 1,
                    "reference_cells_missing disagrees with the rows "
                    "actually present",
                    fix_hint="python tools/restamp_curves.py " + rel))
        return findings
