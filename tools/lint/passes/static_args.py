"""static-config: static jit-arg dataclasses must be frozen + hashable.

The config objects threaded into the round program as STATIC values —
``*Config`` in ``algorithms/``/``faults/``/``comm/``, the
``FaultInjector``, ``ClientPacking`` — are jit cache keys: jax hashes
them to decide whether a dispatch reuses a compiled executable.  A
mutable (unfrozen) config silently mutates under a cached program; an
unhashable field (list/dict/ndarray annotation, ``default_factory=
list``) raises at dispatch — or worse, hashes by identity and splits
the cache.  Verified structurally: ``@dataclass(frozen=True)`` with
``eq`` left True and every field annotation hashable.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Sequence

from tools.lint import astutil
from tools.lint.core import Finding, LintContext, LintPass

# Where static-config dataclasses live (ISSUE 8) and what they look like.
CONFIG_PREFIXES = ("blades_tpu/algorithms", "blades_tpu/faults",
                   "blades_tpu/comm", "blades_tpu/parallel/packed.py")
_NAME_SUFFIXES = ("Config", "Injector", "Packing")

# Annotation heads that cannot be hashed (and so cannot key a jit cache).
_UNHASHABLE_HEADS = {"list", "List", "dict", "Dict", "set", "Set",
                     "bytearray", "MutableMapping", "MutableSequence",
                     "ndarray", "np.ndarray", "numpy.ndarray",
                     "jnp.ndarray", "jax.Array", "Array"}


def _dataclass_decorator(cls: ast.ClassDef) -> Optional[ast.AST]:
    for d in cls.decorator_list:
        name = astutil.dotted(d if not isinstance(d, ast.Call) else d.func)
        if name and name.split(".")[-1] == "dataclass":
            return d
    return None


def _kw_value(deco: ast.AST, kw_name: str):
    if isinstance(deco, ast.Call):
        for kw in deco.keywords:
            if kw.arg == kw_name and isinstance(kw.value, ast.Constant):
                return kw.value.value
    return None


def _annotation_heads(node: ast.AST) -> List[str]:
    """Every dotted head in an annotation: ``Optional[List[int]]`` yields
    Optional, List, int."""
    heads = []
    for sub in ast.walk(node):
        d = astutil.dotted(sub)
        if d is not None:
            heads.append(d)
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            # String annotations: parse and recurse.
            try:
                heads.extend(_annotation_heads(
                    ast.parse(sub.value, mode="eval").body))
            except SyntaxError:
                pass
    return heads


class StaticArgsPass(LintPass):
    name = "static-config"
    doc = "static jit-arg dataclasses: frozen=True, eq on, hashable fields"

    def __init__(self, prefixes: Optional[Sequence[str]] = None):
        self.prefixes = (tuple(prefixes) if prefixes is not None
                         else CONFIG_PREFIXES)

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for src in ctx.matching(list(self.prefixes)):
            if src.tree is None:
                continue
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                if not node.name.endswith(_NAME_SUFFIXES):
                    continue
                deco = _dataclass_decorator(node)
                if deco is None:
                    continue
                findings.extend(self._check(src.rel, node, deco))
        return findings

    def _check(self, rel: str, cls: ast.ClassDef,
               deco: ast.AST) -> Iterable[Finding]:
        if _kw_value(deco, "frozen") is not True:
            yield Finding(
                self.name, rel, cls.lineno,
                f"static config dataclass {cls.name} is not frozen=True: "
                "a mutable jit cache key silently mutates under a cached "
                "program",
                fix_hint="@dataclasses.dataclass(frozen=True)")
        if _kw_value(deco, "eq") is False:
            yield Finding(
                self.name, rel, cls.lineno,
                f"static config dataclass {cls.name} sets eq=False: "
                "identity-hashing splits the jit cache per instance",
                fix_hint="leave eq at its True default")
        for stmt in cls.body:
            if not isinstance(stmt, ast.AnnAssign) or not isinstance(
                    stmt.target, ast.Name):
                continue
            bad = [h for h in _annotation_heads(stmt.annotation)
                   if h in _UNHASHABLE_HEADS
                   or h.split(".")[-1] in _UNHASHABLE_HEADS]
            if bad:
                yield Finding(
                    self.name, rel, stmt.lineno,
                    f"{cls.name}.{stmt.target.id} is annotated with "
                    f"unhashable {sorted(set(bad))}: the instance cannot "
                    "key a jit cache",
                    fix_hint="use a tuple / frozenset / scalar, converting "
                             "in __post_init__ if callers pass lists")
            if isinstance(stmt.value, ast.Call):
                cn = astutil.call_name(stmt.value) or ""
                if cn.split(".")[-1] == "field":
                    for kw in stmt.value.keywords:
                        if kw.arg == "default_factory" and astutil.dotted(
                                kw.value) in ("list", "dict", "set"):
                            yield Finding(
                                self.name, rel, stmt.lineno,
                                f"{cls.name}.{stmt.target.id} defaults to a "
                                f"mutable {astutil.dotted(kw.value)}()",
                                fix_hint="default to () / frozenset() / None")
