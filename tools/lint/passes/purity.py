"""jit-purity: host-side effects traced into jitted round programs.

ADVICE r5 #1's bug class: an ``os.environ`` read inside a function that
``jax.jit`` traces executes ONCE at trace time and is then baked into
the cached executable — flipping the env var later silently has no
effect on that program (the pallas_round MXU_FINISH bug).  The same
goes for ``time.*`` (a constant timestamp), ``print`` (fires at trace,
silent at run), and global mutation (happens once, not per step).

Two ways a function counts as traced:

* **reachable from a jit entry point in its module** — a function
  decorated ``@jax.jit`` / ``@partial(jax.jit, ...)``, named ``*_jit``,
  or passed by name into ``jax.jit(...)`` / ``cached_jit(...)``; the
  intra-module call graph (plain-name and ``self.method`` calls, plus
  functions passed as arguments from traced bodies — ``lax.scan``
  bodies and vmapped closures) closes over it.
* **defined in a round-body module** — the host-sync DEVICE_SIDE list
  plus the model definitions, whose code exists to be traced; there
  every function is suspect.  The deliberate trace-time escape hatches
  (fresh-process env toggles) carry pragmas with their contract.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set

from tools.lint import astutil
from tools.lint.core import Finding, LintContext, LintPass
from tools.lint.passes.host_sync import DEVICE_SIDE

# Modules whose entire surface is trace-candidate code.  The client
# ledger is in DEVICE_SIDE so host-sync polices its per-round update
# discipline, but nothing in it is ever traced — its checkpoint I/O
# (open/np.save) is legitimate host work, so it is excluded here.
TRACED_MODULES = tuple(
    m for m in DEVICE_SIDE if m != "blades_tpu/obs/ledger.py") + (
    "blades_tpu/models/layers.py",
    "blades_tpu/models/mlp.py",
    "blades_tpu/models/cnn.py",
    "blades_tpu/models/resnet.py",
    "blades_tpu/models/cct.py",
)

# Dotted prefixes whose evaluation inside a traced body is a host effect
# baked in at trace time.
_IMPURE_PREFIXES = (
    "os.environ", "os.getenv", "os.putenv",
    "time.time", "time.perf_counter", "time.monotonic", "time.sleep",
    "time.process_time",
    "datetime.now", "datetime.datetime.now", "datetime.utcnow",
    "np.random", "numpy.random", "random.random", "random.randint",
    "random.choice", "random.shuffle", "random.seed",
)
_IMPURE_CALLS = {"print", "input", "open", "breakpoint"}

_HINT = ("resolve host state OUTSIDE the traced function (an un-jitted "
         "wrapper, a static config field) and pass the result in; a "
         "traced read executes once at trace time and is baked into "
         "every cached executable")


# Nested defs are analyzed as their own functions (traced iff reachable
# themselves), so their contents must not be attributed to the parent.
# Lambdas stay: a lambda's body runs inline within the enclosing trace.
_NESTED_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


def _impure_nodes(fn: ast.AST) -> List[tuple]:
    """(line, description) for each impure construct in this body."""
    out = []
    for sub in astutil.scope_nodes(fn, prune=_NESTED_SCOPES):
        if isinstance(sub, ast.Global):
            out.append((sub.lineno, "`global` statement (trace-time "
                        "mutation happens once, not per step)"))
        elif isinstance(sub, (ast.Attribute, ast.Name)):
            path = astutil.dotted(sub)
            if path and any(path == p or path.startswith(p + ".")
                            for p in _IMPURE_PREFIXES):
                out.append((sub.lineno, f"`{path}` read"))
        elif isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                and sub.func.id in _IMPURE_CALLS:
            out.append((sub.lineno, f"`{sub.func.id}()` call"))
    # Dedupe attribute chains (os.environ.get reports once per chain).
    seen: Set[tuple] = set()
    uniq = []
    for line, what in out:
        if (line, what.split(".")[0]) not in seen:
            seen.add((line, what.split(".")[0]))
            uniq.append((line, what))
    return uniq


class PurityPass(LintPass):
    name = "jit-purity"
    doc = ("os.environ / time.* / print / global mutation reachable "
           "from a jitted entry point")

    def __init__(self, traced_modules: Optional[Sequence[str]] = None):
        self.traced_modules = (tuple(traced_modules)
                               if traced_modules is not None
                               else TRACED_MODULES)

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for src in ctx.files:
            if src.tree is None:
                continue
            fns = astutil.function_defs(src.tree)
            by_name: Dict[str, List[ast.AST]] = {}
            for fn in fns:
                by_name.setdefault(fn.name, []).append(fn)
            whole_module = src.rel in self.traced_modules
            if whole_module:
                traced = {fn.name for fn in fns}
                entry_of = {fn.name: f"round-body module {src.rel}"
                            for fn in fns}
            else:
                traced, entry_of = self._reach(src, fns, by_name)
            for fn in fns:
                if fn.name not in traced:
                    continue
                for line, what in _impure_nodes(fn):
                    findings.append(Finding(
                        self.name, src.rel, line,
                        f"{what} inside `{fn.name}` "
                        f"(traced: {entry_of[fn.name]})",
                        fix_hint=_HINT))
        return findings

    # -- reachability -------------------------------------------------------

    def _reach(self, src, fns, by_name) -> tuple:
        entries: Dict[str, str] = {}
        for fn in fns:
            decos = astutil.decorator_names(fn)
            if any(d in ("jit", "jax.jit", "pjit", "jax.pjit")
                   for d in decos):
                entries[fn.name] = f"@jit entry `{fn.name}`"
            elif fn.name.endswith("_jit"):
                entries[fn.name] = f"`{fn.name}` (_jit naming contract)"
        if src.tree is not None:
            for call in astutil.walk_calls(src.tree):
                cn = astutil.call_name(call)
                if cn and cn.split(".")[-1] in ("jit", "cached_jit", "pjit") \
                        and call.args:
                    target = astutil.dotted(call.args[0])
                    if target and target in by_name:
                        entries.setdefault(
                            target, f"passed to {cn}() as `{target}`")
        # Close over the intra-module call graph: in a traced body, any
        # plain-name reference to a module function is traced too (called
        # directly, or passed into lax.scan/vmap/cond).
        traced: Set[str] = set(entries)
        entry_of: Dict[str, str] = dict(entries)
        frontier = list(entries)
        while frontier:
            name = frontier.pop()
            for fn in by_name.get(name, []):
                for sub in ast.walk(fn):
                    ref = None
                    if isinstance(sub, ast.Name) and isinstance(
                            sub.ctx, ast.Load):
                        ref = sub.id
                    elif isinstance(sub, ast.Attribute) and isinstance(
                            sub.value, ast.Name) and sub.value.id == "self":
                        ref = sub.attr
                    if ref and ref in by_name and ref not in traced:
                        traced.add(ref)
                        entry_of[ref] = entry_of[name]
                        frontier.append(ref)
        return traced, entry_of
