"""host-sync: no device->host round trips inside jitted-round modules.

The AST generalization of the retired ``tests/test_no_host_sync.py``
grep: every ``device_get`` / ``block_until_ready`` / numpy conversion /
``.item()`` / ``float(<array expr>)`` inside the modules whose code runs
inside (or builds) the jitted round stalls the dispatch pipeline once
per round — through a remote-execution relay that costs more than the
round itself.  Sanctioned flush points live in HOST modules (fedavg
finalize_row, the sweep's batched emit, perf/async_metrics), which are
not scanned; a device-side line that must sync carries
``# blades-lint: disable=host-sync — <why>``.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Sequence

from tools.lint import astutil
from tools.lint.core import Finding, LintContext, LintPass

# Modules whose code runs inside (or traces into) the jitted round.
DEVICE_SIDE = (
    "blades_tpu/core/round.py",
    "blades_tpu/core/server.py",
    "blades_tpu/core/task.py",
    "blades_tpu/core/health.py",
    "blades_tpu/core/callbacks.py",
    "blades_tpu/data/sampler.py",
    "blades_tpu/data/augment.py",
    "blades_tpu/adversaries/base.py",
    "blades_tpu/adversaries/update_attacks.py",
    "blades_tpu/adversaries/training_attacks.py",
    "blades_tpu/faults/injector.py",
    "blades_tpu/comm/codecs.py",
    # Buffered-async subsystem (ISSUE 14): the cycle program and the
    # realization/weight modules trace into the jitted cycle; the host
    # engine (arrivals/engine.py) is deliberately NOT here — its
    # device_get of the realization windows is the sanctioned host
    # boundary.
    "blades_tpu/arrivals/cycle.py",
    "blades_tpu/arrivals/process.py",
    "blades_tpu/arrivals/weights.py",
    # Out-of-core state staging (ISSUE 15): the store + prefetcher ARE
    # the staging hot path — a stray blocking fetch there stalls the
    # round pipeline exactly like one inside the jitted round.  The
    # sanctioned prefetcher boundary (cohort-id fetch, the write-back
    # fetch, one-time store init) carries per-line justification
    # pragmas; everything else is a finding.
    "blades_tpu/state/store.py",
    "blades_tpu/state/prefetch.py",
    # Out-of-core training data (ISSUE 20): the data store + streaming
    # plumbing are the data-plane staging hot path — cohort gathers ride
    # the state prefetcher's FIFO worker and the chunked evaluator's
    # per-chunk scalar fetch is the ONE sanctioned eval sync (four
    # scalars per chunk, pragma'd at the site).  Any other blocking
    # fetch here stalls the round pipeline exactly like state staging.
    "blades_tpu/data/store.py",
    "blades_tpu/data/stream.py",
    # Client-lifetime ledger (ISSUE 16): observe() runs once per round
    # on the driver thread between dispatches — an unsanctioned device
    # fetch there re-introduces exactly the per-round stall the
    # deferred-row machinery removed.  The np.asarray coercions over
    # ALREADY-FETCHED rows are the sanctioned boundary and carry
    # per-line pragmas; any new sync is a finding.
    "blades_tpu/obs/ledger.py",
    # Control plane (ISSUE 17): policy decisions and the controller's
    # step() run once per round on the driver thread between dispatches
    # over ALREADY-FETCHED rows — an unsanctioned device fetch there
    # stalls the pipeline like any other, and worse: it would smuggle
    # device state into decisions the replay contract says are pure in
    # (policy, pre-state, sensor row, round, tick), making the journal
    # non-rederivable.  Raw wall-clock in decisions is the same hazard
    # and is already frozen out repo-wide by trace-discipline.
    "blades_tpu/control/policy.py",
    "blades_tpu/control/controller.py",
    "blades_tpu/ops/aggregators.py",
    "blades_tpu/ops/clustering.py",
    "blades_tpu/ops/layout.py",
    "blades_tpu/ops/masked.py",
    "blades_tpu/ops/pallas_round.py",
    "blades_tpu/ops/pallas_rowstats.py",
    "blades_tpu/ops/pallas_select.py",
    "blades_tpu/parallel/streamed.py",
    "blades_tpu/parallel/streamed_geometry.py",
    "blades_tpu/parallel/sharded.py",
    "blades_tpu/parallel/dsharded.py",
    "blades_tpu/parallel/packed.py",
    # Decentralized gossip round (ISSUE 19): the per-node round program
    # traces into shard_map — a stray sync there stalls every node's
    # dispatch.  graph.py is deliberately NOT here: it is host-side
    # numpy by design (tables are built once at setup).
    "blades_tpu/topology/gossip.py",
)

_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready",
               "np.asarray", "np.array", "numpy.asarray", "numpy.array"}
# jnp/jax attribute roots whose presence inside a float()/int() argument
# marks the argument as an on-device array expression.
_ARRAY_ROOTS = {"jnp", "jax"}
_REDUCTIONS = {"sum", "mean", "max", "min", "all", "any", "prod"}

_HINT = ("move the fetch to a sanctioned flush point (fedavg "
         "finalize_row / sweep batched emit / perf.async_metrics), or "
         "pragma the line if it is genuinely setup-time/once-per-object")


def _is_array_expr(node: ast.AST) -> bool:
    """Heuristic: does this expression produce an on-device array?
    True when it mentions a ``jnp.``/``jax.`` attribute or calls an
    array reduction method (``x.sum()`` ...)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute):
            root = sub
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id in _ARRAY_ROOTS:
                return True
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr in _REDUCTIONS:
            return True
    return False


class HostSyncPass(LintPass):
    name = "host-sync"
    doc = ("device->host sync (device_get / block_until_ready / "
           "np.asarray / .item() / float(array)) in jitted-round modules")

    def __init__(self, modules: Optional[Sequence[str]] = None):
        self.modules = tuple(modules) if modules is not None else DEVICE_SIDE

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        scanning_repo = (ctx.root / "blades_tpu").is_dir() \
            and self.modules is DEVICE_SIDE
        for rel in self.modules:
            src = ctx.file(rel)
            if src is None:
                # Partial scans (--changed / explicit paths) simply skip
                # absent modules; a module GONE from disk on a full scan
                # means this list went stale.
                if scanning_repo and not (ctx.root / rel).exists():
                    findings.append(Finding(
                        self.name, rel, 1,
                        "host-sync module list is stale: file is gone",
                        fix_hint="update DEVICE_SIDE in "
                                 "tools/lint/passes/host_sync.py"))
                continue
            if src.tree is None:
                continue
            for call in astutil.walk_calls(src.tree):
                cn = astutil.call_name(call)
                if cn in _SYNC_CALLS:
                    findings.append(Finding(
                        self.name, src.rel, call.lineno,
                        f"host-sync call {cn}() in a jitted-round module",
                        fix_hint=_HINT))
                elif (isinstance(call.func, ast.Attribute)
                        and call.func.attr in ("item", "block_until_ready")
                        and not call.args and not call.keywords):
                    findings.append(Finding(
                        self.name, src.rel, call.lineno,
                        f".{call.func.attr}() in a jitted-round module",
                        fix_hint=_HINT))
                elif (isinstance(call.func, ast.Name)
                        and call.func.id in ("float", "int")
                        and len(call.args) == 1
                        and _is_array_expr(call.args[0])):
                    findings.append(Finding(
                        self.name, src.rel, call.lineno,
                        f"{call.func.id}() on an array expression forces "
                        "a device sync in a jitted-round module",
                        fix_hint=_HINT))
        return findings
