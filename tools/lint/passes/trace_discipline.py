"""trace-discipline: every measured second flows through the span layer.

ISSUE 12 consolidated ``utils/timers.py`` + ``utils/profiling.py`` into
:mod:`blades_tpu.obs.trace` as the SINGLE timing source of truth: phase
durations are spans (they aggregate, nest, export to Chrome traces, and
correlate with the jax profiler), and the sanctioned raw clock is
``obs.trace.now()``.  A raw ``time.time()`` / ``time.perf_counter()`` /
``time.monotonic()`` call anywhere else under ``blades_tpu/`` produces a
duration nobody can see in a trace — the drift this pass freezes out,
exactly like host-sync froze out stray ``device_get``\\ s.

Scope: ``blades_tpu/`` only (bench.py and tools/ are measurement
harnesses outside the traced driver).  The trace/timer modules
themselves are the allowed homes.  Detection covers the module-attribute
form (``time.perf_counter()``), ``from time import perf_counter``
aliases, and the ``_ns`` variants; ``time.sleep`` is not a measurement
and stays legal, as does passing ``time.perf_counter`` itself as an
injectable clock default (a reference, not a call).  Genuinely
sanctioned wall-clock stamps (e.g. the autotuner plan-cache
``created_unix`` metadata) carry the unified pragma with a reason.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set

from tools.lint import astutil
from tools.lint.core import Finding, LintContext, LintPass

#: Where raw clock reads are legal: the span layer itself and its
#: back-compat shims.
TIMER_MODULES = (
    "blades_tpu/obs/trace.py",
    "blades_tpu/utils/timers.py",
    "blades_tpu/utils/profiling.py",
)

#: ``time`` module attributes whose CALL is a duration/wall-clock read.
RAW_CLOCKS = frozenset({
    "time", "perf_counter", "monotonic",
    "time_ns", "perf_counter_ns", "monotonic_ns",
})

_HINT = ("time the block with a blades_tpu.obs.trace span "
         "(Tracer.span/time, or start/finish around non-nestable "
         "blocks), or read obs.trace.now() for a bare elapsed delta; "
         "pragma the line only for a sanctioned wall-clock metadata "
         "stamp")


class TraceDisciplinePass(LintPass):
    name = "trace-discipline"
    doc = ("raw time.time()/perf_counter()/monotonic() calls in "
           "blades_tpu/ outside the trace/timer modules")

    def __init__(self, prefixes: Optional[Sequence[str]] = None,
                 allowed: Optional[Sequence[str]] = None):
        self.prefixes = tuple(prefixes) if prefixes is not None \
            else ("blades_tpu",)
        self.allowed = frozenset(allowed) if allowed is not None \
            else frozenset(TIMER_MODULES)

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for src in ctx.matching(self.prefixes):
            if src.rel in self.allowed or src.tree is None:
                continue
            time_mods, clock_aliases = self._imports(src.tree)
            if not time_mods and not clock_aliases:
                continue
            for call in astutil.walk_calls(src.tree):
                cn = astutil.call_name(call)
                if cn is None:
                    continue
                if cn in clock_aliases:
                    findings.append(Finding(
                        self.name, src.rel, call.lineno,
                        f"raw clock call {cn}() (imported from the time "
                        "module) outside the trace/timer modules",
                        fix_hint=_HINT))
                    continue
                head, _, tail = cn.rpartition(".")
                if head in time_mods and tail in RAW_CLOCKS:
                    findings.append(Finding(
                        self.name, src.rel, call.lineno,
                        f"raw clock call {cn}() outside the trace/timer "
                        "modules — this duration is invisible to the "
                        "span tree",
                        fix_hint=_HINT))
        return findings

    @staticmethod
    def _imports(tree: ast.Module):
        """(names the ``time`` module is bound to, names its clock
        functions are bound to) in this file — import-based, so a local
        variable or another module named ``time`` cannot false-positive."""
        time_mods: Set[str] = set()
        clock_aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "time":
                        time_mods.add(alias.asname or alias.name)
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for alias in node.names:
                    if alias.name in RAW_CLOCKS:
                        clock_aliases[alias.asname or alias.name] = \
                            alias.name
        return time_mods, clock_aliases
