"""use-after-donate: reads of a buffer after a donating dispatch.

PR 3's contract — ``jax.jit(fn, donate_argnums=(0,))`` lets XLA reuse
the argument's buffers in place, so the PRE-step value is invalidated
the moment the dispatch is issued.  Reading it afterwards raises (best
case) or reads freed memory through stale references; until this pass
the invariant lived in a docstring.

Detection is module-local and flow-insensitive-but-ordered:

1. Collect every *donating callable* the module defines — a name bound
   to ``jax.jit(f, donate_argnums=...)`` / ``cached_jit(...,
   donate_argnums=...)`` (attribute targets like ``self._step`` count),
   or a function decorated ``@partial(jax.jit, donate_argnums=...)``.
   ``donate_argnums`` must resolve to literal int positions; a plain
   name is chased through one local ``x = (0,) if cond else ()``-style
   assignment (positions union — donation *may* happen is enough).
2. Walk each scope's statements in order: a call to a donating callable
   marks the dotted path at each donated position as dead; a later load
   of that path is a finding; any rebind revives it.  Loop bodies are
   walked twice so a donation in iteration ``i`` flags a read in
   iteration ``i+1`` (``for ...: m = step(state)`` with no rebind).

Cross-module donators (a factory returning a donating jit from another
file) are out of scope — the factory's own module is where the call
discipline lives, and every in-repo factory call site rebinds in the
same statement.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.lint import astutil
from tools.lint.core import Finding, LintContext, LintPass

_JIT_FACTORIES = {"jax.jit", "jit", "cached_jit", "pjit", "jax.pjit"}


def _resolve_argnums(node: ast.AST,
                     scope_assigns: Dict[str, ast.AST]) -> Optional[Tuple[int, ...]]:
    """Literal donate_argnums positions, chasing one level of local
    assignment and conditional expressions (union of branches)."""
    lit = astutil.literal_int_tuple(node)
    if lit is not None:
        return lit
    if isinstance(node, ast.IfExp):
        a = _resolve_argnums(node.body, scope_assigns)
        b = _resolve_argnums(node.orelse, scope_assigns)
        if a is None and b is None:
            return None
        return tuple(sorted(set(a or ()) | set(b or ())))
    if isinstance(node, ast.Name) and node.id in scope_assigns:
        return _resolve_argnums(scope_assigns[node.id], {})
    return None


def _donating_call(call: ast.Call,
                   scope_assigns: Dict[str, ast.AST]) -> Optional[Tuple[int, ...]]:
    """donate positions if this Call constructs a donating jit."""
    cn = astutil.call_name(call)
    if cn is None:
        return None
    if cn.split(".")[-1] not in {f.split(".")[-1] for f in _JIT_FACTORIES} \
            and cn not in _JIT_FACTORIES:
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            nums = _resolve_argnums(kw.value, scope_assigns)
            if nums:
                return nums
    return None


class _ScopeWalker:
    """Ordered statement walk of one function/module body."""

    def __init__(self, owner: "DonationPass", src_rel: str,
                 donators: Dict[str, Tuple[int, ...]]):
        self.owner = owner
        self.rel = src_rel
        self.donators = donators
        self.dead: Dict[str, Tuple[int, str]] = {}  # path -> (line, callee)
        self.findings: List[Finding] = []
        self._seen: Set[Tuple[int, str]] = set()

    def walk(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._statement(stmt)

    # -- one statement ------------------------------------------------------

    def _statement(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested scopes are walked separately
        # 1) loads of currently-dead paths (the donation call's own
        #    arguments evaluate before the dispatch, so same-statement
        #    loads check against the PRE-statement dead set).
        self._check_loads(stmt)
        # 2) donation calls kill their buffer args.  Compound statements
        #    contribute only their HEADER here — calls in their bodies
        #    are handled by the recursion in step 4, in body order.
        for node in self._header_nodes(stmt):
            self._mark_donations(node)
        # 3) rebinds revive.
        for path in astutil.assign_target_paths(stmt):
            self.dead.pop(path, None)
            # Rebinding `x` also revives `x.attr` paths.
            stale = [p for p in self.dead if p.startswith(path + ".")]
            for p in stale:
                self.dead.pop(p, None)
        # 4) recurse into compound statements, loop bodies twice (a
        #    donation surviving iteration N is read by iteration N+1).
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            self.walk(list(stmt.body))
            self.walk(list(stmt.body))
            self.walk(list(stmt.orelse))
        elif isinstance(stmt, ast.If):
            before = dict(self.dead)
            self.walk(list(stmt.body))
            after_body = self.dead
            self.dead = dict(before)
            self.walk(list(stmt.orelse))
            # Union: donated in either branch stays suspect afterwards.
            self.dead.update(after_body)
        elif isinstance(stmt, ast.Try):
            self.walk(list(stmt.body))
            for h in stmt.handlers:
                self.walk(list(h.body))
            self.walk(list(stmt.orelse))
            self.walk(list(stmt.finalbody))
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            self.walk(list(stmt.body))

    @staticmethod
    def _header_nodes(stmt: ast.stmt) -> List[ast.AST]:
        """The statement's own expressions, excluding nested bodies."""
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return [stmt.iter]
        if isinstance(stmt, ast.While) or isinstance(stmt, ast.If):
            return [stmt.test]
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return [i.context_expr for i in stmt.items]
        if isinstance(stmt, ast.Try):
            return []
        return [stmt]

    def _mark_donations(self, node: ast.AST) -> None:
        for call in astutil.walk_calls(node):
            nums = self.donators.get(astutil.call_name(call) or "")
            if not nums:
                continue
            for pos in nums:
                if pos < len(call.args):
                    path = astutil.dotted(call.args[pos])
                    if path is not None:
                        self.dead[path] = (call.lineno,
                                           astutil.call_name(call) or "?")

    def _check_loads(self, stmt: ast.stmt) -> None:
        if not self.dead:
            return
        # Compound statements: only inspect the header expression here
        # (bodies are recursed into with updated state).
        for node in self._header_nodes(stmt):
            for sub in ast.walk(node):
                path = astutil.dotted(sub)
                if path is None or not isinstance(getattr(sub, "ctx", None),
                                                  (ast.Load,)):
                    continue
                hit = self.dead.get(path)
                if hit is None:
                    # A load of x.y where x itself was donated dies too.
                    for dead_path, h in self.dead.items():
                        if path.startswith(dead_path + "."):
                            hit = h
                            break
                if hit is None:
                    continue
                dline, callee = hit
                key = (sub.lineno, path)
                if key in self._seen:
                    continue
                self._seen.add(key)
                self.findings.append(Finding(
                    self.owner.name, self.rel, sub.lineno,
                    f"'{path}' is read after being donated to {callee}() "
                    f"at line {dline} (donate_argnums invalidates the "
                    "buffer at dispatch)",
                    fix_hint="rebind the result over the donated name "
                             "(state = step(state, ...)), or drop "
                             "donate_argnums for this dispatch"))


class DonationPass(LintPass):
    name = "use-after-donate"
    doc = "reads of a buffer after it was donated into a jit dispatch"

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for src in ctx.files:
            if src.tree is None:
                continue
            fns = astutil.function_defs(src.tree)
            # Module-global donators: decorated functions and dotted
            # (attribute) targets like `self._step` — callable from any
            # scope.  Plain-name assignments are scoped to the function
            # that makes them: `step` in one helper is not `step` in
            # another.
            global_don = self._scope_donators(src.tree, dotted_only=True)
            global_don.update(self._decorated_donators(src.tree))
            scopes: List[Tuple[List[ast.stmt], Dict]] = [
                (list(src.tree.body), self._scope_donators(src.tree))]
            for fn in fns:
                scopes.append((list(fn.body), self._scope_donators(fn)))
            for body, local_don in scopes:
                donators = dict(global_don)
                donators.update(local_don)
                if not donators:
                    continue
                w = _ScopeWalker(self, src.rel, donators)
                w.walk(body)
                findings.extend(w.findings)
        return findings

    # -- phase A: donator collection ----------------------------------------

    def _scope_donators(self, scope: ast.AST,
                        dotted_only: bool = False) -> Dict[str, Tuple[int, ...]]:
        """Donating assignments within ``scope``.  ``dotted_only`` keeps
        attribute paths (``self._step``), collected module-wide for the
        global map; otherwise plain names assigned in the scope's OWN
        statements (nested defs excluded) are returned."""
        nodes = (list(ast.walk(scope)) if dotted_only
                 else astutil.scope_nodes(scope))
        assigns: Dict[str, ast.AST] = {}
        for node in nodes:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = astutil.dotted(node.targets[0])
                if t is not None and "." not in t:
                    assigns[t] = node.value
        donators: Dict[str, Tuple[int, ...]] = {}
        for node in nodes:
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            nums = _donating_call(node.value, assigns)
            if not nums:
                continue
            for t in node.targets:
                path = astutil.dotted(t)
                if path is None:
                    continue
                if dotted_only != ("." in path):
                    continue
                donators[path] = nums
        return donators

    def _decorated_donators(self, tree: ast.Module) -> Dict[str, Tuple[int, ...]]:
        """@partial(jax.jit, donate_argnums=...) / @jax.jit(...) forms."""
        donators: Dict[str, Tuple[int, ...]] = {}
        for fn in astutil.function_defs(tree):
            for d in fn.decorator_list:
                if isinstance(d, ast.Call):
                    names = {astutil.dotted(d.func) or ""} | {
                        astutil.dotted(a) or "" for a in d.args}
                    if not ({"jax.jit", "jit"} & names):
                        continue
                    for kw in d.keywords:
                        if kw.arg == "donate_argnums":
                            nums = _resolve_argnums(kw.value, {})
                            if nums:
                                donators[fn.name] = nums
        return donators
