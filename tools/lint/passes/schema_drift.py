"""schema-drift: round-record keys vs the obs schema, both directions.

``blades_tpu/obs/schema.py`` is the contract every downstream consumer
(visualize, BENCH graders, dashboards) parses; the strict validator
already rejects unknown keys AT RUNTIME — but only on code paths a test
happens to drive.  This pass closes the gap statically, in both
directions:

* **stamped-but-unregistered (error)** — a constant string key stored
  into a host-side round-record dict (``row[...] = ``, ``row.update({``
  ``...})``, the codec's ``round_metrics`` literal, the logger's
  ``base=`` stamp) that ``ROUND_RECORD_FIELDS`` does not register would
  fail schema validation the first time that config runs.
* **registered-but-never-stamped (warning)** — a registered key no
  stamp site produces is either dead weight or stamped through a
  dynamic path the analysis cannot see; the registration line carries a
  pragma naming that path when it is the latter (the lane-override
  knobs).

Stamp collection covers: constant-key subscript stores and dict
literals bound to row-like names (``row``/``comm_row``/``rec``/
``_last_eval``), ``row.update({...})`` literals, ``for k in ("a", "b"):
row[k] = ...`` literal loops, dict literals returned by functions named
``round_metrics``, and ``base={...}`` logger keywords.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.lint import astutil
from tools.lint.core import Finding, LintContext, LintPass, WARNING

SCHEMA_MODULE = "blades_tpu/obs/schema.py"
SCHEMA_TABLE = "ROUND_RECORD_FIELDS"

# Host modules that stamp round-record (metrics.jsonl / result.json row)
# keys.  Device-side metrics dicts (core/round.py) are NOT records — the
# driver copies the schema'd subset host-side.
STAMP_MODULES = (
    "blades_tpu/algorithms/fedavg.py",
    "blades_tpu/tune/sweep.py",
    "blades_tpu/tune/lanes.py",
    "blades_tpu/comm/codecs.py",
    # round_fields() builds the per-round ledger stamp (`rec` dict
    # literal) that fedavg merges into the row verbatim.
    "blades_tpu/obs/ledger.py",
)
_ROW_NAMES = {"row", "comm_row", "rec", "record", "_last_eval"}
_DICT_RETURN_FNS = {"round_metrics"}


def _basename(path: str) -> str:
    return path.split(".")[-1]


class SchemaDriftPass(LintPass):
    name = "schema-drift"
    doc = "metric keys stamped into rows vs obs/schema.py registrations"

    def __init__(self, schema_module: str = SCHEMA_MODULE,
                 stamp_modules: Optional[Sequence[str]] = None):
        self.schema_module = schema_module
        self.stamp_modules = (tuple(stamp_modules)
                              if stamp_modules is not None else STAMP_MODULES)

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        schema_src = ctx.file(self.schema_module)
        if schema_src is None or schema_src.tree is None:
            return []  # partial scan without the schema: nothing to check
        registered = self._registered(schema_src.tree)
        if not registered:
            return []
        stamped: Dict[str, Tuple[str, int]] = {}
        findings: List[Finding] = []
        all_stamp_modules_seen = True
        for rel in self.stamp_modules:
            src = ctx.file(rel)
            if src is None or src.tree is None:
                all_stamp_modules_seen = False
                continue
            for key, line in self._stamped_keys(src.tree):
                stamped.setdefault(key, (src.rel, line))
        for key, (rel, line) in sorted(stamped.items()):
            if key not in registered:
                findings.append(Finding(
                    self.name, rel, line,
                    f"metric key '{key}' is stamped into round records "
                    "but not registered in obs/schema.py — strict "
                    "validation rejects the row at runtime",
                    fix_hint="register it in ROUND_RECORD_FIELDS (types + "
                             "required flag) or rename to a registered key"))
        # The never-stamped direction needs EVERY stamp module in view —
        # on a partial scan (--changed) absent modules would make every
        # registered key look orphaned.
        if not all_stamp_modules_seen:
            return findings
        for key, line in sorted(registered.items()):
            if key not in stamped:
                findings.append(Finding(
                    self.name, self.schema_module, line,
                    f"registered metric key '{key}' is never stamped by "
                    "any known round-record site",
                    fix_hint="drop the registration, or pragma the line "
                             "naming the dynamic stamp path",
                    severity=WARNING))
        return findings

    # -- schema side --------------------------------------------------------

    def _registered(self, tree: ast.Module) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for node in tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = [t for t in node.targets
                           if isinstance(t, ast.Name)]
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = ([node.target]
                           if isinstance(node.target, ast.Name) else [])
                value = node.value
            else:
                continue
            if not any(t.id == SCHEMA_TABLE for t in targets):
                continue
            if isinstance(value, ast.Dict):
                for k in value.keys:
                    if isinstance(k, ast.Constant) and isinstance(
                            k.value, str):
                        out[k.value] = k.lineno
        return out

    # -- stamp side ---------------------------------------------------------

    def _stamped_keys(self, tree: ast.Module) -> Iterable[Tuple[str, int]]:
        # Literal `for k in ("a", "b")` loop vars, scoped by loop node.
        loop_keys: Dict[str, List[str]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.For) and isinstance(
                    node.target, ast.Name):
                lits = self._const_str_seq(node.iter)
                if lits:
                    loop_keys.setdefault(node.target.id, []).extend(lits)
        for node in ast.walk(tree):
            # row["key"] = ... / row[k] = ... inside a literal loop
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        base = astutil.dotted(t.value)
                        if base is None or _basename(base) not in _ROW_NAMES:
                            continue
                        if isinstance(t.slice, ast.Constant) and isinstance(
                                t.slice.value, str):
                            yield t.slice.value, t.lineno
                        elif isinstance(t.slice, ast.Name):
                            for key in loop_keys.get(t.slice.id, []):
                                yield key, t.lineno
                    else:
                        base = astutil.dotted(t)
                        if base is not None \
                                and _basename(base) in _ROW_NAMES \
                                and isinstance(node.value, ast.Dict):
                            yield from self._dict_keys(node.value)
            # row.update({...})
            elif isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "update":
                    base = astutil.dotted(node.func.value)
                    if base is not None and _basename(base) in _ROW_NAMES \
                            and node.args \
                            and isinstance(node.args[0], ast.Dict):
                        yield from self._dict_keys(node.args[0])
                for kw in node.keywords:
                    if kw.arg == "base" and isinstance(kw.value, ast.Dict):
                        yield from self._dict_keys(kw.value)
        # dict literals returned from round_metrics-style functions
        for fn in astutil.function_defs(tree):
            if fn.name not in _DICT_RETURN_FNS:
                continue
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Return) and isinstance(
                        sub.value, ast.Dict):
                    yield from self._dict_keys(sub.value)

    @staticmethod
    def _dict_keys(d: ast.Dict) -> Iterable[Tuple[str, int]]:
        for k in d.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                yield k.value, k.lineno

    @staticmethod
    def _const_str_seq(node: ast.AST) -> List[str]:
        if isinstance(node, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in node.elts):
            return [e.value for e in node.elts]
        return []
