"""topology-discipline: neighborhood exchange stays behind the counted
gossip program.

The decentralized round (``blades_tpu/topology/gossip.py``) moves every
per-node replica exchange through PassRecorder-counted collectives, so
the ``gossip_ici_bytes`` stamp reconciles event-by-event against the
analytic comm model (``parallel/comm_model.gossip_round_volumes``) —
the pod-scale ``ici_bytes`` contract, extended to peer graphs.  A file
that builds topology neighbor tables AND spells a raw cross-device
collective re-introduces an UNCOUNTED exchange: the wire bytes the row
reports stop covering the bytes the round actually moved, which is the
exact drift the reconciliation tests pin.  Enforced statically like
streamed-pass-discipline.

Detection is import-based, so collectives in modules that never touch
the topology tables (``parallel/hier.py``'s counted gathers, the mesh
helpers) never false-positive: a call is flagged only in a file that
also imports table-building machinery from ``blades_tpu.topology``
(``TopologyConfig`` / ``NeighborTables`` / ``get_topology``), outside
the gossip module itself.  Deliberate reference-path uses carry the
unified pragma (``# blades-lint: disable=topology-discipline — <why>``).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from tools.lint import astutil
from tools.lint.core import Finding, LintContext, LintPass

#: The gossip module — the only place neighborhood-exchange collectives
#: may be spelled against the topology tables (every one counted).
GOSSIP_MODULE = "blades_tpu/topology/gossip.py"

_TOPOLOGY_MODULES = frozenset({
    "blades_tpu.topology",
    "blades_tpu.topology.graph",
})
#: Importing any of these marks the file as table-building.
_TABLE_NAMES = frozenset({
    "TopologyConfig", "NeighborTables", "get_topology", "neighbor_tables",
})

#: Raw cross-device exchange primitives (each an uncounted wire move
#: when spelled outside the gossip program's recorder).
_COLLECTIVES = frozenset({
    "jax.lax.all_gather", "lax.all_gather",
    "jax.lax.psum", "lax.psum",
    "jax.lax.psum_scatter", "lax.psum_scatter",
    "jax.lax.ppermute", "lax.ppermute",
    "jax.lax.all_to_all", "lax.all_to_all",
})

_HINT = ("route the exchange through topology/gossip.py's counted "
         "gathers (PassRecorder.count_ici) so gossip_ici_bytes keeps "
         "reconciling against comm_model.gossip_round_volumes, or "
         "pragma the line if the collective is deliberately outside "
         "the gossip wire accounting")


class TopologyDisciplinePass(LintPass):
    name = "topology-discipline"
    doc = ("raw cross-device collectives in files that build topology "
           "neighbor tables, outside the counted gossip program")

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for src in ctx.files:
            if src.rel == GOSSIP_MODULE or src.tree is None:
                continue
            if not self._builds_tables(src.tree):
                continue
            for call in astutil.walk_calls(src.tree):
                cn = astutil.call_name(call)
                if cn in _COLLECTIVES:
                    findings.append(Finding(
                        self.name, src.rel, call.lineno,
                        f"raw collective {cn}() in a file that builds "
                        "topology neighbor tables — an uncounted "
                        "neighborhood exchange outside the gossip "
                        "program", fix_hint=_HINT))
        return findings

    @staticmethod
    def _builds_tables(tree: ast.Module) -> bool:
        """Does this file import table-building machinery from the
        topology package (including ``import ... as`` renames)?"""
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                if node.module in _TOPOLOGY_MODULES:
                    if any(a.name in _TABLE_NAMES for a in node.names):
                        return True
            elif isinstance(node, ast.Import):
                if any(a.name in _TOPOLOGY_MODULES for a in node.names):
                    return True
        return False
