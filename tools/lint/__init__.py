"""blades-lint: JAX-aware static analysis for the load-bearing invariants.

The pure-functional analogue of a race detector: instead of data races,
the bug classes here are broken purity contracts — use-after-donate,
PRNG key reuse, host effects traced into jit bodies, host syncs in the
round pipeline, unhashable static jit args, metric-schema drift, stale
artifact stamps, and unmarked mesh tests.

CLI::

    python -m tools.lint              # full tree, human-readable
    python -m tools.lint --changed    # only files changed vs HEAD
    python -m tools.lint --json       # machine-readable findings

Tier-1 enforcement: ``tests/test_lint.py`` runs every pass over the
tree and fails on new ERROR findings.  Suppression:
``# blades-lint: disable=<pass> — <reason>`` (see tools/lint/core.py).
"""

from tools.lint.core import (  # noqa: F401
    ERROR,
    WARNING,
    Finding,
    LintContext,
    LintPass,
    SourceFile,
    collect_files,
    run_passes,
)
