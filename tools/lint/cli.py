"""blades-lint CLI: ``python -m tools.lint [--changed] [--json] [paths]``.

Exit 0 = no unsuppressed ERROR findings (warnings never fail); 1 =
findings; 2 = usage error.  ``--json`` emits machine-readable findings
for the sweep/bench harnesses (a list of finding dicts under
``"findings"`` plus a ``"summary"`` block).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from tools.lint.core import EXCLUDE_PARTS, ERROR, changed_files, run_passes
from tools.lint.passes import ALL_PASSES


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="tools.lint",
        description="blades-lint: static analysis for the codebase's "
                    "load-bearing JAX invariants",
    )
    p.add_argument("paths", nargs="*",
                   help="restrict to these files (default: the full tree — "
                        "blades_tpu/, tests/, tools/, bench.py)")
    p.add_argument("--changed", action="store_true",
                   help="lint only files changed vs HEAD (+ untracked)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable findings on stdout")
    p.add_argument("--root", default=".",
                   help="repo root (default: cwd)")
    p.add_argument("--list-passes", action="store_true",
                   help="print the registered passes and exit")
    args = p.parse_args(argv)

    root = Path(args.root).resolve()
    if args.list_passes:
        for pa in ALL_PASSES:
            print(f"{pa.name:18s} {pa.doc}")
        return 0
    only = None
    if args.paths:
        only = [Path(pp).resolve() for pp in args.paths]
    elif args.changed:
        # Unlike explicit operands, --changed keeps the tree-scan
        # exclusions: touching a lint FIXTURE (a deliberate violation)
        # must not fail the changed-files gate.
        only = [p for p in changed_files(root)
                if not any(part in EXCLUDE_PARTS for part in p.parts)]
    if only is not None:
        # Drop non-lintable operands HERE so the summary line counts the
        # files actually parsed, not every changed artifact/markdown.
        only = [p for p in only if p.suffix == ".py" and p.is_file()]
        if not only and args.changed:
            print("blades-lint: no changed python files")
            return 0
    try:
        findings = run_passes(root, ALL_PASSES, only=only)
    except ValueError as exc:  # e.g. a path outside --root
        print(f"blades-lint: {exc}", file=sys.stderr)
        return 2
    errors = [f for f in findings if f.severity == ERROR]
    warnings = [f for f in findings if f.severity != ERROR]
    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in findings],
            "summary": {"errors": len(errors), "warnings": len(warnings),
                        "passes": [pa.name for pa in ALL_PASSES]},
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        scope = (f"{len(only)} file(s)" if only is not None else "full tree")
        print(f"blades-lint: {len(errors)} error(s), {len(warnings)} "
              f"warning(s) over {scope} ({len(ALL_PASSES)} passes)")
    return 1 if errors else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
