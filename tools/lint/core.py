"""blades-lint core: findings, pragma allowlist, file collection.

The JAX-native analogue of a race detector: the codebase is pure-
functional by construction, so the bug classes that matter are the ones
that break the invariants purity rests on — buffer donation, PRNG key
discipline, host-trace impurity, host syncs in the round body, static
jit-arg hashability, and metric-schema drift.  Each invariant is one
:class:`LintPass`; this module is the shared plumbing.

Pragma grammar (supersedes the ad-hoc ``# host-sync: ok`` pragmas)::

    some_call()  # blades-lint: disable=<pass>[,<pass>] — <reason>
    # blades-lint: disable-file=<pass>[,<pass>] — <reason>

``disable=`` suppresses the named passes on ITS line; ``disable-file=``
(anywhere in the file, conventionally the header) suppresses them for
the whole file.  ``disable=all`` suppresses every pass.  A reason of at
least 8 characters is mandatory — a bare pragma defeats the audit trail
and is itself reported as a ``pragma`` finding, as is a pass name no
registered pass answers to (a typo'd pragma silently suppressing
nothing is worse than a loud one).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import subprocess
import tokenize
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# Pass names contain hyphens, so the reason separator (an em/en dash or
# "-") must be whitespace-preceded: `disable=host-sync — once per mask`.
PRAGMA_RE = re.compile(
    r"#\s*blades-lint:\s*(?P<kind>disable-file|disable)\s*=\s*"
    r"(?P<passes>[A-Za-z0-9_,\- ]+?)(?:\s+[—–-]+\s*(?P<reason>.*))?$"
)
MIN_REASON_LEN = 8

# Severities.  Only ERROR findings fail the run; WARNING surfaces in the
# report (and --json) but exits 0 — the schema pass's registered-but-
# never-stamped direction lives there.
ERROR = "error"
WARNING = "warning"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One finding: where, which pass, what, and how to fix it."""

    pass_name: str
    path: str  # repo-relative
    line: int
    message: str
    fix_hint: str = ""
    severity: str = ERROR

    def render(self) -> str:
        tag = "" if self.severity == ERROR else f" {self.severity.upper()}"
        out = f"{self.path}:{self.line}:{tag} [{self.pass_name}] {self.message}"
        if self.fix_hint:
            out += f"\n    fix: {self.fix_hint}"
        return out

    def to_json(self) -> Dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Pragma:
    line: int  # 0 for file-level
    passes: Tuple[str, ...]
    reason: str
    file_level: bool


def _comment_tokens(text: str) -> List[Tuple[int, str]]:
    """(line, comment-text) for every actual ``#`` comment.

    Pragmas are recognized ONLY in comment tokens — a pragma spelled
    inside a docstring or string literal (e.g. a module documenting the
    grammar) must not become a live suppression.  Tokenization of a
    malformed file stops at the bad token; such files get a ``parse``
    finding anyway, so losing their trailing comments is fine.
    """
    out: List[Tuple[int, str]] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.string))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        pass
    return out


class SourceFile:
    """A parsed python file + its pragma allowlist, shared across passes."""

    def __init__(self, path: Path, root: Path):
        self.path = path
        self.rel = str(path.relative_to(root))
        self.text = path.read_text(errors="replace")
        self.lines = self.text.splitlines()
        try:
            self.tree: Optional[ast.Module] = ast.parse(
                self.text, filename=self.rel)
            self.parse_error: Optional[SyntaxError] = None
        except SyntaxError as exc:
            self.tree = None
            self.parse_error = exc
        self.pragmas: List[Pragma] = []
        for lineno, comment in _comment_tokens(self.text):
            m = PRAGMA_RE.search(comment)
            if not m:
                continue
            names = tuple(p.strip() for p in m.group("passes").split(",")
                          if p.strip())
            self.pragmas.append(Pragma(
                line=lineno, passes=names,
                reason=(m.group("reason") or "").strip(),
                file_level=m.group("kind") == "disable-file",
            ))

    def disabled(self, pass_name: str, line: int) -> bool:
        for p in self.pragmas:
            if pass_name in p.passes or "all" in p.passes:
                if p.file_level or p.line == line:
                    return True
        return False


class LintPass:
    """Base class: subclasses set ``name``/``doc`` and implement ``run``.

    ``run`` receives the :class:`LintContext` and yields findings; the
    runner applies pragma suppression afterwards, so passes never need
    to know the pragma grammar.
    """

    name: str = "unnamed"
    doc: str = ""

    def run(self, ctx: "LintContext") -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


class LintContext:
    """Everything a pass may need: the file set, the repo root, and
    whether this is a partial (``--changed`` / explicit-path) scan —
    passes checking repo-wide state (artifact stamps) skip partial
    scans rather than fail them on files nobody asked about."""

    def __init__(self, root: Path, files: Sequence[SourceFile],
                 partial: bool = False):
        self.root = root
        self.files = list(files)
        self.partial = partial
        self._by_rel = {f.rel: f for f in self.files}

    def file(self, rel: str) -> Optional[SourceFile]:
        return self._by_rel.get(rel)

    def matching(self, prefixes: Sequence[str]) -> List[SourceFile]:
        return [f for f in self.files
                if any(f.rel == p or f.rel.startswith(p.rstrip("/") + "/")
                       for p in prefixes)]


# Roots scanned by default (ISSUE 8: blades_tpu/, bench.py, tests/ —
# plus tools/ so the lint suite lints itself).  Fixture snippets are
# DELIBERATE violations and must never enter the default tree scan.
DEFAULT_ROOTS = ("blades_tpu", "tests", "tools", "bench.py")
EXCLUDE_PARTS = ("lint_fixtures", "__pycache__")


def collect_files(root: Path,
                  only: Optional[Sequence[Path]] = None) -> List[SourceFile]:
    """The python files lint runs over, as parsed :class:`SourceFile`\\ s.

    ``only`` restricts collection to that explicit set (the ``--changed``
    and positional-path CLI modes); exclusions still apply.
    """
    if only is not None:
        # Explicit paths (--changed / CLI operands) are linted as asked —
        # including fixture files, which the tests target deliberately.
        return [SourceFile(p, root) for p in only
                if p.suffix == ".py" and p.is_file()]
    paths: List[Path] = []
    for r in DEFAULT_ROOTS:
        p = root / r
        if p.is_file():
            paths.append(p)
        elif p.is_dir():
            paths.extend(sorted(p.rglob("*.py")))
    return [SourceFile(p, root) for p in paths
            if not any(part in EXCLUDE_PARTS for part in p.parts)]


def changed_files(root: Path) -> List[Path]:
    """Files changed vs HEAD plus untracked files (``--changed`` mode)."""
    names: Set[str] = set()
    for args in (["git", "diff", "--name-only", "HEAD"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            r = subprocess.run(args, cwd=root, capture_output=True,
                               text=True, check=True)
        except (OSError, subprocess.CalledProcessError):
            continue
        names.update(n for n in r.stdout.splitlines() if n.strip())
    return [root / n for n in sorted(names) if (root / n).exists()]


def audit_pragmas(files: Sequence[SourceFile],
                  known_passes: Set[str]) -> List[Finding]:
    """The pragma allowlist's own checks: reasons and real pass names."""
    findings = []
    for f in files:
        for p in f.pragmas:
            where = p.line
            if len(p.reason) < MIN_REASON_LEN:
                findings.append(Finding(
                    "pragma", f.rel, where,
                    "blades-lint pragma without a justification",
                    fix_hint="append '— <why this line is exempt>' "
                             f"(>= {MIN_REASON_LEN} chars)",
                ))
            unknown = [n for n in p.passes
                       if n != "all" and n not in known_passes]
            if unknown:
                findings.append(Finding(
                    "pragma", f.rel, where,
                    f"pragma names unknown pass(es) {unknown}",
                    fix_hint="known passes: "
                             + ", ".join(sorted(known_passes)),
                ))
    return findings


def run_passes(root: Path, passes: Sequence[LintPass],
               only: Optional[Sequence[Path]] = None) -> List[Finding]:
    """Run every pass, apply pragma suppression, return sorted findings."""
    files = collect_files(root, only=only)
    ctx = LintContext(root, files, partial=only is not None)
    known = {p.name for p in passes}
    findings: List[Finding] = list(audit_pragmas(files, known))
    for f in files:
        if f.parse_error is not None:
            findings.append(Finding(
                "parse", f.rel, f.parse_error.lineno or 1,
                f"unparseable: {f.parse_error.msg}"))
    for p in passes:
        for finding in p.run(ctx):
            src = ctx.file(finding.path)
            if src is not None and src.disabled(p.name, finding.line):
                continue
            findings.append(finding)
    return sorted(findings, key=lambda x: (x.path, x.line, x.pass_name))
