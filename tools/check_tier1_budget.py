#!/usr/bin/env python
"""Tier-1 wall-time guard + slow-marker audit.

Two checks, both runnable from CI and exercised by ``tests/test_tools.py``:

1. **Budget guard** (``--log``): parse a tier-1 pytest log (the
   ``tee /tmp/_t1.log`` stream ROADMAP.md's verify command writes,
   ideally produced with ``--durations=N``) and FAIL when the projected
   tier-1 wall time exceeds ``--threshold`` (default 85%) of the
   ``--cap`` (default 870 s, the driver's timeout).  The projection
   prefers pytest's own summary total ("... in 823.70s"); when the log
   only carries ``--durations`` lines (e.g. a partial run), their sum
   stands in.  Failing at 85% leaves headroom for box-speed variance
   before the hard timeout kills the run mid-suite.

2. **Marker audit** (``--tests-dir``): AST-scan the test tree for tests
   that construct or consume the 8-virtual-device mesh —
   a fixture or test body calling ``make_mesh`` / ``shard_federation``,
   or requesting a module-local fixture that does — WITHOUT a ``slow``
   marker (module ``pytestmark``, decorator, or the fixture itself being
   used only by marked tests).  Mesh compiles are the single most
   expensive test class on this box; an unmarked one silently eats the
   tier-1 budget.  Since ISSUE 8 the audit IMPLEMENTATION lives in the
   blades-lint framework (``tools/lint/passes/slow_markers.py``, the
   ``slow-markers`` pass) so all static analysis runs through one
   visitor core; this CLI keeps its historical surface and delegates.

Exit code 0 = all checks pass; 1 = violation; 2 = usage/parse error.

Usage::

    python tools/check_tier1_budget.py --log /tmp/_t1.log
    python tools/check_tier1_budget.py --audit-only
"""

from __future__ import annotations

import argparse
import re
import sys
from pathlib import Path
from typing import List, Optional, Tuple

# Importable both as `tools.check_tier1_budget` and as a top-level
# module with tools/ on sys.path (the historical test harness does the
# latter); either way the lint package needs the repo root.
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from tools.lint.passes import slow_markers as _slow  # noqa: E402

CAP_SECONDS = 870.0
THRESHOLD = 0.85
MESH_CALLS = _slow.MESH_CALLS

_DURATION_RE = re.compile(
    r"^\s*(\d+(?:\.\d+)?)s\s+(call|setup|teardown)\s+(\S+)"
)
# pytest summary: "== 359 passed, 3 skipped in 823.70s (0:13:43) =="
_TOTAL_RE = re.compile(r"\bin\s+(\d+(?:\.\d+)?)s\b")


# ---------------------------------------------------------------------------
# budget guard
# ---------------------------------------------------------------------------


def parse_durations(text: str) -> List[Tuple[float, str, str]]:
    """``--durations`` lines as ``(seconds, phase, test id)``."""
    out = []
    for line in text.splitlines():
        m = _DURATION_RE.match(line)
        if m:
            out.append((float(m.group(1)), m.group(2), m.group(3)))
    return out


def parse_total_seconds(text: str) -> Optional[float]:
    """The wall total from pytest's final summary line, if present."""
    total = None
    for line in text.splitlines():
        if ("passed" in line or "failed" in line or "error" in line) and (
            line.strip().startswith("=") or " in " in line
        ):
            m = _TOTAL_RE.search(line)
            if m:
                total = float(m.group(1))
    return total


def projected_tier1_seconds(text: str) -> Tuple[Optional[float], str]:
    """(projection, provenance) for a tier-1 log."""
    total = parse_total_seconds(text)
    if total is not None:
        return total, "pytest summary wall total"
    durations = parse_durations(text)
    if durations:
        return sum(d[0] for d in durations), (
            f"sum of {len(durations)} --durations entries (no summary "
            "line found — partial log?)"
        )
    return None, "no pytest summary or --durations lines found"


def check_budget(log_path: Path, cap: float, threshold: float) -> List[str]:
    """Violation messages (empty = within budget)."""
    try:
        text = log_path.read_text(errors="replace")
    except OSError as exc:
        return [f"cannot read {log_path}: {exc}"]
    projected, provenance = projected_tier1_seconds(text)
    if projected is None:
        return [f"{log_path}: {provenance}"]
    budget = cap * threshold
    print(f"tier-1 projection: {projected:.1f}s ({provenance}); "
          f"budget {budget:.1f}s = {threshold:.0%} of the {cap:.0f}s cap")
    if projected > budget:
        heavy = sorted(parse_durations(text), reverse=True)[:10]
        hints = "".join(f"\n    {s:7.1f}s {phase:8s} {tid}"
                        for s, phase, tid in heavy)
        return [
            f"projected tier-1 time {projected:.1f}s exceeds "
            f"{threshold:.0%} of the {cap:.0f}s cap ({budget:.1f}s) — "
            f"move compile-heavy cases to the slow lane.  Heaviest:"
            + (hints or " (no --durations in log)")
        ]
    return []


# ---------------------------------------------------------------------------
# marker audit (delegates to the blades-lint slow-markers pass)
# ---------------------------------------------------------------------------


def audit_file(path: Path) -> List[str]:
    """Unmarked mesh tests in one file (violation messages)."""
    out = []
    for f in _slow.audit_path(path):
        if "unparseable" in f.message:
            out.append(f"{path}: {f.message}")
        else:
            # Historical message shape: "<file>::<test>: builds the ..."
            test_name, rest = f.message.split(" ", 1)
            out.append(f"{path.name}::{test_name}: {rest}")
    return out


def check_markers(tests_dir: Path) -> List[str]:
    violations: List[str] = []
    for path in sorted(tests_dir.glob("test_*.py")):
        violations.extend(audit_file(path))
    return violations


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="check_tier1_budget",
        description="tier-1 wall-time guard + slow-marker audit",
    )
    p.add_argument("--log", default="/tmp/_t1.log",
                   help="tier-1 pytest log (from the ROADMAP verify "
                   "command's tee; add --durations=N for hotspot hints)")
    p.add_argument("--cap", type=float, default=CAP_SECONDS,
                   help="tier-1 hard timeout in seconds (default 870)")
    p.add_argument("--threshold", type=float, default=THRESHOLD,
                   help="fail when projection exceeds this fraction of "
                   "the cap (default 0.85)")
    p.add_argument("--tests-dir", default="tests")
    p.add_argument("--audit-only", action="store_true",
                   help="run only the marker audit (no log needed)")
    p.add_argument("--budget-only", action="store_true",
                   help="run only the wall-time guard")
    args = p.parse_args(argv)

    problems: List[str] = []
    if not args.audit_only:
        problems += check_budget(Path(args.log), args.cap, args.threshold)
    if not args.budget_only:
        problems += check_markers(Path(args.tests_dir))
    for msg in problems:
        print(f"FAIL: {msg}", file=sys.stderr)
    if not problems:
        print("tier-1 budget + marker audit: OK")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
