#!/usr/bin/env python
"""Tier-1 wall-time guard + slow-marker audit.

Two checks, both runnable from CI and exercised by ``tests/test_tools.py``:

1. **Budget guard** (``--log``): parse a tier-1 pytest log (the
   ``tee /tmp/_t1.log`` stream ROADMAP.md's verify command writes,
   ideally produced with ``--durations=N``) and FAIL when the projected
   tier-1 wall time exceeds ``--threshold`` (default 85%) of the
   ``--cap`` (default 870 s, the driver's timeout).  The projection
   prefers pytest's own summary total ("... in 823.70s"); when the log
   only carries ``--durations`` lines (e.g. a partial run), their sum
   stands in.  Failing at 85% leaves headroom for box-speed variance
   before the hard timeout kills the run mid-suite.

2. **Marker audit** (``--tests-dir``): AST-scan the test tree for tests
   that construct or consume the 8-virtual-device mesh —
   a fixture or test body calling ``make_mesh`` / ``shard_federation``,
   or requesting a module-local fixture that does — WITHOUT a ``slow``
   marker (module ``pytestmark``, decorator, or the fixture itself being
   used only by marked tests).  Mesh compiles are the single most
   expensive test class on this box; an unmarked one silently eats the
   tier-1 budget.

Exit code 0 = all checks pass; 1 = violation; 2 = usage/parse error.

Usage::

    python tools/check_tier1_budget.py --log /tmp/_t1.log
    python tools/check_tier1_budget.py --audit-only
"""

from __future__ import annotations

import argparse
import ast
import re
import sys
from pathlib import Path
from typing import Dict, List, Optional, Tuple

CAP_SECONDS = 870.0
THRESHOLD = 0.85
MESH_CALLS = {"make_mesh", "shard_federation"}

_DURATION_RE = re.compile(
    r"^\s*(\d+(?:\.\d+)?)s\s+(call|setup|teardown)\s+(\S+)"
)
# pytest summary: "== 359 passed, 3 skipped in 823.70s (0:13:43) =="
_TOTAL_RE = re.compile(r"\bin\s+(\d+(?:\.\d+)?)s\b")


# ---------------------------------------------------------------------------
# budget guard
# ---------------------------------------------------------------------------


def parse_durations(text: str) -> List[Tuple[float, str, str]]:
    """``--durations`` lines as ``(seconds, phase, test id)``."""
    out = []
    for line in text.splitlines():
        m = _DURATION_RE.match(line)
        if m:
            out.append((float(m.group(1)), m.group(2), m.group(3)))
    return out


def parse_total_seconds(text: str) -> Optional[float]:
    """The wall total from pytest's final summary line, if present."""
    total = None
    for line in text.splitlines():
        if ("passed" in line or "failed" in line or "error" in line) and (
            line.strip().startswith("=") or " in " in line
        ):
            m = _TOTAL_RE.search(line)
            if m:
                total = float(m.group(1))
    return total


def projected_tier1_seconds(text: str) -> Tuple[Optional[float], str]:
    """(projection, provenance) for a tier-1 log."""
    total = parse_total_seconds(text)
    if total is not None:
        return total, "pytest summary wall total"
    durations = parse_durations(text)
    if durations:
        return sum(d[0] for d in durations), (
            f"sum of {len(durations)} --durations entries (no summary "
            "line found — partial log?)"
        )
    return None, "no pytest summary or --durations lines found"


def check_budget(log_path: Path, cap: float, threshold: float) -> List[str]:
    """Violation messages (empty = within budget)."""
    try:
        text = log_path.read_text(errors="replace")
    except OSError as exc:
        return [f"cannot read {log_path}: {exc}"]
    projected, provenance = projected_tier1_seconds(text)
    if projected is None:
        return [f"{log_path}: {provenance}"]
    budget = cap * threshold
    print(f"tier-1 projection: {projected:.1f}s ({provenance}); "
          f"budget {budget:.1f}s = {threshold:.0%} of the {cap:.0f}s cap")
    if projected > budget:
        heavy = sorted(parse_durations(text), reverse=True)[:10]
        hints = "".join(f"\n    {s:7.1f}s {phase:8s} {tid}"
                        for s, phase, tid in heavy)
        return [
            f"projected tier-1 time {projected:.1f}s exceeds "
            f"{threshold:.0%} of the {cap:.0f}s cap ({budget:.1f}s) — "
            f"move compile-heavy cases to the slow lane.  Heaviest:"
            + (hints or " (no --durations in log)")
        ]
    return []


# ---------------------------------------------------------------------------
# marker audit
# ---------------------------------------------------------------------------


def _has_slow_mark(deco_list) -> bool:
    for d in deco_list:
        for node in ast.walk(d):
            if isinstance(node, ast.Attribute) and node.attr == "slow":
                return True
    return False


def _is_fixture(deco_list) -> bool:
    for d in deco_list:
        for node in ast.walk(d):
            if isinstance(node, ast.Attribute) and node.attr == "fixture":
                return True
            if isinstance(node, ast.Name) and node.id == "fixture":
                return True
    return False


def _module_slow(tree: ast.Module) -> bool:
    """``pytestmark = pytest.mark.slow`` (or a list containing it)."""
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "pytestmark"
            for t in node.targets
        ):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Attribute) and sub.attr == "slow":
                    return True
    return False


def _calls_mesh(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if name in MESH_CALLS:
                return True
    return False


def audit_file(path: Path) -> List[str]:
    """Unmarked mesh tests in one file (violation messages)."""
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except SyntaxError as exc:
        return [f"{path}: unparseable ({exc})"]
    if _module_slow(tree):
        return []
    mesh_fixtures = set()
    functions = [n for n in ast.walk(tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in functions:
        if _is_fixture(fn.decorator_list) and _calls_mesh(fn):
            mesh_fixtures.add(fn.name)
    violations = []
    for fn in functions:
        if not fn.name.startswith("test"):
            continue
        if _has_slow_mark(fn.decorator_list):
            continue
        args = {a.arg for a in fn.args.args}
        uses_mesh = _calls_mesh(fn) or (args & mesh_fixtures)
        if uses_mesh:
            via = (f"fixture {sorted(args & mesh_fixtures)[0]!r}"
                   if args & mesh_fixtures else "direct mesh call")
            violations.append(
                f"{path.name}::{fn.name}: builds the 8-device mesh "
                f"({via}) without @pytest.mark.slow"
            )
    return violations


def check_markers(tests_dir: Path) -> List[str]:
    violations: List[str] = []
    for path in sorted(tests_dir.glob("test_*.py")):
        violations.extend(audit_file(path))
    return violations


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="check_tier1_budget",
        description="tier-1 wall-time guard + slow-marker audit",
    )
    p.add_argument("--log", default="/tmp/_t1.log",
                   help="tier-1 pytest log (from the ROADMAP verify "
                   "command's tee; add --durations=N for hotspot hints)")
    p.add_argument("--cap", type=float, default=CAP_SECONDS,
                   help="tier-1 hard timeout in seconds (default 870)")
    p.add_argument("--threshold", type=float, default=THRESHOLD,
                   help="fail when projection exceeds this fraction of "
                   "the cap (default 0.85)")
    p.add_argument("--tests-dir", default="tests")
    p.add_argument("--audit-only", action="store_true",
                   help="run only the marker audit (no log needed)")
    p.add_argument("--budget-only", action="store_true",
                   help="run only the wall-time guard")
    args = p.parse_args(argv)

    problems: List[str] = []
    if not args.audit_only:
        problems += check_budget(Path(args.log), args.cap, args.threshold)
    if not args.budget_only:
        problems += check_markers(Path(args.tests_dir))
    for msg in problems:
        print(f"FAIL: {msg}", file=sys.stderr)
    if not problems:
        print("tier-1 budget + marker audit: OK")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
