#!/usr/bin/env python
"""Inspect / invalidate the execution autotuner's persistent plan cache.

The autotuner (``blades_tpu/perf/autotune.py``) persists each winning
execution plan to one JSON file per ``(config fingerprint, tier, device
kind, jaxlib version)`` key under ``$BLADES_TPU_PLAN_CACHE_DIR`` (or
``~/.cache/blades_tpu/plans``).  This tool is the operator surface for
that cache:

- ``list`` (default): one line per entry — digest, winner ``plan_id``,
  selection mode, device kind, jaxlib, age.  Files the corrupt-tolerant
  reader rejects (torn writes, stale ``version`` stamps) are listed as
  ``CORRUPT/STALE`` rather than hidden — they cost a re-tune on next
  use, which an operator may want to know about.
- ``show <digest>``: the full entry — key, plan dict (paste-able into
  ``FedavgConfig.resources(tuned_plan=...)`` to pin it), and the
  selection provenance (per-candidate timings or the
  heuristic-fallback marker).
- ``invalidate [digest]``: delete one entry (plus its orphaned
  ``.tmp``), or ``--all`` to clear the cache; the next autotuned run
  re-tunes.

Usage::

    python -m tools.show_plan                      # list entries
    python -m tools.show_plan show 3f2a…           # dump one entry
    python -m tools.show_plan invalidate 3f2a…     # drop one entry
    python -m tools.show_plan invalidate --all     # clear the cache
    python -m tools.show_plan --cache-dir /tmp/p   # non-default location
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def _age(created) -> str:
    try:
        secs = max(0.0, time.time() - float(created))
    except (TypeError, ValueError):
        return "?"
    if secs < 3600:
        return f"{secs / 60:.0f}m"
    if secs < 86400:
        return f"{secs / 3600:.1f}h"
    return f"{secs / 86400:.1f}d"


def cmd_list(cache) -> int:
    entries = cache.entries()
    if not entries:
        print(f"plan cache {cache.dir}: empty")
        return 0
    print(f"plan cache {cache.dir}: {len(entries)} entr"
          f"{'y' if len(entries) == 1 else 'ies'}")
    for digest, entry in entries:
        if entry is None:
            print(f"  {digest[:12]}  CORRUPT/STALE (will re-tune; "
                  "'invalidate' to drop)")
            continue
        key = entry.get("key", {})
        prov = entry.get("provenance", {})
        plan_id = prov.get("winner_id") or "?"
        print(f"  {digest[:12]}  {plan_id:<40s} mode={prov.get('mode', '?')}"
              f" tier={key.get('tier', '?')}"
              f" device={key.get('device_kind', '?')}"
              f" jaxlib={key.get('jaxlib', '?')}"
              f" age={_age(entry.get('created_unix'))}")
    return 0


def cmd_show(cache, digest: str) -> int:
    for d, entry in cache.entries():
        if d.startswith(digest):
            if entry is None:
                print(f"{d}: corrupt or stale-version entry "
                      "(unreadable; 'invalidate' to drop)")
                return 1
            print(json.dumps(entry, indent=2, sort_keys=True))
            return 0
    print(f"no cache entry matching {digest!r} under {cache.dir}")
    return 1


def cmd_invalidate(cache, digest, all_: bool) -> int:
    if not all_ and not digest:
        print("invalidate: pass a digest (prefix ok) or --all")
        return 2
    if digest and not all_:
        matches = [d for d, _ in cache.entries() if d.startswith(digest)]
        if not matches:
            print(f"no cache entry matching {digest!r} under {cache.dir}")
            return 1
        removed = []
        for d in matches:
            removed += cache.invalidate(d)
    else:
        removed = cache.invalidate()
    for name in removed:
        print(f"removed {cache.dir / name}")
    if not removed:
        print(f"plan cache {cache.dir}: nothing to remove")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools.show_plan",
        description="dump / invalidate the execution autotuner's "
        "persistent plan cache (see README 'Execution autotuner')",
    )
    parser.add_argument("--cache-dir", default=None, metavar="DIR",
                        help="cache location (default "
                        "$BLADES_TPU_PLAN_CACHE_DIR or "
                        "~/.cache/blades_tpu/plans)")
    sub = parser.add_subparsers(dest="cmd")
    sub.add_parser("list", help="one line per entry (default)")
    p_show = sub.add_parser("show", help="dump one entry as JSON")
    p_show.add_argument("digest", help="entry digest (prefix ok)")
    p_inv = sub.add_parser("invalidate", help="delete entries")
    p_inv.add_argument("digest", nargs="?", default=None,
                       help="entry digest (prefix ok)")
    p_inv.add_argument("--all", action="store_true",
                       help="clear every entry (and orphaned .tmp files)")
    args = parser.parse_args(argv)

    from blades_tpu.perf.autotune import PlanCache

    cache = PlanCache(args.cache_dir)
    if args.cmd == "show":
        return cmd_show(cache, args.digest)
    if args.cmd == "invalidate":
        return cmd_invalidate(cache, args.digest, args.all)
    return cmd_list(cache)


if __name__ == "__main__":
    sys.exit(main())
